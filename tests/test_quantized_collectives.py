"""In-trace quantized collectives + blockwise wire codecs (ISSUE 8, EQuARX).

Covers the tentpole contract: the blockwise int8/fp8 codecs are pure-jnp
transforms shared bit-for-bit by the eager and compiled paths (jit vs eager
encode/decode parity), `sync_async` honors the configured codec inside a
shard_map trace with the error-feedback residual threaded as carried state,
`jit.TrainStep(grad_comm=...)` runs the quantize -> psum-of-int ->
dequantize sequence inside the compiled train step (fp32 wire bit-identical
to the implicit-psum path; quantized wire convergence-parity on gpt-test),
the traced wire-bytes counters show the >=2x reduction vs bf16, the EQuARX
§RS quantized reduce_scatter decomposition, and the strategy/cost-model/
bench/gate wiring.
"""
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
import paddle_tpu.optimizer as optim
import paddle_tpu.distributed.collective as coll
import paddle_tpu.distributed.mesh as mesh_mod
from paddle_tpu.distributed import fleet, grad_comm
from paddle_tpu.distributed.overlap import OverlappedGradCommunicator
from paddle_tpu.framework.tensor import Tensor
from paddle_tpu.jit import TrainStep

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
rng = np.random.RandomState(0)

BLOCK = grad_comm.BLOCK_CODECS


@pytest.fixture(autouse=True)
def reset_mesh(fresh_mesh):
    yield  # fresh_mesh (conftest) owns save/clear/restore


def _two_rank_sum_doubles(calls=None):
    """Two identical emulated ranks: every SUM doubles (int payload AND the
    fp32 abs-max scale vector — both ride sum-typed exchanges), MAX/AVG are
    identity."""
    def fake(t, op=None, group=None, **kw):
        if calls is not None:
            calls.append((str(t._value.dtype), op, tuple(t._value.shape)))
        if op == coll.ReduceOp.SUM:
            t._value = t._value * 2
        return t
    return fake


def _mlp(seed=7):
    paddle.seed(seed)
    return nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 1))


X = rng.standard_normal((16, 8)).astype(np.float32)
Y = rng.standard_normal((16, 1)).astype(np.float32)


# ------------------------------------------------------------ codec layer
@pytest.mark.parametrize("codec", BLOCK)
def test_blockwise_roundtrip_and_residual_exactness(codec):
    bs = 256
    x = jnp.asarray(rng.standard_normal(5000).astype(np.float32) * 3.0)
    scales = grad_comm.block_scales(grad_comm.block_absmax(x, bs), codec)
    q = grad_comm.block_encode(x, scales, bs, codec)
    deq = grad_comm.block_decode(q, scales, world=1, dtype=np.float32,
                                 numel=5000)
    if codec == "int8_block":
        # per-BLOCK half-step bound — the whole point of blockwise scales:
        # a quiet block's error is bounded by ITS scale, not the bucket's
        per_elem_bound = np.repeat(np.asarray(scales) * 0.5001, bs)[:5000]
        assert np.all(np.abs(np.asarray(deq - x)) <= per_elem_bound)
    else:
        # e4m3: 3 mantissa bits -> ~6.25% relative error, plus the
        # subnormal floor of the blockwise scale
        err = np.abs(np.asarray(deq - x))
        bound = np.abs(np.asarray(x)) * 0.0723 + np.repeat(
            np.asarray(scales), bs)[:5000]
        assert np.all(err <= bound)
    # the error-feedback residual is exactly what the wire dropped
    res = grad_comm.block_residual(x, q, scales, 5000)
    np.testing.assert_allclose(np.asarray(deq + res), np.asarray(x),
                               rtol=0, atol=1e-6)


@pytest.mark.parametrize("codec", BLOCK)
def test_codec_eager_vs_jit_wire_parity(codec):
    """The shared-verbatim contract at world=1: the WIRE payload (the bits
    a collective would actually move) is identical whether the codec runs
    eagerly or inside a compiled program; the decoded update agrees to the
    last place XLA's fusion is allowed to touch (one multiply rounding),
    and decode+residual reproduce the input exactly on both paths."""
    bs = 128
    x = jnp.asarray(rng.standard_normal(1000).astype(np.float32))

    def pipeline(v):
        scales = grad_comm.block_scales(grad_comm.block_absmax(v, bs),
                                        codec)
        q = grad_comm.block_encode(v, scales, bs, codec)
        deq = grad_comm.block_decode(q, scales, 1, jnp.float32, 1000)
        return q, scales, deq, grad_comm.block_residual(v, q, scales, 1000)

    eq, es, edeq, eres = pipeline(x)
    jq, js, jdeq, jres = jax.jit(pipeline)(x)
    # wire bits: the quantized payload exactly; the fp32 scale vector to
    # the one multiply rounding XLA's fusion may move
    assert np.array_equal(np.asarray(eq), np.asarray(jq))
    np.testing.assert_allclose(np.asarray(es), np.asarray(js),
                               rtol=2e-7, atol=0)
    # decode: identical payload x identical scales — ulp-level agreement
    np.testing.assert_allclose(np.asarray(edeq), np.asarray(jdeq),
                               rtol=0, atol=1e-6)
    # the lossless invariant holds bit-for-bit on each path separately
    np.testing.assert_allclose(np.asarray(edeq + eres), np.asarray(x),
                               rtol=0, atol=1e-6)
    np.testing.assert_allclose(np.asarray(jdeq + jres), np.asarray(x),
                               rtol=0, atol=1e-6)


def test_blockwise_eager_sync_stats_and_wire(monkeypatch):
    calls = []
    monkeypatch.setattr(coll, "all_reduce", _two_rank_sum_doubles(calls))
    params = []
    for i, shp in enumerate([(64, 64), (64,)]):
        p = Tensor(np.zeros(shp, np.float32))
        p.stop_gradient = False
        p.name = f"p{i}"
        p.grad = Tensor(rng.standard_normal(shp).astype(np.float32))
        params.append(p)
    comm = grad_comm.GradCommunicator(
        grad_comm.GradCommConfig("int8_block", block_size=256))
    comm.sync(params, world=2)
    numel = 64 * 64 + 64
    nb = -(-numel // 256)
    # one per-block scale-vector SUM + one int payload SUM per bucket
    assert [c[1] for c in calls] == [coll.ReduceOp.SUM, coll.ReduceOp.SUM]
    assert calls[0][0] == "float32" and calls[0][2] == (nb,)
    assert calls[1][0] == "int32"
    assert comm.stats["collectives"] == 2
    assert comm.stats["comm_bytes"] == numel * 1 + 4 * nb
    assert comm.stats["path"] == "eager"
    assert 0 in comm._residuals     # error feedback recorded


@pytest.mark.parametrize("codec", BLOCK)
def test_blockwise_error_feedback_convergence(codec, monkeypatch):
    """PR-1 acceptance style: an MLP trained with the blockwise quantized
    sync + error feedback lands within the int8 tolerance of the
    un-quantized run."""
    x = rng.standard_normal((32, 8)).astype(np.float32)
    w_true = rng.standard_normal((8, 1)).astype(np.float32)
    y = np.tanh(x @ w_true).astype(np.float32)

    def train(c, steps=60):
        paddle.seed(11)
        net = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 1))
        opt = optim.SGD(learning_rate=0.3, parameters=net.parameters())
        comm = (None if c is None else grad_comm.GradCommunicator(
            grad_comm.GradCommConfig(c, block_size=64)))
        losses = []
        for _ in range(steps):
            loss = F.mse_loss(net(paddle.to_tensor(x)), paddle.to_tensor(y))
            loss.backward()
            if comm is not None:
                comm.sync([p for p in net.parameters()
                           if not p.stop_gradient], world=2)
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.numpy()))
        return losses

    monkeypatch.setattr(coll, "all_reduce", _two_rank_sum_doubles())
    exact = train(None)
    quant = train(codec)
    assert exact[-1] < exact[0] * 0.1, "reference run failed to converge"
    assert quant[-1] < quant[0] * 0.1, f"{codec}+EF run failed to converge"
    assert abs(quant[-1] - exact[-1]) <= max(0.05 * exact[-1], 0.005), \
        (codec, quant[-1], exact[-1])


def test_config_block_size_validation_and_state_guard():
    with pytest.raises(ValueError):
        grad_comm.GradCommConfig("int8_block", block_size=0)
    with pytest.raises(ValueError):
        grad_comm.GradCommConfig("int8_block", block_size="big")
    c1 = grad_comm.GradCommunicator(
        grad_comm.GradCommConfig("int8_block", block_size=1024))
    state = c1.state_dict()
    assert state["block_size"] == 1024
    c2 = grad_comm.GradCommunicator(
        grad_comm.GradCommConfig("int8_block", block_size=512))
    with pytest.raises(ValueError, match="block_size mismatch"):
        c2.load_state_dict(state)


# ----------------------------------------------------- in-trace sync_async
def test_sync_async_in_trace_honors_blockwise_codec():
    """Inside a shard_map trace the blockwise codec actually runs: the
    decoded values equal the hand-applied pure-codec pipeline over the
    REAL 2-device psum, the futures carry the residuals (carried state),
    and stats report the actual (quantized) wire with path=traced."""
    from jax.sharding import PartitionSpec as P

    m = mesh_mod.set_mesh(
        mesh_mod.build_mesh({"data": 2}, devices=jax.devices()[:2]))
    shapes = [(3, 5), (7,), (2, 2, 4)]
    gs = [rng.standard_normal((2,) + s).astype(np.float32) for s in shapes]
    bs = 8
    cfg = grad_comm.GradCommConfig("int8_block", block_size=bs)
    comm = OverlappedGradCommunicator(cfg)

    def make_params(vals):
        params = []
        for v in vals:
            p = Tensor(jnp.zeros(v.shape), _internal=True)
            p.stop_gradient = False
            p.grad = Tensor(v, _internal=True)
            params.append(p)
        return params

    def body(*rank_grads):
        vals = [g.reshape(s) for g, s in zip(rank_grads, shapes)]
        params = make_params(vals)
        buckets = comm.buckets_for(params)
        res = {b.index: jnp.zeros((b.size,), jnp.float32) for b in buckets}
        futs = comm.sync_async(params, world=2, residuals=res)
        # reference: the same pure codec functions over an explicit psum
        refs = []
        for b in buckets:
            flat = jnp.concatenate([vals[pi].reshape(-1)
                                    for pi in b.param_indices]) \
                if len(b.param_indices) > 1 \
                else vals[b.param_indices[0]].reshape(-1)
            am = jax.lax.psum(grad_comm.block_absmax(flat, bs), "data")
            sc = grad_comm.block_scales(am, "int8_block")
            q = grad_comm.block_encode(flat, sc, bs, "int8_block")
            qs = jax.lax.psum(q, "data")
            refs.append(grad_comm.block_decode(qs, sc, 2, jnp.float32,
                                               b.size))
        return (tuple(f.wait() for f in futs) + tuple(refs)
                + tuple(f.residual for f in futs))

    outs = mesh_mod.compat_shard_map(
        body, m, P("data"), P())(*gs)
    n = len(comm._buckets)
    got, ref, res_out = outs[:n], outs[n:2 * n], outs[2 * n:]
    for g, r in zip(got, ref):
        assert np.array_equal(np.asarray(g), np.asarray(r))
    for r in res_out:
        assert np.all(np.isfinite(np.asarray(r)))
    assert comm.stats["path"] == "traced"
    total = sum(b.size for b in comm._buckets)
    scale_b = sum(grad_comm.scale_bytes(b.size, bs) for b in comm._buckets)
    assert comm.stats["comm_bytes"] == total * 1 + scale_b
    # no tracer ever landed in the host-side residual store
    assert comm._residuals == {}


def test_traced_sync_with_error_feedback_refuses_host_residuals():
    """sync() inside a trace with an EF codec must fail loudly instead of
    leaking a tracer into self._residuals (the carried-state contract)."""
    from jax.sharding import PartitionSpec as P

    m = mesh_mod.set_mesh(
        mesh_mod.build_mesh({"data": 2}, devices=jax.devices()[:2]))
    g = rng.standard_normal((2, 64)).astype(np.float32)
    comm = grad_comm.GradCommunicator(
        grad_comm.GradCommConfig("int8_block"))

    def body(v):
        p = Tensor(jnp.zeros((64,)), _internal=True)
        p.stop_gradient = False
        p.grad = Tensor(v.reshape(64), _internal=True)
        comm.sync([p], world=2)
        return p.grad._value

    with pytest.raises(RuntimeError, match="carried state"):
        mesh_mod.compat_shard_map(body, m, P("data"), P())(g)


def test_fused_step_commits_future_residuals(monkeypatch):
    """FusedFlatUpdater consumes sync_async futures without unflattening —
    and commits their error-feedback residuals back to the communicator so
    the skip-the-scatter path keeps cross-step feedback."""
    from paddle_tpu.optimizer.fused import FusedFlatUpdater

    monkeypatch.setattr(coll, "all_reduce", _two_rank_sum_doubles())
    net = _mlp()
    opt = optim.Adam(learning_rate=0.05, parameters=net.parameters())
    params = [p for p in net.parameters() if not p.stop_gradient]
    comm = OverlappedGradCommunicator(
        grad_comm.GradCommConfig("int8_block", comm_buffer_size=0.0002,
                                 last_comm_buffer_size=0.0001))
    fused = FusedFlatUpdater(opt, params, communicator=comm)
    F.mse_loss(net(paddle.to_tensor(X)), paddle.to_tensor(Y)).backward()
    buckets = comm.buckets_for(params)
    # explicit residuals => sync_async does NOT store them host-side...
    res = {b.index: jnp.zeros((b.size,), jnp.float32) for b in buckets}
    futs = comm.sync_async(params, world=2, residuals=res)
    assert comm._residuals == {}
    fused.step(futures=futs)          # ...the fused consumer commits them
    assert sorted(comm._residuals) == sorted(b.index for b in buckets)
    for f in futs:
        assert np.array_equal(np.asarray(comm._residuals[f.bucket.index]),
                              np.asarray(f.residual))


# -------------------------------------------- EQuARX §RS (ZeRO-2 traced)
def test_traced_reduce_scatter_quantized():
    """Both halves of the ring decomposition ship the 1-byte wire: the
    reduce_scatter half under shared blockwise scales, the all_gather half
    requantized per rank — and the reassembled average stays within the
    two quantization steps of the true mean."""
    from jax.sharding import PartitionSpec as P

    m = mesh_mod.set_mesh(
        mesh_mod.build_mesh({"data": 2}, devices=jax.devices()[:2]))
    n = 3000
    g = rng.standard_normal((2, n)).astype(np.float32)
    cfg = grad_comm.GradCommConfig("int8_block", block_size=256)

    def body(x):
        full, shard, res, wire, ncoll = \
            grad_comm.traced_reduce_scatter_quantized(
                x.reshape(n), "data", 2, cfg)
        return full, shard, res

    full, shard, res = mesh_mod.compat_shard_map(
        body, m, P("data"), (P(), P("data"), P()))(g)
    ref = g.mean(axis=0)
    step = 2.0 * np.abs(g).max() * 2 / 127   # two (summed-absmax) steps
    assert np.abs(np.asarray(full) - ref).max() <= step
    assert np.asarray(res).shape == (n,)
    # reduce_bucket routes the traced ZeRO-2 form through the §RS path
    comm = grad_comm.GradCommunicator(cfg)

    def body2(x):
        b = grad_comm.GradBucket(0, np.dtype(np.float32))
        b.add(0, (n,))
        reduced, nr, wire, ncoll = comm.reduce_bucket(
            b, x.reshape(n), 2, use_reduce_scatter=True,
            residual=jnp.zeros((n,), jnp.float32))
        return reduced, nr

    reduced, nr = mesh_mod.compat_shard_map(
        body2, m, P("data"), P())(g)
    assert np.abs(np.asarray(reduced) - ref).max() <= step
    assert np.asarray(nr).shape == (n,)


# ------------------------------------------------- TrainStep in-trace comm
def _train_mlp_step(codec, steps=4, mesh_devices=2):
    if mesh_devices:
        mesh_mod.set_mesh(mesh_mod.build_mesh(
            {"data": mesh_devices}, devices=jax.devices()[:mesh_devices]))
    else:
        mesh_mod._current[0] = None
    paddle.seed(7)
    net = _mlp()
    opt = optim.AdamW(learning_rate=1e-2, parameters=net.parameters())
    gc = None if codec is None else grad_comm.GradCommConfig(
        codec, comm_buffer_size=0.0002, last_comm_buffer_size=0.0001,
        block_size=64)
    step = TrainStep(net, F.mse_loss, opt, grad_comm=gc)
    losses = [float(step(inputs=(paddle.to_tensor(X),),
                         labels=(paddle.to_tensor(Y),)))
              for _ in range(steps)]
    return losses, step


def test_trainstep_gc_fp32_bit_identical_to_implicit_psum():
    """The explicit-SPMD wire path with an fp32 codec must reproduce the
    implicit-psum pjit step EXACTLY — same math, different spelling."""
    l_plain, _ = _train_mlp_step(None)
    l_fp32, step = _train_mlp_step("fp32")
    assert l_plain == l_fp32
    assert step.comm_stats["path"] == "traced"
    assert step.comm_stats["n_buckets"] >= 3


@pytest.mark.parametrize("codec", BLOCK)
def test_trainstep_gc_quantized_convergence(codec):
    """Quantized wire inside the compiled step: loss curve tracks the fp32
    one within the PR-1 int8 tolerance, residuals persist across calls."""
    l_fp32, _ = _train_mlp_step("fp32", steps=6)
    l_q, step = _train_mlp_step(codec, steps=6)
    assert l_q[-1] < l_q[0], "quantized compiled run failed to improve"
    assert abs(l_q[-1] - l_fp32[-1]) <= max(0.05 * l_fp32[-1], 0.01), \
        (codec, l_q[-1], l_fp32[-1])
    assert step._gc_comm._residuals, "no carried residuals after steps"
    # inert without a >1-replica mesh: bit-identical to the plain step
    l_off, step_off = _train_mlp_step(codec, mesh_devices=0)
    l_plain_off, _ = _train_mlp_step(None, mesh_devices=0)
    assert l_off == l_plain_off
    assert step_off.comm_stats is None


def test_trainstep_gc_wire_counters_on_gpt_test():
    """The acceptance counter: inside a jitted train step on gpt-test the
    int8_block wire bytes are ~4x under fp32 and ~2x under bf16, recorded
    per executed step in grad_comm_bytes_total{codec=,path=traced}."""
    from paddle_tpu.models import (
        GPTForCausalLM, GPTPretrainingCriterion, gpt_presets,
    )
    from paddle_tpu.observability import get_registry

    mesh_mod.set_mesh(
        mesh_mod.build_mesh({"data": 2}, devices=jax.devices()[:2]))
    rs = np.random.RandomState(0)
    ids = rs.randint(0, 256, (4, 16)).astype(np.int64)
    labels = rs.randint(0, 256, (4, 16)).astype(np.int64)
    reg = get_registry()
    fam = reg.counter("grad_comm_bytes_total", labels=("codec", "path"))

    def run(codec, steps=2):
        paddle.seed(1234)
        m = GPTForCausalLM(gpt_presets("gpt-test"), seed=7)
        crit = GPTPretrainingCriterion()
        o = optim.AdamW(learning_rate=1e-3, parameters=m.parameters())
        step = TrainStep(m, lambda lg, lb: crit(lg, lb), o,
                         grad_comm=grad_comm.GradCommConfig(codec))
        c0 = fam.labels(codec=codec, path="traced").value
        losses = [float(step(inputs=(paddle.to_tensor(ids, dtype="int64"),),
                             labels=(paddle.to_tensor(labels,
                                                      dtype="int64"),)))
                  for _ in range(steps)]
        return losses, step, \
            fam.labels(codec=codec, path="traced").value - c0

    l32, s32, bytes_fp32 = run("fp32")
    lb, sb, bytes_bf16 = run("bf16")
    lq, sq, bytes_blk = run("int8_block")
    # counters tick per EXECUTED step with the actual traced wire bytes
    assert bytes_fp32 == 2 * s32.comm_stats["comm_bytes"]
    assert bytes_blk == 2 * sq.comm_stats["comm_bytes"]
    # int8_block: 4x under fp32; vs bf16 the payload halves again and the
    # fp32-scale-per-1024-elements overhead costs ~0.4% (1.99x)
    assert bytes_fp32 >= 3.9 * bytes_blk
    assert bytes_bf16 >= 1.98 * bytes_blk
    # and the quantized compiled run still trains
    assert lq[-1] <= lq[0] * 1.02
    assert abs(lq[0] - l32[0]) / l32[0] < 0.05


def _train_mlp_step_flagged(codec, flag, steps=4, clip=None,
                            block_size=128):
    """_train_mlp_step with FLAGS_kernel_autotune toggled for the run —
    the fused dequant+update wiring (ISSUE 13 follow-on, PR 15) keys off
    the flag at trace time."""
    from paddle_tpu.framework import flags as flags_mod

    flags_mod.set_flags({"FLAGS_kernel_autotune": bool(flag)})
    try:
        mesh_mod.set_mesh(mesh_mod.build_mesh(
            {"data": 2}, devices=jax.devices()[:2]))
        paddle.seed(7)
        net = _mlp()
        opt = optim.AdamW(learning_rate=1e-2, parameters=net.parameters(),
                          grad_clip=clip)
        gc = grad_comm.GradCommConfig(
            codec, comm_buffer_size=0.0002, last_comm_buffer_size=0.0001,
            block_size=block_size)
        step = TrainStep(net, F.mse_loss, opt, grad_comm=gc)
        losses = [float(step(inputs=(paddle.to_tensor(X),),
                             labels=(paddle.to_tensor(Y),)))
                  for _ in range(steps)]
        params = [np.asarray(p._value) for p in net.parameters()]
        slots = [{k: np.asarray(v) for k, v in s.items()}
                 for s in step._slots]
        return losses, params, slots, step
    finally:
        flags_mod.set_flags({"FLAGS_kernel_autotune": False})


def test_trainstep_gc_fused_dequant_update_parity():
    """ISSUE 13 follow-on (PR 15 satellite): with the kernel flag on, the
    compiled TrainStep(grad_comm=) keeps the summed blockwise payload and
    the fused pallas dequant+update kernel consumes it — the decoded
    gradient never materializes in HBM. Parity pin vs the jnp decode
    path: same losses, params and moments (CPU interpret mode runs the
    kernel's exact op sequence; documented fma freedom is below these
    tolerances on this model)."""
    l_jnp, p_jnp, s_jnp, _ = _train_mlp_step_flagged("int8_block", False)
    l_fused, p_fused, s_fused, step = _train_mlp_step_flagged(
        "int8_block", True)
    np.testing.assert_allclose(l_fused, l_jnp, rtol=1e-6)
    for a, b in zip(p_fused, p_jnp):
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)
    for sa, sb in zip(s_fused, s_jnp):
        for k in sb:
            np.testing.assert_allclose(sa[k], sb[k], rtol=1e-6,
                                       atol=1e-7, err_msg=k)
    # wire accounting is the same payload either way
    assert step.comm_stats["path"] == "traced"
    assert step.comm_stats["codec"] == "int8_block"


def test_trainstep_gc_fused_gated_off_by_clip():
    """grad_clip needs the decoded gradients — the fused payload path
    must step aside (flag on, clip configured) and still match the
    flag-off run exactly (both run the jnp decode + clip)."""
    from paddle_tpu.nn import ClipGradByGlobalNorm

    l_off, p_off, _, _ = _train_mlp_step_flagged(
        "int8_block", False, clip=ClipGradByGlobalNorm(0.5))
    l_on, p_on, _, _ = _train_mlp_step_flagged(
        "int8_block", True, clip=ClipGradByGlobalNorm(0.5))
    assert l_on == l_off
    for a, b in zip(p_on, p_off):
        np.testing.assert_array_equal(a, b)


def test_trainstep_gc_rejects_unsupported_compositions():
    net = _mlp()
    opt = optim.SGD(learning_rate=0.1, parameters=net.parameters())
    with pytest.raises(ValueError, match="grad_accum"):
        TrainStep(net, F.mse_loss, opt, grad_accum_steps=2,
                  grad_comm="int8_block")
    with pytest.raises(ValueError, match="unknown grad_comm codec"):
        TrainStep(net, F.mse_loss, opt, grad_comm="fp8")


# ------------------------------------------------------- hapi + strategy
def test_strategy_block_size_reaches_config():
    st = fleet.DistributedStrategy()
    st.grad_comm = True
    st.grad_comm_configs = {"codec": "fp8_block", "block_size": 512}
    cfg = grad_comm.config_from_strategy(st)
    assert cfg.codec == "fp8_block" and cfg.block_size == 512
    with pytest.raises(ValueError):
        st.grad_comm_configs = {"bogus_knob": 1}


def test_hapi_fused_step_picks_up_strategy_grad_comm():
    """Model.prepare(jit_compile)'s TrainStep carries the strategy codec
    when fleet ran with grad_comm on (and stays inert without a mesh)."""
    from paddle_tpu.hapi import Model

    strategy = fleet.DistributedStrategy()
    strategy.grad_comm = True
    strategy.grad_comm_configs = {"codec": "int8_block"}
    from paddle_tpu.distributed.fleet import _fleet_state

    saved = dict(_fleet_state)
    try:
        fleet.init(is_collective=True, strategy=strategy)
        net = _mlp()
        model = Model(net)
        model.prepare(optimizer=optim.SGD(learning_rate=0.1,
                                          parameters=net.parameters()),
                      loss=F.mse_loss)
        model.train_batch([X], [Y])
        assert model._train_step is not None
        assert model._train_step._gc_comm is not None
        assert model._train_step._gc_comm.config.codec == "int8_block"
    finally:
        _fleet_state.clear()
        _fleet_state.update(saved)


# --------------------------------------------------- cost model + tooling
def test_comm_cost_blockwise_pricing():
    from paddle_tpu.cost_model import comm_cost

    gb = 350e6
    bf16 = comm_cost(gb, world=8, codec="bf16")
    blk = comm_cost(gb, world=8, codec="int8_block")
    fp8 = comm_cost(gb, world=8, codec="fp8_block", block_size=512)
    int8 = comm_cost(gb, world=8, codec="int8")
    assert bf16["time_s"] > blk["time_s"]
    # scale overhead priced: 4B per block_size elements of fp32 grads
    assert blk["wire_bytes"] == int(gb * 0.25 + gb / 1024)
    assert fp8["wire_bytes"] == int(gb * 0.25 + gb / 512)
    assert blk["wire_bytes"] > int8["wire_bytes"] - 1  # scales cost a bit
    # blockwise pays the scale-exchange collective per bucket, like int8
    import math
    assert blk["collectives"] == 2 * math.ceil(
        blk["wire_bytes"] / (25 * 1024 * 1024))


def test_grad_comm_bench_traced_columns_and_artifact():
    import sys

    sys.path.insert(0, os.path.join(REPO, "tools"))
    import grad_comm_bench

    d = json.load(open(os.path.join(REPO, "artifacts",
                                    "grad_comm_bench.json")))
    rows = d["codecs"]
    for codec in grad_comm.CODECS:
        assert codec in rows, codec
        row = rows[codec]
        assert row["traced_path"] == "traced"
        # the compiled wire moves the PLANNED codec bytes, not raw fp32
        assert row["traced_comm_bytes_per_step"] == \
            row["planned_comm_bytes"]
    assert rows["fp32"]["traced_comm_bytes_per_step"] >= \
        3.9 * rows["int8_block"]["traced_comm_bytes_per_step"]
    assert rows["bf16"]["traced_comm_bytes_per_step"] >= \
        1.98 * rows["int8_block"]["traced_comm_bytes_per_step"]

    # the tool measures what it plans, live (1 traced step per codec)
    model = grad_comm_bench._build_model()
    params = [p for p in model.parameters() if not p.stop_gradient]
    traced = grad_comm_bench.measure_traced(params, steps=1)
    for codec, row in traced.items():
        plan = grad_comm.comm_plan(
            params, grad_comm.GradCommConfig(codec=codec))
        assert row["traced_comm_bytes_per_step"] == \
            plan["comm_bytes_per_step"], codec


def test_bench_gate_covers_traced_wire_bytes():
    import sys

    sys.path.insert(0, os.path.join(REPO, "tools"))
    import bench_gate

    base = {"value": 1000.0, "comm_bytes_per_step_traced": 125160}
    worse = {"value": 1000.0, "comm_bytes_per_step_traced": 249344}
    trajectory = [("r1", base)]
    rows, compared, regressed = bench_gate.gate(worse, trajectory, 0.20)
    verdicts = {r["metric"]: r["verdict"] for r in rows}
    assert verdicts["comm_bytes_per_step_traced"] == "REGRESSED"
    assert regressed >= 1
    rows, compared, regressed = bench_gate.gate(dict(base), trajectory, 0.20)
    verdicts = {r["metric"]: r["verdict"] for r in rows}
    assert verdicts["comm_bytes_per_step_traced"] == "OK"
    assert regressed == 0


# ------------------------------------------------------- static analysis
def test_codec_purity_rule_t002():
    from paddle_tpu.analysis import analyze_sources

    dirty = (
        "import numpy as np\n"
        "import jax.numpy as jnp\n"
        "def block_encode(flat, scales, bs, codec):\n"
        "    return np.round(flat / scales)\n")
    clean = (
        "import jax.numpy as jnp\n"
        "def block_encode(flat, scales, bs, codec):\n"
        "    return jnp.round(flat / scales)\n")
    path = "paddle_tpu/distributed/grad_comm.py"
    findings = analyze_sources({path: dirty})
    assert any(f.rule == "T002" for f in findings), findings
    assert not any(f.rule == "T002"
                   for f in analyze_sources({path: clean}))
    # same source elsewhere is not a codec module — rule scoped tight
    assert not any(f.rule == "T002"
                   for f in analyze_sources({"paddle_tpu/x.py": dirty}))
    from paddle_tpu.analysis import RULES

    assert "T002" in RULES and all(RULES["T002"])


def test_repo_codecs_clean_under_t002():
    """The real codec module passes its own rule (the static gate keeps
    the shared-verbatim contract enforced in tier-1)."""
    from paddle_tpu.analysis import analyze_sources

    path = os.path.join(REPO, "paddle_tpu", "distributed", "grad_comm.py")
    findings = analyze_sources(
        {"paddle_tpu/distributed/grad_comm.py": open(path).read()})
    assert not [f for f in findings if f.rule == "T002"]

"""Elastic resharding + preemption tolerance (ISSUE 10:
distributed/sharding/reshard.py, robustness/preemption.py,
CheckpointManager.load_sharded/gc hardening, ResumableLoader rank
streams, ElasticController reshard-on-scale).

Covers the tentpole contract: an N→M sharded-checkpoint transform that is
BIT-IDENTICAL to the gather→rewrap reference for fp32 params and slots
(gpt-test world=4 → 2 and 6), the documented residual re-split policy,
geometry-drifted loads resharding instead of refusing (typed refusal
without the flag), SIGTERM → latched → emergency checkpoint at the step
boundary (tagged, retention-exempt) → resumable stop — plus the
satellites (manifest hardening, GC exemption, loader stream
reassignment, bench gates).
"""
import json
import os
import signal

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
import paddle_tpu.optimizer as optim
import paddle_tpu.distributed.collective as coll
from paddle_tpu.distributed import grad_comm
from paddle_tpu.distributed.sharding import (
    Stage3ParamShards, save_group_sharded_checkpoint,
)
from paddle_tpu.distributed.sharding import reshard as rs
from paddle_tpu.framework.errors import CheckpointGeometryError
from paddle_tpu.io import DataLoader
from paddle_tpu.observability import get_registry
from paddle_tpu.optimizer.fused import FusedFlatUpdater
from paddle_tpu.robustness import (
    CheckpointManager, PreemptionHandler, ResumableLoader,
)
from paddle_tpu.robustness import distributed_ft as ft

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
rng = np.random.RandomState(0)


@pytest.fixture(autouse=True)
def reset_mesh(fresh_mesh):
    # an ambient mesh left by earlier suites would flip the stores out of
    # single-process emulation (no peer shards) and reshard the fit
    # TrainStep; fresh_mesh (conftest) owns save/clear/restore
    yield

X = rng.standard_normal((16, 8)).astype(np.float32)
Y = rng.standard_normal((16, 1)).astype(np.float32)


def _mlp(seed=7):
    paddle.seed(seed)
    return nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 1))


def _cfg(codec="fp32"):
    return grad_comm.GradCommConfig(codec, comm_buffer_size=0.0002,
                                    last_comm_buffer_size=0.0001,
                                    block_size=64)


def _store_for(net, world, codec="fp32"):
    params = [p for p in net.parameters() if not p.stop_gradient]
    comm = grad_comm.GradCommunicator(_cfg(codec))
    store = Stage3ParamShards(params, comm, rank=0, world=world)
    store.shard_()
    return store, comm, params


# ------------------------------------------------------------ pure transform
class TestTransform:
    def test_emulated_rewrap_bit_identical(self):
        """world=4 → 3: the transformed shards equal a fresh world=3
        store's sharding of the SAME full parameters (gather→rewrap),
        bit for bit, own and peer shards alike."""
        net = _mlp(seed=11)
        store4, _, _ = _store_for(net, 4)
        state = store4.state_dict()
        new = rs.reshard_zero3_states([state], 3)[0]
        assert new["world"] == 3 and new["rank"] == 0
        net_ref = _mlp(seed=11)  # same init → same full params
        ref_store, _, _ = _store_for(net_ref, 3)
        ref = ref_store.state_dict()
        assert set(new["shards"]) == set(ref["shards"])
        for i in ref["shards"]:
            assert np.array_equal(np.asarray(ref["shards"][i]),
                                  np.asarray(new["shards"][i])), i
            assert set(new["peer_shards"][i]) == {1, 2}
            for r in ref["peer_shards"][i]:
                assert np.array_equal(
                    np.asarray(ref["peer_shards"][i][r]),
                    np.asarray(new["peer_shards"][i][r])), (i, r)

    def test_real_multifile_layout_roundtrip(self):
        """N real per-rank states (own shards only) → M per-rank states;
        the reassembled full buckets are unchanged."""
        net = _mlp(seed=3)
        store, _, _ = _store_for(net, 4)
        emu = store.state_dict()
        # split the emulated state into 4 "real" per-rank states
        states = []
        for r in range(4):
            shards = {i: (emu["shards"][i] if r == 0
                          else emu["peer_shards"][i][r])
                      for i in emu["shards"]}
            states.append({"bucket_key": emu["bucket_key"], "rank": r,
                           "world": 4, "bucket_sizes": emu["bucket_sizes"],
                           "shards": shards})
        want = rs.assemble_full_buckets(states)
        out = rs.reshard_zero3_states(states, 6)
        assert len(out) == 6
        assert all(o["world"] == 6 and "peer_shards" not in o for o in out)
        got = rs.assemble_full_buckets(out)
        for i in want:
            assert np.array_equal(want[i], got[i]), i
            # chunk geometry is ceil(size/6)
            size = emu["bucket_sizes"][i]
            assert len(out[0]["shards"][i]) == rs.chunk_of(size, 6)

    def test_residual_policy_sum_preserved(self):
        """Σ over new ranks of the re-split residuals == Σ over old ranks
        (the invariant the next sync's error re-injection depends on)."""
        maps = [{0: np.full(7, float(r + 1), np.float32),
                 2: np.arange(7, dtype=np.float32) * (r + 1)}
                for r in range(4)]
        out = rs.reshard_residual_maps(maps, 3)
        assert len(out) == 3
        for k in (0, 2):
            want = np.sum([m[k] for m in maps], axis=0)
            got = np.sum([m[k] for m in out], axis=0)
            np.testing.assert_allclose(got, want, rtol=1e-6)
        # single shared map (emulation) passes through unchanged
        solo = rs.reshard_residual_maps([{1: np.ones(3, np.float32)}], 1)
        np.testing.assert_array_equal(solo[0][1], np.ones(3, np.float32))

    def test_slot_state_rechunk_bit_identical(self, monkeypatch):
        """Adam shard slots re-chunk exactly: the full flat moment buffers
        reassembled from world=4 and from the transformed world=2 states
        are the same bytes; scalar slots (beta pows) are copied."""
        def fake(t, op=None, group=None, **kw):
            return t
        monkeypatch.setattr(coll, "all_reduce", fake)
        net = _mlp(seed=5)
        o = optim.Adam(learning_rate=0.05, parameters=net.parameters())
        store, comm, params = _store_for(net, 4)
        store.install_hooks(net)
        fused = FusedFlatUpdater(o, params, communicator=comm)
        loss = F.mse_loss(net(paddle.to_tensor(X)), paddle.to_tensor(Y))
        loss.backward()
        comm.sync(params, world=4, use_reduce_scatter=True)
        fused.step_sharded(rank=0, world=4, param_store=store)
        st = fused.shard_slots_state()
        assert st["bucket_sizes"]
        out = rs.reshard_slot_states([st], 2)[0]
        sizes = st["bucket_sizes"]
        for i, slots in st["own"].items():
            for k, v in slots.items():
                if np.shape(v) == ():
                    assert float(out["own"][i][k]) == float(v)
                    continue
                full4 = np.concatenate(
                    [np.asarray(st["own"][i][k])]
                    + [np.asarray(st["peer"][(i, r)][k])
                       for r in range(1, 4)])[:sizes[i]]
                full2 = np.concatenate(
                    [np.asarray(out["own"][i][k]),
                     np.asarray(out["peer"][(i, 1)][k])])[:sizes[i]]
                assert np.array_equal(full4, full2), (i, k)

    def test_missing_bucket_sizes_refused_loudly(self):
        net = _mlp()
        store, _, _ = _store_for(net, 2)
        state = store.state_dict()
        state.pop("bucket_sizes")
        from paddle_tpu.framework.errors import CheckpointCorruptError

        with pytest.raises(CheckpointCorruptError, match="bucket_sizes"):
            rs.reshard_zero3_states([state], 3)

    def test_reshard_report_measures_and_verifies(self):
        net = _mlp()
        rep = rs.reshard_report([p for p in net.parameters()], _cfg(),
                                old_world=4, new_world=2)
        assert rep["bit_identical"] and rep["reshard_ms"] >= 0
        assert rep["from_world"] == 4 and rep["to_world"] == 2
        snap = get_registry().snapshot()
        assert snap["reshard_ms"] == rep["reshard_ms"]


# ----------------------------------------------------- acceptance (gpt-test)
class TestGptAcceptance:
    """The acceptance bar: a gpt-test ZeRO-3 job checkpointed at world=4
    resumes at world=2 AND world=6 with fp32 params/slots bit-identical
    to the gather→rewrap reference, and training CONTINUES through the
    resharded geometry to the uninterrupted run's exact losses."""

    STEPS, KILL_AT = 4, 2

    def _build(self, world, codec="fp32"):
        from paddle_tpu.models import (
            GPTForCausalLM, GPTPretrainingCriterion, gpt_presets,
        )

        paddle.seed(1234)
        m = GPTForCausalLM(gpt_presets("gpt-test"), seed=7)
        crit = GPTPretrainingCriterion()
        o = optim.AdamW(learning_rate=1e-3, parameters=m.parameters())
        cfg = grad_comm.GradCommConfig(
            codec, comm_buffer_size=0.05, last_comm_buffer_size=0.01,
            block_size=64)
        comm = grad_comm.GradCommunicator(cfg)
        params = [p for p in m.parameters() if not p.stop_gradient]
        fused = FusedFlatUpdater(o, params, communicator=comm)
        store = Stage3ParamShards(params, comm, rank=0, world=world)
        store.shard_()
        store.install_hooks(m)
        m._zero3 = store
        return m, crit, comm, fused, store, params

    @staticmethod
    def _one(m, crit, comm, fused, store, params, ids, labels, world):
        loss = crit(m(paddle.to_tensor(ids, dtype="int64")),
                    paddle.to_tensor(labels, dtype="int64"))
        loss.backward()
        comm.sync(params, world=world, use_reduce_scatter=True)
        fused.step_sharded(rank=0, world=world, param_store=store)
        for p in params:
            p.clear_grad()
        return float(loss.numpy())

    def test_world4_to_2_and_6_bit_identical(self, tmp_path):
        rs_np = np.random.RandomState(0)
        ids = rs_np.randint(0, 256, (2, 16)).astype(np.int64)
        labels = rs_np.randint(0, 256, (2, 16)).astype(np.int64)

        # ---------------- reshape-reference: uninterrupted at world=4
        m, crit, comm, fused, store, params = self._build(4)
        want = [self._one(m, crit, comm, fused, store, params, ids,
                          labels, 4) for _ in range(self.STEPS)]

        # ---------------- kill at step 2, emergency sharded save
        m, crit, comm, fused, store, params = self._build(4)
        got = [self._one(m, crit, comm, fused, store, params, ids,
                         labels, 4) for _ in range(self.KILL_AT)]
        mgr = save_group_sharded_checkpoint(
            m, str(tmp_path), self.KILL_AT, rank=0, world_size=1,
            fused=fused,
            job_state=ft.capture_job_state(reducer=comm, zero3=store),
            metadata={"reason": "preemption"})
        full4 = rs.assemble_full_buckets([store.state_dict()])
        slots4 = fused.shard_slots_state()
        del m, crit, comm, fused, store, params  # "the process dies here"

        # ---------------- resume at world=2 and CONTINUE training
        paddle.seed(999)  # different entropy — restore must win
        m, crit, comm, fused, store, params = self._build(2)
        with pytest.raises(CheckpointGeometryError):  # refusal is typed
            mgr.load_sharded(rank=0, world_size=1, zero3_world=2)
        payload, step, manifest = mgr.load_sharded(
            rank=0, world_size=1, zero3_world=2, allow_reshard=True)
        assert step == self.KILL_AT
        store.load_state_dict(payload["zero3"])
        fused.load_shard_slots_state(payload["fused_shard_slots"])
        restored = ft.restore_job_state(payload["job_state"], reducer=comm,
                                        zero3=store, allow_reshard=True)
        assert {"rng", "zero3"} <= set(restored)
        # params bit-identical to gather→rewrap: reassembled full buckets
        # equal the world=4 store's
        full2 = rs.assemble_full_buckets([store.state_dict()])
        for i in full4:
            assert np.array_equal(full4[i], full2[i]), i
        # slots bit-identical (full flat moment buffers)
        slots2 = fused.shard_slots_state()
        sizes = slots4["bucket_sizes"]
        for i, sl in slots4["own"].items():
            for k, v in sl.items():
                if np.shape(v) == ():
                    continue
                w = np.concatenate(
                    [np.asarray(slots4["own"][i][k])]
                    + [np.asarray(slots4["peer"][(i, r)][k])
                       for r in range(1, 4)])[:sizes[i]]
                g = np.concatenate(
                    [np.asarray(slots2["own"][i][k]),
                     np.asarray(slots2["peer"][(i, 1)][k])])[:sizes[i]]
                assert np.array_equal(w, g), (i, k)
        got += [self._one(m, crit, comm, fused, store, params, ids,
                          labels, 2) for _ in range(self.STEPS -
                                                    self.KILL_AT)]
        assert got == want, (got, want)  # EXACT equality through the shrink

        # ---------------- resume at world=6 (grow): geometry + bits
        m6, crit6, comm6, fused6, store6, params6 = self._build(6)
        payload6, _, _ = mgr.load_sharded(
            rank=0, world_size=1, zero3_world=6, allow_reshard=True)
        store6.load_state_dict(payload6["zero3"])
        fused6.load_shard_slots_state(payload6["fused_shard_slots"])
        full6 = rs.assemble_full_buckets([store6.state_dict()])
        for i in full4:
            assert np.array_equal(full4[i], full6[i]), i
        for b in store6.buckets:
            assert len(store6.own_shard(b.index)) == \
                rs.chunk_of(b.size, 6)
        # the transform was counted
        snap = get_registry().snapshot()
        totals = snap.get("reshard_total", {})
        assert any("from_world=4" in k and "to_world=2" in k
                   for k in totals), totals
        assert any("from_world=4" in k and "to_world=6" in k
                   for k in totals), totals

    def test_int8_block_convergence_parity_through_shrink(self,
                                                          monkeypatch):
        """Blockwise-quantized training across a 4→2 shrink: the shared
        scales change granularity with the world (summed abs-max over 2
        vs 4 emulated ranks), so bit-equality is not expected — but the
        residual re-split policy must keep the resumed trajectory within
        convergence-parity of the uninterrupted world=4 run (pinned
        band), and the residual mass is preserved exactly."""
        world_holder = [4]

        def fake_all_reduce(t, op=None, group=None, **kw):
            # identical-replica emulation at any world: SUM-typed
            # exchanges (int payloads and fp32 abs-max vectors) scale by
            # the emulated world; AVG/MAX are identity
            if op == coll.ReduceOp.SUM:
                t._value = t._value * world_holder[0]
            return t

        monkeypatch.setattr(coll, "all_reduce", fake_all_reduce)
        rs_np = np.random.RandomState(1)
        ids = rs_np.randint(0, 256, (2, 16)).astype(np.int64)
        labels = rs_np.randint(0, 256, (2, 16)).astype(np.int64)

        m, crit, comm, fused, store, params = self._build(
            4, codec="int8_block")
        want = [self._one(m, crit, comm, fused, store, params, ids,
                          labels, 4) for _ in range(4)]
        assert comm._residuals  # the codec really carried

        m, crit, comm, fused, store, params = self._build(
            4, codec="int8_block")
        got = [self._one(m, crit, comm, fused, store, params, ids,
                         labels, 4) for _ in range(2)]
        res_before = {k: np.asarray(v).copy()
                      for k, v in comm._residuals.items()}
        state = store.state_dict()
        slots = fused.shard_slots_state()
        js = ft.capture_job_state(reducer=comm, zero3=store)

        paddle.seed(999)
        world_holder[0] = 2
        m, crit, comm, fused, store, params = self._build(
            2, codec="int8_block")
        payload = rs.reshard_payloads(
            [{"zero3": state, "fused_shard_slots": slots,
              "job_state": js}], 2)[0]
        store.load_state_dict(payload["zero3"])
        fused.load_shard_slots_state(payload["fused_shard_slots"])
        ft.restore_job_state(payload["job_state"], reducer=comm,
                             zero3=store, allow_reshard=True)
        # emulated single communicator: residuals pass through EXACTLY
        for k, v in res_before.items():
            assert np.array_equal(v, np.asarray(comm._residuals[k])), k
        got += [self._one(m, crit, comm, fused, store, params, ids,
                          labels, 2) for _ in range(2)]
        # convergence parity: same first half, post-shrink steps within a
        # pinned band of the reference trajectory (scale granularity
        # changed, values may not be bit-equal)
        assert got[:2] == want[:2]
        for g, w in zip(got[2:], want[2:]):
            assert abs(g - w) <= 0.05 * abs(w) + 1e-3, (got, want)


# ----------------------------------------------- manager + elastic wiring
class TestLoadShardedAndElastic:
    def _sharded_ckpt(self, root, world=2, step=5):
        mgr = CheckpointManager(str(root))
        for r in range(world):
            mgr.save_shard({"model": {"w": np.full(4, r, np.float32)},
                            "job_state": {"rank": r, "rng": None}},
                           step, r, world)
        mgr.finalize_sharded(step, world)
        return mgr

    def test_reshard_checkpoint_commits_new_geometry(self, tmp_path):
        net = _mlp(seed=2)
        store, comm, params = _store_for(net, 4)
        net._zero3 = store
        mgr = save_group_sharded_checkpoint(
            net, str(tmp_path), 3, rank=0, world_size=1,
            job_state=ft.capture_job_state(reducer=comm, zero3=store))
        manifest = rs.reshard_checkpoint(mgr, 3, 2)
        assert manifest["metadata"]["resharded_from"] == 4
        assert manifest["metadata"]["resharded_to"] == 2
        payload = mgr.load(3, shard=0)
        assert payload["zero3"]["world"] == 2
        assert set(payload["zero3"]["peer_shards"][0]) == {1}
        # no-op when geometry already matches
        m2 = rs.reshard_checkpoint(mgr, 3, 2)
        assert m2["metadata"]["resharded_to"] == 2

    def test_load_sharded_plain_and_refusal(self, tmp_path):
        mgr = self._sharded_ckpt(tmp_path, world=2, step=5)
        payload, step, manifest = mgr.load_sharded(rank=1, world_size=2)
        assert step == 5 and payload["job_state"]["rank"] == 1
        with pytest.raises(CheckpointGeometryError) as ei:
            mgr.load_sharded(rank=0, world_size=3)
        assert ei.value.from_world == 2 and ei.value.to_world == 3
        # transform path: 2 files -> 3 payloads, model replicated
        p0, _, _ = mgr.load_sharded(rank=2, world_size=3,
                                    allow_reshard=True)
        np.testing.assert_array_equal(p0["model"]["w"],
                                      np.zeros(4, np.float32))
        assert p0["job_state"]["rank"] == 2
        # step defaults to the newest valid sharded one
        assert mgr.load_sharded(world_size=2)[1] == 5

    def test_elastic_controller_reshards_on_scale(self, tmp_path):
        from paddle_tpu.distributed.fleet.elastic import (
            ElasticController, ElasticManager, LocalKVStore,
        )

        net = _mlp(seed=4)
        store, comm, params = _store_for(net, 4)
        net._zero3 = store
        mgr = save_group_sharded_checkpoint(
            net, str(tmp_path), 7, rank=0, world_size=1,
            job_state=ft.capture_job_state(reducer=comm, zero3=store))
        ctl = ElasticController(
            ElasticManager("h0", "1:4", store=LocalKVStore()),
            launch_fn=lambda eps: [], checkpoint_manager=mgr)
        info = ctl._maybe_reshard(3)   # the shrink-restart path
        assert info == {"step": 7, "from_world": 4, "to_world": 3}
        assert ctl.reshard_events == [info]
        payload = mgr.load(7, shard=0)
        assert payload["zero3"]["world"] == 3
        # matching world: no-op; disabled: no-op
        assert ctl._maybe_reshard(3) is None
        ctl.reshard_on_scale = False
        assert ctl._maybe_reshard(2) is None


# ------------------------------------------------- manifest + retention GC
class TestCheckpointHardening:
    def test_incomplete_sharded_manifest_falls_back(self, tmp_path):
        """Satellite 1: a sharded manifest whose world_size exceeds its
        shard entries is INVALID — load_latest falls back to the newest
        fully-valid step instead of surfacing a late typed error."""
        mgr = CheckpointManager(str(tmp_path))
        # good earlier sharded checkpoint
        for r in range(2):
            mgr.save_shard({"w": r}, 1, r, 2)
        mgr.finalize_sharded(1, 2)
        # later checkpoint whose manifest CLAIMS world_size=3 with 2 shards
        for r in range(2):
            mgr.save_shard({"w": r}, 2, r, 2)
        mgr.finalize_sharded(2, 2)
        mpath = os.path.join(mgr.step_path(2), "MANIFEST.json")
        man = json.loads(open(mpath).read())
        man["world_size"] = 3
        with open(mpath, "w") as f:
            f.write(json.dumps(man))
        assert mgr.validate(2) is None
        state, step, manifest = mgr.load_latest()
        assert step == 1 and manifest["world_size"] == 2

    def test_preemption_checkpoints_exempt_from_retention(self, tmp_path):
        """Satellite 2: emergency saves neither count toward keep-last-N
        nor get deleted by it — a preemption save can't evict the last
        full periodic checkpoint."""
        mgr = CheckpointManager(str(tmp_path), keep_last_n=2)
        mgr.save({"w": 1}, 0)
        mgr.save({"w": 2}, 1)
        from paddle_tpu.robustness.preemption import timed_emergency_save

        ms = timed_emergency_save(mgr, {"w": 3}, 2,
                                  job_state={"rank": 0})
        assert ms >= 0
        assert mgr.is_emergency(2) and not mgr.is_emergency(1)
        # two more periodic saves: retention works over PERIODIC steps
        # only — the emergency step survives, and so do the newest 2
        # periodic ones
        mgr.save({"w": 4}, 3)
        mgr.save({"w": 5}, 4)
        assert mgr.steps() == [2, 3, 4]
        snap = get_registry().snapshot()
        assert snap["emergency_checkpoints_total"] >= 1
        assert snap["emergency_save_ms"] == pytest.approx(ms, abs=1e-3)


# --------------------------------------------------------- preemption latch
class TestPreemptionHandler:
    def test_sigterm_latches_and_exit_status(self):
        h = PreemptionHandler(grace_seconds=5.0).install()
        try:
            assert not h.should_stop()
            os.kill(os.getpid(), signal.SIGTERM)
            assert h.wait(2.0)
            assert h.should_stop() and h.requested
            assert h.exit_status() == 128 + int(signal.SIGTERM)
            assert 0 < h.grace_remaining() <= 5.0
        finally:
            h.uninstall()
        snap = get_registry().snapshot()
        assert any(k.startswith("source=signal")
                   for k in snap.get("preemptions_total", {}))

    def test_flag_file_latches_sticky(self, tmp_path):
        flag = str(tmp_path / "preempt.flag")
        h = PreemptionHandler(flag_file=flag)
        assert not h.requested
        open(flag, "w").write("evict")
        assert h.should_stop()
        os.remove(flag)
        assert h.requested  # sticky
        h.reset()
        assert not h.requested

    def test_programmatic_request(self):
        h = PreemptionHandler()
        h.request()
        assert h.should_stop() and h.exit_status() == 128 + 15

    def test_fit_stops_at_step_boundary_with_emergency_save(self,
                                                            tmp_path):
        """hapi integration: a latched preemption stops fit at the next
        step boundary and commits a tagged emergency checkpoint through
        the RobustCheckpoint callback."""
        from paddle_tpu.hapi import Model
        from paddle_tpu.hapi.callbacks import RobustCheckpoint

        paddle.seed(0)
        net = _mlp()
        model = Model(net)
        model.prepare(optim.SGD(learning_rate=0.05,
                                parameters=net.parameters()),
                      loss=F.mse_loss)
        h = PreemptionHandler()
        data = list(zip(X, Y))

        class TripWire(RobustCheckpoint):
            pass

        rc = TripWire(str(tmp_path / "ckpt"), save_freq=100)
        seen = []

        orig = Model.train_batch

        def counting(self, *a, **kw):
            out = orig(self, *a, **kw)
            seen.append(1)
            if len(seen) == 3:
                h.request()   # the eviction notice, mid-run
            return out

        Model.train_batch = counting
        try:
            model.fit(data, batch_size=4, epochs=5, verbose=0,
                      callbacks=[rc], preemption=h)
        finally:
            Model.train_batch = orig
        assert model.preempted and model.stop_training
        assert len(seen) == 3   # stopped at the boundary right after
        mgr = rc.manager
        found = mgr.load_latest()
        assert found is not None
        _state, step, manifest = found
        assert manifest["metadata"]["reason"] == "preemption"
        assert mgr.is_emergency(step)
        # resumable: weights + job_state present
        assert "model" in found[0]
        assert mgr.load_job_state(step) is not None

    def test_train_epoch_range_preemption(self, tmp_path):
        from paddle_tpu.incubate.checkpoint.auto_checkpoint import (
            TrainEpochRange,
        )

        h = PreemptionHandler()
        seen = []
        r = TrainEpochRange(6, save_dir=str(tmp_path), job_id="j1",
                            state={"x": {"v": 1}}, preemption_handler=h)
        for epoch in r:
            seen.append(epoch)
            if epoch == 2:
                h.request()
        assert seen == [0, 1, 2] and r.preempted
        assert r.ckpt.is_emergency(2)
        # restart resumes past the emergency-saved epoch
        r2 = TrainEpochRange(6, save_dir=str(tmp_path), job_id="j1",
                             state={"x": {"v": 1}})
        assert r2.start_epoch == 3


# --------------------------------------------- resumable loader satellites
class TestResumableLoaderElastic:
    def test_epoch_boundary_resume(self):
        """A checkpoint taken exactly at an epoch boundary resumes into
        the NEXT epoch's permutation — no spurious empty epoch, no epoch
        counter drift."""
        from paddle_tpu.framework import random as rng_mod

        data = [np.full((2,), i, np.float32) for i in range(8)]
        paddle.seed(42)
        ref = ResumableLoader(DataLoader(data, batch_size=2, shuffle=True))
        epoch0 = [np.asarray(b) for b in ref]
        epoch1_want = [np.asarray(b) for b in ref]

        paddle.seed(42)
        loader = ResumableLoader(DataLoader(data, batch_size=2,
                                            shuffle=True))
        got0 = [np.asarray(b) for b in loader]
        for w, g in zip(epoch0, got0):
            np.testing.assert_array_equal(w, g)
        state = loader.state_dict()
        assert state["batch_idx"] == 0 and state["epoch"] == 1
        rng_snap = rng_mod.get_rng_state()
        del loader  # "the process dies at the epoch boundary"

        paddle.seed(777)  # different entropy — restore must win
        loader2 = ResumableLoader(DataLoader(data, batch_size=2,
                                             shuffle=True))
        rng_mod.set_rng_state(rng_snap)
        loader2.load_state_dict(state)
        got1 = [np.asarray(b) for b in loader2]
        assert len(got1) == len(epoch1_want)
        for w, g in zip(epoch1_want, got1):
            np.testing.assert_array_equal(w, g)
        assert loader2.epoch == 2

    def test_world_change_stream_reassignment(self):
        """Fast-forward across a world-size change: the global stream
        position carries over and the remaining batches partition exactly
        across the NEW rank count (each exactly once, rank-strided)."""
        data = [np.full((1,), i, np.float32) for i in range(24)]

        def fresh(rank, world):
            return ResumableLoader(DataLoader(data, batch_size=1,
                                              shuffle=False),
                                   rank=rank, world=world)

        # world=4: run 2 steps on every rank (global position 8)
        states = []
        for r in range(4):
            ld = fresh(r, 4)
            it = iter(ld)
            mine = [int(next(it)[0]) for _ in range(2)]
            assert mine == [r, r + 4]
            states.append(ld.state_dict())
        # every rank's step-aligned state agrees on the global position
        assert {s["batch_idx"] for s in states} == {8}

        # resume at world=3 from rank 0's state
        taken = {}
        for r in range(3):
            ld = fresh(r, 3)
            ld.load_state_dict(states[0])
            ld.reassign(r, 3)
            taken[r] = [int(b[0]) for b in ld]
        # union = exactly the unconsumed tail, strided by the new world
        got = sorted(v for vs in taken.values() for v in vs)
        assert got == list(range(8, 24))
        for r in range(3):
            assert taken[r] == [g for g in range(8, 24) if g % 3 == r], \
                (r, taken)

    def test_world_one_unchanged_semantics(self):
        data = [np.full((2,), i, np.float32) for i in range(10)]
        paddle.seed(5)
        ld = ResumableLoader(DataLoader(data, batch_size=2, shuffle=True))
        it = iter(ld)
        next(it), next(it)
        st = ld.state_dict()
        assert st["batch_idx"] == 2 and st["world"] == 1
        assert len(ld) == 5

    def test_rank_bounds_validated(self):
        data = [np.zeros(1, np.float32)]
        with pytest.raises(ValueError, match="outside world"):
            ResumableLoader(DataLoader(data, batch_size=1), rank=3, world=2)
        ld = ResumableLoader(DataLoader(data, batch_size=1))
        with pytest.raises(ValueError, match="outside world"):
            ld.reassign(2, 2)


# --------------------------------------------------------------- bench gate
class TestBenchGateReshardFields:
    def test_gate_gates_reshard_and_emergency(self):
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "bench_gate", os.path.join(REPO, "tools", "bench_gate.py"))
        bg = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bg)
        base = {"value": 1000.0, "device_kind": "cpu", "fallback": "cpu",
                "reshard_ms": 10.0, "emergency_save_ms": 5.0}
        trajectory = [("r1", base)]
        ok = dict(base, reshard_ms=11.0, emergency_save_ms=5.5)
        rows, compared, regressed = bg.gate(ok, trajectory, 0.20)
        assert regressed == 0 and compared >= 3
        bad = dict(base, reshard_ms=15.0)
        rows, _, regressed = bg.gate(bad, trajectory, 0.20)
        assert regressed == 1
        row = {r["metric"]: r for r in rows}
        assert row["reshard_ms"]["verdict"] == "REGRESSED"
        slow = dict(base, emergency_save_ms=9.0)
        _, _, regressed = bg.gate(slow, trajectory, 0.20)
        assert regressed == 1
        # records predating ISSUE 10 just SKIP the new fields
        old = {"value": 1000.0, "device_kind": "cpu", "fallback": "cpu"}
        _, compared, regressed = bg.gate(old, trajectory, 0.20)
        assert regressed == 0 and compared >= 1

    def test_chaos_artifact_has_preempt_phase(self):
        d = json.load(open(os.path.join(REPO, "artifacts",
                                        "chaos_train.json")))
        pr = d["preempt"]
        assert pr["ok"] and pr["sigterm_latched"] and pr["resharded"]
        assert pr["refused_resumes"] == 0 and pr["refused_without_flag"]
        assert pr["world_from"] == 4 and pr["world_to"] == 3
        assert pr["emergency_save_ms"] > 0
        assert pr["losses_resumed"] == pr["losses_reference"]

"""Shared toy pipeline model for the 1F1B tests and throughput bench.

One definition of the stacked-tanh stage model (embed -> P stages of
KPER scanned layers -> linear head + MSE), its pipe-sharded PartitionSpecs,
and a contention-robust bench loop — used by tests/test_pipeline_1f1b.py,
tests/test_pipeline_throughput.py, and tools/pipeline_throughput.py so the
three can't drift apart.
"""
import time

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

DIN, DOUT = 32, 8

SPECS = {"we": P(), "w": P("pipe", None, None), "b": P("pipe", None),
         "wh": P()}


def make_params(rs, l_total, hid, din=DIN, dout=DOUT):
    return {
        "we": jnp.asarray(rs.randn(din, hid) * 0.3, jnp.float32),
        "w": jnp.asarray(rs.randn(l_total, hid, hid) * 0.3, jnp.float32),
        "b": jnp.asarray(rs.randn(l_total, hid) * 0.1, jnp.float32),
        "wh": jnp.asarray(rs.randn(hid, dout) * 0.3, jnp.float32),
    }


def embed_fn(p, r):
    return jnp.tanh(r @ p["we"])


def stage_fn(p, h):
    def one(carry, wl):
        w, b = wl
        return jnp.tanh(carry @ w + b), None

    out, _ = jax.lax.scan(one, h, (p["w"], p["b"]))
    return out


def loss_fn(p, y, lbl):
    return jnp.mean((y @ p["wh"] - lbl) ** 2)


def gpipe_value_and_grad(mesh, M, p, x, lbl, remat):
    """GPipe fill-drain train step: AD through pipeline_spmd, optionally
    with jax.checkpoint on the stage body (recompute parity with 1F1B).
    The comparison baseline used by both the throughput test and the
    bench tool."""
    from paddle_tpu.distributed.pipeline import pipeline_spmd

    body = jax.checkpoint(stage_fn) if remat else stage_fn

    def train_loss(p):
        h = embed_fn(p, x)
        y = pipeline_spmd(
            lambda sp, mbx: body({"w": sp[0], "b": sp[1]}, mbx),
            (p["w"], p["b"]), h, mesh=mesh,
            param_specs=(SPECS["w"], SPECS["b"]), microbatches=M)
        return loss_fn(p, y, lbl)

    return jax.value_and_grad(train_loss)(p)


def bench_min(fn, args, steps):
    """min-of-N per-step wall time: the minimum is robust to contention
    bursts on a shared host (any single clean window gives the true
    cost), unlike a mean over few iterations."""
    return bench_min_interleaved([fn], args, steps)[0]


def bench_min_interleaved(fns, args, steps):
    """min-of-N for SEVERAL step fns, measured round-robin so a
    multi-second contention burst (another process compiling, CI noisy
    neighbor) degrades every config's samples instead of landing entirely
    on whichever config happened to be mid-measurement — ratios between
    the returned minima stay meaningful under load."""
    for fn in fns:
        jax.block_until_ready(fn(*args))  # compile + warm each
    best = [float("inf")] * len(fns)
    for _ in range(steps):
        for i, fn in enumerate(fns):
            t0 = time.perf_counter()
            jax.block_until_ready(fn(*args))
            best[i] = min(best[i], time.perf_counter() - t0)
    return best

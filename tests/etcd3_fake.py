"""Socket-level fake etcd v3 gateway for elastic tests.

Speaks the actual protocol the client uses: HTTP/1.1 POSTs with the
grpc-gateway JSON mapping (base64 keys/values, int64s as strings) for
LeaseGrant/LeaseKeepAlive/LeaseRevoke/Put/Range/DeleteRange, plus the
chunked-streaming /v3/watch. Leases expire on a sweeper thread, firing
DELETE watch events — so TTL-based node-death detection is exercised
end to end over the wire.
"""
from __future__ import annotations

import base64
import itertools
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

__all__ = ["Etcd3Fake"]


def _unb64(s):
    return base64.b64decode(s)


def _b64(b):
    if isinstance(b, str):
        b = b.encode()
    return base64.b64encode(b).decode()


class _State:
    def __init__(self):
        self.kv = {}       # key(bytes) -> (value(bytes), lease_id)
        self.leases = {}   # id -> expires_at
        self.ttls = {}     # id -> ttl
        self.lock = threading.Lock()
        self.watchers = []  # (range_start, range_end, wfile, wlock)
        self.ids = itertools.count(7000)

    def fire(self, typ, key, value):
        ev = {"result": {"events": [
            {"type": typ, "kv": {"key": _b64(key),
                                 **({"value": _b64(value)} if value else {})}}
        ]}}
        line = (json.dumps(ev) + "\n").encode()
        dead = []
        for w in self.watchers:
            lo, hi, wfile, wlock = w
            if not (lo <= key < hi):
                continue
            try:
                with wlock:
                    wfile.write(b"%x\r\n%s\r\n" % (len(line), line))
                    wfile.flush()
            except OSError:
                dead.append(w)
        for w in dead:
            try:
                self.watchers.remove(w)
            except ValueError:
                pass

    def sweep(self):
        now = time.time()
        with self.lock:
            gone = [lid for lid, exp in self.leases.items() if exp <= now]
            for lid in gone:
                del self.leases[lid]
                self.ttls.pop(lid, None)
            victims = [k for k, (_, lid) in self.kv.items()
                       if lid and lid not in self.leases]
            for k in victims:
                del self.kv[k]
        for k in victims:
            self.fire("DELETE", k, None)


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, *a):
        pass

    def _json(self, obj, code=200):
        data = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_POST(self):
        st: _State = self.server.state
        n = int(self.headers.get("Content-Length", 0))
        body = json.loads(self.rfile.read(n) or b"{}")
        path = self.path

        if path == "/v3/lease/grant":
            ttl = int(body["TTL"])
            with st.lock:
                lid = next(st.ids)
                st.leases[lid] = time.time() + ttl
                st.ttls[lid] = ttl
            return self._json({"ID": str(lid), "TTL": str(ttl)})

        if path == "/v3/lease/keepalive":
            lid = int(body["ID"])
            with st.lock:
                live = lid in st.leases
                if live:
                    st.leases[lid] = time.time() + st.ttls[lid]
                ttl = st.ttls.get(lid, 0) if live else 0
            # gateway wraps the streaming response in {"result": ...}
            return self._json({"result": {"ID": str(lid),
                                          "TTL": str(int(ttl))}})

        if path == "/v3/lease/revoke":
            lid = int(body["ID"])
            with st.lock:
                st.leases.pop(lid, None)
            st.sweep()
            return self._json({})

        if path == "/v3/kv/put":
            key = _unb64(body["key"])
            val = _unb64(body["value"])
            lid = int(body.get("lease", 0) or 0)
            with st.lock:
                if lid and lid not in st.leases:
                    return self._json(
                        {"error": "etcdserver: requested lease not found",
                         "code": 5}, code=400)
                st.kv[key] = (val, lid)
            st.fire("PUT", key, val)
            return self._json({})

        if path == "/v3/kv/range":
            st.sweep()
            lo = _unb64(body["key"])
            hi = _unb64(body.get("range_end", "")) if body.get("range_end") \
                else lo + b"\x00"
            with st.lock:
                kvs = [{"key": _b64(k), "value": _b64(v)}
                       for k, (v, _) in sorted(st.kv.items())
                       if lo <= k < hi]
            return self._json({"kvs": kvs, "count": str(len(kvs))})

        if path == "/v3/kv/deleterange":
            key = _unb64(body["key"])
            with st.lock:
                existed = st.kv.pop(key, None)
            if existed is not None:
                st.fire("DELETE", key, None)
            return self._json({"deleted": "1" if existed else "0"})

        if path == "/v3/watch":
            lo = _unb64(body["create_request"]["key"])
            hi = _unb64(body["create_request"].get("range_end", "")) or \
                lo + b"\x00"
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()
            wlock = threading.Lock()
            created = (json.dumps({"result": {"created": True}}) + "\n"
                       ).encode()
            with wlock:
                self.wfile.write(b"%x\r\n%s\r\n" % (len(created), created))
                self.wfile.flush()
            st.watchers.append((lo, hi, self.wfile, wlock))
            # hold the connection, probing liveness with empty progress
            # notifications (client ignores event-less results); a closed
            # peer raises on write and ends the watch
            probe = (json.dumps({"result": {}}) + "\n").encode()
            while True:
                time.sleep(0.5)
                try:
                    with wlock:
                        self.wfile.write(b"%x\r\n%s\r\n"
                                         % (len(probe), probe))
                        self.wfile.flush()
                except OSError:
                    return

        self._json({"error": f"bad path {path}"}, code=404)


class Etcd3Fake:
    def __init__(self, sweep_interval=0.1, port=0):
        self.state = _State()
        self._server = ThreadingHTTPServer(("127.0.0.1", int(port)),
                                           _Handler)
        self._server.state = self.state
        self._server.daemon_threads = True
        self._stop = threading.Event()
        self.sweep_interval = sweep_interval

    def start(self):
        threading.Thread(target=self._server.serve_forever,
                         daemon=True).start()

        def sweeper():
            while not self._stop.is_set():
                self.state.sweep()
                self._stop.wait(self.sweep_interval)

        threading.Thread(target=sweeper, daemon=True).start()
        return self

    @property
    def endpoint(self):
        h, p = self._server.server_address[:2]
        return f"{h}:{p}"

    def stop(self):
        self._stop.set()
        self._server.shutdown()
        self._server.server_close()

"""Memory-bounded 1F1B pipeline schedule (distributed/pipeline.py).

VERDICT r2 missing #1: live activations bounded by pipeline depth P, not
micro-batch count M. Reference capability:
fleet/meta_parallel/pipeline_parallel.py:80-150 (1F1B interleaving) and
paddle/fluid/framework/section_worker.cc:143-199.

Covers: loss+grad parity against a sequential single-program reference
(M == P and M == 4P), composition with tensor parallelism, and the memory
bound itself — compiled temp bytes stay ~flat as M grows at fixed
micro-batch size, while the fill-drain AD-of-scan path grows O(M).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from paddle_tpu.distributed import mesh as mesh_mod
from paddle_tpu.distributed.pipeline import pipeline_1f1b, pipeline_spmd

from pipeline_toy import (  # the shared toy pipeline model
    DIN, DOUT, SPECS, embed_fn, loss_fn, make_params, stage_fn,
)

PIPE = 4
KPER = 2  # layers per stage
HID = 16


@pytest.fixture
def pipe_mesh():
    prev = mesh_mod.get_mesh()
    mesh = mesh_mod.build_mesh({"pipe": PIPE}, devices=jax.devices()[:PIPE])
    mesh_mod.set_mesh(mesh)
    yield mesh
    mesh_mod.set_mesh(prev)


def _make_params(rs, hid=HID):
    return make_params(rs, PIPE * KPER, hid)


def _sequential_loss(params, x, lbl):
    """Same math, one device, no pipeline: the parity oracle."""
    h = embed_fn(params, x)
    h = stage_fn(params, h)  # scans ALL L layers at once
    return loss_fn(params, h, lbl)


@pytest.mark.parametrize("M", [PIPE, 4 * PIPE])
def test_1f1b_matches_sequential(pipe_mesh, M):
    rs = np.random.RandomState(0)
    params = _make_params(rs)
    b = 2 * M
    x = jnp.asarray(rs.randn(b, DIN), jnp.float32)
    lbl = jnp.asarray(rs.randn(b, DOUT), jnp.float32)

    loss, grads = jax.jit(
        lambda p, xx, ll: pipeline_1f1b(
            embed_fn, stage_fn, loss_fn, p, xx, ll,
            mesh=pipe_mesh, param_specs=SPECS, microbatches=M)
    )(params, x, lbl)

    # oracle: mean over micro-batches of per-micro-batch mean == full mean
    ref_loss, ref_grads = jax.value_and_grad(_sequential_loss)(params, x, lbl)

    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    for k in params:
        np.testing.assert_allclose(
            np.asarray(grads[k]), np.asarray(ref_grads[k]),
            rtol=2e-4, atol=1e-6, err_msg=k)


@pytest.mark.requires_vma_shard_map
def test_1f1b_composes_with_tp():
    """pipe=4 x model=2: column/row-parallel stage matmuls with explicit
    psum — Megatron inside the 1F1B schedule."""
    prev = mesh_mod.get_mesh()
    mesh = mesh_mod.build_mesh({"pipe": PIPE, "model": 2},
                               devices=jax.devices()[:8])
    mesh_mod.set_mesh(mesh)
    try:
        rs = np.random.RandomState(1)
        hid = HID
        params = {
            "we": jnp.asarray(rs.randn(DIN, hid) * 0.3, jnp.float32),
            # col-parallel w1 [L, hid, hid] sharded on dim 2,
            # row-parallel w2 [L, hid, hid] sharded on dim 1
            "w1": jnp.asarray(rs.randn(PIPE, hid, hid) * 0.3, jnp.float32),
            "w2": jnp.asarray(rs.randn(PIPE, hid, hid) * 0.3, jnp.float32),
            "wh": jnp.asarray(rs.randn(hid, DOUT) * 0.3, jnp.float32),
        }
        specs = {
            "we": P(),
            "w1": P("pipe", None, "model"),
            "w2": P("pipe", "model", None),
            "wh": P(),
        }

        def tp_stage(p, h):
            # ONE stacked layer per stage here: p["w1"] arrives [1, hid, k]
            mid = jnp.tanh(h @ p["w1"][0])          # col-parallel
            part = mid @ p["w2"][0]                 # row-parallel partial
            return jnp.tanh(jax.lax.psum(part, "model"))

        def seq_ref(p, x, lbl):
            h = embed_fn(p, x)
            for s in range(PIPE):
                mid = jnp.tanh(h @ p["w1"][s])
                h = jnp.tanh(mid @ p["w2"][s])
            return loss_fn(p, h, lbl)

        M = 2 * PIPE
        b = 2 * M
        x = jnp.asarray(rs.randn(b, DIN), jnp.float32)
        lbl = jnp.asarray(rs.randn(b, DOUT), jnp.float32)

        loss, grads = jax.jit(
            lambda p, xx, ll: pipeline_1f1b(
                embed_fn, tp_stage, loss_fn, p, xx, ll,
                mesh=mesh, param_specs=specs, microbatches=M)
        )(params, x, lbl)
        ref_loss, ref_grads = jax.value_and_grad(seq_ref)(params, x, lbl)

        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
        for k in params:
            np.testing.assert_allclose(
                np.asarray(grads[k]), np.asarray(ref_grads[k]),
                rtol=2e-4, atol=1e-6, err_msg=k)
    finally:
        mesh_mod.set_mesh(prev)


def _tmp_bytes(lowered):
    mem = lowered.compile().memory_analysis()
    if mem is None:
        pytest.skip("backend exposes no memory analysis")
    return int(mem.temp_size_in_bytes)


def test_1f1b_memory_is_o_p_not_o_m(pipe_mesh):
    """THE 1F1B claim: at fixed micro-batch size, growing M (so the global
    batch grows M*mb) leaves compiled temp memory ~flat for the 1F1B
    schedule, while the fill-drain AD-of-scan path grows ~O(M)."""
    rs = np.random.RandomState(2)
    hid = 64
    params = _make_params(rs, hid=hid)
    mb = 8

    def lower_1f1b(M):
        x = jnp.zeros((M * mb, DIN), jnp.float32)
        lbl = jnp.zeros((M * mb, DOUT), jnp.float32)
        return jax.jit(
            lambda p, xx, ll: pipeline_1f1b(
                embed_fn, stage_fn, loss_fn, p, xx, ll,
                mesh=pipe_mesh, param_specs=SPECS, microbatches=M)
        ).lower(params, x, lbl)

    def lower_gpipe(M):
        """fill-drain: AD through pipeline_spmd (the pre-1F1B path)."""
        x = jnp.zeros((M * mb, DIN), jnp.float32)
        lbl = jnp.zeros((M * mb, DOUT), jnp.float32)
        stage_specs = (SPECS["w"], SPECS["b"])

        def train_loss(p, xx, ll):
            h = embed_fn(p, xx)
            y = pipeline_spmd(
                lambda sp, mbx: stage_fn({"w": sp[0], "b": sp[1]}, mbx),
                (p["w"], p["b"]), h, mesh=pipe_mesh,
                param_specs=stage_specs, microbatches=M)
            return loss_fn(p, y, ll)

        return jax.jit(jax.grad(train_loss)).lower(params, x, lbl)

    m_small, m_big = PIPE, 4 * PIPE
    t1 = _tmp_bytes(lower_1f1b(m_small))
    t_sat = _tmp_bytes(lower_1f1b(2 * PIPE))  # S saturated at 2P-1
    t2 = _tmp_bytes(lower_1f1b(m_big))
    g1 = _tmp_bytes(lower_gpipe(m_small))
    g2 = _tmp_bytes(lower_gpipe(m_big))

    # 1F1B absolute accounting: temp = base + S*slot_bytes with
    # S = min(M, 2P-1) stash slots of one mb-sized stage input each
    # (measured exact on XLA-CPU; the epsilon absorbs scheduling noise)
    slot_bytes = mb * hid * 4
    s_small = min(m_small, 2 * PIPE - 1)
    s_big = min(m_big, 2 * PIPE - 1)
    eps = max(4096, int(0.05 * t1))
    assert t2 - t1 <= (s_big - s_small) * slot_bytes + eps, \
        (t1, t2, slot_bytes)
    # once S saturates, temp is FLAT in M — a slow O(M) leak fails here
    assert t2 <= t_sat + max(4096, int(0.02 * t_sat)), (t_sat, t2)
    # fill-drain AD keeps all M micro-batch residuals alive -> grows with M
    assert g2 > 2.0 * g1, (g1, g2)
    # and at the same M the 1F1B program is the smaller one
    assert t2 < g2, (t2, g2)


@pytest.mark.requires_vma_shard_map
def test_gpt_1f1b_train_step_matches_single_device():
    """Full-model integration: GPT trained with the 1F1B schedule on a
    pipe2 x model2 x data2 mesh tracks the single-device TrainStep losses."""
    import paddle_tpu as paddle
    import paddle_tpu.optimizer as opt
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models import (
        GPTForCausalLM, GPTPretrainingCriterion, gpt_presets,
        gpt_1f1b_train_step,
    )

    rs = np.random.RandomState(3)
    b, s = 8, 16
    cfg_kw = dict(mode="scan", use_flash_attention=False)
    ids_np = rs.randint(0, 128, (b, s))
    lbl_np = rs.randint(0, 128, (b, s))

    def run_single():
        mesh_mod.set_mesh(None)
        cfg = gpt_presets("gpt-test", **cfg_kw)
        model = GPTForCausalLM(cfg, seed=0)
        crit = GPTPretrainingCriterion()
        optim = opt.AdamW(learning_rate=1e-3,
                          parameters=model.parameters())
        step = TrainStep(model, lambda lg, lb: crit(lg, lb), optim)
        ids = paddle.to_tensor(ids_np, dtype="int64")
        lbl = paddle.to_tensor(lbl_np, dtype="int64")
        return [float(step(inputs=(ids,), labels=(lbl,)))
                for _ in range(3)]

    def run_1f1b():
        mesh = mesh_mod.build_mesh({"pipe": 2, "model": 2, "data": 2},
                                   devices=jax.devices()[:8])
        mesh_mod.set_mesh(mesh)
        cfg = gpt_presets("gpt-test", pp_microbatches=4, **cfg_kw)
        model = GPTForCausalLM(cfg, seed=0)
        optim = opt.AdamW(learning_rate=1e-3,
                          parameters=model.parameters())
        step = gpt_1f1b_train_step(model, optim)
        ids = paddle.to_tensor(ids_np, dtype="int64")
        lbl = paddle.to_tensor(lbl_np, dtype="int64")
        return [float(step(inputs=(ids,), labels=(lbl,)))
                for _ in range(3)]

    prev = mesh_mod.get_mesh()
    try:
        base = run_single()
        pp = run_1f1b()
    finally:
        mesh_mod.set_mesh(prev)
    np.testing.assert_allclose(pp, base, rtol=2e-4, atol=2e-5)


def test_gpt_1f1b_with_ulysses_sequence_parallel():
    """1F1B x Ulysses (all_to_all head/seq swap) x dp — the second SP
    scheme must also compose with the hand-scheduled pipeline."""
    import paddle_tpu as paddle
    import paddle_tpu.optimizer as opt
    from paddle_tpu.models import (
        GPTForCausalLM, gpt_presets, gpt_1f1b_train_step,
    )

    prev = mesh_mod.get_mesh()
    try:
        mesh_mod.set_mesh(mesh_mod.build_mesh(
            {"pipe": 2, "sep": 2, "data": 2}, devices=jax.devices()[:8]))
        cfg = gpt_presets("gpt-test", mode="scan", pp_microbatches=4,
                          use_flash_attention=False,
                          use_ulysses_attention=True)
        model = GPTForCausalLM(cfg, seed=0)
        optim = opt.AdamW(learning_rate=1e-3,
                          parameters=model.parameters())
        step = gpt_1f1b_train_step(model, optim)
        rs = np.random.RandomState(0)
        ids = paddle.to_tensor(rs.randint(0, 256, (8, 32)), dtype="int64")
        lbl = paddle.to_tensor(rs.randint(0, 256, (8, 32)), dtype="int64")
        losses = [float(step(inputs=(ids,), labels=(lbl,)))
                  for _ in range(3)]
        assert np.all(np.isfinite(losses))
        assert losses[-1] < losses[0]  # it trains
    finally:
        mesh_mod.set_mesh(prev)


@pytest.mark.requires_vma_shard_map
def test_gpt_1f1b_bf16_with_remat():
    """Config-4 regime: bf16 params + jax.checkpoint recompute inside the
    hand-scheduled backward — must train (fp32 grad accumulation)."""
    import paddle_tpu as paddle
    import paddle_tpu.optimizer as opt
    from paddle_tpu.models import (
        GPTForCausalLM, gpt_presets, gpt_1f1b_train_step,
    )

    prev = mesh_mod.get_mesh()
    try:
        mesh_mod.set_mesh(mesh_mod.build_mesh(
            {"pipe": 2, "model": 2, "data": 2}, devices=jax.devices()[:8]))
        cfg = gpt_presets("gpt-test", mode="scan", pp_microbatches=4,
                          use_flash_attention=False, dtype="bfloat16",
                          recompute=True)
        model = GPTForCausalLM(cfg, seed=0)
        optim = opt.AdamW(learning_rate=1e-3,
                          parameters=model.parameters())
        step = gpt_1f1b_train_step(model, optim)
        rs = np.random.RandomState(0)
        ids = paddle.to_tensor(rs.randint(0, 256, (8, 32)), dtype="int64")
        lbl = paddle.to_tensor(rs.randint(0, 256, (8, 32)), dtype="int64")
        losses = [float(step(inputs=(ids,), labels=(lbl,)))
                  for _ in range(4)]
        assert np.all(np.isfinite(losses)) and losses[-1] < losses[0]
    finally:
        mesh_mod.set_mesh(prev)


@pytest.mark.parametrize("M", [1, 2])
def test_1f1b_fewer_microbatches_than_stages(pipe_mesh, M):
    """M < P degenerates gracefully (deep bubble but exact math)."""
    rs = np.random.RandomState(4)
    params = _make_params(rs)
    b = 2 * M
    x = jnp.asarray(rs.randn(b, DIN), jnp.float32)
    lbl = jnp.asarray(rs.randn(b, DOUT), jnp.float32)
    loss, grads = jax.jit(
        lambda p, xx, ll: pipeline_1f1b(
            embed_fn, stage_fn, loss_fn, p, xx, ll,
            mesh=pipe_mesh, param_specs=SPECS, microbatches=M)
    )(params, x, lbl)
    ref_loss, ref_grads = jax.value_and_grad(_sequential_loss)(params, x,
                                                               lbl)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    for k in params:
        np.testing.assert_allclose(np.asarray(grads[k]),
                                   np.asarray(ref_grads[k]),
                                   rtol=2e-4, atol=1e-6, err_msg=k)

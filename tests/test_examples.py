"""The examples/ scripts must actually run (subprocess smoke tests)."""
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ENV = dict(os.environ, JAX_PLATFORMS="cpu",
           XLA_FLAGS=os.environ.get("XLA_FLAGS", "")
           + " --xla_force_host_platform_device_count=4",
           PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""))


def _run(script, *args, timeout=300):
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", script), *args],
        capture_output=True, text=True, timeout=timeout, env=ENV, cwd=REPO)
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    return r.stdout


@pytest.mark.slow
def test_train_gpt_single():
    out = _run("train_gpt.py", "--steps", "6", "--batch", "4", "--seq", "32")
    assert "loss" in out


@pytest.mark.slow
def test_train_gpt_hybrid_mesh():
    out = _run("train_gpt.py", "--steps", "4", "--batch", "8",
               "--seq", "32", "--dp", "2", "--tp", "2")
    assert "loss" in out


@pytest.mark.slow
def test_serve_predictor():
    out = _run("serve_predictor.py")
    assert "served predictions" in out


@pytest.mark.slow
def test_wide_deep_ps():
    out = _run("wide_deep_ps.py")
    assert "table rows" in out


@pytest.mark.slow
def test_long_context_sp_examples():
    for scheme in ("ring", "ulysses"):
        out = _run("long_context_sp.py", "--scheme", scheme, "--sep", "2",
                   "--dp", "2", "--seq", "64", "--steps", "4",
                   "--batch", "4")
        assert "done" in out, out
        losses = [float(l.rsplit(" ", 1)[-1]) for l in out.splitlines()
                  if "loss" in l]
        assert losses and losses[-1] < losses[0], (scheme, losses)


@pytest.mark.slow
def test_plan_mesh_example():
    # runs the compiler-as-cost-model planner when libtpu is present, and
    # must exit cleanly (with the documented note) when it is not
    out = _run("plan_mesh.py", "--devices", "8", timeout=600)
    assert ("chosen mesh" in out) or ("no TPU AOT compiler" in out), out


@pytest.mark.slow
def test_graph_embedding_example():
    """VERDICT r3 weak #9: the graph table feeding a real training loop —
    node2vec walks -> skip-gram embeddings; communities must separate
    (the script asserts margin > 0.2 itself)."""
    out = _run("graph_embedding.py", "--epochs", "40")
    assert "margin" in out


def test_heter_pass_training():
    out = _run("heter_pass_training.py")
    assert "trained:" in out

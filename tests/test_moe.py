"""MoE / expert-parallel tests (reference building blocks:
global_scatter/global_gather, distributed/utils.py:57,179)."""
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.optimizer as opt
from paddle_tpu.distributed import MoELayer, global_gather, global_scatter
from paddle_tpu.distributed import mesh as mesh_mod
from paddle_tpu.jit import TrainStep


@pytest.fixture(autouse=True)
def clean_mesh(fresh_mesh):
    yield  # fresh_mesh (conftest) owns save/clear/restore


class TestGlobalScatterGather:
    def test_roundtrip(self):
        x = paddle.to_tensor(np.random.randn(6, 4).astype("float32"))
        lc = paddle.to_tensor(np.array([2, 4]), dtype="int64")
        gc = paddle.to_tensor(np.array([2, 4]), dtype="int64")
        y = global_scatter(x, lc, gc)
        z = global_gather(y, lc, gc)
        np.testing.assert_allclose(z.numpy(), x.numpy())

    def test_count_mismatch_raises(self):
        x = paddle.to_tensor(np.random.randn(5, 4).astype("float32"))
        with pytest.raises(ValueError):
            global_scatter(x, [2, 4], [2, 4])


class TestMoELayer:
    def test_forward_and_grad(self):
        layer = MoELayer(hidden_size=16, ffn_hidden_size=32, num_experts=4,
                         seed=0)
        x = paddle.to_tensor(np.random.randn(2, 8, 16).astype("float32"))
        x.stop_gradient = False
        y = layer(x)
        assert y.shape == [2, 8, 16]
        assert layer.aux_loss is not None and float(layer.aux_loss) > 0
        (y.sum() + layer.aux_loss).backward()
        assert layer.gate_w.grad is not None
        assert layer.w_in.grad is not None

    def test_capacity_drops_tokens(self):
        """With tiny capacity some tokens get zero output (dropped)."""
        layer = MoELayer(hidden_size=8, ffn_hidden_size=8, num_experts=2,
                         capacity_factor=0.25, seed=0)
        x = paddle.to_tensor(np.random.randn(1, 16, 8).astype("float32"))
        y = layer(x)
        norms = np.linalg.norm(y.numpy().reshape(16, 8), axis=-1)
        assert (norms < 1e-6).any()

    def test_expert_parallel_matches_single(self):
        rs = np.random.RandomState(0)
        xv = rs.randn(2, 16, 8).astype("float32")

        single = MoELayer(hidden_size=8, ffn_hidden_size=16, num_experts=4,
                          seed=2)
        y_ref = single(paddle.to_tensor(xv)).numpy()

        mesh_mod.set_mesh(mesh_mod.build_mesh({"data": 2, "expert": 4}))
        ep = MoELayer(hidden_size=8, ffn_hidden_size=16, num_experts=4, seed=2)
        import jax

        from paddle_tpu.jit.functional import FunctionalModule

        fm = FunctionalModule(ep)

        def fwd(pvals, x):
            out, _ = fm.call(pvals, [], jax.random.key(0), (x,), training=False)
            return out

        y_ep = np.asarray(jax.jit(fwd)(fm.param_values(), xv))
        np.testing.assert_allclose(y_ep, y_ref, rtol=2e-3, atol=2e-4)

    def test_moe_training_step_on_mesh(self):
        mesh_mod.set_mesh(mesh_mod.build_mesh({"data": 2, "expert": 4}))
        import paddle_tpu.nn as nn

        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.moe = MoELayer(hidden_size=8, ffn_hidden_size=16,
                                    num_experts=4, seed=1)
                self.head = nn.Linear(8, 4)

            def forward(self, x):
                return self.head(self.moe(x))

        m = Net()
        crit = nn.CrossEntropyLoss()
        o = opt.AdamW(learning_rate=1e-3, parameters=m.parameters())
        step = TrainStep(m, lambda lg, lb: crit(lg, lb), o)
        rs = np.random.RandomState(0)
        x = paddle.to_tensor(rs.randn(4, 6, 8).astype("float32"))
        lb = paddle.to_tensor(rs.randint(0, 4, (4, 6)), dtype="int64")
        losses = [float(step(inputs=(x,), labels=(lb,))) for _ in range(3)]
        assert losses[-1] < losses[0]

"""Distributed tests on the 8-device virtual CPU mesh (the reference's
multi-process localhost strategy, SURVEY.md §4, adapted to SPMD)."""
import jax
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
import paddle_tpu.optimizer as optim
from paddle_tpu.distributed import fleet
import paddle_tpu.distributed as dist
import paddle_tpu.distributed.mesh as mesh_mod
from paddle_tpu.distributed.fleet.meta_parallel import (
    ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding,
)
from paddle_tpu.jit import TrainStep

rng = np.random.RandomState(0)


@pytest.fixture(autouse=True)
def reset_mesh(fresh_mesh):
    yield  # fresh_mesh (conftest) owns save/clear/restore


def test_build_mesh_shapes():
    import jax

    m = mesh_mod.build_mesh({"data": 2, "model": 4})
    assert m.shape == {"data": 2, "model": 4}
    with pytest.raises(ValueError):
        mesh_mod.build_mesh({"data": 3})


def test_fleet_init_topology():
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2, "pp_degree": 2}
    fleet.init(is_collective=True, strategy=strategy)
    hcg = fleet.get_hybrid_communicate_group()
    assert hcg.get_data_parallel_world_size() == 2
    assert hcg.get_model_parallel_world_size() == 2
    assert hcg.get_pipe_parallel_world_size() == 2
    assert hcg.get_parallel_mode() == "pipeline"
    topo = hcg.topology()
    assert topo.world_size() == 8
    # comm lists partition the world
    lists = topo.get_comm_list("model")
    flat = sorted(i for l in lists for i in l)
    assert flat == list(range(8))


def test_strategy_validation():
    s = fleet.DistributedStrategy()
    with pytest.raises(ValueError):
        s.not_a_real_toggle = True
    with pytest.raises(ValueError):
        s.hybrid_configs = {"bogus_key": 3}
    s.sharding = True
    s.sharding_configs = {"stage": 2}
    assert s.sharding_configs["stage"] == 2


def test_collectives_in_shard_map():
    """Per-primitive semantics vs NumPy — the analog of the reference's
    test_collective_base two-rank pickle-compare harness."""
    from jax.sharding import PartitionSpec as P

    m = mesh_mod.set_mesh(mesh_mod.build_mesh({"data": 8}))
    x = np.arange(32, dtype=np.float32).reshape(8, 4)

    def allreduce_prog(v):
        t = paddle.to_tensor(v)
        dist.all_reduce(t)
        return t._value

    out = mesh_mod.compat_shard_map(allreduce_prog, m, P("data"), P("data"))(x)
    expect = np.tile(x.sum(0), (8, 1)).reshape(8, 1, 4).squeeze(1)
    np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-6)

    def allgather_prog(v):
        t = paddle.to_tensor(v)
        g = dist.all_gather(None, t)
        return g._value

    out = np.asarray(
        mesh_mod.compat_shard_map(allgather_prog, m, P("data"), P("data"))(x)
    )
    # each shard gathers all 8 rows: [8, 1, 4] per shard -> (64, 1, 4) global
    assert out.shape == (64, 1, 4)
    np.testing.assert_allclose(out[:8, 0, :], x)

    def broadcast_prog(v):
        t = paddle.to_tensor(v)
        dist.broadcast(t, src=3)
        return t._value

    out = np.asarray(
        mesh_mod.compat_shard_map(broadcast_prog, m, P("data"), P("data"))(x)
    )
    np.testing.assert_allclose(out, np.tile(x[3], (8, 1)))


def test_reduce_scatter_shard_map():
    """reduce_scatter semantics + the all_gather inverse pairing
    (all_gather(reduce_scatter(x)) == all_reduce(x))."""
    from jax.sharding import PartitionSpec as P

    m = mesh_mod.set_mesh(mesh_mod.build_mesh({"data": 8}))
    x = np.arange(8 * 8 * 4, dtype=np.float32).reshape(64, 4)

    def rs_prog(v):  # per-shard input (8, 4); output chunk (1, 4)
        return dist.reduce_scatter(paddle.to_tensor(v))._value

    out = np.asarray(
        mesh_mod.compat_shard_map(rs_prog, m, P("data"), P("data"))(x))
    # rank r keeps row r of the across-rank sum of the (8, 4) blocks
    expect = x.reshape(8, 8, 4).sum(axis=0)
    np.testing.assert_allclose(out, expect, rtol=1e-6)

    def rs_ag_prog(v):
        rs = dist.reduce_scatter(paddle.to_tensor(v))
        return dist.all_gather(None, rs)._value.reshape(8, 4)

    out = np.asarray(
        mesh_mod.compat_shard_map(rs_ag_prog, m, P("data"), P("data"))(x))
    # every rank re-assembles the full reduction == all_reduce
    np.testing.assert_allclose(out.reshape(8, 8, 4),
                               np.tile(expect, (8, 1, 1)), rtol=1e-6)

    def rs_avg_prog(v):
        return dist.reduce_scatter(paddle.to_tensor(v),
                                   op=dist.ReduceOp.AVG)._value

    out = np.asarray(
        mesh_mod.compat_shard_map(rs_avg_prog, m, P("data"), P("data"))(x))
    np.testing.assert_allclose(out, expect / 8, rtol=1e-6)


def test_alltoall_shard_map():
    from jax.sharding import PartitionSpec as P

    m = mesh_mod.set_mesh(mesh_mod.build_mesh({"data": 8}))
    # paddle alltoall: each rank's input splits into nranks chunks along dim0;
    # rank r's output chunk s is rank s's chunk r (a block transpose)
    x = np.arange(64, dtype=np.float32).reshape(64, 1)

    def prog(v):
        t = paddle.to_tensor(v)
        return dist.alltoall(t)._value

    out = np.asarray(
        mesh_mod.compat_shard_map(prog, m, P("data"), P("data"))(x)
    )
    np.testing.assert_allclose(out.reshape(8, 8), x.reshape(8, 8).T)


class MpNet(nn.Layer):
    def __init__(self, vocab=32, hidden=16):
        super().__init__()
        self.emb = VocabParallelEmbedding(vocab, hidden)
        self.col = ColumnParallelLinear(hidden, hidden * 2, gather_output=False)
        self.row = RowParallelLinear(hidden * 2, hidden, input_is_parallel=True)
        self.head = nn.Linear(hidden, vocab)

    def forward(self, ids):
        h = self.emb(ids)
        h = F.gelu(self.col(h))
        return self.head(self.row(h))


def _train(net, step_fn, ids, labels, n=8):
    return [float(step_fn(paddle.to_tensor(ids), paddle.to_tensor(labels)).numpy())
            for _ in range(n)]


def test_tp_dp_sharded_train_matches_single_device():
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 4, "pp_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    paddle.seed(7)
    net = fleet.distributed_model(MpNet())
    inner = net._layers
    w0 = {k: v.numpy().copy() for k, v in inner.state_dict().items()}
    opt = fleet.distributed_optimizer(
        optim.Adam(learning_rate=0.01, parameters=inner.parameters())
    )
    step = TrainStep(inner, lambda o, y: F.cross_entropy(o.reshape([-1, 32]),
                                                         y.reshape([-1])),
                     opt._inner_opt)
    ids = rng.randint(0, 32, (8, 4)).astype(np.int64)
    labels = rng.randint(0, 32, (8, 4)).astype(np.int64)
    sharded_losses = _train(net, step, ids, labels)

    # single-device replay from identical init
    mesh_mod._current[0] = None
    net2 = MpNet()
    net2.set_state_dict(w0)
    opt2 = optim.Adam(learning_rate=0.01, parameters=net2.parameters())
    step2 = TrainStep(net2, lambda o, y: F.cross_entropy(o.reshape([-1, 32]),
                                                         y.reshape([-1])), opt2)
    single_losses = _train(net2, step2, ids, labels)
    np.testing.assert_allclose(sharded_losses, single_losses, rtol=1e-4, atol=1e-5)


def test_sharding_stage3_param_partition():
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
                               "sharding_degree": 8}
    strategy.sharding = True
    strategy.sharding_configs = {"stage": 3, "sharding_degree": 8}
    fleet.init(is_collective=True, strategy=strategy)
    paddle.seed(1)
    net = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 8))
    wrapped = fleet.distributed_model(net)
    # params got a 'sharding' spec on a divisible dim
    specs = [p.dist_spec for p in net.parameters()]
    assert any(s is not None and "sharding" in str(s) for s in specs)
    opt = optim.Adam(learning_rate=0.01, parameters=net.parameters())
    step = TrainStep(net, lambda o, y: F.mse_loss(o, y), opt)
    x = rng.rand(8, 16).astype(np.float32)
    y = rng.rand(8, 8).astype(np.float32)
    losses = _train(wrapped, step, x, y)
    assert losses[-1] < losses[0]
    # parameter values remain sharded over the sharding axis
    w = net[0].weight._value
    assert "sharding" in str(w.sharding.spec)
    # the memory profile actually shrinks: each device holds 1/8 of the
    # param (VERDICT: "matching Paddle's stage-3 memory profile")
    shard = w.addressable_shards[0].data
    assert shard.size == w.size // 8, (shard.size, w.size)
    # optimizer slots shard the same way once marked by group_sharded
    opt2 = optim.Adam(learning_rate=0.01, parameters=net.parameters())
    opt2._slot_shard_axis = "sharding"
    step2 = TrainStep(net, lambda o, y: F.mse_loss(o, y), opt2)
    step2(inputs=(paddle.to_tensor(x),), labels=(paddle.to_tensor(y),))
    slot_arrays = [a for a in jax.tree_util.tree_leaves(step2._slots)
                   if hasattr(a, "addressable_shards") and a.ndim >= 1
                   and a.size >= 8]
    assert slot_arrays, "no slot arrays recorded on the TrainStep"
    assert any(a.addressable_shards[0].data.size <= a.size // 8
               for a in slot_arrays), [
        (a.addressable_shards[0].data.size, a.size) for a in slot_arrays]


def test_data_parallel_wrapper_api():
    m = mesh_mod.set_mesh(mesh_mod.build_mesh({"data": 8}))
    net = dist.DataParallel(nn.Linear(4, 2))
    out = net(paddle.to_tensor(rng.rand(8, 4).astype(np.float32)))
    assert out.shape == [8, 2]
    assert len(net.state_dict()) == 2
    loss = net.scale_loss(out.sum())
    loss.backward()
    net.apply_collective_grads()


def test_strategy_bits_select_meta_optimizers():
    """lars/lamb/gradient_merge/localsgd strategy bits pick their
    implementations in fleet.distributed_optimizer, like dgc already did
    (reference: StrategyCompiler + each meta-optimizer's _can_apply)."""
    from paddle_tpu.distributed.fleet.meta_optimizers import (
        GradientMergeOptimizer, LocalSGDOptimizer,
    )
    from paddle_tpu.optimizer import Lamb, Lars

    net = nn.Linear(4, 2)

    st = fleet.DistributedStrategy()
    st.lars = True
    wrapped = fleet.distributed_optimizer(
        optim.Momentum(parameters=net.parameters()), strategy=st)
    assert isinstance(wrapped._inner_opt, Lars)

    st = fleet.DistributedStrategy()
    st.lamb = True
    st.lamb_configs = {"lamb_weight_decay": 0.02,
                       "exclude_from_weight_decay": [".b_"]}
    wrapped = fleet.distributed_optimizer(
        optim.Adam(parameters=net.parameters()), strategy=st)
    assert isinstance(wrapped._inner_opt, Lamb)
    lamb = wrapped._inner_opt
    wds = {p.name: lamb._param_wd(p) for p in net.parameters()}
    # bias (name contains '.b_') excluded from decay; weight keeps it
    assert any(w == 0.0 for w in wds.values()) and \
        any(w == 0.02 for w in wds.values()), wds
    # non-Adam passes through (reference _can_apply)
    wrapped = fleet.distributed_optimizer(
        optim.SGD(parameters=net.parameters()), strategy=st)
    assert not isinstance(wrapped._inner_opt, Lamb)

    st = fleet.DistributedStrategy()
    st.gradient_merge = True
    st.gradient_merge_configs = {"k_steps": 4, "avg": True}
    wrapped = fleet.distributed_optimizer(
        optim.SGD(parameters=net.parameters()), strategy=st)
    assert isinstance(wrapped._inner_opt, GradientMergeOptimizer)
    assert wrapped._inner_opt.k_steps == 4

    st = fleet.DistributedStrategy()
    st.localsgd = True
    st.localsgd_configs = {"k_steps": 3}
    wrapped = fleet.distributed_optimizer(
        optim.SGD(parameters=net.parameters()), strategy=st)
    assert isinstance(wrapped._inner_opt, LocalSGDOptimizer)
    assert wrapped._inner_opt.k_steps == 3


def test_fp16_allreduce_casts_grads_for_the_collective(monkeypatch):
    """strategy.fp16_allreduce (reference fp16_allreduce_optimizer.py):
    DP grads cross the wire as bf16 and come back in the param dtype."""
    import paddle_tpu.distributed.collective as coll
    import paddle_tpu.distributed.env as env_mod
    from paddle_tpu.distributed.fleet import _fleet_state

    net = dist.DataParallel(nn.Linear(4, 2))
    loss = net(paddle.to_tensor(rng.rand(8, 4).astype(np.float32))).sum()
    loss.backward()

    wire_dtypes = []
    monkeypatch.setattr(env_mod, "get_world_size", lambda: 2)
    monkeypatch.setattr(
        coll, "all_reduce",
        lambda t, op=None, **kw: wire_dtypes.append(str(t._value.dtype)) or t)

    prev = _fleet_state.get("strategy")
    try:
        strategy = fleet.DistributedStrategy()
        strategy.fp16_allreduce = True
        _fleet_state["strategy"] = strategy
        net.apply_collective_grads()
    finally:
        _fleet_state["strategy"] = prev

    assert wire_dtypes and all(d == "bfloat16" for d in wire_dtypes), \
        wire_dtypes
    for p in net.parameters():  # restored to the param-grad dtype
        if p.grad is not None:
            assert str(p.grad._value.dtype) == "float32"

    # flag off: grads cross in fp32
    wire_dtypes.clear()
    net.apply_collective_grads()
    assert wire_dtypes and all(d == "float32" for d in wire_dtypes)


def test_env_defaults():
    assert dist.get_world_size() >= 1
    assert dist.get_rank() == 0
    env = dist.ParallelEnv()
    assert env.world_size >= 1


def test_fleet_dgc_strategy_swaps_optimizer():
    """strategy.dgc=True swaps a Momentum inner optimizer for DGCMomentum
    (reference: meta_optimizers/dgc_optimizer.py _can_apply on Momentum)."""
    strategy = fleet.DistributedStrategy()
    strategy.dgc = True
    strategy.dgc_configs = {"rampup_begin_step": 2, "sparsity": [0.5]}
    fleet.init(is_collective=True, strategy=strategy)
    net = nn.Linear(4, 4)
    inner = optim.Momentum(learning_rate=0.1, momentum=0.9,
                           parameters=net.parameters())
    wrapped = fleet.distributed_optimizer(inner, strategy=strategy)
    assert type(wrapped._inner_opt).__name__ == "DGCMomentum"
    assert wrapped._inner_opt._rampup_begin == 2
    assert wrapped._inner_opt._sparsity == [0.5]
    # non-Momentum optimizers pass through unchanged
    adam = optim.Adam(parameters=net.parameters())
    wrapped2 = fleet.distributed_optimizer(adam, strategy=strategy)
    assert wrapped2._inner_opt is adam


def test_localsgd_warmup_is_synchronous(monkeypatch):
    """Reference localsgd_optimizer.py: cond(step > begin_step,
    begin_localsgd, communicate) — replicas average EVERY step during
    warm-up, then every k_steps (ADVICE r4: the inverted gate trained
    fully unsynchronized until begin_step)."""
    import paddle_tpu.distributed.collective as coll
    import paddle_tpu.distributed.env as env_mod
    from paddle_tpu.distributed.fleet.meta_optimizers import LocalSGDOptimizer

    net = nn.Linear(4, 2)
    opt = LocalSGDOptimizer(optim.SGD(parameters=net.parameters()),
                            k_steps=4, begin_step=3)
    monkeypatch.setattr(env_mod, "get_world_size", lambda: 2)
    calls = []
    monkeypatch.setattr(coll, "all_reduce",
                        lambda t, *a, **kw: calls.append(1) or t)

    synced = []
    for _ in range(8):
        net(paddle.to_tensor(rng.rand(8, 4).astype(np.float32))).sum().backward()
        before = len(calls)
        opt.step()
        synced.append(len(calls) > before)
        opt.clear_grad()
    # steps 1-3: warm-up sync; 4-6 local; 7 = 3+k sync; 8 local
    assert synced == [True, True, True, False, False, False, True, False]

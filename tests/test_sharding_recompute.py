"""Tests: recompute (rematerialization), group_sharded (ZeRO) API, gradient
merge / LocalSGD meta-optimizers.

Reference analogs: unittests dygraph_recompute.py,
dygraph_group_sharded_stage2/3*.py, test_fleet_gradient_merge_meta_optimizer.
"""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu.distributed.fleet.utils import recompute


class Block(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(8, 16)
        self.fc2 = nn.Linear(16, 8)

    def forward(self, x):
        return self.fc2(paddle.tanh(self.fc1(x)))


class TestRecompute:
    def _grads(self, use_recompute):
        paddle.seed(0)
        net = Block()
        rs = np.random.RandomState(0)
        x = paddle.to_tensor(rs.randn(4, 8).astype("float32"))
        x.stop_gradient = False
        if use_recompute:
            y = recompute(net, x)
        else:
            y = net(x)
        loss = (y ** 2).sum()
        loss.backward()
        return (float(loss), x.grad.numpy().copy(),
                net.fc1.weight.grad.numpy().copy(),
                net.fc2.weight.grad.numpy().copy())

    def test_grads_match_plain_backward(self):
        l0, gx0, g10, g20 = self._grads(False)
        l1, gx1, g11, g21 = self._grads(True)
        np.testing.assert_allclose(l0, l1, rtol=1e-6)
        np.testing.assert_allclose(gx0, gx1, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(g10, g11, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(g20, g21, rtol=1e-5, atol=1e-6)

    def test_rng_preserved_with_dropout(self):
        paddle.seed(42)
        drop = nn.Sequential(nn.Linear(8, 8), nn.Dropout(0.5))
        x = paddle.to_tensor(np.ones((2, 8), "float32"))
        x.stop_gradient = False
        y = recompute(drop, x)
        # grads must correspond to the SAME dropout mask used in forward:
        # element-wise, dy/dx nonzero exactly where forward output nonzero
        mask_fwd = (np.abs(y.numpy()) > 0)
        y.sum().backward()
        assert drop[0].weight.grad is not None
        assert np.isfinite(x.grad.numpy()).all()
        assert mask_fwd.mean() < 0.95  # dropout actually dropped something

    def test_no_grad_passthrough(self):
        net = Block()
        x = paddle.to_tensor(np.ones((2, 8), "float32"))
        with paddle.no_grad():
            y = recompute(net, x)
        assert y.stop_gradient


class TestGroupSharded:
    def test_levels_and_markers(self):
        import jax
        from paddle_tpu.distributed import mesh as mesh_mod
        from paddle_tpu.distributed.sharding import group_sharded_parallel

        mesh_mod.set_mesh(mesh_mod.build_mesh({"sharding": 4, "data": 2}))
        try:
            net = Block()
            o = opt.AdamW(learning_rate=1e-3, parameters=net.parameters())
            m, o2, _ = group_sharded_parallel(net, o, "os")
            assert o2._slot_shard_axis == "sharding"
            assert all(getattr(p, "dist_spec", None) is None
                       for p in m.parameters())

            net3 = Block()
            o3 = opt.AdamW(learning_rate=1e-3, parameters=net3.parameters())
            m3, _, _ = group_sharded_parallel(net3, o3, "p_g_os")
            specs = [getattr(p, "dist_spec", None) for p in m3.parameters()]
            assert any(s is not None for s in specs)
        finally:
            mesh_mod.set_mesh(None)

    def test_stage2_trains_on_mesh(self):
        import jax
        from jax.sharding import PartitionSpec as P
        from paddle_tpu.distributed import mesh as mesh_mod
        from paddle_tpu.distributed.sharding import group_sharded_parallel
        from paddle_tpu.jit import TrainStep

        mesh_mod.set_mesh(mesh_mod.build_mesh({"data": 2, "sharding": 4}))
        try:
            paddle.seed(0)
            net = Block()
            o = opt.AdamW(learning_rate=1e-2, parameters=net.parameters())
            net, o, _ = group_sharded_parallel(net, o, "os_g")
            step = TrainStep(net, lambda out, lbl: ((out - lbl) ** 2).mean(),
                             o, batch_spec=P(("data", "sharding")))
            rs = np.random.RandomState(0)
            x = paddle.to_tensor(rs.randn(16, 8).astype("float32"))
            y = paddle.to_tensor(rs.randn(16, 8).astype("float32"))
            losses = [float(step(inputs=(x,), labels=(y,)))
                      for _ in range(5)]
            assert losses[-1] < losses[0]
        finally:
            mesh_mod.set_mesh(None)

    def test_save_group_sharded_model(self, tmp_path):
        from paddle_tpu.distributed.sharding import (
            group_sharded_parallel, save_group_sharded_model,
        )

        net = Block()
        o = opt.AdamW(learning_rate=1e-3, parameters=net.parameters())
        net, o, _ = group_sharded_parallel(net, o, "os")
        save_group_sharded_model(net, str(tmp_path), o)
        import os

        assert os.path.exists(str(tmp_path / "model.pdparams"))

    def test_bad_level_raises(self):
        import pytest
        from paddle_tpu.distributed.sharding import group_sharded_parallel

        net = Block()
        o = opt.SGD(learning_rate=0.1, parameters=net.parameters())
        with pytest.raises(ValueError):
            group_sharded_parallel(net, o, "stage9")


class TestMetaOptimizers:
    def test_gradient_merge(self):
        from paddle_tpu.distributed.fleet.meta_optimizers import (
            GradientMergeOptimizer,
        )

        paddle.seed(0)
        net = nn.Linear(4, 1, bias_attr=False)
        inner = opt.SGD(learning_rate=1.0, parameters=net.parameters())
        gm = GradientMergeOptimizer(inner, k_steps=2, avg=True)
        w0 = net.weight.numpy().copy()
        x = paddle.to_tensor(np.ones((2, 4), "float32"))

        loss = net(x).sum()
        loss.backward()
        g1 = net.weight.grad.numpy().copy()
        gm.step()  # step 1: accumulate only
        np.testing.assert_allclose(net.weight.numpy(), w0)

        loss = net(x).sum()
        loss.backward()
        gm.step()  # step 2: apply averaged update
        gm.clear_grad()
        expect = w0 - (g1 + g1) / 2  # same batch twice, averaged
        np.testing.assert_allclose(net.weight.numpy(), expect, rtol=1e-5)
        assert net.weight.grad is None

    def test_local_sgd_single_process_noop_average(self):
        from paddle_tpu.distributed.fleet.meta_optimizers import (
            LocalSGDOptimizer,
        )

        net = nn.Linear(2, 1)
        inner = opt.SGD(learning_rate=0.1, parameters=net.parameters())
        ls = LocalSGDOptimizer(inner, k_steps=1)
        x = paddle.to_tensor(np.ones((1, 2), "float32"))
        net(x).sum().backward()
        ls.step()  # world_size==1 → no averaging, just the SGD update
        assert np.isfinite(net.weight.numpy()).all()

    def test_dygraph_sharding_optimizer_wrapper(self):
        from paddle_tpu.distributed.fleet.meta_optimizers import (
            DygraphShardingOptimizer,
        )

        net = Block()
        inner = opt.AdamW(learning_rate=1e-3, parameters=net.parameters())
        sh = DygraphShardingOptimizer(inner_optimizer=inner)
        assert sh._slot_shard_axis == "sharding"
        x = paddle.to_tensor(np.ones((2, 8), "float32"))
        (net(x) ** 2).sum().backward()
        sh.step()
        sh.clear_grad()


def test_recompute_multi_tensor_inputs():
    """Regression: recompute with >1 Tensor argument (elementwise __eq__ used
    to blow up the backward membership test)."""
    paddle.seed(0)
    lin = nn.Linear(8, 8)

    def fn(a, b):
        return lin(a) * b

    rs = np.random.RandomState(0)
    a = paddle.to_tensor(rs.randn(4, 8).astype("float32"))
    b = paddle.to_tensor(rs.randn(4, 8).astype("float32"))
    a.stop_gradient = False
    b.stop_gradient = False
    out = recompute(fn, a, b)
    out.sum().backward()
    assert a.grad is not None and b.grad is not None
    # reference grads without recompute
    a2 = paddle.to_tensor(a.numpy()); a2.stop_gradient = False
    b2 = paddle.to_tensor(b.numpy()); b2.stop_gradient = False
    (lin(a2) * b2).sum().backward()
    np.testing.assert_allclose(a.grad.numpy(), a2.grad.numpy(), rtol=1e-5)
    np.testing.assert_allclose(b.grad.numpy(), b2.grad.numpy(), rtol=1e-5)

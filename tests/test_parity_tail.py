"""Behavioral tests for the API-parity tail: hermitian FFTs (vs torch),
control ops, loss family, sparse attention, static.nn builders, datasets.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
import paddle_tpu.static.nn as snn
import paddle_tpu.vision.ops as vo

rs = np.random.RandomState(0)


def test_hermitian_fft_family_matches_torch():
    torch = pytest.importorskip("torch")
    import paddle_tpu.fft as pfft

    x = (rs.randn(4, 5) + 1j * rs.randn(4, 5)).astype(np.complex64)
    r = rs.randn(6, 8).astype(np.float32)
    for norm in ("backward", "forward", "ortho"):
        np.testing.assert_allclose(
            pfft.hfftn(paddle.to_tensor(x), norm=norm).numpy(),
            torch.fft.hfftn(torch.from_numpy(x), norm=norm).numpy(),
            rtol=2e-4, atol=1e-4)
        np.testing.assert_allclose(
            pfft.ihfftn(paddle.to_tensor(r), norm=norm).numpy(),
            torch.fft.ihfftn(torch.from_numpy(r), norm=norm).numpy(),
            rtol=2e-4, atol=1e-5)
    np.testing.assert_allclose(
        pfft.hfft2(paddle.to_tensor(x)).numpy(),
        torch.fft.hfft2(torch.from_numpy(x)).numpy(), rtol=2e-4, atol=1e-4)


def test_diag_embed_matches_torch():
    torch = pytest.importorskip("torch")
    x = rs.randn(2, 5).astype(np.float32)
    for off in (-1, 0, 2):
        np.testing.assert_allclose(
            F.diag_embed(paddle.to_tensor(x), offset=off).numpy(),
            torch.diag_embed(torch.from_numpy(x), offset=off).numpy(),
            rtol=1e-6)


def test_max_unpool_1d_3d_roundtrip():
    x1 = paddle.to_tensor(rs.randn(2, 3, 8).astype("float32"))
    p1, idx1 = F.max_pool1d(x1, 2, return_mask=True)
    up1 = F.max_unpool1d(p1, idx1, 2)
    assert up1.shape == [2, 3, 8]
    # unpooled grid holds the pooled maxima at their argmax positions
    assert np.allclose(np.sort(up1.numpy()[up1.numpy() != 0]),
                       np.sort(p1.numpy().ravel()))
    x3 = paddle.to_tensor(rs.randn(1, 2, 4, 4, 4).astype("float32"))
    p3, idx3 = F.max_pool3d(x3, 2, return_mask=True)
    up3 = F.max_unpool3d(p3, idx3, 2)
    assert up3.shape == [1, 2, 4, 4, 4]


def test_sparse_attention_full_pattern_equals_dense():
    import jax

    b, h, L, d = 1, 2, 4, 8
    q = rs.randn(b, h, L, d).astype("float32")
    k = rs.randn(b, h, L, d).astype("float32")
    v = rs.randn(b, h, L, d).astype("float32")
    offs = np.tile(np.arange(0, (L + 1) * L, L, dtype=np.int32), (b, h, 1))
    cols = np.tile(np.tile(np.arange(L, dtype=np.int32), L), (b, h, 1))
    out = F.sparse_attention(
        paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v),
        paddle.to_tensor(offs), paddle.to_tensor(cols)).numpy()
    att = jax.nn.softmax(
        np.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(d), axis=-1)
    np.testing.assert_allclose(out, np.einsum("bhqk,bhkd->bhqd", att, v),
                               rtol=1e-4, atol=1e-5)


def test_sparse_attention_respects_pattern():
    """A diagonal-only pattern attends to self only: out == v."""
    b, h, L, d = 1, 1, 4, 4
    q = rs.randn(b, h, L, d).astype("float32")
    k = rs.randn(b, h, L, d).astype("float32")
    v = rs.randn(b, h, L, d).astype("float32")
    offs = np.arange(L + 1, dtype=np.int32).reshape(1, 1, -1)
    cols = np.arange(L, dtype=np.int32).reshape(1, 1, -1)
    out = F.sparse_attention(
        paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v),
        paddle.to_tensor(offs), paddle.to_tensor(cols)).numpy()
    np.testing.assert_allclose(out, v, rtol=1e-5)


def test_hsigmoid_loss_and_layer():
    import paddle_tpu.nn as nn

    x = paddle.to_tensor(rs.randn(4, 6).astype("float32"),
                         stop_gradient=False)
    lbl = paddle.to_tensor(rs.randint(0, 10, (4,)).astype("int64"))
    w = paddle.to_tensor(rs.randn(9, 6).astype("float32"))
    loss = F.hsigmoid_loss(x, lbl, 10, w)
    assert loss.shape == [4, 1]
    assert np.isfinite(loss.numpy()).all() and (loss.numpy() > 0).all()
    loss.sum().backward()
    assert x.grad is not None
    layer = nn.HSigmoidLoss(6, 10)
    out = layer(x, lbl)
    assert out.shape == [4, 1]


def test_margin_cross_entropy_reduces_to_softmax_ce():
    """With zero margins and scale 1, equals plain softmax CE on cos."""
    lg = (rs.rand(4, 10).astype("float32") * 2 - 1)
    lbl = rs.randint(0, 10, (4,)).astype("int64")
    ours = F.margin_cross_entropy(
        paddle.to_tensor(lg), paddle.to_tensor(lbl), margin1=1.0,
        margin2=0.0, margin3=0.0, scale=1.0).numpy()
    e = np.exp(lg - lg.max(-1, keepdims=True))
    sm = e / e.sum(-1, keepdims=True)
    expect = -np.log(sm[np.arange(4), lbl]).mean()
    np.testing.assert_allclose(ours, expect, rtol=1e-5)


def test_gather_tree_backtrace():
    # T=2, B=1, W=2: step-1 beams chose parents [1, 0]
    ids = paddle.to_tensor(np.array(
        [[[10, 20]], [[30, 40]]], np.int64))
    par = paddle.to_tensor(np.array(
        [[[0, 0]], [[1, 0]]], np.int64))
    out = F.gather_tree(ids, par).numpy()
    # final beam 0 came from parent 1: path [20, 30]; beam 1 from 0: [10, 40]
    np.testing.assert_array_equal(out[:, 0, 0], [20, 30])
    np.testing.assert_array_equal(out[:, 0, 1], [10, 40])


def test_yolo_loss_finite_and_sensitive():
    x = paddle.to_tensor(rs.randn(2, 3 * 9, 8, 8).astype("float32"),
                         stop_gradient=False)
    gt = paddle.to_tensor(np.array(
        [[[0.5, 0.5, 0.2, 0.3], [0, 0, 0, 0]]] * 2, "float32"))
    lbl = paddle.to_tensor(np.array([[1, 0]] * 2, "int64"))
    loss = vo.yolo_loss(x, gt, lbl, anchors=[10, 13, 16, 30, 33, 23],
                        anchor_mask=[0, 1, 2], class_num=4,
                        ignore_thresh=0.7, downsample_ratio=32)
    assert loss.shape == [2] and np.isfinite(loss.numpy()).all()
    loss.sum().backward()
    assert np.isfinite(x.grad.numpy()).all()


def test_static_nn_builders_compute():
    x4 = paddle.to_tensor(rs.randn(2, 4, 8, 8).astype("float32"))
    assert snn.conv2d_transpose(x4, 5, 3).shape == [2, 5, 10, 10]
    assert snn.group_norm(x4, 2).shape == [2, 4, 8, 8]
    w = paddle.to_tensor(rs.randn(6, 10).astype("float32"))
    sn = snn.spectral_norm(w, power_iters=20)
    assert abs(float(np.linalg.svd(sn.numpy())[1][0]) - 1.0) < 1e-3
    em = paddle.to_tensor(rs.randn(2, 5, 4).astype("float32"))
    path = snn.crf_decoding(em)
    assert path.shape == [2, 5]
    flatx = paddle.to_tensor(rs.randn(4, 8).astype("float32"))
    lbl = paddle.to_tensor(rs.randint(0, 50, (4, 1)).astype("int64"))
    assert snn.nce(flatx, lbl, 50).shape == [4, 1]


def test_ema_apply_restore():
    import paddle_tpu.static as static

    p = paddle.create_parameter([3], "float32")
    ema = static.ExponentialMovingAverage(decay=0.5)
    orig = p.numpy().copy()
    ema.update([p])
    p._value = p._value + 100.0
    ema.update([p])
    with ema.apply():
        inside = p.numpy().copy()
    np.testing.assert_allclose(p.numpy(), orig + 100.0, rtol=1e-5)
    assert (inside < orig + 100.0).all()  # shadow lags the jump


def test_movielens_wmt_parsers(tmp_path):
    from paddle_tpu.text import WMT16, Movielens

    ml = tmp_path / "ml-1m"
    ml.mkdir()
    (ml / "users.dat").write_text("1::M::25::4::00000\n2::F::35::7::11111\n")
    (ml / "movies.dat").write_text(
        "10::Toy Story (1995)::Animation|Comedy\n20::Heat (1995)::Action\n")
    (ml / "ratings.dat").write_text(
        "1::10::5::100\n2::20::3::200\n1::20::4::300\n")
    ds = Movielens(data_file=str(ml), mode="train", test_ratio=0.0)
    assert len(ds) == 3
    uid, g, a, j, mid, title, cats, rating = ds[0]
    assert rating in (3.0, 4.0, 5.0)

    wmt = tmp_path / "wmt"
    wmt.mkdir()
    (wmt / "train.src").write_text("a b c\nd e\n")
    (wmt / "train.trg").write_text("x y\nz\n")
    ds2 = WMT16(data_file=str(wmt), mode="train")
    assert len(ds2) == 2
    src, tin, tout = ds2[0]
    assert tin[0] == 0 and tout[-1] == 1  # <s> prefix, <e> suffix


def test_distributed_entries_and_gloo():
    import paddle_tpu.distributed as dist

    assert dist.CountFilterEntry(3).to_attr() == "count_filter_entry:3"
    assert dist.ProbabilityEntry(0.5).to_attr() == "probability_entry:0.5"
    assert "show:clk" in dist.ShowClickEntry("show", "clk").to_attr()
    with pytest.raises(ValueError):
        dist.CountFilterEntry(-1)
    dist.gloo_init_parallel_env(0, 1, "127.0.0.1:1")
    dist.gloo_barrier()  # world==1: immediate
    dist.gloo_release()


def test_py_func_reference_backward_contract():
    """backward_func receives (inputs..., outputs..., out_grads...)."""
    import paddle_tpu.static as static

    seen = {}

    def bwd(a, out, g):
        seen["args"] = (a.copy(), out.copy(), g.copy())
        return g * 3.0

    x = paddle.to_tensor(rs.randn(2, 3).astype("float32"),
                         stop_gradient=False)
    tmpl = paddle.zeros([2, 3])
    r = static.py_func(lambda a: a * 3.0, x, tmpl, backward_func=bwd)
    r.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), np.full((2, 3), 3.0),
                               rtol=1e-6)
    a, out, g = seen["args"]
    np.testing.assert_allclose(out, a * 3.0, rtol=1e-6)
    np.testing.assert_allclose(g, np.ones((2, 3)), rtol=1e-6)


def test_hsigmoid_path_nodes_unique_non_power_of_two():
    """num_classes=5 (not a power of 2): every label's path must visit
    DISTINCT internal nodes (the old %-aliasing bug shared rows)."""
    from paddle_tpu.nn.functional.loss import hsigmoid_loss

    x = paddle.to_tensor(rs.randn(5, 4).astype("float32"))
    w = paddle.to_tensor(np.zeros((4, 4), "float32"))
    for c in range(5):
        lbl = paddle.to_tensor(np.array([c], "int64"))
        # reference SimpleCode: nodes (c+C)>>(i+1) - 1 while >= 1
        cc = c + 5
        nodes = []
        i = 0
        while (cc >> (i + 1)) >= 1:
            nodes.append((cc >> (i + 1)) - 1)
            i += 1
        assert len(set(nodes)) == len(nodes), (c, nodes)
        assert all(0 <= n < 4 for n in nodes), (c, nodes)
        loss = hsigmoid_loss(x[:1], lbl, 5, w)
        # zero weights: every step is log_sigmoid(0) = -log 2
        np.testing.assert_allclose(np.asarray(loss.numpy()).reshape(()),
                                   len(nodes) * np.log(2.0), rtol=1e-5)


def test_max_unpool_reference_output_formula():
    x = paddle.to_tensor(rs.randn(1, 1, 4).astype("float32"))
    p, idx = F.max_pool1d(x, 2, return_mask=True)
    # kernel 3, stride 2: (2-1)*2 + 3 = 5
    up = F.max_unpool1d(p, idx, kernel_size=3, stride=2)
    assert up.shape == [1, 1, 5], up.shape


def test_ema_with_idiom_double_enter_safe():
    import paddle_tpu.static as static

    p = paddle.create_parameter([2], "float32")
    ema = static.ExponentialMovingAverage(0.5)
    ema.update([p])
    orig = p.numpy().copy()
    ctx = ema.apply()
    with ctx:  # single with over a returned ctx: must not double-swap
        pass
    np.testing.assert_allclose(p.numpy(), orig, rtol=1e-6)


def test_fleet_data_generator_slot_format():
    from paddle_tpu.distributed.fleet import (
        Fleet, MultiSlotDataGenerator, MultiSlotStringDataGenerator,
    )

    class G(MultiSlotDataGenerator):
        def generate_sample(self, line):
            def it():
                yield [("words", [int(x) for x in line.split()]),
                       ("label", [1])]
            return it

    out = G().run_from_memory(["1926 8 17", "4 5"])
    assert out == "3 1926 8 17 1 1\n2 4 5 1 1\n"

    class S(MultiSlotStringDataGenerator):
        def generate_sample(self, line):
            def it():
                yield [("q", line.split())]
            return it

    assert S().run_from_memory(["a b"]) == "2 a b\n"
    f = Fleet()
    assert callable(f.init)


def test_data_generator_slot_count_mismatch_raises():
    from paddle_tpu.distributed.fleet import MultiSlotDataGenerator

    class Bad(MultiSlotDataGenerator):
        def generate_sample(self, line):
            def it():
                if line == "a":
                    yield [("w", [1]), ("l", [0])]
                else:
                    yield [("w", [1])]  # slot set shrank
            return it

    with pytest.raises(ValueError):
        Bad().run_from_memory(["a", "b"])

"""Legacy pslib fleet wrapper + FleetUtil surface (VERDICT r4 missing #4).

Reference: python/paddle/fluid/incubate/fleet/parameter_server/pslib/
(the DownpourSGD fleet singleton over fleet_wrapper.cc) and
incubate/fleet/utils/fleet_util.py (global metrics, day/pass model
lifecycle, online pass intervals). These pin that the legacy entry
points drive the REAL native PS subsystem and that the global metric
math matches oracles.
"""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as optim
from paddle_tpu.incubate.fleet.parameter_server.pslib import (
    DownpourOptimizer, PSLib,
)
from paddle_tpu.incubate.fleet.utils import FleetUtil, GlobalMetrics

rng = np.random.RandomState(11)


@pytest.fixture
def pslib_local():
    f = PSLib().init()
    # fresh in-process runtime per test
    from paddle_tpu.distributed.ps import LocalPs, TheOnePSRuntime

    f._runtime = TheOnePSRuntime()
    f._runtime.client = LocalPs()
    return f


class TestFleetUtilMetrics:
    def test_global_auc_matches_metric_accumulate(self):
        from paddle_tpu.metric import Auc

        preds = rng.rand(500)
        labels = (rng.rand(500) < preds).astype(np.int64)  # correlated
        m = Auc(num_thresholds=4095)
        m.update(preds, labels)
        auc, n = FleetUtil().get_global_auc(m)
        assert n == 500
        np.testing.assert_allclose(auc, m.accumulate(), rtol=1e-9)
        assert auc > 0.6  # genuinely discriminative data

    def test_global_metrics_against_numpy_oracle(self):
        gm = GlobalMetrics(num_thresholds=4095)
        preds = rng.rand(2000)
        labels = (rng.rand(2000) < 0.3).astype(np.float64)
        # two update calls: accumulation must compose
        gm.update(preds[:800], labels[:800])
        gm.update(preds[800:], labels[800:])
        out = FleetUtil().get_global_metrics(gm)
        np.testing.assert_allclose(out["mae"], np.abs(preds - labels).mean(),
                                   rtol=1e-9)
        np.testing.assert_allclose(
            out["rmse"], np.sqrt(((preds - labels) ** 2).mean()), rtol=1e-9)
        np.testing.assert_allclose(out["actual_ctr"], labels.mean(),
                                   rtol=1e-9)
        np.testing.assert_allclose(out["predicted_ctr"], preds.mean(),
                                   rtol=1e-9)
        np.testing.assert_allclose(
            out["copc"], labels.mean() / preds.mean(), rtol=1e-9)
        assert out["total_ins_num"] == 2000

    def test_set_zero(self):
        gm = GlobalMetrics()
        gm.update([0.5], [1])
        FleetUtil().set_zero(gm)
        assert gm.compute()["total_ins_num"] == 0

    def test_online_pass_interval(self):
        fu = FleetUtil()
        iv = fu.get_online_pass_interval(
            days="{20190720..20190729}", hours="{0..23}",
            split_interval=5, split_per_pass=2,
            is_data_hourly_placed=False)
        assert len(iv) == (24 * 60 // 5) // 2
        assert iv[0] == ["0000", "0005"]
        assert iv[-1] == ["2350", "2355"]
        # hourly placement names splits by hour
        iv_h = fu.get_online_pass_interval(
            days="20190720", hours="{0..1}", split_interval=30,
            split_per_pass=2, is_data_hourly_placed=True)
        assert iv_h[0] == ["00", "00"]


class TestPslibFleet:
    def test_table_save_load_shrink_clear(self, pslib_local, tmp_path):
        f = pslib_local
        c = f.init_worker()
        c.create_table(0, dim=4, optimizer="sgd", lr=1.0, init_range=0.0)
        keys = np.arange(16, dtype=np.uint64)
        c.push(0, keys, np.ones((16, 4), np.float32))
        assert c.table_size(0) == 16

        d = f.save_persistables(None, str(tmp_path / "model"))
        assert os.path.exists(os.path.join(d, "table_0"))

        f.clear_model()
        assert c.table_size(0) == 0
        f.load_model(str(tmp_path / "model"))
        assert c.table_size(0) == 16
        np.testing.assert_allclose(
            c.pull(0, keys, create_if_missing=False), -1.0)

        # shrink drops cold rows (show decayed below threshold)
        dropped = f.shrink_sparse_table(decay=0.0, threshold=0.5)
        assert dropped == 16 and c.table_size(0) == 0

    def test_rpc_server_lifecycle(self, tmp_path):
        f = PSLib().init()
        from paddle_tpu.distributed.ps import TheOnePSRuntime

        f._runtime = TheOnePSRuntime()
        ep = f.init_server()
        try:
            c = f.init_worker([ep])
            c.create_table(1, dim=2, optimizer="sgd", lr=0.5,
                           init_range=0.0)
            c.push(1, np.asarray([7], np.uint64),
                   np.ones((1, 2), np.float32))
            np.testing.assert_allclose(
                c.pull(1, np.asarray([7], np.uint64)), -0.5)
            # facade save path covers PsClient-tracked tables
            d = f.save_persistables(None, str(tmp_path / "m"))
            import glob
            assert glob.glob(os.path.join(d, "table_1*"))  # per-shard files
            assert f.shrink_sparse_table(decay=0.0, threshold=0.5) == 1
        finally:
            f.stop_worker()
            f.stop_server()

    def test_downpour_optimizer_minimizes(self, pslib_local):
        f = pslib_local
        net = nn.Linear(4, 1)
        opt = f.distributed_optimizer(
            optim.SGD(learning_rate=0.1, parameters=net.parameters()))
        assert isinstance(opt, DownpourOptimizer)
        x = paddle.to_tensor(rng.rand(8, 4).astype(np.float32))
        y = paddle.to_tensor(rng.rand(8, 1).astype(np.float32))
        losses = []
        for _ in range(10):
            loss = nn.functional.mse_loss(net(x), y)
            losses.append(float(loss.numpy()))
            opt.minimize(loss)
        assert losses[-1] < losses[0]

    def test_fleet_util_model_lifecycle(self, pslib_local, tmp_path):
        f = pslib_local
        c = f.init_worker()
        c.create_table(0, dim=2, optimizer="sgd", lr=1.0, init_range=0.0)
        c.push(0, np.asarray([1, 2], np.uint64), np.ones((2, 2), np.float32))

        import paddle_tpu.incubate.fleet.utils.fleet_util as fu_mod

        fu = FleetUtil()
        out = str(tmp_path / "out")
        path = fu.save_model(out, 20260731, 3)
        assert os.path.exists(os.path.join(path, "table_0"))
        fu.write_model_donefile(out, 20260731, 3)
        day, pass_id, last = fu.get_last_save_model(out)
        assert (day, pass_id, last) == (20260731, 3, path)

        f.clear_model()
        fu.load_model(out, 20260731, 3)
        assert c.table_size(0) == 2


def test_rpc_save_load_roundtrip(tmp_path):
    """RPC mode save -> clear -> load_model must round-trip through the
    per-shard file naming (table_<id>.shard<i>)."""
    f = PSLib().init()
    from paddle_tpu.distributed.ps import TheOnePSRuntime

    f._runtime = TheOnePSRuntime()
    ep = f.init_server()
    try:
        c = f.init_worker([ep])
        c.create_table(2, dim=3, optimizer="sgd", lr=1.0, init_range=0.0)
        keys = np.arange(5, dtype=np.uint64)
        c.push(2, keys, np.ones((5, 3), np.float32))
        d = f.save_persistables(None, str(tmp_path / "m"))
        f.clear_model()
        assert c.table_size(2) == 0
        f.load_model(d)
        assert c.table_size(2) == 5
        np.testing.assert_allclose(
            c.pull(2, keys, create_if_missing=False), -1.0)
    finally:
        f.stop_worker()
        f.stop_server()


def test_save_covers_tables_created_by_other_clients(tmp_path):
    """save_persistables uses the SERVER's table list, so a checkpoint
    covers tables a different worker created."""
    import glob

    from paddle_tpu.distributed.ps import PsClient, PsServer

    server = PsServer().start()
    try:
        other = PsClient([server.endpoint])
        other.create_table(9, dim=2, optimizer="sgd", lr=1.0,
                           init_range=0.0)
        other.push(9, np.asarray([3], np.uint64),
                   np.ones((1, 2), np.float32))
        other.close()

        f = PSLib().init()
        from paddle_tpu.distributed.ps import TheOnePSRuntime

        f._runtime = TheOnePSRuntime()
        f._runtime.client = PsClient([server.endpoint])
        d = f.save_persistables(None, str(tmp_path / "m"))
        assert glob.glob(os.path.join(d, "table_9*"))
        f._runtime.client.close()
    finally:
        server.stop()


def test_fluid_incubate_import_path_parity():
    """Reference scripts import `paddle.fluid.incubate.fleet...` verbatim;
    the compat alias must resolve the full dotted path."""
    from paddle_tpu.fluid.incubate.fleet.parameter_server.pslib import (
        fleet as pslib_fleet,
    )
    from paddle_tpu.fluid.incubate.fleet.utils.fleet_util import FleetUtil

    assert isinstance(pslib_fleet, PSLib)
    assert FleetUtil().mode == "pslib"


def test_distributed_metric_registry(tmp_path):
    """paddle.distributed.metric surface (reference metrics.py): yaml
    monitor registration, masked updates, message formatting."""
    from paddle_tpu.distributed.metric import (
        MetricRegistry, init_metric, print_auc, print_metric,
    )

    yml = tmp_path / "metrics.yaml"
    yml.write_text(
        "monitors:\n"
        "  - {name: join_auc, method: AucCalculator, phase: JOINING,\n"
        "     label: click, target: prob}\n"
        "  - {name: update_auc, method: MaskAucCalculator, phase: UPDATING,\n"
        "     label: click, target: prob, mask: m}\n")
    reg = MetricRegistry()
    init_metric(reg, str(yml))
    assert reg.get_metric_name_list(1) == ["join_auc"]
    assert reg.get_metric_name_list(0) == ["update_auc"]

    preds = rng.rand(400)
    labels = (rng.rand(400) < preds).astype(np.int64)
    reg.update("join_auc", preds, labels)
    # masked variant only sees half the instances
    mask = np.arange(400) % 2 == 0
    reg.update("update_auc", preds, labels, mask=mask)

    msg = print_metric(reg, "join_auc")
    assert "AUC=" in msg and "INS Count=400" in msg
    msgs = print_auc(reg, is_day=False, phase="update")
    assert len(msgs) == 1 and "INS Count=200" in msgs[0]
    auc = reg.get_metric_msg("join_auc")[0]
    assert auc > 0.6
    reg.reset()
    assert reg.get_metric_msg("join_auc")[-1] == 0


def test_metric_yaml_phase_fallback_and_grouped_warning(tmp_path):
    import warnings

    from paddle_tpu.distributed.metric import MetricRegistry, init_metric

    yml = tmp_path / "m.yaml"
    yml.write_text("monitors:\n  - {name: a, method: AucCalculator}\n")
    reg = MetricRegistry()
    init_metric(reg, str(yml), phase=1)  # no yaml phase: arg supplies it
    assert reg.get_metric_name_list(1) == ["a"]

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        reg.init_metric("WuAucCalculator", "wu", "l", "t")
    assert any("grouped" in str(x.message) for x in w)

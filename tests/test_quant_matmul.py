"""Pallas int8 weight-only quantized matmul (ops/quant_matmul.py)."""
import numpy as np

import paddle_tpu  # noqa: F401  (conftest platform setup)
from paddle_tpu.ops.quant_matmul import quant_matmul, quantize_int8

import jax.numpy as jnp


def test_quantize_roundtrip_error_small():
    rs = np.random.RandomState(0)
    w = jnp.asarray(rs.randn(64, 128).astype("f4"))
    q, s = quantize_int8(w)
    assert q.dtype == jnp.int8 and s.shape == (1, 128)
    deq = np.asarray(q, np.float32) * np.asarray(s)
    # int8 symmetric: error bounded by scale/2 per element
    err = np.abs(deq - np.asarray(w))
    assert (err <= np.asarray(s) / 2 + 1e-6).all()


def test_quant_matmul_matches_dequant_reference():
    rs = np.random.RandomState(1)
    x = jnp.asarray(rs.randn(256, 512).astype("f4"))
    w = jnp.asarray(rs.randn(512, 256).astype("f4"))
    q, s = quantize_int8(w)
    out = quant_matmul(x, q, s)
    ref = np.asarray(x) @ (np.asarray(q, np.float32) * np.asarray(s))
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-3)
    # and close to the full-precision product (quantization error only)
    full = np.asarray(x) @ np.asarray(w)
    rel = np.abs(np.asarray(out) - full).mean() / np.abs(full).mean()
    assert rel < 0.02, rel


def test_ragged_shapes_fall_back():
    rs = np.random.RandomState(2)
    x = jnp.asarray(rs.randn(10, 48).astype("f4"))
    w = jnp.asarray(rs.randn(48, 24).astype("f4"))
    q, s = quantize_int8(w)
    out = quant_matmul(x, q, s)
    ref = np.asarray(x) @ (np.asarray(q, np.float32) * np.asarray(s))
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-3)


def test_int8_linear_serving_conversion():
    """convert_to_int8 swaps Linears for pallas-kernel Int8Linear with small
    output error (weight-only int8)."""
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.quantization import Int8Linear, convert_to_int8

    rs = np.random.RandomState(0)
    net = nn.Sequential(nn.Linear(64, 128), nn.ReLU(), nn.Linear(128, 32))
    x = paddle.to_tensor(rs.randn(16, 64).astype("f4"))
    ref = net(x).numpy()
    convert_to_int8(net)
    assert isinstance(net[0], Int8Linear) and isinstance(net[2], Int8Linear)
    out = net(x).numpy()
    rel = np.abs(out - ref).mean() / (np.abs(ref).mean() + 1e-9)
    assert rel < 0.05, rel

"""1F1B throughput guard (VERDICT r3 next #2).

The memory half of the 1F1B claim is proven by
test_pipeline_1f1b.py::test_1f1b_memory_is_o_p_not_o_m; this file guards
the SPEED half: with the segmented schedule (fill ticks skip the backward
phase, drain ticks skip the forward phase), 1F1B's work-unit cost at
M = 4P is 4M+4P-4 — equal to GPipe-fill-drain-with-remat's 4(M+P-1) —
so measured throughput must stay within implementation-overhead distance
of both GPipe variants, while holding the O(P) stash.

Reference anchor: section_worker.cc:143-199 — 1F1B is a memory win at
equal speed, not a throughput trade.

On this 1-core host the virtual devices serialize, so wall-clock ~ total
work summed over stages; the RATIO between schedules is what the bounds
below pin (and it carries to real chips, where the same tick accounting
divides by P).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.distributed import mesh as mesh_mod
from paddle_tpu.distributed.pipeline import pipeline_1f1b

from pipeline_toy import (
    DIN, DOUT, SPECS, bench_min_interleaved, embed_fn, gpipe_value_and_grad,
    loss_fn, make_params, stage_fn,
)

PIPE = 4
KPER = 2
HID = 256
MB = 8
M = 4 * PIPE          # the M = 4P regime the VERDICT asks about
STEPS = 5             # min-of-5: robust to contention bursts


@pytest.fixture(scope="module")
def pipe_mesh():
    prev = mesh_mod.get_mesh()
    mesh = mesh_mod.build_mesh({"pipe": PIPE}, devices=jax.devices()[:PIPE])
    mesh_mod.set_mesh(mesh)
    yield mesh
    mesh_mod.set_mesh(prev)


def test_1f1b_throughput_matches_gpipe_at_m4p(pipe_mesh):
    rs = np.random.RandomState(0)
    params = make_params(rs, PIPE * KPER, HID)
    batch = M * MB
    x = jnp.asarray(rs.randn(batch, DIN), jnp.float32)
    lbl = jnp.asarray(rs.randn(batch, DOUT), jnp.float32)

    t_gpipe, t_gpipe_remat, t_1f1b = bench_min_interleaved(
        [jax.jit(lambda p, xx, ll: gpipe_value_and_grad(
             pipe_mesh, M, p, xx, ll, remat=False)),
         jax.jit(lambda p, xx, ll: gpipe_value_and_grad(
             pipe_mesh, M, p, xx, ll, remat=True)),
         jax.jit(lambda p, xx, ll: pipeline_1f1b(
             embed_fn, stage_fn, loss_fn, p, xx, ll,
             mesh=pipe_mesh, param_specs=SPECS, microbatches=M))],
        (params, x, lbl), STEPS)

    # Equal memory policy (both recompute): work-unit model says 1.0x at
    # M=4P; allow 30% for VJP/permute machinery (measured ~1.10x) + noise.
    # A regression to the pre-segmentation schedule (model 1.42x, the
    # whole-tick scan) fails this bound.
    assert t_1f1b <= 1.30 * t_gpipe_remat, (t_1f1b, t_gpipe_remat)
    # Against no-remat fill-drain (O(M) memory), the recompute overhead is
    # bounded: model 76/57 = 1.33x (measured ~1.28x); allow 1.55x.
    assert t_1f1b <= 1.55 * t_gpipe, (t_1f1b, t_gpipe)

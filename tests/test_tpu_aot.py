"""The distributed train step and pallas kernels COMPILE for real TPU.

The CPU virtual-mesh suite proves the sharded programs are numerically
correct; these tests prove the TPU compiler (via jax.experimental
.topologies — ahead-of-time, no TPU execution) accepts them: the GSPMD
ZeRO-2 + TP TrainStep on a described v5e:2x4, and the pallas
flash-attention kernel's Mosaic lowering on a v5e chip. A regression here
means "works on the CPU mesh, breaks on TPU hardware" — exactly the gap
VERDICT r3 flagged for the CPU-only HBM estimate (tools/gpt13b_aot_tpu.py
and tools/hybrid_aot_tpu.py carry the full config matrix; this is the
fast always-on subset).

Runs in a subprocess: the topology compile client is process-global state
the suite shouldn't inherit.
"""
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

PROBE = (
    "from jax.experimental import topologies; "
    "topologies.get_topology_desc(platform='tpu', topology_name='v5e:2x4')"
)

CHILD = r"""
import sys, time
sys.path.insert(0, %r)
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax.experimental import topologies

sys.path.insert(0, %r + "/tools")
from hybrid_aot_tpu import aot_compile_step, build_config_a

step, inputs, labels = build_config_a()
r = aot_compile_step(step, inputs, labels)
assert r.get("peak_hbm_bytes", 0) > 0, r
print("TRAINSTEP-AOT-OK", r["compile_seconds"])

from paddle_tpu.ops.flash_attention import flash_attention_val
topo = topologies.get_topology_desc(platform="tpu", topology_name="v5e:2x4")
mesh1 = Mesh(np.asarray(topo.devices[:1]).reshape(1), ("x",))
sh = NamedSharding(mesh1, P())
SDS = jax.ShapeDtypeStruct
q = SDS((4, 512, 4, 64), jnp.bfloat16, sharding=sh)
jax.jit(lambda a, b, c: flash_attention_val(a, b, c, block_size=256),
        in_shardings=(sh, sh, sh), out_shardings=sh).lower(q, q, q).compile()
print("PALLAS-AOT-OK")
""" % (REPO, REPO)


def _has_tpu_compiler():
    try:
        r = subprocess.run(
            [sys.executable, "-c", PROBE],
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
            capture_output=True, timeout=120)
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def test_trainstep_and_pallas_compile_for_tpu():
    if not _has_tpu_compiler():
        pytest.skip("no TPU AOT compiler (libtpu topology) available")
    proc = subprocess.run(
        [sys.executable, "-c", CHILD],
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        capture_output=True, text=True, timeout=900)
    assert proc.returncode == 0, proc.stderr[-3000:]
    assert "TRAINSTEP-AOT-OK" in proc.stdout
    assert "PALLAS-AOT-OK" in proc.stdout

"""The distributed train step and pallas kernels COMPILE for real TPU.

The CPU virtual-mesh suite proves the sharded programs are numerically
correct; these tests prove the TPU compiler (via jax.experimental
.topologies — ahead-of-time, no TPU execution) accepts them: the GSPMD
ZeRO-2 + TP TrainStep on a described v5e:2x4, and the pallas
flash-attention kernel's Mosaic lowering on a v5e chip. A regression here
means "works on the CPU mesh, breaks on TPU hardware" — exactly the gap
VERDICT r3 flagged for the CPU-only HBM estimate (tools/gpt13b_aot_tpu.py
and tools/hybrid_aot_tpu.py carry the full config matrix; this is the
fast always-on subset).

Runs in a subprocess: the topology compile client is process-global state
the suite shouldn't inherit.
"""
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

PROBE = (
    "from jax.experimental import topologies; "
    "topologies.get_topology_desc(platform='tpu', topology_name='v5e:2x4')"
)

CHILD = r"""
import sys, time
sys.path.insert(0, %r)
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax.experimental import topologies

sys.path.insert(0, %r + "/tools")
from hybrid_aot_tpu import aot_compile_step, build_config_a

step, inputs, labels = build_config_a()
r = aot_compile_step(step, inputs, labels)
assert r.get("peak_hbm_bytes", 0) > 0, r
print("TRAINSTEP-AOT-OK", r["compile_seconds"])

from paddle_tpu.jit.aot import compile_pallas_flash_for_tpu
compile_pallas_flash_for_tpu((4, 512, 4, 64), block_size=256, grad=False)
print("PALLAS-AOT-OK")
""" % (REPO, REPO)


_COMPILER_STATE = {"ok": None}


def _has_tpu_compiler():
    """Probe once per session, retrying with backoff when the failure
    looks like libtpu lockfile CONTENTION (another process compiling) —
    VERDICT r4 #9: contention must not silently disable these gates. A
    missing-libtpu failure stays fast (no retry)."""
    if _COMPILER_STATE["ok"] is not None:
        return _COMPILER_STATE["ok"]
    import time

    ok = False
    for attempt, backoff in enumerate((0, 5, 10, 20)):
        if backoff:
            time.sleep(backoff)
        contended = False
        try:
            r = subprocess.run(
                [sys.executable, "-c", PROBE],
                env={**os.environ, "JAX_PLATFORMS": "cpu"},
                capture_output=True, text=True, timeout=120)
            ok = r.returncode == 0
            err = (r.stderr or "").lower()
            # lock-specific phrasing only (incl. libtpu's canonical
            # "The TPU is already in use by process with pid N"); broad
            # tokens like "unavailable" would retry a genuinely-missing
            # libtpu through the full backoff
            contended = any(tok in err for tok in
                            ("lockfile", "libtpu_lockfile",
                             "held by", "another process",
                             "already in use", "in use by process"))
        except subprocess.TimeoutExpired:
            contended = True  # a held lock hangs the client
        if ok or not contended:
            break
    _COMPILER_STATE["ok"] = ok
    return ok


def test_trainstep_and_pallas_compile_for_tpu():
    if not _has_tpu_compiler():
        pytest.skip("TPU AOT compiler unavailable (no libtpu, or another "
                    "process holds the libtpu lockfile — it is "
                    "single-process)")
    proc = subprocess.run(
        [sys.executable, "-c", CHILD],
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        capture_output=True, text=True, timeout=900)
    assert proc.returncode == 0, proc.stderr[-3000:]
    assert "TRAINSTEP-AOT-OK" in proc.stdout
    assert "PALLAS-AOT-OK" in proc.stdout


PLANNER_CHILD = r"""
import sys
sys.path.insert(0, %r)
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.optimizer as opt
from paddle_tpu.jit import TrainStep
from paddle_tpu.models import (GPTForCausalLM, GPTPretrainingCriterion,
                               gpt_presets)
from paddle_tpu.distributed import mesh as mesh_mod
from paddle_tpu.distributed.auto_parallel.planner import (
    plan, enumerate_factorizations)

# pure-search unit: factor assignment honors caps, drops degree-1 axes
f = enumerate_factorizations(8, ("data", "model"), caps={"model": 4})
assert {tuple(sorted(c.items())) for c in f} == {
    (("data", 8),), (("data", 4), ("model", 2)),
    (("data", 2), ("model", 4))}, f

crit = GPTPretrainingCriterion()
rs = np.random.RandomState(0)

def builder(shape_map, activate_mesh):
    cfg = gpt_presets("gpt-test", mode="scan", use_flash_attention=False)
    model = GPTForCausalLM(cfg, seed=0)
    optim = opt.AdamW(learning_rate=1e-4, parameters=model.parameters())
    step = TrainStep(model, lambda lg, lb: crit(lg, lb), optim,
                     batch_spec=P(("data", "sharding")))
    ids = paddle.to_tensor(rs.randint(0, cfg.vocab_size, (16, 16)),
                           dtype="int64")
    lbl = paddle.to_tensor(rs.randint(0, cfg.vocab_size, (16, 16)),
                           dtype="int64")
    activate_mesh()
    return step, (ids,), (lbl,)

plans = plan(builder, 8, axes=("data", "model"), caps={"model": 4},
             verbose=False)
assert len(plans) == 3, plans
assert all(p.error is None for p in plans), plans
assert all(p.est_seconds and p.est_seconds > 0 for p in plans), plans
assert all(p.peak_hbm_bytes and p.fits for p in plans), plans
# sorted best-first by the estimate
secs = [p.est_seconds for p in plans]
assert secs == sorted(secs), plans
assert mesh_mod.get_mesh() is None  # planner restored ambient mesh
print("PLANNER-OK", plans[0].shape_map)
""" % (REPO,)


GPT13B_CHILD = r"""
import json, sys
sys.path.insert(0, %r)
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, %r + "/tools")
from gpt13b_aot_tpu import compile_config4

est = compile_config4()  # the exact configuration the artifact records
assert est.get("peak_hbm_bytes", 0) > 0, est
print("HBM13B_JSON:" + json.dumps(est))
""" % (REPO, REPO)


@pytest.mark.slow
def test_gpt13b_fits_v5e_by_the_real_tpu_compiler():
    """BASELINE config-4 feasibility pinned with the TPU backend, not the
    CPU proxy (tests/test_gpt13b_memory.py keeps the CPU guard): the full
    AdamW step (ZeRO-2 sharding32 x mp2, bf16 + remat + flash) must fit a
    v5e chip per XLA-TPU's own memory accounting. Artifact counterpart:
    artifacts/gpt13b_aot_tpu.json (2.55 GiB/device)."""
    if not _has_tpu_compiler():
        pytest.skip("TPU AOT compiler unavailable (no libtpu, or another "
                    "process holds the libtpu lockfile — it is "
                    "single-process)")
    import json

    proc = subprocess.run(
        [sys.executable, "-c", GPT13B_CHILD],
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        capture_output=True, text=True, timeout=1200)
    assert proc.returncode == 0, proc.stderr[-3000:]
    est = None
    for line in proc.stdout.splitlines():
        if line.startswith("HBM13B_JSON:"):
            est = json.loads(line[len("HBM13B_JSON:"):])
    assert est is not None, proc.stdout[-1000:]
    peak_gib = est["peak_hbm_bytes"] / 2**30
    assert 1.0 <= peak_gib <= 16.0, est


def test_mesh_planner_ranks_with_tpu_compiler():
    """distributed.auto_parallel.planner: the reference's Planner+cost_model
    (auto_parallel/planner.py:829) redesigned with XLA-TPU AOT compilation
    as the cost model — candidates enumerate, compile, rank, mesh state
    restored."""
    if not _has_tpu_compiler():
        pytest.skip("TPU AOT compiler unavailable (no libtpu, or another "
                    "process holds the libtpu lockfile — it is "
                    "single-process)")
    proc = subprocess.run(
        [sys.executable, "-c", PLANNER_CHILD],
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        capture_output=True, text=True, timeout=900)
    assert proc.returncode == 0, proc.stderr[-3000:]
    assert "PLANNER-OK" in proc.stdout

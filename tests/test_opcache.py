"""Eager op-cache (framework/autograd.py): compiled dispatch correctness.

SURVEY §7 hard part 1 — eager dispatch must not re-trace per op. These tests
pin the cache's correctness contract; the 10x speedup evidence lives in the
commit history (100-op loop: 11.5x on CPU).
"""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.framework.autograd as ag


def setup_function(_):
    ag.clear_op_cache()


def test_cache_populates_and_hits():
    x = paddle.to_tensor(np.ones((4, 4), np.float32))
    x.stop_gradient = False
    before = len(ag._OPCACHE)
    y1 = paddle.tanh(x)
    mid = len(ag._OPCACHE)
    y2 = paddle.tanh(x)
    after = len(ag._OPCACHE)
    assert mid > before
    assert after == mid  # second call hits
    np.testing.assert_allclose(y1.numpy(), y2.numpy())


def test_cached_gradients_correct():
    x = paddle.to_tensor(np.random.RandomState(0).randn(8, 8).astype("float32"))
    x.stop_gradient = False
    # run twice: second pass uses cached fwd+bwd
    for _ in range(2):
        y = (paddle.tanh(x) * 2.0).sum()
        y.backward()
        g = x.grad.numpy().copy()
        x.clear_gradient()
    expect = 2.0 / np.cosh(np.asarray(
        x.numpy(), np.float64)) ** 2
    np.testing.assert_allclose(g, expect.astype(np.float32), rtol=1e-5)


def test_shape_change_gets_new_entry():
    a = paddle.to_tensor(np.ones((2, 2), np.float32))
    b = paddle.to_tensor(np.ones((3, 3), np.float32))
    paddle.exp(a)
    n1 = len(ag._OPCACHE)
    paddle.exp(b)
    assert len(ag._OPCACHE) > n1


def test_closure_over_array_skips_cache():
    import jax.numpy as jnp

    from paddle_tpu.framework.autograd import call_op

    x = paddle.to_tensor(np.ones((2, 2), np.float32))
    bias = jnp.ones((2, 2))  # unhashable closure cell
    n0 = len(ag._OPCACHE)
    out = call_op(lambda v: v + bias, x, op_name="closure_arr")
    assert len(ag._OPCACHE) == n0
    np.testing.assert_allclose(out.numpy(), 2 * np.ones((2, 2)))


def test_scalar_closure_is_cached_per_value():
    # hardshrink-style lambdas capture a float threshold; different values
    # must not collide
    x = paddle.to_tensor(np.asarray([[0.3, 0.7]], np.float32))
    y1 = paddle.nn.functional.hardshrink(x, threshold=0.5)
    y2 = paddle.nn.functional.hardshrink(x, threshold=0.1)
    np.testing.assert_allclose(y1.numpy(), [[0.0, 0.7]])
    np.testing.assert_allclose(y2.numpy(), [[0.3, 0.7]])


def test_integer_outputs_still_work():
    x = paddle.to_tensor(np.random.RandomState(1).randn(4, 5).astype("float32"))
    x.stop_gradient = False
    vals, idx = paddle.topk(x, k=2)
    loss = vals.sum()
    loss.backward()
    assert x.grad is not None
    assert int(x.grad.numpy().sum() + 0.5) == 8  # 2 ones per row


def test_negative_zero_scalar_not_cache_aliased():
    # ADVICE r3 (low): -0.0 == 0.0 hashes equal, so the scalar cache must
    # key on the sign of zero or 1/x flips between +inf and -inf
    pos = paddle.to_tensor(np.asarray([1.0], np.float32))
    a = (pos * 0.0).numpy()       # populates the cache with +0.0
    b = (pos * -0.0).numpy()      # must NOT reuse the +0.0 array
    assert np.signbit(b[0]) and not np.signbit(a[0])
    inv = (1.0 / (pos * -0.0)).numpy()
    assert np.isneginf(inv[0]), inv

"""paddle.static facade tests: Program recording, Executor replay, training.

Parity model: the reference's static-graph tests
(unittests/test_executor_*.py, §3.1 call stack). Build-time op recording +
jitted replay replaces ProgramDesc + the C++ interpreter loop.
"""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
import paddle_tpu.optimizer as opt
import paddle_tpu.static as static


def setup_function(_):
    paddle.enable_static()


def teardown_function(_):
    paddle.disable_static()


def test_program_record_and_run():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 4], "float32")
        w = paddle.to_tensor(np.eye(4, dtype="float32") * 2.0)
        y = paddle.matmul(x, w)
        z = y + 1.0
    assert len(main.ops) >= 2
    exe = static.Executor()
    feed_x = np.arange(8, dtype="float32").reshape(2, 4)
    (out,) = exe.run(main, feed={"x": feed_x}, fetch_list=[z])
    np.testing.assert_allclose(out, feed_x * 2.0 + 1.0)


def test_feed_batch_differs_from_build():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 3], "float32")
        y = F.relu(x) * 3.0
    exe = static.Executor()
    for bs in (1, 5, 2):
        feed_x = np.random.RandomState(bs).randn(bs, 3).astype("float32")
        (out,) = exe.run(main, feed={"x": feed_x}, fetch_list=[y])
        np.testing.assert_allclose(out, np.maximum(feed_x, 0) * 3.0,
                                   rtol=1e-6)


def test_layer_under_program_guard():
    main = static.Program()
    startup = static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [None, 8], "float32")
        lin = nn.Linear(8, 2)
        y = lin(x)
    exe = static.Executor()
    exe.run(startup)  # no-op: params initialized eagerly
    feed_x = np.random.RandomState(0).randn(4, 8).astype("float32")
    (out,) = exe.run(main, feed={"x": feed_x}, fetch_list=[y])
    expect = feed_x @ np.asarray(lin.weight.numpy()) + np.asarray(
        lin.bias.numpy())
    np.testing.assert_allclose(out, expect, rtol=1e-5, atol=1e-5)


def test_static_nn_fc():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 6], "float32")
        y = static.nn.fc(x, 3, activation="relu")
    exe = static.Executor()
    (out,) = exe.run(main, feed={"x": np.ones((2, 6), "float32")},
                     fetch_list=[y])
    assert out.shape == (2, 3)
    assert (out >= 0).all()


def test_append_backward_grad_fetch():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 4], "float32")
        lin = nn.Linear(4, 1, bias_attr=False)
        loss = (lin(x) ** 2).mean()
        pairs = static.append_backward(loss)
    assert len(pairs) == 1
    exe = static.Executor()
    feed_x = np.random.RandomState(0).randn(3, 4).astype("float32")
    loss_v, grad_v = exe.run(main, feed={"x": feed_x},
                             fetch_list=[loss, pairs[0][1]])
    # finite-difference check on one weight element
    w = np.asarray(lin.weight.numpy())
    eps = 1e-3

    def loss_at(wv):
        return float((((feed_x @ wv) ** 2)).mean())

    wp, wm = w.copy(), w.copy()
    wp[0, 0] += eps
    wm[0, 0] -= eps
    num = (loss_at(wp) - loss_at(wm)) / (2 * eps)
    np.testing.assert_allclose(grad_v[0, 0], num, rtol=1e-2, atol=1e-3)


def test_static_training_minimize():
    main = static.Program()
    startup = static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [None, 4], "float32")
        label = static.data("label", [None, 1], "float32")
        lin = nn.Linear(4, 1)
        pred = lin(x)
        loss = F.mse_loss(pred, label)
        sgd = opt.SGD(learning_rate=0.1, parameters=[lin.weight, lin.bias])
        sgd.minimize(loss)
    exe = static.Executor()
    exe.run(startup)
    rs = np.random.RandomState(0)
    true_w = rs.randn(4, 1).astype("float32")
    losses = []
    for i in range(30):
        xb = rs.randn(16, 4).astype("float32")
        yb = xb @ true_w
        (lv,) = exe.run(main, feed={"x": xb, "label": yb},
                        fetch_list=[loss])
        losses.append(float(lv))
    assert losses[-1] < losses[0] * 0.1, losses[:3] + losses[-3:]


def test_program_clone_for_test():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 2], "float32")
        y = x * 2.0
        sgd = opt.SGD(learning_rate=0.1, parameters=[])
    test_prog = main.clone(for_test=True)
    assert test_prog._train is None
    exe = static.Executor()
    (out,) = exe.run(test_prog, feed={"x": np.ones((1, 2), "float32")},
                     fetch_list=[y])
    np.testing.assert_allclose(out, np.full((1, 2), 2.0))


def test_save_load_roundtrip(tmp_path):
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 4], "float32")
        lin = nn.Linear(4, 2)
        y = lin(x)
    path = str(tmp_path / "model")
    static.save(main, path)
    old_w = np.asarray(lin.weight.numpy()).copy()
    lin.weight.set_value(np.zeros_like(old_w))
    static.load(main, path)
    np.testing.assert_allclose(np.asarray(lin.weight.numpy()), old_w)


def test_default_programs_and_name_lookup():
    main = static.Program()
    with static.program_guard(main):
        assert static.default_main_program() is main
        x = static.data("img", [None, 3], "float32")
    v = main.var("img")
    assert v is not None


def test_input_spec():
    spec = static.InputSpec([None, 8], "float32", "x")
    assert spec.shape == (None, 8)
    t = paddle.to_tensor(np.zeros((2, 3), "float32"))
    s2 = static.InputSpec.from_tensor(t)
    assert s2.shape == (2, 3)


def test_feed_validation_errors():
    import pytest

    main = static.Program()
    with static.program_guard(main):
        x = static.data("image", [None, 2], "float32")
        y = x * 2.0
    exe = static.Executor()
    with pytest.raises(ValueError, match="not data"):
        exe.run(main, feed={"imgae": np.ones((1, 2), "float32")},
                fetch_list=[y])
    with pytest.raises(ValueError, match="not fed"):
        exe.run(main, feed={}, fetch_list=[y])


def test_grad_fetch_two_params_ordering():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 2], "float32")
        lin = nn.Linear(2, 1)  # creates weight then bias
        loss = (lin(x) ** 2).mean()
        # request grads in reversed registration order
        pairs = static.append_backward(loss, parameter_list=[lin.bias,
                                                             lin.weight])
    exe = static.Executor()
    feed_x = np.random.RandomState(0).randn(4, 2).astype("float32")
    gb, gw = exe.run(main, feed={"x": feed_x},
                     fetch_list=[pairs[0][1], pairs[1][1]])
    assert gb.shape == tuple(lin.bias.shape)
    assert gw.shape == tuple(lin.weight.shape)
    # analytic check: dL/db = mean(2*pred), dL/dW = mean(2*pred*x)
    w = np.asarray(lin.weight.numpy())
    b = np.asarray(lin.bias.numpy())
    pred = feed_x @ w + b
    np.testing.assert_allclose(gb, (2 * pred).mean(0), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(
        gw, (2 * pred[:, None, :] * feed_x[:, :, None]).mean(0),
        rtol=1e-4, atol=1e-5)


def test_gradients_wrt_input_var():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 3], "float32")
        loss = (x ** 2).sum()
        refs = static.gradients(loss, [x])
    exe = static.Executor()
    feed_x = np.array([[1.0, -2.0, 3.0]], dtype="float32")
    (gx,) = exe.run(main, feed={"x": feed_x}, fetch_list=refs)
    np.testing.assert_allclose(gx, 2 * feed_x, rtol=1e-6)


def test_static_nn_fc_num_flatten_dims():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 3, 4], "float32")
        y = static.nn.fc(x, 5, num_flatten_dims=2)
    exe = static.Executor()
    (out,) = exe.run(main, feed={"x": np.ones((2, 3, 4), "float32")},
                     fetch_list=[y])
    assert out.shape == (2, 3, 5)


def test_global_scope_after_guard_exit():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 4], "float32")
        lin = nn.Linear(4, 2)
        lin.weight.name = "scope_probe_w"
        y = lin(x)
    v = static.global_scope().find_var("scope_probe_w")
    assert v is not None
    assert v.get_tensor().shape == (4, 2)


def test_gradients_wrt_intermediate_var():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 3], "float32")
        h = x * 2.0
        loss = (h ** 2).sum()
        refs = static.gradients(loss, [h])
    exe = static.Executor()
    feed_x = np.array([[1.0, -1.0, 2.0]], dtype="float32")
    (gh,) = exe.run(main, feed={"x": feed_x}, fetch_list=refs)
    np.testing.assert_allclose(gh, 2 * (2 * feed_x), rtol=1e-6)  # dL/dh = 2h

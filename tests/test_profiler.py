"""Profiler tests (reference analogs: test_profiler.py, test_newprofiler.py)."""
import json
import os

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.profiler as profiler
from paddle_tpu.profiler import (
    Profiler, ProfilerState, ProfilerTarget, RecordEvent, export_chrome_tracing,
    make_scheduler,
)


def test_record_event_and_op_hook():
    net = nn.Linear(8, 8)
    x = paddle.to_tensor(np.ones((2, 8), "float32"))
    with Profiler(targets=[ProfilerTarget.CPU]) as prof:
        with RecordEvent("fwd"):
            y = net(x)
        (y ** 2).sum().backward()
    names = {e.name for e in prof.events}
    assert "fwd" in names
    assert any(n for n in names if n != "fwd")  # op-level events recorded


def test_chrome_trace_export(tmp_path):
    with Profiler() as prof:
        with RecordEvent("work"):
            paddle.to_tensor(np.ones(4, "float32")) * 2
    path = prof.export(str(tmp_path / "trace.json"))
    data = json.load(open(path))
    assert any(ev["name"] == "work" for ev in data["traceEvents"])
    assert all({"ph", "ts", "dur"} <= set(ev) for ev in data["traceEvents"])


def test_on_trace_ready_handler(tmp_path):
    handler = export_chrome_tracing(str(tmp_path / "profdir"))
    with Profiler(on_trace_ready=handler):
        with RecordEvent("e"):
            pass
    files = os.listdir(str(tmp_path / "profdir"))
    assert any(f.endswith(".pt.trace.json") for f in files)


def test_scheduler_states():
    sched = make_scheduler(closed=1, ready=1, record=2, repeat=1)
    states = [sched(i) for i in range(5)]
    assert states[0] == ProfilerState.CLOSED
    assert states[1] == ProfilerState.READY
    assert states[2] == ProfilerState.RECORD
    assert states[3] == ProfilerState.RECORD_AND_RETURN
    assert states[4] == ProfilerState.CLOSED


def test_tuple_scheduler_records_only_window():
    x = paddle.to_tensor(np.ones(4, "float32"))
    prof = Profiler(scheduler=(1, 3))
    prof.start()
    for step in range(4):
        x * 2  # one op per step
        prof.step()
    prof.stop()
    # step-0 op not recorded (state CLOSED at step 0), steps 1-2 recorded
    op_events = [e for e in prof.events if e.kind == "op"]
    assert len(op_events) == 2


def test_summary_table():
    with Profiler() as prof:
        with RecordEvent("alpha"):
            pass
    table = prof.summary()
    assert "alpha" in table
    assert "Calls" in table


def test_scheduler_skip_first():
    sched = make_scheduler(closed=0, ready=0, record=2, skip_first=3)
    assert [sched(i) for i in range(3)] == [ProfilerState.CLOSED] * 3
    assert sched(3) == ProfilerState.RECORD
    assert sched(4) == ProfilerState.RECORD_AND_RETURN
    assert sched(5) == ProfilerState.RECORD  # repeat=0: cycles forever


def test_scheduler_repeat_exhaustion():
    sched = make_scheduler(closed=1, ready=0, record=1, repeat=2)
    # two full cycles of (CLOSED, RECORD_AND_RETURN), then CLOSED forever
    assert [sched(i) for i in range(6)] == [
        ProfilerState.CLOSED, ProfilerState.RECORD_AND_RETURN,
        ProfilerState.CLOSED, ProfilerState.RECORD_AND_RETURN,
        ProfilerState.CLOSED, ProfilerState.CLOSED,
    ]


def test_scheduler_closed_ready_record_cycle():
    sched = make_scheduler(closed=2, ready=1, record=3, repeat=1,
                           skip_first=1)
    states = [sched(i) for i in range(8)]
    assert states == [
        ProfilerState.CLOSED,                 # skip_first
        ProfilerState.CLOSED, ProfilerState.CLOSED,   # closed=2
        ProfilerState.READY,                  # ready=1
        ProfilerState.RECORD, ProfilerState.RECORD,   # record
        ProfilerState.RECORD_AND_RETURN,      # last record slot
        ProfilerState.CLOSED,                 # repeat exhausted
    ]


def test_tuple_scheduler_yields_record_and_return():
    """ISSUE 3 satellite: the (start, end) tuple scheduler goes through
    make_scheduler (no dead-code lambda) and ends the window on
    RECORD_AND_RETURN so per-cycle export fires."""
    prof = Profiler(scheduler=(1, 3))
    states = [prof.scheduler(i) for i in range(4)]
    assert states == [
        ProfilerState.CLOSED, ProfilerState.RECORD,
        ProfilerState.RECORD_AND_RETURN, ProfilerState.CLOSED,
    ]


def test_step_fires_on_trace_ready_per_cycle(tmp_path):
    """ISSUE 3 satellite: when a record cycle ends (RECORD_AND_RETURN),
    on_trace_ready fires with that cycle's events, which are then cleared
    — per-cycle export, not only at stop()."""
    exports = []

    def handler(prof):
        exports.append([e.name for e in prof.events])

    x = paddle.to_tensor(np.ones(4, "float32"))
    prof = Profiler(scheduler=make_scheduler(closed=1, ready=0, record=1,
                                             repeat=2),
                    on_trace_ready=handler)
    prof.start()
    for step in range(4):
        with RecordEvent(f"user_{step}"):
            x * 2
        prof.step()
    prof.stop()
    # cycles end after steps 1 and 3; each export carries only ITS events
    assert len(exports) == 2
    assert any("user_1" == n for n in exports[0])
    assert not any("user_3" == n for n in exports[0])
    assert any("user_3" == n for n in exports[1])
    assert not any("user_1" == n for n in exports[1])


def test_export_chrome_tracing_per_cycle_files(tmp_path):
    handler = export_chrome_tracing(str(tmp_path))
    x = paddle.to_tensor(np.ones(4, "float32"))
    with Profiler(scheduler=(0, 1), on_trace_ready=handler) as prof:
        x * 2
        prof.step()
    files = sorted(os.listdir(tmp_path))
    assert len(files) == 1                 # cycle export; nothing new at stop
    assert files[0].endswith(".pt.trace.json")


def test_nested_profiler_restores_hook_and_active():
    """ISSUE 3 satellite: a nested Profiler start/stop must hand RecordEvent
    collection and the op hook back to the OUTER profiler, not to None."""
    from paddle_tpu.framework import autograd

    x = paddle.to_tensor(np.ones(4, "float32"))
    outer = Profiler().start()
    with RecordEvent("outer_before"):
        x * 2
    inner = Profiler().start()
    with RecordEvent("inner_only"):
        x * 2
    inner.stop()
    with RecordEvent("outer_after"):
        x * 2
    # outer's op hook is live again after inner.stop()
    assert autograd._op_profiler == outer._op_hook
    outer.stop()
    assert autograd._op_profiler is None
    outer_names = {e.name for e in outer.events}
    assert {"outer_before", "outer_after"} <= outer_names
    assert "inner_only" not in outer_names
    assert "inner_only" in {e.name for e in inner.events}


def test_span_tree_nesting():
    with Profiler() as prof:
        with RecordEvent("step"):
            with RecordEvent("forward"):
                with RecordEvent("attn"):
                    pass
            with RecordEvent("backward"):
                pass
        with RecordEvent("solo"):
            pass
    roots = prof.span_tree()
    by_name = {r["event"].name: r for r in roots}
    assert set(by_name) == {"step", "solo"}
    step = by_name["step"]
    kids = [c["event"].name for c in step["children"]]
    assert kids == ["forward", "backward"]
    fwd = step["children"][0]
    assert [c["event"].name for c in fwd["children"]] == ["attn"]
    # chrome export carries the linkage in args
    import json as _json
    import tempfile

    with tempfile.NamedTemporaryFile("r", suffix=".json") as f:
        prof.export(f.name)
        data = _json.load(open(f.name))
    ev = {e["name"]: e for e in data["traceEvents"]}
    assert ev["attn"]["args"]["parent_id"] == ev["forward"]["args"]["id"]
    assert ev["forward"]["args"]["parent_id"] == ev["step"]["args"]["id"]
    assert ev["step"]["args"]["parent_id"] is None


def test_op_events_parent_under_enclosing_span():
    x = paddle.to_tensor(np.ones(4, "float32"))
    with Profiler() as prof:
        with RecordEvent("fwd"):
            x * 2
    ops = [e for e in prof.events if e.kind == "op"]
    fwd = next(e for e in prof.events if e.name == "fwd")
    assert ops and all(o.parent_id == fwd.id for o in ops)


def test_nan_inf_flag_roundtrip():
    import jax

    paddle.set_flags({"FLAGS_check_nan_inf": True})
    assert jax.config.jax_debug_nans
    paddle.set_flags({"FLAGS_check_nan_inf": False})
    flags = paddle.get_flags(["FLAGS_check_nan_inf"])
    assert flags["FLAGS_check_nan_inf"] is False
    jax.config.update("jax_debug_nans", False)

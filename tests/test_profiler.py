"""Profiler tests (reference analogs: test_profiler.py, test_newprofiler.py)."""
import json
import os

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.profiler as profiler
from paddle_tpu.profiler import (
    Profiler, ProfilerState, ProfilerTarget, RecordEvent, export_chrome_tracing,
    make_scheduler,
)


def test_record_event_and_op_hook():
    net = nn.Linear(8, 8)
    x = paddle.to_tensor(np.ones((2, 8), "float32"))
    with Profiler(targets=[ProfilerTarget.CPU]) as prof:
        with RecordEvent("fwd"):
            y = net(x)
        (y ** 2).sum().backward()
    names = {e.name for e in prof.events}
    assert "fwd" in names
    assert any(n for n in names if n != "fwd")  # op-level events recorded


def test_chrome_trace_export(tmp_path):
    with Profiler() as prof:
        with RecordEvent("work"):
            paddle.to_tensor(np.ones(4, "float32")) * 2
    path = prof.export(str(tmp_path / "trace.json"))
    data = json.load(open(path))
    assert any(ev["name"] == "work" for ev in data["traceEvents"])
    assert all({"ph", "ts", "dur"} <= set(ev) for ev in data["traceEvents"])


def test_on_trace_ready_handler(tmp_path):
    handler = export_chrome_tracing(str(tmp_path / "profdir"))
    with Profiler(on_trace_ready=handler):
        with RecordEvent("e"):
            pass
    files = os.listdir(str(tmp_path / "profdir"))
    assert any(f.endswith(".pt.trace.json") for f in files)


def test_scheduler_states():
    sched = make_scheduler(closed=1, ready=1, record=2, repeat=1)
    states = [sched(i) for i in range(5)]
    assert states[0] == ProfilerState.CLOSED
    assert states[1] == ProfilerState.READY
    assert states[2] == ProfilerState.RECORD
    assert states[3] == ProfilerState.RECORD_AND_RETURN
    assert states[4] == ProfilerState.CLOSED


def test_tuple_scheduler_records_only_window():
    x = paddle.to_tensor(np.ones(4, "float32"))
    prof = Profiler(scheduler=(1, 3))
    prof.start()
    for step in range(4):
        x * 2  # one op per step
        prof.step()
    prof.stop()
    # step-0 op not recorded (state CLOSED at step 0), steps 1-2 recorded
    op_events = [e for e in prof.events if e.kind == "op"]
    assert len(op_events) == 2


def test_summary_table():
    with Profiler() as prof:
        with RecordEvent("alpha"):
            pass
    table = prof.summary()
    assert "alpha" in table
    assert "Calls" in table


def test_nan_inf_flag_roundtrip():
    import jax

    paddle.set_flags({"FLAGS_check_nan_inf": True})
    assert jax.config.jax_debug_nans
    paddle.set_flags({"FLAGS_check_nan_inf": False})
    flags = paddle.get_flags(["FLAGS_check_nan_inf"])
    assert flags["FLAGS_check_nan_inf"] is False
    jax.config.update("jax_debug_nans", False)

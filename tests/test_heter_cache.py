"""Capacity-bounded HeterCache: eviction + batched faults + coalesced
write-back (VERDICT r4 #4).

Reference: paddle/fluid/framework/fleet/heter_ps/heter_comm.h (per-device
cache with merged pulls/pushes) and ps_gpu_wrapper.cc. The e2e test runs
TWO worker threads sharing ONE PS server through one cache, asserting
cache-hit-rate, fault batching, and value parity against direct PS math.
"""
import threading

import numpy as np
import pytest

from paddle_tpu.distributed.ps import PsClient, PsServer
from paddle_tpu.distributed.ps.heter_cache import HeterCache

DIM = 4


@pytest.fixture
def ps():
    server = PsServer().start()
    client = PsClient([server.endpoint])
    client.create_table(0, dim=DIM, optimizer="sgd", lr=1.0, init_range=0.0)
    yield client
    client.close()
    server.stop()


def test_lru_eviction_bounds_device_rows_and_writes_back(ps):
    cache = HeterCache(ps, 0, dim=DIM, capacity=4, lr=1.0,
                       fault_window_s=0.0, flush_rows=2)
    # fill capacity
    cache.lookup(np.arange(4))
    assert cache.live_rows == 4
    cache.push_grads([0, 1], np.ones((2, DIM), np.float32))
    # touch 1,2,3 so key 0 is LRU — faulting key 9 must evict 0
    cache.lookup([1, 2, 3])
    cache.lookup([9])
    assert cache.live_rows == 4
    assert 0 not in cache._slot_of and 9 in cache._slot_of
    assert cache.evictions == 1
    # key 0 was dirty: its grad sits in the coalesce buffer (below
    # flush_rows) — the PS hasn't been pushed yet
    assert cache.writeback_pushes == 0
    # a second dirty eviction reaches flush_rows=2 -> ONE batched push
    cache.push_grads([1], np.ones((1, DIM), np.float32))
    cache.lookup([2, 3, 9])
    cache.lookup([10])   # evicts key 1 (dirty) -> buffer hits 2 -> flush
    assert cache.writeback_pushes == 1
    # sgd lr=1.0, init 0: pushed grad 1.0 => value -1.0 on the server
    np.testing.assert_allclose(ps.pull(0, np.asarray([0], np.uint64)),
                               -1.0)


def test_lfu_policy_keeps_hot_rows(ps):
    cache = HeterCache(ps, 0, dim=DIM, capacity=2, policy="lfu",
                       fault_window_s=0.0)
    cache.lookup([0])
    cache.lookup([0])
    cache.lookup([0])   # key 0: count 3
    cache.lookup([1])   # key 1: count 1
    cache.lookup([5])   # evicts the LEAST FREQUENT (key 1), not LRU(0)
    assert 0 in cache._slot_of and 1 not in cache._slot_of


def test_flush_pushes_all_dirty_rows_once(ps):
    cache = HeterCache(ps, 0, dim=DIM, capacity=8, lr=1.0,
                       fault_window_s=0.0)
    cache.lookup(np.arange(6))
    cache.push_grads(np.arange(6), np.full((6, DIM), 2.0, np.float32))
    cache.flush()
    assert cache.writeback_pushes == 1  # ONE rpc for all six rows
    np.testing.assert_allclose(
        ps.pull(0, np.arange(6, dtype=np.uint64)), -2.0)
    # flush is idempotent: accumulators were cleared
    cache.flush()
    assert cache.writeback_pushes == 1


def test_concurrent_fault_aggregation_single_pull(ps):
    """Two workers faulting simultaneously on disjoint id sets produce
    ONE merged pull rpc (the heter_comm batched fault), not two."""
    cache = HeterCache(ps, 0, dim=DIM, capacity=64, fault_window_s=0.25)
    start = threading.Barrier(2)
    outs = {}

    def worker(wid, ids):
        start.wait()
        outs[wid] = np.asarray(cache.lookup(ids))

    ts = [threading.Thread(target=worker, args=(i, np.arange(i * 8, i * 8 + 8)))
          for i in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30)
    assert set(outs) == {0, 1}
    assert cache.fault_pulls == 1, cache.fault_pulls
    assert cache.live_rows == 16


def test_two_workers_one_server_hit_rate_and_parity(ps):
    """e2e: two heter workers train embedding rows through one shared
    cache against one PS server; the cache must (a) serve repeat lookups
    from device (high hit rate), (b) keep PS values in parity with the
    direct no-cache math."""
    cache = HeterCache(ps, 0, dim=DIM, capacity=32, lr=1.0,
                       fault_window_s=0.0)
    n_steps, n_ids = 20, 8

    def worker(wid):
        ids = np.arange(wid * n_ids, (wid + 1) * n_ids)  # disjoint per worker
        for _ in range(n_steps):
            vals = np.asarray(cache.lookup(ids))
            assert vals.shape == (n_ids, DIM)
            cache.push_grads(ids, np.full((n_ids, DIM), 0.1, np.float32))

    ts = [threading.Thread(target=worker, args=(i,)) for i in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=60)
    cache.flush()
    # hit rate: each worker faults its 8 ids once, then hits 19*8 times
    assert cache.hit_rate() > 0.9, cache.hit_rate()
    # parity: total grad per id = 20 * 0.1 = 2.0; sgd lr=1.0 from 0 init
    got = ps.pull(0, np.arange(2 * n_ids, dtype=np.uint64))
    np.testing.assert_allclose(got, -2.0, rtol=1e-5)


def test_cached_lookup_sees_accumulated_grads_only_after_writeback(ps):
    """Write-back semantics: in-cache values are the PULLED snapshot;
    the PS applies the merged update at flush (downpour per-pass step)."""
    cache = HeterCache(ps, 0, dim=DIM, capacity=4, lr=1.0,
                       fault_window_s=0.0)
    v0 = np.asarray(cache.lookup([3]))
    cache.push_grads([3], np.ones((1, DIM), np.float32))
    np.testing.assert_allclose(np.asarray(cache.lookup([3])), v0)
    cache.flush()
    np.testing.assert_allclose(
        ps.pull(0, np.asarray([3], np.uint64)), v0 - 1.0)


def test_heter_embedding_autograd_over_heter_cache(ps):
    """heter_embedding composes with the capacity-bounded tier: forward
    gathers, backward accumulates into the cache, flush hits the PS."""
    import paddle_tpu as paddle
    from paddle_tpu.distributed.ps.heter_trainer import heter_embedding

    cache = HeterCache(ps, 0, dim=DIM, capacity=8, lr=1.0,
                       fault_window_s=0.0)
    ids = paddle.to_tensor(np.asarray([1, 2, 1], np.int64))
    emb = heter_embedding(cache, ids)
    assert emb.shape == [3, DIM]
    emb.sum().backward()
    cache.flush()
    # id 1 appears twice: grad 2.0; id 2 once: grad 1.0 (sgd lr=1 from 0)
    got = ps.pull(0, np.asarray([1, 2], np.uint64))
    np.testing.assert_allclose(got[0], -2.0)
    np.testing.assert_allclose(got[1], -1.0)


def test_push_grads_survives_concurrent_eviction(ps):
    """An eviction between a worker's forward and backward must not crash
    the step: the grad routes through the write-back buffer instead."""
    cache = HeterCache(ps, 0, dim=DIM, capacity=2, lr=1.0,
                       fault_window_s=0.0)
    cache.lookup([7])
    cache.lookup([8, 9])   # capacity 2: evicts 7
    assert 7 not in cache._slot_of
    cache.push_grads([7], np.ones((1, DIM), np.float32))  # no KeyError
    cache.flush()
    np.testing.assert_allclose(ps.pull(0, np.asarray([7], np.uint64)),
                               -1.0)


def test_lookup_wider_than_capacity_raises(ps):
    cache = HeterCache(ps, 0, dim=DIM, capacity=4, fault_window_s=0.0)
    with pytest.raises(ValueError, match="capacity"):
        cache.lookup(np.arange(5))


def test_install_batch_does_not_evict_itself(ps):
    """A multi-key fault into a full cache must not thrash its own batch
    (install-time stamps): both new keys survive."""
    cache = HeterCache(ps, 0, dim=DIM, capacity=2, fault_window_s=0.0)
    cache.lookup([0, 1])
    out = np.asarray(cache.lookup([5, 6]))   # one fault, both installed
    assert out.shape == (2, DIM)
    assert 5 in cache._slot_of and 6 in cache._slot_of
    assert cache.fault_pulls == 2


def test_hit_rate_counts_cold_ids_as_misses_only(ps):
    cache = HeterCache(ps, 0, dim=DIM, capacity=8, fault_window_s=0.0)
    cache.lookup(np.arange(4))     # 4 cold misses (not also hits)
    assert (cache.hits, cache.misses) == (0, 4)
    cache.lookup(np.arange(4))
    assert (cache.hits, cache.misses) == (4, 4)


def test_oversized_concurrent_union_degrades_to_sequential_service(ps):
    """When the UNION of concurrent workers' misses exceeds capacity but
    each worker's own set fits, the fault leader clamps its batch (own
    ids first) and the rest serve in later rounds — both workers
    complete; nobody errors or livelocks."""
    cache = HeterCache(ps, 0, dim=DIM, capacity=4, fault_window_s=0.3)
    start = threading.Barrier(2)
    outs, errs = {}, {}

    def worker(wid, ids):
        start.wait()
        try:
            outs[wid] = np.asarray(cache.lookup(ids))
        except Exception as e:
            errs[wid] = e

    ts = [threading.Thread(target=worker,
                           args=(i, np.arange(i * 4, i * 4 + 4)))
          for i in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30)
    assert not any(t.is_alive() for t in ts), "livelocked"
    assert not errs, errs
    assert all(outs[w].shape == (4, DIM) for w in outs)
    # and a fresh small lookup still works
    assert np.asarray(cache.lookup([100])).shape == (1, DIM)


def test_compiled_pass_step_trains_and_syncs(ps):
    """CompiledPassStep (PSGPUTrainer hot loop, one XLA program per
    step): loss decreases, ONE pull + ONE sync per pass, device adagrad
    values land on the PS via end_pass(assign=True), and the padded slab
    keeps the compiled program shape-stable across passes."""
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu.distributed.ps.heter_cache import DevicePassCache
    from paddle_tpu.distributed.ps.heter_trainer import CompiledPassStep

    rs = np.random.RandomState(0)
    slots_n, vocab = 4, 64
    ps.create_table(5, dim=DIM, init_range=0.01, lr=0.1,
                    optimizer="adagrad")
    cache = DevicePassCache(ps, 5, lr=0.1)
    deep = paddle.nn.Sequential(
        paddle.nn.Linear(DIM * slots_n, 16), paddle.nn.ReLU(),
        paddle.nn.Linear(16, 1))
    optim = paddle.optimizer.Adam(learning_rate=5e-3,
                                  parameters=deep.parameters())
    step = CompiledPassStep(
        cache, deep, optim,
        lambda out, labels: F.binary_cross_entropy_with_logits(
            out[:, 0], labels),
        table_optimizer="adagrad", table_lr=0.1)

    true_w = rs.randn(vocab)

    def batch(n=64):
        ids = rs.randint(0, vocab, (n, slots_n))
        return ids, (true_w[ids].sum(1) > 0).astype("float32")

    losses = []
    first_exec = None
    for p_i in range(6):
        bs = [batch() for _ in range(4)]
        cache.begin_pass(np.concatenate([b[0].reshape(-1) for b in bs]),
                         pad_to=vocab)
        for b in bs:
            losses.append(float(step(cache, b).numpy()))
        cache.end_pass(assign=True)
        if first_exec is None:
            first_exec = step._jit  # same jitted callable reused below
    assert step._jit is first_exec
    assert losses[-1] < losses[0], (losses[0], losses[-1])
    assert cache.pulls == 6 and cache.pushes == 6  # one rpc pair per pass
    # the trained values really landed on the PS
    vals = ps.pull(5, np.arange(vocab, dtype=np.uint64),
                   create_if_missing=False)
    assert np.abs(vals).max() > 0.05  # moved far from init_range=0.01


def test_four_workers_contend_for_small_cache(ps):
    """4 workers, capacity for only half the combined working set:
    eviction + refault churn must stay correct (no lost grads, no
    crashes), with write-back preserving every update."""
    cache = HeterCache(ps, 0, dim=DIM, capacity=16, lr=1.0,
                       fault_window_s=0.01, flush_rows=8)
    n_steps, n_ids = 12, 8
    errors = []

    def worker(wid):
        try:
            ids = np.arange(wid * n_ids, (wid + 1) * n_ids)
            for _ in range(n_steps):
                vals = np.asarray(cache.lookup(ids))
                assert vals.shape == (n_ids, DIM)
                cache.push_grads(ids, np.full((n_ids, DIM), 0.25,
                                              np.float32))
        except Exception as e:  # surface to the main thread
            errors.append(e)

    ts = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=120)
    assert not errors, errors
    assert not any(t.is_alive() for t in ts), "worker hung"
    cache.flush()
    assert cache.live_rows <= 16
    # every id's total grad = 12 * 0.25 = 3.0 (sgd lr=1 from 0 init)
    got = ps.pull(0, np.arange(4 * n_ids, dtype=np.uint64))
    np.testing.assert_allclose(got, -3.0, rtol=1e-5)
    assert cache.evictions > 0  # the pressure was real


# --------------------------------------------------------------------------
# ISSUE 20 satellites: duplicate-id SUM regression + eviction under skew
# --------------------------------------------------------------------------

def test_device_pass_cache_duplicate_ids_accumulate_sum(ps):
    """Regression: push_grads with the SAME id repeated in one call must
    scatter-ADD every contribution (a plain index_update would silently
    keep only the last row — downpour merge semantics say SUM)."""
    from paddle_tpu.distributed.ps.heter_cache import DevicePassCache

    cache = DevicePassCache(ps, 0, lr=1.0)
    cache.begin_pass(np.asarray([1, 2], np.uint64))
    g = np.asarray([[1.0] * DIM, [2.0] * DIM, [4.0] * DIM], np.float32)
    cache.push_grads(np.asarray([1, 1, 2]), g)   # id 1 twice
    acc = np.asarray(cache._gacc)
    np.testing.assert_allclose(acc[cache._slot_of[1]], 3.0)  # 1+2, not 2
    np.testing.assert_allclose(acc[cache._slot_of[2]], 4.0)
    cache.end_pass()
    got = ps.pull(0, np.asarray([1, 2], np.uint64))
    np.testing.assert_allclose(got[0], -3.0)   # sgd lr=1 from 0 init
    np.testing.assert_allclose(got[1], -4.0)


def test_heter_cache_duplicate_ids_accumulate_sum(ps):
    """Same regression for the capacity-bounded cache: duplicates within
    one push_grads call (and across calls) SUM into the accumulator."""
    cache = HeterCache(ps, 0, dim=DIM, capacity=4, lr=1.0,
                       fault_window_s=0.0)
    cache.lookup([1, 2])
    g = np.asarray([[1.0] * DIM, [2.0] * DIM, [4.0] * DIM], np.float32)
    cache.push_grads(np.asarray([1, 1, 2]), g)   # id 1 twice in ONE call
    cache.push_grads(np.asarray([1]), np.full((1, DIM), 8.0, np.float32))
    cache.flush()
    got = ps.pull(0, np.asarray([1, 2], np.uint64))
    np.testing.assert_allclose(got[0], -(1.0 + 2.0 + 8.0))
    np.testing.assert_allclose(got[1], -4.0)


def test_eviction_buffers_dirty_rows_before_slot_reuse(ps):
    """Skewed-traffic eviction ordering: when a dirty row is forced out,
    its accumulated grad must land in the write-back buffer BEFORE the
    slot is handed to the incoming key — and survive to the PS at flush.
    flush_rows is large so the buffer is inspectable mid-flight."""
    cache = HeterCache(ps, 0, dim=DIM, capacity=2, lr=1.0,
                       fault_window_s=0.0, flush_rows=64)
    cache.lookup([10, 11])
    cache.push_grads([10], np.full((1, DIM), 2.5, np.float32))  # 10 dirty
    cache.lookup([11])            # touch 11 -> 10 is the LRU victim
    cache.lookup([12])            # evicts dirty 10, installs 12
    assert 10 not in cache._slot_of and 12 in cache._slot_of
    # the grad is sitting in the coalesce buffer, not lost with the slot
    assert 10 in cache._wb_keys
    i = cache._wb_keys.index(10)
    np.testing.assert_allclose(cache._wb_grads[i], 2.5)
    # ... and the reused slot's accumulator was zeroed for the new tenant
    np.testing.assert_allclose(
        np.asarray(cache._gacc)[cache._slot_of[12]], 0.0)
    cache.flush()
    np.testing.assert_allclose(ps.pull(0, np.asarray([10], np.uint64)),
                               -2.5)


def test_capacity_exceeding_pass_matches_uncached_reference_bitwise(ps):
    """A pass whose working set is 3x the cache capacity (heavy eviction
    + refault churn) must leave the PS bit-identical to the same grads
    pushed straight through the client: no update lost, duplicated, or
    rounded differently. Grads are dyadic rationals so summation order
    cannot introduce float drift — any mismatch is a real lost/extra
    update."""
    ps.create_table(7, dim=DIM, optimizer="sgd", lr=1.0, init_range=0.0)
    ps.create_table(8, dim=DIM, optimizer="sgd", lr=1.0, init_range=0.0)
    cache = HeterCache(ps, 7, dim=DIM, capacity=8, lr=1.0,
                       fault_window_s=0.0, flush_rows=4)
    rs = np.random.RandomState(0)
    vocab = 24                      # 3x capacity
    for _ in range(10):
        ids = rs.randint(0, vocab, 6).astype(np.uint64)
        # dyadic grads: k/8 with k in [-16, 16) — exact in f32 sums
        g = (rs.randint(-16, 16, (6, DIM)) / 8.0).astype(np.float32)
        cache.lookup(ids)
        cache.push_grads(ids, g)
        ref_ids, ref_g = ids.copy(), g.copy()
        # uncached reference: merge duplicates host-side, push directly
        uniq, inv = np.unique(ref_ids, return_inverse=True)
        merged = np.zeros((uniq.size, DIM), np.float32)
        np.add.at(merged, inv, ref_g)
        ps.push(8, uniq, merged, lr=1.0)
    cache.flush()
    assert cache.evictions > 0, "pressure was supposed to be real"
    all_ids = np.arange(vocab, dtype=np.uint64)
    np.testing.assert_array_equal(ps.pull(7, all_ids), ps.pull(8, all_ids))

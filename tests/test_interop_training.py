"""Imported TRAINING programs: backward + optimizer ops, resume, jit.

VERDICT r3 next #4b/#4c. Reference io.py loads train programs too —
append_backward's *_grad ops plus optimizer ops (fill_constant + sgd /
adam tails) — and the executor's scope keeps mutated persistables across
runs, so training RESUMES. This file authors such programs in the
certified wire format (tests/test_interop_golden.py proves the encoders
byte-match real protobuf) and checks:

  - a linear-regression train program (mul/add/sub/square/mean forward,
    full *_grad chain, sgd updates) trains: loss drops across run() calls
  - grads match jax.grad of the same forward (oracle)
  - adam state (moments, beta pows) rides the persistable blob: stopping
    after 2 steps, saving, reloading and running 1 more step is
    bit-identical to 3 straight steps
  - imported while / scalar conditional_block now lower to
    lax.while_loop / lax.cond under jit (as_fn), matching eager run()
"""
import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.interop import load_paddle_inference_model
from paddle_tpu.interop.serializer import save_paddle_inference_model

from test_interop_importer import (
    A_BOOL, A_FLOAT, A_INT, A_INTS, BOOL, FEED_MINIBATCH, FETCH_LIST, FP32,
    attr, attr_block, block_desc, lod_tensor_stream, op_desc, program_desc,
    var_desc,
)


def _v(name, dims=(), persistable=False, dtype=FP32):
    return var_desc(name, dtype=dtype, dims=dims, persistable=persistable)


def _train_program_ops(optimizer="sgd"):
    """feed x[-1,4], y[-1,1]; pred = x@w + b; loss = mean((pred-y)^2);
    full backward chain; sgd (or adam for w) updates. The op layout
    mirrors what append_backward + optimizer.minimize emit."""
    ops = [
        op_desc("feed", [("X", ["feed"])], [("Out", ["x"])],
                [attr("col", A_INT, 0)]),
        op_desc("feed", [("X", ["feed"])], [("Out", ["yt"])],
                [attr("col", A_INT, 1)]),
        op_desc("mul", [("X", ["x"]), ("Y", ["w"])], [("Out", ["xw"])],
                [attr("x_num_col_dims", A_INT, 1),
                 attr("y_num_col_dims", A_INT, 1)]),
        op_desc("elementwise_add", [("X", ["xw"]), ("Y", ["b"])],
                [("Out", ["pred"])], [attr("axis", A_INT, -1)]),
        op_desc("elementwise_sub", [("X", ["pred"]), ("Y", ["yt"])],
                [("Out", ["diff"])], [attr("axis", A_INT, -1)]),
        op_desc("square", [("X", ["diff"])], [("Out", ["sq"])]),
        op_desc("mean", [("X", ["sq"])], [("Out", ["loss"])]),
        # ---- append_backward tail ----
        op_desc("fill_constant", [], [("Out", ["loss@GRAD"])],
                [attr("shape", A_INTS, [1]), attr("value", A_FLOAT, 1.0),
                 attr("dtype", A_INT, FP32)]),
        op_desc("mean_grad",
                [("X", ["sq"]), ("Out@GRAD", ["loss@GRAD"])],
                [("X@GRAD", ["sq@GRAD"])]),
        op_desc("square_grad",
                [("X", ["diff"]), ("Out@GRAD", ["sq@GRAD"])],
                [("X@GRAD", ["diff@GRAD"])]),
        op_desc("elementwise_sub_grad",
                [("X", ["pred"]), ("Y", ["yt"]),
                 ("Out@GRAD", ["diff@GRAD"])],
                [("X@GRAD", ["pred@GRAD"])], [attr("axis", A_INT, -1)]),
        op_desc("elementwise_add_grad",
                [("X", ["xw"]), ("Y", ["b"]), ("Out@GRAD", ["pred@GRAD"])],
                [("X@GRAD", ["xw@GRAD"]), ("Y@GRAD", ["b@GRAD"])],
                [attr("axis", A_INT, -1)]),
        op_desc("mul_grad",
                [("X", ["x"]), ("Y", ["w"]), ("Out@GRAD", ["xw@GRAD"])],
                [("Y@GRAD", ["w@GRAD"])],
                [attr("x_num_col_dims", A_INT, 1),
                 attr("y_num_col_dims", A_INT, 1)]),
    ]
    if optimizer == "sgd":
        ops.append(op_desc(
            "sgd",
            [("Param", ["w"]), ("Grad", ["w@GRAD"]),
             ("LearningRate", ["learning_rate"])],
            [("ParamOut", ["w"])]))
    else:
        ops.append(op_desc(
            "adam",
            [("Param", ["w"]), ("Grad", ["w@GRAD"]),
             ("Moment1", ["m1"]), ("Moment2", ["m2"]),
             ("Beta1Pow", ["b1pow"]), ("Beta2Pow", ["b2pow"]),
             ("LearningRate", ["learning_rate"])],
            [("ParamOut", ["w"]), ("Moment1Out", ["m1"]),
             ("Moment2Out", ["m2"]), ("Beta1PowOut", ["b1pow"]),
             ("Beta2PowOut", ["b2pow"])],
            [attr("beta1", A_FLOAT, 0.9), attr("beta2", A_FLOAT, 0.999),
             attr("epsilon", A_FLOAT, 1e-8)]))
    ops.append(op_desc(
        "sgd",
        [("Param", ["b"]), ("Grad", ["b@GRAD"]),
         ("LearningRate", ["learning_rate"])],
        [("ParamOut", ["b"])]))
    ops.append(op_desc("fetch", [("X", ["loss"])], [("Out", ["fetch"])],
                       [attr("col", A_INT, 0)]))
    return ops


def _write_train_artifact(d, optimizer, w, b, lr, adam_state=None):
    vars_ = [
        var_desc("feed", type_id=FEED_MINIBATCH, persistable=True),
        var_desc("fetch", type_id=FETCH_LIST, persistable=True),
        _v("x", (-1, 4)), _v("yt", (-1, 1)),
        _v("w", (4, 1), persistable=True),
        _v("b", (1,), persistable=True),
        _v("learning_rate", (1,), persistable=True),
        _v("xw", (-1, 1)), _v("pred", (-1, 1)), _v("diff", (-1, 1)),
        _v("sq", (-1, 1)), _v("loss", (1,)),
        _v("loss@GRAD", (1,)), _v("sq@GRAD", (-1, 1)),
        _v("diff@GRAD", (-1, 1)), _v("pred@GRAD", (-1, 1)),
        _v("xw@GRAD", (-1, 1)), _v("b@GRAD", (1,)), _v("w@GRAD", (4, 1)),
    ]
    params = {"w": w, "b": b, "learning_rate": lr}
    if optimizer == "adam":
        vars_ += [_v("m1", (4, 1), persistable=True),
                  _v("m2", (4, 1), persistable=True),
                  _v("b1pow", (1,), persistable=True),
                  _v("b2pow", (1,), persistable=True)]
        params.update(adam_state)
    (d / "__model__").write_bytes(program_desc([
        block_desc(0, vars_, _train_program_ops(optimizer))]))
    with open(d / "__params__", "wb") as f:
        for name in sorted(params):
            f.write(lod_tensor_stream(params[name]))


def _data(rs, n=16):
    x = rs.randn(n, 4).astype(np.float32)
    w_true = np.asarray([[1.0], [-2.0], [0.5], [3.0]], np.float32)
    y = x @ w_true + 0.25
    return x, y


def test_training_program_trains_and_matches_jax_grad(tmp_path):
    rs = np.random.RandomState(0)
    w0 = (rs.randn(4, 1) * 0.1).astype(np.float32)
    b0 = np.zeros(1, np.float32)
    lr = np.asarray([0.1], np.float32)
    _write_train_artifact(tmp_path, "sgd", w0, b0, lr)
    prog = load_paddle_inference_model(str(tmp_path),
                                       params_filename="__params__")

    x, y = _data(rs)
    losses = [float(prog.run({"x": x, "yt": y})[0]) for _ in range(20)]
    assert losses[-1] < 0.05 * losses[0], losses

    # one-step oracle: same update via jax.grad
    def loss_fn(w, b):
        return jnp.mean((x @ w + b - y) ** 2)

    gw, gb = jax.grad(loss_fn, argnums=(0, 1))(jnp.asarray(w0),
                                               jnp.asarray(b0))
    _write_train_artifact(tmp_path, "sgd", w0, b0, lr)
    prog2 = load_paddle_inference_model(str(tmp_path),
                                        params_filename="__params__")
    prog2.run({"x": x, "yt": y})
    np.testing.assert_allclose(prog2.params["w"], w0 - 0.1 * np.asarray(gw),
                               rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(prog2.params["b"], b0 - 0.1 * np.asarray(gb),
                               rtol=1e-5, atol=1e-7)


def test_adam_training_resumes_exactly_from_saved_artifact(tmp_path):
    rs = np.random.RandomState(1)
    w0 = (rs.randn(4, 1) * 0.1).astype(np.float32)
    b0 = np.zeros(1, np.float32)
    lr = np.asarray([0.01], np.float32)
    adam0 = {"m1": np.zeros((4, 1), np.float32),
             "m2": np.zeros((4, 1), np.float32),
             "b1pow": np.asarray([0.9], np.float32),
             "b2pow": np.asarray([0.999], np.float32)}
    x, y = _data(rs)

    src = tmp_path / "src"
    src.mkdir()
    _write_train_artifact(src, "adam", w0, b0, lr, adam0)

    # A: three straight steps
    prog_a = load_paddle_inference_model(str(src),
                                         params_filename="__params__")
    la = [float(prog_a.run({"x": x, "yt": y})[0]) for _ in range(3)]

    # B: two steps, save EVERYTHING (incl. moments/pows), reload, one more
    prog_b = load_paddle_inference_model(str(src),
                                         params_filename="__params__")
    lb = [float(prog_b.run({"x": x, "yt": y})[0]) for _ in range(2)]
    ckpt = tmp_path / "ckpt"
    save_paddle_inference_model(prog_b, str(ckpt))
    prog_c = load_paddle_inference_model(str(ckpt),
                                         params_filename="__params__")
    lb.append(float(prog_c.run({"x": x, "yt": y})[0]))

    np.testing.assert_allclose(lb, la, rtol=1e-6)
    np.testing.assert_allclose(prog_c.params["w"], prog_a.params["w"],
                               rtol=1e-6)
    np.testing.assert_allclose(prog_c.params["m2"], prog_a.params["m2"],
                               rtol=1e-6)
    np.testing.assert_allclose(prog_c.params["b1pow"],
                               prog_a.params["b1pow"], rtol=1e-6)


def _while_artifact(d):
    vars_main = [
        var_desc("feed", type_id=FEED_MINIBATCH, persistable=True),
        var_desc("fetch", type_id=FETCH_LIST, persistable=True),
        _v("n"), _v("i"), _v("acc"),
        var_desc("cond", dtype=BOOL, dims=()),
    ]
    ops_main = [
        op_desc("feed", [("X", ["feed"])], [("Out", ["n"])],
                [attr("col", A_INT, 0)]),
        op_desc("fill_constant", [], [("Out", ["i"])],
                [attr("shape", A_INTS, []), attr("value", A_FLOAT, 0.0),
                 attr("dtype", A_INT, FP32)]),
        op_desc("fill_constant", [], [("Out", ["acc"])],
                [attr("shape", A_INTS, []), attr("value", A_FLOAT, 0.0),
                 attr("dtype", A_INT, FP32)]),
        op_desc("less_than", [("X", ["i"]), ("Y", ["n"])],
                [("Out", ["cond"])]),
        op_desc("while",
                [("X", ["i", "acc", "n"]), ("Condition", ["cond"])],
                [("Out", ["i", "acc"])], [attr_block("sub_block", 1)]),
        op_desc("fetch", [("X", ["acc"])], [("Out", ["fetch"])],
                [attr("col", A_INT, 0)]),
    ]
    ops_sub = [
        op_desc("increment", [("X", ["i"])], [("Out", ["i"])],
                [attr("step", A_FLOAT, 1.0)]),
        op_desc("elementwise_add", [("X", ["acc"]), ("Y", ["i"])],
                [("Out", ["acc"])], [attr("axis", A_INT, -1)]),
        op_desc("less_than", [("X", ["i"]), ("Y", ["n"])],
                [("Out", ["cond"])]),
    ]
    (d / "__model__").write_bytes(program_desc([
        block_desc(0, vars_main, ops_main),
        block_desc(1, [], ops_sub),
    ]))


def test_imported_while_jits_via_lax_while_loop(tmp_path):
    """VERDICT r3 missing #6: tensor-condition while now compiles — the
    same program, same trip-count-follows-data behavior, one XLA
    program (so the trip count is runtime-dynamic, not unrolled)."""
    _while_artifact(tmp_path)
    prog = load_paddle_inference_model(str(tmp_path))
    fn = jax.jit(lambda feed: prog.as_fn()(feed))
    for n, expect in [(3.0, 6.0), (7.0, 28.0), (0.0, 0.0)]:
        (acc,) = fn({"n": jnp.float32(n)})
        assert float(acc) == expect, (n, float(acc))
        # eager interpretation agrees
        (acc_e,) = prog.run({"n": np.float32(n)})
        assert float(acc_e) == expect


def test_imported_conditional_block_jits_via_lax_cond(tmp_path):
    vars_main = [
        var_desc("feed", type_id=FEED_MINIBATCH, persistable=True),
        var_desc("fetch", type_id=FETCH_LIST, persistable=True),
        _v("x", (-1,)),
        var_desc("flag", dtype=BOOL, dims=()),
        _v("zero"), _v("s"), _v("y", (-1,)),
    ]
    ops_main = [
        op_desc("feed", [("X", ["feed"])], [("Out", ["x"])],
                [attr("col", A_INT, 0)]),
        op_desc("reduce_sum", [("X", ["x"])], [("Out", ["s"])],
                [attr("keep_dim", A_BOOL, False)]),
        op_desc("fill_constant", [], [("Out", ["zero"])],
                [attr("shape", A_INTS, []), attr("value", A_FLOAT, 0.0),
                 attr("dtype", A_INT, FP32)]),
        op_desc("greater_than", [("X", ["s"]), ("Y", ["zero"])],
                [("Out", ["flag"])]),
        op_desc("assign", [("X", ["x"])], [("Out", ["y"])]),
        op_desc("conditional_block", [("Cond", ["flag"]), ("Input", ["x"])],
                [("Out", ["y"])],
                [attr_block("sub_block", 1),
                 attr("is_scalar_condition", A_BOOL, True)]),
        op_desc("fetch", [("X", ["y"])], [("Out", ["fetch"])],
                [attr("col", A_INT, 0)]),
    ]
    ops_sub = [
        op_desc("scale", [("X", ["x"])], [("Out", ["y"])],
                [attr("scale", A_FLOAT, 2.0), attr("bias", A_FLOAT, 0.0)]),
    ]
    (tmp_path / "__model__").write_bytes(program_desc([
        block_desc(0, vars_main, ops_main),
        block_desc(1, [], ops_sub),
    ]))
    prog = load_paddle_inference_model(str(tmp_path))
    fn = jax.jit(lambda feed: prog.as_fn()(feed))
    pos = np.asarray([1.0, 2.0], np.float32)
    neg = np.asarray([-1.0, -2.0], np.float32)
    np.testing.assert_allclose(np.asarray(fn({"x": pos})[0]), pos * 2)
    np.testing.assert_allclose(np.asarray(fn({"x": neg})[0]), neg)


def test_training_program_jits_end_to_end(tmp_path):
    """The whole imported TRAIN step (forward + backward + sgd) compiles
    as one XLA program via as_fn; fetching loss + updated params matches
    the eager interpreter bit-for-bit."""
    rs = np.random.RandomState(2)
    w0 = (rs.randn(4, 1) * 0.1).astype(np.float32)
    b0 = np.zeros(1, np.float32)
    lr = np.asarray([0.1], np.float32)
    _write_train_artifact(tmp_path, "sgd", w0, b0, lr)
    prog = load_paddle_inference_model(str(tmp_path),
                                       params_filename="__params__")
    x, y = _data(rs)

    fetches = ["loss", "w", "b"]
    prog.fetch_names = fetches  # fetch updated params too
    jfn = jax.jit(lambda feed: prog.as_fn()(feed))
    loss_j, w_j, b_j = jfn({"x": jnp.asarray(x), "yt": jnp.asarray(y)})

    prog2 = load_paddle_inference_model(str(tmp_path),
                                        params_filename="__params__")
    loss_e, w_e, b_e = prog2.run({"x": x, "yt": y}, fetch_list=fetches)
    np.testing.assert_allclose(np.asarray(loss_j), loss_e, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(w_j), w_e, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(b_j), b_e, rtol=1e-6)

"""Distributed fault-tolerance runtime (ISSUE 4): collective timeouts with
retry/escalation, replica-divergence (SDC) detection with recovery policies,
deterministic full-job resume (bit-parity proof on the gpt-test config), and
the rank-loss → shrink → resume path.

Chaos style follows tests/test_robustness.py: every failure class is
*injected* at an exact call index (fault_injection.FaultyCollective /
ChaosGroup) and the recovery path is asserted, never assumed.
"""
import os
import sys
import time

import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as optim
import paddle_tpu.distributed.collective as coll
from paddle_tpu.framework import random as rng_mod
from paddle_tpu.framework.tensor import Tensor
from paddle_tpu.io import DataLoader
from paddle_tpu.observability.metrics import get_registry
from paddle_tpu.robustness import distributed_ft as ft
from paddle_tpu.robustness import (
    ChaosGroup, CheckpointManager, CollectiveTimeoutError, FaultyCollective,
    HangDetector, NanGuard, ReplicaDivergenceError, ReplicaGuard,
    ResumableLoader, TransientCollectiveError,
)
from paddle_tpu.distributed import grad_comm

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_ft_state():
    """No leaked chaos interposers, flag defaults, or hang detectors."""
    yield
    ft._chaos.clear()
    ft.set_default_hang_detector(None)
    paddle.set_flags({"FLAGS_collective_timeout_s": 0.0})


def _counter(name, **labels):
    fam = get_registry().get(name)
    if fam is None:
        return 0
    return (fam.labels(**labels) if labels else fam).value


def _params(values):
    out = []
    for i, v in enumerate(values):
        p = Tensor(np.asarray(v, np.float32))
        p.stop_gradient = False
        p.name = f"p{i}"
        out.append(p)
    return out


# ---------------------------------------------------------------- timeouts
class TestGroupTimeout:
    def test_new_group_stores_timeout_and_reprs_it(self):
        g = coll.new_group(timeout=12.5)
        assert g.timeout == 12.5
        assert "timeout=12.5s" in repr(g)

    def test_timedelta_accepted(self):
        import datetime

        g = coll.new_group(timeout=datetime.timedelta(seconds=30))
        assert g.timeout == 30.0

    def test_default_from_flag(self):
        paddle.set_flags({"FLAGS_collective_timeout_s": 7})
        g = coll.new_group()
        assert g.timeout == 7.0
        # groups with no own timeout defer to the flag at call time
        assert ft.effective_timeout(coll.Group(99, ("data",))) == 7.0
        paddle.set_flags({"FLAGS_collective_timeout_s": 0.0})
        assert coll.new_group().timeout is None
        assert ft.effective_timeout(None) is None


class TestCollectiveTimeoutAndRetry:
    def test_hang_times_out_then_retry_succeeds(self):
        g = ChaosGroup(plan={1: ("hang", 5.0)}, timeout=0.1)
        t = Tensor(np.ones(4, np.float32))
        before = _counter("collective_timeouts_total", op="all_reduce")
        t0 = time.monotonic()
        coll.all_reduce(t, group=g)
        # attempt 1 hung and was timed out; the retry found no fault
        assert time.monotonic() - t0 < 3.0
        assert g.chaos.hangs == 1 and g.chaos.calls == 2
        assert _counter("collective_timeouts_total",
                        op="all_reduce") == before + 1
        np.testing.assert_array_equal(t.numpy(), np.ones(4))

    def test_timeout_exhaustion_raises_typed_and_escalates(self):
        hangs = []
        hd = HangDetector(timeout=999, on_hang=hangs.append)
        ft.set_default_hang_detector(hd)
        g = ChaosGroup(plan={i: ("hang", 2.0) for i in (1, 2, 3)},
                       timeout=0.05)
        t = Tensor(np.ones(2, np.float32))
        with pytest.raises(CollectiveTimeoutError) as ei:
            coll.all_reduce(t, group=g)
        err = ei.value
        assert err.op == "all_reduce" and err.group is g
        assert err.rank == 0 and err.timeout == 0.05 and err.attempt == 3
        # the wedge escalated to the watchdog (whose on_hang pairs with the
        # external supervisor)
        assert hd.hang_count == 1 and hd.stalled and len(hangs) == 1

    def test_transient_failure_retried_with_success(self):
        fc = FaultyCollective(plan={1: ("fail", None)})
        t = Tensor(np.full(3, 2.0, np.float32))
        before = _counter("collective_retries_total", op="all_reduce",
                          reason="transient")
        with fc:
            coll.all_reduce(t)
        assert fc.fails == 1 and fc.calls == 2
        assert _counter("collective_retries_total", op="all_reduce",
                        reason="transient") == before + 1
        np.testing.assert_array_equal(t.numpy(), np.full(3, 2.0))

    def test_transient_exhaustion_raises(self):
        fc = FaultyCollective(plan={i: ("fail", None) for i in (1, 2, 3)})
        t = Tensor(np.ones(2, np.float32))
        with fc, pytest.raises(TransientCollectiveError):
            coll.all_reduce(t)
        assert fc.fails == 3

    def test_bitflip_corrupts_payload_silently(self):
        """The SDC model: the collective SUCCEEDS, the data is wrong —
        exactly what only ReplicaGuard can catch."""
        t = Tensor(np.zeros(4, np.float32))
        with FaultyCollective(plan={1: ("bitflip", 9)}):
            coll.all_reduce(t)
        assert np.asarray(t.numpy()).any(), "bit-flip did not land"

    def test_fast_path_untouched_without_timeout_or_chaos(self):
        t = Tensor(np.ones(3, np.float32))
        coll.all_reduce(t)  # no group timeout, flag 0, no chaos installed
        np.testing.assert_array_equal(t.numpy(), np.ones(3))

    def test_guard_covers_other_collectives(self):
        fc = FaultyCollective(plan={1: ("fail", None), 3: ("fail", None)})
        t = Tensor(np.arange(4, dtype=np.float32))
        with fc:
            out = coll.reduce_scatter(t)          # retried once (calls 1, 2)
            got = coll.all_gather(None, t)        # retried once (calls 3, 4)
        assert fc.fails == 2 and fc.calls == 4
        np.testing.assert_array_equal(out.numpy(), t.numpy())
        np.testing.assert_array_equal(got.numpy(), t.numpy())


# ---------------------------------------------------------- replica guard
def _two_replica_reduce(other):
    """Emulate a 2-rank world: the agreement reduce sees this replica's
    digest and `other`'s."""
    def reduce_fn(digest):
        d2 = ft.params_digest(other)
        both = np.stack([digest, d2])
        return both.min(axis=0), both.max(axis=0)
    return reduce_fn


class TestReplicaGuard:
    def test_agreement_ok(self):
        a = _params([np.arange(6).reshape(2, 3), np.ones(4)])
        b = _params([np.arange(6).reshape(2, 3), np.ones(4)])
        guard = ReplicaGuard(policy="raise",
                             reduce_fn=_two_replica_reduce(b))
        assert guard.check(a) == "ok"
        assert guard.divergences == 0

    def test_bitflip_detected_and_raises(self):
        from paddle_tpu.robustness.fault_injection import flip_bit

        a = _params([np.ones((3, 3))])
        b = _params([np.ones((3, 3))])
        flip_bit(b[0], bit_index=17)  # SDC on the peer replica
        guard = ReplicaGuard(policy="raise",
                             reduce_fn=_two_replica_reduce(b))
        before = _counter("integrity_checks_total", result="diverged")
        with pytest.raises(ReplicaDivergenceError) as ei:
            guard.check(a, step=42)
        assert ei.value.step == 42
        assert not np.array_equal(ei.value.agreed_min, ei.value.agreed_max)
        assert _counter("integrity_checks_total",
                        result="diverged") == before + 1

    def test_rebroadcast_policy_recovers(self):
        a = _params([np.ones((2, 2))])
        b = _params([np.ones((2, 2))])
        from paddle_tpu.robustness.fault_injection import flip_bit

        flip_bit(a[0], bit_index=3)  # OUR replica took the hit

        def rebroadcast(params):
            for p, src in zip(params, b):
                p._value = src._value
        guard = ReplicaGuard(policy="rebroadcast_from_src",
                             reduce_fn=_two_replica_reduce(b),
                             rebroadcast_fn=rebroadcast)
        assert guard.check(a) == "rebroadcast_from_src"
        np.testing.assert_array_equal(a[0].numpy(), b[0].numpy())
        assert guard.check(a) == "ok"  # agreement actually restored

    def test_rollback_policy_restores_checkpoint(self, tmp_path):
        from paddle_tpu.robustness.fault_injection import flip_bit

        a = _params([np.full((2, 2), 5.0)])
        b = _params([np.full((2, 2), 5.0)])
        mgr = CheckpointManager(str(tmp_path))
        mgr.save({"params": [np.asarray(p.numpy()) for p in a]}, 10)

        class Target:  # RobustCheckpoint duck type: restore ALL replicas
            def rollback(self):
                found = mgr.load_latest()
                if found is None:
                    return False
                for replica in (a, b):
                    for p, v in zip(replica, found[0]["params"]):
                        p._value = jnp.asarray(v)
                return True

        flip_bit(b[0], bit_index=40)
        guard = ReplicaGuard(policy="rollback", checkpoint=Target(),
                             reduce_fn=_two_replica_reduce(b))
        assert guard.check(a) == "rollback"
        np.testing.assert_array_equal(a[0].numpy(), np.full((2, 2), 5.0))
        np.testing.assert_array_equal(b[0].numpy(), np.full((2, 2), 5.0))

    def test_rollback_without_valid_checkpoint_escalates(self):
        from paddle_tpu.robustness.fault_injection import flip_bit

        a, b = _params([np.ones(3)]), _params([np.ones(3)])
        flip_bit(b[0], 1)

        class NoCkpt:
            def rollback(self):
                return False

        guard = ReplicaGuard(policy="rollback", checkpoint=NoCkpt(),
                             reduce_fn=_two_replica_reduce(b))
        with pytest.raises(ReplicaDivergenceError, match="no valid"):
            guard.check(a)

    def test_recovery_that_does_not_restore_agreement_raises(self):
        from paddle_tpu.robustness.fault_injection import flip_bit

        a, b = _params([np.ones(3)]), _params([np.ones(3)])
        flip_bit(b[0], 1)
        guard = ReplicaGuard(policy="rebroadcast_from_src",
                             reduce_fn=_two_replica_reduce(b),
                             rebroadcast_fn=lambda params: None)  # useless
        with pytest.raises(ReplicaDivergenceError,
                           match="did not restore agreement"):
            guard.check(a)

    def test_default_reduce_goes_through_collectives(self):
        """Without a custom reduce_fn the digest agreement rides real
        all_reduce calls — so chaos corruption of the digest exchange
        itself is detected too."""
        a = _params([np.ones((4, 4))])
        guard = ReplicaGuard(policy="raise")
        assert guard.check(a) == "ok"  # world == 1: trivially agrees
        with FaultyCollective(plan={1: ("bitflip", 2)}, ops=("all_reduce",)):
            with pytest.raises(ReplicaDivergenceError):
                guard.check(a)

    def test_every_n_gating(self):
        a = _params([np.ones(2)])
        guard = ReplicaGuard(policy="raise", every_n=3,
                             reduce_fn=_two_replica_reduce(a))
        results = [guard.maybe_check(a) for _ in range(6)]
        assert results == ["skipped", "skipped", "ok"] * 2
        assert guard.checks == 2

    def test_policy_validation(self):
        with pytest.raises(ValueError):
            ReplicaGuard(policy="shrug")
        with pytest.raises(ValueError):
            ReplicaGuard(policy="rollback")  # needs a checkpoint target


class TestBucketAgreement:
    def test_identical_ranks_agree(self):
        params = _params([np.ones((8, 8)), np.ones(8)])
        r1 = grad_comm.GradCommunicator()
        r2 = grad_comm.GradCommunicator()
        for p in params:
            p.grad = Tensor(np.zeros(p.shape, np.float32))

        def cross(digest):
            sig = tuple(b.signature() for b in r2.buckets_for(params))
            import zlib

            crc = zlib.crc32(repr(sig).encode())
            d2 = np.array([crc >> 16, crc & 0xFFFF], np.int32)
            both = np.stack([digest, d2])
            return both.min(axis=0), both.max(axis=0)

        d = ft.agree_bucket_assignment(r1, params, reduce_fn=cross)
        assert d.dtype == np.int32

    def test_disagreement_raises(self):
        params = _params([np.ones((4, 4))])
        for p in params:
            p.grad = Tensor(np.zeros(p.shape, np.float32))
        r = grad_comm.GradCommunicator()
        bad = lambda d: (d - 1, d)  # a rank reduced a different layout
        with pytest.raises(ReplicaDivergenceError, match="bucket"):
            ft.agree_bucket_assignment(r, params, reduce_fn=bad)


# ------------------------------------------------------------- job state
def _two_identical_rank_all_reduce():
    def fake(t, op=None, group=None, **kw):
        if op == coll.ReduceOp.SUM and jnp.issubdtype(t._value.dtype,
                                                      jnp.integer):
            t._value = t._value * 2
        return t
    return fake


def _graded_params(shapes, seed):
    rs = np.random.RandomState(seed)
    params = _params([np.zeros(s, np.float32) for s in shapes])
    for p in params:
        p.grad = Tensor(rs.standard_normal(p.shape).astype(np.float32))
    return params


class TestGradCommJobState:
    SHAPES = [(32, 16), (16,), (16, 4)]

    def test_error_feedback_state_survives_restart(self, monkeypatch):
        """The satellite fix: an int8 resume with restored residuals is
        bit-identical to the uninterrupted run; without restore it is not."""
        monkeypatch.setattr(coll, "all_reduce",
                            _two_identical_rank_all_reduce())

        def sync_round(comm, seed):
            params = _graded_params(self.SHAPES, seed)
            comm.sync(params, world=2)
            return [np.asarray(p.grad.numpy()).copy() for p in params]

        # uninterrupted: two syncs on one communicator (residual carries)
        comm = grad_comm.GradCommunicator(grad_comm.GradCommConfig("int8"))
        sync_round(comm, seed=0)
        want = sync_round(comm, seed=1)

        # crash after step 1: state saved, a NEW communicator restores it
        comm_a = grad_comm.GradCommunicator(grad_comm.GradCommConfig("int8"))
        sync_round(comm_a, seed=0)
        state = comm_a.state_dict()
        assert state["residuals"], "int8+EF run should carry residuals"
        comm_b = grad_comm.GradCommunicator(grad_comm.GradCommConfig("int8"))
        comm_b.load_state_dict(state)
        got = sync_round(comm_b, seed=1)
        for w, g in zip(want, got):
            np.testing.assert_array_equal(w, g)

        # and the negative control: dropping the residuals (the pre-fix
        # behavior) changes the synced gradients
        comm_c = grad_comm.GradCommunicator(grad_comm.GradCommConfig("int8"))
        lossy = sync_round(comm_c, seed=1)
        assert any(not np.array_equal(w, l) for w, l in zip(want, lossy))

    def test_first_bucket_build_after_load_keeps_residuals(self, monkeypatch):
        monkeypatch.setattr(coll, "all_reduce",
                            _two_identical_rank_all_reduce())
        comm = grad_comm.GradCommunicator(grad_comm.GradCommConfig("int8"))
        params = _graded_params(self.SHAPES, seed=3)
        comm.sync(params, world=2)
        state = comm.state_dict()
        fresh = grad_comm.GradCommunicator(grad_comm.GradCommConfig("int8"))
        fresh.load_state_dict(state)
        fresh.buckets_for(params)  # the resume-path first build
        assert fresh._residuals, "bucket build cleared restored residuals"

    def test_codec_mismatch_rejected(self):
        comm = grad_comm.GradCommunicator(grad_comm.GradCommConfig("int8"))
        other = grad_comm.GradCommunicator(grad_comm.GradCommConfig("bf16"))
        with pytest.raises(ValueError, match="codec mismatch"):
            other.load_state_dict(comm.state_dict())


class TestRngAndLoaderState:
    def test_rng_state_roundtrip(self):
        import jax

        paddle.seed(31)
        rng_mod.next_key()
        rng_mod.host_rng().rand(3)
        snap = rng_mod.get_rng_state()
        dev1 = np.asarray(jax.random.key_data(rng_mod.next_key()))
        host1 = rng_mod.host_rng().rand(5)
        paddle.seed(999)  # scramble
        rng_mod.set_rng_state(snap)
        np.testing.assert_array_equal(
            np.asarray(jax.random.key_data(rng_mod.next_key())), dev1)
        np.testing.assert_array_equal(rng_mod.host_rng().rand(5), host1)

    def test_resumable_loader_bit_exact_resume(self):
        data = [np.full((4,), i, np.float32) for i in range(20)]
        paddle.seed(5)
        loader = ResumableLoader(DataLoader(data, batch_size=4, shuffle=True))
        it = iter(loader)
        consumed = [next(it), next(it)]
        state = loader.state_dict()
        rng_snap = rng_mod.get_rng_state()
        rest_want = [np.asarray(b) for b in it]
        assert state["batch_idx"] == 2 and len(rest_want) == 3

        paddle.seed(404)  # a restarted process with different entropy
        loader2 = ResumableLoader(DataLoader(data, batch_size=4,
                                             shuffle=True))
        rng_mod.set_rng_state(rng_snap)
        loader2.load_state_dict(state)
        rest_got = [np.asarray(b) for b in loader2]
        assert len(rest_got) == 3
        for w, g in zip(rest_want, rest_got):
            np.testing.assert_array_equal(w, g)
        # and the next epoch's shuffle continues the same stream
        assert loader2.epoch == state["epoch"] + 1

    def test_nan_guard_state_roundtrip(self):
        g = NanGuard(policy="skip_step", max_consecutive_bad=8)
        g.check(loss=float("nan"))
        g.check(loss=float("nan"))
        g.check(loss=1.0)
        g.check(loss=float("nan"))
        fresh = NanGuard(policy="skip_step", max_consecutive_bad=8)
        fresh.load_state_dict(g.state_dict())
        assert fresh.consecutive_bad == 1
        assert fresh.total_bad == 3 and fresh.total_steps == 4


class TestCheckpointJobState:
    def test_job_state_entry_committed_and_loaded(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        mgr.save({"w": np.ones(3)}, 4, job_state={"rank": 0, "note": "hi"})
        js = mgr.load_job_state()
        assert js == {"rank": 0, "note": "hi"}
        assert js == mgr.load_job_state(4)
        state, step, manifest = mgr.load_latest()
        assert step == 4 and "job_state.pdparams" in manifest["entries"]
        np.testing.assert_array_equal(state["w"], np.ones(3))

    def test_checkpoint_without_job_state_returns_none(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        mgr.save({"w": 1}, 0)
        assert mgr.load_job_state() is None
        assert CheckpointManager(str(tmp_path / "empty")).load_job_state() \
            is None

    def test_async_save_carries_job_state(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        mgr.save_async({"w": 2}, 7, job_state={"rng": [1, 2, 3]})
        mgr.wait()
        assert mgr.load_job_state(7) == {"rng": [1, 2, 3]}

    def test_capture_restore_roundtrip(self, monkeypatch):
        monkeypatch.setattr(coll, "all_reduce",
                            _two_identical_rank_all_reduce())
        paddle.seed(77)
        comm = grad_comm.GradCommunicator(grad_comm.GradCommConfig("int8"))
        comm.sync(_graded_params([(8, 8)], seed=0), world=2)
        guard = NanGuard()
        guard.check(loss=float("nan"))
        data = [np.zeros(2, np.float32)] * 8
        loader = ResumableLoader(DataLoader(data, batch_size=2, shuffle=True))
        next(iter(loader))
        js = ft.capture_job_state(reducer=comm, data_iter=loader,
                                  nan_guard=guard, extra={"step": 9})
        assert js["extra"] == {"step": 9} and js["rank"] == 0

        comm2 = grad_comm.GradCommunicator(grad_comm.GradCommConfig("int8"))
        guard2 = NanGuard()
        loader2 = ResumableLoader(DataLoader(data, batch_size=2,
                                             shuffle=True))
        restored = ft.restore_job_state(js, reducer=comm2, data_iter=loader2,
                                        nan_guard=guard2)
        assert restored == ["rng", "grad_comm", "data", "nan_guard"]
        assert guard2.total_bad == 1
        assert loader2.batch_idx == 1
        assert comm2._residuals


# ------------------------------------------- crash → resume parity (gpt)
class TestCrashResumeParity:
    """The acceptance proof: a crash→resume run is bit-identical to the
    uninterrupted run on the gpt-test config — losses match EXACTLY."""

    STEPS, CRASH_AT, BATCH = 4, 2, 2

    def _dataset(self):
        rs = np.random.RandomState(0)
        return [(rs.randint(0, 256, (8,)).astype(np.int64),
                 rs.randint(0, 256, (8,)).astype(np.int64))
                for _ in range(self.STEPS * self.BATCH)]

    def _build(self):
        from paddle_tpu.models import (
            GPTForCausalLM, GPTPretrainingCriterion, gpt_presets,
        )

        m = GPTForCausalLM(gpt_presets("gpt-test"), seed=7)
        crit = GPTPretrainingCriterion()
        o = optim.AdamW(learning_rate=1e-3, parameters=m.parameters())
        return m, crit, o

    def _loader(self):
        return ResumableLoader(DataLoader(self._dataset(),
                                          batch_size=self.BATCH,
                                          shuffle=True))

    @staticmethod
    def _step(m, crit, o, batch):
        ids, labels = batch
        loss = crit(m(paddle.to_tensor(ids, dtype="int64")),
                    paddle.to_tensor(labels, dtype="int64"))
        loss.backward()
        o.step()
        o.clear_grad()
        return float(loss.numpy())

    def test_bit_identical_resume(self, tmp_path):
        # ------------------------------ reference: uninterrupted run
        paddle.seed(1234)
        m, crit, o = self._build()
        loader = self._loader()
        want = [self._step(m, crit, o, b) for b in loader]
        assert len(want) == self.STEPS

        # ------------------------------ run again, crash mid-epoch
        paddle.seed(1234)
        m, crit, o = self._build()
        loader = self._loader()
        mgr = CheckpointManager(str(tmp_path))
        got, it = [], iter(loader)
        for _ in range(self.CRASH_AT):
            got.append(self._step(m, crit, o, next(it)))
        mgr.save({"model": m.state_dict(), "optimizer": o.state_dict()},
                 self.CRASH_AT,
                 job_state=ft.capture_job_state(data_iter=loader))
        del m, crit, o, loader, it  # "the process dies here"

        # ------------------------------ resumed process: fresh everything
        paddle.seed(999)  # different entropy — restore must win
        m, crit, o = self._build()
        loader = self._loader()
        state, step, js = ft.elastic_resume(mgr, data_iter=loader)
        assert step == self.CRASH_AT and js is not None
        m.set_state_dict(state["model"])
        o.set_state_dict(state["optimizer"])
        got += [self._step(m, crit, o, b) for b in loader]

        assert got == want, (got, want)  # EXACT float equality, no tolerance

    def test_bit_identical_resume_traced_residuals(self, tmp_path):
        """ISSUE 8: same proof for the COMPILED path — a jitted
        TrainStep(grad_comm=int8_block) on a 2-replica mesh, crashed after
        2 steps and resumed from checkpoint + job_state, reproduces the
        uninterrupted run's losses exactly. The carried error-feedback
        residuals ride job_state via capture_job_state(train_step=...);
        without them the quantized updates after resume would silently
        diverge."""
        import jax

        import paddle_tpu.distributed.mesh as mesh_mod
        import paddle_tpu.nn.functional as F
        from paddle_tpu.jit import TrainStep

        rs = np.random.RandomState(3)
        X = rs.standard_normal((8, 8)).astype(np.float32)
        Y = rs.standard_normal((8, 1)).astype(np.float32)

        saved_mesh = mesh_mod.get_mesh()
        mesh_mod.set_mesh(mesh_mod.build_mesh(
            {"data": 2}, devices=jax.devices()[:2]))
        try:
            def build():
                paddle.seed(1234)
                net = nn.Sequential(nn.Linear(8, 16), nn.Tanh(),
                                    nn.Linear(16, 1))
                opt = optim.AdamW(learning_rate=1e-2,
                                  parameters=net.parameters())
                step = TrainStep(
                    net, F.mse_loss, opt,
                    grad_comm=grad_comm.GradCommConfig(
                        "int8_block", comm_buffer_size=0.0002,
                        last_comm_buffer_size=0.0001, block_size=64))
                return net, opt, step

            def one(step):
                return float(step(inputs=(paddle.to_tensor(X),),
                                  labels=(paddle.to_tensor(Y),)))

            # ---------------- reference: uninterrupted
            net, opt, step = build()
            want = [one(step) for _ in range(4)]
            assert step._gc_comm._residuals   # the codec really carried

            # ---------------- crash after 2 steps
            net, opt, step = build()
            got = [one(step) for _ in range(2)]
            mgr = CheckpointManager(str(tmp_path))
            mgr.save({"model": net.state_dict(),
                      "optimizer": opt.state_dict()}, 2,
                     job_state=ft.capture_job_state(train_step=step))
            del net, opt, step  # "the process dies here"

            # ---------------- resumed process: fresh everything
            paddle.seed(999)    # different entropy — restore must win
            net, opt, step = build()
            state, resume_step, js = ft.elastic_resume(mgr)
            assert resume_step == 2 and js is not None
            net.set_state_dict(state["model"])
            opt.set_state_dict(state["optimizer"])
            restored = ft.restore_job_state(js, train_step=step)
            assert "grad_comm" in restored
            assert step._gc_comm._residuals   # traced residuals are back
            got += [one(step) for _ in range(2)]

            assert got == want, (got, want)   # EXACT equality, incl. rng
        finally:
            mesh_mod.set_mesh(saved_mesh)

    def test_bit_identical_resume_stage3(self, tmp_path, monkeypatch):
        """ISSUE 9: the same proof for ZeRO-3 at-rest sharding — a
        mid-epoch kill with SHARDED params (Stage3ParamShards), SHARDED
        optimizer slots (FusedFlatUpdater.step_sharded), and int8_block
        error-feedback residuals resumes bit-identically through
        save_group_sharded_checkpoint + capture_job_state. The resumed
        process restores the shards (never materializing full params),
        the shard slots, the residuals, and the rng/data position."""
        import paddle_tpu.nn.functional as F
        from paddle_tpu.distributed.sharding import (
            Stage3ParamShards, save_group_sharded_checkpoint,
        )
        from paddle_tpu.optimizer.fused import FusedFlatUpdater

        def fake_all_reduce(t, op=None, group=None, **kw):
            if op == coll.ReduceOp.SUM and jnp.issubdtype(
                    t._value.dtype, jnp.integer):
                t._value = t._value * 2
            return t

        monkeypatch.setattr(coll, "all_reduce", fake_all_reduce)
        rs = np.random.RandomState(3)
        data = [(rs.standard_normal((4, 8)).astype(np.float32),
                 rs.standard_normal((4, 1)).astype(np.float32))
                for _ in range(4)]

        def build():
            paddle.seed(1234)
            net = nn.Sequential(nn.Linear(8, 16), nn.Tanh(),
                                nn.Linear(16, 1))
            opt = optim.AdamW(learning_rate=1e-2,
                              parameters=net.parameters())
            cfg = grad_comm.GradCommConfig(
                "int8_block", comm_buffer_size=0.0002,
                last_comm_buffer_size=0.0001, block_size=64)
            comm = grad_comm.GradCommunicator(cfg)
            params = [p for p in net.parameters() if not p.stop_gradient]
            fused = FusedFlatUpdater(opt, params, communicator=comm)
            store = Stage3ParamShards(params, comm, rank=0, world=2)
            store.shard_()
            store.install_hooks(net)
            net._zero3 = store
            loader = ResumableLoader(DataLoader(data, batch_size=1,
                                                shuffle=True))
            return net, opt, comm, fused, store, params, loader

        def one(net, comm, fused, store, params, batch):
            xb, yb = batch
            loss = F.mse_loss(net(paddle.to_tensor(xb)),
                              paddle.to_tensor(yb))
            loss.backward()
            comm.sync(params, world=2, use_reduce_scatter=True)
            fused.step_sharded(rank=0, world=2, param_store=store)
            for p in params:
                p.clear_grad()
            return float(loss.numpy())

        # ---------------- reference: uninterrupted
        net, opt, comm, fused, store, params, loader = build()
        want = [one(net, comm, fused, store, params, b) for b in loader]
        assert len(want) == 4
        assert comm._residuals   # the blockwise codec really carried

        # ---------------- crash after 2 steps, sharded save
        net, opt, comm, fused, store, params, loader = build()
        got, it = [], iter(loader)
        for _ in range(2):
            got.append(one(net, comm, fused, store, params, next(it)))
        mgr = save_group_sharded_checkpoint(
            net, str(tmp_path), 2, rank=0, world_size=1, fused=fused,
            job_state=ft.capture_job_state(reducer=comm, data_iter=loader,
                                           zero3=store))
        del net, opt, comm, fused, store, params, loader, it  # dies here

        # ---------------- resumed process: fresh everything
        paddle.seed(999)   # different entropy — restore must win
        net, opt, comm, fused, store, params, loader = build()
        payload = mgr.load(2, shard=0)
        store.load_state_dict(payload["zero3"])
        fused.load_shard_slots_state(payload["fused_shard_slots"])
        restored = ft.restore_job_state(payload["job_state"],
                                        reducer=comm, data_iter=loader,
                                        zero3=store)
        assert {"rng", "grad_comm", "data", "zero3"} <= set(restored)
        assert comm._residuals   # residuals are back
        # params are STILL at rest — the resume never materialized them
        from paddle_tpu.distributed.sharding.stage3 import FreedParamValue

        assert all(isinstance(p._value, FreedParamValue) for p in params)
        got += [one(net, comm, fused, store, params, b) for b in loader]

        assert got == want, (got, want)   # EXACT equality, no tolerance


# -------------------------------------------- rank loss → shrink → resume
class _FakeProc:
    def __init__(self, rc=None):
        self.rc = rc

    def poll(self):
        return self.rc

    def terminate(self):
        if self.rc is None:
            self.rc = -15

    def kill(self):
        self.rc = -9

    def wait(self, timeout=None):
        return self.rc


class TestElasticShrinkResume:
    def test_rank_loss_shrinks_and_resumes_from_checkpoint(self, tmp_path,
                                                           monkeypatch):
        """The full chaos-matrix rank-loss row: a member dies → the
        controller restarts with shrunk endpoints and surfaces the exact
        resume step; the shrunk job restores weights + job_state and
        re-agrees the grad_comm bucket assignment before its first sync."""
        import threading

        from paddle_tpu.distributed.fleet.elastic import (
            ElasticController, ElasticManager, LocalKVStore,
        )

        monkeypatch.setattr(coll, "all_reduce",
                            _two_identical_rank_all_reduce())
        # the job checkpointed up to step 6 before the rank died
        mgr = CheckpointManager(str(tmp_path))
        comm = grad_comm.GradCommunicator(grad_comm.GradCommConfig("int8"))
        params = _graded_params([(16, 8), (8,)], seed=2)
        comm.sync(params, world=2)
        mgr.save({"params": [np.asarray(p.numpy()) for p in params]}, 6,
                 job_state=ft.capture_job_state(reducer=comm))

        store = LocalKVStore()
        em = ElasticManager("node-a", "1:2", store=store, ttl=30,
                            heartbeat_interval=0.05)
        store.put(em.prefix + "/node-b", "node-b")
        events, lives = [], []

        def launch(eps):
            lives.append(list(eps))
            if len(lives) == 1:
                threading.Timer(
                    0.1, lambda: store.delete(em.prefix + "/node-b")).start()
                return [_FakeProc(None)]
            return [_FakeProc(0)]

        ctl = ElasticController(em, launch, poll_interval=0.05,
                                on_restart=events.append,
                                checkpoint_manager=mgr)
        assert ctl.run(np_timeout=5) == 0
        assert len(lives) == 2 and len(lives[1]) == 1  # world shrank 2 -> 1
        assert events[0]["reason"] == "scale"
        assert events[0]["resume_step"] == 6  # controller pinned the step

        # ---- the shrunk life resumes: weights + job_state, then proves
        # bucket agreement before the first gradient sync
        comm2 = grad_comm.GradCommunicator(grad_comm.GradCommConfig("int8"))
        params2 = _graded_params([(16, 8), (8,)], seed=2)
        state, step, js = ft.elastic_resume(mgr, reducer=comm2)
        assert step == 6 and js["grad_comm"]["residuals"]
        for p, v in zip(params2, state["params"]):
            p._value = jnp.asarray(v)
        np.testing.assert_array_equal(params2[0].numpy(), params[0].numpy())
        ft.agree_bucket_assignment(
            comm2, params2, reduce_fn=lambda d: (d, d))  # world of 1 agrees
        comm2.sync(params2, world=1)  # and the first post-shrink sync runs

    def test_restart_metrics_and_missing_checkpoint(self):
        from paddle_tpu.distributed.fleet.elastic import (
            ElasticController, ElasticManager, LocalKVStore,
        )

        em = ElasticManager("solo", "1:1", store=LocalKVStore(), ttl=30,
                            heartbeat_interval=0.05)
        lives, events = [], []

        def launch(eps):
            lives.append(eps)
            return [_FakeProc(3 if len(lives) == 1 else 0)]

        before = _counter("elastic_restarts_total", reason="crash")
        ctl = ElasticController(em, launch, poll_interval=0.02,
                                on_restart=events.append)
        assert ctl.run(np_timeout=5) == 0
        assert _counter("elastic_restarts_total",
                        reason="crash") == before + 1
        assert "resume_step" not in events[0]  # no manager wired


# ------------------------------------------------------------ hapi wiring
class TestHapiIntegration:
    def _fit(self, tmp_path=None, **kw):
        from paddle_tpu import Model

        rs = np.random.RandomState(0)
        x = rs.standard_normal((16, 4)).astype(np.float32)
        y = (x @ rs.standard_normal((4, 1))).astype(np.float32)
        net = nn.Linear(4, 1)
        model = Model(net)
        model.prepare(optimizer=optim.SGD(learning_rate=0.1,
                                          parameters=net.parameters()),
                      loss=nn.MSELoss())
        model.fit(list(zip(x, y)), batch_size=4, epochs=1, verbose=0, **kw)
        return model

    def test_fit_beats_hang_detector_each_step(self):
        hd = HangDetector(timeout=300)
        before = _counter("watchdog_heartbeats_total")
        self._fit(hang_detector=hd)
        # one beat per train step (16 samples / batch 4 = 4 steps) plus the
        # start() beat
        assert _counter("watchdog_heartbeats_total") >= before + 5
        assert hd._thread is None  # fit started it, fit stopped it
        assert ft.get_default_hang_detector() is None  # registration undone

    def test_fit_accepts_timeout_number(self):
        model = self._fit(hang_detector=120.0)
        assert model._hang_detector is None  # torn down after fit

    def test_robust_checkpoint_resume_restores_job_state(self, tmp_path):
        from paddle_tpu.hapi.callbacks import RobustCheckpoint

        paddle.seed(21)
        cb = RobustCheckpoint(str(tmp_path), save_freq=1)
        self._fit(callbacks=[cb])
        mgr = CheckpointManager(str(tmp_path))
        js = mgr.load_job_state()
        assert js is not None and "rng" in js  # default capture ran

        # a fresh process resumes: weights AND rng come back
        paddle.seed(333)
        from paddle_tpu import Model

        net2 = nn.Linear(4, 1)
        model2 = Model(net2)
        model2.prepare(optimizer=optim.SGD(learning_rate=0.1,
                                           parameters=net2.parameters()),
                       loss=nn.MSELoss())
        cb2 = RobustCheckpoint(str(tmp_path))
        cb2.set_model(model2)
        step = cb2.resume()
        assert step == 0  # epoch 0 was the last save
        trained = CheckpointManager(str(tmp_path)).load_latest()[0]["model"]
        np.testing.assert_array_equal(net2.weight.numpy(),
                                      np.asarray(trained["weight"]))


# ---------------------------------------------------------- chaos torture
class TestChaosTrainQuick:
    def test_quick_chaos_train(self, tmp_path):
        """The <15s tier-1 slice of tools/chaos_train.py: seeded fault
        schedule over a 2-replica DP run — every injected fault detected
        and recovered, crash→resume bit-parity holds."""
        sys.path.insert(0, os.path.join(REPO, "tools"))
        try:
            from chaos_train import run_chaos_train
        finally:
            sys.path.pop(0)
        summary = run_chaos_train(steps=12, seed=3, root=str(tmp_path))
        assert summary["ok"], summary
        assert summary["parity"]["ok"]
        # overlapped-sync chaos slice (ISSUE 5): hang + transient injected
        # on a mid-backward bucket's collective; retries + flush() ordering
        # must keep the overlapped run's losses EXACTLY the serial run's
        ov = summary["overlap"]
        assert ov["ok"], ov
        assert ov["hangs_injected"] == 1 and ov["transients_injected"] == 1
        assert ov["losses_overlapped"] == ov["losses_serial"]
        # flight-recorder postmortem (ISSUE 6): a mid-backward hang that
        # exhausts its retries must leave a dump whose tail names the hung
        # bucket's lane span and carries the CollectiveTimeoutError event
        fr = summary["flightrec"]
        assert fr["ok"], fr
        assert fr["timeout_raised"]
        assert fr["hung_bucket"] is not None
        assert fr["tail_has_lane_span"] and fr["tail_has_timeout_event"]
        assert os.path.exists(fr["dump_path"])
        # preemption + elastic reshard slice (ISSUE 10): a real SIGTERM on
        # a world=4 ZeRO-3 job commits an emergency sharded checkpoint at
        # the step boundary and resumes at world=3 through the reshard
        # transform — zero refused resumes, exact fp32 loss parity vs the
        # uninterrupted reshape-reference
        pr = summary["preempt"]
        assert pr["ok"], pr
        assert pr["sigterm_latched"] and pr["resharded"]
        assert pr["refused_resumes"] == 0 and pr["refused_without_flag"]
        assert pr["emergency_save_ms"] is not None \
            and pr["grace_seconds"] > 0
        assert pr["losses_resumed"] == pr["losses_reference"]
        chaos = summary["chaos"]
        assert chaos["bitflips_injected"] > 0
        assert chaos["bitflips_detected"] == chaos["bitflips_injected"]
        assert chaos["hangs_injected"] > 0 and chaos["transients_injected"] > 0
        assert chaos["silent_divergence_steps"] == 0
        assert chaos["final_replicas_identical"]
        # elastic fleet controller slice (ISSUE 17): under the recorded
        # preemption + diurnal-arrival trace, preemption-ahead scaling
        # must beat the reactive baseline on goodput, answer every
        # preemption notice with an in-grace emergency save, and lose
        # ZERO requests across every drain + re-admit scale event
        fl = summary["fleet"]
        assert fl["ok"], fl
        assert fl["fleet_goodput_ratio"] >= 1.2
        assert fl["scale_event_lost_requests"] == 0
        assert fl["scale_events_drained_requests"] >= 1
        assert fl["preempt_saves_in_grace"] is True
        assert fl["preempt_unanswered_policy"] == 0
        # the baseline proves the hazard is real: with no controller the
        # notice goes unanswered and the job pays a crash-restart
        assert fl["reactive"]["preempt_unanswered"] >= 1
        # every chip-second accounted, decisions replay deterministically
        for mode in ("policy", "reactive"):
            assert fl[mode]["conservation_ok"], mode
            assert fl[mode]["decision_replay_ok"], mode

    def test_artifact_schema(self):
        import json

        path = os.path.join(REPO, "artifacts", "chaos_train.json")
        if not os.path.exists(path):
            pytest.skip("no recorded chaos run")
        rec = json.load(open(path))
        assert rec["ok"] and rec["parity"]["ok"]
        assert rec["overlap"]["ok"]
        assert rec["overlap"]["losses_overlapped"] == \
            rec["overlap"]["losses_serial"]
        assert rec["chaos"]["silent_divergence_steps"] == 0
        assert rec["chaos"]["bitflips_detected"] == \
            rec["chaos"]["bitflips_injected"]

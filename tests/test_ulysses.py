"""Ulysses sequence parallelism: all_to_all head/seq swap over 'sep'.

Net-new capability (SURVEY §5: the reference has no SP); scheme per
DeepSpeed-Ulysses. Bar: sharded output/grads equal the single-device
attention, composing with TP head sharding.
"""
import jax
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.distributed.mesh as mesh_mod
from paddle_tpu.distributed.ulysses import ulysses_attention_val

rs = np.random.RandomState(0)


@pytest.fixture(autouse=True)
def reset_mesh(fresh_mesh):
    yield  # fresh_mesh (conftest) owns save/clear/restore


def _ref_attention(q, k, v, causal=True):
    scale = 1.0 / np.sqrt(q.shape[-1])
    logits = np.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        keep = np.tril(np.ones((sq, sk), bool), k=sk - sq)
        logits = np.where(keep, logits, -1e30)
    e = np.exp(logits - logits.max(-1, keepdims=True))
    probs = e / e.sum(-1, keepdims=True)
    return np.einsum("bhqk,bkhd->bqhd", probs, v)


def test_ulysses_matches_single_device():
    mesh_mod.set_mesh(mesh_mod.build_mesh({"sep": 4, "data": 2}))
    b, s, n, d = 2, 16, 4, 8
    q = rs.randn(b, s, n, d).astype(np.float32)
    k = rs.randn(b, s, n, d).astype(np.float32)
    v = rs.randn(b, s, n, d).astype(np.float32)
    out = jax.jit(ulysses_attention_val)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), _ref_attention(q, k, v),
                               rtol=2e-4, atol=1e-5)


def test_ulysses_grads_match_plain_attention():
    mesh_mod.set_mesh(mesh_mod.build_mesh({"sep": 4, "data": 2}))
    b, s, n, d = 2, 8, 4, 4
    q = rs.randn(b, s, n, d).astype(np.float32)
    k = rs.randn(b, s, n, d).astype(np.float32)
    v = rs.randn(b, s, n, d).astype(np.float32)

    def loss_ul(q_, k_, v_):
        return (ulysses_attention_val(q_, k_, v_) ** 2).sum()

    from paddle_tpu.distributed.ulysses import _plain_attention

    def loss_ref(q_, k_, v_):
        return (_plain_attention(q_, k_, v_, True) ** 2).sum()

    g_ul = jax.grad(loss_ul, argnums=(0, 1, 2))(q, k, v)
    mesh_mod._current[0] = None  # reference on a single device
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_ul, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-4, atol=1e-5)


def test_ulysses_composes_with_tp_head_sharding():
    mesh_mod.set_mesh(mesh_mod.build_mesh({"sep": 2, "model": 2,
                                           "data": 2}))
    b, s, n, d = 2, 8, 4, 4  # n=4: 2 local heads per model shard, /2 sep
    q = rs.randn(b, s, n, d).astype(np.float32)
    k = rs.randn(b, s, n, d).astype(np.float32)
    v = rs.randn(b, s, n, d).astype(np.float32)
    out = jax.jit(ulysses_attention_val)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), _ref_attention(q, k, v),
                               rtol=2e-4, atol=1e-5)


def test_ulysses_head_divisibility_error():
    mesh_mod.set_mesh(mesh_mod.build_mesh({"sep": 8, "data": 1}))
    q = rs.randn(1, 8, 4, 4).astype(np.float32)  # 4 heads < sep 8
    with pytest.raises(ValueError, match="not divisible"):
        jax.jit(ulysses_attention_val)(q, q, q)


def test_gpt_ulysses_mode_matches_dense():
    from paddle_tpu.jit.functional import FunctionalModule
    from paddle_tpu.models import GPTForCausalLM, gpt_presets

    ids = rs.randint(0, 256, (2, 16)).astype("int64")

    def run(use_ulysses, topo):
        mesh_mod._current[0] = None
        if topo:
            mesh_mod.set_mesh(mesh_mod.build_mesh(topo))
        paddle.seed(9)
        cfg = gpt_presets("gpt-test", max_position_embeddings=32,
                          use_ulysses_attention=use_ulysses)
        model = GPTForCausalLM(cfg, seed=0)
        model.eval()
        fm = FunctionalModule(model)
        out, _ = fm.call(fm.param_values(), [], jax.random.key(0),
                         (ids,), training=False)
        return np.asarray(out)

    dense = run(False, None)
    ul = run(True, {"sep": 2, "data": 2, "model": 2})
    np.testing.assert_allclose(ul, dense, rtol=2e-3, atol=2e-4)


def test_tensor_level_api():
    mesh_mod.set_mesh(mesh_mod.build_mesh({"sep": 2, "data": 4}))
    import paddle_tpu.distributed as dist

    q = paddle.to_tensor(rs.randn(4, 8, 2, 4).astype("float32"),
                         stop_gradient=False)
    out = dist.ulysses_attention(q, q, q)
    assert out.shape == [4, 8, 2, 4]
    out.sum().backward()
    assert q.grad is not None

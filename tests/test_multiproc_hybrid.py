"""Multi-process hybrid parallelism with loss parity (VERDICT r3 next #3).

The reference proves its distributed runtime with real subprocesses per
rank (test_dist_base.py:783,1032 spawns pservers/trainers; collective
tests launch 2 ranks). Single-process SPMD over a virtual mesh hides
cross-host init, device-ordering and sharding-transfer bugs — so here TWO
spawned processes (4 XLA host devices each) rendezvous via
init_parallel_env -> jax.distributed.initialize and run REAL training
steps over meshes that span the process boundary:

  config A  GSPMD MLP train step on a data4 x model2 mesh (tensor-parallel
            matmuls + cross-process data parallelism, GSPMD-partitioned)
  config B  the segmented 1F1B pipeline schedule on a pipe2 x data4 mesh
            whose PIPE axis crosses the process boundary — every
            ppermute hop is a cross-process transfer

Both loss sequences must match an in-process single-device oracle (same
seeds, same math) and agree exactly across ranks.
"""
import os
import socket
import subprocess
import sys
import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pipeline_toy import DIN, DOUT, embed_fn, loss_fn, make_params, stage_fn

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

STEPS = 4
LR = 0.05
HID = 32
PIPE, KPER = 2, 2
M, MB = 4, 4         # 1F1B micro-batches

WORKER = textwrap.dedent("""
    import os, sys
    rank = int(sys.argv[1]); port = sys.argv[2]
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    os.environ["PADDLE_TRAINER_ID"] = str(rank)
    os.environ["PADDLE_TRAINERS_NUM"] = "2"
    os.environ["PADDLE_MASTER"] = "127.0.0.1:" + port
    sys.path.insert(0, {repo!r})
    sys.path.insert(0, {repo!r} + "/tests")

    import paddle_tpu.distributed as dist
    env = dist.init_parallel_env()

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    jax.config.update("jax_default_matmul_precision", "highest")
    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 8, len(jax.devices())

    from pipeline_toy import (DIN, DOUT, SPECS, embed_fn, loss_fn,
                              make_params, stage_fn)
    from paddle_tpu.distributed.pipeline import pipeline_1f1b

    STEPS, LR, HID = {steps}, {lr}, {hid}
    PIPE, KPER, M, MB = {pipe}, {kper}, {m}, {mb}

    def gshard(mesh, spec, arr):
        s = NamedSharding(mesh, spec)
        return jax.make_array_from_callback(
            arr.shape, s, lambda idx: arr[idx])

    # ---- config A: GSPMD MLP on data4 x model2 (data crosses procs) ----
    mesh_a = Mesh(np.array(jax.devices()).reshape(4, 2), ("data", "model"))
    rs = np.random.RandomState(0)
    w1 = (rs.randn(DIN, 64) * 0.3).astype(np.float32)
    w2 = (rs.randn(64, DOUT) * 0.3).astype(np.float32)
    xb = rs.randn(32, DIN).astype(np.float32)
    yb = rs.randn(32, DOUT).astype(np.float32)

    def loss_a(params, x, y):
        h = jnp.tanh(x @ params[0])
        return jnp.mean((h @ params[1] - y) ** 2)

    @jax.jit
    def step_a(params, x, y):
        l, g = jax.value_and_grad(loss_a)(params, x, y)
        return l, tuple(p - LR * gi for p, gi in zip(params, g))

    params = (gshard(mesh_a, P(None, "model"), w1),
              gshard(mesh_a, P("model", None), w2))
    x = gshard(mesh_a, P("data", None), xb)
    y = gshard(mesh_a, P("data", None), yb)
    la = []
    for _ in range(STEPS):
        l, params = step_a(params, x, y)
        la.append(float(l))
    print("LOSSES_A", rank, " ".join(f"{{v:.8f}}" for v in la), flush=True)

    # ---- config B: 1F1B on pipe2 x data4 — pipe crosses processes ----
    mesh_b = Mesh(np.array(jax.devices()).reshape(2, 4), ("pipe", "data"))
    rs2 = np.random.RandomState(1)
    tparams = make_params(rs2, PIPE * KPER, HID)
    xb2 = rs2.randn(M * MB, DIN).astype(np.float32)
    yb2 = rs2.randn(M * MB, DOUT).astype(np.float32)

    @jax.jit
    def step_b(p, x, lbl):
        loss, grads = pipeline_1f1b(
            embed_fn, stage_fn, loss_fn, p, x, lbl,
            mesh=mesh_b, param_specs=SPECS, microbatches=M)
        new = jax.tree.map(
            lambda w, g: (w - LR * g).astype(w.dtype), p, grads)
        return loss, new

    tp = {{k: gshard(mesh_b, SPECS[k], np.asarray(v))
          for k, v in tparams.items()}}
    xg = gshard(mesh_b, P("data", None), xb2)
    yg = gshard(mesh_b, P("data", None), yb2)
    lb = []
    for _ in range(STEPS):
        l, tp = step_b(tp, xg, yg)
        lb.append(float(l))
    print("LOSSES_B", rank, " ".join(f"{{v:.8f}}" for v in lb), flush=True)
    print("RANK_OK", rank, flush=True)
""").format(repo=REPO, steps=STEPS, lr=LR, hid=HID, pipe=PIPE, kper=KPER,
            m=M, mb=MB)


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _oracle_a():
    rs = np.random.RandomState(0)
    w1 = (rs.randn(DIN, 64) * 0.3).astype(np.float32)
    w2 = (rs.randn(64, DOUT) * 0.3).astype(np.float32)
    xb = rs.randn(32, DIN).astype(np.float32)
    yb = rs.randn(32, DOUT).astype(np.float32)

    def loss(params, x, y):
        h = jnp.tanh(x @ params[0])
        return jnp.mean((h @ params[1] - y) ** 2)

    params = (jnp.asarray(w1), jnp.asarray(w2))
    out = []
    step = jax.jit(lambda p, x, y: jax.value_and_grad(loss)(p, x, y))
    for _ in range(STEPS):
        l, g = step(params, xb, yb)
        params = tuple(p - LR * gi for p, gi in zip(params, g))
        out.append(float(l))
    return out


def _oracle_b():
    rs2 = np.random.RandomState(1)
    params = make_params(rs2, PIPE * KPER, HID)
    xb2 = rs2.randn(M * MB, DIN).astype(np.float32)
    yb2 = rs2.randn(M * MB, DOUT).astype(np.float32)

    def seq_loss(p, x, lbl):
        h = embed_fn(p, x)
        h = stage_fn(p, h)
        return loss_fn(p, h, lbl)

    step = jax.jit(lambda p, x, y: jax.value_and_grad(seq_loss)(p, x, y))
    out = []
    for _ in range(STEPS):
        l, g = step(params, xb2, yb2)
        params = jax.tree.map(
            lambda w, gi: (w - LR * gi).astype(w.dtype), params, g)
        out.append(float(l))
    return out


@pytest.mark.timeout(420)
@pytest.mark.requires_cpu_multiprocess
def test_two_process_hybrid_training_parity(tmp_path):
    port = str(_free_port())
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in env.get("PYTHONPATH", "").split(os.pathsep)
        if p and "axon" not in p)
    procs = [subprocess.Popen([sys.executable, str(script), str(r), port],
                              env=env, stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True)
             for r in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=390)
            outs.append(out)
    finally:
        # a crashed rank leaves its peer blocked in rendezvous forever;
        # never leak a hung worker past the test
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {r} failed:\n{out[-4000:]}"
        assert f"RANK_OK {r}" in out

    def parse(tag, out):
        for line in out.splitlines():
            if line.startswith(tag):
                return [float(v) for v in line.split()[2:]]
        raise AssertionError(f"{tag} not found in:\n{out[-2000:]}")

    for tag, oracle in (("LOSSES_A", _oracle_a()), ("LOSSES_B", _oracle_b())):
        seq0 = parse(tag, outs[0])
        seq1 = parse(tag, outs[1])
        # both ranks observe the same replicated loss
        np.testing.assert_allclose(seq0, seq1, rtol=1e-6, err_msg=tag)
        # and it matches the in-process single-device oracle
        np.testing.assert_allclose(seq0, oracle, rtol=2e-4, atol=1e-6,
                                   err_msg=tag)

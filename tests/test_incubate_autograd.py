"""Functional autograd API (incubate/autograd.py): jvp/vjp/Jacobian/Hessian.

Reference: python/paddle/incubate/autograd/ — the prim-op transform system,
dissolved into jax transforms.
"""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.incubate import autograd as A


def f_square_sum(x):
    return (x * x).sum()


def f_vec(x):
    return paddle.tanh(x) * 2.0


def test_jvp():
    x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
    v = paddle.to_tensor(np.array([1.0, 0.0], np.float32))
    out, jv = A.jvp(f_square_sum, x, v)
    assert float(out) == 5.0
    assert float(jv) == 2.0  # d(sum x^2)·[1,0] = 2*x1


def test_vjp():
    x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
    out, g = A.vjp(f_square_sum, x)
    np.testing.assert_allclose(g.numpy(), [2.0, 4.0])


def test_jacobian_full_matrix():
    x = paddle.to_tensor(np.array([0.5, -0.5], np.float32))
    J = A.Jacobian(f_vec, x)
    assert J.shape == (2, 2)
    expect = np.diag(2.0 / np.cosh([0.5, -0.5]) ** 2).astype(np.float32)
    np.testing.assert_allclose(J[:].numpy(), expect, rtol=1e-5)


def test_hessian():
    x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
    H = A.Hessian(f_square_sum, x)
    np.testing.assert_allclose(H[:].numpy(), 2 * np.eye(2), rtol=1e-6)


def test_batched_jacobian():
    x = paddle.to_tensor(np.random.RandomState(0).randn(3, 2).astype("f4"))
    J = A.Jacobian(lambda v: v * v, x, is_batched=True)
    assert J.shape == (3, 2, 2)
    for b in range(3):
        np.testing.assert_allclose(
            J[:].numpy()[b], np.diag(2 * x.numpy()[b]), rtol=1e-5)


def test_forward_grad_and_grad():
    x = paddle.to_tensor(np.array([3.0], np.float32))
    fg = A.forward_grad(lambda v: v * v, x)
    np.testing.assert_allclose(fg.numpy(), [6.0])
    g = A.grad(lambda v: v * v * v, x)
    np.testing.assert_allclose(g.numpy(), [27.0])

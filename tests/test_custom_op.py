"""Custom C++ op plug-in (utils/cpp_extension.py).

Reference capability: framework/custom_operator.cc + utils/cpp_extension —
user-compiled C++ operators callable from Python with autograd.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.utils import cpp_extension

SRC = r"""
#include <cstdint>
#include <cmath>

// y = x^3  (elementwise)
extern "C" void cube(const float** inputs, const int64_t* sizes,
                     int num_inputs, float* out, int64_t out_size) {
  const float* x = inputs[0];
  for (int64_t i = 0; i < out_size; ++i) out[i] = x[i] * x[i] * x[i];
}

// dx = 3x^2 * dy   (cotangent arrives as the LAST input)
extern "C" void cube_grad(const float** inputs, const int64_t* sizes,
                          int num_inputs, int wrt, float* out,
                          int64_t out_size) {
  const float* x = inputs[0];
  const float* dy = inputs[num_inputs - 1];
  for (int64_t i = 0; i < out_size; ++i) out[i] = 3.f * x[i] * x[i] * dy[i];
}
"""


@pytest.fixture(scope="module")
def cube_mod(tmp_path_factory):
    src = tmp_path_factory.mktemp("ext") / "cube_op.cc"
    src.write_text(SRC)
    return cpp_extension.load(name="cube", sources=[str(src)])


def test_custom_op_forward(cube_mod):
    x = paddle.to_tensor(np.array([1.0, 2.0, -3.0], np.float32))
    out = cube_mod.cube(x)
    np.testing.assert_allclose(out.numpy(), [1.0, 8.0, -27.0])


def test_custom_op_backward(cube_mod):
    x = paddle.to_tensor(np.array([1.0, 2.0, -3.0], np.float32))
    x.stop_gradient = False
    y = cube_mod.cube(x)
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [3.0, 12.0, 27.0])


def test_custom_op_under_jit(cube_mod):
    import jax
    import jax.numpy as jnp

    from paddle_tpu.framework.tensor import Tensor

    @jax.jit
    def f(v):
        t = Tensor(v, _internal=True)
        return cube_mod.cube(t)._value

    out = f(jnp.asarray([2.0, 3.0], jnp.float32))
    np.testing.assert_allclose(np.asarray(out), [8.0, 27.0])


def test_compile_error_is_reported(tmp_path):
    bad = tmp_path / "bad.cc"
    bad.write_text("this is not C++")
    with pytest.raises(RuntimeError, match="build failed"):
        cpp_extension.load(name="bad", sources=[str(bad)])

"""Compat knobs must warn or act, never silently no-op (VERDICT r2 weak #5
/ item 8): inert DistributedStrategy bits and CUDA-era inference Config
knobs warn once; fleet.util.all_reduce really reduces; DataParallel
implements find_unused_parameters semantics.
"""
import warnings

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def test_strategy_inert_bits_warn_once():
    from paddle_tpu.distributed.fleet import DistributedStrategy
    from paddle_tpu.distributed.fleet.base import distributed_strategy as ds

    ds._warned_inert.discard("semi_auto")
    s = DistributedStrategy()
    with pytest.warns(UserWarning, match="semi_auto"):
        s.semi_auto = True
    assert s.semi_auto is True
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        s.semi_auto = True  # second set: silent (warned once)

    ds._warned_inert.discard("heter_ccl_mode")
    with pytest.warns(UserWarning, match="heter_ccl_mode"):
        s2 = DistributedStrategy()
        s2.heter_ccl_mode = True


def test_inference_config_cuda_knobs_warn():
    from paddle_tpu import inference
    from paddle_tpu.inference import Config

    inference._compat_warned.discard("enable_mkldnn")
    inference._compat_warned.discard("enable_use_gpu")
    cfg = Config("m")
    with pytest.warns(UserWarning, match="enable_mkldnn"):
        cfg.enable_mkldnn()
    with pytest.warns(UserWarning, match="enable_use_gpu"):
        cfg.enable_use_gpu(100, 0)
    with pytest.raises(NotImplementedError):
        cfg.enable_tensorrt_engine()


def test_fleet_util_all_reduce_single_world_identity():
    from paddle_tpu.distributed import fleet

    out = fleet.util.all_reduce(np.asarray([1.0, 2.0]), mode="sum")
    np.testing.assert_allclose(out, [1.0, 2.0])


class _TwoHeads(nn.Layer):
    def __init__(self):
        super().__init__()
        self.used = nn.Linear(4, 4)
        self.unused = nn.Linear(4, 4)

    def forward(self, x):
        return self.used(x)


def _dp_backward(find_unused):
    from paddle_tpu.distributed.parallel import DataParallel

    model = DataParallel(_TwoHeads(),
                         find_unused_parameters=find_unused)
    x = paddle.to_tensor(np.ones((2, 4), np.float32))
    loss = model(x).sum()
    loss.backward()
    return model


def test_find_unused_parameters_zero_fills(monkeypatch):
    from paddle_tpu.distributed import parallel as par

    monkeypatch.setattr("paddle_tpu.distributed.env.get_world_size",
                        lambda: 2)
    calls = []
    monkeypatch.setattr(
        "paddle_tpu.distributed.collective.all_reduce",
        lambda t, op=None, **kw: calls.append(t))
    model = _dp_backward(find_unused=True)
    model.apply_collective_grads()
    # every trainable param (incl. the unused head, zero-filled) is on the
    # wire — since grad_comm they travel coalesced, so the bucket count is
    # what crosses, and it must carry ALL params' elements
    n_params = len(list(model.parameters()))
    assert 1 <= len(calls) < n_params
    wired = sum(t._value.size for t in calls)
    assert wired == sum(p.size for p in model.parameters())
    assert model._grad_comm.stats["n_params"] == n_params
    for p in model._layers.unused.parameters():
        assert p.grad is not None
        np.testing.assert_allclose(p.grad.numpy(), 0.0)


def test_unused_parameters_without_flag_raise(monkeypatch):
    monkeypatch.setattr("paddle_tpu.distributed.env.get_world_size",
                        lambda: 2)
    model = _dp_backward(find_unused=False)
    with pytest.raises(RuntimeError, match="find_unused_parameters"):
        model.apply_collective_grads()


class TestFlagTail:
    """VERDICT r3 missing #7: the reference flag tail with real TPU
    analogs — verbosity, communicator defaults, loss-scaling floor."""

    def test_flag_tail_present_and_settable(self):
        names = ["FLAGS_v", "FLAGS_fraction_of_cpu_memory_to_use",
                 "FLAGS_paddle_num_threads", "FLAGS_sort_sum_gradient",
                 "FLAGS_communicator_max_merge_var_num",
                 "FLAGS_min_loss_scaling", "FLAGS_use_pinned_memory"]
        got = paddle.get_flags(names)
        assert set(got) == set(names)
        try:
            paddle.set_flags({"FLAGS_fraction_of_cpu_memory_to_use": 0.5})
            assert paddle.get_flags(
                ["FLAGS_fraction_of_cpu_memory_to_use"]
            )["FLAGS_fraction_of_cpu_memory_to_use"] == 0.5
        finally:
            paddle.set_flags({"FLAGS_fraction_of_cpu_memory_to_use": 1.0})

    def test_flags_v_drives_logger_level(self):
        import logging

        paddle.set_flags({"FLAGS_v": 2})
        assert logging.getLogger("paddle_tpu").level == logging.DEBUG
        paddle.set_flags({"FLAGS_v": 0})
        assert logging.getLogger("paddle_tpu").level == logging.WARNING

    def test_communicator_reads_flag_defaults(self):
        from paddle_tpu.distributed.ps import LocalPs
        from paddle_tpu.distributed.ps.communicator import Communicator

        class S:
            a_sync = True
            a_sync_configs = {}

        paddle.set_flags({"FLAGS_communicator_max_merge_var_num": 7})
        try:
            comm = Communicator.create(LocalPs(), S())
            assert comm.max_merge == 7
        finally:
            paddle.set_flags({"FLAGS_communicator_max_merge_var_num": 20})

    def test_min_loss_scaling_floor(self):
        from paddle_tpu.amp import GradScaler

        paddle.set_flags({"FLAGS_min_loss_scaling": 64.0})
        try:
            s = GradScaler(enable=True, init_loss_scaling=128.0,
                           decr_ratio=0.25, decr_every_n_nan_or_inf=1)
            s._on_bad_step()  # 128 * 0.25 = 32 < floor -> clamp to 64
            assert s._scale == 64.0
            s._on_bad_step()  # stays at the floor
            assert s._scale == 64.0
        finally:
            paddle.set_flags({"FLAGS_min_loss_scaling": 1.0})

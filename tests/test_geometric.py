"""paddle.geometric message passing (reference: graph_send_recv_op)."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import geometric as G


def _graph():
    # edges: 0->2, 1->2, 1->0
    x = paddle.to_tensor(np.array([[1., 2.], [3., 4.], [5., 6.]], "f4"))
    src = paddle.to_tensor(np.array([0, 1, 1]))
    dst = paddle.to_tensor(np.array([2, 2, 0]))
    return x, src, dst


def test_send_u_recv_sum_mean_max():
    x, src, dst = _graph()
    out = G.send_u_recv(x, src, dst, "sum").numpy()
    np.testing.assert_allclose(out[2], [4., 6.])   # x0 + x1
    np.testing.assert_allclose(out[0], [3., 4.])   # x1
    np.testing.assert_allclose(out[1], 0.0)        # no in-edges
    mean = G.send_u_recv(x, src, dst, "mean").numpy()
    np.testing.assert_allclose(mean[2], [2., 3.])
    mx = G.send_u_recv(x, src, dst, "max").numpy()
    np.testing.assert_allclose(mx[2], [3., 4.])
    np.testing.assert_allclose(mx[1], 0.0)         # empty segment zeroed


def test_send_ue_recv_edge_features():
    x, src, dst = _graph()
    e = paddle.to_tensor(np.array([10., 20., 30.], "f4"))
    out = G.send_ue_recv(x, e, src, dst, "add", "sum").numpy()
    np.testing.assert_allclose(out[2], [(1 + 10) + (3 + 20),
                                        (2 + 10) + (4 + 20)])


def test_segment_ops():
    data = paddle.to_tensor(np.array([[1.], [2.], [3.], [4.]], "f4"))
    ids = paddle.to_tensor(np.array([0, 0, 1, 1]))
    np.testing.assert_allclose(G.segment_sum(data, ids).numpy()[:, 0],
                               [3., 7.])
    np.testing.assert_allclose(G.segment_mean(data, ids).numpy()[:, 0],
                               [1.5, 3.5])
    np.testing.assert_allclose(G.segment_max(data, ids).numpy()[:, 0],
                               [2., 4.])
    np.testing.assert_allclose(G.segment_min(data, ids).numpy()[:, 0],
                               [1., 3.])


def test_grad_through_send_u_recv():
    x, src, dst = _graph()
    x.stop_gradient = False
    G.send_u_recv(x, src, dst, "sum").sum().backward()
    # node 0 used once, node 1 twice, node 2 never
    np.testing.assert_allclose(x.grad.numpy()[:, 0], [1., 2., 0.])

"""Kernel-vs-reference property grid for the ISSUE 13 pallas kernels.

All four families in interpret mode (conftest's 8-device CPU platform):
fused dequant+update, blockwise codec, flash attention (independent
q/k blocks), quant_matmul (tuned tiles + deterministic seeds). The
equivalence contract under test: codec payload bits EXACT; fused update
within 1 ulp of the jnp composition per application (XLA fma-contraction
freedom between the two graph shapes — see ops/pallas/fused_update.py).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu.distributed import grad_comm as gc
from paddle_tpu.framework import flags
from paddle_tpu.ops.pallas import autotune as at
from paddle_tpu.ops.pallas import codec as pc
from paddle_tpu.ops.pallas import fused_update as fu

import jax
import jax.numpy as jnp


def assert_ulp(a, b, max_ulp=1, msg=""):
    """Elementwise ulp distance between two same-dtype float arrays."""
    a, b = np.asarray(a), np.asarray(b)
    assert a.dtype == b.dtype and a.shape == b.shape, (a.dtype, b.dtype)
    kind = {2: np.int16, 4: np.int32, 8: np.int64}[a.dtype.itemsize]
    ai, bi = a.view(kind), b.view(kind)
    # map sign-magnitude float ordering onto two's complement ints
    ai = np.where(ai < 0, np.array(-(2 ** (a.dtype.itemsize * 8 - 1)),
                                   kind) - ai, ai)
    bi = np.where(bi < 0, np.array(-(2 ** (a.dtype.itemsize * 8 - 1)),
                                   kind) - bi, bi)
    d = np.abs(ai.astype(np.int64) - bi.astype(np.int64))
    assert d.max() <= max_ulp, f"{msg} max ulp {d.max()} at {d.argmax()}"


def _optimizer(kind_name, params):
    mk = {
        "SGD": lambda: opt.SGD(learning_rate=1e-3, parameters=params),
        "Momentum": lambda: opt.Momentum(learning_rate=1e-3, momentum=0.9,
                                         use_nesterov=True,
                                         parameters=params),
        "Adam": lambda: opt.Adam(learning_rate=1e-3, parameters=params),
        "AdamW": lambda: opt.AdamW(learning_rate=1e-3, weight_decay=0.01,
                                   parameters=params),
    }
    return mk[kind_name]()


def _slots_for(o, n, seed):
    rs = np.random.RandomState(seed)
    slots = {}
    for k, v in o._init_slots(jnp.zeros((1,), jnp.float32)).items():
        if np.shape(v) == ():
            slots[k] = v
        elif k == "moment2":  # second moments are non-negative
            slots[k] = jnp.abs(jnp.asarray(rs.randn(n), jnp.float32)) * 0.01
        else:
            slots[k] = jnp.asarray(rs.randn(n), jnp.float32) * 0.01
    return slots


# --------------------------------------------------- fused update vs jnp

@pytest.mark.parametrize("kind_name", ["SGD", "Momentum", "Adam", "AdamW"])
@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
@pytest.mark.parametrize("n", [128, 1000, 12345])   # odd, non-row-aligned
def test_fused_update_matches_bucket_fn(kind_name, dtype, n):
    lin = nn.Linear(4, 4)
    o = _optimizer(kind_name, lin.parameters())
    kind, hyper = fu.rule_spec(o)
    wd = 0.01 if kind_name == "AdamW" else 0.0
    rs = np.random.RandomState(n)
    p = jnp.asarray(rs.randn(n), jnp.dtype(dtype))
    g = jnp.asarray(rs.randn(n), jnp.float32)
    slots = _slots_for(o, n, n + 1)
    lr = jnp.asarray(1e-3, jnp.float32)

    fused = jax.jit(lambda p, g, s, lr: fu.fused_update_flat(
        p, g, s, lr, kind=kind, hyper=hyper, lm=1.0, wd=wd))

    def ref(p, g, s, lr):          # FusedFlatUpdater._bucket_fn's body
        new_p, new_s = o._update(p, g.astype(p.dtype), s, lr, 1.0, wd)
        return new_p.astype(p.dtype), new_s

    pa, sa = fused(p, g, dict(slots), lr)
    pb, sb = jax.jit(ref)(p, g, dict(slots), lr)
    assert pa.dtype == pb.dtype == p.dtype
    if dtype == "float32":
        assert_ulp(pa, pb, 8, f"{kind_name} params")
        # fma freedom touches only isolated elements — the overwhelming
        # majority must be bit-equal
        eq = (np.asarray(pa) == np.asarray(pb)).mean()
        assert eq > 0.999, eq
    else:  # bf16 rounding collapses sub-ulp fma differences
        assert (np.asarray(pa.astype(jnp.float32))
                == np.asarray(pb.astype(jnp.float32))).all()
    assert set(sa) == set(sb)
    for k in sa:
        if np.shape(sa[k]) == ():
            assert float(sa[k]) == float(sb[k]), k
        else:
            assert_ulp(sa[k], sb[k], 8, f"{kind_name} slot {k}")


@pytest.mark.parametrize("codec", ["int8_block", "fp8_block"])
@pytest.mark.parametrize("with_residual", [False, True])
def test_fused_dequant_update_matches_decode_then_update(codec,
                                                         with_residual):
    n, bs, world = 5000, 1024, 2
    rs = np.random.RandomState(3)
    flat = jnp.asarray(rs.randn(n), jnp.float32)
    scales = gc.block_scales(gc.block_absmax(flat, bs), codec)
    q = gc.block_encode(flat, scales, bs, codec)
    residual = (jnp.asarray(rs.randn(n), jnp.float32) * 1e-3
                if with_residual else None)
    lin = nn.Linear(4, 4)
    o = _optimizer("Adam", lin.parameters())
    kind, hyper = fu.rule_spec(o)
    p = jnp.asarray(rs.randn(n), jnp.float32)
    slots = _slots_for(o, n, 4)
    lr = jnp.asarray(1e-3, jnp.float32)

    fused = jax.jit(lambda p, q, s, sl, lr: fu.fused_dequant_update_flat(
        p, q, s, world, sl, lr, kind=kind, hyper=hyper, block_size=bs,
        residual=residual))

    def ref(p, q, s, sl, lr):
        g = gc.block_decode(q, s, world, jnp.float32, n)
        if residual is not None:
            g = (g.astype(jnp.float32) + residual).astype(jnp.float32)
        new_p, new_s = o._update(p, g.astype(p.dtype), sl, lr, 1.0, 0.0)
        return new_p.astype(p.dtype), new_s

    pa, sa = fused(p, q, scales, dict(slots), lr)
    pb, sb = jax.jit(ref)(p, q, scales, dict(slots), lr)
    assert_ulp(pa, pb, 8, "dequant params")
    for k in ("moment1", "moment2"):
        assert_ulp(sa[k], sb[k], 8, k)


def test_fused_dequant_ragged_block_size_falls_back():
    n, bs = 1000, 96          # 96 % 128 != 0 -> jnp decode + fused update
    rs = np.random.RandomState(5)
    flat = jnp.asarray(rs.randn(n), jnp.float32)
    scales = gc.block_scales(gc.block_absmax(flat, bs), "int8_block")
    q = gc.block_encode(flat, scales, bs, "int8_block")
    lin = nn.Linear(4, 4)
    o = _optimizer("SGD", lin.parameters())
    kind, hyper = fu.rule_spec(o)
    p = jnp.asarray(rs.randn(n), jnp.float32)
    lr = jnp.asarray(1e-3, jnp.float32)
    pa, _ = fu.fused_dequant_update_flat(p, q, scales, 2, {}, lr,
                                         kind=kind, hyper=hyper,
                                         block_size=bs)
    g = gc.block_decode(q, scales, 2, jnp.float32, n)
    pb, _ = o._update(p, g, {}, lr, 1.0, 0.0)
    assert_ulp(pa, pb.astype(p.dtype), 8)


def test_fused_updater_use_kernel_step_parity():
    """FusedFlatUpdater(use_kernel=True) vs the jnp path: bit-identical
    first step, ulp-bounded trajectory (fma freedom compounds across
    steps but never grows past a few ulp)."""
    rs = np.random.RandomState(0)
    x = paddle.to_tensor(rs.randn(16, 32).astype("f4"))

    def run(use_kernel, steps):
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(32, 64), nn.ReLU(), nn.Linear(64, 8))
        o = opt.AdamW(learning_rate=1e-3, weight_decay=0.01,
                      parameters=net.parameters())
        from paddle_tpu.optimizer.fused import FusedFlatUpdater

        fused = FusedFlatUpdater(o, net.parameters(),
                                 use_kernel=use_kernel)
        for _ in range(steps):
            net(x).sum().backward()
            fused.step()
            for p in net.parameters():
                p.clear_gradient()
        return [np.asarray(p._value) for p in net.parameters()]

    for a, b in zip(run(False, 1), run(True, 1)):
        assert (a == b).all()          # single step: bit-identical
    for a, b in zip(run(False, 3), run(True, 3)):
        assert_ulp(a, b, 16, "3-step trajectory")


def test_fused_updater_kernel_sharded_step_parity(monkeypatch):
    """step_sharded (ZeRO-2 shape) with the kernel path computes the
    same owned-shard update as the jnp path — the padded-shard geometry
    goes through the same fused kernel."""
    from paddle_tpu.distributed import collective as coll
    from paddle_tpu.framework.tensor import Tensor
    from paddle_tpu.optimizer.fused import FusedFlatUpdater

    rs = np.random.RandomState(1)

    def run(use_kernel):
        paddle.seed(1)
        net = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4))
        o = opt.Adam(learning_rate=1e-3, parameters=net.parameters())
        params = [p for p in net.parameters() if not p.stop_gradient]
        fused = FusedFlatUpdater(o, params, use_kernel=use_kernel)
        g_rs = np.random.RandomState(2)
        for p in params:
            p.grad = Tensor(g_rs.standard_normal(p.shape)
                            .astype(np.float32) * 1e-2)
        shards = []

        def fake_all_gather(tl, t, group=None, **kw):
            # capture this rank's updated shard; hand back a full-size
            # buffer so _scatter_params can proceed
            shards.append(np.asarray(t._value))
            return Tensor(np.concatenate([np.asarray(t._value)] * 2),
                          _internal=True)

        monkeypatch.setattr(coll, "all_gather", fake_all_gather)
        fused.step_sharded(rank=0, world=2)
        return shards

    for a, b in zip(run(False), run(True)):
        assert_ulp(a, b, 8)


# ------------------------------------------------------------ codec kernels

@pytest.mark.parametrize("codec", ["int8_block", "fp8_block"])
@pytest.mark.parametrize("n,bs", [(5000, 1024), (128, 128), (777, 128),
                                  (4096, 512)])
def test_codec_kernels_bit_identical(codec, n, bs):
    rs = np.random.RandomState(n + bs)
    flat = jnp.asarray(rs.randn(n), jnp.float32)
    scales = gc.block_scales(gc.block_absmax(flat, bs), codec)
    qa = pc.block_encode(flat, scales, bs, codec)
    qb = gc.block_encode(flat, scales, bs, codec)
    assert qa.dtype == qb.dtype and qa.shape == qb.shape
    assert (np.asarray(qa) == np.asarray(qb)).all()
    da = pc.block_decode(qa, scales, 2, jnp.float32, n)
    db = gc.block_decode(qb, scales, 2, jnp.float32, n)
    assert (np.asarray(da) == np.asarray(db)).all()


def test_codec_ragged_block_size_falls_back_to_jnp():
    n, bs = 500, 96
    rs = np.random.RandomState(9)
    flat = jnp.asarray(rs.randn(n), jnp.float32)
    scales = gc.block_scales(gc.block_absmax(flat, bs), "int8_block")
    qa = pc.block_encode(flat, scales, bs, "int8_block")
    qb = gc.block_encode(flat, scales, bs, "int8_block")
    assert (np.asarray(qa) == np.asarray(qb)).all()
    da = pc.block_decode(qa, scales, 4, jnp.bfloat16, n)
    db = gc.block_decode(qb, scales, 4, jnp.bfloat16, n)
    assert (np.asarray(da.astype(jnp.float32))
            == np.asarray(db.astype(jnp.float32))).all()


def test_codec_kernels_under_shard_map():
    """world>1 wrap: the codec kernels run inside shard_map (where the
    traced ZeRO-2 path uses them on TPU) without vma/partitioning
    crashes, and match the jnp pair per shard."""
    from paddle_tpu.distributed import mesh as mesh_mod

    mesh = mesh_mod.build_mesh({"data": 2}, devices=jax.devices()[:2])
    from jax.sharding import PartitionSpec as P

    n, bs = 2048, 128
    rs = np.random.RandomState(11)
    flat = jnp.asarray(rs.randn(2 * n), jnp.float32)

    def per_shard(x):
        scales = gc.block_scales(gc.block_absmax(x, bs), "int8_block")
        q = pc.block_encode(x, scales, bs, "int8_block")
        return pc.block_decode(q, scales, 1, jnp.float32, n)

    out = mesh_mod.compat_shard_map(per_shard, mesh, (P("data"),),
                                    P("data"))(flat)

    def per_shard_ref(x):
        scales = gc.block_scales(gc.block_absmax(x, bs), "int8_block")
        q = gc.block_encode(x, scales, bs, "int8_block")
        return gc.block_decode(q, scales, 1, jnp.float32, n)

    ref = mesh_mod.compat_shard_map(per_shard_ref, mesh, (P("data"),),
                                    P("data"))(flat)
    assert (np.asarray(out) == np.asarray(ref)).all()


def test_fused_update_under_shard_map():
    from paddle_tpu.distributed import mesh as mesh_mod
    from jax.sharding import PartitionSpec as P

    mesh = mesh_mod.build_mesh({"data": 2}, devices=jax.devices()[:2])
    n = 1024
    rs = np.random.RandomState(12)
    p = jnp.asarray(rs.randn(2 * n), jnp.float32)
    g = jnp.asarray(rs.randn(2 * n), jnp.float32)
    lr = jnp.asarray(1e-3, jnp.float32)

    def shard_update(p, g):
        return fu.fused_update_flat(p, g, {}, lr, kind="sgd", hyper={})[0]

    out = mesh_mod.compat_shard_map(shard_update, mesh,
                                    (P("data"), P("data")),
                                    P("data"))(p, g)
    ref = np.asarray(p) - 1e-3 * np.asarray(g)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-6,
                               atol=1e-7)


# --------------------------------------------------------- flash attention

def _ref_attn(q, k, v, causal):
    import math

    scale = 1.0 / math.sqrt(q.shape[-1])
    logits = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    if causal:
        ql, kl = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((ql, kl), bool), k=kl - ql)
        logits = jnp.where(mask, logits, -1e30)
    p = jax.nn.softmax(logits, -1)
    return jnp.einsum("bhqk,bkhd->bqhd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("dtype,tol", [("float32", 1e-5),
                                       ("bfloat16", 2e-2)])
@pytest.mark.parametrize("s,bq,bk", [(96, 32, 16), (160, 16, 32),
                                     (128, 64, 32)])
def test_flash_independent_blocks_grid(causal, dtype, tol, s, bq, bk):
    from paddle_tpu.ops.flash_attention import flash_attention_val

    rs = np.random.RandomState(s + bq)
    mk = lambda: jnp.asarray(rs.randn(2, s, 2, 32), jnp.dtype(dtype))
    q, k, v = mk(), mk(), mk()
    out = flash_attention_val(q, k, v, causal=causal, block_q=bq,
                              block_k=bk)
    ref = _ref_attn(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=tol, atol=tol)


def test_flash_supported_independent_blocks():
    from paddle_tpu.ops.flash_attention import flash_attention_supported

    assert flash_attention_supported((2, 96, 4, 64), block_q=32,
                                     block_k=16)
    assert not flash_attention_supported((2, 96, 4, 64), block_q=64,
                                         block_k=32)   # 96 % 64 != 0
    assert not flash_attention_supported((2, 96, 4, 64), block_q=32,
                                         block_k=7)    # < 8
    assert flash_attention_supported((2, 128, 4, 64))  # ladder path


def test_flash_tuned_dispatch_consults_cache():
    """A cache entry with an asymmetric (block_q, block_k) winner is
    applied under the flag (and produces reference numerics); an entry
    that no longer divides the live seq len falls back to the ladder."""
    from paddle_tpu.ops.flash_attention import (flash_attention_val,
                                                flash_block_choice)

    rs = np.random.RandomState(7)
    q = jnp.asarray(rs.randn(1, 128, 2, 32), jnp.float32)
    c = at.TuneCache()
    c.put(at.cache_key("flash_attention", (1, 128, 2, 32),
                       "float32-causal"),
          {"block_q": 32, "block_k": 64})
    flags.set_flags({"FLAGS_kernel_autotune": True})
    try:
        at.reset_runtime_cache(c)
        choice = flash_block_choice((1, 128, 2, 32))
        assert choice == {"block_q": 32, "block_k": 64, "source": "tuned"}
        out = flash_attention_val(q, q, q, causal=True)
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(_ref_attn(q, q, q, True)),
                                   rtol=1e-5, atol=1e-5)
        # 96 shares the 128 bucket but 96 % 64 != 0 -> ladder fallback
        q96 = jnp.asarray(rs.randn(1, 96, 2, 32), jnp.float32)
        choice96 = flash_block_choice((1, 96, 2, 32))
        assert choice96["source"] == "fallback"
        out96 = flash_attention_val(q96, q96, q96, causal=True)
        np.testing.assert_allclose(
            np.asarray(out96), np.asarray(_ref_attn(q96, q96, q96, True)),
            rtol=1e-5, atol=1e-5)
    finally:
        flags.set_flags({"FLAGS_kernel_autotune": False})
        at.reset_runtime_cache()


# ------------------------------------------------------------- quant_matmul

def test_quant_matmul_tuned_tiles_dispatch():
    from paddle_tpu.ops.quant_matmul import quant_matmul, quantize_int8

    rs = np.random.RandomState(2)
    x = jnp.asarray(rs.randn(128, 256).astype("f4"))
    w = jnp.asarray(rs.randn(256, 128).astype("f4"))
    qw, s = quantize_int8(w)
    ref = np.asarray(x) @ (np.asarray(qw, np.float32) * np.asarray(s))
    c = at.TuneCache()
    c.put(at.cache_key("quant_matmul", (128, 256, 128), jnp.float32),
          {"block_m": 64, "block_n": 64, "block_k": 128})
    flags.set_flags({"FLAGS_kernel_autotune": True})
    try:
        at.reset_runtime_cache(c)
        out = quant_matmul(x, qw, s)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4,
                                   atol=1e-3)
    finally:
        flags.set_flags({"FLAGS_kernel_autotune": False})
        at.reset_runtime_cache()
    out_def = quant_matmul(x, qw, s)
    np.testing.assert_allclose(np.asarray(out_def), ref, rtol=1e-4,
                               atol=1e-3)


def test_quantize_int8_stochastic_deterministic():
    """Same seed -> same int8 bits, on every call (the pltpu.prng path
    this replaces was backend-dependent and had no CPU lowering at
    all); different seeds -> different roundings; error stays bounded
    by one quantization step."""
    from paddle_tpu.ops.quant_matmul import quantize_int8

    rs = np.random.RandomState(4)
    w = jnp.asarray(rs.randn(64, 128).astype("f4"))
    qa, sa = quantize_int8(w, stochastic=True, seed=42)
    qb, _ = quantize_int8(w, stochastic=True, seed=42)
    qc, _ = quantize_int8(w, stochastic=True, seed=43)
    assert (np.asarray(qa) == np.asarray(qb)).all()
    assert not (np.asarray(qa) == np.asarray(qc)).all()
    deq = np.asarray(qa, np.float32) * np.asarray(sa)
    err = np.abs(deq - np.asarray(w))
    assert (err <= np.asarray(sa) + 1e-6).all()
    # unbiased-ish: mean error well under half a step
    assert abs((deq - np.asarray(w)).mean()) < float(np.asarray(sa).mean())


def test_stable_seed_is_process_stable():
    from paddle_tpu.ops.quant_matmul import stable_seed

    assert stable_seed("linear_0.w_0") == stable_seed("linear_0.w_0")
    assert stable_seed("linear_0.w_0") != stable_seed("linear_1.w_0")
    # pinned crc32 value: would catch a regression back to the salted
    # builtin hash() (different every process) without a subprocess
    assert stable_seed("linear_0.w_0") == 354945823


def test_int8_linear_deterministic_across_conversions():
    from paddle_tpu.quantization import Int8Linear

    paddle.seed(7)
    lin = nn.Linear(32, 16)
    a = Int8Linear(lin, stochastic=True)
    b = Int8Linear(lin, stochastic=True)
    assert (np.asarray(a.qweight._value)
            == np.asarray(b.qweight._value)).all()


# --------------------------------------------------- inference int8 opt-in

def test_predictor_int8_weights_opt_in(tmp_path):
    """Config.enable_int8_weights: imported-model weights go int8 at
    rest (halved bytes, deterministic seeds) with small output error vs
    the fp predictor."""
    from paddle_tpu import inference
    from test_interop_importer import (A_INT, FEED_MINIBATCH, FETCH_LIST,
                                       attr, block_desc, lod_tensor_stream,
                                       op_desc, program_desc, var_desc)

    rs = np.random.RandomState(6)
    w1 = rs.randn(16, 32).astype("f4")
    w2 = rs.randn(32, 4).astype("f4")
    vars_ = [
        var_desc("feed", type_id=FEED_MINIBATCH, persistable=True),
        var_desc("fetch", type_id=FETCH_LIST, persistable=True),
        var_desc("x", dims=(-1, 16)),
        var_desc("w1", dims=(16, 32), persistable=True),
        var_desc("w2", dims=(32, 4), persistable=True),
        var_desc("h0", dims=(-1, 32)), var_desc("h1", dims=(-1, 32)),
        var_desc("out", dims=(-1, 4)),
    ]
    mulattrs = [attr("x_num_col_dims", A_INT, 1),
                attr("y_num_col_dims", A_INT, 1)]
    ops = [
        op_desc("feed", [("X", ["feed"])], [("Out", ["x"])],
                [attr("col", A_INT, 0)]),
        op_desc("mul", [("X", ["x"]), ("Y", ["w1"])], [("Out", ["h0"])],
                mulattrs),
        op_desc("relu", [("X", ["h0"])], [("Out", ["h1"])]),
        op_desc("mul", [("X", ["h1"]), ("Y", ["w2"])], [("Out", ["out"])],
                mulattrs),
        op_desc("fetch", [("X", ["out"])], [("Out", ["fetch"])],
                [attr("col", A_INT, 0)]),
    ]
    (tmp_path / "__model__").write_bytes(
        program_desc([block_desc(0, vars_, ops)]))
    with open(tmp_path / "__params__", "wb") as f:
        for arr in (w1, w2):        # combined persistables, sorted names
            f.write(lod_tensor_stream(arr))

    xs = rs.randn(8, 16).astype("f4")
    pred = inference.create_predictor(inference.Config(str(tmp_path)))
    ref = pred.run([xs])[0]

    cfg8 = inference.Config(str(tmp_path))
    cfg8.enable_int8_weights()
    assert cfg8.int8_weights()
    pred8 = inference.create_predictor(cfg8)
    art = pred8._artifact
    assert set(art._int8_dtypes) == {"w1", "w2"}
    for name in art._int8_dtypes:
        q, s = art._params[name]
        assert q.dtype == jnp.int8
    out = pred8.run([xs])[0]
    rel = np.abs(out - ref).mean() / (np.abs(ref).mean() + 1e-9)
    assert rel < 0.05, rel

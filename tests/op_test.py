"""OpTest-style harness.

Reference: python/paddle/fluid/tests/unittests/op_test.py (OpTest:280) — per-op
checks: forward vs NumPy semantics, analytic grads vs central finite
differences. Here ops are checked through the eager tape (the dygraph path);
the jit parity suite covers the compiled path.
"""
from __future__ import annotations

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.framework.tensor import Tensor


def check_forward(op, np_ref, arrays, rtol=1e-5, atol=1e-6, **kwargs):
    """op(*Tensors, **kwargs) vs np_ref(*ndarrays)."""
    tensors = [paddle.to_tensor(a) for a in arrays]
    out = op(*tensors, **kwargs)
    ref = np_ref(*arrays)
    if isinstance(out, (tuple, list)):
        for o, r in zip(out, ref):
            np.testing.assert_allclose(o.numpy(), r, rtol=rtol, atol=atol)
    else:
        np.testing.assert_allclose(out.numpy(), ref, rtol=rtol, atol=atol)
    return out


def numeric_grad(f, arrays, idx, eps=1e-2):
    """Central finite differences of scalar-valued f w.r.t. arrays[idx]."""
    base = [a.copy() for a in arrays]
    g = np.zeros_like(base[idx], dtype=np.float64)
    flat = base[idx].reshape(-1)
    gf = g.reshape(-1)
    for i in range(flat.size):
        orig = flat[i]
        flat[i] = orig + eps
        fp = float(f(*base))
        flat[i] = orig - eps
        fm = float(f(*base))
        flat[i] = orig
        gf[i] = (fp - fm) / (2 * eps)
    return g


def check_grad(op, arrays, grad_idx=None, rtol=5e-3, atol=5e-4, reduce_fn=None, **kwargs):
    """Tape gradient of sum(op(...)) vs finite differences."""
    if grad_idx is None:
        grad_idx = range(len(arrays))
    arrays = [np.asarray(a, dtype=np.float64).astype(np.float32) for a in arrays]

    def scalar_np(*arrs):
        ts = [paddle.to_tensor(a.astype(np.float32)) for a in arrs]
        out = op(*ts, **kwargs)
        if reduce_fn is not None:
            return reduce_fn(out).numpy()
        if isinstance(out, (tuple, list)):
            out = out[0]
        return out.sum().numpy()

    tensors = [paddle.to_tensor(a, stop_gradient=False) for a in arrays]
    out = op(*tensors, **kwargs)
    if reduce_fn is not None:
        loss = reduce_fn(out)
    else:
        if isinstance(out, (tuple, list)):
            out = out[0]
        loss = out.sum()
    loss.backward()
    for i in grad_idx:
        assert tensors[i].grad is not None, f"missing grad for input {i}"
        ng = numeric_grad(scalar_np, arrays, i)
        np.testing.assert_allclose(
            tensors[i].grad.numpy(), ng, rtol=rtol, atol=atol,
            err_msg=f"analytic vs numeric grad mismatch for input {i}",
        )

"""Prefix-cached, sampled, speculative serving (ISSUE 16).

Contracts pinned here:
- CounterKeyStream / BatchSampler: per-request counter-based RNG streams
  — a request's token at position i depends only on (sampler seed,
  request identity, i), never on batch placement; temperature=0 IS
  np.argmax (the pre-ISSUE-16 greedy, token-for-token).
- Pool prefix cache: chain-keyed block sharing with refcounts, LRU over
  refcount-0 blocks (evictions counted), copy-on-write before any append
  into a shared block (the sharer's bytes never move), reserve/rollback
  scratch leak-free.
- Engine: cache on/off greedy parity + hit/miss accounting; appending
  past a shared prefix never mutates bytes another live sequence reads
  (mirror == pool.gather bit-exact for BOTH, mid-flight); replica
  eviction + requeue replays top-p sampled requests bit-identically.
- Speculative decode: draft-proposed tokens are verified losslessly —
  outputs are token-for-token the non-speculative sampler's, accepted
  tokens/step > 1 with a self-draft, and zero KV blocks leak even under
  replica chaos.
"""
import threading

import numpy as np
import pytest

from paddle_tpu.framework.random import CounterKeyStream
from paddle_tpu.models import GPTForCausalLM, gpt_presets
from paddle_tpu.serving import (
    BatchSampler, GPTDecodeModel, KVBlockPool, ReplicaSet, RequestQueue,
    SamplingParams, ServeRequest, ServingEngine,
)


@pytest.fixture(autouse=True)
def reset_mesh(fresh_mesh):
    """Same rationale as tests/test_serving.py: clear any ambient mesh a
    prior suite left behind."""


def _mini_cfg(**over):
    kw = dict(hidden_size=32, num_heads=2, num_layers=2, vocab_size=64,
              max_position_embeddings=64)
    kw.update(over)
    return gpt_presets("gpt-test", **kw)


@pytest.fixture(scope="module")
def dm():
    return GPTDecodeModel(GPTForCausalLM(_mini_cfg(), seed=0))


def _pool(dm, codec="fp32", n_blocks=32, block_tokens=8):
    return KVBlockPool(n_blocks=n_blocks, block_tokens=block_tokens,
                       elems_per_token=dm.elems_per_token, codec=codec)


def _drive(engine, max_steps=300):
    for _ in range(max_steps):
        worked = engine.step()
        if not worked and not engine.running and not engine.queue.depth:
            return
    raise AssertionError("engine did not drain")


def _run(dm, prompts, max_new=6, sampling=None, **ekw):
    q = RequestQueue()
    eng = ServingEngine(dm, _pool(dm), q, **ekw)
    reqs = [ServeRequest(prompt_ids=np.asarray(p), max_new_tokens=max_new,
                         request_id=f"r{i}",
                         **({"sampling": sampling} if sampling else {}))
            for i, p in enumerate(prompts)]
    for r in reqs:
        q.submit(r)
    _drive(eng)
    assert all(r.outcome == "completed" for r in reqs)
    return eng, reqs


# ---------------------------------------------------------------------------
# RNG streams + sampler
# ---------------------------------------------------------------------------

class TestCounterKeyStream:
    def test_keys_depend_only_on_identity_and_counter(self):
        import jax.random

        a, b = CounterKeyStream(seed=7), CounterKeyStream(seed=7)
        # query in different orders: same (identity, counter) -> same key
        k1 = a.key("req-x", 3)
        a.key("req-y", 0)
        b.key("req-y", 9)
        k2 = b.key("req-x", 3)
        np.testing.assert_array_equal(jax.random.key_data(k1),
                                      jax.random.key_data(k2))
        # distinct counters and identities give distinct keys
        assert not np.array_equal(jax.random.key_data(a.key("req-x", 4)),
                                  jax.random.key_data(k1))
        assert not np.array_equal(jax.random.key_data(a.key("req-z", 3)),
                                  jax.random.key_data(k1))

    def test_seed_separates_streams(self):
        import jax.random

        assert not np.array_equal(
            jax.random.key_data(CounterKeyStream(0).key("r", 0)),
            jax.random.key_data(CounterKeyStream(1).key("r", 0)))


class TestBatchSampler:
    def _logits(self, rs, n, vocab=64):
        return (rs.randn(n, vocab) * 3).astype(np.float32)

    def test_temperature_zero_is_argmax(self):
        rs = np.random.RandomState(0)
        logits = self._logits(rs, 5)
        s = BatchSampler(seed=0)
        toks = s.sample(logits, [SamplingParams()] * 5,
                        [f"r{i}" for i in range(5)], [0] * 5)
        np.testing.assert_array_equal(toks, np.argmax(logits, axis=-1))

    def test_batch_placement_invariance(self):
        """The token sampled for (request, position) must not depend on
        which other rows share the batch — the eviction/requeue replay
        contract at the sampler level."""
        rs = np.random.RandomState(1)
        logits = self._logits(rs, 4)
        sp = SamplingParams(temperature=0.8, top_k=20, top_p=0.9)
        s = BatchSampler(seed=3)
        full = s.sample(logits, [sp] * 4,
                        ["a", "b", "c", "d"], [5, 6, 7, 8])
        solo = s.sample(logits[2:3], [sp], ["c"], [7])
        assert full[2] == solo[0]
        # and reversed batch order
        rev = s.sample(logits[::-1].copy(), [sp] * 4,
                       ["d", "c", "b", "a"], [8, 7, 6, 5])
        np.testing.assert_array_equal(rev[::-1], full)

    def test_top_k_one_is_argmax(self):
        rs = np.random.RandomState(2)
        logits = self._logits(rs, 3)
        s = BatchSampler(seed=0)
        sp = SamplingParams(temperature=1.5, top_k=1)
        toks = s.sample(logits, [sp] * 3, ["x", "y", "z"], [0, 1, 2])
        np.testing.assert_array_equal(toks, np.argmax(logits, axis=-1))

    def test_top_p_keeps_nucleus_only(self):
        """With one token holding ~all probability mass, any top_p keeps
        exactly that token."""
        logits = np.full((2, 64), -10.0, np.float32)
        logits[0, 17] = 10.0
        logits[1, 42] = 10.0
        s = BatchSampler(seed=5)
        sp = SamplingParams(temperature=1.0, top_p=0.5)
        toks = s.sample(logits, [sp] * 2, ["p", "q"], [0, 0])
        np.testing.assert_array_equal(toks, [17, 42])

    def test_explicit_seed_overrides_request_identity(self):
        rs = np.random.RandomState(3)
        logits = self._logits(rs, 1)
        s = BatchSampler(seed=0)
        sp = SamplingParams(temperature=0.9, seed=123)
        a = s.sample(logits, [sp], ["first-id"], [4])
        b = s.sample(logits, [sp], ["other-id"], [4])
        assert a[0] == b[0]


# ---------------------------------------------------------------------------
# Pool-level prefix cache
# ---------------------------------------------------------------------------

class TestPrefixCachePool:
    def _fill(self, pool, table, n, seed=0):
        rs = np.random.RandomState(seed)
        kv = rs.randn(n, pool.elems_per_token).astype(np.float32)
        pool.append(table, kv)
        return kv

    def test_full_block_sharing_and_refcount(self, dm):
        pool = _pool(dm)
        prompt = np.arange(20, dtype=np.int32)  # 2 full blocks + 4 rows
        a = pool.alloc_table(24, prefix_tokens=prompt)
        assert a.n_tokens == 0 and a.n_shared == 0
        kv = self._fill(pool, a, 20)
        pool.register_prefix(a, prompt)
        assert pool.probe_prefix(prompt) == 20
        b = pool.alloc_table(24, prefix_tokens=prompt)
        # b shares a's blocks: full blocks by id, partial via COW spare
        assert b.n_tokens == 20 and b.n_shared == 3
        assert b.block_ids[:2] == a.block_ids[:2]
        assert b.block_ids[2] == a.block_ids[2] and b.cow_spare is not None
        np.testing.assert_array_equal(pool.gather(b), kv)
        # releasing one sharer must not free the other's data
        pool.free_table(b)
        np.testing.assert_array_equal(pool.gather(a), kv)
        pool.free_table(a)
        assert pool.blocks_in_use == 0
        assert pool.cached_blocks >= 2  # indexed blocks parked in LRU

    def test_lru_eviction_recycles_cold_blocks(self, dm):
        pool = _pool(dm, n_blocks=8)
        prompts = [np.full((8,), i, np.int32) for i in range(7)]
        for i, p in enumerate(prompts):
            t = pool.alloc_table(8, prefix_tokens=p)
            self._fill(pool, t, 8, seed=i)
            pool.register_prefix(t, p)
            pool.free_table(t)
        # 7 distinct one-block prefixes through an 8-block pool: the
        # oldest entries were evicted from the LRU to make room
        assert pool.blocks_in_use == 0
        assert pool.cached_blocks <= 8
        # hottest (= most recent) prefix still resident, coldest gone
        assert pool.probe_prefix(prompts[-1]) == 8

    def test_cow_before_append_preserves_sharer_bytes(self, dm):
        pool = _pool(dm)
        prompt = np.arange(12, dtype=np.int32)  # block0 full, block1: 4 rows
        a = pool.alloc_table(20, prefix_tokens=prompt)
        kv_a = self._fill(pool, a, 12)
        pool.register_prefix(a, prompt)
        b = pool.alloc_table(20, prefix_tokens=prompt)
        assert b.n_shared == 2 and b.cow_spare is not None
        shared_block = b.block_ids[1]
        # b appends past the shared prefix -> COW must fire
        rs = np.random.RandomState(9)
        kv_b_new = rs.randn(3, pool.elems_per_token).astype(np.float32)
        pool.append(b, kv_b_new)
        assert b.block_ids[1] != shared_block  # b moved to its copy
        assert b.n_shared == 1 and b.cow_spare is None
        # a's bytes never moved; b reads prefix + its own suffix
        np.testing.assert_array_equal(pool.gather(a), kv_a)
        np.testing.assert_array_equal(pool.gather(b)[:12], kv_a)
        got_b = pool.gather(b)[12:]
        np.testing.assert_array_equal(
            got_b, kv_b_new)  # fp32 codec: bit-exact
        pool.free_table(a)
        pool.free_table(b)
        assert pool.blocks_in_use == 0

    def test_reserve_rollback_leak_free(self, dm):
        pool = _pool(dm)
        t = pool.alloc_table(10)
        self._fill(pool, t, 10)
        base_blocks = len(t.block_ids)
        pool.reserve(t, 9)  # spec scratch: k+1 lookahead
        assert len(t.block_ids) > base_blocks
        rs = np.random.RandomState(4)
        pool.append(t, rs.randn(9, pool.elems_per_token).astype(np.float32))
        pool.rollback(t, 7)  # reject 7 of the 9 drafted rows
        assert t.n_tokens == 12
        assert len(t.block_ids) == max(base_blocks,
                                       pool.blocks_needed(12))
        pool.free_table(t)
        assert pool.blocks_in_use == 0


# ---------------------------------------------------------------------------
# Engine: cached admission + COW + sampling replay
# ---------------------------------------------------------------------------

class TestEnginePrefixCache:
    def test_cache_on_off_greedy_parity_and_hit_accounting(self, dm):
        from paddle_tpu.serving.engine import _m_prefix_hit, _m_prefix_miss

        rs = np.random.RandomState(0)
        shared = rs.randint(0, 64, (20,))
        hit0, miss0 = _m_prefix_hit.get(), _m_prefix_miss.get()
        eng_on, r_on = _run(dm, [shared, shared, shared], max_new=4)
        assert _m_prefix_hit.get() - hit0 > 0
        assert _m_prefix_miss.get() - miss0 > 0
        _, r_off = _run(dm, [shared, shared, shared], max_new=4,
                        prefix_cache=False)
        assert [r.generated for r in r_on] == [r.generated for r in r_off]
        assert eng_on.pool.blocks_in_use == 0

    def test_cow_pinned_mid_flight_mirror_equals_gather(self, dm):
        """Two live sequences share a prompt prefix; each samples a
        DIFFERENT continuation (distinct request ids). At every step both
        sequences' incremental mirrors must equal pool.gather bit-exactly
        — i.e. appending past the shared prefix copied, never mutated,
        bytes the other sequence still reads."""
        rs = np.random.RandomState(1)
        shared = rs.randint(0, 64, (17,))  # partial tail block: COW fires
        sp = SamplingParams(temperature=1.2, top_k=0, top_p=1.0)
        q = RequestQueue()
        eng = ServingEngine(dm, _pool(dm), q)
        reqs = [ServeRequest(prompt_ids=shared.copy(), max_new_tokens=6,
                             request_id=f"cow{i}", sampling=sp)
                for i in range(2)]
        for r in reqs:
            q.submit(r)
        checked = 0
        for _ in range(300):
            worked = eng.step()
            for s in eng.running:
                np.testing.assert_array_equal(
                    s.mirror[:s.n_past], eng.pool.gather(s.table))
                checked += 1
            if not worked and not eng.running and not q.depth:
                break
        assert checked > 0
        assert all(r.outcome == "completed" for r in reqs)
        # distinct ids -> distinct streams -> the continuations diverged
        # (shared-prefix COW actually exercised divergent appends)
        assert reqs[0].generated != reqs[1].generated
        assert eng.pool.blocks_in_use == 0

    def test_eviction_requeue_replays_bit_identical(self, dm):
        """CHAOS + sampling: a hung replica's top-p requests re-run on
        the survivor and must land the SAME sampled tokens — position-
        keyed streams make replay independent of replica and batch."""
        sp = SamplingParams(temperature=0.9, top_k=16, top_p=0.9)
        rs = np.random.RandomState(2)
        prompts = [rs.randint(0, 64, (6,)) for _ in range(6)]
        # reference: clean single-replica run
        _, ref = _run(dm, prompts, max_new=6, sampling=sp)
        expect = {r.request_id: r.generated for r in ref}

        gate, hung = threading.Event(), threading.Event()

        def hang_hook(eng):
            if eng.running and not gate.is_set():
                hung.set()
                gate.wait(30)

        rset = ReplicaSet(dm, n_replicas=2, n_blocks=32, block_tokens=8,
                          max_batch=2, watchdog_timeout=0.3,
                          pre_step_hooks={0: hang_hook})
        try:
            with rset:
                ids = []
                for i, p in enumerate(prompts):
                    r = ServeRequest(prompt_ids=p, max_new_tokens=6,
                                     request_id=f"r{i}", sampling=sp)
                    assert rset.submit(r)
                    ids.append(r.request_id)
                assert hung.wait(20)
                res = rset.wait(ids, timeout=60)
        finally:
            gate.set()
        assert len(res) == 6
        assert [e["reason"] for e in rset.evictions] == ["hang"]
        replayed = [r for r in res.values() if r.attempts > 0]
        assert replayed, "chaos run must actually replay something"
        for rid, r in res.items():
            assert r.generated == expect[rid], \
                f"{rid} replay diverged (attempts={r.attempts})"


# ---------------------------------------------------------------------------
# Bench plumbing
# ---------------------------------------------------------------------------

class TestPrefixSpecBenchGate:
    def test_gate_new_serve_metrics(self):
        import importlib.util
        import os

        spec = importlib.util.spec_from_file_location(
            "bench_gate", os.path.join(os.path.dirname(__file__), "..",
                                       "tools", "bench_gate.py"))
        bg = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bg)
        assert bg.GATES["serve_cache_hit_tokens_per_s"][1] == "higher"
        assert bg.GATES["serve_spec_tokens_per_step"][1] == "higher"
        base = {"value": 100.0, "device_kind": "cpu", "fallback": "cpu",
                "serve_cache_hit_tokens_per_s": 5000.0,
                "serve_spec_tokens_per_step": 4.0}
        good = dict(base, serve_cache_hit_tokens_per_s=5200.0,
                    serve_spec_tokens_per_step=4.2)
        bad = dict(base, serve_cache_hit_tokens_per_s=1000.0,
                    serve_spec_tokens_per_step=1.5)
        old = {"value": 100.0, "device_kind": "cpu", "fallback": "cpu"}
        traj = [("r1", base)]
        verdicts = {r["metric"]: r["verdict"]
                    for r in bg.gate(good, traj, 0.20)[0]}
        assert verdicts["serve_cache_hit_tokens_per_s"] == "OK"
        assert verdicts["serve_spec_tokens_per_step"] == "OK"
        verdicts = {r["metric"]: r["verdict"]
                    for r in bg.gate(bad, traj, 0.20)[0]}
        assert verdicts["serve_cache_hit_tokens_per_s"] == "REGRESSED"
        assert verdicts["serve_spec_tokens_per_step"] == "REGRESSED"
        # records predating PR 16 SKIP, never fail
        verdicts = {r["metric"]: r["verdict"]
                    for r in bg.gate(old, traj, 0.20)[0]}
        assert verdicts["serve_cache_hit_tokens_per_s"] == "SKIP"
        assert verdicts["serve_spec_tokens_per_step"] == "SKIP"

    def test_artifact_carries_acceptance_claims(self):
        """The committed serve_bench.json must hold the ISSUE 16 numbers
        (regenerate with `python tools/serve_bench.py`)."""
        import json
        import os

        path = os.path.join(os.path.dirname(__file__), "..", "artifacts",
                            "serve_bench.json")
        with open(path) as f:
            rec = json.load(f)
        p = rec["prefix_cache"]
        assert p["speedup"] >= 2.0
        assert p["sequence_match_fraction"] == 1.0
        assert p["prefill_computed_ratio"] < 0.5
        assert rec["serve_cache_hit_tokens_per_s"] > 0
        s = rec["speculative"]
        assert s["lossless"] is True
        assert s["accepted_tokens_per_step"] > 1.0
        assert s["speculative"]["kv_blocks_leaked"] == 0
        assert rec["serve_spec_tokens_per_step"] == \
            s["accepted_tokens_per_step"]


# ---------------------------------------------------------------------------
# Speculative decoding
# ---------------------------------------------------------------------------

class TestSpeculative:
    @pytest.mark.parametrize("sampling", [
        None, SamplingParams(temperature=0.8, top_k=20, top_p=0.95)],
        ids=["greedy", "top_p"])
    def test_lossless_vs_non_speculative(self, dm, sampling):
        rs = np.random.RandomState(3)
        prompts = [rs.randint(0, 64, (6,)) for _ in range(3)]
        _, ref = _run(dm, prompts, max_new=10, sampling=sampling)
        eng, got = _run(dm, prompts, max_new=10, sampling=sampling,
                        draft_model=dm.truncated(1), spec_k=4)
        for a, b in zip(ref, got):
            assert a.generated == b.generated
        assert eng.spec_steps > 0
        assert eng.pool.blocks_in_use == 0

    def test_self_draft_accepts_everything(self, dm):
        """Draft == target: every proposal verifies, so each spec step
        commits k+1 tokens (up to the max_new_tokens tail)."""
        rs = np.random.RandomState(4)
        eng, _ = _run(dm, [rs.randint(0, 64, (6,))], max_new=10,
                      draft_model=dm, spec_k=4)
        aps = eng.spec_emitted / max(1, eng.spec_steps)
        assert aps > 4.0
        assert eng.pool.blocks_in_use == 0

    def test_chaos_with_spec_zero_lost_zero_leaked(self, dm):
        """A crashing replica mid-speculation: every request completes on
        the survivor, outputs match the clean run, and no LIVE replica
        leaks KV blocks (reserve/rollback unwound; the dead replica's
        pool is abandoned with it by design — see engine.drain)."""
        rs = np.random.RandomState(5)
        prompts = [rs.randint(0, 64, (5,)) for _ in range(6)]
        draft = dm.truncated(1)
        _, ref = _run(dm, prompts, max_new=8, draft_model=draft, spec_k=4)
        expect = {r.request_id: r.generated for r in ref}

        state = {"armed": True}

        def crash_hook(eng):
            if eng.running and state["armed"]:
                state["armed"] = False
                raise RuntimeError("injected replica crash")

        rset = ReplicaSet(dm, n_replicas=2, n_blocks=32, block_tokens=8,
                          max_batch=2, pre_step_hooks={0: crash_hook},
                          draft_model=draft, spec_k=4)
        with rset:
            ids = []
            for i, p in enumerate(prompts):
                r = ServeRequest(prompt_ids=p, max_new_tokens=8,
                                 request_id=f"r{i}")
                assert rset.submit(r)
                ids.append(r.request_id)
            res = rset.wait(ids, timeout=60)
        assert len(res) == 6
        assert [e["reason"] for e in rset.evictions] == ["error"]
        for rid, r in res.items():
            assert r.generated == expect[rid]
        live = [e for e in rset.engines if e.alive]
        assert live
        for eng in live:
            assert eng.pool.blocks_in_use == 0, eng.pool.stats()

#!/usr/bin/env python
"""Kernel bench: tuned-vs-default timings per pallas kernel family.

    python tools/kernel_bench.py                 # sweep + report
    python tools/kernel_bench.py --seed-cache    # also persist winners to
                                                 # artifacts/kernel_tune_cache.json
    python tools/kernel_bench.py --out PATH      # JSON destination
                                                 # (default artifacts/kernel_bench.json)

Runs the autotune harness (paddle_tpu/ops/pallas/autotune.py) over one
representative problem per family — flash_attention, quant_matmul,
fused_update, block_codec — and writes the per-kernel report:

  {"device_kind", "platform", "kernels": {family: {n_candidates,
   n_validated, default_params, winner_params, default_ms, winner_ms,
   roofline_floor_s, timed}}}

On a live TPU the harness times compiled Mosaic executions; anywhere else
(CPU tier-1, AOT hosts) candidates are validated against the jnp
reference but never timed — the interpret contract — so winner fields
stay null. ``--seed-cache`` swaps in a DETERMINISTIC SYNTHETIC timer
(labelled as such in the output): it ranks candidates by a documented
tile-preference formula floored at 3x the cost_model roofline, which
exercises the full select→validate→persist pipeline and produces the
committed demonstration cache. Synthetic timings never pose as
measurements: the JSON carries ``"timer": "synthetic"`` and real TPU runs
overwrite the cache with measured winners.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _problems():
    """One representative problem per family: (family, args tuple)."""
    import jax.numpy as jnp
    import numpy as np

    import paddle_tpu  # noqa: F401  (platform setup)
    import paddle_tpu.optimizer as opt
    import paddle_tpu.nn as nn
    from paddle_tpu.distributed import grad_comm as gc
    from paddle_tpu.ops.quant_matmul import quantize_int8

    rs = np.random.RandomState(0)
    out = []

    q = jnp.asarray(rs.randn(1, 512, 4, 64), jnp.float32)
    out.append(("flash_attention", (q, q, q, True)))

    x = jnp.asarray(rs.randn(256, 512), jnp.float32)
    w = jnp.asarray(rs.randn(512, 256), jnp.float32)
    qw, scales = quantize_int8(w)
    out.append(("quant_matmul", (x, qw, scales)))

    n = 1 << 18
    lin = nn.Linear(4, 4)
    o = opt.AdamW(learning_rate=1e-3, weight_decay=0.01,
                  parameters=lin.parameters())
    from paddle_tpu.ops.pallas.fused_update import rule_spec

    kind, hyper = rule_spec(o)
    p = jnp.asarray(rs.randn(n), jnp.float32)
    g = jnp.asarray(rs.randn(n), jnp.float32)
    slots = {"moment1": jnp.zeros((n,), jnp.float32),
             "moment2": jnp.zeros((n,), jnp.float32),
             "beta1_pow": jnp.ones((), jnp.float32),
             "beta2_pow": jnp.ones((), jnp.float32)}
    lr = jnp.asarray(1e-3, jnp.float32)
    out.append(("fused_update", (p, g, slots, lr, kind, hyper, 1.0, 0.01)))

    flat = jnp.asarray(rs.randn(1 << 18), jnp.float32)
    am = gc.block_absmax(flat, 1024)
    sc = gc.block_scales(am, "int8_block")
    out.append(("block_codec",
                (flat, sc, 1024, "int8_block", 2, int(flat.shape[0]))))
    return out


def _synthetic_timer(floor_s: float):
    """Deterministic demonstration timer: bigger tiles/blocks 'run
    faster' (the usual on-device shape up to VMEM limits), floored at
    3x the roofline so the noise rejection never fires on it. Purely a
    ranking function — the numbers it returns are NOT measurements."""
    def timer(params, fn):
        weight = sum(float(v) for v in params.values()
                     if isinstance(v, (int, float)))
        return 3.0 * floor_s * (1.0 + 64.0 / max(weight, 1.0))

    return timer


def run(seed_cache: bool = False) -> dict:
    from paddle_tpu.cost_model import kernel_roofline
    from paddle_tpu.ops import pallas as pk

    at = pk.autotune
    device = at.current_device_kind()
    report = {"device_kind": device,
              "timer": ("synthetic" if seed_cache else "device"),
              "kernels": {}}
    cache = at.TuneCache.load(at.artifact_cache_path()) if seed_cache \
        else None
    for family, args in _problems():
        fam = at.FAMILIES[family]
        timer = None
        if seed_cache:
            flops, nbytes = fam.cost(*args)
            timer = _synthetic_timer(kernel_roofline(flops, nbytes, device))
        t0 = time.perf_counter()
        rep = at.autotune(family, *args, timer=timer,
                          cache=cache, persist=seed_cache,
                          cache_path=(at.artifact_cache_path()
                                      if seed_cache else None))
        report["kernels"][family] = {
            "n_candidates": rep["n_candidates"],
            "n_validated": rep["n_validated"],
            "n_timed": rep["n_timed"],
            "default_params": rep["default_params"],
            "winner_params": rep["winner_params"],
            "default_ms": rep["default_ms"],
            "winner_ms": rep["winner_ms"],
            "roofline_floor_s": rep["roofline_floor_s"],
            "persisted": rep["persisted"],
            "sweep_wall_s": round(time.perf_counter() - t0, 2),
        }
    if seed_cache and cache is not None:
        # ensure the runtime copy matches the committed artifact
        cache.save(at.runtime_cache_path())
        at.reset_runtime_cache()
    return report


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out",
                    default=os.path.join(REPO, "artifacts",
                                         "kernel_bench.json"))
    ap.add_argument("--seed-cache", action="store_true",
                    help="persist winners (synthetic demonstration timer) "
                         "into artifacts/kernel_tune_cache.json")
    args = ap.parse_args(argv)

    report = run(seed_cache=args.seed_cache)
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(args.out, "w", encoding="utf-8") as f:
        json.dump(report, f, indent=1, sort_keys=True)
        f.write("\n")
    for fam, r in sorted(report["kernels"].items()):
        win = (f"winner={r['winner_params']} "
               f"({r['winner_ms']:.3f}ms vs default "
               f"{r['default_ms']:.3f}ms)"
               if r["winner_ms"] and r["default_ms"] else
               "validated-only (no device timing)")
        print(f"kernel_bench: {fam:<16} {r['n_validated']}/"
              f"{r['n_candidates']} validated · {win}")
    print(f"kernel_bench: wrote {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""AOT-validate the Wide&Deep compiled pass step for TPU.

The bench's widedeep mode was rewired to CompiledPassStep after the
tunnel wedged; before the delta window spends its budget, prove the
exact program (gather + dense fwd/bwd + Adam + device adagrad at the
bench's TPU shapes) passes the REAL XLA-TPU compiler, and record its
memory/step estimates. Writes artifacts/widedeep_aot_probe.json.
"""
from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental import topologies
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu.distributed.ps import LocalPs
    from paddle_tpu.distributed.ps.heter_cache import DevicePassCache
    from paddle_tpu.distributed.ps.heter_trainer import CompiledPassStep
    from paddle_tpu.framework.target import force_target
    from paddle_tpu.jit.aot import estimate_step_seconds

    batch, slots, vocab, dim = 512, 16, 10000, 8  # the bench's TPU shapes

    ps = LocalPs()
    ps.create_table(0, dim=dim, init_range=0.01, lr=0.1,
                    optimizer="adagrad")
    cache = DevicePassCache(ps, 0, lr=0.1)
    deep = paddle.nn.Sequential(
        paddle.nn.Linear(dim * slots, 64), paddle.nn.ReLU(),
        paddle.nn.Linear(64, 1))
    optim = paddle.optimizer.Adam(learning_rate=1e-3,
                                  parameters=deep.parameters())
    step = CompiledPassStep(
        cache, deep, optim,
        lambda out, labels: F.binary_cross_entropy_with_logits(
            out[:, 0], labels),
        table_optimizer="adagrad", table_lr=0.1)
    step._build()

    fm, opt = step._fm, optim
    train_p, frozen_p = fm.split_values(fm.param_values())
    opt_state = opt.init_state_tree(train_p)

    topo = topologies.get_topology_desc(platform="tpu",
                                        topology_name="v5e:2x4")
    mesh1 = Mesh(np.asarray(topo.devices[:1]).reshape(1), ("x",))
    sh = NamedSharding(mesh1, P())
    SDS = jax.ShapeDtypeStruct

    def sds(v):
        return SDS(tuple(np.shape(v)), jnp.asarray(v).dtype, sharding=sh)

    args = (
        tuple(sds(v) for v in train_p),
        tuple(sds(v) for v in frozen_p),
        [sds(v) for v in fm.buffer_values()],
        [{k: sds(x) for k, x in s.items()} for s in opt_state],
        SDS((vocab, dim), jnp.float32, sharding=sh),   # rows slab
        SDS((vocab, dim), jnp.float32, sharding=sh),   # gacc/adagrad state
        SDS((batch, slots), jnp.int32, sharding=sh),   # slot indices
        SDS((batch,), jnp.float32, sharding=sh),       # labels
        sds(jax.random.key(0)),
        SDS((), jnp.float32, sharding=sh),             # lr
    )
    with force_target("tpu"):
        t0 = time.time()
        compiled = step._jit.lower(*args).compile()
        secs = round(time.time() - t0, 1)
    mem = compiled.memory_analysis()
    cost = compiled.cost_analysis() or {}
    est = estimate_step_seconds({
        "optimal_seconds": cost.get("optimal_seconds"),
        "flops": cost.get("flops"),
        "bytes_accessed": cost.get("bytes accessed",
                                   cost.get("bytes_accessed"))})
    out = {
        "config": f"widedeep compiled pass step, b{batch} slots{slots} "
                  f"vocab{vocab} dim{dim}, v5e single chip",
        "compile_seconds": secs,
        "peak_hbm_bytes": int(mem.temp_size_in_bytes
                              + mem.argument_size_in_bytes),
        "est_step_seconds": est and round(est["seconds"], 6),
        "est_signal": est and est["signal"],
        "est_examples_per_sec": est and round(batch / est["seconds"], 1),
        "note": "est_* are compiler/roofline numbers, not measurements",
    }
    path = os.path.join(REPO, "artifacts", "widedeep_aot_probe.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))


if __name__ == "__main__":
    main()

"""Eager per-op dispatch latency, per op-cache hit/miss (VERDICT r4 #7).

SURVEY §7 hard-part 1: eager op dispatch must stay usable on TPU. This
measures, against the LIVE ambient backend (TPU when the tunnel
executes; CPU PJRT otherwise — the JSON is labeled either way):

  hit_us        op-cache HIT dispatch (the steady-state eager path)
  miss_us       op-cache MISS (fresh trace+compile per op: new shapes)
  train_hit_us  grad-enabled loop: dispatch + tape build + cached bwd

Writes artifacts/eager_dispatch.json. tests/test_eager_dispatch.py is
the regression guard over the hit path.
"""
from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def measure(n_hit: int = 400, n_miss: int = 5, force_cpu: bool = False) -> dict:
    # n_miss stays SMALL: every miss op pays a real compile — several
    # seconds each over a TPU tunnel — and the mean stabilizes quickly
    import jax

    if force_cpu:
        # the axon sitecustomize clobbers the JAX_PLATFORMS env var, so
        # the CPU fallback must pin the platform through jax.config
        jax.config.update("jax_platforms", "cpu")

    import paddle_tpu as paddle

    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", dev.platform)
    on_tpu = "tpu" in dev.platform.lower() or "TPU" in kind

    # ---- hit path: repeated same-shape ops ride the op cache ----
    x = paddle.ones([256, 256])

    def chain(t, k):
        for _ in range(k):
            t = t * 1.0001 + 0.1
        return t

    _ = float(chain(x, 20).sum())  # warm
    t0 = time.perf_counter()
    y = chain(x, n_hit)
    _ = float(y.sum())
    hit_us = (time.perf_counter() - t0) / (2 * n_hit) * 1e6

    # ---- miss path: a fresh shape per op defeats the cache, so every
    # dispatch pays trace + compile (the first-touch cost a user sees) ----
    t0 = time.perf_counter()
    for i in range(n_miss):
        t = paddle.ones([8, 8 + i])
        _ = float((t * 2.0 + float(i)).sum())
    miss_us = (time.perf_counter() - t0) / (2 * n_miss) * 1e6

    # ---- grad-enabled hit path (the eager TRAINING shape) ----
    xs = paddle.ones([16, 16])
    w = paddle.ones([16, 16])
    w.stop_gradient = False
    k = 20

    def train_iter():
        t = xs
        for _ in range(k):
            t = (t @ w) * 0.5
        loss = t.sum()
        loss.backward()
        g = w.grad
        w.clear_grad()
        return g

    _ = train_iter()
    iters = max(1, n_hit // (2 * k))
    t0 = time.perf_counter()
    for _ in range(iters):
        g = train_iter()
    _ = float(g.sum().numpy())
    train_hit_us = (time.perf_counter() - t0) / (iters * 2 * k) * 1e6

    return {
        "device_kind": kind,
        "on_tpu": on_tpu,
        "hit_us": round(hit_us, 2),
        "miss_us": round(miss_us, 2),
        "train_hit_us": round(train_hit_us, 2),
        "miss_over_hit": round(miss_us / hit_us, 1) if hit_us else None,
        "n_hit_ops": 2 * n_hit,
        "n_miss_ops": 2 * n_miss,
        "note": ("miss pays trace+compile (first touch of a shape); hit "
                 "is the steady-state dispatch SURVEY §7 risk #1 tracks; "
                 "100us/op is the usability target on TPU"),
    }


def main():
    rec = measure(force_cpu="--cpu" in sys.argv)
    path = os.path.join(REPO, "artifacts", "eager_dispatch.json")
    existing = {}
    try:
        existing = json.load(open(path))
    except (FileNotFoundError, json.JSONDecodeError):
        pass
    # keep one record per device kind; a TPU record is never overwritten
    # by a CPU fallback run
    key = "tpu" if rec["on_tpu"] else "cpu"
    existing[key] = rec
    with open(path, "w") as f:
        json.dump(existing, f, indent=1)
    print(json.dumps(rec))


if __name__ == "__main__":
    main()

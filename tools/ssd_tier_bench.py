"""SSD-tier pull/push throughput at CTR scale (VERDICT r3 next #8).

1M-row table, majority spilled to the disk log, then timed pull storms
(the heter-PS BuildGPUTask bulk-pull shape) and push storms, single- and
multi-threaded. The round-3 tier serialized every faulted row behind one
FILE*/mutex; reads now go through pread under a shared lock, so
concurrent pulls of disk-resident rows scale with threads (on multi-core
hosts; this 1-core box still shows the syscall-path cost honestly).

Reference contrast: ssd_sparse_table.cc gets concurrent reads from
rocksdb. Writes artifacts/ssd_tier_bench.json.
"""
import json
import os
import sys
import tempfile
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paddle_tpu.core.table import SparseTable

ROWS = int(os.environ.get("SSD_BENCH_ROWS", 1_000_000))
DIM = 8
RESIDENT = ROWS // 5          # spill 80% to disk
PULL_N = 200_000              # keys per timed storm
THREADS = int(os.environ.get("SSD_BENCH_THREADS", 4))


def main():
    rs = np.random.RandomState(0)
    table = SparseTable(dim=DIM, shard_bits=6, optimizer="sgd",
                        init_range=0.01, lr=0.1)
    tmp = tempfile.mkdtemp()
    table.enable_ssd(os.path.join(tmp, "spill.log"))

    keys = np.arange(ROWS, dtype=np.uint64)
    t0 = time.perf_counter()
    for i in range(0, ROWS, 100_000):           # materialize all rows
        table.pull(keys[i:i + 100_000])
    t_fill = time.perf_counter() - t0

    t0 = time.perf_counter()
    evicted = table.spill(RESIDENT)
    t_spill = time.perf_counter() - t0
    assert evicted == ROWS - RESIDENT, evicted
    disk_rows = table.ssd_rows()

    out = {"rows": ROWS, "dim": DIM, "resident": RESIDENT,
           "disk_rows": int(disk_rows),
           "fill_s": round(t_fill, 2), "spill_s": round(t_spill, 2)}

    def storm(tag, n_threads):
        # uniform random keys: ~80% of pulls fault from disk on the first
        # touch. Re-randomize per storm so earlier storms' fault-ins don't
        # turn later storms into pure memory hits.
        ks = rs.randint(0, ROWS, PULL_N).astype(np.uint64)
        t0 = time.perf_counter()
        if n_threads == 1:
            table.pull(ks)
        else:
            chunk = PULL_N // n_threads
            with ThreadPoolExecutor(n_threads) as ex:
                list(ex.map(table.pull,
                            [ks[i * chunk:(i + 1) * chunk]
                             for i in range(n_threads)]))
        dt = time.perf_counter() - t0
        out[tag] = round(PULL_N / dt, 1)
        # re-spill so the next storm faces a cold majority again
        table.spill(RESIDENT)

    storm("pull_rows_per_s_1thread", 1)
    storm(f"pull_rows_per_s_{THREADS}threads", THREADS)

    # push storm: updates fault + apply adagrad/sgd in C
    ks = rs.randint(0, ROWS, PULL_N).astype(np.uint64)
    grads = rs.randn(PULL_N, DIM).astype(np.float32)
    t0 = time.perf_counter()
    table.push(ks, grads)
    out["push_rows_per_s_1thread"] = round(
        PULL_N / (time.perf_counter() - t0), 1)

    # pure-memory baseline for scale: pull of resident-only keys
    ks_mem = rs.randint(0, RESIDENT // 2, PULL_N).astype(np.uint64)
    table.pull(ks_mem)  # ensure resident
    t0 = time.perf_counter()
    table.pull(ks_mem)
    out["pull_rows_per_s_memory_tier"] = round(
        PULL_N / (time.perf_counter() - t0), 1)

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "artifacts", "ssd_tier_bench.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))
    print(f"saved -> {path}", file=sys.stderr)


if __name__ == "__main__":
    main()

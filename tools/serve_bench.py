"""Serving bench: open-loop QPS sweep of the continuous-batching runtime
vs the sequential single-request baseline (ISSUE 14 deliverable).

Phases (all on the gpt-test preset, CPU-safe):

  baseline    the pre-serving world: one request at a time through a
              batch-1 engine (prefill -> decode loop, no queue overlap).
              Its request rate is the saturation capacity the sweep is
              scaled against.
  sweep       open-loop Poisson-ish arrivals at increasing QPS multiples
              of the baseline capacity into a ReplicaSet; per point:
              generated tokens/s, request latency p50/p95/p99, queue
              depth (mean/max), batch occupancy, completed/rejected.
              The acceptance claim: at and beyond the QPS where the
              baseline saturates (x1.0), continuous batching sustains
              strictly higher tokens/s.
  kv          the same fixed workload against fp32 vs int8_block KV
              pools: peak at-rest bytes (int8 must be <= ~1/4 of fp32)
              and generated-token agreement.
  chaos       2 replicas, one hung mid-run: the watchdog evicts it and
              every accepted request still completes (zero lost).
  prefix      the million-user mix (ISSUE 16): Zipfian traffic over a
              handful of long shared system prompts, cache off vs on —
              shared prefixes prefill exactly once, so prefill tokens
              COMPUTED collapse and end-to-end tokens/s must be >= 2x
              the no-cache run on the same mix (greedy outputs equal).
  spec        speculative decoding (ISSUE 16): a layer-truncated
              self-draft proposes spec_k tokens per step, the target
              verifies losslessly — outputs token-for-token equal to
              the plain engine, accepted-tokens-per-step > 1.
  boot        zero-cold-start plane (ISSUE 19): cold replica boot (a
              fresh model's jit wrappers — real XLA compiles) vs warm
              boot (pre-compiled shape buckets), plus TTFT from
              re-admission to first token across a warm-handoff
              eviction under load.

Writes artifacts/serve_bench.json; ``serve_tokens_per_s`` (best sweep
point), ``serve_p99_ms`` (at the x1.0 saturation point),
``serve_cache_hit_tokens_per_s``, ``serve_spec_tokens_per_step``,
``replica_boot_warm_ms`` and ``ttft_after_eviction_ms``
feed the bench.py gpt record and are gated by tools/bench_gate.py.

  python tools/serve_bench.py [--quick] [--out artifacts/serve_bench.json]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def build_decode_model(preset: str = "gpt-test", **overrides):
    from paddle_tpu.models import GPTForCausalLM, gpt_presets
    from paddle_tpu.serving import GPTDecodeModel

    return GPTDecodeModel(
        GPTForCausalLM(gpt_presets(preset, **overrides), seed=0))


def make_workload(n: int, vocab: int, seed: int = 0,
                  prompt_lo: int = 8, prompt_hi: int = 24,
                  new_lo: int = 8, new_hi: int = 24):
    """Deterministic request mix: ragged prompts, ragged decode lengths."""
    rs = np.random.RandomState(seed)
    specs = []
    for _ in range(n):
        specs.append((rs.randint(0, vocab,
                                 (int(rs.randint(prompt_lo, prompt_hi)),)),
                      int(rs.randint(new_lo, new_hi))))
    return specs


def _fresh_requests(specs):
    from paddle_tpu.serving import ServeRequest

    return [ServeRequest(prompt_ids=p, max_new_tokens=m) for p, m in specs]


def _lat_ms(reqs):
    lats = sorted(r.latency_ms for r in reqs)

    def pct(q):
        return round(lats[min(len(lats) - 1, int(q * len(lats)))], 2)

    return {"p50_ms": pct(0.50), "p95_ms": pct(0.95), "p99_ms": pct(0.99)}


def _ttft_ms(reqs):
    """Time-to-first-token percentiles (submit -> first sampled token);
    the interactive-latency number total latency hides behind long
    decodes (ISSUE 18)."""
    vals = sorted((r.t_first_token - r.t_submit) * 1e3
                  for r in reqs if r.t_first_token)
    if not vals:
        return {}

    def pct(q):
        return round(vals[min(len(vals) - 1, int(q * len(vals)))], 2)

    return {"ttft_p50_ms": pct(0.50), "ttft_p95_ms": pct(0.95),
            "ttft_p99_ms": pct(0.99)}


def run_sequential_baseline(dm, specs) -> dict:
    """One request at a time, batch 1 — the pre-ISSUE-14 Predictor
    serving model. Closed loop: next request starts when this one ends
    (its throughput ceiling, which open-loop arrivals saturate)."""
    from paddle_tpu.serving import (
        KVBlockPool, RequestQueue, ServingEngine,
    )

    reqs = _fresh_requests(specs)
    pool = KVBlockPool(n_blocks=32, block_tokens=16,
                       elems_per_token=dm.elems_per_token, codec="fp32")
    t0 = time.monotonic()
    for r in reqs:
        q = RequestQueue(max_depth=1)
        eng = ServingEngine(dm, pool, q, max_batch=1)
        r.t_submit = time.monotonic()
        q.submit(r)
        while eng.step() or eng.running or q.depth:
            pass
    wall = time.monotonic() - t0
    toks = sum(len(r.generated) for r in reqs)
    assert all(r.outcome == "completed" for r in reqs)
    return {
        "requests": len(reqs),
        "wall_s": round(wall, 3),
        "tokens": toks,
        "tokens_per_s": round(toks / wall, 1),
        "requests_per_s": round(len(reqs) / wall, 3),
        **_lat_ms(reqs),
    }


def run_open_loop(dm, specs, qps: float, n_replicas: int = 2,
                  codec: str = "fp32", n_blocks: int = 64,
                  max_batch: int = 8) -> dict:
    """Open-loop arrivals at fixed QPS into a ReplicaSet; arrivals do
    NOT wait for completions (the load model a public endpoint sees)."""
    from paddle_tpu.serving import ReplicaSet
    from paddle_tpu.serving.engine import _m_occupancy

    reqs = _fresh_requests(specs)
    rset = ReplicaSet(dm, n_replicas=n_replicas, n_blocks=n_blocks,
                      block_tokens=16, codec=codec, max_batch=max_batch)
    depth_samples, occ_samples = [], []
    stop_sampler = threading.Event()

    def sampler():
        while not stop_sampler.wait(0.02):
            depth_samples.append(rset.queue.depth)
            occ_samples.append(sum(
                _m_occupancy.labels(replica=e.name).get()
                for e in rset.engines if e.alive))

    st = threading.Thread(target=sampler, daemon=True,
                          name="serve-bench-sampler")
    accepted, rejected = [], 0
    t0 = time.monotonic()
    with rset:
        st.start()
        for i, r in enumerate(reqs):
            target = t0 + i / qps
            now = time.monotonic()
            if target > now:
                time.sleep(target - now)
            if rset.submit(r):
                accepted.append(r)
            else:
                rejected += 1
        res = rset.wait([r.request_id for r in accepted], timeout=600)
        wall = time.monotonic() - t0
        stop_sampler.set()
    assert len(res) == len(accepted), "open-loop run lost requests"
    toks = sum(len(r.generated) for r in res.values())
    return {
        "qps": round(qps, 3),
        "offered": len(reqs),
        "accepted": len(accepted),
        "rejected": rejected,
        "wall_s": round(wall, 3),
        "tokens_per_s": round(toks / wall, 1),
        "mean_queue_depth": round(float(np.mean(depth_samples or [0])), 2),
        "max_queue_depth": int(np.max(depth_samples or [0])),
        "mean_batch_occupancy": round(float(np.mean(occ_samples or [0])), 3),
        **_lat_ms(list(res.values())),
        **_ttft_ms(list(res.values())),
    }


def run_kv_codec_compare(dm, specs) -> dict:
    """Same workload, fp32 vs int8_block KV at rest: peak bytes + token
    agreement (the quantized cache must not change what gets served,
    within the pinned parity bounds)."""
    from paddle_tpu.serving import KVBlockPool, RequestQueue, ServingEngine

    out = {}
    gen = {}
    for codec in ("fp32", "int8_block"):
        reqs = _fresh_requests(specs)
        pool = KVBlockPool(n_blocks=64, block_tokens=16,
                           elems_per_token=dm.elems_per_token, codec=codec)
        q = RequestQueue(max_depth=len(reqs))
        eng = ServingEngine(dm, pool, q, max_batch=8)
        for r in reqs:
            q.submit(r)
        peak = 0
        while eng.step() or eng.running or q.depth:
            peak = max(peak, pool.bytes_in_use())
        assert all(r.outcome == "completed" for r in reqs)
        out[codec] = {"peak_bytes": peak,
                      "block_bytes": pool.block_bytes()}
        gen[codec] = [r.generated for r in reqs]
    match = np.mean([a == b for a, b in
                     zip(gen["fp32"], gen["int8_block"])])
    total = {c: sum(len(g) for g in gen[c]) for c in gen}
    tok_match = np.mean([
        np.mean([x == y for x, y in zip(a, b)])
        for a, b in zip(gen["fp32"], gen["int8_block"])])
    ratio = out["int8_block"]["peak_bytes"] / out["fp32"]["peak_bytes"]
    return {
        "fp32_peak_bytes": out["fp32"]["peak_bytes"],
        "int8_block_peak_bytes": out["int8_block"]["peak_bytes"],
        "bytes_ratio": round(ratio, 4),
        "sequence_match_fraction": round(float(match), 4),
        "token_match_fraction": round(float(tok_match), 4),
        "tokens": total,
    }


def make_zipf_workload(n: int, vocab: int, n_sys: int = 4,
                       sys_len: int = 96, suffix_len: int = 4,
                       max_new: int = 6, seed: int = 0):
    """Zipfian traffic over a handful of system prompts: every request
    is one of ``n_sys`` long shared prefixes + a short unique suffix —
    the chat-endpoint shape where prefix caching pays."""
    rs = np.random.RandomState(seed)
    sys_prompts = [rs.randint(0, vocab, (sys_len,)) for _ in range(n_sys)]
    w = 1.0 / np.arange(1, n_sys + 1) ** 1.1
    w /= w.sum()
    specs = []
    for _ in range(n):
        k = int(rs.choice(n_sys, p=w))
        prompt = np.concatenate(
            [sys_prompts[k], rs.randint(0, vocab, (suffix_len,))])
        specs.append((prompt, max_new))
    return specs


def _drive_engine(dm, specs, n_blocks=128, block_tokens=16, max_batch=8,
                  **engine_kw):
    """Closed drive of one engine over a workload; returns (requests,
    wall seconds, engine)."""
    from paddle_tpu.serving import KVBlockPool, RequestQueue, ServingEngine

    reqs = _fresh_requests(specs)
    pool = KVBlockPool(n_blocks=n_blocks, block_tokens=block_tokens,
                       elems_per_token=dm.elems_per_token, codec="fp32")
    q = RequestQueue(max_depth=len(reqs) + 1)
    eng = ServingEngine(dm, pool, q, max_batch=max_batch, **engine_kw)
    for r in reqs:
        q.submit(r)
    t0 = time.monotonic()
    while eng.step() or eng.running or q.depth:
        pass
    wall = time.monotonic() - t0
    assert all(r.outcome == "completed" for r in reqs)
    return reqs, wall, eng


def run_prefix_cache_zipf(dm, specs) -> dict:
    """Same Zipfian mix, prefix cache off vs on. Shared prefixes must
    prefill exactly once: prefill tokens COMPUTED drop to ~(first
    occurrences + tails) and end-to-end tokens/s >= 2x no-cache."""
    from paddle_tpu.serving.engine import (
        _m_prefill_tok, _m_prefix_hit, _m_prefix_miss,
    )

    out = {}
    gen = {}
    for mode in ("no_cache", "cache"):
        on = mode == "cache"
        # untimed warm pass (fresh pool each time — jit compiles live on
        # the shared model, the prefix cache lives on the pool) so the
        # timed runs compare serving work, not compile time
        _drive_engine(dm, specs[:min(10, len(specs))], prefix_cache=on)
        pre0, hit0, miss0 = (_m_prefill_tok.get(), _m_prefix_hit.get(),
                             _m_prefix_miss.get())
        reqs, wall, eng = _drive_engine(dm, specs, prefix_cache=on)
        toks = sum(len(r.generated) for r in reqs)
        out[mode] = {
            "wall_s": round(wall, 3),
            "tokens": toks,
            "tokens_per_s": round(toks / wall, 1),
            "prefill_tokens_computed": int(_m_prefill_tok.get() - pre0),
            "cache_hit_tokens": int(_m_prefix_hit.get() - hit0),
            "cache_miss_tokens": int(_m_prefix_miss.get() - miss0),
        }
        gen[mode] = [list(r.generated) for r in reqs]
    seq_match = float(np.mean([a == b for a, b in
                               zip(gen["no_cache"], gen["cache"])]))
    cache = out["cache"]
    speedup = out["no_cache"]["wall_s"] / cache["wall_s"]
    prompt_tokens = sum(len(p) for p, _ in specs)
    return {
        "n_requests": len(specs),
        "prompt_tokens_offered": prompt_tokens,
        "no_cache": out["no_cache"],
        "cache": cache,
        "prefill_computed_ratio": round(
            cache["prefill_tokens_computed"]
            / max(1, out["no_cache"]["prefill_tokens_computed"]), 4),
        "cache_hit_tokens_per_s": round(
            cache["cache_hit_tokens"] / cache["wall_s"], 1),
        "speedup": round(speedup, 3),
        "sequence_match_fraction": round(seq_match, 4),
        "ok": speedup >= 2.0 and seq_match == 1.0
        and cache["prefill_tokens_computed"]
        < out["no_cache"]["prefill_tokens_computed"],
    }


def run_speculative(dm, specs, spec_k: int = 4,
                    draft_layers: int = 1) -> dict:
    """Decode-heavy workload, plain vs speculative (layer-truncated
    self-draft). The acceptance rule is lossless, so outputs must be
    token-for-token identical; the measured win is committed tokens per
    step (> 1 means the draft is paying for itself)."""
    draft = dm.truncated(draft_layers)
    kw = {"baseline": {}, "speculative": {"draft_model": draft,
                                          "spec_k": spec_k}}
    out = {}
    gen = {}
    for mode, extra in kw.items():
        _drive_engine(dm, specs[:min(6, len(specs))],
                      prefix_cache=False, **extra)     # warm jit buckets
        reqs, wall, eng = _drive_engine(dm, specs, prefix_cache=False,
                                        **extra)
        toks = sum(len(r.generated) for r in reqs)
        out[mode] = {
            "wall_s": round(wall, 3),
            "tokens": toks,
            "tokens_per_s": round(toks / wall, 1),
            "decode_steps": eng.steps,
        }
        if mode == "speculative":
            out[mode]["accepted_tokens_per_step"] = round(
                eng.spec_emitted / max(1, eng.spec_steps), 3)
            out[mode]["kv_blocks_leaked"] = eng.pool.blocks_in_use
        gen[mode] = [list(r.generated) for r in reqs]
    lossless = gen["baseline"] == gen["speculative"]
    aps = out["speculative"]["accepted_tokens_per_step"]
    return {
        "n_requests": len(specs),
        "spec_k": spec_k,
        "draft_layers": draft_layers,
        "baseline": out["baseline"],
        "speculative": out["speculative"],
        "accepted_tokens_per_step": aps,
        "lossless": lossless,
        "ok": lossless and aps > 1.0
        and out["speculative"]["kv_blocks_leaked"] == 0,
    }


def run_tracing_overhead(dm, specs) -> dict:
    """ISSUE 18: the same closed-drive workload with request tracing off
    vs on (FLAGS_serving_tracing). Per-request spans + exemplars must
    cost less than the 20% throughput band bench_gate holds."""
    from paddle_tpu.framework.flags import flag, set_flags

    out = {}
    prev = bool(flag("FLAGS_serving_tracing", True))
    try:
        for mode, on in (("tracing_off", False), ("tracing_on", True)):
            set_flags({"FLAGS_serving_tracing": on})
            _drive_engine(dm, specs[:min(8, len(specs))])    # warm jit
            reqs, wall, eng = _drive_engine(dm, specs)
            toks = sum(len(r.generated) for r in reqs)
            traced = sum(1 for r in reqs if r.trace is not None)
            assert traced == (len(reqs) if on else 0), \
                "tracing flag did not gate trace minting"
            out[mode] = {"wall_s": round(wall, 3), "tokens": toks,
                         "tokens_per_s": round(toks / wall, 1)}
    finally:
        set_flags({"FLAGS_serving_tracing": prev})
    ratio = (out["tracing_on"]["tokens_per_s"]
             / out["tracing_off"]["tokens_per_s"])
    return {
        **out,
        "tokens_per_s_ratio": round(ratio, 4),
        "overhead_fraction": round(max(0.0, 1.0 - ratio), 4),
        "ok": ratio >= 0.8,
    }


def run_chaos_eviction(dm, specs) -> dict:
    """Hang one of two replicas mid-run; zero accepted requests lost."""
    from paddle_tpu.serving import ReplicaSet

    gate = threading.Event()

    def hang_hook(eng):
        if eng.running and eng.steps > 2 and not gate.is_set():
            gate.wait(60)   # stuck until the run ends

    reqs = _fresh_requests(specs)
    # watchdog must outlast a cold jit compile (seconds on CPU) or the
    # SURVIVOR gets evicted for compiling and the set empties out
    rset = ReplicaSet(dm, n_replicas=2, n_blocks=64, block_tokens=16,
                      max_batch=4, watchdog_timeout=5.0,
                      pre_step_hooks={0: hang_hook})
    with rset:
        for r in reqs:
            assert rset.submit(r)
        res = rset.wait([r.request_id for r in reqs], timeout=600)
        gate.set()
    lost = len(reqs) - len(res)
    return {
        "accepted": len(reqs),
        "completed": sum(1 for r in res.values()
                         if r.outcome == "completed"),
        "lost": lost,
        "evictions": rset.evictions,
        "redispatched": sum(1 for r in res.values() if r.attempts > 0),
        "ok": lost == 0 and len(rset.evictions) >= 1,
    }


def run_boot_phase(dm, specs, preset: str = "gpt-test") -> dict:
    """Cold vs warm replica boot + TTFT after a warm-handoff eviction
    (ISSUE 19 zero-cold-start plane).

    cold  a replacement built on a FRESH decode model: fresh jax.jit
          wrappers, so the process-wide jit cache cannot serve it and
          ``warm()`` pays the real XLA compiles — the window the old
          cold path exposed to traffic.
    warm  a replacement sharing the serving model (the in-process warm
          path; with jax.export artifacts this is a deserialize).
    ttft  an eviction storm under load where the replacement boots warm
          BEFORE the outgoing replica drains: time from re-admission to
          first token for the re-dispatched requests, vs the
          steady-state tail.
    """
    from paddle_tpu.serving import ReplicaSet

    reqs = _fresh_requests(specs)
    rset = ReplicaSet(dm, n_replicas=1, n_blocks=128, block_tokens=16,
                      max_batch=8, watchdog_timeout=5.0)
    with rset:
        for r in reqs:
            assert rset.submit(r)
        res = rset.wait([r.request_id for r in reqs], timeout=600)
        steady = sorted((r.t_first_token - r.t_enqueue) * 1e3
                        for r in res.values() if r.t_first_token)
        steady_p99 = round(
            steady[min(len(steady) - 1, int(0.99 * len(steady)))], 2)
        buckets = sorted(rset.warm_buckets(), key=repr)

        rset.scale_up(model=build_decode_model(preset), warm=True)
        cold_ms = rset.last_boot["ms"]
        rset.scale_down(reason="boot_phase")

        rset.scale_up(model=dm, warm=True)
        warm_ms = rset.last_boot["ms"]
        rset.scale_down(reason="boot_phase")

        reqs2 = _fresh_requests(specs)
        for r in reqs2:
            assert rset.submit(r)
        rset.replace()          # warm standby first, THEN fence + drain
        res2 = rset.wait([r.request_id for r in reqs2], timeout=600)
        redis = sorted((r.t_first_token - r.t_enqueue) * 1e3
                       for r in res2.values()
                       if r.t_first_token and r.attempts > 0)
    lost = len(reqs2) - len(res2)
    ttft_after = round(
        redis[min(len(redis) - 1, int(0.99 * len(redis)))], 2) \
        if redis else 0.0
    warm_boots = [b for b in rset.boots if b["mode"] == "warm"]
    return {
        "buckets_warmed": len(buckets),
        "replica_boot_cold_ms": round(cold_ms, 2),
        "replica_boot_warm_ms": round(warm_ms, 2),
        "boot_speedup": round(cold_ms / max(warm_ms, 1e-9), 2),
        "steady_ttft_p99_ms": steady_p99,
        "ttft_after_eviction_ms": ttft_after,
        "redispatched": len(redis),
        "lost": lost,
        "boots": [{k: b[k] for k in ("replica", "mode", "outcome", "ms")}
                  for b in rset.boots],
        "ok": (lost == 0 and warm_ms < cold_ms
               and all(b["outcome"] == "ok" for b in warm_boots)
               and (not redis or ttft_after <= 1.5 * max(steady_p99,
                                                        1e-9))),
    }


def run_serve_bench(quick: bool = False, preset: str = "gpt-test") -> dict:
    dm = build_decode_model(preset)
    vocab = dm.vocab_size
    n = 12 if quick else 32
    specs = make_workload(n, vocab, seed=0)

    print(f"# serve_bench preset={preset} requests={n}", file=sys.stderr)
    baseline = run_sequential_baseline(dm, specs)
    print(f"# baseline: {baseline['tokens_per_s']} tok/s "
          f"{baseline['requests_per_s']} req/s", file=sys.stderr)

    cap = baseline["requests_per_s"]
    multiples = (0.5, 1.0, 2.0) if quick else (0.5, 1.0, 2.0, 4.0)
    sweep = []
    for m in multiples:
        point = run_open_loop(dm, specs, qps=max(0.25, m * cap))
        point["qps_over_baseline_capacity"] = m
        sweep.append(point)
        print(f"# qps x{m}: {point['tokens_per_s']} tok/s "
              f"p99={point['p99_ms']}ms depth~{point['mean_queue_depth']}",
              file=sys.stderr)

    kv = run_kv_codec_compare(dm, specs)
    print(f"# kv: int8/fp32 bytes ratio {kv['bytes_ratio']} "
          f"token match {kv['token_match_fraction']}", file=sys.stderr)

    chaos = run_chaos_eviction(dm, specs)
    print(f"# chaos: lost={chaos['lost']} evictions="
          f"{[e['reason'] for e in chaos['evictions']]}", file=sys.stderr)

    # the prefix phase runs on a WIDER model: at the test preset's width
    # the per-step dispatch overhead swamps prefill FLOPs, so skipping
    # cached prefill would be invisible in wall-clock. hidden=256 makes
    # the 192-token shared-prefix prefill the dominant cost — the regime
    # prefix caching exists for.
    dm_wide = build_decode_model(preset, hidden_size=256, num_heads=4,
                                 max_position_embeddings=256)
    zipf_specs = make_zipf_workload(24 if quick else 64, vocab,
                                    n_sys=3 if quick else 4,
                                    sys_len=192, max_new=3, seed=1)
    prefix = run_prefix_cache_zipf(dm_wide, zipf_specs)
    print(f"# prefix: {prefix['speedup']}x tokens/s, prefill computed "
          f"{prefix['cache']['prefill_tokens_computed']} vs "
          f"{prefix['no_cache']['prefill_tokens_computed']} "
          f"(ratio {prefix['prefill_computed_ratio']})", file=sys.stderr)

    spec_specs = make_workload(8 if quick else 16, vocab, seed=2,
                               prompt_lo=6, prompt_hi=12,
                               new_lo=20, new_hi=28)
    spec = run_speculative(dm, spec_specs)
    print(f"# spec: accepted/step {spec['accepted_tokens_per_step']} "
          f"lossless={spec['lossless']}", file=sys.stderr)

    tracing = run_tracing_overhead(dm, specs)
    print(f"# tracing: on/off tokens/s ratio "
          f"{tracing['tokens_per_s_ratio']} (overhead "
          f"{tracing['overhead_fraction']})", file=sys.stderr)

    boot_specs = make_workload(12 if quick else 24, vocab, seed=3,
                               new_lo=16, new_hi=24)
    boot = run_boot_phase(dm, boot_specs, preset=preset)
    print(f"# boot: cold={boot['replica_boot_cold_ms']}ms "
          f"warm={boot['replica_boot_warm_ms']}ms "
          f"(x{boot['boot_speedup']}) ttft_after_eviction="
          f"{boot['ttft_after_eviction_ms']}ms over "
          f"{boot['redispatched']} redispatched", file=sys.stderr)

    # "saturation" = offered load at/above the baseline's closed-loop
    # capacity: the baseline CANNOT exceed its tokens/s there, so the
    # acceptance comparison is best continuous tokens/s over those points
    saturated = [p for p in sweep
                 if p["qps_over_baseline_capacity"] >= 1.0] or sweep
    best = max(p["tokens_per_s"] for p in sweep)
    best_sat = max(p["tokens_per_s"] for p in saturated)
    return {
        "preset": preset,
        "quick": quick,
        "n_requests": n,
        "sequential_baseline": baseline,
        "continuous": sweep,
        "kv_cache": kv,
        "chaos": chaos,
        "prefix_cache": prefix,
        "speculative": spec,
        "tracing": tracing,
        "boot": boot,
        # gated headline numbers: p99 at the x1.0 point (stable-load
        # tail latency — deeper points measure queueing, not serving)
        "serve_tokens_per_s": best,
        "serve_p99_ms": saturated[0]["p99_ms"],
        # ISSUE 18 gated numbers: time-to-first-token tail at the same
        # stable-load point, and the tracing on/off throughput ratio
        # (1.0 = free; the gate band holds it >= 0.8)
        "serve_ttft_p99_ms": saturated[0].get("ttft_p99_ms", 0.0),
        "serve_tracing_tokens_per_s_ratio": tracing["tokens_per_s_ratio"],
        "speedup_at_saturation": round(
            best_sat / baseline["tokens_per_s"], 3),
        # ISSUE 16 gated numbers: prefix-cache-hit token throughput under
        # the Zipfian mix, and mean target tokens emitted per speculative
        # verify step (1.0 would mean the draft never helps)
        "serve_cache_hit_tokens_per_s": prefix["cache_hit_tokens_per_s"],
        "serve_spec_tokens_per_step": spec["accepted_tokens_per_step"],
        # ISSUE 19 gated numbers: warm replica boot latency (the
        # zero-cold-start plane's whole point) and TTFT from re-admission
        # to first token after a warm-handoff eviction
        "replica_boot_warm_ms": boot["replica_boot_warm_ms"],
        "replica_boot_cold_ms": boot["replica_boot_cold_ms"],
        "ttft_after_eviction_ms": boot["ttft_after_eviction_ms"],
    }


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--quick", action="store_true",
                    help="small workload (CI smoke)")
    ap.add_argument("--preset", default="gpt-test")
    ap.add_argument("--out",
                    default=os.path.join(REPO, "artifacts",
                                         "serve_bench.json"))
    args = ap.parse_args(argv)
    rec = run_serve_bench(quick=args.quick, preset=args.preset)
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(rec, f, indent=1)
        f.write("\n")
    print(json.dumps({k: rec[k] for k in
                      ("serve_tokens_per_s", "serve_p99_ms",
                       "serve_ttft_p99_ms", "speedup_at_saturation",
                       "serve_cache_hit_tokens_per_s",
                       "serve_spec_tokens_per_step",
                       "serve_tracing_tokens_per_s_ratio",
                       "replica_boot_warm_ms", "replica_boot_cold_ms",
                       "ttft_after_eviction_ms")}))
    ok = (rec["speedup_at_saturation"] > 1.0
          and rec["kv_cache"]["bytes_ratio"] <= 0.28
          and rec["chaos"]["ok"]
          and rec["prefix_cache"]["ok"]
          and rec["speculative"]["ok"]
          and rec["tracing"]["ok"]
          and rec["boot"]["ok"])
    print(f"serve_bench: {'pass' if ok else 'FAIL'} "
          f"(speedup_at_saturation={rec['speedup_at_saturation']}, "
          f"kv_ratio={rec['kv_cache']['bytes_ratio']}, "
          f"chaos_lost={rec['chaos']['lost']}, "
          f"prefix_speedup={rec['prefix_cache']['speedup']}, "
          f"spec_tok_per_step={rec['serve_spec_tokens_per_step']}, "
          f"tracing_ratio={rec['serve_tracing_tokens_per_s_ratio']}, "
          f"boot_warm={rec['replica_boot_warm_ms']}ms "
          f"cold={rec['replica_boot_cold_ms']}ms)",
          file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())

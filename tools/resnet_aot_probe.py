"""ResNet-50 batch-size sweep through the REAL TPU compiler (AOT).

The measured round-5 number (1758 samples/s at batch 64, MFU 0.109) is
far under the 0.40 target; the execution tunnel is wedged again, but the
XLA-TPU compiler is reachable via jax.experimental.topologies, so rank
candidate per-chip batch sizes by the compiler's own step-time estimate
and pick the bench config from evidence instead of guessing. Writes
artifacts/resnet_aot_probe.json (est_* fields: compiler/roofline
numbers, not measurements).
"""
from __future__ import annotations

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def probe(batches=(64, 128, 256)):
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    from jax.sharding import PartitionSpec as P

    import paddle_tpu.nn.functional as F
    import paddle_tpu.optimizer as opt
    from paddle_tpu.amp import auto_cast
    from paddle_tpu.distributed import mesh as mesh_mod
    from paddle_tpu.framework import target as target_mod
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.jit.aot import (
        aot_compile_step, estimate_step_seconds, topology_mesh,
    )
    from paddle_tpu.vision.models import resnet50

    model = resnet50(num_classes=1000)
    optim = opt.Momentum(learning_rate=0.01, momentum=0.9,
                         parameters=model.parameters())

    mesh = topology_mesh("v5e:2x4", {"data": 8})
    results = []
    prev = mesh_mod.get_mesh()
    try:
        with target_mod.force_target("tpu"):
            mesh_mod.set_mesh(mesh)
            for batch in batches:
                # np.zeros is calloc-backed: the arrays only template
                # shapes/dtypes for the abstract lowering
                x = np.zeros((batch * 8, 3, 224, 224), np.float32)
                y = np.zeros((batch * 8,), np.int64)
                step = TrainStep(
                    model, lambda lo, yy: F.cross_entropy(lo, yy), optim,
                    batch_spec=P("data"))
                with auto_cast(enable=True, level="O2", dtype="bfloat16"):
                    r = aot_compile_step(step, (x,), (y,), want_cost=True)
                est = estimate_step_seconds(r)
                rec = {
                    "per_chip_batch": batch,
                    "compile_seconds": r.get("compile_seconds"),
                    "est_step_seconds": est and round(est["seconds"], 6),
                    "est_signal": est and est["signal"],
                    "est_samples_per_sec_chip": est and round(
                        batch / est["seconds"], 1),
                    "peak_hbm_bytes": r.get("peak_hbm_bytes"),
                }
                results.append(rec)
                print(rec, flush=True)
    finally:
        mesh_mod.set_mesh(prev)
    return results


def main():
    out = {"config": "resnet50 train step, bf16 O2, DPx8 v5e proxy",
           "note": "est_* are compiler/roofline numbers, not measurements",
           "results": probe()}
    path = os.path.join(REPO, "artifacts", "resnet_aot_probe.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print("saved", path)


if __name__ == "__main__":
    main()

"""Checkpoint torture harness: loop save → crash → resume under FaultyFS.

Every iteration picks a fault plan (crash-before-rename, torn write,
transient OSErrors, slow I/O, or none) from a seeded RNG, attempts to
commit a checkpoint whose content encodes its step, "crashes" where the
injector says, then reboots with a clean filesystem and checks the two
invariants the atomic protocol promises:

  1. no corruption: every *visible* checkpoint passes full checksum
     validation — a crashed save is invisible, never torn;
  2. no lost step: load_latest() returns exactly the last checkpoint whose
     commit succeeded, with the exact payload that was saved.

Exits nonzero on any violation and records a run summary to
artifacts/ckpt_torture.json. The quick (<10 s) variant runs inside tier-1
(tests/test_robustness.py::TestTortureQuick).

    python tools/ckpt_torture.py --iterations 200 --seed 0
"""
from __future__ import annotations

import argparse
import json
import os
import random
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

PLANS = ("none", "crash_rename", "partial_write", "transient", "slow")


def _state_for(step):
    return {"w": np.full((4, 4), float(step), dtype=np.float32),
            "step": int(step)}


def _faulty_fs(plan, rng):
    from paddle_tpu.robustness.fault_injection import FaultyFS

    if plan == "crash_rename":
        return FaultyFS(crash_on_rename=1)
    if plan == "partial_write":
        # 1st write = payload, 2nd = manifest: both must leave no trace
        return FaultyFS(partial_write_on=rng.randint(1, 2))
    if plan == "transient":
        return FaultyFS(transient_oserrors=rng.randint(1, 2))
    if plan == "slow":
        return FaultyFS(slow_io=0.001)
    return FaultyFS()


def run_torture(iterations=100, root=None, seed=0, keep_last_n=3,
                use_async_every=7):
    """Returns a summary dict; summary["ok"] is the overall verdict."""
    from paddle_tpu.robustness.checkpoint import CheckpointManager
    from paddle_tpu.robustness.fault_injection import InjectedCrash

    # injected transient errors are the point of the exercise — the per-retry
    # warnings would drown the summary
    import logging

    logging.getLogger("paddle_tpu.robustness.checkpoint").setLevel(
        logging.ERROR)

    root = root or tempfile.mkdtemp(prefix="ckpt_torture_")
    rng = random.Random(seed)
    summary = {"iterations": iterations, "root": root, "seed": seed,
               "commits": 0, "crashes": 0, "transient_absorbed": 0,
               "async_saves": 0, "lost_steps": 0, "corrupt_visible": 0,
               "stale_tmps_collected": 0, "plan_counts": {p: 0 for p in PLANS},
               "failures": []}
    last_committed = None

    for step in range(iterations):
        plan = rng.choice(PLANS)
        summary["plan_counts"][plan] += 1
        fs = _faulty_fs(plan, rng)
        mgr = CheckpointManager(root, keep_last_n=keep_last_n, fs=fs,
                                retries=3, backoff=0.001)
        use_async = plan in ("none", "slow") and step % use_async_every == 0
        try:
            if use_async:
                summary["async_saves"] += 1
                mgr.save_async(_state_for(step), step)
                mgr.close()  # close() during (possibly) in-flight write
            else:
                mgr.save(_state_for(step), step)
            last_committed = step
            summary["commits"] += 1
            if plan == "transient":
                summary["transient_absorbed"] += 1
        except InjectedCrash:
            summary["crashes"] += 1
        except OSError:
            summary["crashes"] += 1  # retries exhausted = failed save

        # --- reboot: clean fs, fresh manager ---
        clean = CheckpointManager(root, keep_last_n=keep_last_n)
        tmps = [n for n in clean.fs.listdir(root) if ".tmp-" in n]
        clean.gc()
        summary["stale_tmps_collected"] += len(
            [n for n in tmps
             if not clean.fs.exists(os.path.join(root, n))])
        for s in clean.steps():
            if clean.validate(s) is None:
                summary["corrupt_visible"] += 1
                summary["failures"].append(
                    {"step": step, "plan": plan,
                     "error": f"visible checkpoint step {s} fails validation"})
        found = clean.load_latest()
        if last_committed is None:
            continue
        if found is None:
            summary["lost_steps"] += 1
            summary["failures"].append(
                {"step": step, "plan": plan,
                 "error": f"committed step {last_committed} lost entirely"})
            continue
        state, got_step, _ = found
        if got_step != last_committed:
            summary["lost_steps"] += 1
            summary["failures"].append(
                {"step": step, "plan": plan,
                 "error": f"resumed at {got_step}, expected {last_committed}"})
        elif not (state["step"] == last_committed
                  and np.all(state["w"] == float(last_committed))):
            summary["corrupt_visible"] += 1
            summary["failures"].append(
                {"step": step, "plan": plan,
                 "error": f"payload mismatch at step {got_step}"})

    summary["ok"] = (summary["corrupt_visible"] == 0
                     and summary["lost_steps"] == 0
                     and summary["commits"] > 0)
    return summary


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--iterations", type=int, default=200)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--keep-last-n", type=int, default=3)
    ap.add_argument("--root", default=None,
                    help="checkpoint dir (default: fresh temp dir)")
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "artifacts", "ckpt_torture.json"))
    args = ap.parse_args(argv)

    summary = run_torture(iterations=args.iterations, root=args.root,
                          seed=args.seed, keep_last_n=args.keep_last_n)
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(summary, f, indent=1)
    print(json.dumps({k: v for k, v in summary.items() if k != "failures"},
                     indent=1))
    if not summary["ok"]:
        print(f"TORTURE FAILED: {summary['failures'][:5]}", file=sys.stderr)
        return 1
    print(f"OK: {summary['commits']} commits survived "
          f"{summary['crashes']} injected crashes with no corruption "
          f"and no lost steps")
    return 0


if __name__ == "__main__":
    sys.exit(main())

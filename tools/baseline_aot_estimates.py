"""TPU-AOT estimates for the BASELINE throughput configs (2, 3, 4).

Every BASELINE.md row that asks for samples/sec+MFU gets a TPU-backend
artifact even when the tunnel can't execute: the REAL TrainStep for each
config is AOT-compiled with the TPU compiler (jax.experimental
.topologies) at the bench shapes, recording per-device memory and a
labeled roofline step-time bound from the compiler's own cost counters.

Measurements still come from bench.py on the live chip; these rows exist
so a wedged round records TPU-compiler evidence per config, and so
regressions that only show up in TPU lowering (layout, fusion, kernel
choice) are visible without hardware.

Single-chip configs compile as pure data-parallel x8 over a v5e:2x4
topology (TrainStep needs a >1-device mesh to target the topology); the
per-chip program matches the single-chip bench shape plus a grad
all-reduce, so the bound is slightly conservative.

Usage: python tools/baseline_aot_estimates.py
Writes artifacts/baseline_aot_estimates.json.
"""
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from paddle_tpu.jit.aot import V5E_PEAK_BF16_FLOPS as V5E_PEAK_BF16


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")

    import numpy as np
    from jax.sharding import PartitionSpec as P

    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    import paddle_tpu.optimizer as opt
    from paddle_tpu.amp import auto_cast
    from paddle_tpu.distributed import mesh as mesh_mod
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.jit.aot import (
        aot_compile_step, estimate_step_seconds, topology_mesh,
    )

    rs = np.random.RandomState(0)
    results = {}

    def run(name, build, per_chip_items, unit):
        """build() -> (step, inputs, labels, amp, flops_per_item) under no
        mesh; compiled DPx8 against the topology."""
        mesh_mod.set_mesh(None)
        t0 = time.time()
        try:
            step, inputs, labels, amp, flops_per_item = build()
            mesh_mod.set_mesh(topology_mesh("v5e:2x4", {"data": 8}))
            with auto_cast(enable=amp, level="O2", dtype="bfloat16"):
                cost = aot_compile_step(step, inputs, labels,
                                        want_cost=True)
        except Exception as e:
            results[name] = {"error": f"{type(e).__name__}: {str(e)[:300]}"}
            print(f"  {name}: FAILED {results[name]['error'][:100]}")
            return
        finally:
            mesh_mod.set_mesh(None)
        row = {"per_chip_batch_items": per_chip_items, "unit": unit,
               "peak_hbm_bytes": cost.get("peak_hbm_bytes"),
               "compile_seconds": round(time.time() - t0, 1),
               "note": "roofline = LOWER bound on step time; DPx8 proxy"}
        sec = estimate_step_seconds(cost)
        if sec:
            row["est_step_seconds"] = round(sec["seconds"], 6)
            row["est_signal"] = sec["signal"]
            row["est_items_per_sec_chip"] = round(
                per_chip_items / sec["seconds"], 1)
            if flops_per_item and sec["seconds"] > 0:
                row["est_mfu"] = round(
                    flops_per_item * per_chip_items / sec["seconds"]
                    / V5E_PEAK_BF16, 4)
        results[name] = row
        peak = (f"{row['peak_hbm_bytes']/2**30:.2f} GiB"
                if row["peak_hbm_bytes"] is not None else "?")
        print(f"  {name}: peak {peak}, "
              + (f"est {row['est_items_per_sec_chip']:.0f} {unit} "
                 f"({row['est_signal']})" if sec else "no estimate")
              + f" [{row['compile_seconds']:.0f}s]")

    # ---- config 2: ResNet-50, b=64 img=224, bf16 O2 (bench shapes) ----
    def build_resnet():
        from paddle_tpu.vision.models import resnet50

        model = resnet50(num_classes=1000)
        optim = opt.Momentum(learning_rate=0.01, momentum=0.9,
                             parameters=model.parameters())
        step = TrainStep(model, lambda lg, y: F.cross_entropy(lg, y),
                         optim, batch_spec=P("data"))
        b = 64 * 8
        x = paddle.to_tensor(rs.randn(b, 3, 224, 224).astype("float32"))
        y = paddle.to_tensor(rs.randint(0, 1000, (b,)), dtype="int64")
        return step, (x,), (y,), True, 3 * 4.09e9  # ~3x fwd FLOPs/sample

    run("resnet50_b64_224_bf16", build_resnet, 64, "samples/s/chip")

    # ---- config 3: BERT-base MLM+NSP, b=16 s=512, bf16 O2 ----
    def build_bert():
        from paddle_tpu.models import BertForPretraining, bert_presets

        cfg = bert_presets("bert-base")
        model = BertForPretraining(cfg)
        optim = opt.AdamW(learning_rate=1e-4,
                          parameters=model.parameters())
        step = TrainStep(
            model,
            lambda mlm_loss, nsp_logits, nsp_lbl:
                mlm_loss + F.cross_entropy(nsp_logits, nsp_lbl),
            optim, batch_spec=P("data"))
        b, s = 16 * 8, 512
        ids = rs.randint(0, cfg.vocab_size, (b, s))
        mlm = np.where(rs.rand(b, s) < 0.15, ids, -1)
        # same formula as bench.measure_bert: 6*params + bidirectional attn
        h, L, v = cfg.hidden_size, cfg.num_layers, cfg.vocab_size
        n_params = v * h + s * h + 2 * h + L * 12 * h * h + 2 * h * h
        flops_per_sample = (6 * n_params + 12 * L * s * h) * s
        return (step,
                (paddle.to_tensor(ids, dtype="int64"), None, None, None,
                 paddle.to_tensor(mlm, dtype="int64")),
                (paddle.to_tensor(rs.randint(0, 2, (b,)), dtype="int64"),),
                True, flops_per_sample)

    run("bert_base_b16_512_bf16", build_bert, 16, "samples/s/chip")

    # config 4 (GPT-1.3B) is covered by tools/gpt13b_aot_tpu.py and the
    # planner sweep; config 1 (MNIST) is a correctness milestone and
    # config 5 (Wide&Deep PS) is host-side — no AOT row applies.

    path = os.path.join(REPO, "artifacts", "baseline_aot_estimates.json")
    with open(path, "w") as f:
        json.dump(results, f, indent=1)
    print(f"wrote {path}")


if __name__ == "__main__":
    main()

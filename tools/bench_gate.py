"""Bench regression gate: fresh gpt bench vs the BENCH_r*.json trajectory.

Every PR's driver records one `BENCH_rNN.json` (the round's bench.py gpt
JSON under "parsed"); nothing ever LOOKED at the sequence, so a 20%
throughput regression would ride to main unremarked. This gate closes the
loop: it extracts the gated metrics from a candidate record, builds a
per-metric baseline from the comparable trajectory records (same device
class — a CPU-fallback number is never judged against a TPU one), applies
a tolerance band, and exits nonzero on any regression:

  tokens_per_sec    bench `value`                        higher is better
  exposed_comm_ms   `exposed_comm_ms.overlapped`         lower is better
  peak_hbm_bytes    `peak_hbm_bytes_measured` (ISSUE 6)  lower is better

The baseline is the trajectory's BEST value per metric (max/min by
direction): a regression against best-ever is what the tolerance band is
FOR — transient noise lives inside the band, real regressions don't.
Metrics absent from either side are reported as SKIP (old records predate
`exposed_comm_ms`/`peak_hbm_bytes_measured`); the gate fails with exit 2
if NOTHING was comparable, so a format drift can't silently pass.

Modes (exit 0 pass / 1 regression / 2 nothing comparable):

  python tools/bench_gate.py --offline
      newest trajectory record gated against the earlier ones — pure JSON
      reads, <10s, no jax import; the tier-1-adjacent smoke.
  python tools/bench_gate.py --candidate FRESH.json
      gate a recorded bench JSON (or a driver record wrapping one).
  python tools/bench_gate.py
      run `bench.py` (BENCH_MODE=gpt) now and gate its output.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import re
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# metric -> (extractor, direction); direction "higher"/"lower" = better
GATES = {
    "tokens_per_sec": (lambda r: r.get("value"), "higher"),
    "exposed_comm_ms": (
        lambda r: (r.get("exposed_comm_ms") or {}).get("overlapped"),
        "lower"),
    "peak_hbm_bytes": (lambda r: r.get("peak_hbm_bytes_measured"), "lower"),
    # ISSUE 8: wire bytes the COMPILED train step moves per sync — the
    # in-trace codec work must never quietly regress back to fat wire
    "comm_bytes_per_step_traced": (
        lambda r: r.get("comm_bytes_per_step_traced"), "lower"),
    # ISSUE 9 (ZeRO-3): exposed parameter-gather ms with the layer-ahead
    # prefetch, and the per-rank resident parameter bytes at rest — a
    # regression in either quietly un-hides the gathers or un-shards the
    # params (records predating ISSUE 9 SKIP these, by design)
    "zero3_exposed_gather_ms": (
        lambda r: r.get("zero3_exposed_gather_ms"), "lower"),
    "zero3_param_bytes_per_rank": (
        lambda r: r.get("zero3_param_bytes_per_rank"), "lower"),
    # ISSUE 10 (elastic resharding + preemption): the N=4→M=2 shard
    # geometry transform on gpt-test shapes, and the emergency preemption
    # checkpoint commit — both must stay inside the SIGTERM grace window,
    # so neither may quietly regress (records predating ISSUE 10 SKIP)
    "reshard_ms": (lambda r: r.get("reshard_ms"), "lower"),
    "emergency_save_ms": (lambda r: r.get("emergency_save_ms"), "lower"),
    # ISSUE 13 (pallas kernels + autotuner): one fused flat-bucket
    # optimizer update over the bench model's buckets, compiled — the
    # inner loop the fused dequant+update kernel owns on TPU. Monotone ↓
    # within the band; records predating ISSUE 13 SKIP (absent metric)
    "fused_update_ms": (lambda r: r.get("fused_update_ms"), "lower"),
    # ISSUE 14 (serving runtime): continuous-batching generated tokens/s
    # and request p99 latency from the bench serve smoke — throughput
    # must stay monotone up and tail latency monotone down within the
    # band (records predating ISSUE 14 SKIP, absent metric)
    "serve_tokens_per_s": (lambda r: r.get("serve_tokens_per_s"), "higher"),
    "serve_p99_ms": (lambda r: r.get("serve_p99_ms"), "lower"),
    # ISSUE 15 (pipeline training): the composed 1F1B train step's
    # analytic bubble share and its compiled activation watermark —
    # neither may quietly regress (a bubble increase means the schedule
    # geometry degraded; a watermark increase means the depth-bounded
    # memory story broke). Records predating ISSUE 15 SKIP (absent).
    "pipeline_bubble_pct": (
        lambda r: r.get("pipeline_bubble_pct"), "lower"),
    "pipeline_watermark_bytes": (
        lambda r: r.get("pipeline_watermark_bytes"), "lower"),
    # ISSUE 16 (prefix cache + speculative decode): prefix-cache hit-token
    # throughput on the Zipfian serve smoke, and mean committed tokens per
    # speculative verify step — both monotone up within the band (below
    # 1.0 tokens/step the draft model stopped paying for itself; records
    # predating ISSUE 16 SKIP, absent metric)
    "serve_cache_hit_tokens_per_s": (
        lambda r: r.get("serve_cache_hit_tokens_per_s"), "higher"),
    "serve_spec_tokens_per_step": (
        lambda r: r.get("serve_spec_tokens_per_step"), "higher"),
    # ISSUE 18 (request tracing): time-to-first-token tail at the stable
    # x1.0 load point (the interactive-latency number total latency hides
    # behind long decodes), and the tracing-on/off throughput ratio — at
    # 1.0 tracing is free, and the band holds the overhead under 20% so
    # per-request spans + exemplars can never quietly become a tax
    # (records predating ISSUE 18 SKIP, absent metric)
    "serve_ttft_p99_ms": (lambda r: r.get("serve_ttft_p99_ms"), "lower"),
    "serve_tracing_tokens_per_s_ratio": (
        lambda r: r.get("serve_tracing_tokens_per_s_ratio"), "higher"),
    # ISSUE 19 (zero-cold-start plane): warm replica boot latency —
    # pre-compiling the outgoing replica's shape buckets before it
    # drains — and TTFT from re-admission to first token across a
    # warm-handoff eviction. Either regressing means replacements are
    # compiling in traffic again, the exact window this plane closed
    # (records predating ISSUE 19 SKIP, absent metric)
    "replica_boot_warm_ms": (
        lambda r: r.get("replica_boot_warm_ms"), "lower"),
    "ttft_after_eviction_ms": (
        lambda r: r.get("ttft_after_eviction_ms"), "lower"),
    # ISSUE 20: the PS hot path — sustained examples/s of the compiled
    # dense step under the double-buffered sharded-embedding pipeline,
    # and the pull latency the overlap FAILS to hide. Throughput sliding
    # back means the step stopped being one program (or the pipeline
    # serialized); exposed pull creeping up means the prefetch window no
    # longer covers the embedding round-trip (records predating ISSUE 20
    # SKIP, absent metric)
    "ps_examples_per_s": (
        lambda r: r.get("ps_examples_per_s"), "higher"),
    "ps_exposed_pull_ms": (
        lambda r: r.get("ps_exposed_pull_ms"), "lower"),
}


def device_class(rec: dict) -> str:
    """"cpu" for fallback runs, else the device kind — only same-class
    records are comparable (CPU tokens/s says nothing about TPU)."""
    if rec.get("fallback") == "cpu":
        return "cpu"
    return str(rec.get("device_kind", "unknown"))


def extract(rec: dict) -> dict:
    """The gated metrics present in one bench gpt JSON."""
    out = {}
    for name, (get, _) in GATES.items():
        v = get(rec)
        if isinstance(v, (int, float)) and v > 0:
            out[name] = float(v)
    return out


def load_trajectory(root: str = REPO, pattern: str = "BENCH_r*.json"):
    """[(round_name, parsed_record)] for every driver round that produced
    a usable bench JSON (rc == 0, parsed gpt record), in round order."""
    out = []
    for path in sorted(glob.glob(os.path.join(root, pattern))):
        try:
            with open(path) as f:
                d = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        rec = d.get("parsed") if isinstance(d, dict) else None
        if isinstance(d, dict) and d.get("rc") == 0 and isinstance(rec, dict) \
                and "value" in rec:
            name = re.sub(r"\.json$", "", os.path.basename(path))
            out.append((name, rec))
    return out


def build_baseline(trajectory, dev_class: str) -> dict:
    """Per-metric best over the comparable records:
    {metric: (best_value, round_name)}."""
    base = {}
    for name, rec in trajectory:
        if device_class(rec) != dev_class:
            continue
        for metric, value in extract(rec).items():
            _, direction = GATES[metric]
            cur = base.get(metric)
            better = (cur is None
                      or (direction == "higher" and value > cur[0])
                      or (direction == "lower" and value < cur[0]))
            if better:
                base[metric] = (value, name)
    return base


def gate(candidate: dict, trajectory, tolerance: float):
    """Compare one candidate record against the trajectory baseline.
    Returns (rows, n_compared, n_regressed); each row is a dict with
    metric / baseline / candidate / ratio / verdict."""
    dev = device_class(candidate)
    baseline = build_baseline(trajectory, dev)
    cand = extract(candidate)
    rows, compared, regressed = [], 0, 0
    for metric, (_, direction) in GATES.items():
        row = {"metric": metric, "direction": direction}
        if metric not in cand or metric not in baseline:
            row["verdict"] = "SKIP"
            row["why"] = ("absent from candidate" if metric not in cand
                          else "absent from trajectory")
            rows.append(row)
            continue
        best, src = baseline[metric]
        value = cand[metric]
        ratio = value / best
        ok = (ratio >= 1.0 - tolerance if direction == "higher"
              else ratio <= 1.0 + tolerance)
        compared += 1
        regressed += 0 if ok else 1
        row.update(baseline=best, baseline_from=src, candidate=value,
                   ratio=round(ratio, 4), verdict="OK" if ok else "REGRESSED")
        rows.append(row)
    return rows, compared, regressed


def gate_static_wall(budget_s: float, wall=None):
    """Run the full tools/check_static.py pass and gate its wall time
    against an ABSOLUTE budget (the tier-1 contract: the interprocedural
    pass must not quietly eat the suite's time budget). Returns
    (row, regressed) in the same shape the metric gates use; a gate run
    that cannot produce timing JSON counts as format drift, so it
    regresses. ``wall`` overrides the measurement (tests exercise the
    verdict branches without re-running the pass)."""
    row = {"metric": "check_static_wall_s", "direction": "lower",
           "budget": budget_s}
    if wall is None:
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "check_static.py"),
             "--json"],
            capture_output=True, text=True, timeout=120)
        try:
            # stdout is the JSON report followed by the verdict line
            doc, _ = json.JSONDecoder().raw_decode(proc.stdout.lstrip())
            wall = doc.get("wall_s")
        except (json.JSONDecodeError, AttributeError):
            wall = None
        if not isinstance(wall, (int, float)):
            row.update(verdict="REGRESSED",
                       why=f"check_static produced no timing JSON "
                           f"(rc={proc.returncode})")
            return row, True
    ok = wall <= budget_s
    row.update(candidate=float(wall),
               verdict="OK" if ok else "REGRESSED")
    return row, not ok


FLEET_MIN_GOODPUT_RATIO = 1.2


def gate_fleet(artifact, min_ratio: float = FLEET_MIN_GOODPUT_RATIO):
    """Gate the fleet-controller section of a chaos_train artifact
    (ISSUE 17). Three absolute gates, same row shape as the metric gates:

      fleet_goodput_ratio        >= min_ratio (policy vs reactive baseline)
      scale_event_lost_requests  == 0 (drain + re-admit under churn)
      preempt_saves_in_grace     every preemption notice answered by a
                                 completed emergency save inside its grace
                                 deadline (and none left unanswered)

    ``artifact`` is a path to chaos_train.json or the loaded dict. A
    missing/unreadable fleet section is a REGRESSION, not a SKIP — the
    gate exists so the artifact cannot quietly stop carrying the
    evidence. Returns (rows, n_regressed)."""
    if isinstance(artifact, str):
        try:
            with open(artifact) as f:
                artifact = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            row = {"metric": "fleet_goodput_ratio", "direction": "higher",
                   "budget": min_ratio, "verdict": "REGRESSED",
                   "why": f"unreadable fleet artifact: {e}"}
            return [row], 1
    fleet = artifact.get("fleet") if isinstance(artifact, dict) else None
    if not isinstance(fleet, dict):
        row = {"metric": "fleet_goodput_ratio", "direction": "higher",
               "budget": min_ratio, "verdict": "REGRESSED",
               "why": "artifact has no fleet section — format drift?"}
        return [row], 1

    rows, regressed = [], 0

    ratio = fleet.get("fleet_goodput_ratio")
    ok = isinstance(ratio, (int, float)) and ratio >= min_ratio
    rows.append({"metric": "fleet_goodput_ratio", "direction": "higher",
                 "budget": min_ratio,
                 "candidate": ratio if isinstance(ratio, (int, float))
                 else float("nan"),
                 "verdict": "OK" if ok else "REGRESSED"})
    regressed += 0 if ok else 1

    lost = fleet.get("scale_event_lost_requests")
    ok = lost == 0
    rows.append({"metric": "scale_event_lost_requests",
                 "direction": "lower", "budget": 0,
                 "candidate": lost if isinstance(lost, (int, float))
                 else float("nan"),
                 "verdict": "OK" if ok else "REGRESSED"})
    regressed += 0 if ok else 1

    ok = (fleet.get("preempt_saves_in_grace") is True
          and fleet.get("preempt_unanswered_policy") == 0)
    rows.append({"metric": "preempt_saves_in_grace", "direction": "higher",
                 "budget": 1,
                 "candidate": 1 if ok else 0,
                 "verdict": "OK" if ok else "REGRESSED"})
    regressed += 0 if ok else 1
    return rows, regressed


def gate_warm_handoff(artifact):
    """Gate the warm-handoff section of a chaos_train artifact
    (ISSUE 19). Absolute gates, same row shape as the metric gates:

      warm_handoff_lost            == 0 across >= 3 replacement events
      warm_handoff_boots           every replacement boot mode=warm
                                   outcome=ok (no in-traffic compiles)
      warm_handoff_hang_in_boot    == 0 hang-evictions inside any boot
                                   window [t_start, t]
      warm_handoff_ttft            TTFT after eviction <= 1.5x steady p99

    Unlike the fleet section, an ABSENT warm_handoff section is a SKIP,
    not a regression: artifacts recorded before ISSUE 19 simply predate
    the phase. A present-but-violated section regresses.
    Returns (rows, n_regressed)."""
    if isinstance(artifact, str):
        try:
            with open(artifact) as f:
                artifact = json.load(f)
        except (OSError, json.JSONDecodeError):
            artifact = {}
    wh = artifact.get("warm_handoff") if isinstance(artifact, dict) else None
    if not isinstance(wh, dict):
        return [{"metric": "warm_handoff", "direction": "lower",
                 "verdict": "SKIP",
                 "why": "artifact predates ISSUE 19 (no warm_handoff "
                        "section)"}], 0

    rows, regressed = [], 0
    boots = wh.get("replacement_boots") or []

    lost = wh.get("lost")
    ok = lost == 0 and len(wh.get("events") or []) >= 3
    rows.append({"metric": "warm_handoff_lost", "direction": "lower",
                 "budget": 0,
                 "candidate": lost if isinstance(lost, (int, float))
                 else float("nan"),
                 "verdict": "OK" if ok else "REGRESSED"})
    regressed += 0 if ok else 1

    ok = bool(boots) and all(b.get("mode") == "warm"
                             and b.get("outcome") == "ok" for b in boots)
    rows.append({"metric": "warm_handoff_boots", "direction": "higher",
                 "budget": 1, "candidate": 1 if ok else 0,
                 "verdict": "OK" if ok else "REGRESSED"})
    regressed += 0 if ok else 1

    hib = wh.get("hang_evictions_in_boot_window")
    ok = hib == 0
    rows.append({"metric": "warm_handoff_hang_in_boot",
                 "direction": "lower", "budget": 0,
                 "candidate": hib if isinstance(hib, (int, float))
                 else float("nan"),
                 "verdict": "OK" if ok else "REGRESSED"})
    regressed += 0 if ok else 1

    ttft = wh.get("ttft_after_eviction_ms")
    steady = wh.get("steady_ttft_p99_ms")
    ok = (isinstance(ttft, (int, float)) and isinstance(steady, (int, float))
          and (wh.get("redispatched") == 0
               or ttft <= 1.5 * max(steady, 1e-9)))
    rows.append({"metric": "warm_handoff_ttft", "direction": "lower",
                 "budget": "1.5x steady p99",
                 "candidate": ttft if isinstance(ttft, (int, float))
                 else float("nan"),
                 "verdict": "OK" if ok else "REGRESSED"})
    regressed += 0 if ok else 1
    return rows, regressed


def run_fresh_bench() -> dict:
    """Run bench.py (gpt mode) and parse the result JSON off its last
    stdout line."""
    env = dict(os.environ, BENCH_MODE="gpt")
    proc = subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                          env=env, capture_output=True, text=True,
                          timeout=2700)
    sys.stderr.write(proc.stderr[-2000:])
    for line in reversed(proc.stdout.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            return json.loads(line)
    raise SystemExit(f"bench.py produced no JSON (rc={proc.returncode})")


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--offline", action="store_true",
                    help="gate the newest trajectory record against the "
                         "earlier ones (no bench run, <10s)")
    ap.add_argument("--candidate",
                    help="gate this bench JSON (bare record or driver "
                         "{rc, parsed} wrapper) instead of running bench.py")
    ap.add_argument("--tolerance", type=float, default=0.20,
                    help="allowed fractional slack per metric "
                         "(default 0.20)")
    ap.add_argument("--root", default=REPO,
                    help="directory holding the BENCH_r*.json trajectory")
    ap.add_argument("--static-budget", type=float, default=None,
                    metavar="SECONDS",
                    help="also run tools/check_static.py and fail if its "
                         "full-run wall time exceeds this many seconds "
                         "(the tier-1 static-analysis time budget)")
    ap.add_argument("--fleet-artifact", default=None, metavar="PATH",
                    help="also gate the fleet-controller section of this "
                         "chaos_train.json: goodput ratio >= "
                         f"{FLEET_MIN_GOODPUT_RATIO}, zero lost requests "
                         "across scale events, emergency saves in grace")
    args = ap.parse_args(argv)

    trajectory = load_trajectory(args.root)
    if args.candidate:
        with open(args.candidate) as f:
            candidate = json.load(f)
        if isinstance(candidate, dict) and isinstance(
                candidate.get("parsed"), dict):
            candidate = candidate["parsed"]
        source = args.candidate
    elif args.offline:
        if not trajectory:
            print("bench_gate: no usable BENCH_r*.json records", file=sys.stderr)
            return 2
        source, candidate = trajectory[-1]
        trajectory = trajectory[:-1]
    else:
        candidate = run_fresh_bench()
        source = "bench.py (fresh run)"

    if not trajectory:
        print("bench_gate: empty baseline trajectory", file=sys.stderr)
        return 2

    rows, compared, regressed = gate(candidate, trajectory, args.tolerance)
    if args.static_budget is not None:
        srow, sregressed = gate_static_wall(args.static_budget)
        rows.append(srow)
        compared += 1
        regressed += 1 if sregressed else 0
    if args.fleet_artifact is not None:
        frows, fregressed = gate_fleet(args.fleet_artifact)
        rows.extend(frows)
        compared += len(frows)
        regressed += fregressed
        # ISSUE 19: same artifact also carries the warm-handoff section
        # (SKIP on artifacts that predate the phase)
        wrows, wregressed = gate_warm_handoff(args.fleet_artifact)
        rows.extend(wrows)
        compared += sum(1 for r in wrows if r["verdict"] != "SKIP")
        regressed += wregressed
    print(f"bench_gate: candidate={source} "
          f"device={device_class(candidate)} "
          f"baseline={len(trajectory)} records tol={args.tolerance:.0%}")
    for r in rows:
        if r["verdict"] == "SKIP":
            print(f"  {r['metric']:<18} SKIP ({r['why']})")
        elif "budget" in r:     # absolute gates (static wall, fleet, warm)
            arrow = "^" if r["direction"] == "higher" else "v"
            detail = (f"candidate={r['candidate']:.2f}"
                      if "candidate" in r else r.get("why", ""))
            budget = (f"{r['budget']:.2f}"
                      if isinstance(r["budget"], (int, float))
                      else str(r["budget"]))
            print(f"  {r['metric']:<22} {r['verdict']:<9} "
                  f"{detail} vs budget={budget} ({arrow} better)")
        else:
            arrow = "^" if r["direction"] == "higher" else "v"
            print(f"  {r['metric']:<18} {r['verdict']:<9} "
                  f"candidate={r['candidate']:,.1f} vs "
                  f"best={r['baseline']:,.1f} [{r['baseline_from']}] "
                  f"ratio={r['ratio']} ({arrow} better)")
    if compared == 0:
        print("bench_gate: NOTHING comparable — format drift?",
              file=sys.stderr)
        return 2
    if regressed:
        print(f"bench_gate: {regressed}/{compared} metric(s) REGRESSED")
        return 1
    print(f"bench_gate: pass ({compared} metric(s) within band)")
    return 0


if __name__ == "__main__":
    sys.exit(main())

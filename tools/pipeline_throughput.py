"""1F1B vs GPipe vs single-device throughput (VERDICT r3 next #2).

The reference's 1F1B exists to bound activation memory WITHOUT giving up
throughput (fleet/meta_parallel/pipeline_parallel.py:80-150,
section_worker.cc:143-199: memory win at equal speed). The memory half is
proven by tests/test_pipeline_1f1b.py::test_1f1b_memory_is_o_p_not_o_m;
this tool measures the speed half at equal global batch:

  single      one device, plain jax.grad (no pipeline, the roofline)
  gpipe       AD through pipeline_spmd (fill-drain; O(M) residual memory)
  gpipe_rem   same, jax.checkpoint on the stage body (recompute parity
              with 1F1B: the honest equal-memory-policy comparison)
  1f1b        the hand-scheduled segmented 1F1B scan (O(P) stash)

Work-unit model (1 unit = one stage-forward of one micro-batch; backward
= 2, recompute-backward = 3):

  gpipe       fwd wave (M+P-1) ticks x1 + bwd wave (M+P-1) x2 = 3(M+P-1)
  gpipe_rem   1x + 3x over the two waves                      = 4(M+P-1)
  1f1b        P fill x1 + (M-1) steady x4 + P drain x3        = 4M+4P-4
              (the segmented schedule; the pre-segmentation lockstep scan
               paid 4(M+2P-1) — both phases on every tick)

So at any M the segmented 1F1B costs no more than gpipe_rem, and its edge
over fill-drain grows with P. On this host the CPU "mesh" is 1 real core,
so wall-clock ~ TOTAL work summed over virtual devices; on real multi-chip
hardware the same tick accounting divides by P. Either way the RATIOS
between schedules are what this measures.

Writes artifacts/pipeline_throughput.json and prints the table.
"""
import json
import os
import sys
import time

_NDEV = max(8, int(os.environ.get("PIPE_BENCH_P", 4)))
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + f" --xla_force_host_platform_device_count={_NDEV}")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

if os.environ.get("PIPE_BENCH_BACKEND", "cpu") == "cpu":
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

from paddle_tpu.distributed import mesh as mesh_mod
from paddle_tpu.distributed.pipeline import pipeline_1f1b

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tests"))
from pipeline_toy import (  # the shared toy pipeline model  # noqa: E402
    DIN, DOUT, SPECS, bench_min, embed_fn, gpipe_value_and_grad, loss_fn,
    make_params, stage_fn,
)

PIPE = int(os.environ.get("PIPE_BENCH_P", 4))
KPER = int(os.environ.get("PIPE_BENCH_KPER", 2))   # layers per stage
HID = int(os.environ.get("PIPE_BENCH_HID", 512))
MB = int(os.environ.get("PIPE_BENCH_MB", 16))      # micro-batch size
STEPS = int(os.environ.get("PIPE_BENCH_STEPS", 8))


def bench(fn, args, steps=STEPS):
    return bench_min(fn, args, steps)


def build_steps(mesh, M):
    """Return dict name -> jitted (params, x, lbl) -> grads-ish."""
    def single(p, x, lbl):
        def full(p):
            h = embed_fn(p, x)
            h = stage_fn(p, h)
            return loss_fn(p, h, lbl)
        return jax.value_and_grad(full)(p)

    def f1b(p, x, lbl):
        return pipeline_1f1b(embed_fn, stage_fn, loss_fn, p, x, lbl,
                             mesh=mesh, param_specs=SPECS, microbatches=M)

    return {
        "single": jax.jit(single),
        "gpipe": jax.jit(lambda p, x, l: gpipe_value_and_grad(
            mesh, M, p, x, l, remat=False)),
        "gpipe_remat": jax.jit(lambda p, x, l: gpipe_value_and_grad(
            mesh, M, p, x, l, remat=True)),
        "1f1b": jax.jit(f1b),
    }


def main():
    M = int(os.environ.get("PIPE_BENCH_M", 4 * PIPE))
    batch = M * MB
    mesh = mesh_mod.build_mesh({"pipe": PIPE}, devices=jax.devices()[:PIPE])

    rs = np.random.RandomState(0)
    params = make_params(rs, PIPE * KPER, HID)
    x = jnp.asarray(rs.randn(batch, DIN), jnp.float32)
    lbl = jnp.asarray(rs.randn(batch, DOUT), jnp.float32)

    steps = build_steps(mesh, M)
    rows = {}
    for name, fn in steps.items():
        dt = bench(fn, (params, x, lbl))
        rows[name] = {"step_ms": round(dt * 1e3, 2),
                      "samples_per_sec": round(batch / dt, 1)}
        print(f"{name:12s} {dt*1e3:8.1f} ms/step "
              f"{batch/dt:10.1f} samples/s", file=sys.stderr)

    # analytic tick accounting (units: one stage-forward of one micro-batch)
    model = {
        "gpipe": 3 * (M + PIPE - 1),
        "gpipe_remat": 4 * (M + PIPE - 1),
        "1f1b": 4 * M + 4 * PIPE - 4,
        "1f1b_pre_segmentation": 4 * (M + 2 * PIPE - 1),
    }
    result = {
        "config": {"pipe": PIPE, "layers_per_stage": KPER, "hidden": HID,
                   "microbatches": M, "micro_batch_size": MB,
                   "global_batch": batch, "steps": STEPS,
                   "backend": jax.devices()[0].platform,
                   "note": "1-core host: wall-clock ~ total work over "
                           "virtual devices; ratios carry to real chips"},
        "measured": rows,
        "work_unit_model": model,
        "bubble_fraction_1f1b": round((2 * PIPE - 1) / (M + 2 * PIPE - 1), 4),
        "recompute_overhead": "1f1b and gpipe_remat recompute the stage "
                              "forward during backward (~4/3 fwd FLOPs)",
        "ratio_1f1b_over_gpipe_remat": round(
            rows["1f1b"]["step_ms"] / rows["gpipe_remat"]["step_ms"], 3),
        "ratio_1f1b_over_gpipe": round(
            rows["1f1b"]["step_ms"] / rows["gpipe"]["step_ms"], 3),
    }
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "artifacts",
        "pipeline_throughput.json")
    with open(path, "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps(result["measured"]))
    print(f"saved -> {path}", file=sys.stderr)


if __name__ == "__main__":
    main()

"""1F1B vs GPipe vs single-device throughput (VERDICT r3 next #2).

The reference's 1F1B exists to bound activation memory WITHOUT giving up
throughput (fleet/meta_parallel/pipeline_parallel.py:80-150,
section_worker.cc:143-199: memory win at equal speed). The memory half is
proven by tests/test_pipeline_1f1b.py::test_1f1b_memory_is_o_p_not_o_m;
this tool measures the speed half at equal global batch:

  single      one device, plain jax.grad (no pipeline, the roofline)
  gpipe       AD through pipeline_spmd (fill-drain; O(M) residual memory)
  gpipe_rem   same, jax.checkpoint on the stage body (recompute parity
              with 1F1B: the honest equal-memory-policy comparison)
  1f1b        the hand-scheduled segmented 1F1B scan (O(P) stash)

Work-unit model (1 unit = one stage-forward of one micro-batch; backward
= 2, recompute-backward = 3):

  gpipe       fwd wave (M+P-1) ticks x1 + bwd wave (M+P-1) x2 = 3(M+P-1)
  gpipe_rem   1x + 3x over the two waves                      = 4(M+P-1)
  1f1b        P fill x1 + (M-1) steady x4 + P drain x3        = 4M+4P-4
              (the segmented schedule; the pre-segmentation lockstep scan
               paid 4(M+2P-1) — both phases on every tick)

So at any M the segmented 1F1B costs no more than gpipe_rem, and its edge
over fill-drain grows with P. On this host the CPU "mesh" is 1 real core,
so wall-clock ~ TOTAL work summed over virtual devices; on real multi-chip
hardware the same tick accounting divides by P. Either way the RATIOS
between schedules are what this measures.

Writes artifacts/pipeline_throughput.json and prints the table.

`--composed` (ISSUE 15) benches the COMPOSED training path instead: the
gpt-test PipelineTrainStep (1F1B as the loss+grad engine of one compiled
step, planner-managed activation memory) against the unpipelined
TrainStep at equal global batch, and writes
artifacts/pipeline_bench.json carrying the fields bench.py's gpt JSON
embeds and tools/bench_gate.py gates: `pipeline_bubble_pct` (analytic
(P-1)/(M+P-1) of the running geometry) and `pipeline_watermark_bytes`
(XLA temp bytes of the composed step — the activation watermark the
schedule bounds by depth; the JSON also records the temp bytes at 4x the
micro-batches to show the bound holding).
"""
import json
import os
import sys
import time

_NDEV = max(8, int(os.environ.get("PIPE_BENCH_P", 4)))
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + f" --xla_force_host_platform_device_count={_NDEV}")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

if os.environ.get("PIPE_BENCH_BACKEND", "cpu") == "cpu":
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

from paddle_tpu.distributed import mesh as mesh_mod
from paddle_tpu.distributed.pipeline import pipeline_1f1b

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tests"))
from pipeline_toy import (  # the shared toy pipeline model  # noqa: E402
    DIN, DOUT, SPECS, bench_min, embed_fn, gpipe_value_and_grad, loss_fn,
    make_params, stage_fn,
)

PIPE = int(os.environ.get("PIPE_BENCH_P", 4))
KPER = int(os.environ.get("PIPE_BENCH_KPER", 2))   # layers per stage
HID = int(os.environ.get("PIPE_BENCH_HID", 512))
MB = int(os.environ.get("PIPE_BENCH_MB", 16))      # micro-batch size
STEPS = int(os.environ.get("PIPE_BENCH_STEPS", 8))


def bench(fn, args, steps=STEPS):
    return bench_min(fn, args, steps)


def build_steps(mesh, M):
    """Return dict name -> jitted (params, x, lbl) -> grads-ish."""
    def single(p, x, lbl):
        def full(p):
            h = embed_fn(p, x)
            h = stage_fn(p, h)
            return loss_fn(p, h, lbl)
        return jax.value_and_grad(full)(p)

    def f1b(p, x, lbl):
        return pipeline_1f1b(embed_fn, stage_fn, loss_fn, p, x, lbl,
                             mesh=mesh, param_specs=SPECS, microbatches=M)

    return {
        "single": jax.jit(single),
        "gpipe": jax.jit(lambda p, x, l: gpipe_value_and_grad(
            mesh, M, p, x, l, remat=False)),
        "gpipe_remat": jax.jit(lambda p, x, l: gpipe_value_and_grad(
            mesh, M, p, x, l, remat=True)),
        "1f1b": jax.jit(f1b),
    }


def composed_bench(pipe=2, M=8, batch=16, seq=64, steps=4):
    """Bench the composed PipelineTrainStep vs the unpipelined TrainStep
    at equal global batch on gpt-test; returns the pipeline_bench.json
    record (also printed as the last stdout line for bench.py)."""
    import paddle_tpu as paddle
    import paddle_tpu.optimizer as popt
    from paddle_tpu.distributed import mesh as pmesh
    from paddle_tpu.distributed.pipeline import PipelineTrainStep
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models import (
        GPTForCausalLM, GPTPretrainingCriterion, gpt_presets,
    )

    rs = np.random.RandomState(0)
    ids_np = rs.randint(0, 256, (batch, seq))
    lbl_np = rs.randint(0, 256, (batch, seq))

    def T(a):
        return paddle.to_tensor(a, dtype="int64")

    def make(pipelined, microbatches):
        cfg = gpt_presets("gpt-test", mode="scan",
                          use_flash_attention=False,
                          pp_microbatches=microbatches)
        model = GPTForCausalLM(cfg, seed=0)
        optim = popt.AdamW(learning_rate=1e-3,
                           parameters=model.parameters())
        if pipelined:
            return PipelineTrainStep(model, optim, memory_plan=None)
        crit = GPTPretrainingCriterion()
        return TrainStep(model, lambda lg, lb: crit(lg, lb), optim,
                         grad_accum_steps=microbatches)

    def bench_step(step):
        def one():
            return float(step(inputs=(T(ids_np),), labels=(T(lbl_np),)))

        loss = one()                       # compile + warm
        best = float("inf")
        for _ in range(steps):
            t0 = time.perf_counter()
            loss = one()
            best = min(best, time.perf_counter() - t0)
        return best, loss

    pmesh.set_mesh(None)
    t_ref, loss_ref = bench_step(make(False, M))

    pmesh.set_mesh(pmesh.build_mesh({"pipe": pipe},
                                    devices=jax.devices()[:pipe]))
    step = make(True, M)
    t_pipe, loss_pipe = bench_step(step)
    mem = step.memory_analysis(record=False)
    watermark = int(mem["temp_bytes"]) if mem else None

    # the depth-bound evidence: 4x the micro-batches at the same
    # micro-batch size must not grow the watermark (stash caps at 2P-1)
    watermark_4m = None
    if mem:
        step4 = make(True, 4 * M)
        ids4 = rs.randint(0, 256, (4 * batch, seq))
        step4(inputs=(T(ids4),), labels=(T(ids4),))
        mem4 = step4.memory_analysis(record=False)
        watermark_4m = int(mem4["temp_bytes"]) if mem4 else None
    pmesh.set_mesh(None)

    rep = step.report()
    tokens = batch * seq
    rec = {
        "config": {"preset": "gpt-test", "pipe": pipe, "microbatches": M,
                   "global_batch": batch, "seq": seq, "steps": steps,
                   "backend": jax.devices()[0].platform},
        "pipeline_bubble_pct": rep["pipeline_bubble_pct"],
        "pipeline_watermark_bytes": watermark,
        "watermark_bytes_at_4x_microbatches": watermark_4m,
        "stash_slots": rep["stash_slots"],
        "tokens_per_s": {
            "pipelined": round(tokens / t_pipe, 1),
            "unpipelined": round(tokens / t_ref, 1),
            "ratio": round(t_ref / t_pipe, 3),
        },
        "loss_first_step": {"pipelined": loss_pipe,
                            "unpipelined_ref": loss_ref},
        "note": ("CPU virtual devices serialize the stages: wall-clock "
                 "ratios do not transfer to real chips; bubble % and the "
                 "watermark bound are device-independent"),
    }
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "artifacts", "pipeline_bench.json")
    with open(path, "w") as f:
        json.dump(rec, f, indent=1)
    print(f"saved -> {path}", file=sys.stderr)
    print(json.dumps(rec))
    return rec


def main():
    M = int(os.environ.get("PIPE_BENCH_M", 4 * PIPE))
    batch = M * MB
    mesh = mesh_mod.build_mesh({"pipe": PIPE}, devices=jax.devices()[:PIPE])

    rs = np.random.RandomState(0)
    params = make_params(rs, PIPE * KPER, HID)
    x = jnp.asarray(rs.randn(batch, DIN), jnp.float32)
    lbl = jnp.asarray(rs.randn(batch, DOUT), jnp.float32)

    steps = build_steps(mesh, M)
    rows = {}
    for name, fn in steps.items():
        dt = bench(fn, (params, x, lbl))
        rows[name] = {"step_ms": round(dt * 1e3, 2),
                      "samples_per_sec": round(batch / dt, 1)}
        print(f"{name:12s} {dt*1e3:8.1f} ms/step "
              f"{batch/dt:10.1f} samples/s", file=sys.stderr)

    # analytic tick accounting (units: one stage-forward of one micro-batch)
    model = {
        "gpipe": 3 * (M + PIPE - 1),
        "gpipe_remat": 4 * (M + PIPE - 1),
        "1f1b": 4 * M + 4 * PIPE - 4,
        "1f1b_pre_segmentation": 4 * (M + 2 * PIPE - 1),
    }
    result = {
        "config": {"pipe": PIPE, "layers_per_stage": KPER, "hidden": HID,
                   "microbatches": M, "micro_batch_size": MB,
                   "global_batch": batch, "steps": STEPS,
                   "backend": jax.devices()[0].platform,
                   "note": "1-core host: wall-clock ~ total work over "
                           "virtual devices; ratios carry to real chips"},
        "measured": rows,
        "work_unit_model": model,
        "bubble_fraction_1f1b": round((2 * PIPE - 1) / (M + 2 * PIPE - 1), 4),
        "recompute_overhead": "1f1b and gpipe_remat recompute the stage "
                              "forward during backward (~4/3 fwd FLOPs)",
        "ratio_1f1b_over_gpipe_remat": round(
            rows["1f1b"]["step_ms"] / rows["gpipe_remat"]["step_ms"], 3),
        "ratio_1f1b_over_gpipe": round(
            rows["1f1b"]["step_ms"] / rows["gpipe"]["step_ms"], 3),
    }
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "artifacts",
        "pipeline_throughput.json")
    with open(path, "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps(result["measured"]))
    print(f"saved -> {path}", file=sys.stderr)


if __name__ == "__main__":
    if "--composed" in sys.argv:
        composed_bench()
    else:
        main()

"""TPU perf sprint — run this FIRST THING when the tunnel is healthy.

Probes the chip, then measures in priority order (each result prints
immediately, so a mid-run tunnel death still leaves numbers):

  1. baseline bench (the driver's metric)
  2. fused chunked linear+CE A/B over candidate chunk sizes
  3. flash-attention block-size sweep on the bench shape

Usage:  python tools/tpu_perf_sprint.py [--quick]
Record winners in artifacts/ROUND2_NOTES.md (or the current round's notes)
and flip defaults (GPTConfig.fused_loss_chunk, flash block_size) if a
config beats the baseline.
"""
import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def probe(timeout=240):
    # Execution probe, not enumeration: the 2026-07-31 wedge mode lists
    # devices instantly but hangs any compile/execute, so require a real
    # matmul round-trip before declaring the tunnel healthy.
    code = (
        "import jax, jax.numpy as jnp; "
        "d = jax.devices()[0]; "
        "assert 'tpu' in d.platform.lower() or 'axon' in str(d).lower(); "
        "x = jnp.ones((256, 256), jnp.bfloat16); "
        "(x @ x).block_until_ready(); "
        "print('EXEC-OK')"
    )
    try:
        r = subprocess.run([sys.executable, "-c", code], timeout=timeout,
                           capture_output=True, text=True)
        return r.returncode == 0 and "EXEC-OK" in r.stdout
    except subprocess.TimeoutExpired:
        return False


def run_bench(env_extra, label, timeout=900):
    env = dict(os.environ, _GRAFT_BENCH_CHILD="1", **env_extra)
    t0 = time.time()
    try:
        r = subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                           env=env, capture_output=True, text=True,
                           timeout=timeout)
    except subprocess.TimeoutExpired:
        print(f"  {label}: TIMEOUT after {timeout}s")
        return None
    for line in r.stdout.splitlines():
        if line.startswith("BENCH_JSON:"):
            rec = json.loads(line[len("BENCH_JSON:"):])
            print(f"  {label}: {rec['value']:.0f} {rec['unit']} "
                  f"(vs_baseline {rec['vs_baseline']}) "
                  f"[{time.time()-t0:.0f}s]")
            return rec
    print(f"  {label}: no result; stderr tail: {r.stderr[-300:]}")
    return None


def _save(results):
    path = os.path.join(REPO, "artifacts", "TPU_RESULTS.json")
    try:
        existing = json.load(open(path))
    except (FileNotFoundError, json.JSONDecodeError):
        existing = {}
    # never let failure fallbacks (value 0.0 / "error") or CPU-fallback
    # numbers overwrite real TPU results; CPU fallbacks that did produce a
    # value (e.g. widedeep's device-independent AUC) persist under a
    # separate __cpu key instead
    for k, v in results.items():
        if not v or v.get("error") or not v.get("value"):
            continue
        if v.get("fallback") == "cpu":
            existing[k + "__cpu"] = v
        else:
            existing[k] = v
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(existing, f, indent=1)
    os.replace(tmp, path)
    print(f"saved {len(existing)} results -> {path}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="baseline + one fused chunk only")
    ap.add_argument("--probe-only", action="store_true",
                    help="exit 0 iff the chip executes a matmul (shared "
                         "probe entry point for tpu_watchdog.sh)")
    args = ap.parse_args()

    if args.probe_only:
        sys.exit(0 if probe() else 1)

    print("probing TPU tunnel ...")
    if not probe():
        print("tunnel is DOWN — nothing measured; try again later")
        sys.exit(1)
    print("tunnel healthy; measuring\n")

    results = {}
    results["baseline"] = run_bench({}, "baseline gpt-125m")
    _save(results)

    chunks = ["6288"] if args.quick else ["4192", "6288", "8384", "12576"]
    for c in chunks:
        results[f"fused_ce_{c}"] = run_bench(
            {"BENCH_FUSED_CE": c}, f"fused CE chunk={c}")
        _save(results)

    # the other BASELINE.md configs (each saves immediately; a mid-run
    # tunnel death still leaves the earlier numbers)
    for mode in ("resnet50", "bert", "widedeep", "eager"):
        results[mode] = run_bench({"BENCH_MODE": mode}, f"mode={mode}",
                                  timeout=1500)
        _save(results)

    if not args.quick:
        # flash block sweep: patch via env the kernel reads? The kernel's
        # default block is 512; sweep by running the attention micro-bench
        code = r"""
import time, numpy as np, jax, jax.numpy as jnp
import sys; sys.path.insert(0, {repo!r})
from paddle_tpu.ops.flash_attention import flash_attention_val
b, s, n, d = 8, 1024, 12, 64
rs = np.random.RandomState(0)
q = jnp.asarray(rs.randn(b, s, n, d), jnp.bfloat16)
k = jnp.asarray(rs.randn(b, s, n, d), jnp.bfloat16)
v = jnp.asarray(rs.randn(b, s, n, d), jnp.bfloat16)
for blk in (256, 512, 1024):
    if s % blk: continue
    f = jax.jit(lambda a,bb,c: flash_attention_val(a,bb,c,block_size=blk))
    def g(a,bb,c):
        return jnp.sum(f(a,bb,c))
    gr = jax.jit(jax.grad(g, argnums=(0,1,2)))
    f(q,k,v)[0].block_until_ready(); jax.block_until_ready(gr(q,k,v))
    t0=time.perf_counter()
    for _ in range(20): o=f(q,k,v)
    jax.block_until_ready(o); fwd=(time.perf_counter()-t0)/20*1000
    t0=time.perf_counter()
    for _ in range(10): go=gr(q,k,v)
    jax.block_until_ready(go); bwd=(time.perf_counter()-t0)/10*1000
    print(f"  flash block={{blk}}: fwd {{fwd:.2f}} ms  fwd+bwd {{bwd:.2f}} ms")
""".format(repo=REPO)
        print("flash-attention block sweep (s=1024):")
        subprocess.run([sys.executable, "-c", code], timeout=1200)

    print("\nsummary:")
    base = results.get("baseline")
    for k, v in results.items():
        if v:
            delta = ""
            if (base and base.get("value") and k != "baseline"
                    and v.get("unit") == base.get("unit")):
                delta = f"  ({(v['value']/base['value']-1)*100:+.1f}% vs baseline)"
            print(f"  {k}: {v['value']:.0f} {v.get('unit', '')}{delta}")


if __name__ == "__main__":
    main()

"""Gradient-communication microbenchmark: collectives + bytes per step,
per codec, bucketed vs per-param (ISSUE 1 tooling satellite).

For the test GPT config (gpt-test preset) it counts what one
`DataParallel.apply_collective_grads` actually ISSUES through
`distributed/collective.py` under each grad_comm codec — collectives per
step, wire bytes per step, and host-side encode/scatter time — next to the
un-bucketed per-parameter baseline the seed shipped. Writes
artifacts/grad_comm_bench.json; tests/test_grad_comm.py guards the
collective-count bound in-suite.

Run: python tools/grad_comm_bench.py  (CPU is fine — the accounting is
device-independent; wall times are host-emulation numbers, not ICI.)
"""
from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _build_model():
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.models import GPTForCausalLM, gpt_presets

    cfg = gpt_presets("gpt-test")
    model = GPTForCausalLM(cfg, seed=0)
    # synthesize grads so the sync path runs without a full backward
    from paddle_tpu.framework.tensor import Tensor

    rs = np.random.RandomState(0)
    for p in model.parameters():
        if not p.stop_gradient:
            p.grad = Tensor(rs.standard_normal(p.shape).astype(
                np.dtype(p._value.dtype)) * 1e-2)
    return model


def measure(steps: int = 3) -> dict:
    import paddle_tpu.distributed.collective as coll
    from paddle_tpu.distributed import grad_comm

    model = _build_model()
    params = [p for p in model.parameters() if not p.stop_gradient]

    counted = {"n": 0}
    real_all_reduce = coll.all_reduce

    def counting_all_reduce(t, op=None, group=None, **kw):
        counted["n"] += 1
        return t

    rows = {}
    try:
        coll.all_reduce = counting_all_reduce
        for codec in grad_comm.CODECS:
            cfg = grad_comm.GradCommConfig(codec=codec)
            comm = grad_comm.GradCommunicator(cfg)
            counted["n"] = 0
            t0 = time.perf_counter()
            for _ in range(steps):
                comm.sync(params, world=2)
            dt_ms = (time.perf_counter() - t0) / steps * 1e3
            plan = grad_comm.comm_plan(params, cfg)
            rows[codec] = {
                "collectives_per_step": counted["n"] // steps,
                "comm_bytes_per_step": comm.stats["comm_bytes"],
                "n_buckets": comm.stats["n_buckets"],
                "host_encode_ms": round(dt_ms, 3),
                "planned_collectives": plan["collectives_per_step"],
                "planned_comm_bytes": plan["comm_bytes_per_step"],
                "buckets": comm.describe(),
            }
    finally:
        coll.all_reduce = real_all_reduce

    grad_bytes = sum(
        p.size * 4 for p in params)  # fp32 grads
    return {
        "model": "gpt-test",
        "n_params": len(params),
        "grad_bytes": grad_bytes,
        "per_param_collectives": len(params),
        "codecs": rows,
        "note": ("collectives_per_step counts what apply_collective_grads "
                 "issues; the seed's per-param path issued one per "
                 "parameter. int8 rows include the per-bucket scalar scale "
                 "exchange. host_encode_ms is CPU emulation overhead, not "
                 "ICI time."),
    }


def main():
    rec = measure()
    path = os.path.join(REPO, "artifacts", "grad_comm_bench.json")
    with open(path, "w") as f:
        json.dump(rec, f, indent=1)
    print(json.dumps(rec))


if __name__ == "__main__":
    main()

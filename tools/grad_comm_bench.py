"""Gradient-communication microbenchmark: collectives + bytes per step,
per codec, bucketed vs per-param, eager vs TRACED (ISSUE 1 tooling
satellite; ISSUE 8 adds the in-trace columns).

For the test GPT config (gpt-test preset) it counts what one
`DataParallel.apply_collective_grads` actually ISSUES through
`distributed/collective.py` under each grad_comm codec — collectives per
step, wire bytes per step, and host-side encode/scatter time — next to the
un-bucketed per-parameter baseline the seed shipped. The traced columns run
the same bucket sync INSIDE a compiled shard_map program (`sync_async`,
the jit.TrainStep wire path) and record the wire bytes the compiled step
actually moves per codec plus the compiled step time — before ISSUE 8 the
compiled path shipped raw fp32 regardless of codec. Writes
artifacts/grad_comm_bench.json; tests/test_grad_comm.py guards the
collective-count bound in-suite.

Run: python tools/grad_comm_bench.py  (CPU is fine — the accounting is
device-independent; wall times are host-emulation numbers, not ICI.)
"""
from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
# give the traced columns a 2-way data mesh even on a 1-CPU host (no-op if
# jax is already imported, e.g. under the test suite's 8-device conftest)
if "jax" not in sys.modules and "host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=2")


def _build_model():
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.models import GPTForCausalLM, gpt_presets

    cfg = gpt_presets("gpt-test")
    model = GPTForCausalLM(cfg, seed=0)
    # synthesize grads so the sync path runs without a full backward
    from paddle_tpu.framework.tensor import Tensor

    rs = np.random.RandomState(0)
    for p in model.parameters():
        if not p.stop_gradient:
            p.grad = Tensor(rs.standard_normal(p.shape).astype(
                np.dtype(p._value.dtype)) * 1e-2)
    return model


def measure_traced(params, steps: int = 3) -> dict:
    """Per-codec wire accounting + step time of the bucket sync INSIDE a
    compiled shard_map program (the sync_async / jit.TrainStep path).
    Error-feedback residuals are threaded as carried state (zeros in, the
    futures' residuals out) exactly as TrainStep does."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    import paddle_tpu.distributed.mesh as mesh_mod
    from paddle_tpu.distributed import grad_comm
    from paddle_tpu.distributed.overlap import OverlappedGradCommunicator
    from paddle_tpu.framework.tensor import Tensor

    ndev = min(2, len(jax.devices()))
    saved_mesh = mesh_mod.get_mesh()
    mesh = mesh_mod.set_mesh(mesh_mod.build_mesh(
        {"data": ndev}, devices=jax.devices()[:ndev]))
    shapes = [(tuple(p._value.shape), np.dtype(p._value.dtype))
              for p in params]
    rs = np.random.RandomState(0)
    stacked = [rs.standard_normal((ndev,) + s).astype(dt) * 1e-2
               for s, dt in shapes]

    def fakes(vals):
        ps = []
        for v, (s, dt) in zip(vals, shapes):
            p = Tensor(jnp.zeros(s, dt), _internal=True)
            p.stop_gradient = False
            p.grad = Tensor(v.reshape(s), _internal=True)
            ps.append(p)
        return ps

    rows = {}
    try:
        for codec in grad_comm.CODECS:
            comm = OverlappedGradCommunicator(
                grad_comm.GradCommConfig(codec=codec))
            # bucket plan on host fakes (out_specs need the bucket count)
            plan_buckets = comm.buckets_for(fakes(
                [np.asarray(v[0]) for v in stacked]))
            ef = (comm.config.error_feedback
                  and codec in grad_comm.EF_CODECS)
            stats = {}

            def body(*rank_grads):
                ps = fakes(rank_grads)
                res = ({b.index: jnp.zeros((b.size,), jnp.float32)
                        for b in plan_buckets} if ef else None)
                futs = comm.sync_async(ps, world=ndev, residuals=res)
                stats.update(comm.stats)
                return tuple(f.wait() for f in futs)

            f = jax.jit(mesh_mod.compat_shard_map(
                body, mesh, P("data"),
                tuple([P()] * len(plan_buckets))))
            outs = f(*stacked)           # compile + trace-time accounting
            jax.block_until_ready(outs)
            t0 = time.perf_counter()
            for _ in range(steps):
                outs = f(*stacked)
            jax.block_until_ready(outs)
            dt_ms = (time.perf_counter() - t0) / steps * 1e3
            rows[codec] = {
                "traced_comm_bytes_per_step": stats["comm_bytes"],
                "traced_collectives_per_step": stats["collectives"],
                "traced_path": stats["path"],
                "traced_step_ms": round(dt_ms, 3),
            }
    finally:
        mesh_mod.set_mesh(saved_mesh)
    return rows


def measure(steps: int = 3) -> dict:
    import paddle_tpu.distributed.collective as coll
    from paddle_tpu.distributed import grad_comm

    model = _build_model()
    params = [p for p in model.parameters() if not p.stop_gradient]

    counted = {"n": 0}
    real_all_reduce = coll.all_reduce

    def counting_all_reduce(t, op=None, group=None, **kw):
        counted["n"] += 1
        return t

    rows = {}
    try:
        coll.all_reduce = counting_all_reduce
        for codec in grad_comm.CODECS:
            cfg = grad_comm.GradCommConfig(codec=codec)
            comm = grad_comm.GradCommunicator(cfg)
            counted["n"] = 0
            t0 = time.perf_counter()
            for _ in range(steps):
                comm.sync(params, world=2)
            dt_ms = (time.perf_counter() - t0) / steps * 1e3
            plan = grad_comm.comm_plan(params, cfg)
            rows[codec] = {
                "collectives_per_step": counted["n"] // steps,
                "comm_bytes_per_step": comm.stats["comm_bytes"],
                "n_buckets": comm.stats["n_buckets"],
                "host_encode_ms": round(dt_ms, 3),
                "planned_collectives": plan["collectives_per_step"],
                "planned_comm_bytes": plan["comm_bytes_per_step"],
                "buckets": comm.describe(),
            }
    finally:
        coll.all_reduce = real_all_reduce

    for codec, traced in measure_traced(params, steps=steps).items():
        rows[codec].update(traced)

    grad_bytes = sum(
        p.size * 4 for p in params)  # fp32 grads
    return {
        "model": "gpt-test",
        "n_params": len(params),
        "grad_bytes": grad_bytes,
        "per_param_collectives": len(params),
        "codecs": rows,
        "note": ("collectives_per_step counts what apply_collective_grads "
                 "issues; the seed's per-param path issued one per "
                 "parameter. int8 rows include the per-bucket scalar scale "
                 "exchange; the *_block rows one fp32 scale per 1024 "
                 "elements riding the payload. traced_* columns are the "
                 "same sync compiled via shard_map (the sync_async / "
                 "TrainStep path) — before ISSUE 8 the compiled wire was "
                 "raw fp32 for every codec. host_encode_ms / "
                 "traced_step_ms are CPU emulation overhead, not ICI "
                 "time."),
    }


def main():
    rec = measure()
    path = os.path.join(REPO, "artifacts", "grad_comm_bench.json")
    with open(path, "w") as f:
        json.dump(rec, f, indent=1)
    print(json.dumps(rec))


if __name__ == "__main__":
    main()

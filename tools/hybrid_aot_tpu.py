"""AOT-compile the FULL hybrid-parallel train step with the real TPU compiler.

Complements tools/gpt13b_aot_tpu.py (which covers the BASELINE config-4
GSPMD estimate): this validates that the framework's actual TrainStep —
the same object users drive, including ZeRO-2 slot sharding, Megatron TP,
the 1F1B pipeline schedule and ring-attention sequence parallelism — lowers
and compiles for REAL v5e topologies through jax.experimental.topologies,
with no TPU execution required. The CPU virtual-mesh dryrun proves the
sharded program is correct; this proves the TPU compiler accepts it and
reports its per-device memory.

Configs (mirroring __graft_entry__.dryrun_multichip):
  A  v5e:2x4  (8)  data2 x sharding2 x model2, GSPMD + ZeRO-2
  C  v5e:4x8  (32) data2 x sharding2 x pipe2 x model2 x sep2, ZeRO-2 +
                   1F1B + TP + ring-attention SP jointly

Writes artifacts/hybrid_aot_tpu.json. Runs with JAX_PLATFORMS=cpu — model
init arrays live on CPU; compilation targets the described TPU topology.
"""
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


from paddle_tpu.jit.aot import aot_compile_step, topology_mesh as topo_mesh


def build_config_a():
    """GSPMD ZeRO-2 + TP TrainStep on a v5e:2x4 topology mesh — shared by
    main() and tests/test_tpu_aot.py so the two can't drift.

    Model/optimizer/inputs are built with NO mesh (arrays on CPU): topology
    devices are non-addressable, so only the abstract lowering may see the
    mesh — device_put onto a described topology is impossible.
    """
    import numpy as np
    from jax.sharding import PartitionSpec as P

    import paddle_tpu as paddle
    import paddle_tpu.optimizer as opt
    from paddle_tpu.distributed import mesh as mesh_mod
    from paddle_tpu.distributed.sharding import group_sharded_parallel
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models import (
        GPTForCausalLM, GPTPretrainingCriterion, gpt_presets,
    )

    rs = np.random.RandomState(0)
    crit = GPTPretrainingCriterion()
    mesh_mod.set_mesh(None)
    cfg = gpt_presets("gpt-test", mode="scan", use_flash_attention=False)
    model = GPTForCausalLM(cfg, seed=0)
    optim = opt.AdamW(learning_rate=1e-4, parameters=model.parameters())
    model, optim, _ = group_sharded_parallel(model, optim, "os_g")
    step = TrainStep(model, lambda lg, lb: crit(lg, lb), optim,
                     batch_spec=P(("data", "sharding")))
    batch = 16
    ids = paddle.to_tensor(rs.randint(0, cfg.vocab_size, (batch, 16)),
                           dtype="int64")
    lbl = paddle.to_tensor(rs.randint(0, cfg.vocab_size, (batch, 16)),
                           dtype="int64")
    mesh_mod.set_mesh(
        topo_mesh("v5e:2x4", {"data": 2, "sharding": 2, "model": 2}))
    return step, (ids,), (lbl,)


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")

    import numpy as np
    from jax.sharding import PartitionSpec as P

    import paddle_tpu as paddle
    import paddle_tpu.optimizer as opt
    from paddle_tpu.distributed import mesh as mesh_mod
    from paddle_tpu.distributed.sharding import group_sharded_parallel
    from paddle_tpu.models import (
        GPTForCausalLM, GPTPretrainingCriterion, gpt_presets,
        gpt_1f1b_train_step,
    )

    results = {}
    rs = np.random.RandomState(0)
    crit = GPTPretrainingCriterion()

    # ---- config A: GSPMD ZeRO-2 + TP on v5e:2x4 ----
    step, inputs, labels = build_config_a()
    r = aot_compile_step(step, inputs, labels)
    r["topology"], r["mesh"] = "v5e:2x4", {"data": 2, "sharding": 2,
                                           "model": 2}
    print("A (GSPMD ZeRO-2 + TP, v5e:2x4):", r)
    results["A_gspmd_zero2_tp"] = r

    # ---- config C: all five axes jointly on v5e:4x8 (1F1B + ring SP) ----
    mesh_mod.set_mesh(None)
    cfg_c = gpt_presets("gpt-test", mode="scan", use_flash_attention=False,
                        num_layers=4, pp_microbatches=4,
                        use_ring_attention=True)
    model = GPTForCausalLM(cfg_c, seed=0)
    optim = opt.AdamW(learning_rate=1e-4, parameters=model.parameters())
    model, optim, _ = group_sharded_parallel(model, optim, "os_g")
    batch = 32
    ids = paddle.to_tensor(rs.randint(0, cfg_c.vocab_size, (batch, 16)),
                           dtype="int64")
    lbl = paddle.to_tensor(rs.randint(0, cfg_c.vocab_size, (batch, 16)),
                           dtype="int64")
    # the 1F1B schedule reads the pipe degree at construction time, so the
    # step (unlike model/optim/inputs) is built under the topology mesh
    mesh_mod.set_mesh(topo_mesh("v5e:4x8", {"data": 2, "sharding": 2,
                                            "pipe": 2, "model": 2,
                                            "sep": 2}))
    step = gpt_1f1b_train_step(model, optim,
                               batch_spec=P(("data", "sharding")))
    r = aot_compile_step(step, (ids,), (lbl,))
    r["topology"] = "v5e:4x8"
    r["mesh"] = {"data": 2, "sharding": 2, "pipe": 2, "model": 2, "sep": 2}
    print("C (ZeRO-2 + 1F1B + TP + ring-SP, v5e:4x8):", r)
    results["C_joint_5axis_1f1b"] = r

    # ---- pallas kernels: first TPU-backend validation (tests run them in
    # CPU interpret mode; this proves the Mosaic lowering itself) ----
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding
    import numpy as np

    from paddle_tpu.framework.target import force_target
    from paddle_tpu.jit.aot import compile_pallas_flash_for_tpu
    from paddle_tpu.ops.quant_matmul import quant_matmul
    from jax.experimental import topologies

    b, s, n, d = 8, 1024, 12, 64
    results["pallas_flash_fwd_bwd"] = {
        "compile_seconds": compile_pallas_flash_for_tpu(
            (b, s, n, d), block_size=512, grad=True),
        "shape": [b, s, n, d], "topology": "v5e (single chip)",
        "mosaic": True}
    print("pallas flash fwd+bwd TPU compile:",
          results["pallas_flash_fwd_bwd"])

    topo = topologies.get_topology_desc(platform="tpu",
                                        topology_name="v5e:2x4")
    mesh1 = Mesh(np.asarray(topo.devices[:1]).reshape(1), ("x",))
    sh = NamedSharding(mesh1, P())
    SDS = jax.ShapeDtypeStruct
    # force_target: mesh1 is a raw jax mesh, not the framework's ambient
    # mesh, so the pallas interpret gate needs the explicit pin
    with force_target("tpu"):
        t0 = time.time()
        x_s = SDS((512, 1024), jnp.bfloat16, sharding=sh)
        w_s = SDS((1024, 1024), jnp.int8, sharding=sh)
        sc_s = SDS((1, 1024), jnp.float32, sharding=sh)
        jax.jit(quant_matmul, in_shardings=(sh, sh, sh)).lower(
            x_s, w_s, sc_s).compile()
        results["pallas_int8_matmul"] = {
            "compile_seconds": round(time.time() - t0, 1),
            "shape": [512, 1024, 1024], "topology": "v5e (single chip)",
            "mosaic": True}
        print("pallas int8 matmul TPU compile:",
              results["pallas_int8_matmul"])

    path = os.path.join(REPO, "artifacts", "hybrid_aot_tpu.json")
    with open(path, "w") as f:
        json.dump(results, f, indent=1)
    print(f"wrote {path}")


if __name__ == "__main__":
    main()

"""PS hot-path bench: compiled+pipelined Wide&Deep vs the eager per-step
lookup baseline, under Zipfian key traffic (ISSUE 20 deliverable).

Phases (CPU-safe; one WideDeep config throughout):

  eager     the pre-ISSUE-20 world: distributed_lookup_table per step
            (host pull + Tensor-autograd dense step + host push, dozens
            of eager dispatches per batch) over a LocalPs. Its
            examples/s is the denominator of the >=10x claim.
  pipeline  PsTrainStep (ONE jitted step, rows in / row-grads out) under
            PsPipeline double buffering over a bus-sharded PS
            (FLAGS_ps_shards services on one MessageBus). Reports
            sustained examples/s (compile excluded by a warmup run),
            exposed pull/push ms, step ms.
  depth     depth 1 (serial) vs depth 2 (double-buffered) exposed pull —
            the acceptance claim: at depth 2 exposed pull < step time.
  codec     fp32 vs int8_block vs fp8_block push/pull wire: bytes per
            step per codec (int8 must be <= ~0.3x of fp32) and final
            training loss within a parity band of the fp32 wire (the
            EF residuals doing their job).
  cache     HeterCache (capacity-bounded, LRU) between the pipeline and
            the sharded client: hit rate vs Zipf skew alpha — hot keys
            stay device-resident, the wire only sees misses+evictions.

Writes artifacts/ps_bench.json; ``ps_examples_per_s`` and
``ps_exposed_pull_ms`` feed the bench.py gpt record and are gated by
tools/bench_gate.py.

  python tools/ps_bench.py [--quick] [--out artifacts/ps_bench.json]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _build(slots, dim, lr=1e-3, seed=0):
    import paddle_tpu as paddle
    from paddle_tpu.models import WideDeep, wide_deep_loss

    paddle.seed(seed)
    model = WideDeep(slots, dim)
    opt = paddle.optimizer.Adam(learning_rate=lr,
                                parameters=model.parameters())
    return model, opt, wide_deep_loss


def bench_eager(cfg, batches):
    """Per-step host lookup + eager dense autograd over a LocalPs."""
    import paddle_tpu as paddle
    from paddle_tpu.distributed.ps import LocalPs, distributed_lookup_table

    ps = LocalPs()
    ps.create_table(0, dim=cfg["dim"], init_range=0.01, lr=cfg["lr_sparse"],
                    optimizer="sgd")
    model, opt, loss_fn = _build(cfg["slots"], cfg["dim"])
    losses = []
    t0 = time.perf_counter()
    for ids, labels in batches:
        rows = distributed_lookup_table(
            paddle.to_tensor(ids.astype(np.int64)), table_id=0, client=ps,
            lr=cfg["lr_sparse"])
        logits = model(rows.reshape([ids.shape[0], -1]))
        loss = loss_fn(logits, paddle.to_tensor(labels))
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    wall = time.perf_counter() - t0
    return {"steps": len(batches), "wall_s": round(wall, 4),
            "examples_per_s": round(len(batches) * cfg["batch"] / wall, 1),
            "final_loss": losses[-1]}


def run_pipeline(cfg, batches, codec="fp32", depth=2, cache_capacity=None,
                 warmup=2, shards=None):
    """One measured pipeline run; returns (stats, client wire counters)."""
    from paddle_tpu.distributed.ps.heter_cache import HeterCache
    from paddle_tpu.distributed.ps.pipeline import (
        PsPipeline, PsTrainStep, make_sharded_ps)

    client, services, bus = make_sharded_ps(
        shards if shards is not None else cfg["shards"], codec=codec)
    client.create_table(0, cfg["dim"])
    cache = None
    if cache_capacity:
        cache = HeterCache(client, 0, cfg["dim"], int(cache_capacity),
                           lr=cfg["lr_sparse"])
    model, opt, loss_fn = _build(cfg["slots"], cfg["dim"])
    step = PsTrainStep(model, opt, loss_fn, dim=cfg["dim"],
                       pad_rows=cfg["pad_rows"])
    pipe = PsPipeline(client, 0, step, depth=depth,
                      lr_sparse=cfg["lr_sparse"], cache=cache)
    try:
        if warmup:
            pipe.run(batches[:warmup])   # compile + jit warm outside timing
        b0 = (client.pull_bytes, client.push_bytes)
        stats = pipe.run(batches[warmup:])
        stats["pull_bytes_per_step"] = (
            (client.pull_bytes - b0[0]) // max(1, stats["steps"]))
        stats["push_bytes_per_step"] = (
            (client.push_bytes - b0[1]) // max(1, stats["steps"]))
        stats["codec"] = codec
        stats["depth"] = depth
        if cache is not None:
            stats["cache_hit_rate"] = round(cache.hit_rate(), 4)
            stats["cache_evictions"] = cache.evictions
            stats["cache_fault_pulls"] = cache.fault_pulls
        return stats
    finally:
        pipe.close()
        client.close()
        for s in services:
            s.stop()
        bus.close()


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--quick", action="store_true",
                    help="tier-1 smoke: tiny config, <10s")
    ap.add_argument("--out", default=os.path.join(REPO, "artifacts",
                                                  "ps_bench.json"))
    args = ap.parse_args(argv)

    from paddle_tpu.models import ctr_batches

    if args.quick:
        cfg = dict(batch=64, slots=4, dim=8, vocab=2000, steps=8,
                   eager_steps=2, lr_sparse=0.1, shards=2, pad_rows=256,
                   alphas=(1.1,), cache_capacity=192)
    else:
        cfg = dict(batch=256, slots=8, dim=32, vocab=20000, steps=40,
                   eager_steps=12, lr_sparse=0.1, shards=2, pad_rows=2048,
                   alphas=(0.6, 1.1, 1.6), cache_capacity=4096)

    batches = ctr_batches(cfg["steps"], cfg["batch"], cfg["slots"],
                          cfg["vocab"], alpha=1.1, seed=0)
    out = {"config": {k: v for k, v in cfg.items() if k != "alphas"},
           "quick": bool(args.quick)}

    print("== eager baseline ==", flush=True)
    out["eager"] = bench_eager(cfg, batches[:cfg["eager_steps"]])
    print(f"  {out['eager']['examples_per_s']} ex/s", flush=True)

    print("== compiled + pipelined (fp32 wire) ==", flush=True)
    out["pipeline"] = run_pipeline(cfg, batches, codec="fp32", depth=2)
    out["speedup_vs_eager"] = round(
        out["pipeline"]["examples_per_s"]
        / max(out["eager"]["examples_per_s"], 1e-9), 2)
    print(f"  {out['pipeline']['examples_per_s']} ex/s "
          f"({out['speedup_vs_eager']}x eager), exposed pull "
          f"{out['pipeline']['exposed_pull_ms']} ms / step "
          f"{out['pipeline']['step_ms']} ms", flush=True)

    print("== depth sweep ==", flush=True)
    out["depth"] = {}
    for d in (1, 2):
        r = run_pipeline(cfg, batches, codec="fp32", depth=d)
        out["depth"][str(d)] = {k: r[k] for k in (
            "examples_per_s", "exposed_pull_ms", "exposed_push_ms",
            "step_ms")}
        print(f"  depth {d}: {r['examples_per_s']} ex/s, exposed pull "
              f"{r['exposed_pull_ms']} ms", flush=True)

    print("== codec sweep ==", flush=True)
    out["codec"] = {}
    fp32_loss = None
    for codec in ("fp32", "int8_block", "fp8_block"):
        try:
            r = run_pipeline(cfg, batches, codec=codec, depth=2)
        except RuntimeError as e:   # fp8 dtype missing in this jax
            out["codec"][codec] = {"skipped": str(e)}
            continue
        rec = {"examples_per_s": r["examples_per_s"],
               "pull_bytes_per_step": r["pull_bytes_per_step"],
               "push_bytes_per_step": r["push_bytes_per_step"],
               "final_loss": r["losses"][-1]}
        if codec == "fp32":
            fp32_loss = rec["final_loss"]
            rec["wire_ratio_vs_fp32"] = 1.0
        else:
            fp32_rec = out["codec"]["fp32"]
            rec["wire_ratio_vs_fp32"] = round(
                (rec["pull_bytes_per_step"] + rec["push_bytes_per_step"])
                / max(1, fp32_rec["pull_bytes_per_step"]
                      + fp32_rec["push_bytes_per_step"]), 4)
            rec["loss_gap_vs_fp32"] = round(
                abs(rec["final_loss"] - fp32_loss), 4)
        out["codec"][codec] = rec
        print(f"  {codec}: wire {rec.get('wire_ratio_vs_fp32')}x fp32, "
              f"final loss {rec['final_loss']:.4f}", flush=True)

    print("== cache vs skew ==", flush=True)
    out["cache"] = {}
    for alpha in cfg["alphas"]:
        ab = ctr_batches(cfg["steps"], cfg["batch"], cfg["slots"],
                         cfg["vocab"], alpha=alpha, seed=1)
        r = run_pipeline(cfg, ab, codec="fp32", depth=2,
                         cache_capacity=cfg["cache_capacity"])
        out["cache"][str(alpha)] = {
            "hit_rate": r["cache_hit_rate"],
            "evictions": r["cache_evictions"],
            "fault_pulls": r["cache_fault_pulls"],
            "examples_per_s": r["examples_per_s"]}
        print(f"  alpha={alpha}: hit rate {r['cache_hit_rate']}, "
              f"{r['cache_evictions']} evictions", flush=True)

    # headline fields for bench.py / bench_gate.py
    out["ps_examples_per_s"] = out["pipeline"]["examples_per_s"]
    out["ps_exposed_pull_ms"] = out["pipeline"]["exposed_pull_ms"]
    out["pipeline"].pop("losses", None)
    for rec in out["codec"].values():
        rec.pop("losses", None)

    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(out, f, indent=2, default=float)
    print(f"wrote {args.out}")
    return out


if __name__ == "__main__":
    main()

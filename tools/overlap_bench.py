"""Overlapped gradient-sync microbenchmark: exposed comm ms/step and
overlap efficiency, serial vs bucket-ready overlapped, per codec (ISSUE 5
tooling satellite). The `zero3` section (ISSUE 9) measures the PARAMETER
direction: per-bucket all_gather exposure of the stage-3 at-rest store,
prefetched (layer-ahead on the CollectiveLane) vs synchronous, plus the
per-rank resident parameter bytes the sharding buys.

For the test GPT config (gpt-test preset) this measures one
`GradCommunicator.sync` (serial — everything exposed) against one
`OverlappedGradCommunicator` prepare → emulated-backward → flush cycle
(buckets launch on the background lane as their grads land; only the flush
wait is exposed), per grad_comm codec. The overlapped run drives the REAL
hook/lane/collective machinery; what is emulated is only the backward
compute window the launches get to hide under (`--compute-ms`, spread
across the per-param grad-ready events).

Caveat (same as tools/grad_comm_bench.py): on CPU the wall times are host
encode/concat emulation, not ICI transfer — the artifact records the
overlap STRUCTURE (exposed drops, efficiency > 0), not TPU absolute times.

Writes artifacts/overlap_bench.json; tests/test_overlap.py guards the
"overlapped exposed < serial exposed" invariant in-suite.

Run: python tools/overlap_bench.py
"""
from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def measure(compute_ms: float = 40.0, repeats: int = 3,
            comm_buffer_size: float = 0.05) -> dict:
    """Best-of-`repeats` serial vs overlapped exposure per codec. The small
    `comm_buffer_size` (MB) splits gpt-test's grads into several buckets so
    the bucket-ready pipeline actually has stages to overlap."""
    from paddle_tpu.distributed import grad_comm
    from paddle_tpu.distributed.overlap import overlap_report
    from paddle_tpu.models import GPTForCausalLM, gpt_presets

    model = GPTForCausalLM(gpt_presets("gpt-test"), seed=0)
    params = [p for p in model.parameters() if not p.stop_gradient]

    rows = {}
    for codec in grad_comm.CODECS:
        cfg = grad_comm.GradCommConfig(codec=codec,
                                       comm_buffer_size=comm_buffer_size,
                                       last_comm_buffer_size=0.01)
        best = None
        for _ in range(repeats):
            rep = overlap_report(params, cfg, world=2,
                                 compute_s=compute_ms / 1e3)
            if best is None or (rep["overlapped_exposed_comm_ms"]
                                < best["overlapped_exposed_comm_ms"]):
                best = rep
        rows[codec] = best

    # ---- ZeRO-3 section (ISSUE 9): parameter-gather exposure of the
    # stage-3 at-rest store, prefetched vs synchronous, per bucket
    from paddle_tpu.distributed.sharding.stage3 import zero3_gather_report

    z3 = None
    for _ in range(repeats):
        rep = zero3_gather_report(
            params, grad_comm.GradCommConfig(
                comm_buffer_size=comm_buffer_size,
                last_comm_buffer_size=0.01),
            world=2, compute_s=compute_ms / 1e3)
        if z3 is None or (rep["prefetch_exposed_gather_ms"]
                          < z3["prefetch_exposed_gather_ms"]):
            z3 = rep

    return {
        "model": "gpt-test",
        "n_params": len(params),
        "emulated_backward_ms": compute_ms,
        "comm_buffer_size_MB": comm_buffer_size,
        "codecs": rows,
        "zero3": z3,
        "note": ("overlapped exposed time = flush-barrier wait after an "
                 "emulated backward window; serial exposed = the whole "
                 "sync. Host-emulation wall times (CPU), structure not "
                 "ICI absolutes; the overlapped launches run the real "
                 "hook/lane/execute_collective machinery."),
    }


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--compute-ms", type=float, default=40.0,
                    help="emulated backward window the launches hide under")
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--out", default=os.path.join(REPO, "artifacts",
                                                  "overlap_bench.json"))
    args = ap.parse_args(argv)
    rec = measure(compute_ms=args.compute_ms, repeats=args.repeats)
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(rec, f, indent=1)
    for codec, row in rec["codecs"].items():
        print(f"{codec:>5}: serial exposed {row['serial_exposed_comm_ms']:8.3f} ms"
              f" | overlapped exposed {row['overlapped_exposed_comm_ms']:8.3f} ms"
              f" | efficiency {row['overlap_efficiency']:.3f}"
              f" ({row['buckets_launched_early']}/{row['n_buckets']}"
              f" buckets early)")
    z3 = rec["zero3"]
    print(f"zero3: sync exposed gather {z3['sync_exposed_gather_ms']:8.3f} ms"
          f" | prefetched {z3['prefetch_exposed_gather_ms']:8.3f} ms"
          f" | param bytes/rank {z3['zero3_param_bytes_per_rank']:,}"
          f" (full {z3['param_bytes_full']:,}, world {z3['world']})")
    print(f"summary -> {args.out}")
    ok = all(row["overlapped_exposed_comm_ms"]
             < row["serial_exposed_comm_ms"]
             for row in rec["codecs"].values())
    ok = ok and (z3["prefetch_exposed_gather_ms"]
                 < z3["sync_exposed_gather_ms"])
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())

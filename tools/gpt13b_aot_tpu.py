"""AOT-compile the GPT-1.3B train step with the REAL TPU compiler.

BASELINE config-4 / VERDICT r3 item 7: the 5.9 GiB/device HBM estimate for
GPT-1.3B (ZeRO stage-2 sharding32 x mp2, b=64 s=2048, bf16 + remat) was
produced by the CPU backend's memory_analysis, which ignores TPU layout
padding and XLA-TPU's fusion/remat choices. This tool compiles the SAME
step via jax.experimental.topologies against a described v5e-64 topology —
no TPU hardware needed, the TPU compiler runs ahead-of-time — and records
the TPU-backend numbers next to the CPU estimate.

Usage: python tools/gpt13b_aot_tpu.py [--topology v5e:8x8]
Writes artifacts/gpt13b_aot_tpu.json.
"""
import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


# BASELINE config 4, in ONE place — main() defaults and
# tests/test_tpu_aot.py's 1.3B pin both read it, so the test always
# compiles the configuration the artifact records
CONFIG4 = {
    "topology": "v5e:8x8", "sharding": 32, "model": 2,
    "batch": 64, "seq": 2048,
    "preset_kwargs": dict(mode="scan", dtype="bfloat16", recompute=True,
                          use_flash_attention=True),
}


class NoMemoryAnalysis(RuntimeError):
    """The backend compiled but exposed no memory analysis (exit 2)."""


def compile_config4(topology=None, sharding=None, model=None, batch=None,
                    seq=None):
    """gpt_hbm_estimate for (a variant of) BASELINE config 4 against the
    described topology; returns the estimate dict with compile_seconds
    (the gpt_hbm_estimate call only — imports/topology excluded, so
    entries stay comparable). Raises NoMemoryAnalysis when the backend
    compiles but reports no memory accounting."""
    from paddle_tpu.jit.aot import topology_mesh
    from paddle_tpu.models import gpt_presets
    from paddle_tpu.models.gpt import gpt_hbm_estimate

    c = CONFIG4

    def pick(v, key):
        return v if v is not None else c[key]

    mesh = topology_mesh(pick(topology, "topology"),
                         {"sharding": pick(sharding, "sharding"),
                          "model": pick(model, "model")})
    cfg = gpt_presets("gpt-1.3b", **c["preset_kwargs"])
    t0 = time.time()
    est = gpt_hbm_estimate(cfg, mesh, global_batch=pick(batch, "batch"),
                           seq=pick(seq, "seq"))
    if est is None:
        raise NoMemoryAnalysis("TPU backend exposed no memory analysis")
    est["compile_seconds"] = round(time.time() - t0, 1)
    return est


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--topology", default=CONFIG4["topology"],
                    help="libtpu topology name (64 chips for config 4)")
    ap.add_argument("--sharding", type=int, default=CONFIG4["sharding"])
    ap.add_argument("--model", type=int, default=CONFIG4["model"])
    ap.add_argument("--batch", type=int, default=CONFIG4["batch"])
    ap.add_argument("--seq", type=int, default=CONFIG4["seq"])
    args = ap.parse_args()

    import jax

    # compile-only: keep every real array on CPU so a wedged TPU runtime
    # can't hang the tool (the TPU compiler is reached via the topology)
    jax.config.update("jax_platforms", "cpu")

    try:
        est = compile_config4(topology=args.topology,
                              sharding=args.sharding, model=args.model,
                              batch=args.batch, seq=args.seq)
    except NoMemoryAnalysis as e:
        print(e)
        sys.exit(2)
    compile_s = est["compile_seconds"]
    est["backend"] = "tpu-aot"
    est["topology"] = args.topology
    est["mesh"] = {"sharding": args.sharding, "model": args.model}
    pk = CONFIG4["preset_kwargs"]
    flash = pk["use_flash_attention"]
    est["config"] = {"batch": args.batch, "seq": args.seq,
                     "preset": "gpt-1.3b", "dtype": pk["dtype"],
                     "recompute": pk["recompute"],
                     "use_flash_attention": flash}
    peak_gib = est["peak_hbm_bytes"] / 2**30
    est["fits_v5e_16gb"] = peak_gib <= 16.0
    print(f"TPU-AOT peak HBM/device: {peak_gib:.2f} GiB  "
          f"(args {est['argument_bytes']/2**30:.2f} + temps "
          f"{est['temp_bytes']/2**30:.2f} + out {est['output_bytes']/2**30:.2f} "
          f"- aliased {est['alias_bytes']/2**30:.2f})  "
          f"compile {compile_s:.0f}s")
    path = os.path.join(REPO, "artifacts", "gpt13b_aot_tpu.json")
    try:
        results = json.load(open(path))
        if "peak_hbm_bytes" in results:  # pre-accumulation single-entry file
            results = {}
    except (FileNotFoundError, json.JSONDecodeError):
        results = {}
    key = (f"{args.topology}_sharding{args.sharding}xmodel{args.model}"
           f"_b{args.batch}_s{args.seq}" + ("_flash" if flash else ""))
    results[key] = est
    with open(path, "w") as f:
        json.dump(results, f, indent=1)
    print(f"wrote {path}")
    if not est["fits_v5e_16gb"]:
        print("does not fit v5e HBM!")
        sys.exit(1)


if __name__ == "__main__":
    main()

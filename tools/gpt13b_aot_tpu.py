"""AOT-compile the GPT-1.3B train step with the REAL TPU compiler.

BASELINE config-4 / VERDICT r3 item 7: the 5.9 GiB/device HBM estimate for
GPT-1.3B (ZeRO stage-2 sharding32 x mp2, b=64 s=2048, bf16 + remat) was
produced by the CPU backend's memory_analysis, which ignores TPU layout
padding and XLA-TPU's fusion/remat choices. This tool compiles the SAME
step via jax.experimental.topologies against a described v5e-64 topology —
no TPU hardware needed, the TPU compiler runs ahead-of-time — and records
the TPU-backend numbers next to the CPU estimate.

Usage: python tools/gpt13b_aot_tpu.py [--topology v5e:8x8]
Writes artifacts/gpt13b_aot_tpu.json.
"""
import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--topology", default="v5e:8x8",
                    help="libtpu topology name (64 chips for config 4)")
    ap.add_argument("--sharding", type=int, default=32)
    ap.add_argument("--model", type=int, default=2)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--seq", type=int, default=2048)
    args = ap.parse_args()

    import jax

    # compile-only: keep every real array on CPU so a wedged TPU runtime
    # can't hang the tool (the TPU compiler is reached via the topology)
    jax.config.update("jax_platforms", "cpu")

    from jax.experimental import topologies

    t0 = time.time()
    topo = topologies.get_topology_desc(platform="tpu",
                                        topology_name=args.topology)
    try:
        mesh = topologies.make_mesh(topo, (args.sharding, args.model),
                                    ("sharding", "model"))
    except NotImplementedError:
        # the ICI-aware layout refuses shapes that need a physical axis
        # split (e.g. 32x2 on an 8x8 torus); device order doesn't change
        # the per-device memory estimate, so fall back to raw order
        import numpy as np
        from jax.sharding import Mesh
        devs = np.asarray(topo.devices).reshape(args.sharding, args.model)
        mesh = Mesh(devs, ("sharding", "model"))
    print(f"topology {args.topology}: mesh {dict(zip(mesh.axis_names, mesh.devices.shape))} "
          f"[{time.time()-t0:.1f}s]")

    from paddle_tpu.distributed import mesh as mesh_mod
    from paddle_tpu.models import gpt_presets
    from paddle_tpu.models.gpt import gpt_hbm_estimate

    mesh_mod.set_mesh(mesh)
    cfg = gpt_presets("gpt-1.3b", mode="scan", dtype="bfloat16",
                      recompute=True, use_flash_attention=True)
    t0 = time.time()
    est = gpt_hbm_estimate(cfg, mesh, global_batch=args.batch, seq=args.seq)
    compile_s = time.time() - t0
    if est is None:
        print("TPU backend exposed no memory analysis")
        sys.exit(2)
    est["compile_seconds"] = round(compile_s, 1)
    est["backend"] = "tpu-aot"
    est["topology"] = args.topology
    est["mesh"] = {"sharding": args.sharding, "model": args.model}
    est["config"] = {"batch": args.batch, "seq": args.seq,
                     "preset": "gpt-1.3b", "dtype": "bfloat16",
                     "recompute": True,
                     "use_flash_attention": cfg.use_flash_attention}
    peak_gib = est["peak_hbm_bytes"] / 2**30
    est["fits_v5e_16gb"] = peak_gib <= 16.0
    print(f"TPU-AOT peak HBM/device: {peak_gib:.2f} GiB  "
          f"(args {est['argument_bytes']/2**30:.2f} + temps "
          f"{est['temp_bytes']/2**30:.2f} + out {est['output_bytes']/2**30:.2f} "
          f"- aliased {est['alias_bytes']/2**30:.2f})  "
          f"compile {compile_s:.0f}s")
    path = os.path.join(REPO, "artifacts", "gpt13b_aot_tpu.json")
    try:
        results = json.load(open(path))
        if "peak_hbm_bytes" in results:  # pre-accumulation single-entry file
            results = {}
    except (FileNotFoundError, json.JSONDecodeError):
        results = {}
    key = (f"{args.topology}_sharding{args.sharding}xmodel{args.model}"
           f"_b{args.batch}" + ("_flash" if cfg.use_flash_attention else ""))
    results[key] = est
    with open(path, "w") as f:
        json.dump(results, f, indent=1)
    print(f"wrote {path}")
    if not est["fits_v5e_16gb"]:
        print("does not fit v5e HBM!")
        sys.exit(1)


if __name__ == "__main__":
    main()

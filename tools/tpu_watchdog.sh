#!/bin/bash
# Probe the TPU tunnel every 5 min; when healthy, run the perf sprint once
# and record everything under artifacts/. Leaves a marker file so the main
# session can see status at a glance.
cd /root/repo
MARKER=artifacts/TPU_STATUS.txt
LOG=artifacts/ROUND3_SPRINT.log
while true; do
  if timeout 90 python -c "import jax; assert any('tpu' in d.platform.lower() or 'axon' in str(d).lower() for d in jax.devices())" 2>/dev/null; then
    echo "HEALTHY $(date -u +%FT%TZ)" >> "$MARKER"
    echo "=== sprint started $(date -u +%FT%TZ) ===" >> "$LOG"
    python tools/tpu_perf_sprint.py >> "$LOG" 2>&1
    rc=$?
    echo "=== sprint done $(date -u +%FT%TZ) rc=$rc ===" >> "$LOG"
    # keep probing afterwards so we know the window is still open,
    # but don't re-run the sprint automatically
    while timeout 90 python -c "import jax; assert any('tpu' in d.platform.lower() or 'axon' in str(d).lower() for d in jax.devices())" 2>/dev/null; do
      echo "STILL-HEALTHY $(date -u +%FT%TZ)" >> "$MARKER"
      sleep 300
    done
    echo "WEDGED-AGAIN $(date -u +%FT%TZ)" >> "$MARKER"
  else
    echo "WEDGED $(date -u +%FT%TZ)" >> "$MARKER"
  fi
  sleep 300
done

#!/bin/bash
# Probe the TPU tunnel every 5 min; when healthy, run the perf sprint once
# and record everything under artifacts/. Leaves a marker file so the main
# session can see status at a glance.
#
# The probe requires a real matmul EXECUTION on the chip, not just device
# enumeration: one observed wedge mode (round 4, 2026-07-31) answers
# jax.devices() instantly yet hangs any compile/execute call.
cd /root/repo
MARKER=artifacts/TPU_STATUS.txt
LOG=artifacts/ROUND4_SPRINT.log
# shared probe entry point: one definition of "healthy" (matmul executes)
probe_ok() { timeout 300 python tools/tpu_perf_sprint.py --probe-only 2>/dev/null; }
while true; do
  if probe_ok; then
    echo "HEALTHY-EXEC $(date -u +%FT%TZ)" >> "$MARKER"
    echo "=== sprint started $(date -u +%FT%TZ) ===" >> "$LOG"
    python tools/tpu_perf_sprint.py >> "$LOG" 2>&1
    rc=$?
    echo "=== sprint done $(date -u +%FT%TZ) rc=$rc ===" >> "$LOG"
    # keep probing afterwards so we know the window is still open,
    # but don't re-run the sprint automatically
    while probe_ok; do
      echo "STILL-HEALTHY $(date -u +%FT%TZ)" >> "$MARKER"
      sleep 300
    done
    echo "WEDGED-AGAIN $(date -u +%FT%TZ)" >> "$MARKER"
  else
    echo "WEDGED-OR-ENUM-ONLY $(date -u +%FT%TZ)" >> "$MARKER"
  fi
  sleep 300
done

"""Compiler-ranked parallelism plans for GPT-1.3B on a v5e-64 slice.

The auto-parallel planner applied to the BASELINE config-4 north star:
enumerate (data, sharding, model) mesh factorizations of 64 chips, compile
the full AdamW train step for each candidate ahead-of-time with the REAL
TPU compiler (abstract shapes — no arrays, no TPU execution), and rank by
the compiler's estimated step time under the 16 GB v5e HBM budget.

Reference analog: auto_parallel/planner.py's MCMC search scored by
cost_model.py's simulator — here the search is exhaustive (the space is
tiny once axes are named) and the score is the compiler's own cost model,
which cannot drift from the real executable.

Every per-candidate row records compiler ESTIMATES, not measurements;
tokens/s and MFU derived from optimal_seconds are labeled est_*.

Usage: python tools/mesh_planner_13b.py [--quick]
Writes artifacts/mesh_plan_13b.json (+ prints the ranked table).
"""
import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from paddle_tpu.jit.aot import V5E_PEAK_BF16_FLOPS as V5E_PEAK_BF16  # noqa: E402
HBM_BUDGET = 16 * 2**30
GLOBAL_BATCH, SEQ, N_CHIPS = 64, 2048, 64


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="3 representative candidates only")
    args = ap.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")

    from paddle_tpu.distributed.auto_parallel.planner import (
        enumerate_factorizations,
    )
    from paddle_tpu.jit.aot import topology_mesh
    from paddle_tpu.distributed import mesh as mesh_mod
    from paddle_tpu.models import gpt_presets
    from paddle_tpu.models.gpt import gpt_hbm_estimate

    # model axis caps at num_heads=16; batch axes (data x sharding) must
    # divide global batch 64
    cands = enumerate_factorizations(N_CHIPS, ("data", "sharding", "model"),
                                     caps={"model": 16})
    cands = [c for c in cands
             if GLOBAL_BATCH % (c.get("data", 1) * c.get("sharding", 1)) == 0]
    if args.quick:
        keep = [{"sharding": 32, "model": 2}, {"data": 64},
                {"data": 8, "sharding": 4, "model": 2}]
        cands = [c for c in cands if c in keep]

    cfg = gpt_presets("gpt-1.3b", mode="scan", dtype="bfloat16",
                      recompute=True, use_flash_attention=True)
    rows = []
    print(f"{len(cands)} candidates; ~1 min compile each\n")
    for shape_map in cands:
        label = "x".join(f"{a}{d}" for a, d in sorted(shape_map.items()))
        t0 = time.time()
        try:
            mesh = topology_mesh("v5e:8x8", shape_map)
            mesh_mod.set_mesh(mesh)
            est = gpt_hbm_estimate(cfg, mesh, global_batch=GLOBAL_BATCH,
                                   seq=SEQ)
        except Exception as e:
            rows.append({"mesh": shape_map, "error": f"{type(e).__name__}: "
                         f"{str(e)[:200]}"})
            print(f"  {label}: FAILED {type(e).__name__} "
                  f"[{time.time()-t0:.0f}s]")
            continue
        finally:
            mesh_mod.set_mesh(None)
        if est is None:  # backend exposed no memory analysis
            rows.append({"mesh": shape_map,
                         "error": "memory_analysis unavailable"})
            print(f"  {label}: no memory analysis [{time.time()-t0:.0f}s]")
            continue
        row = {"mesh": shape_map, **est,
               "compile_seconds": round(time.time() - t0, 1)}
        row["fits_v5e_16gb"] = est["peak_hbm_bytes"] <= HBM_BUDGET
        from paddle_tpu.jit.aot import estimate_step_seconds

        sec = estimate_step_seconds(est)
        if sec is not None:
            row["est_step_seconds"] = round(sec["seconds"], 6)
            row["est_signal"] = sec["signal"]
            toks = GLOBAL_BATCH * SEQ / N_CHIPS
            row["est_tokens_per_sec_chip"] = round(toks / sec["seconds"], 1)
            if est.get("flops"):
                row["est_mfu"] = round(
                    est["flops"] / sec["seconds"] / V5E_PEAK_BF16, 4)
        print(f"  {label}: peak {est['peak_hbm_bytes']/2**30:.2f} GiB"
              + (f", est step {row['est_step_seconds']*1e3:.1f} ms"
                 f" ({row['est_signal']})"
                 f", est {row.get('est_tokens_per_sec_chip', 0):.0f} tok/s/chip"
                 f", est MFU {row.get('est_mfu', float('nan')):.3f}"
                 if sec is not None else "")
              + f" [{row['compile_seconds']:.0f}s]")
        rows.append(row)

    def rank(r):
        if r.get("error"):
            return (2, 0.0)
        if not r.get("fits_v5e_16gb"):
            return (1, 0.0)
        return (0, r.get("est_step_seconds") or float("inf"))

    rows.sort(key=rank)
    out = {"config": {"preset": "gpt-1.3b", "global_batch": GLOBAL_BATCH,
                      "seq": SEQ, "topology": "v5e:8x8",
                      "dtype": "bfloat16", "recompute": True,
                      "note": "compiler AOT estimates, not measurements"},
           "ranked": rows}
    path = os.path.join(REPO, "artifacts", "mesh_plan_13b.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    best = rows[0]
    print(f"\nbest plan: {best['mesh']}  "
          f"(est step {best.get('est_step_seconds', 0)*1e3:.1f} ms, "
          f"peak {best.get('peak_hbm_bytes', 0)/2**30:.2f} GiB)")
    print(f"wrote {path}")


if __name__ == "__main__":
    main()

"""Config-4 loss-curve golden: a pre-registered 200-step curve
(VERDICT r4 #10).

BASELINE.md config 4's acceptance is "GPT-1.3B ... loss curve matches
CUDA baseline". The hardware run needs a PRE-REGISTERED curve to match,
so this pins one: the full 1.3B TRAINING SCHEDULE (AdamW b1=0.9 b2=0.95
wd=0.1, global-norm clip 1.0, linear-warmup->cosine lr, ZeRO-2 x mp2
hybrid — the exact BASELINE parallelism) at reduced width so the
8-device virtual CPU mesh can run 200 steps deterministically. Seeds,
config, per-step losses, and match tolerances all land in
artifacts/gpt13b_loss_golden.json; tests/test_loss_golden.py re-runs a
prefix as the regression guard.

Data is a seeded order-2 Markov token stream — learnable structure, so
the curve has a real descent to match, not noise around ln(vocab).
"""
from __future__ import annotations

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

SEED_MODEL = 1234
SEED_DATA = 4321
STEPS = 200
BATCH, SEQ = 8, 128
VOCAB = 512

# reduced-width 1.3B: same depth-to-width feel, tractable on 8 CPU devs
CFG = dict(vocab_size=VOCAB, hidden_size=192, num_layers=6, num_heads=8,
           max_position_embeddings=SEQ, mode="scan",
           use_flash_attention=False)
# 1.3B trains at peak_lr 2e-4; the reduced-width replica takes the
# width-scaled equivalent (~lr * 2048/192) so the 200-step curve has a
# real descent to match rather than a flat warmup tail
SCHED = dict(peak_lr=2e-3, warmup_steps=20, total_steps=STEPS,
             weight_decay=0.1, beta1=0.9, beta2=0.95, clip_norm=1.0,
             note="peak_lr width-scaled from the 1.3B schedule's 2e-4")
TOPO = {"sharding": 4, "model": 2}  # BASELINE config 4: ZeRO-2 x mp2


def _transition_table():
    """Fixed random Markov table: each token has 4 equally-likely
    successors. Cross-entropy floor = ln(4) ≈ 1.386 — a LEARNABLE
    lookup (unlike modular-arithmetic streams, which gradient descent
    only groks far beyond 200 steps), so the golden curve has a real
    descent for the hardware run to match."""
    import numpy as np

    return np.random.RandomState(99).randint(0, VOCAB, (VOCAB, 4))


_TABLE = None


def markov_batch(rs, step):
    import numpy as np

    global _TABLE
    if _TABLE is None:
        _TABLE = _transition_table()
    mix = rs[(step * 7919) % len(rs)]
    ids = np.zeros((BATCH, SEQ + 1), np.int64)
    ids[:, 0] = mix[:BATCH] % VOCAB
    for t in range(1, SEQ + 1):
        choice = (mix[(BATCH + t) % len(mix)] + np.arange(BATCH)) % 4
        ids[:, t] = _TABLE[ids[:, t - 1], choice]
    return ids[:, :-1], ids[:, 1:]


def build_step():
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    import paddle_tpu.optimizer as opt
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.distributed import mesh as mesh_mod
    from paddle_tpu.distributed.sharding import group_sharded_parallel
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models import (
        GPTForCausalLM, GPTPretrainingCriterion, gpt_presets,
    )

    mesh_mod.set_mesh(mesh_mod.build_mesh(TOPO))
    paddle.seed(SEED_MODEL)
    model = GPTForCausalLM(gpt_presets("gpt-test", **CFG), seed=SEED_MODEL)
    crit = GPTPretrainingCriterion()
    sched = opt.lr.LinearWarmup(
        opt.lr.CosineAnnealingDecay(SCHED["peak_lr"],
                                    T_max=SCHED["total_steps"]),
        warmup_steps=SCHED["warmup_steps"], start_lr=0.0,
        end_lr=SCHED["peak_lr"])
    optim = opt.AdamW(
        learning_rate=sched, weight_decay=SCHED["weight_decay"],
        beta1=SCHED["beta1"], beta2=SCHED["beta2"],
        grad_clip=nn.ClipGradByGlobalNorm(SCHED["clip_norm"]),
        parameters=model.parameters())
    model, optim, _ = group_sharded_parallel(model, optim, "os_g")
    step = TrainStep(model, lambda lg, lb: crit(lg, lb), optim,
                     batch_spec=P("sharding"))
    return step, sched


def run(steps=STEPS):
    import numpy as np

    import paddle_tpu as paddle

    rs = np.random.RandomState(SEED_DATA).randint(
        0, 1 << 30, size=(64, 4 * BATCH + SEQ + 8)).astype(np.int64)
    step, sched = build_step()
    losses = []
    for i in range(steps):
        ids, labels = markov_batch(rs, i)
        loss = step(inputs=(paddle.to_tensor(ids),),
                    labels=(paddle.to_tensor(labels),))
        sched.step()
        losses.append(round(float(loss), 6))
    return losses


def main():
    import numpy as np

    steps = int(sys.argv[1]) if len(sys.argv) > 1 else STEPS
    losses = run(steps)
    first, last = losses[0], np.mean(losses[-10:])
    rec = {
        "config": CFG, "schedule": SCHED, "topology": TOPO,
        "seeds": {"model": SEED_MODEL, "data": SEED_DATA},
        "batch": BATCH, "seq": SEQ, "steps": steps,
        "losses": losses,
        "tolerances": {
            "per_step_rtol_f32_same_backend": 1e-4,
            "per_step_rtol_hardware_bf16": 0.05,
            "smoothed10_rtol_hardware_bf16": 0.02,
            "note": ("same-backend f32 reruns must match per-step to "
                     "1e-4; the TPU bf16 hardware run matches the "
                     "10-step-smoothed curve to 2% and per-step to 5%"),
        },
        "summary": {"first_loss": first, "final10_mean": round(float(last), 4),
                    "descent": round(float(first - last), 4)},
    }
    path = os.path.join(REPO, "artifacts", "gpt13b_loss_golden.json")
    with open(path, "w") as f:
        json.dump(rec, f, indent=1)
    print(json.dumps({"steps": steps, "first": first,
                      "final10_mean": rec["summary"]["final10_mean"]}))


if __name__ == "__main__":
    # virtual-mesh tool by design: pin the CPU platform via jax.config
    # (the axon sitecustomize clobbers the JAX_PLATFORMS env var) and
    # force 8 host devices BEFORE the backend initializes
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")
    import jax

    jax.config.update("jax_platforms", "cpu")
    main()

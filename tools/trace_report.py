"""Step-time-breakdown report: chrome trace × metrics snapshot (ISSUE 3).

Joins two artifacts the telemetry layer produces —

  1. a chrome trace exported by `paddle_tpu.profiler.Profiler` (the span
     tree: "step" spans delimit steps, phase spans fill them), and
  2. a `MetricsRegistry` snapshot (JSON; grad_comm / checkpoint / dispatch
     counters)

— into ONE report: per-phase wall time next to the matching counters, so
the comm row shows not just "x ms" but "x ms, N collectives, B bytes/step"
and the two accountings can be cross-checked against
artifacts/grad_comm_bench.json.

Usage:
    python tools/trace_report.py TRACE.json METRICS.json
    python tools/trace_report.py --demo [--codec bf16] [--steps 3]
        # runs a 3-step gpt-test training loop (eager tape + bucketed grad
        # sync at world=2 + a checkpoint save) under Profiler+StepTimer,
        # exports trace + snapshot to --out (default /tmp), then reports.

The demo's comm row must agree with tools/grad_comm_bench.py's artifact for
the same codec (collectives/step and bytes/step) — that agreement is the
acceptance check that the wall-time view and the counter view describe the
same wire.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


# ----------------------------------------------------------------- joining
def metrics_extras(metrics: dict, steps: int) -> dict:
    """Per-phase extra columns pulled out of a registry snapshot."""
    extras = {}
    steps = max(int(steps), 1)

    colls = metrics.get("grad_comm_collectives_total") or {}
    byts = metrics.get("grad_comm_bytes_total") or {}
    if colls:
        # label keys look like "codec=bf16,path=eager" (the path label is
        # ISSUE 8's eager-vs-traced wire split)
        def labels(k):
            return dict(kv.split("=", 1) for kv in k.split(","))

        total_coll = sum(colls.values())
        total_bytes = sum(byts.values())
        extras["comm"] = {
            "collectives/step": round(total_coll / steps, 2),
            "bytes/step": int(round(total_bytes / steps)),
            "codec": "+".join(sorted({labels(k).get("codec", k)
                                      for k in colls})),
        }
        by_path = {}
        for k, v in byts.items():
            p = labels(k).get("path", "eager")
            by_path[p] = by_path.get(p, 0) + v
        if len(by_path) > 1 or "traced" in by_path:
            extras["comm"]["bytes/step by path"] = {
                p: int(round(v / steps)) for p, v in sorted(by_path.items())}
    saves = metrics.get("checkpoint_save_seconds") or {}
    if isinstance(saves, dict) and saves.get("count"):
        extras["checkpoint"] = {
            "saves": saves["count"],
            "mean_ms": round(saves["mean"] * 1e3, 2),
        }
    return extras


def cache_hit_rate(metrics: dict):
    hits = metrics.get("trace_cache_hits_total") or 0
    misses = metrics.get("trace_cache_misses_total") or 0
    return hits / (hits + misses) if (hits + misses) else None


def quantile_section(metrics: dict) -> list:
    """Step-time percentiles (ISSUE 6 satellite): the p50/p95/p99 the
    serving SLO loop consumes, straight from the step_time_seconds
    histogram snapshot."""
    h = metrics.get("step_time_seconds")
    if not isinstance(h, dict) or not h.get("count"):
        return []
    row = " ".join(f"{q}={h[q] * 1e3:.2f}ms" for q in ("p50", "p95", "p99")
                   if h.get(q) is not None)
    return [f"step-time percentiles ({h['count']} steps): {row}"] if row \
        else []


def memory_section(metrics: dict, memory: dict = None) -> list:
    """HBM/host accounting: live/allocator gauges from the snapshot plus
    the compiled-path peaks vs the recorded rooflines."""
    lines = []
    live = metrics.get("live_tensor_bytes")
    if live:
        lines.append(f"live tensor bytes: {int(live):,}")
    peak = metrics.get("peak_hbm_bytes")
    if peak:
        lines.append(f"allocator peak bytes: {int(peak):,}")
    compiled = (memory or {}).get("compiled") or {}
    comp_gauge = metrics.get("compiled_peak_hbm_bytes")
    if not compiled and isinstance(comp_gauge, dict):
        compiled = {k.split("=", 1)[1]: {"peak_hbm_bytes": v}
                    for k, v in comp_gauge.items()}
    for entry, rec in sorted(compiled.items()):
        lines.append(f"compiled peak [{entry}]: "
                     f"{int(rec['peak_hbm_bytes']):,} bytes")
    rooflines = (memory or {}).get("rooflines") or {}
    if rooflines:
        names = ", ".join(f"{k}={v / 2**30:.2f}GiB"
                          for k, v in sorted(rooflines.items()))
        lines.append(f"cost-model rooflines: {names}")
    if lines:
        lines.insert(0, "memory accounting")
    return lines


def cross_rank_section(aggregated: dict) -> list:
    """Rank-0 aggregate view: merged counter totals + the straggler gauge."""
    if not aggregated:
        return []
    lines = [f"cross-rank aggregate ({len(aggregated.get('ranks', []))} "
             f"ranks: {aggregated.get('ranks')})"]
    st = aggregated.get("step_time", {})
    if st.get("per_rank_mean_s"):
        per = " ".join(f"r{i}={v * 1e3:.1f}ms"
                       for i, v in enumerate(st["per_rank_mean_s"]))
        lines.append(f"  step_time_skew: {st.get('skew', 0.0):.3f}  ({per})")
    merged = aggregated.get("metrics", {})
    for name in ("collectives_total", "grad_comm_bytes_total",
                 "eager_dispatch_total"):
        fam = merged.get(name)
        if not fam:
            continue
        if fam["kind"] == "counter":
            total = sum(fam["children"].values())
            lines.append(f"  {name} (summed over ranks): {int(total):,}")
    if aggregated.get("degraded"):
        lines.append(f"  DEGRADED to local view: {aggregated['degraded']}")
    return lines


def build_report(trace: dict, metrics: dict, aggregated: dict = None,
                 memory: dict = None) -> str:
    from paddle_tpu.observability.step_timer import (
        breakdown_from_trace, format_breakdown,
    )

    agg = breakdown_from_trace(trace)
    lines = ["step-time breakdown (trace × metrics join)",
             format_breakdown(agg, extra=metrics_extras(metrics,
                                                        agg["steps"]))]
    hr = cache_hit_rate(metrics)
    if hr is not None:
        lines.append(f"trace-cache hit rate: {hr * 100:.1f}% "
                     f"({metrics.get('trace_cache_hits_total')} hits / "
                     f"{metrics.get('trace_cache_misses_total')} misses)")
    disp = metrics.get("eager_dispatch_total")
    if disp is not None:
        lines.append(f"eager dispatches: {disp}")
    lines += quantile_section(metrics)
    lines += memory_section(metrics, memory)
    lines += cross_rank_section(aggregated or metrics.get("_aggregated"))
    return "\n".join(lines)


def load_report(trace_path: str, metrics_path: str) -> str:
    with open(trace_path) as f:
        trace = json.load(f)
    with open(metrics_path) as f:
        metrics = json.load(f)
    # accept either a bare snapshot or an export_jsonl-style record
    if "metrics" in metrics and isinstance(metrics["metrics"], dict):
        metrics = metrics["metrics"]
    return build_report(trace, metrics)


# -------------------------------------------------------------------- demo
def run_demo(out_dir: str, steps: int = 3, codec: str = "bf16",
             world: int = 2):
    """3-step gpt-test eager training run, fully instrumented: Profiler
    trace (span tree), StepTimer rows, grad_comm counters at `world`,
    one checkpoint save. Returns (trace_path, metrics_path, report)."""
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.optimizer as opt
    from paddle_tpu.models import (
        GPTForCausalLM, GPTPretrainingCriterion, gpt_presets,
    )
    from paddle_tpu.observability import StepTimer, get_registry
    from paddle_tpu.profiler import Profiler, ProfilerTarget, RecordEvent
    from paddle_tpu.robustness.checkpoint import CheckpointManager
    from paddle_tpu.distributed import grad_comm

    os.makedirs(out_dir, exist_ok=True)
    reg = get_registry()
    reg.reset()

    cfg = gpt_presets("gpt-test")
    model = GPTForCausalLM(cfg, seed=0)
    crit = GPTPretrainingCriterion()
    optim = opt.AdamW(learning_rate=1e-4, parameters=model.parameters())
    comm = grad_comm.GradCommunicator(grad_comm.GradCommConfig(codec=codec))
    ckpt = CheckpointManager(os.path.join(out_dir, "ckpt"), keep_last_n=1)
    params = [p for p in model.parameters() if not p.stop_gradient]

    rs = np.random.RandomState(0)
    batch, seq = 2, 32

    timer = StepTimer(registry=reg)
    prof = Profiler(targets=[ProfilerTarget.CPU])
    step_seconds = []
    with prof, timer:
        for i in range(steps):
            t0 = time.perf_counter()
            with RecordEvent("step"):
                with RecordEvent("data"):
                    ids = paddle.to_tensor(
                        rs.randint(0, cfg.vocab_size, (batch, seq)),
                        dtype="int64")
                    labels = paddle.to_tensor(
                        rs.randint(0, cfg.vocab_size, (batch, seq)),
                        dtype="int64")
                with RecordEvent("forward"):
                    logits = model(ids)
                    loss = crit(logits, labels)
                with RecordEvent("backward"):
                    loss.backward()
                comm.sync(params, world=world)   # emits the "comm" span
                with RecordEvent("optimizer"):
                    optim.step()
                    optim.clear_grad()
                if i == steps - 1:               # emits "checkpoint" span
                    ckpt.save(model.state_dict(), i)
            prof.step()
            timer.step()
            step_seconds.append(time.perf_counter() - t0)
        ckpt.close()

    # distributed-plane sections (ISSUE 6): a memory-accounting sample and
    # one EMULATED 3-rank aggregation round — rank 1 is a 1.3x straggler,
    # so the report's skew line shows a nonzero step_time_skew the way a
    # real straggling host would
    from paddle_tpu.observability import (
        MetricsAggregator, memory as obs_memory, note_step_time,
    )

    for s in step_seconds:
        note_step_time(s)
    memory = obs_memory.memory_report()

    def _emulated_gather(payload, _ranks=3, _straggler=1.3):
        import copy

        outs = []
        for r in range(_ranks):
            p = copy.deepcopy(payload)
            p["rank"] = r
            mean = p["step_time"].get("mean_s") or 0.0
            if r == 1:
                p["step_time"]["mean_s"] = mean * _straggler
            outs.append(p)
        return outs

    aggregated = MetricsAggregator(gather_fn=_emulated_gather).aggregate()

    trace_path = os.path.join(out_dir, "trace.json")
    prof.export(trace_path)
    metrics_path = os.path.join(out_dir, "metrics.json")
    snapshot = reg.snapshot()
    with open(metrics_path, "w") as f:
        json.dump(snapshot, f, indent=1)

    with open(trace_path) as f:
        trace = json.load(f)
    report = build_report(trace, snapshot, aggregated=aggregated,
                          memory=memory)
    # cross-check: the comm row's counters must equal the communicator's
    # own per-step stats (same accounting as artifacts/grad_comm_bench.json)
    per_step_coll = comm.stats["collectives"]
    per_step_bytes = comm.stats["comm_bytes"]
    report += (f"\ngrad_comm cross-check ({codec}, world={world}): "
               f"{per_step_coll} collectives/step, "
               f"{per_step_bytes} bytes/step")
    return trace_path, metrics_path, report


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("trace", nargs="?", help="chrome trace JSON")
    ap.add_argument("metrics", nargs="?", help="metrics snapshot JSON")
    ap.add_argument("--demo", action="store_true",
                    help="run the instrumented 3-step gpt-test loop first")
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--codec", default="bf16",
                    help="grad_comm codec for the demo (fp32|bf16|int8)")
    ap.add_argument("--out", default="/tmp/paddle_tpu_trace_report",
                    help="demo output directory")
    args = ap.parse_args(argv)

    if args.demo:
        trace_path, metrics_path, report = run_demo(
            args.out, steps=args.steps, codec=args.codec)
        print(f"# trace:   {trace_path}\n# metrics: {metrics_path}")
        print(report)
        return 0
    if not (args.trace and args.metrics):
        ap.error("TRACE and METRICS paths required (or --demo)")
    print(load_report(args.trace, args.metrics))
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Static-analysis gate: run the paddle_tpu/analysis suite over the tree.

    python tools/check_static.py --baseline tools/static_baseline.json

Exit codes (CI contract, also asserted by tests/test_static_analysis.py):
    0  clean — every finding is baselined, every baseline entry is live
    1  NEW findings (not in the baseline): fix them or consciously
       baseline them with --write-baseline
    2  STALE baseline entries or STALE inline waivers: the finding was
       fixed, so the entry/comment must be deleted — suppressions only
       shrink
    3  parse errors (a framework file no longer parses)

Modes:
    --changed-only [REF]  report findings only for files changed vs the
                          git ref (default HEAD) + untracked files; the
                          project-wide index still builds over ALL files
                          (interprocedural rules need the whole graph),
                          the parsed-AST cache keeps that cheap
    --sarif PATH          additionally write SARIF 2.1.0 for CI
                          annotation ("-" = stdout)
    --no-cache            skip the parsed-AST cache (.cache/static_ast.pkl)
    --fix [--apply]       mechanical auto-fixes: delete fully-stale
                          `# lint-ok:` waiver comments and insert
                          `daemon=True` at C001 Thread sites (the
                          framework thread contract). DRY RUN by default —
                          prints the unified diff; --apply writes it.

The import path is arranged so this runs without jax installed: the
analysis package is pure stdlib, but ``paddle_tpu/__init__`` is not, so
the package is loaded by file path instead of `import paddle_tpu`.
"""
from __future__ import annotations

import argparse
import importlib.util
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CACHE_PATH = os.path.join(REPO, ".cache", "static_ast.pkl")


def _load_analysis():
    """Load paddle_tpu.analysis without importing paddle_tpu itself
    (keeps the gate <1s of import cost and jax-free)."""
    try:
        import paddle_tpu.analysis as pkg  # already imported? use it
        return pkg
    except ImportError:
        pass
    import types
    shim = types.ModuleType("paddle_tpu")
    shim.__path__ = [os.path.join(REPO, "paddle_tpu")]
    sys.modules.setdefault("paddle_tpu", shim)
    spec = importlib.util.spec_from_file_location(
        "paddle_tpu.analysis",
        os.path.join(REPO, "paddle_tpu", "analysis", "__init__.py"),
        submodule_search_locations=[
            os.path.join(REPO, "paddle_tpu", "analysis")])
    mod = importlib.util.module_from_spec(spec)
    sys.modules["paddle_tpu.analysis"] = mod
    spec.loader.exec_module(mod)
    return mod


def _changed_files(ref: str, cwd: str):
    """Git-toplevel-relative posix paths of .py files changed vs ``ref``
    plus untracked ones; None when git is unavailable (caller falls back
    to a full report). Finding paths are reported relative to the same
    toplevel, so the sets compare directly."""
    out = set()
    for args in (["git", "diff", "--name-only", ref, "--", "*.py"],
                 ["git", "ls-files", "--others", "--exclude-standard",
                  "--", "*.py"]):
        try:
            p = subprocess.run(args, cwd=cwd, capture_output=True,
                               text=True, timeout=30)
        except (OSError, subprocess.TimeoutExpired):
            return None
        if p.returncode != 0:
            return None
        out.update(line.strip() for line in p.stdout.splitlines()
                   if line.strip())
    return out


def _sarif(findings, analysis) -> dict:
    """Minimal SARIF 2.1.0 document for CI annotation."""
    rule_ids = sorted({f.rule for f in findings} | set(analysis.RULES))
    rules = []
    for rid in rule_ids:
        inv, rat = analysis.RULES.get(rid, ("", ""))
        rules.append({
            "id": rid,
            "shortDescription": {"text": inv or rid},
            "fullDescription": {"text": rat or inv or rid},
        })
    results = [{
        "ruleId": f.rule,
        "level": "error",
        "message": {"text": f.message},
        "locations": [{
            "physicalLocation": {
                "artifactLocation": {"uri": f.path,
                                     "uriBaseId": "SRCROOT"},
                "region": {"startLine": max(1, f.line)},
            },
        }],
    } for f in findings]
    return {
        "version": "2.1.0",
        "$schema": "https://raw.githubusercontent.com/oasis-tcs/"
                   "sarif-spec/master/Schemata/sarif-schema-2.1.0.json",
        "runs": [{
            "tool": {"driver": {"name": "paddle_tpu.analysis",
                                "rules": rules}},
            "results": results,
        }],
    }


def _fix_waiver_line(line: str) -> str:
    """Strip the `# lint-ok: ...` comment tail from one source line."""
    import re as _re
    out = _re.sub(r"\s*#\s*lint-ok:.*$", "", line)
    return out.rstrip() + ("\n" if line.endswith("\n") else "")


def _fix_daemon_calls(source: str, relpath: str, analysis) -> str:
    """Insert ``daemon=True`` into every threading.Thread(...) call that
    states no daemon= (rule C001). The framework contract is daemon=True:
    the post-suite thread-leak check requires framework threads not to
    outlive the interpreter (docs/ARCHITECTURE: concurrency rules)."""
    import ast
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return source
    conc = analysis.concurrency
    # line-start offsets so (end_lineno, end_col_offset) maps to one
    # character position in the full source
    starts, total = [], 0
    for line in source.splitlines(keepends=True):
        starts.append(total)
        total += len(line)
    edits = []               # absolute offset of the call's closing paren
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and conc._is_thread_call(node)):
            continue
        kwargs = {k.arg for k in node.keywords if k.arg}
        if "daemon" in kwargs or any(k.arg is None for k in node.keywords):
            continue
        if node.end_lineno is None or node.end_lineno > len(starts):
            continue
        pos = starts[node.end_lineno - 1] + node.end_col_offset - 1
        if 0 <= pos < len(source) and source[pos] == ")":
            edits.append(pos)
    for pos in sorted(edits, reverse=True):
        j = pos - 1
        while j >= 0 and source[j] in " \t\r\n":
            j -= 1
        prev = source[j] if j >= 0 else "("
        insert = "daemon=True" if prev in ",(" else ", daemon=True"
        source = source[:pos] + insert + source[pos:]
    return source


def run_fix(findings, stale_waivers, rel_root: str, analysis,
            apply: bool) -> int:
    """The mechanical-fix subset: C001 daemon= insertion + fully-stale
    waiver-comment removal. Dry-run prints a unified diff; --apply writes
    the changed files. Returns the number of files changed (or that would
    change)."""
    import difflib

    by_file = {}
    for w in stale_waivers:
        by_file.setdefault(w["path"], {"waiver_lines": set(),
                                       "daemon": False})
        by_file[w["path"]]["waiver_lines"].add(w["line"])
    for f in findings:
        if f.rule == "C001":
            by_file.setdefault(f.path, {"waiver_lines": set(),
                                        "daemon": False})
            by_file[f.path]["daemon"] = True

    changed = 0
    for rel in sorted(by_file):
        abspath = os.path.join(rel_root, rel)
        try:
            with open(abspath, "r", encoding="utf-8") as fh:
                original = fh.read()
        except OSError as e:
            print(f"check_static --fix: cannot read {rel}: {e}",
                  file=sys.stderr)
            continue
        fixed = original
        lines = fixed.splitlines(keepends=True)
        for ln in sorted(by_file[rel]["waiver_lines"], reverse=True):
            if 1 <= ln <= len(lines):
                lines[ln - 1] = _fix_waiver_line(lines[ln - 1])
        fixed = "".join(lines)
        if by_file[rel]["daemon"]:
            fixed = _fix_daemon_calls(fixed, rel, analysis)
        if fixed == original:
            continue
        changed += 1
        diff = difflib.unified_diff(
            original.splitlines(keepends=True),
            fixed.splitlines(keepends=True),
            fromfile=f"a/{rel}", tofile=f"b/{rel}")
        sys.stdout.writelines(diff)
        if apply:
            with open(abspath, "w", encoding="utf-8") as fh:
                fh.write(fixed)
    verb = "fixed" if apply else "would fix (dry run; pass --apply)"
    print(f"check_static --fix: {verb} {changed} file(s)")
    return changed


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--root", default=os.path.join(REPO, "paddle_tpu"),
                    help="source tree to analyze (default: paddle_tpu/)")
    ap.add_argument("--baseline",
                    default=os.path.join(REPO, "tools",
                                         "static_baseline.json"))
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite the baseline from current findings")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report on stdout")
    ap.add_argument("--rules", default="",
                    help="comma-separated rule ids to restrict to")
    ap.add_argument("--changed-only", nargs="?", const="HEAD", default=None,
                    metavar="REF",
                    help="report findings only for files changed vs REF "
                         "(default HEAD) + untracked files")
    ap.add_argument("--sarif", default=None, metavar="PATH",
                    help="write SARIF 2.1.0 to PATH ('-' = stdout)")
    ap.add_argument("--no-cache", action="store_true",
                    help="skip the parsed-AST cache")
    ap.add_argument("--cache-path", default=CACHE_PATH,
                    help=argparse.SUPPRESS)
    ap.add_argument("--fix", action="store_true",
                    help="mechanical auto-fixes (stale waivers, C001 "
                         "daemon=) — dry-run diff unless --apply")
    ap.add_argument("--apply", action="store_true",
                    help="with --fix: write the fixes instead of printing "
                         "the diff")
    args = ap.parse_args(argv)

    t0 = time.perf_counter()
    analysis = _load_analysis()
    # report paths relative to the repo when analyzing inside it, else
    # relative to the analyzed root (which is then its own git toplevel
    # for --changed-only purposes — the tmp-repo test shape)
    root_abs = os.path.abspath(args.root)
    inside = (root_abs + os.sep).startswith(REPO + os.sep)
    rel_root = REPO if inside else root_abs
    runner = analysis.Analysis(analysis.default_checkers(),
                               rel_root=rel_root)
    cache = None if args.no_cache else analysis.AstCache(args.cache_path)
    findings = runner.run_path(args.root, cache=cache)
    if runner.parse_errors:
        for e in runner.parse_errors:
            print(f"PARSE ERROR: {e}", file=sys.stderr)
        return 3
    if args.rules:
        keep = {r.strip() for r in args.rules.split(",") if r.strip()}
        findings = [f for f in findings if f.rule in keep]
    stale_waivers = runner.stale_waivers

    changed = None
    if args.changed_only is not None:
        changed = _changed_files(args.changed_only, root_abs)
        if changed is None:
            print("check_static: --changed-only: git unavailable, "
                  "reporting all files", file=sys.stderr)
        else:
            findings = [f for f in findings if f.path in changed]
            stale_waivers = [w for w in stale_waivers
                             if w["path"] in changed]

    if args.fix:
        run_fix(findings, stale_waivers, rel_root, analysis,
                apply=args.apply)
        return 0

    if args.write_baseline:
        with open(args.baseline, "w", encoding="utf-8") as f:
            json.dump(analysis.findings_to_baseline(findings), f, indent=1,
                      sort_keys=True)
            f.write("\n")
        print(f"baseline written: {args.baseline} "
              f"({len(findings)} entries)")
        return 0

    baseline = []
    if os.path.exists(args.baseline):
        baseline = analysis.load_baseline(args.baseline)
    if changed is not None:
        baseline = [e for e in baseline if e["path"] in changed]
    new, stale = analysis.diff_against_baseline(findings, baseline)
    wall = time.perf_counter() - t0

    if args.sarif:
        doc = json.dumps(_sarif(findings, analysis), indent=1)
        if args.sarif == "-":
            print(doc)
        else:
            with open(args.sarif, "w", encoding="utf-8") as f:
                f.write(doc + "\n")

    if args.json:
        print(json.dumps({
            "findings": [f.to_dict() for f in findings],
            "new": [f.to_dict() for f in new],
            "stale": stale,
            "stale_waivers": stale_waivers,
            "baseline_entries": len(baseline),
            "changed_only": sorted(changed) if changed is not None else None,
            "wall_s": round(wall, 3),
            "rule_timings": runner.timings,
            "cache": {"hits": cache.hits, "misses": cache.misses}
            if cache else None,
        }, indent=1))
    else:
        per_rule = {}
        for f in findings:
            per_rule[f.rule] = per_rule.get(f.rule, 0) + 1
        scope = f" · {len(changed)} changed file(s)" if changed is not None \
            else ""
        cache_note = f" · cache {cache.hits}h/{cache.misses}m" if cache \
            else ""
        print(f"check_static: {len(findings)} finding(s) "
              f"({', '.join(f'{r}={n}' for r, n in sorted(per_rule.items()))})"
              f" · baseline {len(baseline)} entr(ies){scope}"
              f" · wall {wall:.2f}s{cache_note}")
        for f in new:
            inv = analysis.RULES.get(f.rule, ("", ""))[0]
            print(f"NEW  {f}")
            if inv:
                print(f"      invariant: {inv}")
        for e in stale:
            print(f"STALE baseline entry (finding fixed — delete it): "
                  f"{e['path']}: {e['rule']} {e['message']}")
        for w in stale_waivers:
            print(f"STALE waiver (rule no longer fires — delete the "
                  f"comment): {w['path']}:{w['line']}: "
                  f"# lint-ok: {w['rule']}")

    if new:
        print(f"FAIL: {len(new)} new finding(s) — fix, waive inline "
              "(# lint-ok: <rule> <reason>), or --write-baseline",
              file=sys.stderr)
        return 1
    if stale or stale_waivers:
        what = []
        if stale:
            what.append(f"{len(stale)} stale baseline entr(ies)")
        if stale_waivers:
            what.append(f"{len(stale_waivers)} stale waiver(s)")
        print(f"FAIL: {' + '.join(what)} — remove them "
              f"({os.path.relpath(args.baseline, REPO)} / the # lint-ok "
              "comments)", file=sys.stderr)
        return 2
    print("OK: clean against baseline")
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Static-analysis gate: run the paddle_tpu/analysis suite over the tree.

    python tools/check_static.py --baseline tools/static_baseline.json

Exit codes (CI contract, also asserted by tests/test_static_analysis.py):
    0  clean — every finding is baselined, every baseline entry is live
    1  NEW findings (not in the baseline): fix them or consciously
       baseline them with --write-baseline
    2  STALE baseline entries: the finding was fixed, so the entry must
       be deleted — the baseline only shrinks
    3  parse errors (a framework file no longer parses)

The import path is arranged so this runs without jax installed: the
analysis package is pure stdlib, but ``paddle_tpu/__init__`` is not, so
the package is loaded by file path instead of `import paddle_tpu`.
"""
from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_analysis():
    """Load paddle_tpu.analysis without importing paddle_tpu itself
    (keeps the gate <1s and jax-free)."""
    try:
        import paddle_tpu.analysis as pkg  # already imported? use it
        return pkg
    except ImportError:
        pass
    import types
    shim = types.ModuleType("paddle_tpu")
    shim.__path__ = [os.path.join(REPO, "paddle_tpu")]
    sys.modules.setdefault("paddle_tpu", shim)
    spec = importlib.util.spec_from_file_location(
        "paddle_tpu.analysis",
        os.path.join(REPO, "paddle_tpu", "analysis", "__init__.py"),
        submodule_search_locations=[
            os.path.join(REPO, "paddle_tpu", "analysis")])
    mod = importlib.util.module_from_spec(spec)
    sys.modules["paddle_tpu.analysis"] = mod
    spec.loader.exec_module(mod)
    return mod


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--root", default=os.path.join(REPO, "paddle_tpu"),
                    help="source tree to analyze (default: paddle_tpu/)")
    ap.add_argument("--baseline",
                    default=os.path.join(REPO, "tools",
                                         "static_baseline.json"))
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite the baseline from current findings")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report on stdout")
    ap.add_argument("--rules", default="",
                    help="comma-separated rule ids to restrict to")
    args = ap.parse_args(argv)

    analysis = _load_analysis()
    runner = analysis.Analysis(analysis.default_checkers(), rel_root=REPO)
    findings = runner.run_path(args.root)
    if runner.parse_errors:
        for e in runner.parse_errors:
            print(f"PARSE ERROR: {e}", file=sys.stderr)
        return 3
    if args.rules:
        keep = {r.strip() for r in args.rules.split(",") if r.strip()}
        findings = [f for f in findings if f.rule in keep]

    if args.write_baseline:
        with open(args.baseline, "w", encoding="utf-8") as f:
            json.dump(analysis.findings_to_baseline(findings), f, indent=1,
                      sort_keys=True)
            f.write("\n")
        print(f"baseline written: {args.baseline} "
              f"({len(findings)} entries)")
        return 0

    baseline = []
    if os.path.exists(args.baseline):
        baseline = analysis.load_baseline(args.baseline)
    new, stale = analysis.diff_against_baseline(findings, baseline)

    if args.json:
        print(json.dumps({
            "findings": [f.to_dict() for f in findings],
            "new": [f.to_dict() for f in new],
            "stale": stale,
            "baseline_entries": len(baseline),
        }, indent=1))
    else:
        per_rule = {}
        for f in findings:
            per_rule[f.rule] = per_rule.get(f.rule, 0) + 1
        print(f"check_static: {len(findings)} finding(s) "
              f"({', '.join(f'{r}={n}' for r, n in sorted(per_rule.items()))})"
              f" · baseline {len(baseline)} entr(ies)")
        for f in new:
            inv = analysis.RULES.get(f.rule, ("", ""))[0]
            print(f"NEW  {f}")
            if inv:
                print(f"      invariant: {inv}")
        for e in stale:
            print(f"STALE baseline entry (finding fixed — delete it): "
                  f"{e['path']}: {e['rule']} {e['message']}")

    if new:
        print(f"FAIL: {len(new)} new finding(s) — fix, waive inline "
              "(# lint-ok: <rule> <reason>), or --write-baseline",
              file=sys.stderr)
        return 1
    if stale:
        print(f"FAIL: {len(stale)} stale baseline entr(ies) — remove them "
              f"from {os.path.relpath(args.baseline, REPO)}",
              file=sys.stderr)
        return 2
    print("OK: clean against baseline")
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Extend the 1.3B mesh sweep with PIPELINE-parallel candidates.

tools/mesh_planner_13b.py sweeps (data, sharding, model) through the
abstract GSPMD estimator; the 1F1B pipeline path needs the real TrainStep
(gpt_1f1b_train_step + jit.aot.aot_compile_step), which materializes real
params/slots — fine on this host's RAM, heavier per candidate. This tool
AOT-compiles a small set of pipe-bearing candidates for GPT-1.3B on
v5e:8x8 and appends them to artifacts/mesh_plan_13b.json under
"ranked_pipe", so the planner artifact answers: does 1F1B pipelining beat
ZeRO+TP for BASELINE config 4?

All numbers are compiler estimates / roofline bounds, labeled est_*.

Usage: python tools/mesh_planner_13b_pipe.py [--candidates N]
"""
import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from paddle_tpu.jit.aot import V5E_PEAK_BF16_FLOPS  # noqa: E402

HBM_BUDGET = 16 * 2**30
GLOBAL_BATCH, SEQ, N_CHIPS = 64, 2048, 64

CANDIDATES = [
    {"data": 4, "sharding": 2, "pipe": 4, "model": 2},
    {"data": 2, "pipe": 8, "model": 4},
    {"data": 8, "pipe": 4, "model": 2},
]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--candidates", type=int, default=len(CANDIDATES))
    args = ap.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")

    import numpy as np
    from jax.sharding import PartitionSpec as P

    import paddle_tpu as paddle
    import paddle_tpu.optimizer as opt
    from paddle_tpu.distributed import mesh as mesh_mod
    from paddle_tpu.distributed.sharding import group_sharded_parallel
    from paddle_tpu.jit.aot import (
        aot_compile_step, estimate_step_seconds, topology_mesh,
    )
    from paddle_tpu.models import (
        GPTForCausalLM, gpt_presets, gpt_1f1b_train_step,
    )

    rs = np.random.RandomState(0)
    rows = []
    for shape_map in CANDIDATES[:args.candidates]:
        label = "x".join(f"{a}{d}" for a, d in sorted(shape_map.items()))
        t0 = time.time()
        model = optim = step = None  # finally must survive early failures
        try:
            mesh_mod.set_mesh(None)
            # microbatch size (GLOBAL_BATCH / M) must divide by the batch
            # axes' degree, and M >= P for the schedule to fill; prefer
            # M = 4P (quarter-bubble) when the batch allows it
            bdeg = shape_map.get("data", 1) * shape_map.get("sharding", 1)
            pipe = shape_map.get("pipe", 1)
            mb = min(4 * pipe, GLOBAL_BATCH // bdeg)
            if mb < pipe:
                raise ValueError(
                    f"global batch {GLOBAL_BATCH} too small for pipe "
                    f"{pipe} x batch-degree {bdeg}")
            cfg = gpt_presets(
                "gpt-1.3b", mode="scan", dtype="bfloat16", recompute=True,
                use_flash_attention=True, pp_microbatches=mb)
            model = GPTForCausalLM(cfg, seed=0)
            optim = opt.AdamW(learning_rate=1e-4,
                              parameters=model.parameters())
            model, optim, _ = group_sharded_parallel(model, optim, "os_g")
            ids = paddle.to_tensor(
                rs.randint(0, cfg.vocab_size, (GLOBAL_BATCH, SEQ)),
                dtype="int64")
            lbl = paddle.to_tensor(
                rs.randint(0, cfg.vocab_size, (GLOBAL_BATCH, SEQ)),
                dtype="int64")
            mesh_mod.set_mesh(topology_mesh("v5e:8x8", shape_map))
            step = gpt_1f1b_train_step(
                model, optim, batch_spec=P(("data", "sharding")))
            cost = aot_compile_step(step, (ids,), (lbl,), want_cost=True)
        except Exception as e:
            rows.append({"mesh": shape_map,
                         "error": f"{type(e).__name__}: {str(e)[:300]}"})
            print(f"  {label}: FAILED {type(e).__name__}: {str(e)[:120]} "
                  f"[{time.time()-t0:.0f}s]")
            continue
        finally:
            mesh_mod.set_mesh(None)
            # release ~13 GB of host arrays per candidate — including the
            # TrainStep closure, which holds model+optimizer alive
            model = optim = step = None

        row = {"mesh": shape_map, **cost,
               "wall_seconds": round(time.time() - t0, 1),
               "schedule": "1F1B", "pp_microbatches": mb}
        if row.get("peak_hbm_bytes") is not None:
            row["fits_v5e_16gb"] = row["peak_hbm_bytes"] <= HBM_BUDGET
        sec = estimate_step_seconds(cost)
        if sec:
            row["est_step_seconds"] = round(sec["seconds"], 6)
            row["est_signal"] = sec["signal"]
            row["est_tokens_per_sec_chip"] = round(
                GLOBAL_BATCH * SEQ / N_CHIPS / sec["seconds"], 1)
            if cost.get("flops"):
                # same headline metric as the GSPMD ranked list
                row["est_mfu"] = round(
                    cost["flops"] / sec["seconds"] / V5E_PEAK_BF16_FLOPS, 4)
        peak = row.get("peak_hbm_bytes")
        print(f"  {label}: peak "
              + (f"{peak/2**30:.2f} GiB" if peak else "?")
              + (f", est step {row['est_step_seconds']*1e3:.1f} ms "
                 f"({row['est_signal']}), est "
                 f"{row['est_tokens_per_sec_chip']:.0f} tok/s/chip"
                 if sec else "")
              + f" [{row['wall_seconds']:.0f}s]")
        rows.append(row)

    path = os.path.join(REPO, "artifacts", "mesh_plan_13b.json")
    try:
        out = json.load(open(path))
    except (FileNotFoundError, json.JSONDecodeError):
        out = {}
    # same ranking contract as the sibling GSPMD sweep: errors last,
    # over-budget plans demoted — ranked_pipe[0] must actually FIT
    out["ranked_pipe"] = sorted(
        rows, key=lambda r: (
            2 if r.get("error") else 0 if r.get("fits_v5e_16gb") else 1,
            r.get("est_step_seconds") or float("inf")))
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(f"updated {path} (ranked_pipe: {len(rows)} rows)")


if __name__ == "__main__":
    main()

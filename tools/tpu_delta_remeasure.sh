#!/bin/bash
# Round-5 delta re-measure: the widedeep bench was rewired to the
# compiled pass step and resnet50 to batch 256 AFTER the sprint ran, so
# when the tunnel next executes, re-measure exactly those two modes (plus
# a fresh gpt baseline as a sanity anchor) and merge into TPU_RESULTS.
cd /root/repo
MARKER=artifacts/TPU_STATUS.txt
LOG=artifacts/ROUND5_DELTA.log
probe_ok() { timeout 300 python tools/tpu_perf_sprint.py --probe-only 2>/dev/null; }
while true; do
  if probe_ok; then
    echo "DELTA-WINDOW-OPEN $(date -u +%FT%TZ)" >> "$MARKER"
    echo "=== delta re-measure $(date -u +%FT%TZ) ===" >> "$LOG"
    python - >> "$LOG" 2>&1 <<'EOF'
import json, os, subprocess, sys
sys.path.insert(0, "/root/repo/tools")
from tpu_perf_sprint import run_bench, _save
results = {}
jobs = [
    ("widedeep", {"BENCH_MODE": "widedeep"}, "widedeep-compiled-pass"),
    ("resnet50", {"BENCH_MODE": "resnet50"}, "resnet50-b256"),
    ("baseline", {}, "gpt-sanity"),
    ("gpt_b16_remat", {"BENCH_GPT_BATCH": "16", "BENCH_GPT_REMAT": "1"},
     "gpt b16+remat (6.4GiB by AOT)"),
]
for key, env, label in jobs:
    rec = run_bench(env, label, timeout=1500)
    if rec is not None:
        results[key] = rec
_save(results)
EOF
    echo "=== delta done $(date -u +%FT%TZ) ===" >> "$LOG"
    exit 0
  fi
  echo "DELTA-WAITING $(date -u +%FT%TZ)" >> "$MARKER"
  sleep 300
done

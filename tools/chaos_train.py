"""Chaos training torture harness: distributed faults under a seeded
schedule, with zero tolerance for silent divergence.

Two phases, mirroring tools/ckpt_torture.py's loop-and-assert style:

1. **Parity** — train a small MLP over a shuffled ResumableLoader, crash at
   the midpoint (checkpoint carries the full job_state: RNG streams, data
   position), resume in a "fresh process" with different entropy, and
   require the resumed loss trajectory to be BIT-IDENTICAL to an
   uninterrupted run (exact float equality, no tolerance).

2. **Chaos** — a 2-replica emulated-DP run under a seeded fault schedule:
   collective hangs (bounded by a ChaosGroup timeout, recovered by retry),
   transient collective failures (recovered by backoff retry), and
   parameter bit-flips (SDC — detected by ReplicaGuard's cross-replica
   digest agreement and recovered by rollback to the last valid
   checkpoint). Every injected bit-flip must be detected the same step;
   after every step the replicas must agree — any undetected disagreement
   counts as silent divergence and fails the run.

3. **Warm handoff** (ISSUE 19) — an eviction storm against a live
   2-replica serving set on the real jit-compiled model: hang-eviction,
   planned ``replace()``, and a resize, each replacement booting WARM
   (shape buckets pre-compiled before the outgoing replica drains).
   Zero lost requests, zero hang-evictions inside a boot window, and
   TTFT-after-eviction bounded by 1.5x the steady tail.

Exits nonzero on any violation and records a summary to
artifacts/chaos_train.json. The quick (<15 s) variant runs inside tier-1
(tests/test_distributed_ft.py::TestChaosTrainQuick).

    python tools/chaos_train.py --steps 40 --seed 0
"""
from __future__ import annotations

import argparse
import json
import os
import random
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _build_mlp(seed):
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    import paddle_tpu.optimizer as optim

    paddle.seed(seed)
    net = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 1))
    opt = optim.SGD(learning_rate=0.1, parameters=net.parameters())
    return net, opt


# ------------------------------------------------------------------ parity
def run_parity(root, steps, seed):
    """Uninterrupted vs crash→resume: losses must match exactly."""
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu.io import DataLoader
    from paddle_tpu.robustness import CheckpointManager, ResumableLoader
    from paddle_tpu.robustness import distributed_ft as ft

    rs = np.random.RandomState(seed)
    data = [(rs.standard_normal(8).astype(np.float32),
             rs.standard_normal(1).astype(np.float32))
            for _ in range(steps * 2)]
    crash_at = max(1, steps // 2)

    def step_fn(holder, batch):
        net, opt = holder
        x, y = batch
        loss = F.mse_loss(net(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return float(loss.numpy())

    def fresh():
        paddle.seed(1000 + seed)
        holder = _build_mlp(2000 + seed)
        loader = ResumableLoader(DataLoader(data, batch_size=2, shuffle=True))
        return holder, loader

    # reference: one uninterrupted epoch
    holder, loader = fresh()
    want = [step_fn(holder, b) for b in loader]

    # crash run: same start, die at crash_at with a job_state checkpoint
    mgr = CheckpointManager(os.path.join(root, "parity"))
    holder, loader = fresh()
    got, it = [], iter(loader)
    for _ in range(crash_at):
        got.append(step_fn(holder, next(it)))
    net, opt = holder
    mgr.save({"model": net.state_dict(), "opt": opt.state_dict()}, crash_at,
             job_state=ft.capture_job_state(data_iter=loader))
    del holder, loader, it, net, opt  # "the process dies here"

    # resumed "process": different entropy — the restore must win
    paddle.seed(31337)
    holder = _build_mlp(99)
    loader2 = ResumableLoader(DataLoader(data, batch_size=2, shuffle=True))
    state, step, js = ft.elastic_resume(mgr, data_iter=loader2)
    holder[0].set_state_dict(state["model"])
    holder[1].set_state_dict(state["opt"])
    got += [step_fn(holder, b) for b in loader2]

    return {"ok": got == want, "steps": len(want), "crash_at": crash_at,
            "resumed_from": int(step), "job_state_entries": sorted(js),
            "losses_reference": want, "losses_resumed": got}


# --------------------------------------------------------------- overlap
def run_overlap_parity(steps, seed):
    """Overlapped bucket-ready sync under mid-backward chaos vs the serial
    path: hang + transient faults injected on a mid-backward bucket's
    collective (recovered by the group timeout + retry machinery the lane
    inherits) must leave every step's loss EXACTLY equal to the serial
    run's — the flush() barrier and per-bucket retries may reorder wall
    time, never values."""
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu.distributed import grad_comm
    from paddle_tpu.distributed.overlap import OverlappedGradCommunicator
    from paddle_tpu.robustness.fault_injection import ChaosGroup

    rs = np.random.RandomState(seed)
    x = rs.standard_normal((16, 8)).astype(np.float32)
    y = rs.standard_normal((16, 1)).astype(np.float32)
    # tiny caps -> several buckets, so "mid-backward bucket" is meaningful
    mk_cfg = lambda overlap: grad_comm.GradCommConfig(
        "fp32", comm_buffer_size=0.0002, last_comm_buffer_size=0.0001,
        overlap=overlap)

    def train(comm, group, steps):
        paddle.seed(4000 + seed)
        net, opt = _build_mlp(5000 + seed)
        params = [p for p in net.parameters() if not p.stop_gradient]
        comm.group = group
        losses = []
        for _ in range(steps):
            if hasattr(comm, "prepare"):
                comm.prepare(params, world=2)
            loss = F.mse_loss(net(paddle.to_tensor(x)), paddle.to_tensor(y))
            loss.backward()
            comm.sync(params, world=2)
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.numpy()))
        return losses

    serial = train(grad_comm.GradCommunicator(mk_cfg(False)), None, steps)
    # fault plan: collective call 2 (a mid-backward bucket, 1-based) hangs
    # past the group timeout -> retried; call 5 fails transiently -> backoff
    # retried. Counters advance per invocation, so the retries land on
    # fault-free indices.
    g = ChaosGroup(plan={2: ("hang", 0.4), 5: ("fail", None)}, timeout=0.05)
    overlapped = train(OverlappedGradCommunicator(mk_cfg(True)), g, steps)
    chaos = g.chaos
    return {
        "ok": (serial == overlapped and chaos.hangs == 1
               and chaos.fails == 1),
        "steps": steps,
        "hangs_injected": chaos.hangs,
        "transients_injected": chaos.fails,
        "losses_serial": serial,
        "losses_overlapped": overlapped,
    }


# -------------------------------------------------------- flight recorder
def run_flightrec_postmortem(seed):
    """Mid-backward hang that EXHAUSTS its retries (ISSUE 6): every attempt
    of one bucket's collective hangs past the group timeout, so the lane
    surfaces CollectiveTimeoutError and the escalation path dumps the
    flight recorder. The dump's tail must name the exact bucket that
    stalled — its comm lane span — and carry the timeout event, or the
    postmortem is decoration, not diagnosis."""
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu.distributed import grad_comm
    from paddle_tpu.distributed.overlap import OverlappedGradCommunicator
    from paddle_tpu.framework.errors import CollectiveTimeoutError
    from paddle_tpu.observability import get_flight_recorder
    from paddle_tpu.robustness.fault_injection import ChaosGroup

    rs = np.random.RandomState(seed)
    x = rs.standard_normal((16, 8)).astype(np.float32)
    y = rs.standard_normal((16, 1)).astype(np.float32)
    paddle.seed(6000 + seed)
    net, _ = _build_mlp(7000 + seed)
    params = [p for p in net.parameters() if not p.stop_gradient]
    comm = OverlappedGradCommunicator(grad_comm.GradCommConfig(
        "fp32", comm_buffer_size=0.0002, last_comm_buffer_size=0.0001,
        overlap=True))
    # calls 2/3/4 = bucket 1's attempt + both retries (counters advance per
    # invocation), so the retry budget (DEFAULT_RETRIES=2) is exhausted
    comm.group = ChaosGroup(plan={2: ("hang", 0.4), 3: ("hang", 0.4),
                                  4: ("hang", 0.4)}, timeout=0.05)
    summary = {"timeout_raised": False, "dump_path": None,
               "hung_bucket": None, "tail_has_lane_span": False,
               "tail_has_timeout_event": False}
    comm.prepare(params, world=2)
    loss = F.mse_loss(net(paddle.to_tensor(x)), paddle.to_tensor(y))
    loss.backward()
    rec = get_flight_recorder()
    n_dumps_before = len(rec.dumps)
    try:
        comm.sync(params, world=2)
    except CollectiveTimeoutError:
        summary["timeout_raised"] = True
    if summary["timeout_raised"] and len(rec.dumps) == n_dumps_before:
        # the escalation path's auto dump is budget-capped per process
        # (_MAX_AUTO_DUMPS) and a long session's earlier hang escalations
        # may have spent it; the ring still holds the lane span, so take
        # the postmortem explicitly — the assertions below are about the
        # dump CONTENT, the auto path is exercised in a fresh process
        rec.dump("collective_timeout:budget_fallback", auto=False)
    if rec.dumps:
        summary["dump_path"] = rec.dumps[-1]["path"]
        with open(summary["dump_path"]) as f:
            dump = json.load(f)
        tail = dump["entries"][-40:]
        # the hung bucket = the last comm lane span that STARTED in the ring
        starts = [e for e in tail if e["kind"] == "lane"
                  and e["name"].startswith("comm:")
                  and e.get("phase") == "start"]
        if starts:
            summary["hung_bucket"] = starts[-1].get("bucket")
            summary["tail_has_lane_span"] = True
        summary["tail_has_timeout_event"] = any(
            e["kind"] == "event" and e.get("severity") == "error"
            and "timed out" in e.get("message", "") for e in tail)
    summary["ok"] = (summary["timeout_raised"]
                     and summary["dump_path"] is not None
                     and summary["tail_has_lane_span"]
                     and summary["tail_has_timeout_event"])
    return summary


# -------------------------------------------------- preemption + reshard
def run_preemption_shrink(root, steps, seed, world_from=4, world_to=3):
    """ISSUE 10 end-to-end: a ZeRO-3 (emulated world=4) job gets a REAL
    SIGTERM mid-run, commits an emergency sharded checkpoint at the next
    step boundary (inside the grace window), "dies", and resumes at
    world=3 through the elastic reshard transform — zero refused resumes,
    and the resumed fp32 loss trajectory EXACTLY equals the uninterrupted
    reshape-reference run's."""
    import os as _os
    import signal as _signal
    import time as _time

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F
    import paddle_tpu.optimizer as optim
    from paddle_tpu.distributed import grad_comm
    from paddle_tpu.distributed.sharding import (
        Stage3ParamShards, save_group_sharded_checkpoint,
    )
    from paddle_tpu.framework.errors import CheckpointGeometryError
    from paddle_tpu.io import DataLoader
    from paddle_tpu.optimizer.fused import FusedFlatUpdater
    from paddle_tpu.robustness import (
        CheckpointManager, PreemptionHandler, ResumableLoader,
    )
    from paddle_tpu.robustness import distributed_ft as ft

    steps = max(4, steps)
    kill_at = steps // 2
    rs = np.random.RandomState(seed + 7)
    data = [(rs.standard_normal((4, 8)).astype(np.float32),
             rs.standard_normal((4, 1)).astype(np.float32))
            for _ in range(steps)]
    ckpt_root = os.path.join(root, "preempt")

    def build(world):
        paddle.seed(8000 + seed)
        net = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 1))
        opt = optim.AdamW(learning_rate=1e-2, parameters=net.parameters())
        comm = grad_comm.GradCommunicator(grad_comm.GradCommConfig(
            "fp32", comm_buffer_size=0.0002, last_comm_buffer_size=0.0001))
        params = [p for p in net.parameters() if not p.stop_gradient]
        fused = FusedFlatUpdater(opt, params, communicator=comm)
        store = Stage3ParamShards(params, comm, rank=0, world=world)
        store.shard_()
        store.install_hooks(net)
        net._zero3 = store
        loader = ResumableLoader(DataLoader(data, batch_size=1,
                                            shuffle=True))
        return net, comm, fused, store, params, loader

    def one(net, comm, fused, store, params, batch, world):
        xb, yb = batch
        loss = F.mse_loss(net(paddle.to_tensor(xb)), paddle.to_tensor(yb))
        loss.backward()
        comm.sync(params, world=world, use_reduce_scatter=True)
        fused.step_sharded(rank=0, world=world, param_store=store)
        for p in params:
            p.clear_grad()
        return float(loss.numpy())

    summary = {"steps": steps, "kill_at": kill_at,
               "world_from": world_from, "world_to": world_to,
               "sigterm_latched": False, "emergency_save_ms": None,
               "grace_seconds": None, "refused_without_flag": False,
               "refused_resumes": 0, "resharded": False}

    # ---- reshape-reference: uninterrupted at world_from
    net, comm, fused, store, params, loader = build(world_from)
    want = [one(net, comm, fused, store, params, b, world_from)
            for b in loader]

    # ---- preempted run: REAL SIGTERM mid-step, emergency save at the
    # step boundary, then "the process dies"
    net, comm, fused, store, params, loader = build(world_from)
    handler = PreemptionHandler(grace_seconds=10.0).install()
    got = []
    it = iter(loader)
    try:
        for k in range(kill_at):
            if k == kill_at - 1:
                # the eviction notice arrives DURING the step's compute
                _os.kill(_os.getpid(), _signal.SIGTERM)
            got.append(one(net, comm, fused, store, params, next(it),
                           world_from))
        handler.wait(2.0)  # latch is set by the main-thread handler
        if not handler.should_stop():
            summary["ok"] = False
            summary["error"] = "SIGTERM never latched"
            return summary
        summary["sigterm_latched"] = True
        t0 = _time.perf_counter()
        save_group_sharded_checkpoint(
            net, ckpt_root, kill_at, rank=0, world_size=1, fused=fused,
            job_state=ft.capture_job_state(reducer=comm, data_iter=loader,
                                           zero3=store),
            metadata={"reason": "preemption"})
        summary["emergency_save_ms"] = round(
            (_time.perf_counter() - t0) * 1e3, 3)
        summary["grace_seconds"] = handler.grace_remaining()
        summary["exit_status"] = handler.exit_status()
    finally:
        handler.uninstall()
    del net, comm, fused, store, params, loader, it  # dies here

    # ---- resumed "process" at world_to: geometry drift must RESHARD,
    # never refuse
    paddle.seed(31337)  # different entropy — the restore must win
    net, comm, fused, store, params, loader = build(world_to)
    mgr = CheckpointManager(ckpt_root)
    try:  # the refusal is still typed + diagnosable without the flag
        mgr.load_sharded(rank=0, world_size=1, zero3_world=world_to)
    except CheckpointGeometryError:
        summary["refused_without_flag"] = True
    try:
        payload, step, _manifest = mgr.load_sharded(
            rank=0, world_size=1, zero3_world=world_to, allow_reshard=True)
    except CheckpointGeometryError:
        summary["refused_resumes"] += 1
        summary["ok"] = False
        return summary
    summary["resharded"] = True
    store.load_state_dict(payload["zero3"])
    fused.load_shard_slots_state(payload["fused_shard_slots"])
    ft.restore_job_state(payload["job_state"], reducer=comm,
                         data_iter=loader, zero3=store, allow_reshard=True)
    got += [one(net, comm, fused, store, params, b, world_to)
            for b in loader]

    summary["losses_reference"] = want
    summary["losses_resumed"] = got
    summary["ok"] = (got == want and summary["sigterm_latched"]
                     and summary["resharded"]
                     and summary["refused_without_flag"]
                     and summary["refused_resumes"] == 0
                     and summary["emergency_save_ms"] is not None
                     and summary["grace_seconds"] > 0)
    return summary


# ------------------------------------------------------- fleet controller
FLEET_TRACE_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "artifacts", "fleet_trace.json")


def record_fleet_trace(seed=17):
    """Generate the recorded preemption + Zipfian-arrival trace (ISSUE
    17). The checked-in artifacts/fleet_trace.json is exactly this dict:
    re-recording with the same seed is byte-stable, so the trace is both
    a fixture and reproducible evidence.

    Shape: a diurnal day at 1 virtual second per tick — night (train-
    heavy, sparse arrivals) then day (serve-heavy, 3 Zipfian arrivals per
    tick), with a straggler window, one preemption notice with a grace
    deadline, and one capacity-add event. Overheads are charged in ticks
    from the constants here, so both policy and baseline pay identical
    prices for identical actions."""
    rng = random.Random(seed)
    horizon, night_end = 48, 24
    # Zipf-weighted prompt pool: rank r picked with weight 1/(r+1)
    pool = [[(seed + 7 * i + 3 * j) % 16 for j in range(4 + i % 4)]
            for i in range(6)]
    weights = [1.0 / (r + 1) for r in range(len(pool))]
    total_w = sum(weights)

    def zipf_pick():
        x, acc = rng.random() * total_w, 0.0
        for r, w in enumerate(weights):
            acc += w
            if x <= acc:
                return r
        return len(weights) - 1

    arrivals = []
    for t in range(horizon):
        if t < night_end:
            arrivals.append([zipf_pick()] if rng.random() < 0.33 else [])
        else:
            arrivals.append([zipf_pick() for _ in range(3)])
    return {
        "version": 1, "seed": seed, "recorded_utc": "2026-08-07T00:00:00Z",
        "tick_s": 1.0, "horizon": horizon, "night_end": night_end,
        "total_chips": 8, "train_world0": 5, "serve_replicas0": 2,
        "tokens_per_chip_tick": 64,
        "serve_max_new": 6, "serve_max_batch": 4, "kv_blocks": 16,
        "block_tokens": 8, "queue_depth": 32, "ckpt_every": 16,
        # ticks one action costs; "serve_compile" is a new replica's warm-up
        "overhead_ticks": {"save": 1, "reshard": 1, "compile": 1,
                           "serve_compile": 2, "crash_restart": 3},
        "prompt_pool": pool,
        "arrivals": arrivals,
        "preemptions": [{"t": 20, "grace_ticks": 6}],
        "capacity_adds": [{"t": 30}],
        # operator-directed consolidation mid-backlog: retires a BUSY
        # replica, so the drain + re-admit path runs with live in-flight
        # requests — the zero-lost gate has to survive real churn, not an
        # idle scale_down with nothing to drain
        "consolidations": [{"t": 40}],
        "straggler": {"start": 6, "until": 22, "skew": 0.8},
    }


def _load_fleet_trace(path=None):
    path = path or FLEET_TRACE_PATH
    if os.path.exists(path):
        with open(path) as f:
            return json.load(f)
    return record_fleet_trace()


class _TinyDecodeModel:
    """Deterministic numpy decode model with the GPTDecodeModel duck
    surface the engine drives (prefill/decode/elems_per_token/
    max_context). Next token = (sum(prompt) + position) % vocab — pure
    function of the request, so the fleet phase's token streams replay
    bit-identically with no jit, no RNG, no wall-clock dependence."""

    vocab_size = 16
    max_context = 64
    elems_per_token = 4

    def __init__(self):
        self._params = [np.zeros((1,), np.float32)]

    def param_list(self):
        return self._params

    def _logits_for(self, base, pos):
        row = np.zeros((self.vocab_size,), np.float32)
        row[int(base + pos) % self.vocab_size] = 1.0
        return row

    def prefill(self, prompts):
        logits = np.stack([self._logits_for(int(np.sum(p)), len(p))
                           for p in prompts])
        kvs = [np.full((len(p), self.elems_per_token),
                       float(np.sum(p) % 7), np.float32) for p in prompts]
        return logits, kvs

    def decode(self, ids, pos, past, past_len):
        B = ids.shape[0]
        logits = np.zeros((B, self.vocab_size), np.float32)
        for i in range(B):
            logits[i] = self._logits_for(int(past[i, 0, 0] * 7 + ids[i]),
                                         int(pos[i]) + 1)
        kv = np.ones((B, self.elems_per_token), np.float32)
        return logits, kv


class _FleetTrainPlant:
    """The training side of the fleet: a REAL emulated-world ZeRO-3 job
    (Stage3ParamShards + FusedFlatUpdater + reduce-scatter grad sync,
    the run_preemption_shrink machinery) driven by the trace clock. Every
    resize is a real sharded save + PR-10 reshard load at the new world;
    the trace only decides WHEN they happen and how many ticks they
    cost."""

    def __init__(self, root, seed, trace, ledger, handler, manager):
        self.ckpt_root = os.path.join(root, "fleet_train")
        self.seed = seed
        self.trace = trace
        self.ledger = ledger
        self.handler = handler
        self.manager = manager
        self.tpc = int(trace["tokens_per_chip_tick"])
        self.overhead = trace["overhead_ticks"]
        self.world = int(trace["train_world0"])
        self.step_no = 0
        self.max_step = 0
        self.tokens = 0
        self.busy = []               # ledger accounts, one per pending tick
        self.straggler_active = False
        self.straggler_shed = False
        self.preempt_records = []
        self.resizes = []
        self.save_ms_total = 0.0
        rs = np.random.RandomState(seed + 11)
        self._data = [(rs.standard_normal((4, 8)).astype(np.float32),
                       rs.standard_normal((4, 1)).astype(np.float32))
                      for _ in range(64)]
        self._hosts = []
        for _ in range(self.world):
            self._register_host()
        self._build(self.world)

    # --------------------------------------------------------- membership
    def _register_host(self):
        host = f"host{len(self._hosts)}"
        self._hosts.append(host)
        self.manager.store.put(f"{self.manager.prefix}/{host}", host)

    def _deregister_host(self):
        if self._hosts:
            host = self._hosts.pop()
            self.manager.store.delete(f"{self.manager.prefix}/{host}")

    def spare_hosts(self):
        return max(0, len(self.manager.members()) - self.world)

    # ------------------------------------------------------------- signals
    def step_time_p99_ms(self):
        return 1800.0 if self.straggler_active else 900.0

    def step_time_skew(self):
        return float(self.trace["straggler"]["skew"]) \
            if self.straggler_active else 0.02

    def preempt_pending(self):
        return self.handler.requested     # polls the flag file

    def preempt_grace_s(self):
        return self.handler.grace_remaining()

    # ----------------------------------------------------------- real job
    def _build(self, world):
        import paddle_tpu as paddle
        import paddle_tpu.nn as nn
        import paddle_tpu.optimizer as optim
        from paddle_tpu.distributed import grad_comm
        from paddle_tpu.distributed.sharding import Stage3ParamShards
        from paddle_tpu.optimizer.fused import FusedFlatUpdater

        paddle.seed(8000 + self.seed)
        self.net = nn.Sequential(nn.Linear(8, 16), nn.Tanh(),
                                 nn.Linear(16, 1))
        opt = optim.AdamW(learning_rate=1e-2,
                          parameters=self.net.parameters())
        self.comm = grad_comm.GradCommunicator(grad_comm.GradCommConfig(
            "fp32", comm_buffer_size=0.0002, last_comm_buffer_size=0.0001))
        self.params = [p for p in self.net.parameters()
                       if not p.stop_gradient]
        self.fused = FusedFlatUpdater(opt, self.params,
                                      communicator=self.comm)
        self.store = Stage3ParamShards(self.params, self.comm, rank=0,
                                       world=world)
        self.store.shard_()
        self.store.install_hooks(self.net)
        self.net._zero3 = self.store

    def _one_step(self):
        import paddle_tpu as paddle
        import paddle_tpu.nn.functional as F

        xb, yb = self._data[self.step_no % len(self._data)]
        loss = F.mse_loss(self.net(paddle.to_tensor(xb)),
                          paddle.to_tensor(yb))
        loss.backward()
        self.comm.sync(self.params, world=self.world,
                       use_reduce_scatter=True)
        self.fused.step_sharded(rank=0, world=self.world,
                                param_store=self.store)
        for p in self.params:
            p.clear_grad()
        return float(loss.numpy())

    def _save(self, reason):
        from paddle_tpu.distributed.sharding import (
            save_group_sharded_checkpoint,
        )
        from paddle_tpu.robustness import distributed_ft as ft
        import time as _time

        t0 = _time.perf_counter()
        save_group_sharded_checkpoint(
            self.net, self.ckpt_root, self.step_no, rank=0, world_size=1,
            fused=self.fused,
            job_state=ft.capture_job_state(reducer=self.comm,
                                           zero3=self.store),
            metadata={"reason": reason})
        ms = (_time.perf_counter() - t0) * 1e3
        self.save_ms_total += ms
        return ms

    def _load(self, world):
        from paddle_tpu.robustness import CheckpointManager
        from paddle_tpu.robustness import distributed_ft as ft

        self._build(world)
        payload, step, _mf = CheckpointManager(self.ckpt_root).load_sharded(
            rank=0, world_size=1, zero3_world=world, allow_reshard=True)
        self.store.load_state_dict(payload["zero3"])
        self.fused.load_shard_slots_state(payload["fused_shard_slots"])
        ft.restore_job_state(payload["job_state"], reducer=self.comm,
                             zero3=self.store, allow_reshard=True)
        self.step_no = int(step)
        self.world = int(world)

    def _resize(self, to_world, reason, emergency=False):
        """Real save at the current world + real reshard-load at the new
        one; the trace charges save/reshard/compile ticks as busy time."""
        save_ms = self._save("preemption" if emergency else reason)
        self._load(to_world)
        self.busy.extend(["save"] * self.overhead["save"]
                         + ["reshard"] * self.overhead["reshard"]
                         + ["compile"] * self.overhead["compile"])
        self.resizes.append({"to_world": to_world, "reason": reason,
                             "save_ms": round(save_ms, 3)})
        return save_ms

    # ----------------------------------------------------------- actuators
    def preempt_shrink(self):
        assert self.handler.should_stop()   # drains: stamps the grace clock
        save_ms = self._resize(self.world - 1, "preempt", emergency=True)
        self._deregister_host()
        self.preempt_records.append({
            "save_ms": round(save_ms, 3),
            "wall_grace_remaining_s": round(
                self.handler.grace_remaining(), 3),
            "exit_status": self.handler.exit_status()})
        self.handler.reset()
        if self.handler.flag_file and os.path.exists(self.handler.flag_file):
            os.remove(self.handler.flag_file)

    def shed_straggler(self):
        self._resize(self.world - 1, "shed_straggler")
        self._deregister_host()
        self.straggler_active = False
        self.straggler_shed = True

    def grow(self):
        # grow is gated on OBSERVED membership: register the new host,
        # then require the ElasticManager to see a window-valid member
        # set before resharding up (the wait_for_np contract)
        self._register_host()
        if len(self.manager.members()) < self.world + 1 \
                or not self.manager.wait_for_np(timeout=0.5):
            self._deregister_host()
            return False
        self._resize(self.world + 1, "grow")
        return True

    def release_chip(self):
        self._resize(self.world - 1, "arbitrate_to_serve")
        self._deregister_host()

    def crash_restart(self):
        """The reactive baseline's preemption outcome: the chip dies at
        the grace deadline with NO emergency save — resume from the last
        periodic checkpoint at world−1, replaying the lost steps
        (charged as recompute, earning zero tokens)."""
        self._load(self.world - 1)
        self._deregister_host()
        self.busy.extend(["drain"] + ["reshard"] + ["compile"]
                         * max(1, self.overhead["crash_restart"] - 2))

    # ----------------------------------------------------------- trace tick
    def tick(self, clock):
        if self.busy:
            self.ledger.charge(self.busy.pop(0), self.world)
            return
        self._one_step()
        self.step_no += 1
        if self.step_no > self.max_step:
            self.max_step = self.step_no
            rate = 0.5 if self.straggler_active else 1.0
            self.ledger.tokens("train", int(self.tpc * self.world * rate))
            self.tokens += int(self.tpc * self.world * rate)
            self.ledger.charge("train_useful", self.world)
        else:
            self.ledger.charge("recompute", self.world)
        if self.trace["ckpt_every"] and clock > 0 \
                and clock % self.trace["ckpt_every"] == 0:
            self._save("periodic")


class _FleetServePlant:
    """The serving side: a REAL ReplicaSet (engines, paged KV pools,
    admission queue) over the deterministic tiny decode model, driven
    synchronously via ``ReplicaSet.pump`` mechanics so every tick is a
    pure function of the trace. Scale up/down goes through the PR-14
    drain + re-admit path — the zero-lost guarantee under policy churn
    is asserted, not assumed."""

    def __init__(self, trace, ledger, mode):
        from paddle_tpu.serving import ReplicaSet
        from paddle_tpu.serving.scheduler import RequestQueue

        self.trace = trace
        self.ledger = ledger
        self.mode = mode
        self.horizon = int(trace["horizon"])
        self.model = _TinyDecodeModel()
        self.queue = RequestQueue(max_depth=int(trace["queue_depth"]))
        self.rs = ReplicaSet(
            self.model, n_replicas=int(trace["serve_replicas0"]),
            queue=self.queue, n_blocks=int(trace["kv_blocks"]),
            block_tokens=int(trace["block_tokens"]), codec="fp32",
            max_batch=int(trace["serve_max_batch"]), prefix_cache=False)
        self.submit_tick = {}
        self.done_tick = {}
        self.submitted = 0
        self.accepted = 0
        self.rejected = 0
        self.completed_by_horizon = 0
        self.tokens_by_horizon = 0
        self.warmup = {}      # engine idx -> compile ticks left
        self.clock = 0

    # ------------------------------------------------------------- signals
    @property
    def replicas(self):
        return self.rs.alive_replicas

    @property
    def queue_depth(self):
        return self.queue.depth

    def latency_p99_ms(self):
        waiting = [t for rid, t in self.submit_tick.items()
                   if rid not in self.done_tick]
        if not waiting:
            return 0.0
        return 1000.0 * (self.clock - min(waiting))

    # ----------------------------------------------------------- actuators
    def scale_up(self):
        idx = self.rs.scale_up(reason="fleet_policy")
        self.warmup[idx] = int(self.trace["overhead_ticks"]["serve_compile"])
        return idx

    def scale_down(self):
        return self.rs.scale_down(reason="fleet_policy")

    # ----------------------------------------------------------- trace tick
    def arrive(self, tick, prompt_idxs):
        from paddle_tpu.serving.scheduler import ServeRequest

        for j, pi in enumerate(prompt_idxs):
            prompt = np.asarray(self.trace["prompt_pool"][pi], np.int32)
            req = ServeRequest(
                prompt_ids=prompt,
                max_new_tokens=int(self.trace["serve_max_new"]),
                eos_id=None, request_id=f"{self.mode}-t{tick}-{j}")
            self.submitted += 1
            if self.queue.submit(req):
                self.accepted += 1
                self.submit_tick[req.request_id] = tick
            else:
                self.rejected += 1

    def tick(self, clock):
        self.clock = clock
        for i, eng in enumerate(self.rs.engines):
            if not eng.alive:
                continue
            if self.warmup.get(i, 0) > 0:
                self.warmup[i] -= 1
                self.ledger.charge("compile", 1)
                continue
            worked = eng.step()
            self.ledger.charge("serve_useful" if worked else "idle", 1)
        self._collect(clock)

    def _collect(self, clock):
        for rid, req in list(self.rs.results.items()):
            if rid in self.done_tick:
                continue
            self.done_tick[rid] = clock
            if clock < self.horizon and req.outcome == "completed":
                self.completed_by_horizon += 1
                self.tokens_by_horizon += len(req.generated)

    def wind_down(self, max_pumps=500):
        """Post-horizon: finish every accepted request (completions out
        here count for the zero-lost invariant, not for goodput)."""
        for _ in range(max_pumps):
            alive = [e for e in self.rs.engines if e.alive]
            if not alive:
                break
            self.warmup = {}
            if self.queue.depth == 0 and all(not e.running for e in alive):
                break
            for e in alive:
                e.step()
        self._collect(self.horizon + max_pumps)

    def lost_requests(self):
        done = sum(1 for rid in self.submit_tick
                   if rid in self.rs.results
                   and self.rs.results[rid].outcome == "completed")
        return self.accepted - done


def _run_fleet_mode(trace, mode, root, seed, signals="probe"):
    """One full trace run ("policy", "reactive", or "adapter"); returns
    the per-mode summary with its goodput ledger. ``signals="adapter"``
    (ISSUE 18) feeds the controller through a SignalsAdapter over the
    LIVE engine metrics — queue-depth gauge + windowed latency/TTFT
    histogram quantiles + SLO burn — instead of the plant probes; the
    policy and actuation paths are byte-identical."""
    from paddle_tpu.distributed.fleet.elastic import (
        ElasticManager, FleetController, GoodputLedger, LocalKVStore,
        ReactivePolicy, ScalePolicy, SignalsAdapter,
    )
    from paddle_tpu.robustness import PreemptionHandler

    horizon = int(trace["horizon"])
    ledger = GoodputLedger()
    flag_path = os.path.join(root, f"preempt_flag_{mode}")
    if os.path.exists(flag_path):
        os.remove(flag_path)
    handler = PreemptionHandler(flag_file=flag_path, grace_seconds=30.0)
    manager = ElasticManager("host0", "1:16", store=LocalKVStore(),
                             job_id=f"fleet-{mode}")
    train = _FleetTrainPlant(os.path.join(root, mode), seed, trace, ledger,
                             handler, manager)
    serve = _FleetServePlant(trace, ledger, mode)
    if mode != "reactive":
        # serve_p99_high must sit ABOVE the normal end-to-end service
        # time (~7 ticks = 7000 virtual ms for a max_new=6 request at one
        # token per tick), or a healthily-serving request reads as
        # overload and the policy thrashes chips between train and serve,
        # paying the resize bill both ways
        policy = ScalePolicy(
            min_train_world=1, max_train_world=None,
            min_serve_replicas=1, max_serve_replicas=4,
            queue_high=6, queue_low=0, serve_p99_high_ms=10000.0,
            skew_high=0.5, cooldown_s=3.0)
    else:
        policy = ReactivePolicy()
    adapter = None
    serve_signals = serve
    if signals == "adapter":
        # windows tick on the virtual trace clock (1.0 tick_s each); the
        # SLO budgets are wall-ms and stay advisory here — with real
        # engine latencies in single-digit wall ms, the queue-depth gauge
        # is the overload signal that carries the decision
        adapter = SignalsAdapter(serve, replica_set=serve.rs,
                                 window_s=10.0, fast_window_s=5.0,
                                 slow_window_s=15.0)
        serve_signals = adapter
    ctrl = FleetController(policy, train, serve_signals,
                           total_chips=int(trace["total_chips"]),
                           ledger=ledger)

    pending = []          # unanswered preemption notices
    doomed = 0            # notice answered, chip winding down to deadline
    expected_chip_seconds = 0.0
    strag = trace["straggler"]

    for t in range(horizon):
        # 1. trace events land
        for ev in trace["preemptions"]:
            if ev["t"] == t:
                with open(flag_path, "w") as f:
                    f.write("preempt\n")
                pending.append({"t": t,
                                "deadline": t + int(ev["grace_ticks"]),
                                "answered": False})
        for ev in trace["capacity_adds"]:
            if ev["t"] == t:
                ctrl.total_chips += 1
        for ev in trace.get("consolidations", ()):
            if ev["t"] == t:
                # same event in BOTH modes: a busy replica is retired,
                # its in-flight requests drain + re-admit at the head
                serve.rs.scale_down(reason="trace_consolidation")
        if not train.straggler_shed:
            train.straggler_active = strag["start"] <= t < strag["until"]
        if train.straggler_shed and t >= strag["until"] \
                and ctrl.quarantined > doomed:
            # the shed host recovered: back to the free pool
            ctrl.quarantined -= 1
            train.straggler_shed = False
            train._register_host()
        # 2. arrivals
        serve.arrive(t, trace["arrivals"][t])
        # 3. signal -> decision -> actuation
        serve.clock = t
        d = ctrl.tick(t)
        if d.action == "preempt_shrink":
            for p in pending:
                if not p["answered"]:
                    p["answered"] = True
                    doomed += 1
                    ctrl.quarantined += 1
                    done_t = t + trace["overhead_ticks"]["save"]
                    train.preempt_records[-1].update({
                        "notice_t": p["t"], "deadline_t": p["deadline"],
                        "save_done_t": done_t,
                        "in_grace": done_t <= p["deadline"]})
                    break
        # 4. grace deadlines
        for p in pending:
            if p["deadline"] == t:
                ctrl.total_chips -= 1
                if p["answered"]:
                    doomed -= 1
                    ctrl.quarantined -= 1
                else:
                    # reactive: the chip dies mid-step, no emergency save
                    if os.path.exists(flag_path):
                        os.remove(flag_path)
                    handler.reset()
                    train.crash_restart()
        # 5. plants burn the tick
        train.tick(t)
        serve.tick(t)
        # 6. unattributed chips: doomed wind-down is drain, rest idle
        if doomed:
            ledger.charge("drain", doomed)
        if ctrl.quarantined - doomed > 0:
            ledger.charge("idle", ctrl.quarantined - doomed)
        if ctrl.free_chips > 0:
            ledger.charge("idle", ctrl.free_chips)
        expected_chip_seconds += ctrl.total_chips * float(trace["tick_s"])

    serve.wind_down()
    ledger.serve_submitted = serve.submitted
    ledger.serve_completed = serve.completed_by_horizon
    ledger.tokens("serve", serve.tokens_by_horizon)

    unanswered = [p for p in pending if not p["answered"]]
    return {
        "mode": mode,
        "signals": signals,
        "signals_snapshot": (adapter.snapshot() if adapter is not None
                             else None),
        "goodput": round(ledger.goodput(horizon * trace["tick_s"]), 4),
        "ledger": ledger.summary(),
        "conservation_ok": ledger.verify_conservation(
            expected_chip_seconds, tol=1e-6),
        "expected_chip_seconds": expected_chip_seconds,
        "decisions": ctrl.decision_log(),
        "decision_replay_ok": ctrl.replay(),
        "final_train_world": train.world,
        "final_serve_replicas": serve.replicas,
        "train_resizes": train.resizes,
        "preempt_records": train.preempt_records,
        "preempt_unanswered": len(unanswered),
        "serve": {
            "submitted": serve.submitted, "accepted": serve.accepted,
            "rejected": serve.rejected,
            "completed_by_horizon": serve.completed_by_horizon,
            "lost_requests": serve.lost_requests(),
            "scale_events": list(serve.rs.scale_events),
            "evictions": list(serve.rs.evictions),
        },
    }


def run_fleet(root, seed, trace_path=None):
    """ISSUE 17 tentpole phase: the same recorded trace under the elastic
    controller and under the reactive baseline; the verdict couples the
    goodput ratio, the zero-lost invariant across every scale event, and
    every preemption notice being answered by a completed emergency save
    inside its grace deadline."""
    trace = _load_fleet_trace(trace_path)
    policy = _run_fleet_mode(trace, "policy", root, seed)
    reactive = _run_fleet_mode(trace, "reactive", root, seed)
    # ISSUE 18: the same policy run again, but with every decision input
    # derived from live telemetry (SignalsAdapter) instead of plant
    # probes. Validated against the probe-driven run: identical decision
    # sequence, or goodput within 0.9x (the probe's virtual-clock p99 has
    # no wall-clock analog, so a divergent-but-equally-good decision
    # sequence is an accepted outcome).
    adapter = _run_fleet_mode(trace, "adapter", root, seed,
                              signals="adapter")
    ratio = (policy["goodput"] / reactive["goodput"]
             if reactive["goodput"] else float("inf"))
    recs = policy["preempt_records"]
    saves_in_grace = bool(recs) and all(
        r.get("in_grace") and r["wall_grace_remaining_s"] > 0 for r in recs)
    lost = (policy["serve"]["lost_requests"]
            + reactive["serve"]["lost_requests"]
            + adapter["serve"]["lost_requests"])
    drained_total = sum(
        ev["drained"] for m in (policy, reactive)
        for ev in m["serve"]["scale_events"])
    decisions_match = ([d["action"] for d in adapter["decisions"]]
                       == [d["action"] for d in policy["decisions"]])
    adapter_vs_probe = (adapter["goodput"] / policy["goodput"]
                        if policy["goodput"] else float("inf"))
    summary = {
        "trace": {k: trace[k] for k in
                  ("seed", "horizon", "total_chips", "train_world0",
                   "serve_replicas0", "night_end")},
        "fleet_goodput_ratio": round(ratio, 4),
        "goodput_policy": policy["goodput"],
        "goodput_reactive": reactive["goodput"],
        "scale_event_lost_requests": lost,
        "scale_events_drained_requests": drained_total,
        "preempt_saves_in_grace": saves_in_grace,
        "preempt_unanswered_policy": policy["preempt_unanswered"],
        "signals_adapter": {
            "goodput": adapter["goodput"],
            "goodput_vs_probe": round(adapter_vs_probe, 4),
            "decisions_match_probe": decisions_match,
            "decisions": adapter["decisions"],
            "lost_requests": adapter["serve"]["lost_requests"],
            "preempt_unanswered": adapter["preempt_unanswered"],
            "decision_replay_ok": adapter["decision_replay_ok"],
            "snapshot": adapter["signals_snapshot"],
            "ok": ((decisions_match or adapter_vs_probe >= 0.9)
                   and adapter["serve"]["lost_requests"] == 0
                   and adapter["preempt_unanswered"] == 0
                   and adapter["decision_replay_ok"]),
        },
        "policy": policy,
        "reactive": reactive,
        "adapter": adapter,
    }
    summary["ok"] = (
        ratio >= 1.2
        and lost == 0
        and drained_total >= 1   # a scale event really drained live work
        and saves_in_grace
        and policy["preempt_unanswered"] == 0
        and reactive["preempt_unanswered"] >= 1   # baseline really crashed
        and policy["conservation_ok"] and reactive["conservation_ok"]
        and policy["decision_replay_ok"]
        and len(policy["decisions"]) >= 4
        and summary["signals_adapter"]["ok"])
    return summary


# ------------------------------------------------------------------- chaos
FAULTS = ("none", "bitflip", "hang", "transient")


def run_chaos(root, steps, seed, ckpt_every=4):
    """2-replica DP under a seeded fault schedule; every fault must be
    detected and recovered, with zero silent divergence."""
    import jax.numpy as jnp  # noqa: F401 (backend warm before timing)
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu.framework.tensor import Tensor
    from paddle_tpu.robustness import CheckpointManager, ReplicaGuard
    from paddle_tpu.robustness import distributed_ft as ft
    from paddle_tpu.robustness.fault_injection import ChaosGroup, flip_bit
    import paddle_tpu.distributed.collective as coll

    rng = random.Random(seed)
    rs = np.random.RandomState(seed + 1)
    replicas = [_build_mlp(3000 + seed) for _ in range(2)]
    nets = [r[0] for r in replicas]
    opts = [r[1] for r in replicas]
    mgr = CheckpointManager(os.path.join(root, "chaos"), keep_last_n=3)

    def save_ckpt(step):
        mgr.save({"models": [n.state_dict() for n in nets],
                  "opts": [o.state_dict() for o in opts]}, step,
                 job_state=ft.capture_job_state())

    class RollbackTarget:
        """Restore ALL replicas (every rank rolls back in a real job)."""

        def rollback(self):
            found = mgr.load_latest()
            if found is None:
                return False
            st = found[0]
            for n, s in zip(nets, st["models"]):
                n.set_state_dict(s)
            for o, s in zip(opts, st["opts"]):
                o.set_state_dict(s)
            return True

    def cross_replica_reduce(digest):
        d2 = ft.params_digest(nets[1].parameters())
        both = np.stack([digest, d2])
        return both.min(axis=0), both.max(axis=0)

    guard = ReplicaGuard(policy="rollback", checkpoint=RollbackTarget(),
                         reduce_fn=cross_replica_reduce)

    summary = {"steps": steps, "seed": seed,
               "fault_counts": {f: 0 for f in FAULTS},
               "bitflips_injected": 0, "bitflips_detected": 0,
               "hangs_injected": 0, "hangs_recovered": 0,
               "transients_injected": 0, "transients_recovered": 0,
               "rollbacks": 0, "silent_divergence_steps": 0,
               "checkpoints": 0, "failures": []}

    # seeded schedule with every class guaranteed present
    schedule = {1: "bitflip", 2: "hang", 3: "transient"}
    for step in range(4, steps + 1):
        schedule[step] = rng.choice(FAULTS)

    save_ckpt(0)
    summary["checkpoints"] += 1

    for step in range(1, steps + 1):
        fault = schedule.get(step, "none")
        summary["fault_counts"][fault] += 1

        # ---- collective-path faults: a real eager all_reduce of the loss
        # scalar through a ChaosGroup carrying the fault plan
        if fault == "hang":
            summary["hangs_injected"] += 1
            g = ChaosGroup(plan={1: ("hang", 0.5)}, timeout=0.05)
            try:
                coll.all_reduce(Tensor(np.float32(1.0)), group=g)
                summary["hangs_recovered"] += 1
            except Exception as e:  # noqa: BLE001 - recorded, run fails
                summary["failures"].append(
                    {"step": step, "fault": fault, "error": repr(e)})
        elif fault == "transient":
            summary["transients_injected"] += 1
            g = ChaosGroup(plan={1: ("fail", None)})
            try:
                coll.all_reduce(Tensor(np.float32(1.0)), group=g)
                summary["transients_recovered"] += 1
            except Exception as e:  # noqa: BLE001
                summary["failures"].append(
                    {"step": step, "fault": fault, "error": repr(e)})

        # ---- SDC: flip one bit of one replica's parameters mid-step.
        # Mantissa bits only (low two bytes of a float32 word): an
        # exponent/sign flip can NaN the loss, and NaN grads poison BOTH
        # replicas identically through the averaged gradients — the
        # corruption would "heal" into agreement (and the NanGuard, not the
        # ReplicaGuard, owns that failure class). A mantissa flip is the
        # convergence-poisoning SDC this guard exists for.
        if fault == "bitflip":
            summary["bitflips_injected"] += 1
            victim = nets[rng.randrange(2)]
            vparams = list(victim.parameters())
            flip_bit(vparams[rng.randrange(len(vparams))],
                     bit_index=rng.randrange(16) * 32 + rng.randrange(16))

        # ---- the step-boundary integrity check: corruption from the
        # previous step's compute must be caught BEFORE the next update
        # can propagate (or round away) the damage
        try:
            action = guard.check(list(nets[0].parameters()), step=step)
        except Exception as e:  # noqa: BLE001
            summary["failures"].append(
                {"step": step, "fault": fault, "error": repr(e)})
            action = "error"
        if action == "rollback":
            summary["rollbacks"] += 1
            if fault == "bitflip":
                summary["bitflips_detected"] += 1
            else:
                summary["failures"].append(
                    {"step": step, "fault": fault,
                     "error": "rollback without an injected flip"})
        elif fault == "bitflip":
            summary["failures"].append(
                {"step": step, "fault": fault,
                 "error": "injected bit-flip not detected"})

        # ---- one emulated-DP train step: same batch, averaged grads
        x = Tensor(rs.standard_normal((4, 8)).astype(np.float32))
        y = Tensor(rs.standard_normal((4, 1)).astype(np.float32))
        for net in nets:
            F.mse_loss(net(x), y).backward()
        p0, p1 = (list(n.parameters()) for n in nets)
        for a, b in zip(p0, p1):
            if a.grad is None:
                continue
            avg = (np.asarray(a.grad.numpy()) + np.asarray(b.grad.numpy())) / 2
            a.grad.set_value(avg)
            b.grad.set_value(avg)
        for opt in opts:
            opt.step()
            opt.clear_grad()

        # ---- invariant: after detection/recovery the replicas agree
        d0 = ft.params_digest(nets[0].parameters())
        d1 = ft.params_digest(nets[1].parameters())
        if not np.array_equal(d0, d1):
            summary["silent_divergence_steps"] += 1
            summary["failures"].append(
                {"step": step, "fault": fault,
                 "error": "replicas disagree after recovery"})

        # ---- periodic checkpoint, only from an agreed state
        if step % ckpt_every == 0 and np.array_equal(d0, d1):
            save_ckpt(step)
            summary["checkpoints"] += 1

    summary["final_replicas_identical"] = bool(np.array_equal(
        ft.params_digest(nets[0].parameters()),
        ft.params_digest(nets[1].parameters())))
    summary["ok"] = (not summary["failures"]
                     and summary["silent_divergence_steps"] == 0
                     and summary["bitflips_detected"]
                     == summary["bitflips_injected"]
                     and summary["hangs_recovered"]
                     == summary["hangs_injected"]
                     and summary["transients_recovered"]
                     == summary["transients_injected"]
                     and summary["final_replicas_identical"])
    return summary


def run_warm_handoff(seed=0):
    """ISSUE 19 warm-handoff eviction storm: a threaded 2-replica set on
    the real (jit-compiled) gpt-test decode model, hit with three
    replacement events under live traffic —

      1. a watchdog hang-eviction followed by an elastic
         ``scale_up(warm=True)`` replacement,
      2. a planned ``replace()`` (standby warmed BEFORE the outgoing
         replica drains),
      3. a ``scale_down()`` + ``scale_up(warm=True)`` resize.

    Invariants (each one was a real failure mode of the cold path):
      * zero lost requests across all events,
      * every replacement boot is mode=warm outcome=ok — no replacement
        ever pays an in-traffic compile,
      * no ``reason=hang`` eviction lands inside any boot window
        ``[t_start, t]`` (a cold compile used to trip the OTHER
        replica's watchdog; warm boots are too short to overlap one),
      * p99 TTFT from re-admission to first token for re-dispatched
        requests <= 1.5x the steady-state p99.
    """
    import threading
    import time

    from paddle_tpu.models import GPTForCausalLM, gpt_presets
    from paddle_tpu.serving import GPTDecodeModel, ReplicaSet
    from paddle_tpu.serving.scheduler import ServeRequest

    dm = GPTDecodeModel(GPTForCausalLM(gpt_presets("gpt-test"), seed=0))
    rng = np.random.RandomState(seed)

    def _requests(n, tag):
        reqs = []
        for j in range(n):
            plen = int(rng.randint(6, 14))
            reqs.append(ServeRequest(
                prompt_ids=rng.randint(0, dm.vocab_size,
                                       plen).astype(np.int32),
                max_new_tokens=int(rng.randint(10, 18)),
                eos_id=None, request_id=f"wh-{tag}-{j}"))
        return reqs

    # the hang is armed only for event 1; `released` lets the stuck
    # worker thread exit after the watchdog has evicted it
    armed = threading.Event()
    released = threading.Event()

    def hang_hook(eng):
        if (armed.is_set() and not released.is_set()
                and eng.running and eng.steps > 2):
            released.wait(60)

    summary = {"events": [], "accepted": 0, "completed": 0, "lost": -1,
               "replacement_boots": [], "hang_evictions_in_boot_window": -1,
               "steady_ttft_p99_ms": 0.0, "ttft_after_eviction_ms": 0.0,
               "redispatched": 0, "ok": False}
    rset = ReplicaSet(dm, n_replicas=2, n_blocks=96, block_tokens=16,
                      max_batch=4, watchdog_timeout=5.0,
                      pre_step_hooks={0: hang_hook})
    all_reqs = []
    with rset:
        # steady traffic: establishes the shape-bucket ledger the warm
        # boots replay, and the steady TTFT tail the bound compares to
        steady = _requests(10, "steady")
        all_reqs += steady
        for r in steady:
            assert rset.submit(r)
        res = rset.wait([r.request_id for r in steady], timeout=600)
        ttfts = sorted((r.t_first_token - r.t_enqueue) * 1e3
                       for r in res.values() if r.t_first_token)
        summary["steady_ttft_p99_ms"] = round(
            ttfts[min(len(ttfts) - 1, int(0.99 * len(ttfts)))], 2)

        # -- event 1: hang-evict under load, elastic warm replacement
        batch = _requests(8, "hang")
        all_reqs += batch
        for r in batch:
            assert rset.submit(r)
        armed.set()
        deadline = time.monotonic() + 60
        while not rset.evictions and time.monotonic() < deadline:
            time.sleep(0.05)
        rset.scale_up(warm=True, reason="hang_replacement")
        released.set()
        summary["events"].append({"kind": "hang_evict+warm_scale_up",
                                  "boot": dict(rset.last_boot or {})})

        # -- event 2: planned warm handoff of a live replica under load
        batch = _requests(8, "handoff")
        all_reqs += batch
        for r in batch:
            assert rset.submit(r)
        rset.replace()
        summary["events"].append({"kind": "replace",
                                  "boot": dict(rset.last_boot or {})})

        # -- event 3: resize down then warm back up under load
        batch = _requests(8, "resize")
        all_reqs += batch
        for r in batch:
            assert rset.submit(r)
        rset.scale_down(reason="resize")
        rset.scale_up(warm=True, reason="resize")
        summary["events"].append({"kind": "scale_down+warm_scale_up",
                                  "boot": dict(rset.last_boot or {})})

        res = rset.wait([r.request_id for r in all_reqs], timeout=600)
        redis = sorted((r.t_first_token - r.t_enqueue) * 1e3
                       for r in res.values()
                       if r.t_first_token and r.attempts > 0)

    summary["accepted"] = len(all_reqs)
    summary["completed"] = sum(
        1 for r in res.values() if r.outcome == "completed")
    summary["lost"] = summary["accepted"] - summary["completed"]
    summary["redispatched"] = len(redis)
    if redis:
        summary["ttft_after_eviction_ms"] = round(
            redis[min(len(redis) - 1, int(0.99 * len(redis)))], 2)
    summary["replacement_boots"] = [
        {k: b[k] for k in ("replica", "mode", "outcome", "ms")}
        for b in rset.boots]
    summary["hang_evictions_in_boot_window"] = sum(
        1 for e in rset.evictions if e["reason"] == "hang"
        and any(b["t_start"] <= e["t"] <= b["t"] for b in rset.boots))
    summary["evictions"] = [
        {"replica": e["replica"], "reason": e["reason"]}
        for e in rset.evictions]
    warm_ok = (len(rset.boots) >= 3
               and all(b["mode"] == "warm" and b["outcome"] == "ok"
                       for b in rset.boots))
    ttft_ok = (not redis
               or summary["ttft_after_eviction_ms"]
               <= 1.5 * max(summary["steady_ttft_p99_ms"], 1e-9))
    summary["ok"] = (summary["lost"] == 0 and warm_ok
                     and summary["hang_evictions_in_boot_window"] == 0
                     and len(summary["events"]) >= 3 and ttft_ok)
    return summary


def run_chaos_train(steps=40, seed=0, root=None):
    """Both phases; summary["ok"] is the overall verdict."""
    import logging

    # injected faults are the point — per-retry warnings would drown the run
    logging.getLogger("paddle_tpu").setLevel(logging.ERROR)
    root = root or tempfile.mkdtemp(prefix="chaos_train_")
    parity = run_parity(root, steps=max(4, steps // 2), seed=seed)
    overlap = run_overlap_parity(steps=max(4, steps // 8), seed=seed)
    flightrec = run_flightrec_postmortem(seed=seed)
    preempt = run_preemption_shrink(root, steps=max(4, steps // 4),
                                    seed=seed)
    chaos = run_chaos(root, steps=steps, seed=seed)
    fleet = run_fleet(root, seed=seed)
    warm = run_warm_handoff(seed=seed)
    return {"ok": (parity["ok"] and overlap["ok"] and flightrec["ok"]
                   and preempt["ok"] and chaos["ok"] and fleet["ok"]
                   and warm["ok"]),
            "root": root, "seed": seed,
            "parity": parity, "overlap": overlap, "flightrec": flightrec,
            "preempt": preempt, "chaos": chaos, "fleet": fleet,
            "warm_handoff": warm}


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--steps", type=int, default=40)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--root", default=None)
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "artifacts", "chaos_train.json"))
    ap.add_argument("--record-trace", action="store_true",
                    help="re-record artifacts/fleet_trace.json (seeded, "
                         "byte-stable) and exit")
    args = ap.parse_args(argv)

    if args.record_trace:
        os.makedirs(os.path.dirname(FLEET_TRACE_PATH), exist_ok=True)
        with open(FLEET_TRACE_PATH, "w") as f:
            json.dump(record_fleet_trace(), f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"fleet trace -> {FLEET_TRACE_PATH}")
        return 0

    summary = run_chaos_train(steps=args.steps, seed=args.seed,
                              root=args.root)
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(summary, f, indent=1)
    chaos = summary["chaos"]
    print(f"parity: ok={summary['parity']['ok']} "
          f"(crash at step {summary['parity']['crash_at']}, "
          f"{summary['parity']['steps']} steps, exact loss match)")
    ov = summary["overlap"]
    print(f"overlap: ok={ov['ok']} — {ov['steps']} overlapped-sync steps "
          f"under chaos ({ov['hangs_injected']} hang, "
          f"{ov['transients_injected']} transient on mid-backward "
          f"buckets), exact loss match vs serial")
    fr = summary["flightrec"]
    print(f"flightrec: ok={fr['ok']} — retry-exhausted mid-backward hang "
          f"dumped bucket {fr['hung_bucket']}'s lane span + the timeout "
          f"event to {fr['dump_path']}")
    pr = summary["preempt"]
    print(f"preempt: ok={pr['ok']} — SIGTERM at step {pr['kill_at']} of a "
          f"world={pr['world_from']} ZeRO-3 job, emergency sharded "
          f"checkpoint in {pr['emergency_save_ms']}ms, resumed at "
          f"world={pr['world_to']} via reshard "
          f"({pr['refused_resumes']} refused), exact loss parity")
    print(f"chaos:  ok={chaos['ok']} — "
          f"{chaos['bitflips_detected']}/{chaos['bitflips_injected']} "
          f"bit-flips detected, "
          f"{chaos['hangs_recovered']}/{chaos['hangs_injected']} hangs "
          f"recovered, "
          f"{chaos['transients_recovered']}/{chaos['transients_injected']} "
          f"transients absorbed, "
          f"{chaos['silent_divergence_steps']} silent-divergence steps, "
          f"{chaos['rollbacks']} rollbacks, "
          f"{chaos['checkpoints']} checkpoints")
    fl = summary["fleet"]
    print(f"fleet:  ok={fl['ok']} — goodput ratio "
          f"{fl['fleet_goodput_ratio']}x vs reactive "
          f"(policy {fl['goodput_policy']} vs {fl['goodput_reactive']} "
          f"tok/s), {fl['scale_event_lost_requests']} requests lost "
          f"across {len(fl['policy']['serve']['scale_events'])} scale "
          f"events ({fl['scale_events_drained_requests']} drained+"
          f"re-admitted), emergency saves in grace="
          f"{fl['preempt_saves_in_grace']}")
    sa = fl["signals_adapter"]
    print(f"signals: ok={sa['ok']} — adapter-driven run: decisions match "
          f"probe={sa['decisions_match_probe']}, goodput vs probe "
          f"{sa['goodput_vs_probe']}x, {sa['lost_requests']} lost")
    wh = summary["warm_handoff"]
    print(f"warm:   ok={wh['ok']} — {len(wh['events'])} replacement "
          f"events, {wh['lost']} lost of {wh['accepted']}, "
          f"{len(wh['replacement_boots'])} warm boots "
          f"({wh['hang_evictions_in_boot_window']} hang evictions inside "
          f"a boot window), ttft after eviction "
          f"{wh['ttft_after_eviction_ms']}ms vs steady p99 "
          f"{wh['steady_ttft_p99_ms']}ms")
    print(f"summary -> {args.out}")
    return 0 if summary["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())

"""Chaos training torture harness: distributed faults under a seeded
schedule, with zero tolerance for silent divergence.

Two phases, mirroring tools/ckpt_torture.py's loop-and-assert style:

1. **Parity** — train a small MLP over a shuffled ResumableLoader, crash at
   the midpoint (checkpoint carries the full job_state: RNG streams, data
   position), resume in a "fresh process" with different entropy, and
   require the resumed loss trajectory to be BIT-IDENTICAL to an
   uninterrupted run (exact float equality, no tolerance).

2. **Chaos** — a 2-replica emulated-DP run under a seeded fault schedule:
   collective hangs (bounded by a ChaosGroup timeout, recovered by retry),
   transient collective failures (recovered by backoff retry), and
   parameter bit-flips (SDC — detected by ReplicaGuard's cross-replica
   digest agreement and recovered by rollback to the last valid
   checkpoint). Every injected bit-flip must be detected the same step;
   after every step the replicas must agree — any undetected disagreement
   counts as silent divergence and fails the run.

Exits nonzero on any violation and records a summary to
artifacts/chaos_train.json. The quick (<15 s) variant runs inside tier-1
(tests/test_distributed_ft.py::TestChaosTrainQuick).

    python tools/chaos_train.py --steps 40 --seed 0
"""
from __future__ import annotations

import argparse
import json
import os
import random
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _build_mlp(seed):
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    import paddle_tpu.optimizer as optim

    paddle.seed(seed)
    net = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 1))
    opt = optim.SGD(learning_rate=0.1, parameters=net.parameters())
    return net, opt


# ------------------------------------------------------------------ parity
def run_parity(root, steps, seed):
    """Uninterrupted vs crash→resume: losses must match exactly."""
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu.io import DataLoader
    from paddle_tpu.robustness import CheckpointManager, ResumableLoader
    from paddle_tpu.robustness import distributed_ft as ft

    rs = np.random.RandomState(seed)
    data = [(rs.standard_normal(8).astype(np.float32),
             rs.standard_normal(1).astype(np.float32))
            for _ in range(steps * 2)]
    crash_at = max(1, steps // 2)

    def step_fn(holder, batch):
        net, opt = holder
        x, y = batch
        loss = F.mse_loss(net(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return float(loss.numpy())

    def fresh():
        paddle.seed(1000 + seed)
        holder = _build_mlp(2000 + seed)
        loader = ResumableLoader(DataLoader(data, batch_size=2, shuffle=True))
        return holder, loader

    # reference: one uninterrupted epoch
    holder, loader = fresh()
    want = [step_fn(holder, b) for b in loader]

    # crash run: same start, die at crash_at with a job_state checkpoint
    mgr = CheckpointManager(os.path.join(root, "parity"))
    holder, loader = fresh()
    got, it = [], iter(loader)
    for _ in range(crash_at):
        got.append(step_fn(holder, next(it)))
    net, opt = holder
    mgr.save({"model": net.state_dict(), "opt": opt.state_dict()}, crash_at,
             job_state=ft.capture_job_state(data_iter=loader))
    del holder, loader, it, net, opt  # "the process dies here"

    # resumed "process": different entropy — the restore must win
    paddle.seed(31337)
    holder = _build_mlp(99)
    loader2 = ResumableLoader(DataLoader(data, batch_size=2, shuffle=True))
    state, step, js = ft.elastic_resume(mgr, data_iter=loader2)
    holder[0].set_state_dict(state["model"])
    holder[1].set_state_dict(state["opt"])
    got += [step_fn(holder, b) for b in loader2]

    return {"ok": got == want, "steps": len(want), "crash_at": crash_at,
            "resumed_from": int(step), "job_state_entries": sorted(js),
            "losses_reference": want, "losses_resumed": got}


# --------------------------------------------------------------- overlap
def run_overlap_parity(steps, seed):
    """Overlapped bucket-ready sync under mid-backward chaos vs the serial
    path: hang + transient faults injected on a mid-backward bucket's
    collective (recovered by the group timeout + retry machinery the lane
    inherits) must leave every step's loss EXACTLY equal to the serial
    run's — the flush() barrier and per-bucket retries may reorder wall
    time, never values."""
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu.distributed import grad_comm
    from paddle_tpu.distributed.overlap import OverlappedGradCommunicator
    from paddle_tpu.robustness.fault_injection import ChaosGroup

    rs = np.random.RandomState(seed)
    x = rs.standard_normal((16, 8)).astype(np.float32)
    y = rs.standard_normal((16, 1)).astype(np.float32)
    # tiny caps -> several buckets, so "mid-backward bucket" is meaningful
    mk_cfg = lambda overlap: grad_comm.GradCommConfig(
        "fp32", comm_buffer_size=0.0002, last_comm_buffer_size=0.0001,
        overlap=overlap)

    def train(comm, group, steps):
        paddle.seed(4000 + seed)
        net, opt = _build_mlp(5000 + seed)
        params = [p for p in net.parameters() if not p.stop_gradient]
        comm.group = group
        losses = []
        for _ in range(steps):
            if hasattr(comm, "prepare"):
                comm.prepare(params, world=2)
            loss = F.mse_loss(net(paddle.to_tensor(x)), paddle.to_tensor(y))
            loss.backward()
            comm.sync(params, world=2)
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.numpy()))
        return losses

    serial = train(grad_comm.GradCommunicator(mk_cfg(False)), None, steps)
    # fault plan: collective call 2 (a mid-backward bucket, 1-based) hangs
    # past the group timeout -> retried; call 5 fails transiently -> backoff
    # retried. Counters advance per invocation, so the retries land on
    # fault-free indices.
    g = ChaosGroup(plan={2: ("hang", 0.4), 5: ("fail", None)}, timeout=0.05)
    overlapped = train(OverlappedGradCommunicator(mk_cfg(True)), g, steps)
    chaos = g.chaos
    return {
        "ok": (serial == overlapped and chaos.hangs == 1
               and chaos.fails == 1),
        "steps": steps,
        "hangs_injected": chaos.hangs,
        "transients_injected": chaos.fails,
        "losses_serial": serial,
        "losses_overlapped": overlapped,
    }


# -------------------------------------------------------- flight recorder
def run_flightrec_postmortem(seed):
    """Mid-backward hang that EXHAUSTS its retries (ISSUE 6): every attempt
    of one bucket's collective hangs past the group timeout, so the lane
    surfaces CollectiveTimeoutError and the escalation path dumps the
    flight recorder. The dump's tail must name the exact bucket that
    stalled — its comm lane span — and carry the timeout event, or the
    postmortem is decoration, not diagnosis."""
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu.distributed import grad_comm
    from paddle_tpu.distributed.overlap import OverlappedGradCommunicator
    from paddle_tpu.framework.errors import CollectiveTimeoutError
    from paddle_tpu.observability import get_flight_recorder
    from paddle_tpu.robustness.fault_injection import ChaosGroup

    rs = np.random.RandomState(seed)
    x = rs.standard_normal((16, 8)).astype(np.float32)
    y = rs.standard_normal((16, 1)).astype(np.float32)
    paddle.seed(6000 + seed)
    net, _ = _build_mlp(7000 + seed)
    params = [p for p in net.parameters() if not p.stop_gradient]
    comm = OverlappedGradCommunicator(grad_comm.GradCommConfig(
        "fp32", comm_buffer_size=0.0002, last_comm_buffer_size=0.0001,
        overlap=True))
    # calls 2/3/4 = bucket 1's attempt + both retries (counters advance per
    # invocation), so the retry budget (DEFAULT_RETRIES=2) is exhausted
    comm.group = ChaosGroup(plan={2: ("hang", 0.4), 3: ("hang", 0.4),
                                  4: ("hang", 0.4)}, timeout=0.05)
    summary = {"timeout_raised": False, "dump_path": None,
               "hung_bucket": None, "tail_has_lane_span": False,
               "tail_has_timeout_event": False}
    comm.prepare(params, world=2)
    loss = F.mse_loss(net(paddle.to_tensor(x)), paddle.to_tensor(y))
    loss.backward()
    try:
        comm.sync(params, world=2)
    except CollectiveTimeoutError:
        summary["timeout_raised"] = True
    rec = get_flight_recorder()
    if rec.dumps:
        summary["dump_path"] = rec.dumps[-1]["path"]
        with open(summary["dump_path"]) as f:
            dump = json.load(f)
        tail = dump["entries"][-40:]
        # the hung bucket = the last comm lane span that STARTED in the ring
        starts = [e for e in tail if e["kind"] == "lane"
                  and e["name"].startswith("comm:")
                  and e.get("phase") == "start"]
        if starts:
            summary["hung_bucket"] = starts[-1].get("bucket")
            summary["tail_has_lane_span"] = True
        summary["tail_has_timeout_event"] = any(
            e["kind"] == "event" and e.get("severity") == "error"
            and "timed out" in e.get("message", "") for e in tail)
    summary["ok"] = (summary["timeout_raised"]
                     and summary["dump_path"] is not None
                     and summary["tail_has_lane_span"]
                     and summary["tail_has_timeout_event"])
    return summary


# -------------------------------------------------- preemption + reshard
def run_preemption_shrink(root, steps, seed, world_from=4, world_to=3):
    """ISSUE 10 end-to-end: a ZeRO-3 (emulated world=4) job gets a REAL
    SIGTERM mid-run, commits an emergency sharded checkpoint at the next
    step boundary (inside the grace window), "dies", and resumes at
    world=3 through the elastic reshard transform — zero refused resumes,
    and the resumed fp32 loss trajectory EXACTLY equals the uninterrupted
    reshape-reference run's."""
    import os as _os
    import signal as _signal
    import time as _time

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F
    import paddle_tpu.optimizer as optim
    from paddle_tpu.distributed import grad_comm
    from paddle_tpu.distributed.sharding import (
        Stage3ParamShards, save_group_sharded_checkpoint,
    )
    from paddle_tpu.framework.errors import CheckpointGeometryError
    from paddle_tpu.io import DataLoader
    from paddle_tpu.optimizer.fused import FusedFlatUpdater
    from paddle_tpu.robustness import (
        CheckpointManager, PreemptionHandler, ResumableLoader,
    )
    from paddle_tpu.robustness import distributed_ft as ft

    steps = max(4, steps)
    kill_at = steps // 2
    rs = np.random.RandomState(seed + 7)
    data = [(rs.standard_normal((4, 8)).astype(np.float32),
             rs.standard_normal((4, 1)).astype(np.float32))
            for _ in range(steps)]
    ckpt_root = os.path.join(root, "preempt")

    def build(world):
        paddle.seed(8000 + seed)
        net = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 1))
        opt = optim.AdamW(learning_rate=1e-2, parameters=net.parameters())
        comm = grad_comm.GradCommunicator(grad_comm.GradCommConfig(
            "fp32", comm_buffer_size=0.0002, last_comm_buffer_size=0.0001))
        params = [p for p in net.parameters() if not p.stop_gradient]
        fused = FusedFlatUpdater(opt, params, communicator=comm)
        store = Stage3ParamShards(params, comm, rank=0, world=world)
        store.shard_()
        store.install_hooks(net)
        net._zero3 = store
        loader = ResumableLoader(DataLoader(data, batch_size=1,
                                            shuffle=True))
        return net, comm, fused, store, params, loader

    def one(net, comm, fused, store, params, batch, world):
        xb, yb = batch
        loss = F.mse_loss(net(paddle.to_tensor(xb)), paddle.to_tensor(yb))
        loss.backward()
        comm.sync(params, world=world, use_reduce_scatter=True)
        fused.step_sharded(rank=0, world=world, param_store=store)
        for p in params:
            p.clear_grad()
        return float(loss.numpy())

    summary = {"steps": steps, "kill_at": kill_at,
               "world_from": world_from, "world_to": world_to,
               "sigterm_latched": False, "emergency_save_ms": None,
               "grace_seconds": None, "refused_without_flag": False,
               "refused_resumes": 0, "resharded": False}

    # ---- reshape-reference: uninterrupted at world_from
    net, comm, fused, store, params, loader = build(world_from)
    want = [one(net, comm, fused, store, params, b, world_from)
            for b in loader]

    # ---- preempted run: REAL SIGTERM mid-step, emergency save at the
    # step boundary, then "the process dies"
    net, comm, fused, store, params, loader = build(world_from)
    handler = PreemptionHandler(grace_seconds=10.0).install()
    got = []
    it = iter(loader)
    try:
        for k in range(kill_at):
            if k == kill_at - 1:
                # the eviction notice arrives DURING the step's compute
                _os.kill(_os.getpid(), _signal.SIGTERM)
            got.append(one(net, comm, fused, store, params, next(it),
                           world_from))
        handler.wait(2.0)  # latch is set by the main-thread handler
        if not handler.should_stop():
            summary["ok"] = False
            summary["error"] = "SIGTERM never latched"
            return summary
        summary["sigterm_latched"] = True
        t0 = _time.perf_counter()
        save_group_sharded_checkpoint(
            net, ckpt_root, kill_at, rank=0, world_size=1, fused=fused,
            job_state=ft.capture_job_state(reducer=comm, data_iter=loader,
                                           zero3=store),
            metadata={"reason": "preemption"})
        summary["emergency_save_ms"] = round(
            (_time.perf_counter() - t0) * 1e3, 3)
        summary["grace_seconds"] = handler.grace_remaining()
        summary["exit_status"] = handler.exit_status()
    finally:
        handler.uninstall()
    del net, comm, fused, store, params, loader, it  # dies here

    # ---- resumed "process" at world_to: geometry drift must RESHARD,
    # never refuse
    paddle.seed(31337)  # different entropy — the restore must win
    net, comm, fused, store, params, loader = build(world_to)
    mgr = CheckpointManager(ckpt_root)
    try:  # the refusal is still typed + diagnosable without the flag
        mgr.load_sharded(rank=0, world_size=1, zero3_world=world_to)
    except CheckpointGeometryError:
        summary["refused_without_flag"] = True
    try:
        payload, step, _manifest = mgr.load_sharded(
            rank=0, world_size=1, zero3_world=world_to, allow_reshard=True)
    except CheckpointGeometryError:
        summary["refused_resumes"] += 1
        summary["ok"] = False
        return summary
    summary["resharded"] = True
    store.load_state_dict(payload["zero3"])
    fused.load_shard_slots_state(payload["fused_shard_slots"])
    ft.restore_job_state(payload["job_state"], reducer=comm,
                         data_iter=loader, zero3=store, allow_reshard=True)
    got += [one(net, comm, fused, store, params, b, world_to)
            for b in loader]

    summary["losses_reference"] = want
    summary["losses_resumed"] = got
    summary["ok"] = (got == want and summary["sigterm_latched"]
                     and summary["resharded"]
                     and summary["refused_without_flag"]
                     and summary["refused_resumes"] == 0
                     and summary["emergency_save_ms"] is not None
                     and summary["grace_seconds"] > 0)
    return summary


# ------------------------------------------------------------------- chaos
FAULTS = ("none", "bitflip", "hang", "transient")


def run_chaos(root, steps, seed, ckpt_every=4):
    """2-replica DP under a seeded fault schedule; every fault must be
    detected and recovered, with zero silent divergence."""
    import jax.numpy as jnp  # noqa: F401 (backend warm before timing)
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu.framework.tensor import Tensor
    from paddle_tpu.robustness import CheckpointManager, ReplicaGuard
    from paddle_tpu.robustness import distributed_ft as ft
    from paddle_tpu.robustness.fault_injection import ChaosGroup, flip_bit
    import paddle_tpu.distributed.collective as coll

    rng = random.Random(seed)
    rs = np.random.RandomState(seed + 1)
    replicas = [_build_mlp(3000 + seed) for _ in range(2)]
    nets = [r[0] for r in replicas]
    opts = [r[1] for r in replicas]
    mgr = CheckpointManager(os.path.join(root, "chaos"), keep_last_n=3)

    def save_ckpt(step):
        mgr.save({"models": [n.state_dict() for n in nets],
                  "opts": [o.state_dict() for o in opts]}, step,
                 job_state=ft.capture_job_state())

    class RollbackTarget:
        """Restore ALL replicas (every rank rolls back in a real job)."""

        def rollback(self):
            found = mgr.load_latest()
            if found is None:
                return False
            st = found[0]
            for n, s in zip(nets, st["models"]):
                n.set_state_dict(s)
            for o, s in zip(opts, st["opts"]):
                o.set_state_dict(s)
            return True

    def cross_replica_reduce(digest):
        d2 = ft.params_digest(nets[1].parameters())
        both = np.stack([digest, d2])
        return both.min(axis=0), both.max(axis=0)

    guard = ReplicaGuard(policy="rollback", checkpoint=RollbackTarget(),
                         reduce_fn=cross_replica_reduce)

    summary = {"steps": steps, "seed": seed,
               "fault_counts": {f: 0 for f in FAULTS},
               "bitflips_injected": 0, "bitflips_detected": 0,
               "hangs_injected": 0, "hangs_recovered": 0,
               "transients_injected": 0, "transients_recovered": 0,
               "rollbacks": 0, "silent_divergence_steps": 0,
               "checkpoints": 0, "failures": []}

    # seeded schedule with every class guaranteed present
    schedule = {1: "bitflip", 2: "hang", 3: "transient"}
    for step in range(4, steps + 1):
        schedule[step] = rng.choice(FAULTS)

    save_ckpt(0)
    summary["checkpoints"] += 1

    for step in range(1, steps + 1):
        fault = schedule.get(step, "none")
        summary["fault_counts"][fault] += 1

        # ---- collective-path faults: a real eager all_reduce of the loss
        # scalar through a ChaosGroup carrying the fault plan
        if fault == "hang":
            summary["hangs_injected"] += 1
            g = ChaosGroup(plan={1: ("hang", 0.5)}, timeout=0.05)
            try:
                coll.all_reduce(Tensor(np.float32(1.0)), group=g)
                summary["hangs_recovered"] += 1
            except Exception as e:  # noqa: BLE001 - recorded, run fails
                summary["failures"].append(
                    {"step": step, "fault": fault, "error": repr(e)})
        elif fault == "transient":
            summary["transients_injected"] += 1
            g = ChaosGroup(plan={1: ("fail", None)})
            try:
                coll.all_reduce(Tensor(np.float32(1.0)), group=g)
                summary["transients_recovered"] += 1
            except Exception as e:  # noqa: BLE001
                summary["failures"].append(
                    {"step": step, "fault": fault, "error": repr(e)})

        # ---- SDC: flip one bit of one replica's parameters mid-step.
        # Mantissa bits only (low two bytes of a float32 word): an
        # exponent/sign flip can NaN the loss, and NaN grads poison BOTH
        # replicas identically through the averaged gradients — the
        # corruption would "heal" into agreement (and the NanGuard, not the
        # ReplicaGuard, owns that failure class). A mantissa flip is the
        # convergence-poisoning SDC this guard exists for.
        if fault == "bitflip":
            summary["bitflips_injected"] += 1
            victim = nets[rng.randrange(2)]
            vparams = list(victim.parameters())
            flip_bit(vparams[rng.randrange(len(vparams))],
                     bit_index=rng.randrange(16) * 32 + rng.randrange(16))

        # ---- the step-boundary integrity check: corruption from the
        # previous step's compute must be caught BEFORE the next update
        # can propagate (or round away) the damage
        try:
            action = guard.check(list(nets[0].parameters()), step=step)
        except Exception as e:  # noqa: BLE001
            summary["failures"].append(
                {"step": step, "fault": fault, "error": repr(e)})
            action = "error"
        if action == "rollback":
            summary["rollbacks"] += 1
            if fault == "bitflip":
                summary["bitflips_detected"] += 1
            else:
                summary["failures"].append(
                    {"step": step, "fault": fault,
                     "error": "rollback without an injected flip"})
        elif fault == "bitflip":
            summary["failures"].append(
                {"step": step, "fault": fault,
                 "error": "injected bit-flip not detected"})

        # ---- one emulated-DP train step: same batch, averaged grads
        x = Tensor(rs.standard_normal((4, 8)).astype(np.float32))
        y = Tensor(rs.standard_normal((4, 1)).astype(np.float32))
        for net in nets:
            F.mse_loss(net(x), y).backward()
        p0, p1 = (list(n.parameters()) for n in nets)
        for a, b in zip(p0, p1):
            if a.grad is None:
                continue
            avg = (np.asarray(a.grad.numpy()) + np.asarray(b.grad.numpy())) / 2
            a.grad.set_value(avg)
            b.grad.set_value(avg)
        for opt in opts:
            opt.step()
            opt.clear_grad()

        # ---- invariant: after detection/recovery the replicas agree
        d0 = ft.params_digest(nets[0].parameters())
        d1 = ft.params_digest(nets[1].parameters())
        if not np.array_equal(d0, d1):
            summary["silent_divergence_steps"] += 1
            summary["failures"].append(
                {"step": step, "fault": fault,
                 "error": "replicas disagree after recovery"})

        # ---- periodic checkpoint, only from an agreed state
        if step % ckpt_every == 0 and np.array_equal(d0, d1):
            save_ckpt(step)
            summary["checkpoints"] += 1

    summary["final_replicas_identical"] = bool(np.array_equal(
        ft.params_digest(nets[0].parameters()),
        ft.params_digest(nets[1].parameters())))
    summary["ok"] = (not summary["failures"]
                     and summary["silent_divergence_steps"] == 0
                     and summary["bitflips_detected"]
                     == summary["bitflips_injected"]
                     and summary["hangs_recovered"]
                     == summary["hangs_injected"]
                     and summary["transients_recovered"]
                     == summary["transients_injected"]
                     and summary["final_replicas_identical"])
    return summary


def run_chaos_train(steps=40, seed=0, root=None):
    """Both phases; summary["ok"] is the overall verdict."""
    import logging

    # injected faults are the point — per-retry warnings would drown the run
    logging.getLogger("paddle_tpu").setLevel(logging.ERROR)
    root = root or tempfile.mkdtemp(prefix="chaos_train_")
    parity = run_parity(root, steps=max(4, steps // 2), seed=seed)
    overlap = run_overlap_parity(steps=max(4, steps // 8), seed=seed)
    flightrec = run_flightrec_postmortem(seed=seed)
    preempt = run_preemption_shrink(root, steps=max(4, steps // 4),
                                    seed=seed)
    chaos = run_chaos(root, steps=steps, seed=seed)
    return {"ok": (parity["ok"] and overlap["ok"] and flightrec["ok"]
                   and preempt["ok"] and chaos["ok"]),
            "root": root, "seed": seed,
            "parity": parity, "overlap": overlap, "flightrec": flightrec,
            "preempt": preempt, "chaos": chaos}


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--steps", type=int, default=40)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--root", default=None)
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "artifacts", "chaos_train.json"))
    args = ap.parse_args(argv)

    summary = run_chaos_train(steps=args.steps, seed=args.seed,
                              root=args.root)
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(summary, f, indent=1)
    chaos = summary["chaos"]
    print(f"parity: ok={summary['parity']['ok']} "
          f"(crash at step {summary['parity']['crash_at']}, "
          f"{summary['parity']['steps']} steps, exact loss match)")
    ov = summary["overlap"]
    print(f"overlap: ok={ov['ok']} — {ov['steps']} overlapped-sync steps "
          f"under chaos ({ov['hangs_injected']} hang, "
          f"{ov['transients_injected']} transient on mid-backward "
          f"buckets), exact loss match vs serial")
    fr = summary["flightrec"]
    print(f"flightrec: ok={fr['ok']} — retry-exhausted mid-backward hang "
          f"dumped bucket {fr['hung_bucket']}'s lane span + the timeout "
          f"event to {fr['dump_path']}")
    pr = summary["preempt"]
    print(f"preempt: ok={pr['ok']} — SIGTERM at step {pr['kill_at']} of a "
          f"world={pr['world_from']} ZeRO-3 job, emergency sharded "
          f"checkpoint in {pr['emergency_save_ms']}ms, resumed at "
          f"world={pr['world_to']} via reshard "
          f"({pr['refused_resumes']} refused), exact loss parity")
    print(f"chaos:  ok={chaos['ok']} — "
          f"{chaos['bitflips_detected']}/{chaos['bitflips_injected']} "
          f"bit-flips detected, "
          f"{chaos['hangs_recovered']}/{chaos['hangs_injected']} hangs "
          f"recovered, "
          f"{chaos['transients_recovered']}/{chaos['transients_injected']} "
          f"transients absorbed, "
          f"{chaos['silent_divergence_steps']} silent-divergence steps, "
          f"{chaos['rollbacks']} rollbacks, "
          f"{chaos['checkpoints']} checkpoints")
    print(f"summary -> {args.out}")
    return 0 if summary["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())

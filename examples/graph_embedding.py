"""Node embeddings from graph-table walks: node2vec -> skip-gram.

The GNN training loop the reference's graph table feeds (PGL-style:
common_graph_table.cc serves walks to an embedding trainer): sample
node2vec walks from paddle_tpu's GraphTable, build (center, context)
skip-gram pairs with negative sampling, and train an nn.Embedding with
Adam until same-community nodes embed closer than cross-community ones.

Graph: two ring communities bridged by one edge — the classic sanity
structure where walk-based embeddings must separate the halves.

Run: python examples/graph_embedding.py [--epochs 60]
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if os.environ.get("JAX_PLATFORMS") == "cpu":  # honor forced-CPU runs even
    import jax                                 # under a TPU-tunnel shim
    jax.config.update("jax_platforms", "cpu")

import argparse

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed.ps import GraphTable


def build_graph(n_per_side=12, seed=0):
    """Two communities; each node links to its 2 ring neighbors plus 2
    random same-community chords; one bridge edge."""
    rs = np.random.RandomState(seed)
    src, dst = [], []

    def ring(base):
        for i in range(n_per_side):
            a = base + i
            for d in (1, 2):
                b = base + (i + d) % n_per_side
                src.extend([a, b])
                dst.extend([b, a])
            c = base + rs.randint(n_per_side)
            if c != a:
                src.extend([a, c])
                dst.extend([c, a])

    ring(0)
    ring(n_per_side)
    src.extend([0, n_per_side])
    dst.extend([n_per_side, 0])
    g = GraphTable(seed=seed)
    g.add_edges(np.asarray(src, np.int64), np.asarray(dst, np.int64))
    return g, 2 * n_per_side


def skip_gram_pairs(walks, window=2):
    centers, contexts = [], []
    for walk in walks:
        walk = walk[walk >= 0]
        for i, c in enumerate(walk):
            lo, hi = max(0, i - window), min(len(walk), i + window + 1)
            for j in range(lo, hi):
                if j != i:
                    centers.append(c)
                    contexts.append(walk[j])
    return np.asarray(centers, np.int64), np.asarray(contexts, np.int64)


def train(g, n_nodes, dim=16, epochs=60, walks_per_node=6, walk_len=8,
          negatives=4, seed=0):
    paddle.seed(seed)
    emb_in = nn.Embedding(n_nodes, dim)
    emb_out = nn.Embedding(n_nodes, dim)
    optim = paddle.optimizer.Adam(
        learning_rate=0.05,
        parameters=list(emb_in.parameters()) + list(emb_out.parameters()))
    rs = np.random.RandomState(seed)

    losses = []
    for epoch in range(epochs):
        starts = np.tile(np.arange(n_nodes, dtype=np.int64), walks_per_node)
        walks = g.node2vec_walk(starts, walk_len, p=1.0, q=0.5)
        centers, contexts = skip_gram_pairs(walks)
        negs = rs.randint(0, n_nodes, (centers.size, negatives))

        c = emb_in(paddle.to_tensor(centers))           # [B, d]
        pos = emb_out(paddle.to_tensor(contexts))       # [B, d]
        neg = emb_out(paddle.to_tensor(negs))           # [B, k, d]
        pos_logit = (c * pos).sum(-1)
        neg_logit = (c.unsqueeze(1) * neg).sum(-1)      # [B, k]
        loss = (F.binary_cross_entropy_with_logits(
                    pos_logit, paddle.ones_like(pos_logit))
                + F.binary_cross_entropy_with_logits(
                    neg_logit, paddle.zeros_like(neg_logit)))
        loss.backward()
        optim.step()
        optim.clear_grad()
        losses.append(float(loss))
    return emb_in, losses


def community_margin(emb_in, n_nodes):
    """mean intra-community cosine sim minus mean inter-community sim."""
    vecs = emb_in.weight.numpy()
    vecs = vecs / np.linalg.norm(vecs, axis=1, keepdims=True)
    sims = vecs @ vecs.T
    half = n_nodes // 2
    # exclude the diagonal (self-similarity == 1.0) so intra measures
    # pairwise cohesion, not n self-matches inflating the mean
    intra = (sims[:half, :half][~np.eye(half, dtype=bool)].mean()
             + sims[half:, half:][~np.eye(n_nodes - half, dtype=bool)].mean()
             ) / 2
    inter = sims[:half, half:].mean()
    return float(intra - inter), float(intra), float(inter)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=60)
    ap.add_argument("--dim", type=int, default=16)
    args = ap.parse_args()

    g, n_nodes = build_graph()
    emb, losses = train(g, n_nodes, dim=args.dim, epochs=args.epochs)
    margin, intra, inter = community_margin(emb, n_nodes)
    print(f"loss {losses[0]:.4f} -> {losses[-1]:.4f}  "
          f"intra-sim {intra:.3f}  inter-sim {inter:.3f}  "
          f"margin {margin:.3f}")
    assert losses[-1] < losses[0]
    assert margin > 0.2, "communities failed to separate"


if __name__ == "__main__":
    main()

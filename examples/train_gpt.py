"""Train GPT with the fused TrainStep — single chip or hybrid mesh.

    python examples/train_gpt.py                 # single device
    python examples/train_gpt.py --dp 2 --tp 2   # 4-device mesh (set
        XLA_FLAGS=--xla_force_host_platform_device_count=4 JAX_PLATFORMS=cpu
        to try it without TPUs)
"""
import os


if os.environ.get("JAX_PLATFORMS") == "cpu":  # honor forced-CPU runs even
    import jax                                 # under a TPU-tunnel shim
    jax.config.update("jax_platforms", "cpu")

import argparse

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.optimizer as opt
from paddle_tpu.distributed import mesh as mesh_mod
from paddle_tpu.jit import TrainStep
from paddle_tpu.models import GPTForCausalLM, GPTPretrainingCriterion, gpt_presets


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", default="gpt-test")
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=64)
    ap.add_argument("--dp", type=int, default=1)
    ap.add_argument("--tp", type=int, default=1)
    ap.add_argument("--pp", type=int, default=1)
    args = ap.parse_args()

    if args.dp * args.tp * args.pp > 1:
        import jax

        mesh_mod.set_mesh(mesh_mod.build_mesh(
            {"data": args.dp, "model": args.tp, "pipe": args.pp},
            devices=jax.devices()[: args.dp * args.tp * args.pp]))

    cfg = gpt_presets(args.preset, max_position_embeddings=args.seq,
                      mode="scan" if args.pp > 1 else "loop")
    model = GPTForCausalLM(cfg, seed=0)
    crit = GPTPretrainingCriterion()
    optim = opt.AdamW(learning_rate=3e-4, parameters=model.parameters())
    step = TrainStep(model, lambda lg, lb: crit(lg, lb), optim)

    rs = np.random.RandomState(0)
    for i in range(args.steps):
        ids = paddle.to_tensor(
            rs.randint(0, cfg.vocab_size, (args.batch, args.seq)), dtype="int64")
        loss = step(inputs=(ids,), labels=(ids,))
        if i % 5 == 0 or i == args.steps - 1:
            print(f"step {i}: loss {float(loss):.4f}")


if __name__ == "__main__":
    main()

"""Train → save_inference_model → serve with the zero-copy Predictor.

    python examples/serve_predictor.py
"""


import os

if os.environ.get("JAX_PLATFORMS") == "cpu":  # honor forced-CPU runs even
    import jax                                 # under a TPU-tunnel shim
    jax.config.update("jax_platforms", "cpu")


import tempfile

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.static as static


def main():
    paddle.enable_static()
    main_prog, startup = static.Program(), static.Program()
    with static.program_guard(main_prog, startup):
        x = static.data("x", (None, 16), "float32")
        y = static.data("y", (None, 1), "float32")
        h = static.nn.fc(x, size=32, activation="relu")
        pred = static.nn.fc(h, size=1)
        loss = ((pred - y) ** 2).mean()
        paddle.optimizer.Adam(learning_rate=0.01).minimize(loss)

    exe = static.Executor()
    exe.run(startup)
    rs = np.random.RandomState(0)
    w_true = rs.randn(16, 1).astype("float32")
    for i in range(100):
        xb = rs.randn(32, 16).astype("float32")
        (lv,) = exe.run(main_prog, feed={"x": xb, "y": xb @ w_true},
                        fetch_list=[loss])
    print(f"final train loss: {float(lv):.5f}")

    prefix = os.path.join(tempfile.mkdtemp(), "model")
    static.save_inference_model(prefix, [x], [pred], exe,
                                program=main_prog.clone(for_test=True))
    paddle.disable_static()

    from paddle_tpu import inference

    predictor = inference.create_predictor(inference.Config(prefix + ".pdmodel"))
    xb = rs.randn(4, 16).astype("float32")
    out = predictor.run([xb])[0]
    print("served predictions:", out.ravel())
    print("expected:          ", (xb @ w_true).ravel())

    # SaveOptimModel (analysis_predictor.h:265): persist the post-analysis
    # model as the native StableHLO triple — later loads skip the import,
    # the pass stack, and tracing
    optim_prefix = os.path.join(tempfile.mkdtemp(), "optimized")
    predictor.save_optimized_model(optim_prefix)
    fast = inference.create_predictor(inference.Config(optim_prefix))
    out2 = fast.run([xb])[0]
    assert np.allclose(out2, out, rtol=1e-6, atol=1e-7)
    print("optimized-artifact serve matches")


if __name__ == "__main__":
    main()

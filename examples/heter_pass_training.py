"""Heter-PS pass training: the compiled fast path for CTR models.

The eager PS path (examples/wide_deep_ps.py) dispatches one host op per
layer per batch and round-trips embedding rows host<->device on every
lookup. The heter pass path (reference PSGPUTrainer, ps_gpu_wrapper.cc)
pulls each pass's working set into device memory once, trains with ONE
compiled XLA program per step (gather + dense fwd/bwd + Adam + device
adagrad on the embedding slab), and syncs values back at pass end —
5-6x examples/s on CPU, more on a TPU behind a network tunnel.

    python examples/heter_pass_training.py
"""
import os

if os.environ.get("JAX_PLATFORMS") == "cpu":  # honor forced-CPU runs even
    import jax                                 # under a TPU-tunnel shim
    jax.config.update("jax_platforms", "cpu")

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed.ps import LocalPs
from paddle_tpu.distributed.ps.heter_cache import DevicePassCache
from paddle_tpu.distributed.ps.heter_trainer import CompiledPassStep

VOCAB, SLOTS, DIM, BATCH = 1000, 6, 8, 64


def main():
    ps = LocalPs()
    ps.create_table(0, dim=DIM, init_range=0.01, lr=0.1,
                    optimizer="adagrad")
    cache = DevicePassCache(ps, 0, lr=0.1)

    deep = paddle.nn.Sequential(
        paddle.nn.Linear(DIM * SLOTS, 32), paddle.nn.ReLU(),
        paddle.nn.Linear(32, 1))
    optim = paddle.optimizer.Adam(learning_rate=1e-3,
                                  parameters=deep.parameters())
    step = CompiledPassStep(
        cache, deep, optim,
        lambda out, labels: F.binary_cross_entropy_with_logits(
            out[:, 0], labels),
        table_optimizer="adagrad", table_lr=0.1)

    rs = np.random.RandomState(0)
    true_w = rs.randn(VOCAB)

    def batch():
        ids = rs.randint(0, VOCAB, (BATCH, SLOTS))
        return ids, (true_w[ids].sum(1) > 0).astype("float32")

    losses = []
    for p in range(5):  # 5 passes x 10 steps
        bs = [batch() for _ in range(10)]
        cache.begin_pass(np.concatenate([b[0].reshape(-1) for b in bs]),
                         pad_to=VOCAB)  # fixed slab: one compile, ever
        for b in bs:
            losses.append(float(step(cache, b).numpy()))
        cache.end_pass(assign=True)  # device adagrad owns the update
        print(f"pass {p}: loss {losses[-1]:.4f} "
              f"(pulls={cache.pulls} syncs={cache.pushes})")
    assert losses[-1] < losses[0]
    print(f"trained: {losses[0]:.4f} -> {losses[-1]:.4f}, "
          f"table rows {ps.table_size(0)}")


if __name__ == "__main__":
    main()

"""Wide&Deep CTR training over the parameter server (async communicator).

    python examples/wide_deep_ps.py
"""
import os


if os.environ.get("JAX_PLATFORMS") == "cpu":  # honor forced-CPU runs even
    import jax                                 # under a TPU-tunnel shim
    jax.config.update("jax_platforms", "cpu")

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.distributed.ps import LocalPs, TheOnePSRuntime, distributed_lookup_table
from paddle_tpu.distributed.ps.communicator import AsyncCommunicator


def main():
    runtime = TheOnePSRuntime()
    ps = LocalPs()
    ps.create_table(0, dim=8, init_range=0.01, lr=0.1, optimizer="adagrad")
    runtime.client = ps
    runtime.communicator = AsyncCommunicator(ps)
    runtime.communicator.start()

    deep = paddle.nn.Sequential(
        paddle.nn.Linear(8 * 6, 32), paddle.nn.ReLU(), paddle.nn.Linear(32, 1))
    optim = paddle.optimizer.Adam(learning_rate=1e-3,
                                  parameters=deep.parameters())
    rs = np.random.RandomState(0)
    true_w = rs.randn(1000)
    for step in range(50):
        ids = rs.randint(0, 1000, (64, 6))
        labels = (true_w[ids].sum(1) > 0).astype("float32")
        rows = distributed_lookup_table(
            paddle.to_tensor(ids, dtype="int64"), table_id=0, lr=0.1)
        logit = deep(rows.reshape([64, -1]))[:, 0]
        loss = paddle.nn.functional.binary_cross_entropy_with_logits(
            logit, paddle.to_tensor(labels))
        loss.backward()
        optim.step()
        optim.clear_grad()
        if step % 10 == 0:
            print(f"step {step}: loss {float(loss):.4f}  "
                  f"table rows {ps.table_size(0)}")
    runtime.communicator.stop()


if __name__ == "__main__":
    main()

"""Wide&Deep CTR training over the parameter server, two ways.

``--eager`` (the pre-ISSUE-20 path): per-step distributed_lookup_table
through the async communicator — a host pull + Tensor-autograd dense
step + host push for every batch. Simple, and roughly three orders of
magnitude under the accelerator roofline.

Default (ISSUE 20): the compiled hot path — paddle_tpu.models.WideDeep
under PsTrainStep (ONE jitted program per step, pre-gathered rows in /
row-grads out) driven by PsPipeline double buffering over a bus-sharded
PS, so step k computes while step k+1's unique keys prefetch and step
k-1's merged grads push. tools/ps_bench.py measures the gap.

    python examples/wide_deep_ps.py [--eager]
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if os.environ.get("JAX_PLATFORMS") == "cpu":  # honor forced-CPU runs even
    import jax                                 # under a TPU-tunnel shim
    jax.config.update("jax_platforms", "cpu")

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.models import WideDeep, ctr_batches, wide_deep_loss

VOCAB, SLOTS, DIM, BATCH, STEPS = 1000, 6, 8, 64, 50


def main_eager():
    from paddle_tpu.distributed.ps import (
        LocalPs, TheOnePSRuntime, distributed_lookup_table)
    from paddle_tpu.distributed.ps.communicator import AsyncCommunicator

    runtime = TheOnePSRuntime()
    ps = LocalPs()
    ps.create_table(0, dim=DIM, init_range=0.01, lr=0.1,
                    optimizer="adagrad")
    runtime.client = ps
    runtime.communicator = AsyncCommunicator(ps)
    runtime.communicator.start()

    paddle.seed(0)
    model = WideDeep(SLOTS, DIM)
    optim = paddle.optimizer.Adam(learning_rate=1e-3,
                                  parameters=model.parameters())
    for step, (ids, labels) in enumerate(
            ctr_batches(STEPS, BATCH, SLOTS, VOCAB, alpha=1.1, seed=0)):
        rows = distributed_lookup_table(
            paddle.to_tensor(ids.astype(np.int64)), table_id=0, lr=0.1)
        logit = model(rows.reshape([BATCH, -1]))
        loss = wide_deep_loss(logit, paddle.to_tensor(labels))
        loss.backward()
        optim.step()
        optim.clear_grad()
        if step % 10 == 0:
            print(f"step {step}: loss {float(loss):.4f}  "
                  f"table rows {ps.table_size(0)}")
    runtime.communicator.stop()


def main_pipelined():
    from paddle_tpu.distributed.ps.pipeline import (
        PsPipeline, PsTrainStep, make_sharded_ps)

    client, services, bus = make_sharded_ps(2)
    try:
        client.create_table(0, DIM, init_range=0.01, optimizer="adagrad")
        paddle.seed(0)
        model = WideDeep(SLOTS, DIM)
        optim = paddle.optimizer.Adam(learning_rate=1e-3,
                                      parameters=model.parameters())
        step = PsTrainStep(model, optim, wide_deep_loss, dim=DIM,
                           pad_rows=512)
        pipe = PsPipeline(client, 0, step, depth=2, lr_sparse=0.1)
        batches = ctr_batches(STEPS, BATCH, SLOTS, VOCAB, alpha=1.1,
                              seed=0)
        stats = pipe.run(batches)
        pipe.close()
        for i in range(0, STEPS, 10):
            print(f"step {i}: loss {stats['losses'][i]:.4f}")
        print(f"{stats['examples_per_s']:.0f} examples/s, exposed pull "
              f"{stats['exposed_pull_ms']:.3f} ms / step "
              f"{stats['step_ms']:.3f} ms, table rows "
              f"{client.table_size(0)}")
    finally:
        client.close()
        for s in services:
            s.stop()
        bus.close()


if __name__ == "__main__":
    if "--eager" in sys.argv[1:]:
        main_eager()
    else:
        main_pipelined()

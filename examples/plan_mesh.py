"""Pick a parallelism plan with the auto-parallel mesh planner.

The planner compiles YOUR train step for every candidate mesh with the
real TPU compiler (ahead-of-time, via jax.experimental.topologies — no
TPU hardware or execution involved) and ranks candidates by the
compiler's estimated step time under the per-chip HBM budget. The
reference reaches the same goal with a hand-written cost simulator
(auto_parallel/planner.py + cost_model.py); here the cost model IS the
compiler, so it cannot disagree with the executable it ranks.

Run: python examples/plan_mesh.py [--devices 8]
Exits cleanly with a note when no TPU AOT compiler (libtpu) is present.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))  # runnable as `python examples/plan_mesh.py`

import jax

jax.config.update("jax_platforms", "cpu")  # arrays on CPU; compile for TPU

import numpy as np  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

import paddle_tpu as paddle  # noqa: E402
import paddle_tpu.optimizer as opt  # noqa: E402
from paddle_tpu.jit import TrainStep  # noqa: E402
from paddle_tpu.models import (  # noqa: E402
    GPTForCausalLM, GPTPretrainingCriterion, gpt_presets,
)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", type=int, default=8)
    args = ap.parse_args()

    try:
        from jax.experimental import topologies

        topologies.get_topology_desc(platform="tpu",
                                     topology_name="v5e:2x4")
    except Exception as e:
        print(f"no TPU AOT compiler available ({type(e).__name__}); "
              f"nothing to plan")
        return

    from paddle_tpu.distributed.auto_parallel.planner import plan

    crit = GPTPretrainingCriterion()
    rs = np.random.RandomState(0)

    def builder(shape_map, activate_mesh):
        # build model/optimizer/inputs with NO mesh (real arrays must stay
        # on CPU — topology chips are described, not addressable), then
        # activate the candidate mesh for the abstract compile
        cfg = gpt_presets("gpt-test", mode="scan",
                          use_flash_attention=False)
        model = GPTForCausalLM(cfg, seed=0)
        optim = opt.AdamW(learning_rate=1e-4,
                          parameters=model.parameters())
        step = TrainStep(model, lambda lg, lb: crit(lg, lb), optim,
                         batch_spec=P(("data", "sharding")))
        ids = paddle.to_tensor(
            rs.randint(0, cfg.vocab_size, (16, 16)), dtype="int64")
        lbl = paddle.to_tensor(
            rs.randint(0, cfg.vocab_size, (16, 16)), dtype="int64")
        activate_mesh()
        return step, (ids,), (lbl,)

    plans = plan(builder, args.devices,
                 axes=("data", "sharding", "model"),
                 caps={"model": 4})
    print("\nranked plans (best first):")
    for p in plans:
        print(f"  {p}")
    best = plans[0]
    if best.error or not best.fits:
        print("no feasible plan found", file=sys.stderr)
        sys.exit(1)
    est = (f"est step {best.est_seconds*1e3:.2f} ms [{best.est_signal}]"
           if best.est_seconds is not None else "no step estimate")
    mem = (f"{best.peak_hbm_bytes/2**30:.2f} GiB/device"
           if best.peak_hbm_bytes is not None else "memory unreported")
    print(f"\nchosen mesh: {best.shape_map} ({est}, {mem})")


if __name__ == "__main__":
    main()

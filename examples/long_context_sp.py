"""Long-context training via sequence parallelism — ring vs Ulysses.

The sequence dim is sharded over the 'sep' mesh axis, so per-device
activation memory scales with s/P and the O(s^2) score matrix never lands
on one chip (ring: online-softmax k/v rotation; ulysses: all_to_all
head/seq swap). Both are net-new capability vs the reference (SURVEY §5).

    python examples/long_context_sp.py --scheme ring    --sep 4
    python examples/long_context_sp.py --scheme ulysses --sep 4

Try without TPUs:
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/long_context_sp.py --scheme ulysses --sep 4 --dp 2
"""
import os

if os.environ.get("JAX_PLATFORMS") == "cpu":
    import jax
    jax.config.update("jax_platforms", "cpu")

import argparse

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.optimizer as opt
from jax.sharding import PartitionSpec as P
from paddle_tpu.distributed import mesh as mesh_mod
from paddle_tpu.jit import TrainStep
from paddle_tpu.models import (
    GPTForCausalLM, GPTPretrainingCriterion, gpt_presets,
)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--scheme", choices=("ring", "ulysses"), default="ring")
    ap.add_argument("--sep", type=int, default=4)
    ap.add_argument("--dp", type=int, default=1)
    ap.add_argument("--seq", type=int, default=512)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--steps", type=int, default=10)
    args = ap.parse_args()

    import jax

    topo = {"data": args.dp, "sep": args.sep}
    mesh_mod.set_mesh(mesh_mod.build_mesh(topo))
    print(f"mesh: {topo} over {len(jax.devices())} devices")

    cfg = gpt_presets(
        "gpt-test",
        max_position_embeddings=args.seq,
        use_ring_attention=args.scheme == "ring",
        use_ulysses_attention=args.scheme == "ulysses",
    )
    model = GPTForCausalLM(cfg, seed=0)
    crit = GPTPretrainingCriterion()
    optim = opt.AdamW(learning_rate=3e-4, parameters=model.parameters())
    step = TrainStep(model, lambda lg, lb: crit(lg, lb), optim,
                     batch_spec=P(("data",)))

    rs = np.random.RandomState(0)
    ids = paddle.to_tensor(
        rs.randint(0, cfg.vocab_size, (args.batch, args.seq)), dtype="int64")
    labels = paddle.to_tensor(
        rs.randint(0, cfg.vocab_size, (args.batch, args.seq)), dtype="int64")

    for i in range(args.steps):
        loss = step(inputs=(ids,), labels=(labels,))
        if i % 2 == 0 or i == args.steps - 1:
            print(f"step {i:3d}  scheme={args.scheme}  "
                  f"loss {float(loss):.4f}")
    print("done")


if __name__ == "__main__":
    main()

"""paddle.fft — spectral transforms (parity: python/paddle/fft.py wrapping
operators/spectral_op.cc; here jnp.fft lowers to XLA FFT HLO which runs on
the TPU's dedicated FFT path)."""
from __future__ import annotations

import jax.numpy as jnp

from .framework.autograd import call_op as op
from .framework.tensor import Tensor

__all__ = [
    "fft", "ifft", "rfft", "irfft", "hfft", "ihfft",
    "fft2", "ifft2", "rfft2", "irfft2",
    "fftn", "ifftn", "rfftn", "irfftn",
    "fftfreq", "rfftfreq", "fftshift", "ifftshift",
]

_NORMS = {None: "backward", "backward": "backward", "ortho": "ortho",
          "forward": "forward"}


def _norm(norm):
    if norm not in _NORMS:
        raise ValueError(f"norm must be one of {list(_NORMS)}, got {norm!r}")
    return _NORMS[norm]


def _wrap1(jfn):
    def f(x, n=None, axis=-1, norm="backward", name=None):
        return op(lambda v: jfn(v, n=n, axis=axis, norm=_norm(norm)), x,
                  op_name=jfn.__name__)

    return f


def _wrap2(jfn):
    def f(x, s=None, axes=(-2, -1), norm="backward", name=None):
        return op(lambda v: jfn(v, s=s, axes=tuple(axes), norm=_norm(norm)),
                  x, op_name=jfn.__name__)

    return f


def _wrapn(jfn):
    def f(x, s=None, axes=None, norm="backward", name=None):
        ax = tuple(axes) if axes is not None else None
        return op(lambda v: jfn(v, s=s, axes=ax, norm=_norm(norm)), x,
                  op_name=jfn.__name__)

    return f


fft = _wrap1(jnp.fft.fft)
ifft = _wrap1(jnp.fft.ifft)
rfft = _wrap1(jnp.fft.rfft)
irfft = _wrap1(jnp.fft.irfft)
hfft = _wrap1(jnp.fft.hfft)
ihfft = _wrap1(jnp.fft.ihfft)
fft2 = _wrap2(jnp.fft.fft2)
ifft2 = _wrap2(jnp.fft.ifft2)
rfft2 = _wrap2(jnp.fft.rfft2)
irfft2 = _wrap2(jnp.fft.irfft2)
fftn = _wrapn(jnp.fft.fftn)
ifftn = _wrapn(jnp.fft.ifftn)
rfftn = _wrapn(jnp.fft.rfftn)
irfftn = _wrapn(jnp.fft.irfftn)


def fftfreq(n, d=1.0, dtype="float32", name=None):
    return Tensor(jnp.fft.fftfreq(n, d).astype(dtype), _internal=True)


def rfftfreq(n, d=1.0, dtype="float32", name=None):
    return Tensor(jnp.fft.rfftfreq(n, d).astype(dtype), _internal=True)


def fftshift(x, axes=None, name=None):
    ax = tuple(axes) if isinstance(axes, (list, tuple)) else axes
    return op(lambda v: jnp.fft.fftshift(v, axes=ax), x, op_name="fftshift")


def ifftshift(x, axes=None, name=None):
    ax = tuple(axes) if isinstance(axes, (list, tuple)) else axes
    return op(lambda v: jnp.fft.ifftshift(v, axes=ax), x, op_name="ifftshift")

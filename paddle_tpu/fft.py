"""paddle.fft — spectral transforms (parity: python/paddle/fft.py wrapping
operators/spectral_op.cc; here jnp.fft lowers to XLA FFT HLO which runs on
the TPU's dedicated FFT path)."""
from __future__ import annotations

import jax.numpy as jnp

from .framework.autograd import call_op as op
from .framework.tensor import Tensor

__all__ = [
    "fft", "ifft", "rfft", "irfft", "hfft", "ihfft",
    "fft2", "ifft2", "rfft2", "irfft2",
    "fftn", "ifftn", "rfftn", "irfftn",
    "fftfreq", "rfftfreq", "fftshift", "ifftshift",
]

_NORMS = {None: "backward", "backward": "backward", "ortho": "ortho",
          "forward": "forward"}


def _norm(norm):
    if norm not in _NORMS:
        raise ValueError(f"norm must be one of {list(_NORMS)}, got {norm!r}")
    return _NORMS[norm]


def _wrap1(jfn):
    def f(x, n=None, axis=-1, norm="backward", name=None):
        return op(lambda v: jfn(v, n=n, axis=axis, norm=_norm(norm)), x,
                  op_name=jfn.__name__)

    return f


def _wrap2(jfn):
    def f(x, s=None, axes=(-2, -1), norm="backward", name=None):
        return op(lambda v: jfn(v, s=s, axes=tuple(axes), norm=_norm(norm)),
                  x, op_name=jfn.__name__)

    return f


def _wrapn(jfn):
    def f(x, s=None, axes=None, norm="backward", name=None):
        ax = tuple(axes) if axes is not None else None
        return op(lambda v: jfn(v, s=s, axes=ax, norm=_norm(norm)), x,
                  op_name=jfn.__name__)

    return f


fft = _wrap1(jnp.fft.fft)
ifft = _wrap1(jnp.fft.ifft)
rfft = _wrap1(jnp.fft.rfft)
irfft = _wrap1(jnp.fft.irfft)
hfft = _wrap1(jnp.fft.hfft)
ihfft = _wrap1(jnp.fft.ihfft)
fft2 = _wrap2(jnp.fft.fft2)
ifft2 = _wrap2(jnp.fft.ifft2)
rfft2 = _wrap2(jnp.fft.rfft2)
irfft2 = _wrap2(jnp.fft.irfft2)
fftn = _wrapn(jnp.fft.fftn)
ifftn = _wrapn(jnp.fft.ifftn)
rfftn = _wrapn(jnp.fft.rfftn)
irfftn = _wrapn(jnp.fft.irfftn)


def fftfreq(n, d=1.0, dtype="float32", name=None):
    return Tensor(jnp.fft.fftfreq(n, d).astype(dtype), _internal=True)


def rfftfreq(n, d=1.0, dtype="float32", name=None):
    return Tensor(jnp.fft.rfftfreq(n, d).astype(dtype), _internal=True)


def fftshift(x, axes=None, name=None):
    ax = tuple(axes) if isinstance(axes, (list, tuple)) else axes
    return op(lambda v: jnp.fft.fftshift(v, axes=ax), x, op_name="fftshift")


def ifftshift(x, axes=None, name=None):
    ax = tuple(axes) if isinstance(axes, (list, tuple)) else axes
    return op(lambda v: jnp.fft.ifftshift(v, axes=ax), x, op_name="ifftshift")


def _resolve_sn(v, s, axes, last_default):
    """(s, axes) for the hermitian n-d transforms; s[-1] defaults to
    2*(x.shape[axes[-1]]-1) for hfft-like, x.shape for ihfft-like."""
    if axes is None:
        axes = tuple(range(-len(s), 0)) if s is not None else None
    if axes is None:
        axes = tuple(range(v.ndim))
    axes = tuple(int(a) for a in axes)
    if s is None:
        s = [v.shape[a] for a in axes]
        s[-1] = last_default(v.shape[axes[-1]])
    return tuple(int(n) for n in s), axes


def hfftn(x, s=None, axes=None, norm="backward", name=None):
    """N-D FFT of a Hermitian-symmetric input -> real spectrum (reference:
    paddle.fft.hfftn). Uses hfft(a, n) == irfft(conj(a), n) * n, extended
    over the leading axes by fftn."""
    nrm = _norm(norm)

    def fn(v):
        ss, ax = _resolve_sn(v, s, axes, lambda n: 2 * (n - 1))
        out = jnp.fft.irfftn(jnp.conj(v), s=ss, axes=ax, norm="backward")
        scale = 1.0
        for n in ss:
            scale *= n
        if nrm == "backward":
            out = out * scale
        elif nrm == "ortho":
            out = out * jnp.sqrt(scale)
        return out

    return op(fn, x, op_name="hfftn")


def ihfftn(x, s=None, axes=None, norm="backward", name=None):
    """Inverse of hfftn (reference: paddle.fft.ihfftn): real input -> the
    Hermitian half-spectrum. Uses ihfft(a, n) == conj(rfft(a, n)) / n."""
    nrm = _norm(norm)

    def fn(v):
        ss, ax = _resolve_sn(v, s, axes, lambda n: n)
        out = jnp.conj(jnp.fft.rfftn(v.real, s=ss, axes=ax, norm="backward"))
        scale = 1.0
        for n in ss:
            scale *= n
        if nrm == "backward":
            out = out / scale
        elif nrm == "ortho":
            out = out / jnp.sqrt(scale)
        return out

    return op(fn, x, op_name="ihfftn")


def hfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    """2-D Hermitian FFT (reference: paddle.fft.hfft2 == hfftn on 2 axes)."""
    return hfftn(x, s=s, axes=axes, norm=norm, name=name)


def ihfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    """2-D inverse Hermitian FFT (reference: paddle.fft.ihfft2)."""
    return ihfftn(x, s=s, axes=axes, norm=norm, name=name)


__all__ += ["hfft2", "hfftn", "ihfft2", "ihfftn"]

"""Always-on flight recorder: a bounded ring of what just happened.

When a production job hangs or trips, the question is never "what are the
aggregate counters" — it is "what were the LAST things this rank did".
The flight recorder answers it the way an aircraft FDR does: an always-on,
lock-light bounded ring of recent

- **spans**  — every RecordEvent close (tapped from the profiler's span
  sinks, profiler recording or not): phase spans, per-bucket comm spans;
- **events** — every EventLog record (module-level sink): NaN trips,
  checkpoint commits, collective retries;
- **lane entries** — collective-lane activity recorded explicitly by
  distributed/overlap.py and robustness/distributed_ft.py: which bucket
  launched on which group, which attempt of which collective started.

The ring records with one `deque.append` per entry (no lock on the hot
path; the GIL serializes appends and `maxlen` bounds memory), so it can
stay on for the whole job.

On an escalation — `HangDetector` stall/escalate, `NanGuard` trip,
`CollectiveTimeoutError` retry exhaustion, `ReplicaGuard` SDC hit — the
triggering subsystem calls ``dump_flight_recorder(reason)`` and the ring
is written to a postmortem JSON. The tail of that file names the exact
bucket/group/op that was in flight when the job died, which is the
difference between "rank 3 hung" and "bucket 2's all_reduce on group_7
launched and never completed".

Knobs: ``FLAGS_flight_recorder_capacity`` (ring depth; 0 disables
recording entirely) and ``FLAGS_flight_recorder_dir`` (dump directory;
defaults to <tmp>/paddle_tpu_flightrec).
"""
from __future__ import annotations

import json
import os
import tempfile
import threading
import time
from collections import deque
from typing import List, Optional

__all__ = ["FlightRecorder", "get_flight_recorder", "dump_flight_recorder",
           "configure_flight_recorder", "DEFAULT_CAPACITY"]

DEFAULT_CAPACITY = 4096
_MAX_AUTO_DUMPS = 16    # postmortem storms must not fill the disk


def _flag(name, default):
    try:
        from ..framework.flags import flag

        v = flag(name, default)
        return default if v is None else v
    except Exception:
        return default


class FlightRecorder:
    def __init__(self, capacity: Optional[int] = None,
                 dump_dir: Optional[str] = None, rank: Optional[int] = None):
        if capacity is None:
            capacity = int(_flag("FLAGS_flight_recorder_capacity",
                                 DEFAULT_CAPACITY))
        self.capacity = int(capacity)
        self._ring = deque(maxlen=max(1, self.capacity))
        self.enabled = self.capacity > 0
        self.dump_dir = dump_dir
        self.rank = rank
        self.dumps: List[dict] = []
        self._dump_lock = threading.Lock()
        self._seq = 0

    # ----------------------------------------------------------- recording
    def note(self, kind: str, name: str, **fields):
        """One ring entry; the hot path is a dict build + deque append."""
        if not self.enabled:
            return
        rec = {"mono": time.monotonic(), "kind": kind, "name": name}
        if fields:
            rec.update(fields)
        self._ring.append(rec)

    def lane(self, name: str, **fields):
        """Collective-lane activity (bucket launches, attempt starts) —
        the entries a hang postmortem is read for."""
        self.note("lane", name, **fields)

    # sink adapters ---------------------------------------------------------
    def _on_span(self, name, start_ns, end_ns, tid):
        if not self.enabled:
            return
        self._ring.append({
            "mono": time.monotonic(), "kind": "span", "name": name,
            "dur_us": (end_ns - start_ns) / 1e3, "tid": tid,
        })

    def _on_event(self, rec: dict):
        if not self.enabled:
            return
        self._ring.append({
            "mono": rec.get("mono", time.monotonic()), "kind": "event",
            "name": rec.get("kind", "?"),
            "severity": rec.get("severity"),
            "message": rec.get("message", ""),
            "fields": {k: v for k, v in rec.items()
                       if k not in ("mono", "time", "kind", "severity",
                                    "message")},
        })

    # -------------------------------------------------------------- queries
    def entries(self, n: Optional[int] = None, kind: Optional[str] = None):
        evs = list(self._ring)
        if kind is not None:
            evs = [e for e in evs if e["kind"] == kind]
        return evs[-n:] if n else evs

    def __len__(self):
        return len(self._ring)

    def clear(self):
        self._ring.clear()

    # ----------------------------------------------------------------- dump
    def _rank(self) -> int:
        if self.rank is not None:
            return self.rank
        try:
            from ..distributed.env import get_rank

            return int(get_rank())
        except Exception:
            return int(os.environ.get("RANK", 0))

    def _dump_dir(self) -> str:
        d = (self.dump_dir
             or str(_flag("FLAGS_flight_recorder_dir", "") or "")
             or os.path.join(tempfile.gettempdir(), "paddle_tpu_flightrec"))
        os.makedirs(d, exist_ok=True)
        return d

    def dump(self, reason: str, path: Optional[str] = None,
             auto: bool = False) -> Optional[str]:
        """Write the ring (oldest→newest) to a postmortem JSON; returns the
        path (None when recording is disabled or the auto-dump budget is
        spent). Never raises — a postmortem writer that can take down the
        process it is documenting is worse than none."""
        if not self.enabled:
            return None
        with self._dump_lock:
            if auto and len(self.dumps) >= _MAX_AUTO_DUMPS:
                return None
            self._seq += 1
            seq = self._seq
            entries = list(self._ring)
        rank = self._rank()
        try:
            if path is None:
                path = os.path.join(
                    self._dump_dir(),
                    f"flightrec_rank{rank}_{os.getpid()}_{seq:03d}.json")
            rec = {
                "reason": str(reason),
                "time": time.time(),
                "mono": time.monotonic(),
                "rank": rank,
                "pid": os.getpid(),
                "capacity": self.capacity,
                "n_entries": len(entries),
                "entries": entries,
            }
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(rec, f, indent=1, default=str)
            os.replace(tmp, path)
        except Exception:
            return None
        self.dumps.append({"reason": str(reason), "path": path,
                           "time": rec["time"]})
        return path


# ---------------------------------------------------------------------------
# the process-global, always-on instance
# ---------------------------------------------------------------------------

_recorder: Optional[FlightRecorder] = None
_install_lock = threading.Lock()


def get_flight_recorder() -> FlightRecorder:
    """The global recorder; created (and its span/event sinks installed)
    on first use."""
    global _recorder
    if _recorder is None:
        with _install_lock:
            if _recorder is None:
                _recorder = _install(FlightRecorder())
    return _recorder


def configure_flight_recorder(capacity: Optional[int] = None,
                              dump_dir: Optional[str] = None
                              ) -> FlightRecorder:
    """Replace the global recorder (depth / dump-dir change). The old
    ring's entries are dropped — reconfigure before the interesting part."""
    global _recorder
    with _install_lock:
        old = _recorder
        if old is not None:
            _uninstall(old)
        _recorder = _install(FlightRecorder(capacity=capacity,
                                            dump_dir=dump_dir))
    return _recorder


def _install(rec: FlightRecorder) -> FlightRecorder:
    from .. import profiler as _prof
    from . import events as _events

    _prof.add_span_sink(rec._on_span)
    _events.add_event_sink(rec._on_event)
    return rec


def _uninstall(rec: FlightRecorder):
    from .. import profiler as _prof
    from . import events as _events

    _prof.remove_span_sink(rec._on_span)
    _events.remove_event_sink(rec._on_event)


def dump_flight_recorder(reason: str, auto: bool = True) -> Optional[str]:
    """Escalation-path entry point (HangDetector / NanGuard breaker /
    collective-timeout exhaustion / ReplicaGuard): dump the global ring.
    No-throw; returns the dump path or None."""
    try:
        return get_flight_recorder().dump(reason, auto=auto)
    except Exception:
        return None

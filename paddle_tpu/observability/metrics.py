"""Process-global metrics registry: labelled counters / gauges / histograms.

Reference shape: the new-generation profiler's statistic layer
(platform/profiler/) counts events per kind; production TPU stacks pair that
with a Prometheus-style exposition so comm volume, cache hit rates, and
checkpoint latencies are first-class time series rather than log lines.

Design constraints:
- The eager dispatch hot path (framework/autograd.call_op) increments
  counters on EVERY op, so ``Counter.inc`` must be a plain attribute add —
  no dict lookup, no lock (the GIL makes the += effectively atomic for our
  accounting purposes; exactness under free-threading is not a contract).
- Pure stdlib: this module is imported by framework/autograd at package
  init, so it must not pull jax/numpy or any paddle_tpu subpackage.

API:
    reg = get_registry()
    reg.counter("eager_dispatch_total").inc()
    reg.counter("grad_comm_bytes_total", labels=("codec", "path")).labels(
        codec="bf16", path="eager").inc(249344)
    reg.gauge("bucket_fill_ratio").set(0.93)
    reg.histogram("checkpoint_save_seconds").observe(0.8)
    reg.snapshot()        # plain dict, JSON-safe
    reg.to_prometheus()   # text exposition
    reg.export_jsonl(p)   # one snapshot line appended to a JSONL file
    reg.reset()           # zero everything, keep the schema
"""
from __future__ import annotations

import json
import threading
import time
from typing import Dict, Optional, Sequence, Tuple

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "get_registry",
    "DEFAULT_BUCKETS",
]

# latency-oriented default buckets (seconds): 1ms .. 60s, log-ish spacing
DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 60.0)


def _label_key(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _escape_label_value(v) -> str:
    """Prometheus exposition format 0.0.4 label-value escaping: backslash,
    double-quote, and line-feed must be escaped or the scrape line is
    malformed (a quote in the value would terminate it early)."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _escape_help(h) -> str:
    """HELP text escaping (0.0.4): backslash and line-feed only."""
    return str(h).replace("\\", "\\\\").replace("\n", "\\n")


def _fmt_labels(labels: Dict) -> str:
    """`{k="v",...}` with 0.0.4 escaping; empty string for no labels."""
    if not labels:
        return ""
    return ("{" + ",".join(f'{k}="{_escape_label_value(v)}"'
                           for k, v in sorted(labels.items())) + "}")


def _fmt_exemplar(ex) -> str:
    """OpenMetrics exemplar suffix for a _bucket line: empty string when the
    bucket has none, else ` # {trace_id="..."} value`."""
    if ex is None:
        return ""
    value, trace_id = ex
    return f' # {{trace_id="{_escape_label_value(trace_id)}"}} {value}'


class Counter:
    """Monotonically increasing count. ``inc`` is hot-path cheap."""

    __slots__ = ("value",)
    kind = "counter"

    def __init__(self):
        self.value = 0

    def inc(self, n=1):
        self.value += n

    def reset(self):
        self.value = 0

    def get(self):
        return self.value


class Gauge:
    """A value that goes up and down."""

    __slots__ = ("value",)
    kind = "gauge"

    def __init__(self):
        self.value = 0

    def set(self, v):
        self.value = v

    def inc(self, n=1):
        self.value += n

    def dec(self, n=1):
        self.value -= n

    def reset(self):
        self.value = 0

    def get(self):
        return self.value


class Histogram:
    """Cumulative-bucket histogram (Prometheus semantics): each bucket
    counts observations <= its upper bound; +Inf is implicit (== count)."""

    __slots__ = ("bounds", "bucket_counts", "count", "sum", "min", "max",
                 "exemplars")
    kind = "histogram"

    def __init__(self, buckets: Sequence[float] = DEFAULT_BUCKETS):
        self.bounds = tuple(sorted(float(b) for b in buckets))
        self.reset()

    def reset(self):
        self.bucket_counts = [0] * len(self.bounds)
        self.count = 0
        self.sum = 0.0
        self.min = None
        self.max = None
        # last exemplar per bucket (tightest covering bound; final slot is
        # the implicit +Inf bucket): (observed_value, trace_id) or None
        self.exemplars = [None] * (len(self.bounds) + 1)

    def observe(self, v, exemplar=None):
        """Record one observation. ``exemplar`` (a trace id string) tags the
        tightest bucket covering ``v`` so a scraped p99 bucket links back to
        the concrete request trace that landed there (OpenMetrics-style;
        exemplars stay OUT of typed_snapshot so cross-rank merge is
        unchanged)."""
        v = float(v)
        self.count += 1
        self.sum += v
        self.min = v if self.min is None else min(self.min, v)
        self.max = v if self.max is None else max(self.max, v)
        tight = len(self.bounds)  # +Inf slot unless a finite bound covers v
        for i, b in enumerate(self.bounds):
            if v <= b:
                self.bucket_counts[i] += 1
                if i < tight:
                    tight = i
        if exemplar is not None:
            self.exemplars[tight] = (v, str(exemplar))

    @property
    def mean(self):
        return self.sum / self.count if self.count else 0.0

    def quantile(self, q: float):
        """Estimate the q-quantile (0..1) from the cumulative buckets.

        Prometheus `histogram_quantile` semantics: find the first bucket
        whose cumulative count covers q*count and interpolate linearly
        inside it. Observations beyond the last finite bound live in the
        implicit +Inf bucket, where the best estimate is the observed max.
        The estimate is clamped to the observed [min, max] so a coarse
        bucket layout cannot report a value no observation ever had."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile q must be in [0, 1], got {q}")
        if not self.count:
            return None
        target = q * self.count
        prev_c = 0
        for b, c in zip(self.bounds, self.bucket_counts):
            if c >= target and c > prev_c:
                lo = self._prev_bound(b)
                est = lo + (b - lo) * (target - prev_c) / (c - prev_c)
                break
            prev_c = c
        else:
            est = self.max  # target falls in the +Inf bucket
        if self.min is not None:
            est = max(self.min, min(self.max, est))
        return est

    def _prev_bound(self, bound):
        i = self.bounds.index(bound)
        if i > 0:
            return self.bounds[i - 1]
        # lowest bucket: interpolate from the observed min when we have one
        return self.min if self.min is not None and self.min < bound else 0.0

    def get(self):
        out = {
            "count": self.count,
            "sum": self.sum,
            "mean": self.mean,
            "min": self.min,
            "max": self.max,
            "p50": self.quantile(0.5),
            "p95": self.quantile(0.95),
            "p99": self.quantile(0.99),
            "buckets": {str(b): c
                        for b, c in zip(self.bounds, self.bucket_counts)},
        }
        ex = {}
        for i, e in enumerate(self.exemplars):
            if e is not None:
                bound = (str(self.bounds[i]) if i < len(self.bounds)
                         else "+Inf")
                ex[bound] = {"value": e[0], "trace_id": e[1]}
        if ex:
            out["exemplars"] = ex
        return out


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class _Family:
    """A named metric family, optionally labelled. With ``label_names``,
    ``labels(**kv)`` returns (creating on first use) the child metric for
    that label combination; without, the family IS the single child."""

    def __init__(self, name: str, kind: str, help: str = "",
                 label_names: Sequence[str] = (), **kw):
        self.name = name
        self.kind = kind
        self.help = help
        self.label_names = tuple(label_names)
        self._kw = kw
        self._children: Dict[tuple, object] = {}
        self._lock = threading.Lock()
        if not self.label_names:
            self._children[()] = _KINDS[kind](**kw)

    def labels(self, **kv):
        if set(kv) != set(self.label_names):
            raise ValueError(
                f"metric {self.name!r} expects labels {self.label_names}, "
                f"got {tuple(kv)}")
        key = _label_key(kv)
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.setdefault(key, _KINDS[self.kind](**self._kw))
        return child

    def bind(self, **kv):
        """The raw child metric (this combo's, or the unlabelled one) for
        hot-path use: callers keep the reference and pay a plain attribute
        add per event. reset() mutates children in place, so bound
        references stay live across registry resets."""
        return self.labels(**kv) if self.label_names else self._children[()]

    # unlabelled convenience passthrough
    def _solo(self):
        if self.label_names:
            raise ValueError(
                f"metric {self.name!r} is labelled {self.label_names}; "
                f"use .labels(...)")
        return self._children[()]

    def inc(self, n=1):
        self._solo().inc(n)

    def dec(self, n=1):
        self._solo().dec(n)

    def set(self, v):
        self._solo().set(v)

    def observe(self, v, exemplar=None):
        self._solo().observe(v, exemplar=exemplar)

    def quantile(self, q):
        return self._solo().quantile(q)

    def get(self):
        return self._solo().get()

    @property
    def value(self):
        return self._solo().value

    def reset(self):
        for c in self._children.values():
            c.reset()

    def items(self):
        """[(labels_dict, child), ...] snapshot-ordered."""
        return [(dict(k), c) for k, c in sorted(self._children.items())]


class MetricsRegistry:
    """Named families; idempotent declaration (same name + kind returns the
    existing family, a kind clash raises)."""

    def __init__(self):
        self._families: Dict[str, _Family] = {}
        self._lock = threading.Lock()

    # ------------------------------------------------------------ declare
    def _declare(self, name, kind, help, labels, **kw):
        fam = self._families.get(name)
        if fam is not None:
            return self._check_redeclare(fam, kind, labels)
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = self._families[name] = _Family(
                    name, kind, help=help, label_names=labels, **kw)
                return fam
        return self._check_redeclare(fam, kind, labels)

    @staticmethod
    def _check_redeclare(fam, kind, labels):
        """Idempotent re-declaration must actually match: a kind clash OR a
        label-name mismatch raises (silently ignoring differing labels=
        would hand the caller a family whose .labels() rejects every inc)."""
        if fam.kind != kind:
            raise ValueError(
                f"metric {fam.name!r} already registered as {fam.kind}")
        if tuple(labels) != fam.label_names:
            raise ValueError(
                f"metric {fam.name!r} already registered with labels "
                f"{fam.label_names}, re-declared with {tuple(labels)}")
        return fam

    def counter(self, name, help="", labels=()):
        return self._declare(name, "counter", help, labels)

    def gauge(self, name, help="", labels=()):
        return self._declare(name, "gauge", help, labels)

    def histogram(self, name, help="", labels=(), buckets=DEFAULT_BUCKETS):
        return self._declare(name, "histogram", help, labels, buckets=buckets)

    def get(self, name) -> Optional[_Family]:
        return self._families.get(name)

    def names(self):
        return sorted(self._families)

    # ------------------------------------------------------------- export
    def snapshot(self) -> dict:
        """JSON-safe {name: value | {label_str: value}} view. Histograms
        render as their stats dict."""
        out = {}
        for name in sorted(self._families):
            fam = self._families[name]
            if not fam.label_names:
                out[name] = fam.get()
            else:
                out[name] = {
                    ",".join(f"{k}={v}" for k, v in sorted(lbl.items())):
                        child.get()
                    for lbl, child in fam.items()
                }
        return out

    def reset(self):
        for fam in self._families.values():
            fam.reset()

    def to_prometheus(self) -> str:
        """Prometheus text exposition format 0.0.4 (label values and HELP
        text escaped per the spec)."""
        lines = []
        for name in sorted(self._families):
            fam = self._families[name]
            if fam.help:
                lines.append(f"# HELP {name} {_escape_help(fam.help)}")
            lines.append(f"# TYPE {name} {fam.kind}")
            for lbl, child in (fam.items() if fam.label_names
                               else [({}, fam._solo())]):
                sfx = _fmt_labels(lbl)
                if fam.kind == "histogram":
                    # bucket_counts are already cumulative (observe() adds
                    # to every bucket whose bound covers the value); buckets
                    # holding an exemplar render the OpenMetrics-style
                    # ` # {trace_id="..."} value` suffix
                    for i, (b, c) in enumerate(zip(child.bounds,
                                                   child.bucket_counts)):
                        lines.append(f"{name}_bucket"
                                     f"{_fmt_labels(dict(lbl, le=b))} {c}"
                                     f"{_fmt_exemplar(child.exemplars[i])}")
                    lines.append(f"{name}_bucket"
                                 f"{_fmt_labels(dict(lbl, le='+Inf'))} "
                                 f"{child.count}"
                                 f"{_fmt_exemplar(child.exemplars[-1])}")
                    lines.append(f"{name}_sum{sfx} {child.sum}")
                    lines.append(f"{name}_count{sfx} {child.count}")
                else:
                    lines.append(f"{name}{sfx} {child.value}")
        return "\n".join(lines) + "\n"

    def typed_snapshot(self) -> dict:
        """Merge-ready snapshot: unlike snapshot(), keeps the metric KIND
        and the raw per-child state (histograms as bounds + cumulative
        bucket counts), so the cross-rank aggregator (aggregate.py) can
        apply per-kind reduction rules instead of guessing from shapes.

            {name: {"kind", "help", "labels": [...],
                    "children": {label_str: raw_state}}}
        """
        out = {}
        for name in sorted(self._families):
            fam = self._families[name]
            children = {}
            for lbl, child in (fam.items() if fam.label_names
                               else [({}, fam._solo())]):
                key = ",".join(f"{k}={v}" for k, v in sorted(lbl.items()))
                if fam.kind == "histogram":
                    children[key] = {
                        "bounds": list(child.bounds),
                        "bucket_counts": list(child.bucket_counts),
                        "count": child.count, "sum": child.sum,
                        "min": child.min, "max": child.max,
                    }
                else:
                    children[key] = child.value
            out[name] = {"kind": fam.kind, "help": fam.help,
                         "labels": list(fam.label_names),
                         "children": children}
        return out

    def export_jsonl(self, path) -> dict:
        """Append one timestamped snapshot line; returns the record."""
        rec = {"time": time.time(), "metrics": self.snapshot()}
        with open(path, "a") as f:
            f.write(json.dumps(rec) + "\n")
        return rec


_global_registry = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """The process-global registry every built-in subsystem reports into."""
    return _global_registry

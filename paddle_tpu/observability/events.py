"""Structured event log: append-only JSONL with severity + rank tagging.

The metrics registry answers "how many / how long"; the event log answers
"what happened, in what order" — checkpoint commits, NaN-guard trips,
watchdog stalls, collective issues under FLAGS_enable_rpc_profiler. Each
record carries BOTH a wall-clock timestamp (cross-host correlation) and a
monotonic one (correct intervals across NTP steps), plus the process rank so
multi-host logs can be merged and still attributed.

Record shape (one JSON object per line):
    {"time": 1722…, "mono": 123.45, "severity": "info", "kind": "checkpoint",
     "rank": 0, "message": "…", …free-form fields…}

An EventLog keeps a bounded in-memory ring (cheap to query in tests and
tools) and, when constructed with a path, appends each record to the file
as it is logged — append-only, flushed per line, so a crash loses at most
the record being written.
"""
from __future__ import annotations

import json
import os
import threading
import time
import traceback
from collections import deque
from typing import Optional

__all__ = ["EventLog", "SEVERITIES", "get_event_log", "set_event_log",
           "add_event_sink", "remove_event_sink"]

SEVERITIES = ("debug", "info", "warning", "error")

# module-level sinks: called as sink(record_dict) for every record logged on
# ANY EventLog (the flight recorder subscribes here — it must keep seeing
# events even after set_event_log swaps the global instance)
_event_sinks = []


def add_event_sink(sink):
    _event_sinks.append(sink)
    return sink


def remove_event_sink(sink):
    try:
        _event_sinks.remove(sink)
    except ValueError:
        pass


def _current_rank() -> int:
    # lazy: the distributed env must not load (or initialize jax) just
    # because someone logged an event
    try:
        from ..distributed.env import get_rank

        return int(get_rank())
    except Exception:
        return int(os.environ.get("RANK", 0))


class EventLog:
    def __init__(self, path: Optional[str] = None, max_memory: int = 10000,
                 rank: Optional[int] = None):
        self.path = str(path) if path else None
        self.rank = rank
        self._ring = deque(maxlen=max_memory)
        self._lock = threading.Lock()
        self._file = None
        self.dropped = 0
        self.sink_faults = 0                 # broken-sink count (see log())
        self.last_sink_error: Optional[str] = None
        if self.path:
            d = os.path.dirname(self.path)
            if d:
                os.makedirs(d, exist_ok=True)
            self._file = open(self.path, "a")

    # ---------------------------------------------------------------- log
    def log(self, kind: str, message: str = "", severity: str = "info",
            **fields) -> dict:
        if severity not in SEVERITIES:
            raise ValueError(
                f"severity must be one of {SEVERITIES}, got {severity!r}")
        rec = {
            "time": time.time(),
            "mono": time.monotonic(),
            "severity": severity,
            "kind": str(kind),
            "rank": self.rank if self.rank is not None else _current_rank(),
        }
        if message:
            rec["message"] = str(message)
        rec.update(fields)
        with self._lock:
            if len(self._ring) == self._ring.maxlen:
                self.dropped += 1
            self._ring.append(rec)
            if self._file is not None:
                try:
                    self._file.write(json.dumps(rec) + "\n")
                    self._file.flush()
                except (OSError, ValueError):
                    pass  # a full/closed disk must never sink training
        for sink in _event_sinks:
            try:
                sink(rec)
            except Exception:
                # a broken sink must never sink training — but the fault is
                # counted and kept inspectable, not silently dropped: this
                # log IS the recorder of last resort, so it records onto
                # itself rather than recursing through log()
                self.sink_faults += 1
                self.last_sink_error = traceback.format_exc(limit=4)
        return rec

    def debug(self, kind, message="", **fields):
        return self.log(kind, message, severity="debug", **fields)

    def info(self, kind, message="", **fields):
        return self.log(kind, message, severity="info", **fields)

    def warning(self, kind, message="", **fields):
        return self.log(kind, message, severity="warning", **fields)

    def error(self, kind, message="", **fields):
        return self.log(kind, message, severity="error", **fields)

    # -------------------------------------------------------------- query
    def events(self, kind=None, severity=None, min_severity=None):
        with self._lock:
            evs = list(self._ring)
        if kind is not None:
            evs = [e for e in evs if e["kind"] == kind]
        if severity is not None:
            evs = [e for e in evs if e["severity"] == severity]
        if min_severity is not None:
            floor = SEVERITIES.index(min_severity)
            evs = [e for e in evs
                   if SEVERITIES.index(e["severity"]) >= floor]
        return evs

    def tail(self, n=20):
        with self._lock:
            return list(self._ring)[-n:]

    def __len__(self):
        return len(self._ring)

    # ------------------------------------------------------------- export
    def export(self, path):
        """Write the in-memory ring to a fresh JSONL file."""
        with self._lock:
            evs = list(self._ring)
        with open(path, "w") as f:
            for rec in evs:
                f.write(json.dumps(rec) + "\n")
        return path

    def clear(self):
        with self._lock:
            self._ring.clear()
            self.dropped = 0

    def close(self):
        with self._lock:
            if self._file is not None:
                try:
                    self._file.close()
                finally:
                    self._file = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


_global_log = EventLog()


def get_event_log() -> EventLog:
    """The process-global event log the built-in subsystems report into."""
    return _global_log


def set_event_log(log: EventLog) -> EventLog:
    """Swap the global event log (e.g. to attach a file sink); returns the
    previous one so callers can restore it."""
    global _global_log
    prev = _global_log
    _global_log = log
    return prev

"""paddle_tpu.observability — framework-wide telemetry.

Three process-local pillars, wired through every hot subsystem (ISSUE 3
tentpole):

- ``MetricsRegistry`` (metrics.py): process-global labelled counters /
  gauges / histograms with snapshot(), reset(), Prometheus text exposition
  and JSONL export. Fed by framework/autograd (dispatch + trace-cache
  counters), distributed/grad_comm + collective (collectives issued, wire
  bytes per codec, bucket fill ratios), robustness/checkpoint (save/load
  duration histograms, retry counts) and robustness/watchdog (NaN-guard
  trips, heartbeats).
- ``EventLog`` (events.py): append-only structured JSONL with severity,
  monotonic + wall timestamps and rank tagging. The global log collects
  checkpoint commits, NaN trips and watchdog stalls;
  ``FLAGS_enable_rpc_profiler`` additionally streams per-collective events
  into it (the reference's RPC profiler, reinterpreted).
- ``StepTimer`` (step_timer.py): per-step data / forward / backward /
  optimizer / comm / checkpoint breakdown assembled from nested
  RecordEvent spans; ``breakdown_from_trace`` recomputes it offline from a
  chrome trace (tools/trace_report.py).

And the distributed plane on top (ISSUE 6 tentpole):

- ``MetricsAggregator`` (aggregate.py): cross-rank merge of per-rank
  snapshots under per-kind reduction rules (counters sum, gauges
  min/max/mean, histogram buckets add), exchanged through the guarded
  collective layer so PR-4 timeouts/retries/chaos apply; surfaces the
  per-rank step-time spread as the ``step_time_skew`` straggler gauge.
- ``FlightRecorder`` (flight_recorder.py): always-on lock-light bounded
  ring of recent spans, events, and collective-lane launches; dumped to a
  postmortem JSON from every escalation path (HangDetector, NanGuard,
  CollectiveTimeoutError exhaustion, ReplicaGuard).
- ``memory`` (memory.py): live-tensor bytes on the eager path, XLA
  ``memory_analysis`` peaks keyed by trace-cache entry on the compiled
  path, compared against the recorded cost-model rooflines.
- ``TelemetryServer`` (exposition.py): stdlib HTTP endpoint per rank —
  /metrics (Prometheus text), /snapshot (rank-0 aggregate), /events,
  /flightrecorder; ``FLAGS_telemetry_http_port`` turns it on job-wide.
- ``Tracer`` / ``TraceStore`` (tracing.py, ISSUE 18): request-scoped
  tracing — a TraceContext minted per ServeRequest (and per train step)
  whose lifecycle spans land in a bounded store served at /traces and in
  the flight-recorder ring; latency histograms carry the trace id as an
  OpenMetrics exemplar, linking a scraped p99 bucket to a concrete trace.

Reference anchor: platform/profiler/'s HostTracer event tree gives the span
stream; this layer adds the aggregated, exportable telemetry the reference
kept in ad-hoc VLOG lines.
"""
from __future__ import annotations

from .aggregate import (  # noqa: F401
    MetricsAggregator, merge_payloads, merge_typed_snapshots, note_step_time,
)
from .events import (  # noqa: F401
    SEVERITIES, EventLog, add_event_sink, get_event_log, remove_event_sink,
    set_event_log,
)
from .exposition import (  # noqa: F401
    TelemetryServer, get_telemetry_server, parse_prometheus_text,
    start_exposition, stop_exposition,
)
from .flight_recorder import (  # noqa: F401
    FlightRecorder, configure_flight_recorder, dump_flight_recorder,
    get_flight_recorder,
)
from .metrics import (  # noqa: F401
    DEFAULT_BUCKETS, Counter, Gauge, Histogram, MetricsRegistry, get_registry,
)
from .step_timer import (  # noqa: F401
    PHASES, StepTimer, breakdown_from_trace, format_breakdown, phase_of,
)
from .tracing import (  # noqa: F401
    Span, TraceContext, TraceStore, Tracer, get_tracer, tracing_enabled,
)

__all__ = [
    "MetricsRegistry", "Counter", "Gauge", "Histogram", "get_registry",
    "DEFAULT_BUCKETS",
    "EventLog", "SEVERITIES", "get_event_log", "set_event_log",
    "add_event_sink", "remove_event_sink",
    "StepTimer", "PHASES", "phase_of", "breakdown_from_trace",
    "format_breakdown",
    "rpc_profiler_enabled", "enable_rpc_event_log",
    "MetricsAggregator", "merge_payloads", "merge_typed_snapshots",
    "note_step_time",
    "FlightRecorder", "get_flight_recorder", "dump_flight_recorder",
    "configure_flight_recorder",
    "TelemetryServer", "start_exposition", "stop_exposition",
    "get_telemetry_server", "parse_prometheus_text",
    "TraceContext", "Span", "TraceStore", "Tracer", "get_tracer",
    "tracing_enabled",
]

# ---------------------------------------------------------------------------
# FLAGS_enable_rpc_profiler compat wiring (framework/flags.py): the reference
# flag turned on per-RPC span collection in the fluid PS path. Here the
# distributed/ps layers have no RPC layer of their own (XLA/PJRT own the
# wire), so the flag is reinterpreted: when on, distributed + ps paths emit
# per-collective / per-push events into the global EventLog.
# ---------------------------------------------------------------------------

_rpc_profiler = {"enabled": False}


def rpc_profiler_enabled() -> bool:
    return _rpc_profiler["enabled"]


def enable_rpc_event_log(enabled: bool = True):
    """Toggle per-collective event logging (FLAGS_enable_rpc_profiler)."""
    _rpc_profiler["enabled"] = bool(enabled)
    return get_event_log()

"""Cross-rank metric aggregation: per-kind reduction to a rank-0 view.

PR 3's registry is strictly process-local — on a 64-chip job there are 64
`collectives_total` counters and nobody sums them. This module makes the
job-wide view a first-class artifact:

- every rank snapshots its registry (``MetricsRegistry.typed_snapshot`` —
  the snapshot keeps each family's KIND so the merge applies the right
  rule) plus its recent step-time stats;
- the payloads are exchanged through the guarded collective layer
  (``distributed/collective.all_gather`` → ``execute_collective``), so the
  PR-4 machinery — group timeouts, transient retries, chaos injection —
  applies to the telemetry exchange exactly as it does to gradient
  traffic. Telemetry must never wedge training: an exchange that exhausts
  its retries degrades to a local-only aggregate, bumps
  ``telemetry_aggregation_failures_total``, and returns;
- rank 0 merges: **counters sum**, **gauges reduce to min/max/mean**,
  **histogram buckets add element-wise** (counts/sums add, min/max merge —
  quantiles of the merged histogram are the job-wide percentiles);
- the per-rank step-time spread is surfaced as the ``step_time_skew``
  straggler gauge: (max - min) / mean of the per-rank mean step seconds.
  A healthy SPMD job sits near 0; a straggling host shows up as a number,
  not as "rank 17 feels slow".

Emulated multi-rank (this repo's single-process test reality) plugs in via
``gather_fn`` exactly like ``ReplicaGuard.reduce_fn`` does.
"""
from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Callable, List, Optional

from .events import get_event_log
from .metrics import get_registry

__all__ = [
    "MetricsAggregator", "merge_payloads", "merge_typed_snapshots",
    "note_step_time", "local_step_stats", "aggregated_to_plain",
]

# ---------------------------------------------------------------------------
# per-rank step-time tracker (fed by hapi's MetricsCallback / training loops;
# read into every aggregation payload so rank 0 can compute the skew gauge)
# ---------------------------------------------------------------------------

_STEP_WINDOW = 64
_step_times = deque(maxlen=_STEP_WINDOW)
_step_lock = threading.Lock()


def note_step_time(seconds: float):
    """Record one training step's wall seconds into the rank-local window
    the aggregation payload reports (bounded; O(1))."""
    with _step_lock:
        _step_times.append(float(seconds))


def local_step_stats() -> dict:
    with _step_lock:
        times = list(_step_times)
    if not times:
        return {"steps": 0, "mean_s": None, "last_s": None}
    return {"steps": len(times), "mean_s": sum(times) / len(times),
            "last_s": times[-1]}


# ---------------------------------------------------------------------------
# merge rules
# ---------------------------------------------------------------------------

def _merge_counter(values: List[float]):
    return sum(values)


def _merge_gauge(values: List[float]):
    vals = [float(v) for v in values]
    return {"min": min(vals), "max": max(vals),
            "mean": sum(vals) / len(vals)}


def _merge_histogram(states: List[dict]) -> dict:
    """Element-wise bucket addition. Ranks declare histograms from the same
    code, so bounds agree by construction; a mismatch (version skew during
    a rolling restart) falls back to count/sum-only so the merge never
    throws inside a telemetry path."""
    base = states[0]
    bounds = list(base["bounds"])
    if all(list(s["bounds"]) == bounds for s in states[1:]):
        bucket_counts = [sum(s["bucket_counts"][i] for s in states)
                         for i in range(len(bounds))]
    else:
        bounds, bucket_counts = [], []
    mins = [s["min"] for s in states if s["min"] is not None]
    maxs = [s["max"] for s in states if s["max"] is not None]
    count = sum(s["count"] for s in states)
    out = {
        "bounds": bounds,
        "bucket_counts": bucket_counts,
        "count": count,
        "sum": sum(s["sum"] for s in states),
        "min": min(mins) if mins else None,
        "max": max(maxs) if maxs else None,
    }
    out["mean"] = out["sum"] / count if count else 0.0
    if bounds and count:
        from .metrics import Histogram

        h = Histogram(buckets=bounds)
        h.bucket_counts = list(bucket_counts)
        h.count, h.sum = count, out["sum"]
        h.min, h.max = out["min"], out["max"]
        out["p50"] = h.quantile(0.5)
        out["p95"] = h.quantile(0.95)
        out["p99"] = h.quantile(0.99)
    return out


_MERGE = {"counter": _merge_counter, "gauge": _merge_gauge,
          "histogram": _merge_histogram}


def merge_typed_snapshots(snapshots: List[dict]) -> dict:
    """Merge per-rank `MetricsRegistry.typed_snapshot()` dicts under the
    per-kind reduction rules. Families/labels missing on some ranks merge
    over the ranks that have them (a late-joining rank must not zero the
    fleet's counters)."""
    merged = {}
    names = sorted({n for s in snapshots for n in s})
    for name in names:
        fams = [s[name] for s in snapshots if name in s]
        kind = fams[0]["kind"]
        rule = _MERGE[kind]
        child_keys = sorted({k for f in fams for k in f["children"]})
        children = {}
        for key in child_keys:
            vals = [f["children"][key] for f in fams if key in f["children"]]
            children[key] = rule(vals)
        merged[name] = {"kind": kind, "help": fams[0]["help"],
                        "labels": fams[0]["labels"], "ranks": len(fams),
                        "children": children}
    return merged


def _skew(step_stats: List[dict]) -> dict:
    means = [s["mean_s"] for s in step_stats if s.get("mean_s")]
    out = {"per_rank_mean_s": means}
    if len(means) >= 1 and sum(means):
        mean = sum(means) / len(means)
        out["skew"] = (max(means) - min(means)) / mean if mean else 0.0
        out["slowest_rank"] = max(range(len(means)), key=means.__getitem__)
    else:
        out["skew"] = 0.0
    return out


def merge_payloads(payloads: List[dict]) -> dict:
    """Merge full per-rank payloads ({"rank", "step_time", "metrics"})
    into the rank-0 aggregate record."""
    merged = {
        "time": time.time(),
        "ranks": sorted(p.get("rank", i) for i, p in enumerate(payloads)),
        "metrics": merge_typed_snapshots([p["metrics"] for p in payloads]),
        "step_time": _skew([p.get("step_time", {}) for p in payloads]),
    }
    return merged


def aggregated_to_plain(merged_metrics: dict) -> dict:
    """Flatten a merged typed snapshot back to the plain snapshot() shape
    (counters/gauges as values, histograms as stats dicts) so existing
    consumers — tools/trace_report.py's joins — read an aggregate exactly
    like a local snapshot. Labelled families keep their {label: value}
    sub-dicts; unlabelled collapse to the bare value."""
    out = {}
    for name, fam in merged_metrics.items():
        children = {}
        for key, v in fam["children"].items():
            if fam["kind"] == "gauge" and isinstance(v, dict):
                children[key] = v["mean"] if v["min"] == v["max"] else v
            else:
                children[key] = v
        out[name] = children.get("", children) if "" in children else children
    return out


# ---------------------------------------------------------------------------
# the aggregator
# ---------------------------------------------------------------------------

_m_aggs = get_registry().counter(
    "telemetry_aggregations_total",
    help="cross-rank metric aggregation rounds completed").bind()
_m_agg_fail = get_registry().counter(
    "telemetry_aggregation_failures_total",
    help="aggregation exchanges that degraded to local-only "
         "(collective timeout/transient exhaustion)").bind()
_m_skew = get_registry().gauge(
    "step_time_skew",
    help="(max - min) / mean of per-rank mean step seconds — straggler "
         "indicator, ~0 on a healthy job")


class MetricsAggregator:
    """Periodic cross-rank aggregation driver.

        agg = MetricsAggregator(group=telemetry_group)
        ...
        record = agg.aggregate()        # rank-0 merged view (or local-only
                                        # degraded record under faults)

    `gather_fn(payload_dict) -> [payload_dict, ...]` overrides the
    exchange — the chaos harness and single-process tests emulate an
    N-rank world with it (mirroring ReplicaGuard.reduce_fn). The default
    exchange serializes the payload to JSON bytes and all_gathers them
    through the guarded collective layer, so group timeouts / retries /
    chaos interposers apply to telemetry like any other traffic.

    `last` always holds the newest aggregate; `aggregate()` never raises
    out of a telemetry exchange — a fault degrades to the local view and
    counts on telemetry_aggregation_failures_total.
    """

    def __init__(self, group=None, gather_fn: Optional[Callable] = None,
                 registry=None):
        self.group = group
        self.gather_fn = gather_fn
        self.registry = registry or get_registry()
        self.last: Optional[dict] = None
        self.failures = 0

    # ---------------------------------------------------------- payloads
    def local_payload(self) -> dict:
        from ..distributed.env import get_rank

        return {"rank": int(get_rank()), "time": time.time(),
                "step_time": local_step_stats(),
                "metrics": self.registry.typed_snapshot()}

    def _default_gather(self, payload: dict) -> List[dict]:
        """JSON-bytes all_gather over the guarded collective layer."""
        import jax.numpy as jnp
        import numpy as np

        from ..distributed import collective as coll
        from ..framework.tensor import Tensor

        raw = json.dumps(payload).encode()
        buf = np.frombuffer(raw, dtype=np.uint8)
        outs = coll.all_gather([], Tensor(jnp.asarray(buf), _internal=True),
                               group=self.group)
        return [json.loads(bytes(np.asarray(o.numpy())).decode())
                for o in outs]

    # --------------------------------------------------------- aggregate
    def aggregate(self) -> dict:
        """One aggregation round. Returns the merged record; on exchange
        failure returns a `degraded: True` local-only record instead of
        raising (telemetry must never take training down with it)."""
        payload = self.local_payload()
        degraded = None
        try:
            gather = self.gather_fn or self._default_gather
            payloads = list(gather(payload)) or [payload]
        except Exception as e:  # CollectiveTimeoutError, transients, ...
            self.failures += 1
            _m_agg_fail.value += 1
            get_event_log().warning(
                "telemetry", "aggregation exchange failed; using local view",
                error=repr(e))
            payloads = [payload]
            degraded = repr(e)
        record = merge_payloads(payloads)
        if degraded is not None:
            record["degraded"] = degraded
        _m_aggs.value += 1
        _m_skew.set(round(record["step_time"].get("skew", 0.0), 6))
        record["step_time_skew"] = record["step_time"].get("skew", 0.0)
        self.last = record
        return record

"""HBM / host memory accounting: the number every ROADMAP item gates on.

Every capacity decision in this codebase — does ZeRO-3 actually shrink the
resident set, does batch 256 fit v5e's 16 GiB, is the KV cache budget real
— reduces to "peak HBM bytes vs the roofline", and until now that number
existed only inside one-off AOT probes. This module makes it a metric:

- **eager path** — ``live_tensor_bytes()`` sums the bytes of every live
  ``jax.Array`` in the process (the eager dispatch path's working set:
  parameters, grads, activations still referenced). ``sample()`` reads it
  plus the PJRT allocator's view (``device.memory_stats()``: bytes_in_use
  / peak_bytes_in_use — TPU only; None on CPU) into the
  ``live_tensor_bytes`` / ``hbm_bytes_in_use`` / ``peak_hbm_bytes``
  gauges.
- **compiled path** — ``analyze_compiled()`` reads XLA's
  ``memory_analysis()`` off a compiled executable (argument + temp +
  output - aliased = the compiler's peak for one invocation) and
  ``record_compiled(entry, ...)`` keys it by trace-cache entry (the
  ``compiled_peak_hbm_bytes{entry=...}`` gauge), so every cached program's
  footprint is inspectable. ``jit.TrainStep.memory_analysis()`` and
  bench.py's ``peak_hbm_bytes_measured`` ride this.
- **rooflines** — ``load_rooflines()`` reads the recorded AOT estimates
  (artifacts/baseline_aot_estimates.json + the bench gpt estimate) and
  ``roofline_compare()`` reports measured/estimate ratios, the
  cross-check tools/trace_report.py prints.

Everything degrades to None/{} rather than raising: memory accounting
must never be the thing that kills a job.
"""
from __future__ import annotations

import json
import os
import threading
from typing import Dict, Optional

from .events import get_event_log
from .metrics import get_registry

__all__ = [
    "live_tensor_bytes", "device_memory_stats", "sample",
    "LiveBytesWatermark", "sample_watermarks",
    "analyze_compiled", "record_compiled", "compiled_memory",
    "load_rooflines", "roofline_compare", "memory_report",
]

_m_live = get_registry().gauge(
    "live_tensor_bytes",
    help="bytes held by live jax arrays (eager-path working set)")
_m_in_use = get_registry().gauge(
    "hbm_bytes_in_use",
    help="device allocator bytes currently in use (PJRT memory_stats; "
         "0 where the backend reports none)")
_m_peak = get_registry().gauge(
    "peak_hbm_bytes",
    help="device allocator peak bytes in use (PJRT memory_stats; 0 where "
         "the backend reports none)")
_m_compiled = get_registry().gauge(
    "compiled_peak_hbm_bytes",
    help="XLA memory_analysis peak for a compiled program",
    labels=("entry",))

_compiled_lock = threading.Lock()
_compiled: Dict[str, dict] = {}     # entry key -> analysis dict


# ---------------------------------------------------------------------------
# live / allocator accounting (eager path)
# ---------------------------------------------------------------------------

def live_tensor_bytes() -> Optional[int]:
    """Total bytes of every live jax.Array in the process — the eager
    dispatch path's resident tensor set. None when jax (or the API) is
    unavailable."""
    try:
        import jax

        return int(sum(a.nbytes for a in jax.live_arrays()))
    except Exception:
        return None


def device_memory_stats(device=None) -> Optional[dict]:
    """PJRT allocator stats for one device ({bytes_in_use,
    peak_bytes_in_use, ...} on TPU; None on backends that don't report)."""
    try:
        import jax

        dev = device if device is not None else jax.local_devices()[0]
        stats = dev.memory_stats()
        return dict(stats) if stats else None
    except Exception:
        return None


def sample(registry=None) -> dict:
    """One accounting sample; updates the gauges and returns the reading.
    Cheap enough for a per-dump cadence (MetricsCallback), too expensive
    for per-op — live_arrays() walks every registered buffer."""
    live = live_tensor_bytes()
    stats = device_memory_stats()
    out = {"live_tensor_bytes": live}
    if live is not None:
        _m_live.set(int(live))
    if stats:
        out["bytes_in_use"] = int(stats.get("bytes_in_use", 0))
        out["peak_bytes_in_use"] = int(stats.get("peak_bytes_in_use", 0))
        _m_in_use.set(out["bytes_in_use"])
        _m_peak.set(out["peak_bytes_in_use"])
    return out


# ---------------------------------------------------------------------------
# live-bytes watermark (ZeRO-3 free-after-use proof, ISSUE 9)
# ---------------------------------------------------------------------------
# Deterministic, thread-free peak tracking: code that transitions tensor
# lifetimes (the stage-3 store's gather/free points) calls
# sample_watermarks() at each transition, so any active watermark sees the
# peak at exactly the moments live bytes can change. A poller would race
# the transitions and under-read the peak.

_watermark_lock = threading.Lock()
_active_watermarks = []


class LiveBytesWatermark:
    """Peak live-jax-bytes over a window.

        with LiveBytesWatermark() as wm:
            model(x)                   # stage-3 hooks sample at gather/free
        assert wm.delta <= 2 * bucket_bytes + slack

    ``baseline`` is the live-byte reading at entry, ``peak`` the maximum
    seen by any sample() during the window (entry and exit are sampled
    too), ``delta`` the watermark above baseline — for a sharded-at-rest
    model, the bytes the gathered full parameters (plus activations)
    transiently added."""

    def __init__(self):
        self.baseline = 0
        self.peak = 0
        self.n_samples = 0

    def sample(self):
        v = live_tensor_bytes()
        if v is not None:
            self.peak = max(self.peak, int(v))
            self.n_samples += 1
        return v

    @property
    def delta(self) -> int:
        return max(0, self.peak - self.baseline)

    def __enter__(self):
        self.baseline = int(live_tensor_bytes() or 0)
        self.peak = self.baseline
        self.n_samples = 0
        with _watermark_lock:
            _active_watermarks.append(self)
        return self

    def __exit__(self, *exc):
        with _watermark_lock:
            if self in _active_watermarks:
                _active_watermarks.remove(self)
        self.sample()
        return False


def sample_watermarks():
    """Feed every active LiveBytesWatermark one reading — called by code
    that just changed tensor lifetimes (stage-3 gather/free). Free when no
    watermark is active."""
    with _watermark_lock:
        if not _active_watermarks:
            return
        active = list(_active_watermarks)
    for wm in active:
        wm.sample()


# ---------------------------------------------------------------------------
# compiled-path accounting (XLA memory_analysis, keyed by cache entry)
# ---------------------------------------------------------------------------

def analyze_compiled(compiled) -> Optional[dict]:
    """XLA's memory analysis of one compiled executable. Peak =
    arguments + temps + outputs - aliased (donated buffers alias their
    outputs), the same accounting models/gpt.py's AOT estimator uses.
    None when the backend doesn't report."""
    try:
        mem = compiled.memory_analysis()
    except Exception:
        return None
    if mem is None:
        return None
    out = {
        "argument_bytes": int(mem.argument_size_in_bytes),
        "output_bytes": int(mem.output_size_in_bytes),
        "temp_bytes": int(mem.temp_size_in_bytes),
        "alias_bytes": int(mem.alias_size_in_bytes),
        "generated_code_bytes": int(mem.generated_code_size_in_bytes),
    }
    out["peak_hbm_bytes"] = (out["argument_bytes"] + out["temp_bytes"]
                             + out["output_bytes"] - out["alias_bytes"])
    return out


def record_compiled(entry: str, compiled_or_analysis) -> Optional[dict]:
    """Record one trace-cache entry's compiled-path footprint; `entry` is
    the cache key label (e.g. "train_step[...]"). Accepts either a
    compiled executable or an already-built analysis dict. Returns the
    analysis (None if unavailable)."""
    if isinstance(compiled_or_analysis, dict):
        analysis = dict(compiled_or_analysis)
    else:
        analysis = analyze_compiled(compiled_or_analysis)
    if analysis is None:
        return None
    with _compiled_lock:
        _compiled[str(entry)] = analysis
    try:
        _m_compiled.labels(entry=str(entry)).set(
            int(analysis["peak_hbm_bytes"]))
    except (KeyError, TypeError, ValueError) as e:
        # a malformed analysis dict must not break memory recording, but
        # the drop is visible in the event log (rule C003)
        get_event_log().warning("memory", "compiled-peak gauge not set",
                                entry=str(entry), error=repr(e))
    return analysis


def compiled_memory() -> Dict[str, dict]:
    """{entry: analysis} of every recorded compiled program."""
    with _compiled_lock:
        return {k: dict(v) for k, v in _compiled.items()}


# ---------------------------------------------------------------------------
# rooflines
# ---------------------------------------------------------------------------

def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def load_rooflines(path: Optional[str] = None) -> Dict[str, int]:
    """Recorded cost-model peak-HBM estimates, {config_name: bytes}. Reads
    artifacts/baseline_aot_estimates.json (every entry carrying
    peak_hbm_bytes); missing file -> {}."""
    path = path or os.path.join(_repo_root(), "artifacts",
                                "baseline_aot_estimates.json")
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, json.JSONDecodeError):
        return {}
    out = {}
    for name, rec in data.items():
        if isinstance(rec, dict) and rec.get("peak_hbm_bytes"):
            out[name] = int(rec["peak_hbm_bytes"])
    return out


def roofline_compare(measured_bytes: Optional[int],
                     roofline_bytes: Optional[int],
                     name: str = "") -> dict:
    """Measured vs cost-model peak: ratio > 1 means the program uses more
    HBM than the roofline predicted (fragmentation, un-donated buffers);
    far below 1 means the estimate is stale."""
    out = {"name": name, "measured_bytes": measured_bytes,
           "roofline_bytes": roofline_bytes, "ratio": None}
    if measured_bytes and roofline_bytes:
        out["ratio"] = round(measured_bytes / roofline_bytes, 4)
    return out


def memory_report() -> dict:
    """The whole accounting in one dict (trace_report's memory section)."""
    return {
        "sample": sample(),
        "compiled": compiled_memory(),
        "rooflines": load_rooflines(),
    }

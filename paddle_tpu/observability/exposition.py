"""Live telemetry exposition: a stdlib-only HTTP endpoint per rank.

PR 3 made telemetry pull-on-demand from inside the process; a running job
is a black box until someone adds print statements. This module serves the
registry/event-log/flight-recorder over plain HTTP so a human (or a
Prometheus scraper, or the serving runtime's SLO loop) can look at a LIVE
job:

    GET /metrics          Prometheus text exposition (format 0.0.4)
    GET /snapshot         JSON registry snapshot; when an aggregator is
                          attached, the rank-0 cross-rank aggregate
                          (?local=1 forces the local view)
    GET /events?n=100     newest event-log records (JSON)
    GET /flightrecorder   the flight-recorder ring (JSON)
    GET /healthz          liveness probe ("ok")

Enablement: ``TelemetryServer(port).start()`` directly, or set
``FLAGS_telemetry_http_port`` (0 = off, the default) and call
``start_exposition()`` — hapi's MetricsCallback does the latter, so a
`model.fit(...)` with the flag set is scrapeable with zero extra code.
Port 0 binds an ephemeral port (tests); the bound port is on ``.port``.

The server is a daemon ThreadingHTTPServer bound to localhost by default:
telemetry must never block training (handlers only read in-memory state)
and must not expose an unauthenticated port off-host unless explicitly
asked (host="0.0.0.0").

``parse_prometheus_text`` is the STRICT parser the tests scrape through —
it rejects malformed lines (bad escapes, unquoted labels, type clashes),
so exposition bugs fail loudly instead of poisoning a scraper somewhere.
"""
from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

from .events import get_event_log
from .metrics import get_registry

__all__ = ["TelemetryServer", "start_exposition", "stop_exposition",
           "get_telemetry_server", "parse_prometheus_text",
           "register_section", "unregister_section"]

# pluggable JSON sections (path "/<name>"): subsystems register a
# zero-arg provider returning a JSON-safe dict — the serving runtime
# mounts "/serving" while a ReplicaSet is running. A section may also
# carry a subpath provider ("/traces/<id>"): a one-arg callable handed
# the remainder of the path, returning a JSON-safe dict or None (404).
# Read-only, like every other route; provider errors surface as the
# handler's 500 envelope. _state_lock guards this module's mutable
# globals (the section map and the start/stop_exposition _server swap).
_sections: dict = {}
_state_lock = threading.Lock()


def register_section(name: str, provider, subpath_provider=None):
    with _state_lock:
        _sections[name] = (provider, subpath_provider)


def unregister_section(name: str):
    with _state_lock:
        _sections.pop(name, None)


def _known_paths():
    """Every servable path, static routes plus whatever sections are
    registered right now — the single source for /healthz?verbose and the
    404 listing (the old hard-coded five-path list went stale the moment
    the serving runtime mounted "/serving")."""
    with _state_lock:
        dynamic = sorted("/" + s for s in _sections)
    return ["/metrics", "/snapshot", "/events", "/flightrecorder",
            "/healthz"] + dynamic


class _Handler(BaseHTTPRequestHandler):
    server_version = "paddle-tpu-telemetry/1.0"

    # ------------------------------------------------------------ plumbing
    def log_message(self, fmt, *args):  # no stderr chatter per scrape
        pass

    def _send(self, code, body, content_type):
        data = body.encode() if isinstance(body, str) else body
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _json(self, obj, code=200):
        self._send(code, json.dumps(obj, indent=1, default=str),
                   "application/json")

    # ------------------------------------------------------------- routes
    def do_GET(self):
        srv: "TelemetryServer" = self.server._telemetry  # type: ignore
        url = urlparse(self.path)
        q = parse_qs(url.query)
        try:
            if url.path == "/metrics":
                self._send(200, srv.registry.to_prometheus(),
                           "text/plain; version=0.0.4; charset=utf-8")
            elif url.path == "/snapshot":
                self._json(srv.snapshot(local="local" in q))
            elif url.path == "/events":
                n = int(q.get("n", ["100"])[0])
                self._json({"events": srv.event_log().tail(n)})
            elif url.path == "/flightrecorder":
                n = int(q.get("n", ["0"])[0]) or None
                rec = srv.flight_recorder()
                self._json({"capacity": rec.capacity,
                            "n_entries": len(rec),
                            "dumps": rec.dumps,
                            "entries": rec.entries(n)})
            elif url.path == "/healthz":
                # bare probe stays a plain "ok" (liveness contract);
                # ?verbose=1 also lists every live path, dynamically
                # registered sections included
                if "verbose" in q:
                    self._json({"status": "ok", "paths": _known_paths()})
                else:
                    self._send(200, "ok\n", "text/plain")
            elif self._section(url.path):
                pass  # handled (response already sent)
            else:
                self._json({"error": f"unknown path {url.path!r}",
                            "paths": _known_paths()},
                           code=404)
        except Exception as e:  # a handler bug must not kill the server
            self._json({"error": repr(e)}, code=500)

    def _section(self, path: str) -> bool:
        """Dispatch "/<section>" and "/<section>/<sub>" to a registered
        provider. Returns True when the path named a live section (the
        response — 200 or a section-local 404 — has been sent)."""
        parts = path.lstrip("/").split("/", 1)
        with _state_lock:
            entry = _sections.get(parts[0])
        if entry is None:
            return False
        provider, sub_provider = entry
        if len(parts) == 1 or not parts[1]:
            self._json(provider())
            return True
        if sub_provider is None:
            self._json({"error": f"section {parts[0]!r} has no "
                                 f"sub-resources"}, code=404)
            return True
        obj = sub_provider(parts[1])
        if obj is None:
            self._json({"error": f"unknown {parts[0]} id {parts[1]!r}"},
                       code=404)
        else:
            self._json(obj)
        return True


class TelemetryServer:
    """Per-rank telemetry HTTP server (daemon threads; reads only)."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 registry=None, aggregator=None, event_log=None,
                 flight_recorder=None):
        self.host = host
        self.requested_port = int(port)
        self.port: Optional[int] = None
        self.registry = registry or get_registry()
        self.aggregator = aggregator
        self._event_log = event_log
        self._flight_recorder = flight_recorder
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # late-bound so the server always shows the CURRENT global instances
    def event_log(self):
        return self._event_log or get_event_log()

    def flight_recorder(self):
        if self._flight_recorder is not None:
            return self._flight_recorder
        from .flight_recorder import get_flight_recorder

        return get_flight_recorder()

    def snapshot(self, local: bool = False) -> dict:
        if self.aggregator is not None and not local:
            agg = self.aggregator.last or self.aggregator.aggregate()
            return {"aggregated": True, **agg}
        return {"aggregated": False, "metrics": self.registry.snapshot()}

    # ----------------------------------------------------------- lifecycle
    def start(self) -> "TelemetryServer":
        if self._httpd is not None:
            return self
        self._httpd = ThreadingHTTPServer((self.host, self.requested_port),
                                          _Handler)
        self._httpd.daemon_threads = True
        self._httpd._telemetry = self  # type: ignore[attr-defined]
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name=f"telemetry-http-{self.port}")
        self._thread.start()
        get_event_log().info("telemetry", "exposition endpoint up",
                             host=self.host, port=self.port)
        return self

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None

    @property
    def url(self) -> Optional[str]:
        return f"http://{self.host}:{self.port}" if self.port else None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False


_server: Optional[TelemetryServer] = None


def start_exposition(port: Optional[int] = None, aggregator=None,
                     host: str = "127.0.0.1") -> Optional[TelemetryServer]:
    """Start (or return) the global endpoint. `port` defaults to
    FLAGS_telemetry_http_port; 0/unset there means "off" and returns None,
    so callers can wire this unconditionally."""
    global _server
    if _server is not None:
        if aggregator is not None and _server.aggregator is None:
            _server.aggregator = aggregator
        return _server
    if port is None:
        from ..framework.flags import flag

        port = int(flag("FLAGS_telemetry_http_port", 0) or 0)
        if port == 0:
            return None
    srv = TelemetryServer(port=port, host=host,
                          aggregator=aggregator).start()
    with _state_lock:
        _server = srv
    return srv


def stop_exposition():
    global _server
    if _server is not None:
        _server.stop()
        with _state_lock:
            _server = None


def get_telemetry_server() -> Optional[TelemetryServer]:
    return _server


# ---------------------------------------------------------------------------
# strict text-format parser (tests + bench_gate; stdlib only)
# ---------------------------------------------------------------------------

_NAME_RE = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*")
_LINE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?"
    r"\s+(?P<value>[^\s]+)(?:\s+(?P<ts>-?\d+))?$")
_LABEL_RE = re.compile(
    r'(?P<key>[a-zA-Z_][a-zA-Z0-9_]*)="(?P<val>(?:[^"\\]|\\.)*)"(?:,|$)')
# OpenMetrics-style exemplar tail on a sample line:  # {k="v",...} value
# Anchored at end-of-line with the full quoted-label grammar, so a "#"
# inside an (escaped) label value of the sample itself cannot false-match.
_EXEMPLAR_RE = re.compile(
    r' # \{(?P<labels>(?:[a-zA-Z_][a-zA-Z0-9_]*='
    r'"(?:[^"\\]|\\.)*",?)*)\} (?P<value>[^\s]+)$')


def _unescape_label(v: str) -> str:
    out, i = [], 0
    while i < len(v):
        c = v[i]
        if c == "\\":
            if i + 1 >= len(v):
                raise ValueError(f"dangling backslash in label value {v!r}")
            nxt = v[i + 1]
            if nxt == "\\":
                out.append("\\")
            elif nxt == '"':
                out.append('"')
            elif nxt == "n":
                out.append("\n")
            else:
                raise ValueError(f"invalid escape \\{nxt} in {v!r}")
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def parse_prometheus_text(text: str) -> dict:
    """Strictly parse exposition format 0.0.4.

    Returns {family: {"type", "help", "samples": [(name, labels_dict,
    value), ...], "exemplars": [(name, labels_dict, exemplar_labels,
    exemplar_value), ...]}}. Samples stay 3-tuples (existing consumers
    unpack them); exemplar-annotated lines additionally land in the
    family's "exemplars" list. Raises ValueError on any malformed line —
    unparseable sample, bad label escape, malformed exemplar tail, sample
    naming a family whose TYPE was declared differently, non-float value.
    """
    families: dict = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            parts = line[len("# HELP "):].split(" ", 1)
            if not _NAME_RE.fullmatch(parts[0]):
                raise ValueError(f"line {lineno}: bad HELP name {parts[0]!r}")
            families.setdefault(parts[0], {"type": None, "help": None,
                                           "samples": [], "exemplars": []})
            families[parts[0]]["help"] = parts[1] if len(parts) > 1 else ""
            continue
        if line.startswith("# TYPE "):
            parts = line[len("# TYPE "):].split()
            if len(parts) != 2 or parts[1] not in (
                    "counter", "gauge", "histogram", "summary", "untyped"):
                raise ValueError(f"line {lineno}: bad TYPE line {line!r}")
            fam = families.setdefault(parts[0], {"type": None, "help": None,
                                                 "samples": [], "exemplars": []})
            if fam["type"] is not None and fam["type"] != parts[1]:
                raise ValueError(
                    f"line {lineno}: family {parts[0]!r} re-TYPEd "
                    f"{fam['type']} -> {parts[1]}")
            fam["type"] = parts[1]
            continue
        if line.startswith("#"):
            continue  # comment
        # split an exemplar tail (` # {k="v"} value`) off before the sample
        # parse: the sample grammar itself has no "#"
        exemplar = None
        em = _EXEMPLAR_RE.search(line)
        if em is not None:
            ex_labels = _parse_label_block(em.group("labels").rstrip(","),
                                           lineno)
            try:
                ex_value = float(em.group("value"))
            except ValueError:
                raise ValueError(f"line {lineno}: non-numeric exemplar "
                                 f"value {em.group('value')!r}")
            exemplar = (ex_labels, ex_value)
            line = line[:em.start()]
        m = _LINE_RE.match(line)
        if m is None:
            raise ValueError(f"line {lineno}: unparseable sample {line!r}")
        name = m.group("name")
        labels = _parse_label_block(m.group("labels"), lineno)
        try:
            value = float(m.group("value").replace("+Inf", "inf")
                          .replace("-Inf", "-inf"))
        except ValueError:
            raise ValueError(
                f"line {lineno}: non-numeric value {m.group('value')!r}")
        # histogram child samples (<fam>_bucket/_sum/_count) attach to their
        # declared family
        fam_name = name
        for sfx in ("_bucket", "_sum", "_count"):
            base = name[:-len(sfx)] if name.endswith(sfx) else None
            if base and base in families and \
                    families[base]["type"] == "histogram":
                fam_name = base
                break
        fam = families.setdefault(fam_name, {"type": None, "help": None,
                                             "samples": [], "exemplars": []})
        fam["samples"].append((name, labels, value))
        if exemplar is not None:
            fam["exemplars"].append((name, labels) + exemplar)
    return families


def _parse_label_block(raw, lineno: int) -> dict:
    """Strictly parse a `k="v",...` block (sample labels and exemplar
    labels share the grammar). None/empty means no labels."""
    labels: dict = {}
    if not raw:
        return labels
    consumed = 0
    for lm in _LABEL_RE.finditer(raw):
        if lm.start() != consumed:
            raise ValueError(
                f"line {lineno}: malformed label block {raw!r}")
        labels[lm.group("key")] = _unescape_label(lm.group("val"))
        consumed = lm.end()
    if consumed != len(raw):
        raise ValueError(
            f"line {lineno}: trailing junk in label block {raw!r}")
    return labels

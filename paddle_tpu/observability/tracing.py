"""Request-scoped distributed tracing (ISSUE 18).

PR 14's serving runtime and PR 17's fleet controller left a causal gap:
latency histograms say *that* a p99 was missed and the goodput ledger says
*what* a scale event cost, but nothing connects a slow request to the
queue/prefill/decode/eviction/requeue path that produced it. This module
closes that gap with the smallest tracing core that survives the serving
runtime's failure modes:

- ``TraceContext`` — trace_id / per-trace span-id mint / monotonic birth
  timestamp. Minted at ``ServeRequest`` admission (scheduler.submit) and
  carried ON the request, so ``reincarnate()`` after a watchdog eviction
  keeps the same trace across replicas — one timeline per request, not
  one per attempt.
- ``Span`` — name, span_id, parent, monotonic [t_start, t_end), small
  JSON-safe field dict (replica index, token counts, KV adoption, eviction
  reason...). Spans for lifecycle *edges* are recorded complete at the
  point the edge finishes (``record_span``): there is no cross-function
  open-span state to leak when a replica dies mid-step. In-function
  begin/end pairs (``begin_span``/``end_span`` or the ``span()`` context
  manager) are machine-checked closed-on-all-paths by analysis rule F005.
- ``TraceStore`` — bounded (capacity traces, max spans per trace; both
  FLAGS-sized); read-only served at ``/traces`` and ``/traces/<id>`` while
  a ReplicaSet runs.
- Every recorded span is also dropped into the flight-recorder ring
  (kind="trace"), so a postmortem dump interleaves request hops with the
  events/spans the ring already captures.

The link back from metrics: histogram observations pass
``exemplar=ctx.trace_id`` (metrics.Histogram.observe), so a scraped
``serve_request_latency_ms`` p99 bucket names a concrete trace retrievable
at ``/traces/<id>``.

Train side: StepTimer.step() mints a per-step trace and records the phase
breakdown (forward/backward/optimizer/comm/checkpoint/data) as spans, so
train-step phases live on the same timeline store as serve requests.

Everything is gated by ``FLAGS_serving_tracing``; when off, no contexts
are minted and every helper no-ops on ctx=None (serve_bench times the
on/off delta and bench_gate holds it inside the 20% band).
"""
from __future__ import annotations

import itertools
import os
import threading
import time
from collections import OrderedDict
from contextlib import contextmanager
from typing import Dict, Optional

__all__ = [
    "TraceContext", "Span", "TraceStore", "Tracer", "get_tracer",
    "tracing_enabled",
]

_trace_counter = itertools.count(1)


def tracing_enabled() -> bool:
    from ..framework.flags import flag

    return bool(flag("FLAGS_serving_tracing", True))


class TraceContext:
    """One request's (or one train step's) identity on the timeline:
    a trace id plus the mint for span ids within it."""

    __slots__ = ("trace_id", "name", "request_id", "t_start", "_span_ids")

    def __init__(self, trace_id: str, name: str,
                 request_id: Optional[str] = None):
        self.trace_id = trace_id
        self.name = name
        self.request_id = request_id
        self.t_start = time.monotonic()
        self._span_ids = itertools.count(1)

    def next_span_id(self) -> str:
        return f"{self.trace_id}.{next(self._span_ids)}"


class Span:
    """A closed (or closing) interval on a trace's timeline. Timestamps are
    time.monotonic() so ordering survives wall-clock steps."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "t_start",
                 "t_end", "fields")

    def __init__(self, trace_id: str, span_id: str, name: str,
                 parent_id: Optional[str] = None, t_start: float = None,
                 fields: Optional[dict] = None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.t_start = time.monotonic() if t_start is None else t_start
        self.t_end: Optional[float] = None
        self.fields = dict(fields or {})

    @property
    def duration_ms(self) -> Optional[float]:
        if self.t_end is None:
            return None
        return (self.t_end - self.t_start) * 1e3

    def to_dict(self) -> dict:
        return {"span_id": self.span_id, "parent_id": self.parent_id,
                "name": self.name, "t_start": self.t_start,
                "t_end": self.t_end, "duration_ms": self.duration_ms,
                "fields": self.fields}


class TraceStore:
    """Bounded per-request trace store: at most ``capacity`` traces
    (oldest evicted) and ``max_spans`` spans kept per trace (overflow
    counted in ``dropped_spans``, never unbounded memory)."""

    def __init__(self, capacity: int = 256, max_spans: int = 256):
        self.capacity = int(capacity)
        self.max_spans = int(max_spans)
        self.evicted_traces = 0
        self._traces: "OrderedDict[str, dict]" = OrderedDict()
        self._lock = threading.Lock()

    def __len__(self):
        return len(self._traces)

    def open(self, ctx: TraceContext, **fields) -> None:
        if self.capacity <= 0:
            return
        with self._lock:
            rec = self._traces.get(ctx.trace_id)
            if rec is None:
                rec = self._traces[ctx.trace_id] = {
                    "trace_id": ctx.trace_id, "name": ctx.name,
                    "request_id": ctx.request_id, "time": time.time(),
                    "t_start": ctx.t_start, "spans": [],
                    "dropped_spans": 0,
                }
                while len(self._traces) > self.capacity:
                    self._traces.popitem(last=False)
                    self.evicted_traces += 1
            if fields:
                rec.setdefault("fields", {}).update(fields)

    def add(self, span: Span) -> None:
        with self._lock:
            rec = self._traces.get(span.trace_id)
            if rec is None:
                return  # trace evicted (or store disabled): drop quietly
            if len(rec["spans"]) >= self.max_spans:
                rec["dropped_spans"] += 1
                return
            rec["spans"].append(span.to_dict())

    def get(self, trace_id: str) -> Optional[dict]:
        """A JSON-safe copy of one trace, spans in record order."""
        with self._lock:
            rec = self._traces.get(trace_id)
            if rec is None:
                return None
            out = dict(rec)
            out["spans"] = [dict(s) for s in rec["spans"]]
            out["n_spans"] = len(out["spans"])
            return out

    def index(self) -> dict:
        """The /traces listing: per-trace summaries, newest last."""
        with self._lock:
            traces = [{"trace_id": r["trace_id"], "name": r["name"],
                       "request_id": r["request_id"],
                       "n_spans": len(r["spans"]),
                       "dropped_spans": r["dropped_spans"]}
                      for r in self._traces.values()]
        return {"capacity": self.capacity, "max_spans": self.max_spans,
                "n_traces": len(traces),
                "evicted_traces": self.evicted_traces, "traces": traces}

    def clear(self):
        with self._lock:
            self._traces.clear()
            self.evicted_traces = 0


class Tracer:
    """Span recording front-end over a TraceStore + the flight recorder.

    Every helper tolerates ``ctx=None`` (tracing off, or a request minted
    while the flag was off) as a cheap no-op, so call sites never branch
    on the flag themselves."""

    def __init__(self, store: Optional[TraceStore] = None):
        self.store = store if store is not None else TraceStore()

    # ------------------------------------------------------------- minting
    def start_trace(self, name: str, request_id: Optional[str] = None,
                    **fields) -> Optional[TraceContext]:
        if not tracing_enabled():
            return None
        tid = f"t{os.getpid():x}-{next(_trace_counter):06x}"
        ctx = TraceContext(tid, name, request_id=request_id)
        self.store.open(ctx, **fields)
        return ctx

    # ------------------------------------------------------------- records
    def record_span(self, ctx: Optional[TraceContext], name: str,
                    t_start: Optional[float] = None,
                    t_end: Optional[float] = None,
                    **fields) -> Optional[Span]:
        """Record a COMPLETED span in one call — the shape lifecycle edges
        use (queue wait, eviction, requeue...), so a crash between edge
        endpoints can never leak an open span."""
        if ctx is None:
            return None
        now = time.monotonic()
        sp = Span(ctx.trace_id, ctx.next_span_id(), name,
                  t_start=now if t_start is None else t_start,
                  fields=fields)
        sp.t_end = now if t_end is None else t_end
        self._commit(sp)
        return sp

    def begin_span(self, ctx: Optional[TraceContext], name: str,
                   parent_id: Optional[str] = None,
                   **fields) -> Optional[Span]:
        """Open a span; the caller MUST close it with end_span on every
        path (analysis rule F005 proves this on the serving CFGs)."""
        if ctx is None:
            return None
        return Span(ctx.trace_id, ctx.next_span_id(), name,
                    parent_id=parent_id, fields=fields)

    def end_span(self, span: Optional[Span], **fields) -> None:
        if span is None:
            return
        span.t_end = time.monotonic()
        if fields:
            span.fields.update(fields)
        self._commit(span)

    @contextmanager
    def span(self, ctx: Optional[TraceContext], name: str, **fields):
        # bound INSIDE the try so the open's own exception edge still
        # routes through the finally (the F005 proof shape; end_span
        # tolerates None for exactly this window)
        sp = None
        try:
            sp = self.begin_span(ctx, name, **fields)
            yield sp
        finally:
            self.end_span(sp)

    def _commit(self, span: Span) -> None:
        self.store.add(span)
        from .flight_recorder import get_flight_recorder

        get_flight_recorder().note(
            "trace", span.name, trace=span.trace_id, span=span.span_id,
            ms=None if span.duration_ms is None
            else round(span.duration_ms, 3), **span.fields)


_tracer: Optional[Tracer] = None
_tracer_lock = threading.Lock()


def get_tracer() -> Tracer:
    """The process-global tracer; store bounds come from the FLAGS registry
    at first use (reconfigure by replacing the store's limits directly)."""
    global _tracer
    if _tracer is None:
        with _tracer_lock:
            if _tracer is None:
                from ..framework.flags import flag

                _tracer = Tracer(TraceStore(
                    capacity=int(flag("FLAGS_trace_store_capacity", 256)),
                    max_spans=int(flag("FLAGS_trace_max_spans", 256))))
    return _tracer

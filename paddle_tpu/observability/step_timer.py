"""Per-step time breakdown from nested RecordEvent spans.

The profiler answers "which op is slow"; the StepTimer answers the scaling
question EQuARX-style papers start from: of one training step, how much is
data / forward / backward / optimizer / comm / checkpoint? It subscribes to
the profiler's span stream (every RecordEvent end, profiler active or not),
buckets spans into canonical phases by name, and closes a row per step().

    timer = StepTimer().start()
    for batch in loader:
        with RecordEvent("forward"): ...
        with RecordEvent("backward"): ...
        comm.sync(...)              # grad_comm emits its own "comm" span
        with RecordEvent("optimizer"): ...
        timer.step()
    timer.stop()
    timer.report()                  # formatted table; .steps for raw rows

Attribution is by span name (exact phase name, an alias like "fwd", or a
"phase:detail" prefix). Phase times are inclusive — if a phase span nests
inside another phase span the overlap is counted in both and `other` is
clamped at zero; the built-in instrumentation emits phases as siblings, so
in practice rows add up.

`breakdown_from_trace` computes the same rows offline from an exported
chrome trace (tools/trace_report.py): spans named "step" delimit windows,
phase spans inside each window fill the row.
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence

__all__ = ["StepTimer", "PHASES", "phase_of", "breakdown_from_trace",
           "format_breakdown"]

PHASES = ("data", "forward", "backward", "optimizer", "comm", "checkpoint")

_ALIASES = {
    "fwd": "forward",
    "bwd": "backward",
    "opt": "optimizer",
    "optimizer_step": "optimizer",
    "dataloader": "data",
    "all_reduce": "comm",
    "allreduce": "comm",
    "reduce_scatter": "comm",
    "all_gather": "comm",
    "grad_comm": "comm",
    "save": "checkpoint",
    "ckpt": "checkpoint",
}


def phase_of(name: str, phases: Sequence[str] = PHASES) -> Optional[str]:
    """Canonical phase for a span name, or None if it isn't a phase span."""
    base = name.split(":", 1)[0].split("/", 1)[0]
    if base in phases:
        return base
    alias = _ALIASES.get(base)
    return alias if alias in phases else None


class StepTimer:
    def __init__(self, phases: Sequence[str] = PHASES, registry=None):
        self.phases = tuple(phases)
        self.steps: List[dict] = []     # one closed row per step()
        self._current: Dict[str, float] = {}
        self._step_t0 = None
        self._active = False
        self._registry = registry       # optional MetricsRegistry mirror

    # ---------------------------------------------------------- lifecycle
    def start(self):
        from .. import profiler as _prof

        if not self._active:
            _prof.add_span_sink(self._on_span)
            self._active = True
        self._current = {}
        self._step_t0 = time.perf_counter()
        return self

    def stop(self):
        from .. import profiler as _prof

        if self._active:
            _prof.remove_span_sink(self._on_span)
            self._active = False
        return self

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # -------------------------------------------------------------- spans
    def _on_span(self, name, start_ns, end_ns, tid):
        ph = phase_of(name, self.phases)
        if ph is not None:
            self._current[ph] = self._current.get(ph, 0.0) + \
                (end_ns - start_ns) / 1e9

    def step(self) -> dict:
        """Close the current step: record its phase row and reset."""
        now = time.perf_counter()
        wall = now - self._step_t0 if self._step_t0 is not None else 0.0
        row = {ph: self._current.get(ph, 0.0) for ph in self.phases}
        row["total"] = wall
        row["other"] = max(0.0, wall - sum(self._current.values()))
        self.steps.append(row)
        if self._registry is not None:
            h = self._registry.histogram("step_time_seconds",
                                         help="wall time per training step")
            h.observe(wall)
        self._trace_step(row, step_index=len(self.steps) - 1)
        self._current = {}
        self._step_t0 = now
        return row

    def _trace_step(self, row: dict, step_index: int):
        """Mint a per-step trace so checkpoint/comm/optimizer phases share
        the timeline store (and /traces endpoint) with serve requests.
        Phase spans carry the measured duration; their t_start is
        back-computed from the step-close instant (the profiler sink only
        hands us durations), so within a step they overlap — readers
        should order by span_id, not t_start."""
        from .tracing import get_tracer

        tracer = get_tracer()
        ctx = tracer.start_trace("train_step", step=step_index)
        if ctx is None:
            return
        now = time.monotonic()
        tracer.record_span(ctx, "step", t_start=now - row["total"],
                           t_end=now, step=step_index)
        for ph in tuple(self.phases) + ("other",):
            sec = row.get(ph, 0.0)
            if sec > 0.0:
                tracer.record_span(ctx, ph, t_start=now - sec, t_end=now,
                                   step=step_index)

    # ------------------------------------------------------------ reports
    def breakdown(self) -> dict:
        """Aggregate over recorded steps: per-phase total/mean/share."""
        return aggregate_rows(self.steps, self.phases)

    def report(self) -> str:
        return format_breakdown(self.breakdown())


def aggregate_rows(rows: List[dict], phases: Sequence[str] = PHASES) -> dict:
    n = len(rows)
    total = sum(r.get("total", 0.0) for r in rows)
    out = {"steps": n, "total_seconds": total, "phases": {}}
    for ph in tuple(phases) + ("other",):
        tot = sum(r.get(ph, 0.0) for r in rows)
        out["phases"][ph] = {
            "seconds": tot,
            "mean_seconds": tot / n if n else 0.0,
            "share": tot / total if total else 0.0,
        }
    return out


def format_breakdown(agg: dict, extra: Optional[Dict[str, Dict]] = None) -> str:
    """Render an aggregate as the step-time-breakdown table.

    `extra` optionally maps phase -> {column: value} for joined columns
    (e.g. comm collectives/bytes from the metrics registry)."""
    lines = [f"{'phase':<12}{'total_ms':>12}{'ms/step':>12}{'share':>9}"]
    for ph, row in agg["phases"].items():
        line = (f"{ph:<12}{row['seconds'] * 1e3:>12.2f}"
                f"{row['mean_seconds'] * 1e3:>12.2f}"
                f"{row['share'] * 100:>8.1f}%")
        for k, v in (extra or {}).get(ph, {}).items():
            line += f"  {k}={v}"
        lines.append(line)
    per_step = (agg["total_seconds"] / agg["steps"] * 1e3
                if agg["steps"] else 0.0)
    lines.append(f"{'step total':<12}{agg['total_seconds'] * 1e3:>12.2f}"
                 f"{per_step:>12.2f}"
                 f"{100.0:>8.1f}%  ({agg['steps']} steps)")
    return "\n".join(lines)


def breakdown_from_trace(trace: dict, phases: Sequence[str] = PHASES) -> dict:
    """Recompute per-step rows from an exported chrome trace.

    Spans named "step" (emitted by instrumented training loops) delimit the
    windows; phase-named spans inside each window fill the row. Without
    "step" spans the whole trace is one window.
    """
    events = trace.get("traceEvents", trace if isinstance(trace, list) else [])
    spans = [e for e in events if e.get("ph") == "X"]
    step_spans = sorted((e for e in spans if e.get("name") == "step"),
                        key=lambda e: e["ts"])
    if not step_spans:
        t0 = min((e["ts"] for e in spans), default=0.0)
        t1 = max((e["ts"] + e.get("dur", 0.0) for e in spans), default=0.0)
        step_spans = [{"ts": t0, "dur": t1 - t0}]
    rows = []
    for s in step_spans:
        w0, w1 = s["ts"], s["ts"] + s.get("dur", 0.0)
        row = {ph: 0.0 for ph in phases}
        for e in spans:
            ph = phase_of(e.get("name", ""), phases)
            if ph is None:
                continue
            mid = e["ts"] + e.get("dur", 0.0) / 2.0
            if w0 <= mid <= w1:
                row[ph] += e.get("dur", 0.0) / 1e6   # chrome ts/dur are us
        row["total"] = (w1 - w0) / 1e6
        row["other"] = max(0.0, row["total"] - sum(row[ph] for ph in phases))
        rows.append(row)
    return aggregate_rows(rows, phases)

"""BERT tokenization — BasicTokenizer + WordPieceTokenizer + BertTokenizer
and the in-graph `faster_tokenizer` entry.

Reference: paddle/fluid/operators/string/faster_tokenizer_op.h (the C++
BasicTokenizer:48 / WordPieceTokenizer:57 / BertTokenizer:71 used by the
faster_tokenizer op for in-graph serving tokenization). Host-side here —
strings never belong on a TPU; the op form hands ready id tensors to the
compiled program, which is exactly what the reference kernel produces.
"""
from __future__ import annotations

import unicodedata
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["BasicTokenizer", "WordPieceTokenizer", "BertTokenizer",
           "faster_tokenizer"]


def _is_punct(ch: str) -> bool:
    cp = ord(ch)
    if (33 <= cp <= 47 or 58 <= cp <= 64 or 91 <= cp <= 96
            or 123 <= cp <= 126):
        return True
    return unicodedata.category(ch).startswith("P")


def _is_chinese_char(cp: int) -> bool:
    return (0x4E00 <= cp <= 0x9FFF or 0x3400 <= cp <= 0x4DBF
            or 0x20000 <= cp <= 0x2A6DF or 0x2A700 <= cp <= 0x2B73F
            or 0x2B740 <= cp <= 0x2B81F or 0x2B820 <= cp <= 0x2CEAF
            or 0xF900 <= cp <= 0xFAFF or 0x2F800 <= cp <= 0x2FA1F)


class BasicTokenizer:
    """Whitespace/punctuation/CJK splitting + optional lowercasing with
    accent stripping (reference BasicTokenizer)."""

    def __init__(self, do_lower_case: bool = True):
        self.do_lower_case = do_lower_case

    def tokenize(self, text: str) -> List[str]:
        out: List[str] = []
        buf: List[str] = []

        def flush():
            if buf:
                out.append("".join(buf))
                buf.clear()

        for ch in text:
            cp = ord(ch)
            if ch in ("\t", "\n", "\r") or ch.isspace():
                # \t\n\r are category Cc but are WHITESPACE in the BERT
                # cleaner — they must split tokens, not vanish
                flush()
                continue
            if cp == 0 or cp == 0xFFFD or unicodedata.category(ch) in (
                    "Cc", "Cf"):
                continue
            if _is_chinese_char(cp):
                flush()
                out.append(ch)
                continue
            if _is_punct(ch):
                flush()
                out.append(ch)
                continue
            buf.append(ch)
        flush()
        if self.do_lower_case:
            out = [self._lower(t) for t in out]
        return out

    @staticmethod
    def _lower(token: str) -> str:
        token = token.lower()
        token = unicodedata.normalize("NFD", token)
        return "".join(c for c in token
                       if unicodedata.category(c) != "Mn")


class WordPieceTokenizer:
    """Greedy longest-match-first subword split over a vocab
    (reference WordPieceTokenizer; '##' continuation prefix)."""

    def __init__(self, vocab: Dict[str, int], unk_token: str = "[UNK]",
                 max_input_chars_per_word: int = 100):
        self.vocab = vocab
        self.unk_token = unk_token
        self.max_chars = max_input_chars_per_word

    def tokenize(self, token: str) -> List[str]:
        if len(token) > self.max_chars:
            return [self.unk_token]
        pieces: List[str] = []
        start = 0
        while start < len(token):
            end = len(token)
            piece = None
            while start < end:
                sub = token[start:end]
                if start > 0:
                    sub = "##" + sub
                if sub in self.vocab:
                    piece = sub
                    break
                end -= 1
            if piece is None:
                return [self.unk_token]
            pieces.append(piece)
            start = end
        return pieces


class BertTokenizer:
    """Full BERT tokenization pipeline (reference BertTokenizer):
    basic split → WordPiece → [CLS] ids [SEP] (+ pair), padding/truncation.

    `vocab` is a dict or a vocab-file path (one token per line)."""

    def __init__(self, vocab, do_lower_case: bool = True,
                 unk_token: str = "[UNK]", pad_token: str = "[PAD]",
                 cls_token: str = "[CLS]", sep_token: str = "[SEP]",
                 mask_token: str = "[MASK]"):
        if isinstance(vocab, (str, bytes)):
            with open(vocab, encoding="utf-8") as f:
                vocab = {ln.rstrip("\n"): i for i, ln in enumerate(f)}
        self.vocab: Dict[str, int] = dict(vocab)
        self.inv_vocab = {i: t for t, i in self.vocab.items()}
        self.basic = BasicTokenizer(do_lower_case)
        self.wordpiece = WordPieceTokenizer(self.vocab, unk_token)
        self.unk_token = unk_token
        self.pad_token = pad_token
        self.cls_token = cls_token
        self.sep_token = sep_token
        self.mask_token = mask_token

    @property
    def pad_token_id(self) -> int:
        return self.vocab.get(self.pad_token, 0)

    def vocab_size(self) -> int:
        return len(self.vocab)

    def tokenize(self, text: str) -> List[str]:
        out: List[str] = []
        for tok in self.basic.tokenize(text):
            out.extend(self.wordpiece.tokenize(tok))
        return out

    def convert_tokens_to_ids(self, tokens: Sequence[str]) -> List[int]:
        unk = self.vocab.get(self.unk_token, 0)
        return [self.vocab.get(t, unk) for t in tokens]

    def _to_ids(self, text, is_split_into_words):
        if is_split_into_words:
            # pre-split input: per-word basic cleaning (lowercase/accent
            # strip, as the full pipeline would) then wordpiece
            pieces: List[str] = []
            for w in text:
                if self.basic.do_lower_case:
                    w = self.basic._lower(w)
                pieces.extend(self.wordpiece.tokenize(w))
            return self.convert_tokens_to_ids(pieces)
        return self.convert_tokens_to_ids(self.tokenize(text))

    def encode(self, text: str, text_pair: Optional[str] = None,
               max_seq_len: int = 0, pad_to_max_seq_len: bool = False,
               is_split_into_words: bool = False) -> Dict[str, List[int]]:
        """→ {'input_ids', 'token_type_ids'} (reference Encode)."""
        ids_a = self._to_ids(text, is_split_into_words)
        ids_b = (self._to_ids(text_pair, is_split_into_words)
                 if text_pair is not None else None)
        cls = self.vocab.get(self.cls_token, 0)
        sep = self.vocab.get(self.sep_token, 0)
        if max_seq_len:
            # reserve special tokens: 2 for single, 3 for pairs
            overhead = 3 if ids_b is not None else 2
            if max_seq_len < overhead:
                raise ValueError(
                    f"max_seq_len={max_seq_len} cannot fit the {overhead} "
                    "special tokens")
            budget = max_seq_len - overhead
            if ids_b is not None:
                # longest-first truncation (reference behavior)
                while len(ids_a) + len(ids_b) > budget:
                    (ids_a if len(ids_a) >= len(ids_b) else ids_b).pop()
            else:
                ids_a = ids_a[:budget]
        input_ids = [cls] + ids_a + [sep]
        token_type = [0] * len(input_ids)
        if ids_b is not None:
            input_ids += ids_b + [sep]
            token_type += [1] * (len(ids_b) + 1)
        if max_seq_len and pad_to_max_seq_len:
            pad = self.pad_token_id
            while len(input_ids) < max_seq_len:
                input_ids.append(pad)
                token_type.append(0)
        return {"input_ids": input_ids, "token_type_ids": token_type}

    def batch_encode(self, texts: Sequence[str],
                     text_pairs: Optional[Sequence[str]] = None,
                     max_seq_len: int = 0,
                     pad_to_max_seq_len: bool = False,
                     is_split_into_words: bool = False):
        if text_pairs is not None and len(text_pairs) != len(texts):
            raise ValueError(
                f"text_pairs has {len(text_pairs)} entries for "
                f"{len(texts)} texts")
        pairs = text_pairs if text_pairs is not None else [None] * len(texts)
        return [self.encode(t, p, max_seq_len, pad_to_max_seq_len,
                            is_split_into_words)
                for t, p in zip(texts, pairs)]


def faster_tokenizer(text, vocab, text_pair=None, do_lower_case=True,
                     max_seq_len=128, pad_to_max_seq_len=True,
                     is_split_into_words=False):
    """Op-form tokenization (reference: faster_tokenizer_op.cc): a batch of
    strings → (input_ids, token_type_ids) int64 Tensors ready to feed the
    compiled model — the serving-side entry the reference fuses into its
    inference program."""
    import numpy as np

    from ..framework.tensor import to_tensor

    tok = vocab if isinstance(vocab, BertTokenizer) else BertTokenizer(
        vocab, do_lower_case=do_lower_case)
    single = isinstance(text, str) or (
        is_split_into_words and text and isinstance(text[0], str))
    texts = [text] if single else list(text)
    if text_pair is None:
        pairs = None
    elif single:
        pairs = [text_pair]  # one sample → one pair, whatever its type
    else:
        pairs = [text_pair] if isinstance(text_pair, str) else list(text_pair)
    enc = tok.batch_encode(texts, pairs, max_seq_len=max_seq_len,
                           pad_to_max_seq_len=pad_to_max_seq_len,
                           is_split_into_words=is_split_into_words)
    width = max(len(e["input_ids"]) for e in enc)
    pad = tok.pad_token_id
    ids = np.full((len(enc), width), pad, np.int64)
    tt = np.zeros((len(enc), width), np.int64)
    for i, e in enumerate(enc):
        ids[i, :len(e["input_ids"])] = e["input_ids"]
        tt[i, :len(e["token_type_ids"])] = e["token_type_ids"]
    return to_tensor(ids), to_tensor(tt)

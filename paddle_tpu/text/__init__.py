"""paddle.text — text datasets + viterbi decode.

Parity: python/paddle/text/ (Imdb/Imikolov/Movielens/UCIHousing/WMT14/WMT16
datasets, viterbi_decode op). As with vision, no network egress: datasets
parse the standard on-disk formats from user paths.
"""
from __future__ import annotations

import gzip
import os
import re
import tarfile

import numpy as np

from ..io import Dataset

__all__ = ["Imdb", "Imikolov", "UCIHousing", "ViterbiDecoder",
           "viterbi_decode"]

_NO_DOWNLOAD = ("automatic download is unavailable; pass data_file pointing "
                "at a local copy of the standard dataset archive")


class UCIHousing(Dataset):
    """UCI Boston housing (text/datasets/uci_housing.py): whitespace table of
    13 features + price, feature-normalized."""

    def __init__(self, data_file=None, mode="train", download=True):
        if data_file is None:
            raise ValueError(_NO_DOWNLOAD)
        self.mode = mode.lower()
        raw = np.loadtxt(data_file, dtype="float32")
        raw = raw.reshape(-1, 14)
        maxs, mins, avgs = raw.max(0), raw.min(0), raw.mean(0)
        span = np.maximum(maxs - mins, 1e-6)
        feats = (raw[:, :13] - avgs[:13]) / span[:13]
        n_train = int(len(raw) * 0.8)
        if self.mode == "train":
            self.data = feats[:n_train]
            self.label = raw[:n_train, 13:]
        else:
            self.data = feats[n_train:]
            self.label = raw[n_train:, 13:]

    def __getitem__(self, idx):
        return self.data[idx], self.label[idx]

    def __len__(self):
        return len(self.data)


class Imdb(Dataset):
    """IMDB sentiment (text/datasets/imdb.py): aclImdb tar with pos/neg
    review text files; builds a frequency-cutoff word dict."""

    def __init__(self, data_file=None, mode="train", cutoff=150,
                 download=True):
        if data_file is None:
            raise ValueError(_NO_DOWNLOAD)
        self.mode = mode.lower()
        pat = re.compile(rf"aclImdb/{self.mode}/(pos|neg)/.*\.txt$")
        docs, labels = [], []
        with tarfile.open(data_file, "r:*") as tf:
            names = [n for n in tf.getnames() if pat.match(n)]
            for name in sorted(names):
                text = tf.extractfile(name).read().decode(
                    "utf-8", errors="ignore").lower()
                docs.append(re.findall(r"[a-z]+", text))
                labels.append(0 if "/pos/" in name else 1)
        freq: dict = {}
        for doc in docs:
            for w in doc:
                freq[w] = freq.get(w, 0) + 1
        items = sorted(((-c, w) for w, c in freq.items() if c >= 0))
        self.word_idx = {w: i for i, (_, w) in enumerate(items)}
        self.word_idx["<unk>"] = len(self.word_idx)
        unk = self.word_idx["<unk>"]
        self.docs = [np.array([self.word_idx.get(w, unk) for w in d],
                              dtype="int64") for d in docs]
        self.labels = np.array(labels, dtype="int64")

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]

    def __len__(self):
        return len(self.docs)


class Imikolov(Dataset):
    """PTB n-gram dataset (text/datasets/imikolov.py)."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", min_word_freq=50, download=True):
        if data_file is None:
            raise ValueError(_NO_DOWNLOAD)
        self.window_size = window_size
        name = {"train": "ptb.train.txt", "test": "ptb.valid.txt"}[mode]
        with tarfile.open(data_file, "r:*") as tf:
            member = [n for n in tf.getnames() if n.endswith(name)][0]
            text = tf.extractfile(member).read().decode("utf-8")
        lines = [ln.strip().split() for ln in text.strip().split("\n")]
        freq: dict = {}
        for ln in lines:
            for w in ln:
                freq[w] = freq.get(w, 0) + 1
        vocab = {w for w, c in freq.items() if c >= min_word_freq}
        self.word_idx = {w: i for i, w in enumerate(sorted(vocab))}
        self.word_idx.setdefault("<unk>", len(self.word_idx))
        unk = self.word_idx["<unk>"]
        self.data = []
        for ln in lines:
            ids = [self.word_idx.get(w, unk) for w in ln]
            for i in range(len(ids) - window_size + 1):
                self.data.append(np.array(ids[i:i + window_size], "int64"))

    def __getitem__(self, idx):
        return self.data[idx]

    def __len__(self):
        return len(self.data)


# --------------------------------------------------------------------------
# Viterbi decode (reference: operators/viterbi_decode_op.* / paddle.text)
# --------------------------------------------------------------------------

def viterbi_decode(potentials, transition_params, lengths=None,
                   include_bos_eos_tag=True, name=None):
    """Batched Viterbi decode over emission potentials [B, T, N] with
    transition matrix [N, N] (or [N+2, N+2] with BOS/EOS). lax.scan keeps the
    DP loop compiler-friendly."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from ..framework.autograd import call_op as op_call
    from ..framework.tensor import Tensor

    def kernel(pot, trans, lens):
        B, T, N = pot.shape
        if include_bos_eos_tag:
            # trans is [N+2, N+2]; tags N=BOS, N+1=EOS per the reference
            bos, eos = N, N + 1
            init = pot[:, 0] + trans[bos, :N][None, :]
            core = trans[:N, :N]
        else:
            init = pot[:, 0]
            core = trans

        def step(carry, emit_t):
            alpha, t_idx = carry
            scores = alpha[:, :, None] + core[None]  # (B, from, to)
            best = scores.max(axis=1) + emit_t
            back = scores.argmax(axis=1)
            if lens is not None:
                live = (t_idx < lens)[:, None]
                best = jnp.where(live, best, alpha)
                back = jnp.where(live, back,
                                 jnp.arange(N)[None, :].astype(back.dtype))
            return (best, t_idx + 1), back

        (alpha, _), backs = lax.scan(step, (init, jnp.ones((), jnp.int32)),
                                     jnp.swapaxes(pot[:, 1:], 0, 1))
        if include_bos_eos_tag:
            alpha = alpha + trans[:N, eos][None, :]
        last = alpha.argmax(axis=-1)
        score = alpha.max(axis=-1)

        def backtrace(carry, back_t):
            tag = carry
            prev = jnp.take_along_axis(back_t, tag[:, None], 1)[:, 0]
            return prev, prev

        _, path_rev = lax.scan(backtrace, last, backs, reverse=True)
        path = jnp.concatenate([jnp.swapaxes(path_rev, 0, 1),
                                last[:, None]], axis=1)
        return score, path

    args = [potentials, transition_params]
    if lengths is not None:
        return op_call(lambda p, t, l: kernel(p, t, l), potentials,
                       transition_params, lengths, op_name="viterbi_decode")
    return op_call(lambda p, t: kernel(p, t, None), potentials,
                   transition_params, op_name="viterbi_decode")


class ViterbiDecoder:
    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def __call__(self, potentials, lengths=None):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)

"""paddle.text — text datasets + viterbi decode.

Parity: python/paddle/text/ (Imdb/Imikolov/Movielens/UCIHousing/WMT14/WMT16
datasets, viterbi_decode op). As with vision, no network egress: datasets
parse the standard on-disk formats from user paths.
"""
from __future__ import annotations

import gzip
import os
import re
import tarfile

import numpy as np

from ..io import Dataset

__all__ = ["Imdb", "Imikolov", "UCIHousing", "ViterbiDecoder",
           "viterbi_decode"]

_NO_DOWNLOAD = ("automatic download is unavailable; pass data_file pointing "
                "at a local copy of the standard dataset archive")


class UCIHousing(Dataset):
    """UCI Boston housing (text/datasets/uci_housing.py): whitespace table of
    13 features + price, feature-normalized."""

    def __init__(self, data_file=None, mode="train", download=True):
        if data_file is None:
            raise ValueError(_NO_DOWNLOAD)
        self.mode = mode.lower()
        raw = np.loadtxt(data_file, dtype="float32")
        raw = raw.reshape(-1, 14)
        maxs, mins, avgs = raw.max(0), raw.min(0), raw.mean(0)
        span = np.maximum(maxs - mins, 1e-6)
        feats = (raw[:, :13] - avgs[:13]) / span[:13]
        n_train = int(len(raw) * 0.8)
        if self.mode == "train":
            self.data = feats[:n_train]
            self.label = raw[:n_train, 13:]
        else:
            self.data = feats[n_train:]
            self.label = raw[n_train:, 13:]

    def __getitem__(self, idx):
        return self.data[idx], self.label[idx]

    def __len__(self):
        return len(self.data)


class Imdb(Dataset):
    """IMDB sentiment (text/datasets/imdb.py): aclImdb tar with pos/neg
    review text files; builds a frequency-cutoff word dict."""

    def __init__(self, data_file=None, mode="train", cutoff=150,
                 download=True):
        if data_file is None:
            raise ValueError(_NO_DOWNLOAD)
        self.mode = mode.lower()
        pat = re.compile(rf"aclImdb/{self.mode}/(pos|neg)/.*\.txt$")
        docs, labels = [], []
        with tarfile.open(data_file, "r:*") as tf:
            names = [n for n in tf.getnames() if pat.match(n)]
            for name in sorted(names):
                text = tf.extractfile(name).read().decode(
                    "utf-8", errors="ignore").lower()
                docs.append(re.findall(r"[a-z]+", text))
                labels.append(0 if "/pos/" in name else 1)
        freq: dict = {}
        for doc in docs:
            for w in doc:
                freq[w] = freq.get(w, 0) + 1
        items = sorted(((-c, w) for w, c in freq.items() if c >= 0))
        self.word_idx = {w: i for i, (_, w) in enumerate(items)}
        self.word_idx["<unk>"] = len(self.word_idx)
        unk = self.word_idx["<unk>"]
        self.docs = [np.array([self.word_idx.get(w, unk) for w in d],
                              dtype="int64") for d in docs]
        self.labels = np.array(labels, dtype="int64")

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]

    def __len__(self):
        return len(self.docs)


class Imikolov(Dataset):
    """PTB n-gram dataset (text/datasets/imikolov.py)."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", min_word_freq=50, download=True):
        if data_file is None:
            raise ValueError(_NO_DOWNLOAD)
        self.window_size = window_size
        name = {"train": "ptb.train.txt", "test": "ptb.valid.txt"}[mode]
        with tarfile.open(data_file, "r:*") as tf:
            member = [n for n in tf.getnames() if n.endswith(name)][0]
            text = tf.extractfile(member).read().decode("utf-8")
        lines = [ln.strip().split() for ln in text.strip().split("\n")]
        freq: dict = {}
        for ln in lines:
            for w in ln:
                freq[w] = freq.get(w, 0) + 1
        vocab = {w for w, c in freq.items() if c >= min_word_freq}
        self.word_idx = {w: i for i, w in enumerate(sorted(vocab))}
        self.word_idx.setdefault("<unk>", len(self.word_idx))
        unk = self.word_idx["<unk>"]
        self.data = []
        for ln in lines:
            ids = [self.word_idx.get(w, unk) for w in ln]
            for i in range(len(ids) - window_size + 1):
                self.data.append(np.array(ids[i:i + window_size], "int64"))

    def __getitem__(self, idx):
        return self.data[idx]

    def __len__(self):
        return len(self.data)


# --------------------------------------------------------------------------
# Viterbi decode (reference: operators/viterbi_decode_op.* / paddle.text)
# --------------------------------------------------------------------------

def viterbi_decode(potentials, transition_params, lengths=None,
                   include_bos_eos_tag=True, name=None):
    """Batched Viterbi decode over emission potentials [B, T, N] with
    transition matrix [N, N] (or [N+2, N+2] with BOS/EOS). lax.scan keeps the
    DP loop compiler-friendly."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from ..framework.autograd import call_op as op_call
    from ..framework.tensor import Tensor

    def kernel(pot, trans, lens):
        B, T, N = pot.shape
        if include_bos_eos_tag:
            # trans is [N+2, N+2]; tags N=BOS, N+1=EOS per the reference
            bos, eos = N, N + 1
            init = pot[:, 0] + trans[bos, :N][None, :]
            core = trans[:N, :N]
        else:
            init = pot[:, 0]
            core = trans

        def step(carry, emit_t):
            alpha, t_idx = carry
            scores = alpha[:, :, None] + core[None]  # (B, from, to)
            best = scores.max(axis=1) + emit_t
            back = scores.argmax(axis=1)
            if lens is not None:
                live = (t_idx < lens)[:, None]
                best = jnp.where(live, best, alpha)
                back = jnp.where(live, back,
                                 jnp.arange(N)[None, :].astype(back.dtype))
            return (best, t_idx + 1), back

        (alpha, _), backs = lax.scan(step, (init, jnp.ones((), jnp.int32)),
                                     jnp.swapaxes(pot[:, 1:], 0, 1))
        if include_bos_eos_tag:
            alpha = alpha + trans[:N, eos][None, :]
        last = alpha.argmax(axis=-1)
        score = alpha.max(axis=-1)

        def backtrace(carry, back_t):
            tag = carry
            prev = jnp.take_along_axis(back_t, tag[:, None], 1)[:, 0]
            return prev, prev

        _, path_rev = lax.scan(backtrace, last, backs, reverse=True)
        path = jnp.concatenate([jnp.swapaxes(path_rev, 0, 1),
                                last[:, None]], axis=1)
        return score, path

    args = [potentials, transition_params]
    if lengths is not None:
        return op_call(lambda p, t, l: kernel(p, t, l), potentials,
                       transition_params, lengths, op_name="viterbi_decode")
    return op_call(lambda p, t: kernel(p, t, None), potentials,
                   transition_params, op_name="viterbi_decode")


class ViterbiDecoder:
    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def __call__(self, potentials, lengths=None):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)


class Conll05st(Dataset):
    """CoNLL-2005 SRL (text/datasets/conll05.py): parses the test.wsj
    words/props column files into (word_ids, predicate, label_ids) using
    dictionaries built from the data (or given word/verb/target dict
    files)."""

    def __init__(self, data_file=None, word_dict_file=None,
                 verb_dict_file=None, target_dict_file=None, mode="test",
                 download=True):
        if data_file is None:
            raise ValueError(_NO_DOWNLOAD)
        # data_file: directory (or tgz) holding words/ and props/ files
        sentences = self._parse(data_file)
        words = sorted({w for s, _, _ in sentences for w in s})
        labels = sorted({l for _, _, ls in sentences for l in ls})
        verbs = sorted({v for _, v, _ in sentences})
        self.word_dict = (self._load_dict(word_dict_file)
                          if word_dict_file else
                          {w: i for i, w in enumerate(words)})
        self.label_dict = (self._load_dict(target_dict_file)
                           if target_dict_file else
                           {l: i for i, l in enumerate(labels)})
        self.predicate_dict = (self._load_dict(verb_dict_file)
                               if verb_dict_file else
                               {v: i for i, v in enumerate(verbs)})
        unk = len(self.word_dict)
        self.samples = [
            (np.asarray([self.word_dict.get(w, unk) for w in s], np.int64),
             np.asarray([self.predicate_dict.get(v, 0)], np.int64),
             np.asarray([self.label_dict.get(l, 0) for l in ls], np.int64))
            for s, v, ls in sentences]

    @staticmethod
    def _load_dict(path):
        with open(path) as f:
            return {ln.strip().split()[0]: i
                    for i, ln in enumerate(f) if ln.strip()}

    @staticmethod
    def _parse(path):
        """words/props column files → [(tokens, verb, labels)]."""
        import glob as _glob

        if os.path.isdir(path):
            wfiles = sorted(_glob.glob(os.path.join(path, "words", "*")) or
                            _glob.glob(os.path.join(path, "*words*")))
            pfiles = sorted(_glob.glob(os.path.join(path, "props", "*")) or
                            _glob.glob(os.path.join(path, "*props*")))
        else:
            raise ValueError(
                "pass the extracted conll05st directory (words/ + props/)")
        out = []
        for wf, pf in zip(wfiles, pfiles):
            opener = gzip.open if wf.endswith(".gz") else open
            with opener(wf, "rt") as f:
                wlines = f.read().split("\n")
            opener = gzip.open if pf.endswith(".gz") else open
            with opener(pf, "rt") as f:
                plines = f.read().split("\n")
            sent, props = [], []
            for wl, pl in zip(wlines, plines):
                if wl.strip():
                    sent.append(wl.strip())
                    props.append(pl.strip().split())
                elif sent:
                    verb = next((p[0] for p in props if p and p[0] != "-"),
                                "-")
                    labels = [p[-1] if p else "O" for p in props]
                    out.append((sent, verb, labels))
                    sent, props = [], []
            if sent:
                verb = next((p[0] for p in props if p and p[0] != "-"), "-")
                out.append((sent, verb, [p[-1] if p else "O"
                                         for p in props]))
        return out

    def __getitem__(self, idx):
        return self.samples[idx]

    def __len__(self):
        return len(self.samples)


class Movielens(Dataset):
    """MovieLens-1M ratings (text/datasets/movielens.py): ml-1m archive or
    directory with users.dat / movies.dat / ratings.dat ('::' separated).
    Yields (user_id, gender, age, job, movie_id, title_ids, category_ids,
    rating) int64/float arrays like the reference."""

    def __init__(self, data_file=None, mode="train", test_ratio=0.1,
                 rand_seed=0, download=True):
        if data_file is None:
            raise ValueError(_NO_DOWNLOAD)
        root = data_file
        if not os.path.isdir(root):
            raise ValueError("pass the extracted ml-1m directory "
                             "(users.dat / movies.dat / ratings.dat)")
        read = lambda n: open(os.path.join(root, n), encoding="latin-1") \
            .read().strip().split("\n")
        users = {}
        for ln in read("users.dat"):
            uid, gender, age, job, _zip_ = ln.split("::")
            users[int(uid)] = (0 if gender == "M" else 1, int(age), int(job))
        movies, cats, words = {}, {}, {}
        for ln in read("movies.dat"):
            mid, title, genres = ln.split("::")
            title_words = re.sub(r"\(\d{4}\)$", "", title).strip().split()
            for w in title_words:
                words.setdefault(w, len(words))
            gs = genres.split("|")
            for g in gs:
                cats.setdefault(g, len(cats))
            movies[int(mid)] = (
                np.asarray([words[w] for w in title_words], np.int64),
                np.asarray([cats[g] for g in gs], np.int64))
        rng = np.random.RandomState(rand_seed)
        samples = []
        for ln in read("ratings.dat"):
            uid, mid, rating, _ts = ln.split("::")
            uid, mid = int(uid), int(mid)
            if mid not in movies or uid not in users:
                continue
            g, a, j = users[uid]
            title_ids, cat_ids = movies[mid]
            samples.append((np.int64(uid), np.int64(g), np.int64(a),
                            np.int64(j), np.int64(mid), title_ids, cat_ids,
                            np.float32(rating)))
        is_test = rng.rand(len(samples)) < float(test_ratio)
        keep = is_test if mode.lower() == "test" else ~is_test
        self.samples = [s for s, k in zip(samples, keep) if k]

    def __getitem__(self, idx):
        return self.samples[idx]

    def __len__(self):
        return len(self.samples)


class _WMTBase(Dataset):
    """Shared WMT14/WMT16 parsing: parallel src/trg sentence files inside
    the reference archives; builds id sequences with <s>/<e>/<unk>."""

    BOS, EOS, UNK = 0, 1, 2

    def __init__(self, data_file=None, mode="train", src_dict_size=-1,
                 trg_dict_size=-1, lang="en", download=True):
        if data_file is None:
            raise ValueError(_NO_DOWNLOAD)
        pairs = self._read_pairs(data_file, mode.lower())
        self.src_dict = self._build_dict((s for s, _ in pairs),
                                         src_dict_size)
        self.trg_dict = self._build_dict((t for _, t in pairs),
                                         trg_dict_size)
        self.samples = []
        for s, t in pairs:
            sid = [self.src_dict.get(w, self.UNK) for w in s]
            tid = [self.trg_dict.get(w, self.UNK) for w in t]
            self.samples.append((
                np.asarray(sid, np.int64),
                np.asarray([self.BOS] + tid, np.int64),
                np.asarray(tid + [self.EOS], np.int64)))

    @classmethod
    def _build_dict(cls, corpus, size):
        from collections import Counter

        cnt = Counter(w for sent in corpus for w in sent)
        vocab = ["<s>", "<e>", "<unk>"] + [
            w for w, _ in cnt.most_common(None if size in (-1, None)
                                          else max(size - 3, 0))]
        return {w: i for i, w in enumerate(vocab)}

    @staticmethod
    def _read_pairs(root, mode):
        if not os.path.isdir(root):
            raise ValueError("pass the extracted dataset directory")
        import glob as _glob

        def find(sub, exts):
            for e in exts:
                hits = sorted(_glob.glob(os.path.join(root, f"*{sub}*{e}")))
                if hits:
                    return hits[0]
            return None

        src = find(mode, (".src", ".en", "")) or find("src", ("",))
        trg = find(mode, (".trg", ".de", ".fr", "")) or find("trg", ("",))
        if src is None or trg is None or src == trg:
            raise ValueError(
                f"could not locate parallel {mode} src/trg files in {root}")
        opener = gzip.open if src.endswith(".gz") else open
        with opener(src, "rt", encoding="utf-8", errors="replace") as f:
            s_lines = [ln.split() for ln in f.read().strip().split("\n")]
        opener = gzip.open if trg.endswith(".gz") else open
        with opener(trg, "rt", encoding="utf-8", errors="replace") as f:
            t_lines = [ln.split() for ln in f.read().strip().split("\n")]
        return list(zip(s_lines, t_lines))

    def __getitem__(self, idx):
        return self.samples[idx]

    def __len__(self):
        return len(self.samples)


class WMT14(_WMTBase):
    """WMT'14 EN→FR (text/datasets/wmt14.py)."""


class WMT16(_WMTBase):
    """WMT'16 EN→DE (text/datasets/wmt16.py)."""


__all__ += ["Conll05st", "Movielens", "WMT14", "WMT16"]

from .tokenizer import (  # noqa: F401,E402
    BasicTokenizer,
    BertTokenizer,
    WordPieceTokenizer,
    faster_tokenizer,
)

__all__ += ["BasicTokenizer", "BertTokenizer", "WordPieceTokenizer",
            "faster_tokenizer"]

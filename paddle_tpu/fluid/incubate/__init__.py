"""Compat alias: the reference's canonical legacy import path is
`paddle.fluid.incubate.fleet.*` (pslib scripts use it verbatim); route
it to the real implementation under paddle_tpu.incubate.fleet."""
import sys

from ...incubate import fleet as _fleet_pkg

fleet = _fleet_pkg
# make `from paddle_tpu.fluid.incubate.fleet.x.y import z` resolve: the
# submodule path must appear in sys.modules under this package name
sys.modules[__name__ + ".fleet"] = _fleet_pkg

"""Compat alias: the reference's canonical legacy import path is
`paddle.fluid.incubate.fleet.*` (pslib scripts use it verbatim); route
it to the real implementation under paddle_tpu.incubate.fleet.

Every submodule is aliased in sys.modules under the fluid-prefixed name:
a bare package alias would make the import machinery LOAD SECOND COPIES
of the submodules (and with them a second pslib fleet singleton).
"""
import importlib
import sys

_REAL = "paddle_tpu.incubate.fleet"
_SUBS = ("", ".parameter_server", ".parameter_server.pslib",
         ".utils", ".utils.fleet_util")
for _s in _SUBS:
    _m = importlib.import_module(_REAL + _s)
    sys.modules[__name__ + ".fleet" + _s] = _m

fleet = sys.modules[__name__ + ".fleet"]

"""`paddle.fluid` compatibility namespace.

Reference: python/paddle/fluid/__init__.py — the v2.2-era entry point many
user scripts still import directly. Everything here is a re-export of the
real implementations (static Program/Executor, LoD machinery, io, layers);
the fluid names are an API contract, not a separate engine.
"""
from ..framework.lod import (  # noqa: F401
    LoDTensor,
    create_lod_tensor,
    create_random_int_lodtensor,
    merge_lod_tensor,
    split_lod_tensor,
)
from ..framework.device import (  # noqa: F401
    CPUPlace,
    CUDAPlace,
    TPUPlace,
    XPUPlace,
)

CUDAPinnedPlace = CPUPlace  # pinned host memory dissolves into PJRT staging
from ..framework.param_attr import ParamAttr  # noqa: F401
from ..static import (  # noqa: F401
    CompiledProgram,
    Executor,
    Program,
    Variable,
    default_main_program,
    default_startup_program,
    global_scope,
    program_guard,
    scope_guard,
)
from ..framework.flags import get_flags, set_flags  # noqa: F401
from . import core  # noqa: F401
from . import layers  # noqa: F401

__all__ = [
    "LoDTensor", "create_lod_tensor", "create_random_int_lodtensor",
    "split_lod_tensor", "merge_lod_tensor", "CPUPlace", "CUDAPlace",
    "CUDAPinnedPlace", "TPUPlace", "XPUPlace", "ParamAttr", "Program",
    "Variable",
    "CompiledProgram", "Executor", "default_main_program",
    "default_startup_program", "global_scope", "program_guard", "scope_guard",
    "get_flags", "set_flags", "core", "layers",
]

"""`paddle.fluid.core` compatibility shim.

Reference: the pybind extension module (paddle/fluid/pybind/pybind.cc) that
fluid-era user code reaches into for LoDTensor, places, and feature probes.
Here those objects are the Python-native TPU implementations.
"""
from ..framework.device import (  # noqa: F401
    CPUPlace,
    CUDAPlace,
    TPUPlace,
    XPUPlace,
)
from ..framework.lod import LoDTensor  # noqa: F401
from ..framework.selected_rows import SelectedRows  # noqa: F401
from ..framework.tensor import Tensor  # noqa: F401

VarBase = Tensor  # dygraph variable type alias (reference imperative/layer.h:66)


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_npu() -> bool:
    return False


def is_compiled_with_tpu() -> bool:
    return True

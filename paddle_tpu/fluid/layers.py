"""`paddle.fluid.layers` compatibility namespace.

Reference: python/paddle/fluid/layers/ — the v2.2-era functional layer API.
Re-exports of the real implementations (static.nn builders, nn.functional
activations, tensor ops); fluid-era argument spellings are preserved by the
underlying functions where they differ (e.g. fc's num_flatten_dims).
"""
from ..nn.functional import (  # noqa: F401
    elu,
    gelu,
    hardswish as hard_swish,
    leaky_relu,
    log_softmax,
    relu,
    relu6,
    sigmoid,
    softmax,
    softplus,
    softsign,
    swish,
    tanh,
)
from ..nn.functional import (  # noqa: F401
    cross_entropy,
    mse_loss,
    square_error_cost,
)
from ..nn.functional.sequence import (  # noqa: F401
    sequence_concat,
    sequence_expand,
    sequence_first_step,
    sequence_last_step,
    sequence_mask,
    sequence_pad,
    sequence_pool,
    sequence_reverse,
    sequence_slice,
    sequence_softmax,
    sequence_unpad,
)
from ..static import data  # noqa: F401
from ..static.nn import (  # noqa: F401
    batch_norm,
    conv2d,
    dropout,
    embedding,
    fc,
    layer_norm,
)
from ..tensor import (  # noqa: F401
    cast,
    concat,
    mean,
    ones,
    reshape,
    split,
    squeeze,
    stack,
    transpose,
    unsqueeze,
    zeros,
)
from ..tensor import add, divide, multiply, subtract  # noqa: F401
from ..tensor import mean as _mean, sum as _sum

# fluid-era op spellings
elementwise_add = add
elementwise_div = divide
elementwise_mul = multiply
elementwise_sub = subtract
reduce_mean = _mean
reduce_sum = _sum
from ..static.nn import case, cond, switch_case, while_loop  # noqa: F401,E402
from ..nn.functional import cosine_similarity as _cos_similarity


def cos_sim(X, Y, name=None):
    """fluid.layers.cos_sim (reference cos_sim_op): keeps the reduced
    trailing dim, returning [N, 1] where cosine_similarity returns [N]."""
    from ..tensor import unsqueeze

    return unsqueeze(_cos_similarity(X, Y, axis=1), -1)
from ..nn.functional import (  # noqa: F401,E402
    affine_channel, conv_shift, cvm, fsp_matrix, im2sequence,
)
from ..static import (  # noqa: F401,E402
    array_length, array_read, array_write, create_array,
)

"""Fused flat-buffer optimizer updates over grad_comm buckets.

The per-param `Optimizer.step()` unflattens every reduced bucket back into
its parameter views and then runs one update per parameter. For a bucketed
DP step that round-trip is pure overhead: the reduced gradient already
lives in ONE flat buffer per bucket, and every elementwise update rule
(SGD/Momentum/Adam/AdamW/...) commutes with concatenation — so the update
can run directly on the flat buffer, one fused jitted kernel per bucket,
and scatter the new parameter values out once at the end
(arXiv:2004.13336's weight-update-sharding motivation, single-chip form).

`FusedFlatUpdater` owns flat slot buffers per bucket (moments etc. laid out
exactly like the bucket) and drives the optimizer's own pure `_update`
rule, so the math — and therefore the result — is bit-identical to the
per-param path for uniform-hyperparameter buckets: elementwise IEEE ops on
a concatenation equal the concatenation of the per-tensor ops.

Non-elementwise rules (Lamb, Lars, DGCMomentum — per-PARAM norms / top-k)
would silently change semantics if fused over a bucket; they are rejected.

ZeRO stage-2 (`step_sharded`): the reduce_scatter half of the grad sync
leaves each rank holding only its 1/world shard of the reduced bucket; the
update is applied on that OWNED shard (slot buffers exist only for the
shard — the stage-2 memory win) and the updated parameter shards
re-assemble with one all_gather per bucket.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.tensor import Tensor
from ..observability.metrics import get_registry as _get_registry

__all__ = ["FusedFlatUpdater", "FUSABLE_OPTIMIZERS"]

# elementwise update rules: fusing over a flat bucket is exact
FUSABLE_OPTIMIZERS = ("SGD", "Momentum", "Adagrad", "Adadelta", "Adam",
                      "AdamW", "Adamax", "RMSProp")
# per-param norm / top-k rules: fusing would change the math
_UNFUSABLE = ("Lamb", "Lars", "DGCMomentum")

_m_fused = _get_registry().counter(
    "fused_bucket_updates_total",
    help="optimizer updates applied as one fused kernel per bucket").bind()


class FusedFlatUpdater:
    """Apply `optimizer`'s update rule per flat grad_comm bucket.

        comm = OverlappedGradCommunicator(cfg)      # or GradCommunicator
        fused = FusedFlatUpdater(optimizer, params, comm)
        ...
        loss.backward(); comm.sync(params, world)   # reduced grads ready
        fused.step()                                # one kernel per bucket

    `step(futures=...)` consumes `overlap.sync_async` futures directly —
    the reduced flat buffer feeds the update without ever being scattered
    back into per-param grad views.
    """

    def __init__(self, optimizer, params, communicator=None, buckets=None,
                 use_kernel=None):
        kind = type(optimizer).__name__
        if kind in _UNFUSABLE or kind not in FUSABLE_OPTIMIZERS:
            raise ValueError(
                f"{kind} cannot be fused over flat buckets (its update "
                f"uses per-parameter norms/top-k); fusable: "
                f"{FUSABLE_OPTIMIZERS}")
        if optimizer._grad_clip is not None:
            raise ValueError(
                "fused flat updates do not implement grad_clip; clip the "
                "gradients before sync or use the per-param step()")
        # use_kernel: route each bucket's update through the pallas fused
        # dequant+update kernel (ops/pallas/fused_update.py) when the rule
        # has a fused form. None (default) resolves from
        # FLAGS_kernel_autotune, so with the flag unset the jnp path runs
        # byte-for-byte unchanged (the ISSUE-13 inertness contract); the
        # kernel itself is bit-identical for fp32 buckets, so opting in
        # moves wall clock only.
        if use_kernel is None:
            from ..framework.flags import flag

            use_kernel = bool(flag("FLAGS_kernel_autotune"))
        self.use_kernel = bool(use_kernel)
        self.optimizer = optimizer
        self.params = [p for p in params if not p.stop_gradient]
        self.communicator = communicator
        if buckets is None:
            if communicator is not None:
                buckets = communicator.buckets_for(self.params)
            else:
                from ..distributed.grad_comm import build_buckets

                buckets = build_buckets(self.params)
        self.buckets = buckets
        self._slots: Dict[int, dict] = {}      # bucket index -> flat slots
        self._shard_slots: Dict[int, dict] = {}
        # single-process stage-3 emulation: peer ranks' shard slots, kept
        # HOST-side ((bucket, rank) -> numpy slots) so live device bytes
        # stay this rank's
        self._peer_slots: Dict[tuple, dict] = {}
        self._fns: Dict[int, object] = {}
        self._hypers: Dict[int, tuple] = {}
        for b in self.buckets:
            self._hypers[b.index] = self._uniform_hypers(b)

    # ------------------------------------------------------------ plumbing
    def _uniform_hypers(self, bucket) -> tuple:
        """(lr_mult, wd) for the bucket — must be uniform across its params
        (the fused kernel applies ONE scalar pair; a per-element vector
        would break `if wd:` truthiness inside the shared update rules)."""
        lms, wds = set(), set()
        for pi in bucket.param_indices:
            p = self.params[pi]
            lms.add(float(getattr(p, "optimize_attr", {})
                          .get("learning_rate", 1.0)))
            wds.add(float(self.optimizer._param_wd(p)))
        if len(lms) > 1 or len(wds) > 1:
            raise ValueError(
                f"bucket {bucket.index} mixes per-param hyperparameters "
                f"(lr_mult {sorted(lms)}, weight_decay {sorted(wds)}); the "
                f"fused flat update needs them uniform per bucket — use "
                f"the per-param optimizer.step() for this model")
        return lms.pop(), wds.pop()

    def _flat_params(self, bucket):
        if len(bucket.param_indices) == 1:
            return self.params[bucket.param_indices[0]]._value.reshape(-1)
        return jnp.concatenate([self.params[pi]._value.reshape(-1)
                                for pi in bucket.param_indices])

    def _flat_grads(self, bucket):
        if len(bucket.param_indices) == 1:
            return self.params[bucket.param_indices[0]].grad._value \
                .reshape(-1)
        return jnp.concatenate([self.params[pi].grad._value.reshape(-1)
                                for pi in bucket.param_indices])

    def _init_flat_slots(self, bucket, numel=None) -> dict:
        """Flat slot buffers laid out like the bucket. Param-shaped slots
        (moments) concatenate; scalar slots (beta pows) are shared — one
        per bucket, exact because every param starts from the identical
        scalar and steps with the identical betas."""
        n = bucket.size if numel is None else numel
        proto = self.optimizer._init_slots(
            jnp.zeros((1,), bucket.dtype))
        slots = {}
        for k, v in proto.items():
            if np.shape(v) == ():
                slots[k] = v
            else:
                slots[k] = jnp.zeros((n,), v.dtype)
        return slots

    def _bucket_fn(self, bucket):
        fn = self._fns.get(bucket.index)
        if fn is None:
            upd = self.optimizer._update
            lm, wd = self._hypers[bucket.index]

            f = None
            if self.use_kernel:
                from ..ops.pallas.fused_update import bucket_update_fn

                # one-VMEM-pass pallas form of the same rule; None for
                # rules without a fused kernel (falls through to jnp)
                f = bucket_update_fn(self.optimizer, lm, wd)
            if f is None:
                def f(flat_p, flat_g, slots, lr):
                    new_p, new_s = upd(flat_p, flat_g.astype(flat_p.dtype),
                                       slots, lr, lm, wd)
                    return new_p.astype(flat_p.dtype), new_s

            fn = self._fns[bucket.index] = jax.jit(f, donate_argnums=(2,))
        return fn

    def _scatter_params(self, bucket, new_flat):
        for pi, off, n, shape in zip(bucket.param_indices, bucket.offsets,
                                     bucket.numels, bucket.shapes):
            p = self.params[pi]
            p._value = new_flat[off:off + n].reshape(shape).astype(
                p._value.dtype)

    # ---------------------------------------------------------------- step
    def step(self, futures=None):
        """One fused update per bucket. `futures` (from
        `overlap.sync_async`) supplies reduced flat grads directly; without
        them the flat grad is re-assembled from the `.grad` views the
        communicator scattered. A future carrying an error-feedback
        residual (quantized codecs) commits it back to the communicator so
        the skip-the-scatter fast path can't silently drop the cross-step
        feedback."""
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        by_index = ({f.bucket.index: f for f in futures}
                    if futures is not None else {})
        for b in self.buckets:
            fut = by_index.get(b.index)
            if fut is not None:
                flat_g = fut.wait()
                res = getattr(fut, "residual", None)
                if res is not None and self.communicator is not None \
                        and not isinstance(res, jax.core.Tracer):
                    self.communicator._residuals[b.index] = res
            else:
                flat_g = self._flat_grads(b)
            flat_p = self._flat_params(b)
            slots = self._slots.get(b.index)
            if slots is None:
                slots = self._init_flat_slots(b)
            new_p, new_s = self._bucket_fn(b)(flat_p, flat_g, slots, lr)
            self._slots[b.index] = new_s
            self._scatter_params(b, new_p)
            _m_fused.value += 1
        self.optimizer._accumulated_steps += 1

    # ------------------------------------------------------------- ZeRO-2/3
    def step_sharded(self, rank: int, world: int, flat_grad_shards=None,
                     group=None, param_store=None):
        """ZeRO stage-2/3 fused update: apply the rule on this rank's OWNED
        shard of each bucket.

        Stage 2 (default): the parameter shard is sliced from the full
        (replicated) parameters and the updated shards re-assemble with one
        all_gather per bucket.

        Stage 3 (`param_store` = a
        `distributed.sharding.stage3.Stage3ParamShards`): the parameter
        shard comes straight from the at-rest store and the updated shard
        is committed straight back — NO all_gather, the full parameter is
        never materialized for the update; the next forward's prefetched
        gathers see the new values. In single-process emulation the peer
        ranks' updates run here too (host-resident shards + slots), since
        there is no real peer process to run them.

        `flat_grad_shards` maps bucket index -> this rank's reduced grad
        shard (what `reduce_scatter` leaves behind); omitted entries fall
        back to slicing the already-reduced full `.grad` views (the
        emulated single-process path). Slot buffers exist only for the
        shard — 1/world of the stage-1 optimizer-state footprint.
        """
        from ..distributed import collective as _coll

        world = int(world)
        if world <= 1:
            return self.step()
        if param_store is not None:
            return self._step_stage3(rank, world, flat_grad_shards,
                                     param_store)
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        flat_grad_shards = flat_grad_shards or {}
        for b in self.buckets:
            pad = (-b.size) % world
            chunk = (b.size + pad) // world
            lo = rank * chunk
            g_shard = flat_grad_shards.get(b.index)
            if g_shard is None:
                full_g = self._flat_grads(b)
                if pad:
                    full_g = jnp.concatenate(
                        [full_g, jnp.zeros((pad,), full_g.dtype)])
                g_shard = full_g[lo:lo + chunk]
            flat_p = self._flat_params(b)
            if pad:
                flat_p = jnp.concatenate(
                    [flat_p, jnp.zeros((pad,), flat_p.dtype)])
            p_shard = flat_p[lo:lo + chunk]
            slots = self._shard_slots.get(b.index)
            if slots is None:
                slots = self._init_flat_slots(b, numel=chunk)
            new_shard, new_s = self._bucket_fn(b)(p_shard, g_shard, slots, lr)
            self._shard_slots[b.index] = new_s
            # re-assemble the updated parameter from every rank's shard
            gathered = _coll.all_gather(
                None, Tensor(new_shard, _internal=True), group=group)
            new_flat = gathered._value.reshape(-1)[:b.size]
            self._scatter_params(b, new_flat)
            _m_fused.value += 1
        self.optimizer._accumulated_steps += 1

    def _step_stage3(self, rank: int, world: int, flat_grad_shards,
                     param_store):
        """Stage-3 body of step_sharded: update the at-rest shard in place
        (commit to the store, no gather)."""
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        flat_grad_shards = flat_grad_shards or {}
        for b in self.buckets:
            pad = (-b.size) % world
            chunk = (b.size + pad) // world
            lo = rank * chunk
            full_g = None
            g_shard = flat_grad_shards.get(b.index)
            if g_shard is None:
                full_g = self._flat_grads(b)
                if pad:
                    full_g = jnp.concatenate(
                        [full_g, jnp.zeros((pad,), full_g.dtype)])
                g_shard = full_g[lo:lo + chunk]
            p_shard = param_store.own_shard(b.index)
            slots = self._shard_slots.get(b.index)
            if slots is None:
                slots = self._init_flat_slots(b, numel=chunk)
            new_shard, new_s = self._bucket_fn(b)(p_shard, g_shard, slots,
                                                  lr)
            self._shard_slots[b.index] = new_s
            param_store.commit_shard(b.index, new_shard)
            if param_store.emulated:
                # single-process emulation: run the peer ranks' shard
                # updates too (each with ITS shard + slots, exactly what
                # that rank would compute), kept host-resident so the
                # device never holds more than this rank's state
                if full_g is None:
                    full_g = self._flat_grads(b)
                    if pad:
                        full_g = jnp.concatenate(
                            [full_g, jnp.zeros((pad,), full_g.dtype)])
                for r in param_store.peer_ranks():
                    g_r = full_g[r * chunk:(r + 1) * chunk]
                    p_r = jnp.asarray(param_store.peer_shard(b.index, r))
                    s_r = self._peer_slots.get((b.index, r))
                    if s_r is None:
                        s_r = self._init_flat_slots(b, numel=chunk)
                    else:
                        s_r = {k: (v if np.shape(v) == ()
                                   else jnp.asarray(v))
                               for k, v in s_r.items()}
                    n_r, s_r2 = self._bucket_fn(b)(p_r, g_r, s_r, lr)
                    # np.array (copy): zero-copy views would pin the
                    # device buffers the host residency is meant to free
                    self._peer_slots[(b.index, r)] = {
                        k: (v if np.shape(v) == () else np.array(v))
                        for k, v in s_r2.items()}
                    param_store.commit_peer_shard(b.index, r,
                                                  np.array(n_r))
            _m_fused.value += 1
        self.optimizer._accumulated_steps += 1

    # ------------------------------------------------------------ state io
    def shard_slots_state(self) -> dict:
        """Resume-critical SHARD slot buffers (stage-2/3 `step_sharded`
        state — per-param `optimizer._slots` never sees these). Goes into
        the sharded checkpoint payload next to the zero3 shards; without
        it a resumed Adam run restarts its moments from zero and silently
        diverges."""
        def host(slots):
            return {k: (float(v) if np.shape(v) == () else np.asarray(v))
                    for k, v in slots.items()}

        return {
            "own": {int(i): host(s) for i, s in self._shard_slots.items()},
            "peer": {(int(i), int(r)): host(s)
                     for (i, r), s in self._peer_slots.items()},
            # unpadded bucket sizes: what reshard.py needs to strip the
            # world-N padding when re-chunking the slot buffers to a new
            # world size (elastic resume)
            "bucket_sizes": {int(b.index): int(b.size)
                             for b in self.buckets},
        }

    def load_shard_slots_state(self, state: dict):
        """Inverse of shard_slots_state()."""
        self._shard_slots = {
            int(i): {k: (v if np.shape(v) == () else jnp.asarray(v))
                     for k, v in s.items()}
            for i, s in (state.get("own") or {}).items()}
        self._peer_slots = {
            (int(i), int(r)): dict(s)
            for (i, r), s in (state.get("peer") or {}).items()}

    def sync_slots_to_optimizer(self):
        """Scatter the flat slot buffers back into `optimizer._slots` so
        `optimizer.state_dict()` (checkpointing) sees the fused state. The
        inverse import happens lazily: a fused step after
        `load_slots_from_optimizer()` keeps training from restored state."""
        for b in self.buckets:
            slots = self._slots.get(b.index)
            if slots is None:
                continue
            for pi, off, n, shape in zip(b.param_indices, b.offsets,
                                         b.numels, b.shapes):
                p = self.params[pi]
                out = {}
                for k, v in slots.items():
                    if np.shape(v) == ():
                        out[k] = v
                    else:
                        out[k] = v[off:off + n].reshape(shape)
                self.optimizer._slots[id(p)] = out

    def load_slots_from_optimizer(self):
        """Assemble flat bucket slots from per-param `optimizer._slots`
        (after a checkpoint restore). Params without saved slots get their
        init values."""
        for b in self.buckets:
            pieces: Dict[str, List] = {}
            scalar: Dict[str, object] = {}
            for pi in b.param_indices:
                p = self.params[pi]
                slots = self.optimizer._slots.get(id(p))
                if slots is None:
                    slots = self.optimizer._init_slots(p._value)
                for k, v in slots.items():
                    if np.shape(v) == ():
                        scalar[k] = jnp.asarray(v)
                    else:
                        pieces.setdefault(k, []).append(
                            jnp.asarray(v).reshape(-1))
            flat = {k: jnp.concatenate(vs) for k, vs in pieces.items()}
            flat.update(scalar)
            if flat:
                self._slots[b.index] = flat

    def __repr__(self):
        return (f"FusedFlatUpdater({type(self.optimizer).__name__}, "
                f"buckets={len(self.buckets)})")

"""Optimizer base.

Reference: python/paddle/optimizer/optimizer.py + CUDA update kernels in
paddle/fluid/operators/optimizers/ (sgd_op, adam_op, lamb_op, momentum_op...).

TPU-native design: each optimizer defines a *pure* per-parameter update rule
(``_update``); ``step()`` applies one jitted whole-tree update (params, grads,
slots are pytrees; buffers donated so updates are in-place in HBM). The same
pure rule powers the pjit training path (paddle_tpu.jit), so eager and compiled
training share one optimizer implementation.
"""
from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import dtype as dtype_mod
from ..framework.autograd import no_grad
from ..framework.tensor import Tensor
from .lr import LRScheduler


class Optimizer:
    _hyper_defaults: Dict[str, float] = {}

    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, **kwargs):
        if parameters is None:
            from ..framework import autograd as _ag

            if _ag._op_recorder is None:
                raise ValueError(
                    "parameters is required in dygraph mode "
                    "(pass layer.parameters())"
                )
            # static build (reference semantics): parameters are collected
            # from the Program at minimize() time
            parameters = []
        self._parameter_list: List[Tensor] = list(parameters)
        self._learning_rate = learning_rate
        self._grad_clip = grad_clip
        self._weight_decay = weight_decay
        self._name = name
        self._slots: Dict[int, Dict[str, jnp.ndarray]] = {}
        self._step_fn = None
        self._sparse_step_cache: Dict[Any, Any] = {}
        self._accumulated_steps = 0

    # ------------------------------------------------------------- lr plumbing
    def get_lr(self) -> float:
        if isinstance(self._learning_rate, LRScheduler):
            return float(self._learning_rate())
        return float(self._learning_rate)

    def set_lr(self, value):
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError("cannot set_lr when learning_rate is a scheduler")
        self._learning_rate = float(value)

    def set_lr_scheduler(self, scheduler):
        self._learning_rate = scheduler

    # ---------------------------------------------------------- the update rule
    def _init_slots(self, pval) -> Dict[str, jnp.ndarray]:
        return {}

    def _update(self, pval, grad, slots, lr, lr_mult, wd):
        """Pure update: returns (new_pval, new_slots). Override per optimizer."""
        raise NotImplementedError

    def _wd_coeff(self) -> float:
        wd = self._weight_decay
        if wd is None:
            return 0.0
        if isinstance(wd, (int, float)):
            return float(wd)
        # L2Decay regularizer object
        return float(getattr(wd, "_coeff", getattr(wd, "coeff", 0.0)))

    def _param_wd(self, p) -> float:
        """Effective decay coefficient for one parameter (per-param regularizer
        overrides the optimizer-level one; AdamW adds apply_decay_param_fun)."""
        if getattr(p, "regularizer", None) is not None:
            return float(getattr(p.regularizer, "_coeff", self._wd_coeff()))
        return self._wd_coeff()

    # ----------------------------------------------------------------- step()
    def _build_step_fn(self, lr_mults, wds, clip_cfg):
        upd = self._update

        def step_all(pvals, gvals, slots, lr):
            if clip_cfg is not None:
                kind, cval = clip_cfg
                if kind == "global_norm":
                    gsq = sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in gvals)
                    gnorm = jnp.sqrt(gsq)
                    scale = jnp.minimum(1.0, cval / jnp.maximum(gnorm, 1e-12))
                    gvals = [g * scale.astype(g.dtype) for g in gvals]
                elif kind == "norm":
                    new = []
                    for g in gvals:
                        n = jnp.sqrt(jnp.sum(jnp.square(g.astype(jnp.float32))))
                        s = jnp.minimum(1.0, cval / jnp.maximum(n, 1e-12))
                        new.append(g * s.astype(g.dtype))
                    gvals = new
                elif kind == "value":
                    lo, hi = cval
                    gvals = [jnp.clip(g, lo, hi) for g in gvals]
            new_p, new_s = [], []
            for pval, g, s, lm, wd in zip(pvals, gvals, slots, lr_mults, wds):
                np_, ns_ = upd(pval, g, s, lr, lm, wd)
                new_p.append(np_.astype(pval.dtype))
                new_s.append(ns_)
            return new_p, new_s

        # donate only the slot buffers: param values may be aliased by user
        # Tensors (detach(), tape residuals), donating them would invalidate
        # those aliases mid-session
        return jax.jit(step_all, donate_argnums=(2,))

    def _clip_cfg(self):
        gc = self._grad_clip
        if gc is None:
            return None
        cls = type(gc).__name__
        if cls == "ClipGradByGlobalNorm":
            return ("global_norm", gc.clip_norm)
        if cls == "ClipGradByNorm":
            return ("norm", gc.clip_norm)
        if cls == "ClipGradByValue":
            return ("value", (gc.min, gc.max))
        return None

    # -------------------------------------------------- row-sparse updates
    def _sparse_step(self, p, sr, lr):
        """Apply a SelectedRows gradient touching only its rows (reference:
        the lazy-mode sparse adam/sgd kernels, operators/optimizers/*). Slot
        buffers with the parameter's shape are updated row-wise; scalar slots
        (beta pows) update as usual. Weight decay is skipped — decaying the
        full table would densify the update (reference behavior)."""
        if id(p) not in self._slots:
            self._slots[id(p)] = self._init_slots(p._value)
        slots = self._slots[id(p)]
        lm = float(getattr(p, "optimize_attr", {}).get("learning_rate", 1.0))
        key = (id(p), tuple(sr.rows.shape))
        fn = self._sparse_step_cache.get(key)
        if fn is None:
            upd = self._update
            pshape = tuple(p._value.shape)

            def apply(pval, slots, rows, values, lr):
                n = rows.shape[0]
                uniq, inv = jnp.unique(rows, return_inverse=True, size=n,
                                       fill_value=-1)
                vals = jax.ops.segment_sum(values, inv, num_segments=n)
                valid = uniq >= 0
                r = jnp.where(valid, uniq, 0)
                cur = pval[r]
                cur_slots = {
                    k: (v[r] if tuple(v.shape) == pshape else v)
                    for k, v in slots.items()
                }
                new_p, new_slots = upd(cur, vals, cur_slots, lr, lm, 0.0)
                new_p = new_p.astype(pval.dtype)
                dp = jnp.where(valid[:, None], new_p - cur, 0)
                out_p = pval.at[r].add(dp)
                out_slots = {}
                for k, v in slots.items():
                    if tuple(v.shape) == pshape:
                        nv = new_slots[k].astype(v.dtype)
                        dv = jnp.where(valid[:, None], nv - v[r], 0)
                        out_slots[k] = v.at[r].add(dv)
                    else:
                        out_slots[k] = new_slots[k]
                return out_p, out_slots

            fn = self._sparse_step_cache[key] = jax.jit(apply)
        new_p, new_slots = fn(p._value, slots, sr.rows, sr.values, lr)
        p._value = new_p
        self._slots[id(p)] = new_slots

    @no_grad()
    def step(self):
        from ..framework.selected_rows import SelectedRows

        all_params = [p for p in self._parameter_list
                      if p.grad is not None and not p.stop_gradient]
        sparse_ids = {id(p) for p in all_params
                      if isinstance(getattr(p.grad, "_value", None),
                                    SelectedRows)}
        if sparse_ids:
            lr = jnp.asarray(self.get_lr(), jnp.float32)
            for p in all_params:
                if id(p) in sparse_ids:
                    self._sparse_step(p, p.grad._value, lr)
        params = [p for p in all_params if id(p) not in sparse_ids]
        if not params:
            if sparse_ids:
                self._accumulated_steps += 1
            return
        pvals = [p._value for p in params]
        gvals = [p.grad._value.astype(p._value.dtype) for p in params]
        slots = []
        for p in params:
            if id(p) not in self._slots:
                self._slots[id(p)] = self._init_slots(p._value)
            slots.append(self._slots[id(p)])
        if self._step_fn is None or self._step_key != tuple(id(p) for p in params):
            lr_mults = tuple(
                float(getattr(p, "optimize_attr", {}).get("learning_rate", 1.0)) for p in params
            )
            wds = tuple(self._param_wd(p) for p in params)
            self._step_fn = self._build_step_fn(lr_mults, wds, self._clip_cfg())
            self._step_key = tuple(id(p) for p in params)
        lr = jnp.asarray(self.get_lr(), jnp.float32)
        new_p, new_s = self._step_fn(pvals, gvals, slots, lr)
        for p, np_, ns_ in zip(params, new_p, new_s):
            p._value = np_
            self._slots[id(p)] = ns_
        self._accumulated_steps += 1
        self._mark_slot_writer("eager")

    def clear_grad(self, set_to_zero=True):
        for p in self._parameter_list:
            p.grad = None

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        from ..framework import autograd as _ag

        if _ag._op_recorder is not None:
            # static build: register on the Program; Executor.run compiles
            # forward+backward+update into one step (static/__init__.py)
            from .. import static as _static

            prog = _static.default_main_program()
            loss_vid = prog._var_of(loss)
            prog._train = (self, loss_vid)
            prog._loss_id = loss_vid
            if not prog._grad_params:
                from ..framework.tensor import Parameter as _Param

                prog._grad_params = [
                    t for t in prog.externals.values()
                    if isinstance(t, _Param) and not t.stop_gradient
                ]
            return [], []
        loss.backward()
        self.step()
        return [], []

    # -------------------------------------------------------------- state io
    # Slot-state arbitration: moments live in TWO places — optimizer._slots
    # (eager steps, set_state_dict) and TrainStep._slots (compiled steps,
    # donated buffers). The LAST WRITER wins: eager writes mark "eager",
    # each compiled step marks itself; state_dict() and a compiled step's
    # slot carry consult the marker so neither side clobbers newer state.
    def _mark_slot_writer(self, writer):
        import weakref

        self.__dict__["_slot_writer"] = (
            "eager" if writer == "eager" else weakref.ref(writer))

    def _slot_writer_is(self, step) -> bool:
        w = getattr(self, "_slot_writer", None)
        return (w is not None and w != "eager"
                and w() is step)

    def _sync_from_compiled(self):
        """When the last slot writer was a compiled TrainStep, snapshot its
        slots into _slots as HOST copies — a device-array reference would
        be invalidated by the next compiled step's buffer donation (and an
        eager step would donate it right back). When the last writer was
        the eager path, _slots is already the newest state: no overwrite."""
        w = getattr(self, "_slot_writer", None)
        if w is None or w == "eager":
            return
        step = w()
        if step is None or step._slots is None:
            return
        fm = step.fm
        ti = 0
        for p, m in zip(fm.params, fm.trainable_mask):
            if m:
                self._slots[id(p)] = {
                    k: np.asarray(v)
                    for k, v in step._slots[ti].items()}
                ti += 1

    def state_dict(self):
        self._sync_from_compiled()
        sd = {}
        for i, p in enumerate(self._parameter_list):
            slots = self._slots.get(id(p))
            if slots:
                key = p.name or f"param_{i}"
                for k, v in slots.items():
                    sd[f"{key}.{k}"] = np.asarray(v)
        if isinstance(self._learning_rate, LRScheduler):
            sd["LR_Scheduler"] = self._learning_rate.state_dict()
        return sd

    def set_state_dict(self, state_dict):
        self._mark_slot_writer("eager")  # restored state supersedes any
        # compiled step's in-flight slots (they re-import on next call)
        if "LR_Scheduler" in state_dict and isinstance(self._learning_rate, LRScheduler):
            self._learning_rate.set_state_dict(state_dict["LR_Scheduler"])
        # saved per-param key prefixes in save order: the POSITIONAL
        # fallback when name lookup misses — auto-generated param names
        # differ between two in-process builds of the same architecture
        # (the unique_name counter advances), but slot-bearing parameter
        # ORDER doesn't (state_dict only emits params that have slots)
        prefixes = []
        for k in state_dict:
            if k == "LR_Scheduler" or "." not in k:
                continue
            pre = k.rsplit(".", 1)[0]
            if pre not in prefixes:
                prefixes.append(pre)

        def load_with(key, p):
            slots = self._init_slots(p._value)
            found = False
            for k in list(slots):
                sk = f"{key}.{k}"
                if sk in state_dict:
                    v = np.asarray(state_dict[sk])
                    if tuple(v.shape) != tuple(np.shape(slots[k])):
                        return False  # wrong param's state: refuse silently
                    slots[k] = jnp.asarray(v)
                    found = True
            if found:
                self._slots[id(p)] = slots
            return found

        # User-chosen names are always trusted. AUTO-generated names
        # (unique_name counter) are trusted only when one side's name set
        # contains the other's: the counter shifts between builds, so a
        # PARTIAL overlap means this process's 'linear_1.w_0' may be a
        # different param than the checkpoint's — the shape guard can't
        # catch that for homogeneous stacked layers. Containment either
        # way is the legitimate-mismatch shape (frozen params dropped
        # prefixes at save time; a full-model checkpoint loaded into a
        # submodel), where exact names stay meaningful; on genuine
        # partial overlap auto-named params fall back to pure positional
        # alignment (slot-bearing save order is stable across builds).
        def is_auto(p):
            return getattr(p, "_auto_named", False)

        all_auto = {p.name or f"param_{i}"
                    for i, p in enumerate(self._parameter_list)
                    if is_auto(p)}
        trainable_auto = {p.name or f"param_{i}"
                          for i, p in enumerate(self._parameter_list)
                          if is_auto(p)
                          and not getattr(p, "stop_gradient", False)}
        user_names = {p.name for p in self._parameter_list
                      if p.name and not is_auto(p)}
        auto_prefixes = set(prefixes) - user_names
        auto_consistent = (auto_prefixes <= all_auto
                           or trainable_auto <= auto_prefixes)

        # pass 1: exact names; consume matched prefixes so pass 2's order
        # aligns over the REMAINING slot-bearing params only
        missed = []
        for i, p in enumerate(self._parameter_list):
            key = p.name or f"param_{i}"
            if (auto_consistent or not is_auto(p)) and load_with(key, p):
                if key in prefixes:
                    prefixes.remove(key)
            elif not getattr(p, "stop_gradient", False):
                # only trainable params compete for positional state —
                # frozen ones never produced slots at save time, and a
                # same-shaped frozen param must not steal a prefix
                missed.append(p)
        # pass 2: remaining params take remaining prefixes in order (shape
        # guard in load_with skips frozen/extra params' misalignments)
        j = 0
        for p in missed:
            while j < len(prefixes):
                if load_with(prefixes[j], p):
                    j += 1
                    break
                j += 1

    # functional bridge for the pjit path -----------------------------------
    def init_state_tree(self, pvals):
        return [self._init_slots(v) for v in pvals]

    def apply_gradients_tree(self, pvals, gvals, slots, lr):
        """Pure whole-tree update usable inside jit/pjit (no clipping-by-config
        baked; the jit trainer composes clipping itself)."""
        new_p, new_s = [], []
        wd = self._wd_coeff()
        for pval, g, s in zip(pvals, gvals, slots):
            np_, ns_ = self._update(pval, g.astype(pval.dtype), s, lr, 1.0, wd)
            new_p.append(np_.astype(pval.dtype))
            new_s.append(ns_)
        return new_p, new_s

    _step_key = None

"""Optimizers (reference: python/paddle/optimizer/ + operators/optimizers/*.cu).

Update rules are pure jax functions in fp32 master math (bf16 params update
through fp32 intermediates), matching the reference's multi-precision kernels.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from . import lr  # noqa: F401
from .fused import FUSABLE_OPTIMIZERS, FusedFlatUpdater  # noqa: F401
from .optimizer import Optimizer


def _f32(v):
    return v.astype(jnp.float32)


class SGD(Optimizer):
    """Reference: operators/optimizers/sgd_op.h."""

    def _update(self, p, g, s, lr_, lm, wd):
        g = _f32(g)
        if wd:
            g = g + wd * _f32(p)
        return _f32(p) - lr_ * lm * g, s


class Momentum(Optimizer):
    """Reference: operators/optimizers/momentum_op.h (use_nesterov supported)."""

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None, name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def _init_slots(self, pval):
        return {"velocity": jnp.zeros(pval.shape, jnp.float32)}

    def _update(self, p, g, s, lr_, lm, wd):
        g = _f32(g)
        if wd:
            g = g + wd * _f32(p)
        v = self._momentum * s["velocity"] + g
        if self._nesterov:
            new_p = _f32(p) - lr_ * lm * (g + self._momentum * v)
        else:
            new_p = _f32(p) - lr_ * lm * v
        return new_p, {"velocity": v}


class Adagrad(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, initial_accumulator_value=0.0,
                 name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _init_slots(self, pval):
        return {"moment": jnp.full(pval.shape, self._init_acc, jnp.float32)}

    def _update(self, p, g, s, lr_, lm, wd):
        g = _f32(g)
        if wd:
            g = g + wd * _f32(p)
        m = s["moment"] + g * g
        new_p = _f32(p) - lr_ * lm * g / (jnp.sqrt(m) + self._epsilon)
        return new_p, {"moment": m}


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95, parameters=None,
                 weight_decay=None, grad_clip=None, name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._epsilon = epsilon
        self._rho = rho

    def _init_slots(self, pval):
        return {
            "avg_squared_grad": jnp.zeros(pval.shape, jnp.float32),
            "avg_squared_update": jnp.zeros(pval.shape, jnp.float32),
        }

    def _update(self, p, g, s, lr_, lm, wd):
        g = _f32(g)
        if wd:
            g = g + wd * _f32(p)
        asg = self._rho * s["avg_squared_grad"] + (1 - self._rho) * g * g
        upd = g * jnp.sqrt(s["avg_squared_update"] + self._epsilon) / jnp.sqrt(
            asg + self._epsilon
        )
        asu = self._rho * s["avg_squared_update"] + (1 - self._rho) * upd * upd
        return _f32(p) - lr_ * lm * upd, {"avg_squared_grad": asg, "avg_squared_update": asu}


class Adam(Optimizer):
    """Reference: operators/optimizers/adam_op.h (bias-corrected)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, lazy_mode=False,
                 multi_precision=False, name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _init_slots(self, pval):
        return {
            "moment1": jnp.zeros(pval.shape, jnp.float32),
            "moment2": jnp.zeros(pval.shape, jnp.float32),
            "beta1_pow": jnp.ones((), jnp.float32),
            "beta2_pow": jnp.ones((), jnp.float32),
        }

    def _decayed_grad(self, p, g, wd):
        if wd:
            return g + wd * _f32(p)
        return g

    def _update(self, p, g, s, lr_, lm, wd):
        g = self._decayed_grad(p, _f32(g), wd)
        b1p = s["beta1_pow"] * self._beta1
        b2p = s["beta2_pow"] * self._beta2
        m1 = self._beta1 * s["moment1"] + (1 - self._beta1) * g
        m2 = self._beta2 * s["moment2"] + (1 - self._beta2) * g * g
        mhat = m1 / (1 - b1p)
        vhat = m2 / (1 - b2p)
        new_p = self._post_decay(
            _f32(p) - lr_ * lm * mhat / (jnp.sqrt(vhat) + self._epsilon), p, lr_ * lm, wd
        )
        return new_p, {"moment1": m1, "moment2": m2, "beta1_pow": b1p, "beta2_pow": b2p}

    def _post_decay(self, new_p, p, step_lr, wd):
        return new_p


class AdamW(Adam):
    """Decoupled weight decay (reference: python/paddle/optimizer/adamw.py)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=0.01, lr_ratio=None, apply_decay_param_fun=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False, name=None, **kw):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay, grad_clip, name=name)
        self._apply_decay_param_fun = apply_decay_param_fun

    def _decayed_grad(self, p, g, wd):
        return g  # decoupled: no L2 in the gradient

    def _post_decay(self, new_p, p, step_lr, wd):
        if wd:
            return new_p - step_lr * wd * _f32(p)
        return new_p

    def _param_wd(self, p):
        fn = self._apply_decay_param_fun
        if fn is not None and not fn(p.name):
            return 0.0
        return super()._param_wd(p)


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _init_slots(self, pval):
        return {
            "moment": jnp.zeros(pval.shape, jnp.float32),
            "inf_norm": jnp.zeros(pval.shape, jnp.float32),
            "beta1_pow": jnp.ones((), jnp.float32),
        }

    def _update(self, p, g, s, lr_, lm, wd):
        g = _f32(g)
        if wd:
            g = g + wd * _f32(p)
        b1p = s["beta1_pow"] * self._beta1
        m = self._beta1 * s["moment"] + (1 - self._beta1) * g
        u = jnp.maximum(self._beta2 * s["inf_norm"], jnp.abs(g))
        new_p = _f32(p) - lr_ * lm / (1 - b1p) * m / (u + self._epsilon)
        return new_p, {"moment": m, "inf_norm": u, "beta1_pow": b1p}


class RMSProp(Optimizer):
    def __init__(self, learning_rate=0.001, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None, grad_clip=None,
                 name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _init_slots(self, pval):
        s = {
            "mean_square": jnp.zeros(pval.shape, jnp.float32),
            "momentum_acc": jnp.zeros(pval.shape, jnp.float32),
        }
        if self._centered:
            s["mean_grad"] = jnp.zeros(pval.shape, jnp.float32)
        return s

    def _update(self, p, g, s, lr_, lm, wd):
        g = _f32(g)
        if wd:
            g = g + wd * _f32(p)
        ms = self._rho * s["mean_square"] + (1 - self._rho) * g * g
        out = dict(s, mean_square=ms)
        if self._centered:
            mg = self._rho * s["mean_grad"] + (1 - self._rho) * g
            out["mean_grad"] = mg
            denom = jnp.sqrt(ms - mg * mg + self._epsilon)
        else:
            denom = jnp.sqrt(ms + self._epsilon)
        mom = self._momentum * s["momentum_acc"] + lr_ * lm * g / denom
        out["momentum_acc"] = mom
        return _f32(p) - mom, out


class Lamb(Optimizer):
    """Layer-wise adaptive moments for large batch (reference:
    operators/optimizers/lamb_op.h, meta_optimizers/lamb_optimizer.py)."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, name=None, **kw):
        super().__init__(learning_rate, parameters, lamb_weight_decay, grad_clip, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        # fn(param) -> True means NO weight decay for that param
        # (reference lamb_op: exclude_from_weight_decay)
        self._exclude_fn = exclude_from_weight_decay_fn

    def _param_wd(self, p):
        if self._exclude_fn is not None and self._exclude_fn(p):
            return 0.0
        return super()._param_wd(p)

    def _init_slots(self, pval):
        return {
            "moment1": jnp.zeros(pval.shape, jnp.float32),
            "moment2": jnp.zeros(pval.shape, jnp.float32),
            "beta1_pow": jnp.ones((), jnp.float32),
            "beta2_pow": jnp.ones((), jnp.float32),
        }

    def _update(self, p, g, s, lr_, lm, wd):
        g = _f32(g)
        pf = _f32(p)
        b1p = s["beta1_pow"] * self._beta1
        b2p = s["beta2_pow"] * self._beta2
        m1 = self._beta1 * s["moment1"] + (1 - self._beta1) * g
        m2 = self._beta2 * s["moment2"] + (1 - self._beta2) * g * g
        mhat = m1 / (1 - b1p)
        vhat = m2 / (1 - b2p)
        r = mhat / (jnp.sqrt(vhat) + self._epsilon) + wd * pf
        w_norm = jnp.sqrt(jnp.sum(pf * pf))
        r_norm = jnp.sqrt(jnp.sum(r * r))
        ratio = jnp.where(
            (w_norm > 0) & (r_norm > 0), w_norm / jnp.maximum(r_norm, 1e-12), 1.0
        )
        new_p = pf - lr_ * lm * ratio * r
        return new_p, {"moment1": m1, "moment2": m2, "beta1_pow": b1p, "beta2_pow": b2p}


class Lars(Momentum):
    """LARS (reference: operators/optimizers/lars_momentum_op.cu,
    meta_optimizers/lars_optimizer.py)."""

    def __init__(self, learning_rate=0.001, momentum=0.9, lars_coeff=0.001,
                 lars_weight_decay=0.0005, parameters=None, grad_clip=None,
                 exclude_from_weight_decay=None, epsilon=0, name=None, **kw):
        super().__init__(learning_rate, momentum, parameters, False,
                         lars_weight_decay, grad_clip, name)
        self._lars_coeff = lars_coeff
        self._lars_eps = epsilon
        # name substrings excluded from decay (reference lars_momentum_op)
        self._exclude_names = list(exclude_from_weight_decay or [])

    def _param_wd(self, p):
        if any(n in p.name for n in self._exclude_names):
            return 0.0
        return super()._param_wd(p)

    def _update(self, p, g, s, lr_, lm, wd):
        g = _f32(g)
        pf = _f32(p)
        w_norm = jnp.sqrt(jnp.sum(pf * pf))
        g_norm = jnp.sqrt(jnp.sum(g * g))
        local_lr = jnp.where(
            (w_norm > 0) & (g_norm > 0),
            self._lars_coeff * w_norm / (g_norm + wd * w_norm + self._lars_eps),
            1.0,
        )
        v = self._momentum * s["velocity"] + lr_ * lm * local_lr * (g + wd * pf)
        return pf - v, {"velocity": v}


class DGCMomentum(Momentum):
    """Deep Gradient Compression momentum (reference:
    operators/optimizers/dgc_momentum_op.h + fleet meta_optimizer
    dgc_optimizer.py): after `rampup_begin_step`, only the top-`sparsity`
    fraction of gradient magnitudes update immediately; the rest accumulate
    locally (with momentum correction) until they grow large enough.

    TPU framing: under GSPMD the allreduce lives inside the compiled step,
    so DGC's bandwidth saving does not transfer — what is preserved is the
    NUMERICAL method (sparse update + local accumulation + momentum
    correction), which changes convergence behavior and is what the
    reference's unit tests pin down.
    """

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 rampup_begin_step=0, rampup_step=1,
                 sparsity=(0.999,), use_nesterov=False, weight_decay=None,
                 grad_clip=None, name=None, **kw):
        super().__init__(learning_rate, momentum, parameters, use_nesterov,
                         weight_decay, grad_clip, name)
        self._rampup_begin = int(rampup_begin_step)
        self._rampup_step = max(int(rampup_step), 1)
        self._sparsity = list(sparsity)

    def _init_slots(self, pval):
        return {"velocity": jnp.zeros(pval.shape, jnp.float32),
                "accum": jnp.zeros(pval.shape, jnp.float32)}

    def _cur_sparsity(self):
        """Each sparsity level holds for rampup_step/len(sparsity) steps, so
        the final level is reached after rampup_step steps (reference:
        dgc_op get_period_sparsity)."""
        step = max(self._accumulated_steps - self._rampup_begin, 0)
        idx = min(step * len(self._sparsity) // self._rampup_step,
                  len(self._sparsity) - 1)
        return float(self._sparsity[idx])

    def _update(self, p, g, s, lr_, lm, wd):
        g = _f32(g)
        if wd:
            g = g + wd * _f32(p)
        if self._accumulated_steps < self._rampup_begin:
            v = self._momentum * s["velocity"] + g
            return _f32(p) - lr_ * lm * v, {"velocity": v,
                                            "accum": s["accum"]}
        sp = self._cur_sparsity()
        # momentum correction (DGC §3.2): velocity accumulates locally
        u = self._momentum * s["velocity"] + g
        acc = s["accum"] + u
        flat = jnp.abs(acc).reshape(-1)
        k = max(1, int(flat.size * (1.0 - sp)))
        thresh = jax.lax.top_k(flat, k)[0][-1]
        mask = (jnp.abs(acc) >= thresh).astype(jnp.float32)
        sent = acc * mask
        return (_f32(p) - lr_ * lm * sent,
                {"velocity": u * (1.0 - mask), "accum": acc * (1.0 - mask)})


__all__ = [
    "Optimizer", "SGD", "Momentum", "Adagrad", "Adadelta", "Adam", "AdamW",
    "Adamax", "RMSProp", "Lamb", "Lars", "DGCMomentum", "lr",
    "FusedFlatUpdater", "FUSABLE_OPTIMIZERS",
]

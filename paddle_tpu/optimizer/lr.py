"""LR schedulers (reference: python/paddle/optimizer/lr.py — the LRScheduler
family: 14 schedulers driven by .step())."""
from __future__ import annotations

import math


class LRScheduler:
    def __init__(self, learning_rate=0.1, last_epoch=-1, verbose=False):
        self.base_lr = float(learning_rate)
        self.last_epoch = last_epoch
        self.verbose = verbose
        self.last_lr = self.base_lr
        self.step()

    def __call__(self):
        return self.last_lr

    def step(self, epoch=None):
        if epoch is None:
            self.last_epoch += 1
        else:
            self.last_epoch = epoch
        self.last_lr = self.get_lr()
        if self.verbose:
            print(f"Epoch {self.last_epoch}: set learning rate to {self.last_lr}.")

    def get_lr(self):
        raise NotImplementedError

    def state_dict(self):
        return {
            k: v for k, v in self.__dict__.items()
            if isinstance(v, (int, float, bool, str)) or v is None
        }

    def set_state_dict(self, state_dict):
        self.__dict__.update(state_dict)

    set_dict = set_state_dict


class NoamDecay(LRScheduler):
    def __init__(self, d_model, warmup_steps, learning_rate=1.0, last_epoch=-1,
                 verbose=False):
        self.d_model = d_model
        self.warmup_steps = warmup_steps
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        step = max(self.last_epoch, 1)
        return self.base_lr * (self.d_model ** -0.5) * min(
            step ** -0.5, step * (self.warmup_steps ** -1.5)
        )


class PiecewiseDecay(LRScheduler):
    def __init__(self, boundaries, values, last_epoch=-1, verbose=False):
        self.boundaries = list(boundaries)
        self.values = list(values)
        super().__init__(values[0], last_epoch, verbose)

    def get_lr(self):
        for i, b in enumerate(self.boundaries):
            if self.last_epoch < b:
                return self.values[i]
        return self.values[len(self.boundaries)]


class NaturalExpDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * math.exp(-self.gamma * self.last_epoch)


class InverseTimeDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr / (1 + self.gamma * self.last_epoch)


class PolynomialDecay(LRScheduler):
    def __init__(self, learning_rate, decay_steps, end_lr=0.0001, power=1.0, cycle=False,
                 last_epoch=-1, verbose=False):
        self.decay_steps = decay_steps
        self.end_lr = end_lr
        self.power = power
        self.cycle = cycle
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        step = self.last_epoch
        ds = self.decay_steps
        if self.cycle:
            div = math.ceil(step / ds) if step > 0 else 1
            ds = ds * div
        else:
            step = min(step, ds)
        return (self.base_lr - self.end_lr) * ((1 - step / ds) ** self.power) + self.end_lr


class LinearWarmup(LRScheduler):
    def __init__(self, learning_rate, warmup_steps, start_lr, end_lr, last_epoch=-1,
                 verbose=False):
        self.lr_sched = learning_rate if isinstance(learning_rate, LRScheduler) else None
        self.final_lr = learning_rate if not self.lr_sched else None
        self.warmup_steps = warmup_steps
        self.start_lr = start_lr
        self.end_lr = end_lr
        super().__init__(start_lr, last_epoch, verbose)

    def get_lr(self):
        if self.last_epoch < self.warmup_steps:
            return (self.end_lr - self.start_lr) * self.last_epoch / self.warmup_steps + self.start_lr
        if self.lr_sched is not None:
            self.lr_sched.step(self.last_epoch - self.warmup_steps)
            return self.lr_sched()
        return self.final_lr


class ExponentialDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * (self.gamma ** self.last_epoch)


class MultiStepDecay(LRScheduler):
    def __init__(self, learning_rate, milestones, gamma=0.1, last_epoch=-1, verbose=False):
        self.milestones = list(milestones)
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        n = sum(1 for m in self.milestones if self.last_epoch >= m)
        return self.base_lr * (self.gamma ** n)


class StepDecay(LRScheduler):
    def __init__(self, learning_rate, step_size, gamma=0.1, last_epoch=-1, verbose=False):
        self.step_size = step_size
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * (self.gamma ** (self.last_epoch // self.step_size))


class LambdaDecay(LRScheduler):
    def __init__(self, learning_rate, lr_lambda, last_epoch=-1, verbose=False):
        self.lr_lambda = lr_lambda
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * self.lr_lambda(self.last_epoch)

    def state_dict(self):
        d = super().state_dict()
        d.pop("lr_lambda", None)
        return d


class MultiplicativeDecay(LRScheduler):
    def __init__(self, learning_rate, lr_lambda, last_epoch=-1, verbose=False):
        self.lr_lambda = lr_lambda
        self._cur = float(learning_rate)
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        if self.last_epoch > 0:
            self._cur = self._cur * self.lr_lambda(self.last_epoch)
        return self._cur


class CosineAnnealingDecay(LRScheduler):
    def __init__(self, learning_rate, T_max, eta_min=0, last_epoch=-1, verbose=False):
        self.T_max = T_max
        self.eta_min = eta_min
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.eta_min + (self.base_lr - self.eta_min) * (
            1 + math.cos(math.pi * self.last_epoch / self.T_max)
        ) / 2


class OneCycleLR(LRScheduler):
    def __init__(self, max_learning_rate, total_steps, divide_factor=25.0,
                 end_learning_rate=0.0001, phase_pct=0.3, anneal_strategy="cos",
                 three_phase=False, last_epoch=-1, verbose=False):
        self.max_lr = max_learning_rate
        self.total_steps = total_steps
        self.initial_lr = max_learning_rate / divide_factor
        self.end_lr = end_learning_rate
        self.phase_pct = phase_pct
        self.anneal = anneal_strategy
        super().__init__(self.initial_lr, last_epoch, verbose)

    def _interp(self, start, end, pct):
        if self.anneal == "cos":
            return end + (start - end) * (1 + math.cos(math.pi * pct)) / 2
        return (end - start) * pct + start

    def get_lr(self):
        up = int(self.phase_pct * self.total_steps)
        step = min(self.last_epoch, self.total_steps)
        if step <= up:
            return self._interp(self.initial_lr, self.max_lr, step / max(up, 1))
        pct = (step - up) / max(self.total_steps - up, 1)
        return self._interp(self.max_lr, self.end_lr, pct)


class CyclicLR(LRScheduler):
    def __init__(self, base_learning_rate, max_learning_rate, step_size_up,
                 step_size_down=None, mode="triangular", exp_gamma=1.0, scale_fn=None,
                 scale_mode="cycle", last_epoch=-1, verbose=False):
        self.max_lr = max_learning_rate
        self.up = step_size_up
        self.down = step_size_down or step_size_up
        self.mode = mode
        self.exp_gamma = exp_gamma
        super().__init__(base_learning_rate, last_epoch, verbose)

    def get_lr(self):
        total = self.up + self.down
        cycle = math.floor(1 + self.last_epoch / total)
        x = self.last_epoch - (cycle - 1) * total
        if x <= self.up:
            pct = x / self.up
        else:
            pct = 1 - (x - self.up) / self.down
        amp = (self.max_lr - self.base_lr) * pct
        if self.mode == "triangular2":
            amp = amp / (2 ** (cycle - 1))
        elif self.mode == "exp_range":
            amp = amp * (self.exp_gamma ** self.last_epoch)
        return self.base_lr + amp


class ReduceOnPlateau(LRScheduler):
    def __init__(self, learning_rate, mode="min", factor=0.1, patience=10, threshold=1e-4,
                 threshold_mode="rel", cooldown=0, min_lr=0, epsilon=1e-8, verbose=False):
        self.mode = mode
        self.factor = factor
        self.patience = patience
        self.threshold = threshold
        self.threshold_mode = threshold_mode
        self.cooldown = cooldown
        self.min_lr = min_lr
        self.epsilon = epsilon
        self.best = None
        self.num_bad = 0
        self.cooldown_counter = 0
        self.base_lr = float(learning_rate)
        self.last_lr = self.base_lr
        self.last_epoch = 0
        self.verbose = verbose

    def get_lr(self):
        return self.last_lr

    def step(self, metrics=None, epoch=None):
        if metrics is None:
            return
        try:
            cur = float(metrics)
        except TypeError:
            cur = float(metrics.numpy())
        self.last_epoch += 1
        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            self.num_bad = 0
        better = False
        if self.best is None:
            better = True
        elif self.mode == "min":
            thr = (
                self.best * (1 - self.threshold)
                if self.threshold_mode == "rel"
                else self.best - self.threshold
            )
            better = cur < thr
        else:
            thr = (
                self.best * (1 + self.threshold)
                if self.threshold_mode == "rel"
                else self.best + self.threshold
            )
            better = cur > thr
        if better:
            self.best = cur
            self.num_bad = 0
        else:
            self.num_bad += 1
        if self.num_bad > self.patience and self.cooldown_counter <= 0:
            new_lr = max(self.last_lr * self.factor, self.min_lr)
            if self.last_lr - new_lr > self.epsilon:
                self.last_lr = new_lr
                if self.verbose:
                    print(f"ReduceOnPlateau: reduce lr to {new_lr}")
            self.cooldown_counter = self.cooldown
            self.num_bad = 0


class CosineAnnealingWarmRestarts(LRScheduler):
    """SGDR schedule (reference lr.py CosineAnnealingWarmRestarts)."""

    def __init__(self, learning_rate, T_0, T_mult=1, eta_min=0.0,
                 last_epoch=-1, verbose=False):
        if T_0 <= 0 or not isinstance(T_0, int):
            raise ValueError("T_0 must be a positive integer")
        self.T_0 = T_0
        self.T_mult = int(T_mult)
        self.eta_min = float(eta_min)
        self.T_cur = 0
        self.T_i = T_0
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        import math

        step = max(self.last_epoch, 0)
        # locate the current restart cycle
        t_i, t_cur = self.T_0, step
        while t_cur >= t_i:
            t_cur -= t_i
            t_i = t_i * self.T_mult if self.T_mult > 1 else t_i
        return self.eta_min + (self.base_lr - self.eta_min) * (
            1 + math.cos(math.pi * t_cur / t_i)) / 2

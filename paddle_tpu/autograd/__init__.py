"""paddle.autograd — backward/grad API + PyLayer custom ops.

Parity: python/paddle/autograd/ (backward, PyLayer from py_layer.py) over the
VJP-tape engine (framework/autograd.py, the BasicEngine analog).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework import autograd as _engine
from ..framework.autograd import (  # noqa: F401
    enable_grad, is_grad_enabled, no_grad, set_grad_enabled,
)
from ..framework.tensor import Tensor

__all__ = ["backward", "grad", "PyLayer", "PyLayerContext", "no_grad",
           "enable_grad", "is_grad_enabled", "set_grad_enabled"]


def backward(tensors, grad_tensors=None, retain_graph=False):
    """paddle.autograd.backward (reference: dygraph_run_backward,
    pybind/imperative.cc:2438)."""
    tensors = tensors if isinstance(tensors, (list, tuple)) else [tensors]
    if grad_tensors is not None and not isinstance(grad_tensors,
                                                   (list, tuple)):
        grad_tensors = [grad_tensors]
    _engine.run_backward(tensors, grad_tensors, retain_graph=retain_graph)


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False,
         no_grad_vars=None):
    """paddle.grad (reference: PartialGradEngine)."""
    from ..framework import grad as _grad

    return _grad(outputs, inputs, grad_outputs=grad_outputs,
                 retain_graph=retain_graph, create_graph=create_graph,
                 allow_unused=allow_unused)


class PyLayerContext:
    """ctx passed to PyLayer.forward/backward (py_layer.py PyLayerContext)."""

    def __init__(self):
        self._saved = ()
        self.not_inplace_tensors = ()

    def save_for_backward(self, *tensors):
        self._saved = tensors

    @property
    def saved_tensor(self):
        return self._saved

    def saved_tensors(self):
        return self._saved


class PyLayerMeta(type):
    def __init__(cls, name, bases, ns):
        super().__init__(name, bases, ns)


class PyLayer(metaclass=PyLayerMeta):
    """User-defined differentiable op:

        class Exp(PyLayer):
            @staticmethod
            def forward(ctx, x):
                y = paddle.exp(x)
                ctx.save_for_backward(y)
                return y

            @staticmethod
            def backward(ctx, dy):
                (y,) = ctx.saved_tensor
                return dy * y

    Forward runs eagerly (no taping inside); backward is invoked by the tape
    with the output cotangents.
    """

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        with _engine.no_grad():
            outs = cls.forward(ctx, *args, **kwargs)
        multi = isinstance(outs, (tuple, list))
        out_list = list(outs) if multi else [outs]
        out_tensors = [o for o in out_list if isinstance(o, Tensor)]
        if not _engine.is_grad_enabled() or not out_tensors:
            return outs

        # Reference contract (py_layer.py): backward returns one grad per
        # *tensor input of forward*, in forward order — including stop_gradient
        # ones (whose grads are discarded). Align over ALL tensor inputs first,
        # then pick out the trainable subset.
        tensor_inputs = [a for a in args if isinstance(a, Tensor)]
        diff_idx = [i for i, a in enumerate(tensor_inputs)
                    if not a.stop_gradient
                    and jnp.issubdtype(a._value.dtype, jnp.floating)]
        diff_inputs = [tensor_inputs[i] for i in diff_idx]
        out_avals = [jax.ShapeDtypeStruct(o._value.shape, o._value.dtype)
                     for o in out_tensors]

        def vjp_fn(cots):
            cot_list = list(cots) if isinstance(cots, tuple) else [cots]
            cot_tensors = [Tensor(c, _internal=True) for c in cot_list]
            with _engine.no_grad():
                gin = cls.backward(ctx, *cot_tensors)
            gin_list = list(gin) if isinstance(gin, (tuple, list)) else [gin]
            if len(gin_list) not in (len(tensor_inputs), len(diff_inputs)):
                raise ValueError(
                    f"{cls.__name__}.backward returned {len(gin_list)} grads; "
                    f"expected {len(tensor_inputs)} (one per forward tensor "
                    "input)")
            if len(gin_list) == len(tensor_inputs):
                gin_list = [gin_list[i] for i in diff_idx]
            out = []
            for g in gin_list:
                if g is None:
                    out.append(None)
                elif isinstance(g, Tensor):
                    out.append(g._value)
                else:
                    out.append(jnp.asarray(g))
            return out

        node = _engine.GradNode(
            vjp_fn,
            [(t, t._grad_node, t._out_index) for t in diff_inputs],
            out_avals,
            multi_output=len(out_tensors) > 1,
            name=cls.__name__,
        )
        for i, o in enumerate(out_tensors):
            if jnp.issubdtype(o._value.dtype, jnp.floating):
                o.stop_gradient = False
                o._grad_node = node
                o._out_index = i
        return outs

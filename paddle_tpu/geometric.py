"""paddle.geometric — graph message passing + segment ops.

Reference: python/paddle/geometric/ (send_u_recv/send_ue_recv over
operators/graph_send_recv_op.*, segment ops over segment_pool_op) — the GNN
compute layer whose sampling counterpart is ps/graph_table.py.

TPU-native: gathers + jax.ops.segment_* — dense, jit-compatible, MXU/VPU
work; `out_size` must be static under jit (XLA shapes), defaulting to
max(dst)+1 eagerly exactly like the reference's infer path.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .framework.autograd import call_op as op
from .framework.tensor import Tensor

__all__ = ["send_u_recv", "send_ue_recv", "segment_sum", "segment_mean",
           "segment_max", "segment_min"]

_REDUCERS = {
    "sum": jax.ops.segment_sum,
    "mean": None,  # composed below
    "max": jax.ops.segment_max,
    "min": jax.ops.segment_min,
}


def _segment(vals, ids, n, reduce_op):
    if reduce_op == "mean":
        s = jax.ops.segment_sum(vals, ids, num_segments=n)
        cnt = jax.ops.segment_sum(jnp.ones_like(ids, jnp.float32), ids,
                                  num_segments=n)
        return s / jnp.maximum(cnt, 1.0).reshape(
            (-1,) + (1,) * (vals.ndim - 1))
    out = _REDUCERS[reduce_op](vals, ids, num_segments=n)
    if reduce_op in ("max", "min"):
        # empty segments give +-inf; the reference zeroes them
        out = jnp.where(jnp.isfinite(out), out, 0.0)
    return out


def _out_size(dst, out_size, fallback):
    """Resolved HOST-side before tracing (XLA shapes are static — under jit
    pass out_size explicitly, the reference's infer path is eager-only)."""
    import numpy as np

    if out_size is not None:
        return int(out_size)
    dv = dst._value if isinstance(dst, Tensor) else dst
    if isinstance(dv, jax.core.Tracer):
        raise ValueError(
            "out_size is required when dst_index is traced (static shapes)")
    arr = np.asarray(dv)
    return int(arr.max()) + 1 if arr.size else int(fallback)


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    """out[d] = reduce over edges (s→d) of x[s] (graph_send_recv_op)."""
    n = _out_size(dst_index, out_size,
                  x.shape[0] if hasattr(x, "shape") else 0)

    def fn(xv, src, dst):
        return _segment(xv[src], dst, n, reduce_op)

    return op(fn, x, src_index, dst_index, op_name="send_u_recv")


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None, name=None):
    """Messages combine node features x[s] with edge features y
    (graph_send_ue_recv_op): message = x[s] (+|*) y."""
    n = _out_size(dst_index, out_size,
                  x.shape[0] if hasattr(x, "shape") else 0)

    def fn(xv, ev, src, dst):
        msg = xv[src]
        e = ev
        if e.ndim < msg.ndim:
            e = e.reshape(e.shape + (1,) * (msg.ndim - e.ndim))
        msg = msg + e if message_op == "add" else msg * e
        return _segment(msg, dst, n, reduce_op)

    return op(fn, x, y, src_index, dst_index, op_name="send_ue_recv")


def _make_segment(reduce_op):
    def seg(data, segment_ids, name=None):
        n = _out_size(segment_ids, None, 0)

        def fn(v, ids):
            return _segment(v, ids, n, reduce_op)

        return op(fn, data, segment_ids, op_name=f"segment_{reduce_op}")

    seg.__name__ = f"segment_{reduce_op}"
    return seg


segment_sum = _make_segment("sum")
segment_mean = _make_segment("mean")
segment_max = _make_segment("max")
segment_min = _make_segment("min")

"""paddle.version (parity: generated python/paddle/version.py)."""
full_version = "0.1.0"
major = "0"
minor = "1"
patch = "0"
rc = "0"
istaged = False
commit = "unknown"
with_mkl = "OFF"
cuda_version = "False"
cudnn_version = "False"
xpu_version = "False"
tpu = True


def show():
    print(f"paddle_tpu {full_version} (tpu-native; XLA backend)")


def cuda():
    return cuda_version


def cudnn():
    return cudnn_version

"""Crash-safe checkpointing: atomic manifest-committed step directories.

Reference models: orbax-style atomic/async checkpointing (the JAX-ecosystem
standard — write to a temp location, fsync, rename to commit, a manifest
makes the checkpoint visible only once complete) and the reference stack's
fluid auto_checkpoint persistence. Layout on disk:

    root/
      step_000123/
        MANIFEST.json            # committed LAST: step, checksums, metadata
        state.pdparams           # single-writer payload
        shard_00000.pdparams     # …or one per rank when sharded
      step_000124.tmp-<pid>-<n>/ # in-flight or crashed attempt (invisible)

A checkpoint is *visible* only after the temp directory is atomically
renamed onto its final `step_NNNNNN` name; the rename happens after every
entry and the manifest have been written and fsynced, so a crash at any
earlier point leaves nothing but a stale tmp dir (collected by gc()).
`load_latest()` checksums what it finds and falls back to the newest *valid*
checkpoint, so a torn file can never be handed back to training.

All I/O goes through a small filesystem object (`LocalFS`) so the fault
injector (`robustness/fault_injection.py`) can interpose at every syscall
the commit protocol relies on.
"""
from __future__ import annotations

import itertools
import json
import logging
import os
import pickle
import random
import re
import shutil
import threading
import time
import zlib

from ..observability import get_event_log
from ..observability.metrics import get_registry as _get_registry

__all__ = ["CheckpointManager", "LocalFS", "atomic_write", "FORMAT_VERSION",
           "MANIFEST_NAME", "JOB_STATE_NAME"]

_LOG = logging.getLogger(__name__)

# checkpoint telemetry (ISSUE 3 sweep): commit/load latency distributions,
# transient-retry pressure, and corrupt-skip counts — the numbers that decide
# save_freq / async_save / retention in production
_m_save_seconds = _get_registry().histogram(
    "checkpoint_save_seconds", help="wall time of one checkpoint commit")
_m_load_seconds = _get_registry().histogram(
    "checkpoint_load_seconds", help="wall time of one checkpoint load")
_m_saves = _get_registry().counter(
    "checkpoint_saves_total", help="checkpoint commits completed").bind()
_m_retries = _get_registry().counter(
    "checkpoint_retries_total",
    help="transient I/O retries during checkpoint commits").bind()
_m_corrupt = _get_registry().counter(
    "checkpoint_corrupt_skipped_total",
    help="corrupt/partial checkpoints skipped by load_latest").bind()

FORMAT_VERSION = 1
MANIFEST_NAME = "MANIFEST.json"
JOB_STATE_NAME = "job_state.pdparams"
_STEP_RE = re.compile(r"^step_(\d+)$")
_TMP_MARK = ".tmp-"
_tmp_counter = itertools.count()


class LocalFS:
    """The syscall surface the commit protocol depends on. Every operation
    the atomicity guarantee rests on (write, fsync, rename) is a method so
    FaultyFS can inject crashes / torn writes / transient errors at exactly
    the points a real machine fails at."""

    def open(self, path, mode="rb"):
        return open(path, mode)

    def fsync(self, fileobj):
        fileobj.flush()
        os.fsync(fileobj.fileno())

    def fsync_dir(self, path):
        # durability of the rename itself; best-effort (not all platforms
        # allow opening a directory)
        try:
            fd = os.open(path, os.O_RDONLY)
        except OSError:
            return
        try:
            os.fsync(fd)
        finally:
            os.close(fd)

    def replace(self, src, dst):
        os.replace(src, dst)

    def remove(self, path):
        os.remove(path)

    def rmtree(self, path):
        shutil.rmtree(path)

    def makedirs(self, path):
        os.makedirs(path, exist_ok=True)

    def listdir(self, path):
        return os.listdir(path)

    def exists(self, path):
        return os.path.exists(path)

    def mtime(self, path):
        return os.path.getmtime(path)


def _serialize(obj, protocol=4):
    from ..framework.io import _to_saveable

    return pickle.dumps(_to_saveable(obj), protocol=protocol)


def _deserialize(data):
    return pickle.loads(data)


def _tmp_name(path):
    return f"{path}{_TMP_MARK}{os.getpid()}-{next(_tmp_counter)}"


def _with_retries(fn, retries=2, backoff=0.02, jitter=0.25):
    """Run fn, retrying transient filesystem errors with exponential backoff
    plus jitter. Only OSError is retried — an injected crash (BaseException)
    or a logic error must fly through untouched."""
    attempt = 0
    while True:
        try:
            return fn()
        except OSError as e:
            attempt += 1
            if attempt > retries:
                raise
            _m_retries.value += 1
            delay = backoff * (2 ** (attempt - 1)) * (1 + random.uniform(0, jitter))
            _LOG.warning("transient checkpoint I/O error (%r), retry %d/%d "
                         "in %.3fs", e, attempt, retries, delay)
            time.sleep(delay)


def atomic_write(path, data, fs=None, retries=2, backoff=0.02):
    """Write bytes to `path` via temp-file + fsync + rename: readers see the
    old content or the new content, never a torn mix."""
    fs = fs or LocalFS()

    def commit():
        tmp = _tmp_name(path)
        try:
            with fs.open(tmp, "wb") as f:
                f.write(data)
                fs.fsync(f)
            fs.replace(tmp, path)
        except Exception:
            # a clean failure (not a simulated crash) tidies its temp file
            try:
                fs.remove(tmp)
            except OSError:
                pass
            raise
        fs.fsync_dir(os.path.dirname(path) or ".")

    _with_retries(commit, retries=retries, backoff=backoff)


class CheckpointManager:
    """Versioned `step_NNNNNN/` checkpoints with manifest-gated visibility.

    - save(state, step): serialize → temp dir → fsync entries → manifest →
      atomic dir rename → parent fsync. Crash anywhere = no checkpoint.
    - save_async(state, step): same commit on a background thread over a
      snapshot serialized on the caller's thread (copy-on-save, so the
      training loop may mutate weights immediately); wait()/close() join it.
    - load_latest(): newest checkpoint that passes full checksum
      validation; corrupt/partial ones are skipped with a warning.
    - keep_last_n retention (oldest deleted first) + stale-tmp collection.
    - Sharded DP/ZeRO saves: every rank writes its own shard into a shared
      temp dir; rank 0 commits the manifest last so the checkpoint is
      visible only when complete.
    """

    def __init__(self, root, keep_last_n=3, fs=None, retries=2, backoff=0.02,
                 tmp_grace_sec=0.0):
        self.root = str(root)
        self.fs = fs or LocalFS()
        self.keep_last_n = keep_last_n
        self.retries = retries
        self.backoff = backoff
        self.tmp_grace_sec = tmp_grace_sec
        self._lock = threading.Lock()
        self._worker = None
        self._async_error = None
        self._active_tmps = set()  # never gc our own in-flight temp dirs
        self.fs.makedirs(self.root)

    # ------------------------------------------------------------ layout
    def step_path(self, step):
        return os.path.join(self.root, f"step_{int(step):06d}")

    def steps(self):
        """All *visible* step numbers (committed dirs, valid or not)."""
        out = []
        for name in self.fs.listdir(self.root):
            m = _STEP_RE.match(name)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    def valid_steps(self):
        return [s for s in self.steps() if self.validate(s) is not None]

    # ------------------------------------------------------------- save
    @staticmethod
    def _entries(state, job_state):
        entries = {"state.pdparams": _serialize(state)}
        if job_state is not None:
            # resume-critical runtime state beyond the weights (RNG streams,
            # data position, grad_comm residuals — distributed_ft
            # capture_job_state); its own entry so weight-only consumers
            # never pay for it and load_job_state can skip the payload
            entries[JOB_STATE_NAME] = _serialize(job_state)
        return entries

    def save(self, state, step, metadata=None, job_state=None):
        self.wait()
        self._commit(self._entries(state, job_state), step,
                     dict(metadata or {}))

    def save_async(self, state, step, metadata=None, job_state=None):
        self.wait()
        # copy-on-save: the snapshot is fully serialized before returning,
        # so the caller may keep training/mutating weights right away
        entries = self._entries(state, job_state)
        meta = dict(metadata or {})

        def work():
            try:
                self._commit(entries, step, meta)
            except BaseException as e:  # surfaced on wait()/close()
                self._async_error = e

        t = threading.Thread(target=work, daemon=True,
                             name=f"ckpt-save-{step}")
        self._worker = t
        t.start()

    def wait(self):
        """Block until any in-flight async save lands; re-raise its error."""
        t, self._worker = self._worker, None
        if t is not None:
            t.join()
        if self._async_error is not None:
            e, self._async_error = self._async_error, None
            raise e

    def close(self):
        """Flush in-flight work. An async save started before close() still
        commits — close never abandons a checkpoint mid-write."""
        self.wait()

    def _commit(self, entries, step, metadata, sharded=False, world_size=None):
        final = self.step_path(step)
        tmp = _tmp_name(final)
        self._active_tmps.add(tmp)

        def attempt():
            self.fs.makedirs(tmp)
            infos = {}
            for name, data in entries.items():
                self._write_file(os.path.join(tmp, name), data)
                infos[name] = {"crc32": zlib.crc32(data), "size": len(data)}
            manifest = self._manifest(step, infos, metadata, sharded,
                                      world_size)
            self._write_file(os.path.join(tmp, MANIFEST_NAME),
                             json.dumps(manifest, indent=1).encode())
            if self.fs.exists(final):
                self.fs.rmtree(final)
            self.fs.replace(tmp, final)
            self.fs.fsync_dir(self.root)

        from ..profiler import RecordEvent

        t0 = time.perf_counter()
        try:
            with RecordEvent("checkpoint"):
                _with_retries(attempt, retries=self.retries,
                              backoff=self.backoff)
        except Exception as e:
            try:
                if self.fs.exists(tmp):
                    self.fs.rmtree(tmp)
            except OSError:
                pass
            get_event_log().error("checkpoint", f"commit failed: {e!r}",
                                  step=int(step))
            raise
        finally:
            self._active_tmps.discard(tmp)
        dt = time.perf_counter() - t0
        _m_save_seconds.observe(dt)
        _m_saves.value += 1
        get_event_log().info(
            "checkpoint", "committed", step=int(step), path=final,
            seconds=round(dt, 6), sharded=bool(sharded),
            bytes=sum(len(d) for d in entries.values()))
        self.gc()

    def _manifest(self, step, infos, metadata, sharded, world_size):
        return {"format_version": FORMAT_VERSION, "step": int(step),
                "framework": "paddle_tpu", "time": time.time(),
                "sharded": bool(sharded), "world_size": world_size,
                "entries": infos, "metadata": metadata}

    def _write_file(self, path, data):
        with self.fs.open(path, "wb") as f:
            f.write(data)
            self.fs.fsync(f)

    def _read_file(self, path):
        with self.fs.open(path, "rb") as f:
            return f.read()

    # ---------------------------------------------------------- sharded
    @staticmethod
    def shard_entry(rank):
        return f"shard_{int(rank):05d}.pdparams"

    def _shared_tmp(self, step):
        # deterministic name: every rank of the job derives the same temp
        # dir without communicating
        return self.step_path(step) + _TMP_MARK + "shared"

    def save_shard(self, state, step, rank, world_size):
        """Rank-local half of a sharded save: write this rank's shard (plus
        a checksum sidecar) into the shared temp dir. Not visible until
        rank 0 runs finalize_sharded() after a barrier."""
        tmp = self._shared_tmp(step)
        self._active_tmps.add(tmp)
        data = _serialize(state)
        name = self.shard_entry(rank)

        def attempt():
            self.fs.makedirs(tmp)
            self._write_file(os.path.join(tmp, name), data)
            side = {"rank": int(rank), "world_size": int(world_size),
                    "crc32": zlib.crc32(data), "size": len(data)}
            self._write_file(os.path.join(tmp, name + ".meta"),
                             json.dumps(side).encode())

        # the shared tmp stays registered (gc-protected) until
        # finalize_sharded commits it — other saves on this manager must
        # not collect a dir a peer rank is still writing into
        _with_retries(attempt, retries=self.retries, backoff=self.backoff)

    def finalize_sharded(self, step, world_size, metadata=None):
        """Rank 0, after all ranks' save_shard() returned (the barrier is the
        caller's job): verify every shard, then commit the manifest + rename.
        A missing or torn shard raises and leaves the checkpoint invisible."""
        from ..framework.errors import CheckpointCorruptError

        tmp = self._shared_tmp(step)
        final = self.step_path(step)
        self._active_tmps.add(tmp)
        try:
            infos = {}
            for r in range(int(world_size)):
                name = self.shard_entry(r)
                spath = os.path.join(tmp, name)
                mpath = spath + ".meta"
                if not (self.fs.exists(spath) and self.fs.exists(mpath)):
                    raise CheckpointCorruptError(
                        f"sharded checkpoint step {step}: shard {r} missing "
                        f"under {tmp!r} — a rank crashed before its write "
                        f"landed; checkpoint stays invisible")
                side = json.loads(self._read_file(mpath))
                data = self._read_file(spath)
                if len(data) != side["size"] or zlib.crc32(data) != side["crc32"]:
                    raise CheckpointCorruptError(
                        f"sharded checkpoint step {step}: shard {r} torn "
                        f"(size {len(data)} vs {side['size']}); checkpoint "
                        f"stays invisible")
                infos[name] = {"crc32": side["crc32"], "size": side["size"]}

            def commit():
                manifest = self._manifest(step, infos, dict(metadata or {}),
                                          sharded=True,
                                          world_size=int(world_size))
                self._write_file(os.path.join(tmp, MANIFEST_NAME),
                                 json.dumps(manifest, indent=1).encode())
                if self.fs.exists(final):
                    self.fs.rmtree(final)
                self.fs.replace(tmp, final)
                self.fs.fsync_dir(self.root)

            from ..profiler import RecordEvent

            t0 = time.perf_counter()
            with RecordEvent("checkpoint"):
                _with_retries(commit, retries=self.retries,
                              backoff=self.backoff)
            dt = time.perf_counter() - t0
            _m_save_seconds.observe(dt)
            _m_saves.value += 1
            get_event_log().info(
                "checkpoint", "committed (sharded)", step=int(step),
                path=final, seconds=round(dt, 6), sharded=True,
                world_size=int(world_size))
        finally:
            self._active_tmps.discard(tmp)
        self.gc()

    # ------------------------------------------------------------- load
    def validate(self, step):
        """Full integrity check of a visible checkpoint: manifest parses,
        every entry exists with matching size and crc32. Returns the
        manifest, or None if anything is off."""
        d = self.step_path(step)
        mpath = os.path.join(d, MANIFEST_NAME)
        if not self.fs.exists(mpath):
            return None
        try:
            manifest = json.loads(self._read_file(mpath))
        except (ValueError, OSError):
            return None
        if manifest.get("format_version") != FORMAT_VERSION:
            return None
        if manifest.get("step") != int(step):
            return None
        entries = manifest.get("entries") or {}
        if not entries:
            return None
        if manifest.get("sharded"):
            # sharded hardening (ISSUE 10): the manifest must account for
            # EVERY rank's shard file — a manifest whose world_size exceeds
            # its entry set (version drift, hand truncation) used to pass
            # here and surface as a late typed error inside load(); now the
            # step is invalid and load_latest falls back to the newest
            # fully-valid one
            try:
                world = int(manifest.get("world_size"))
            except (TypeError, ValueError):
                return None
            if world <= 0:
                return None
            if any(self.shard_entry(r) not in entries
                   for r in range(world)):
                return None
        for name, info in entries.items():
            p = os.path.join(d, name)
            if not self.fs.exists(p):
                return None
            try:
                data = self._read_file(p)
            except OSError:
                return None
            if len(data) != info.get("size") or \
                    zlib.crc32(data) != info.get("crc32"):
                return None
        return manifest

    def load(self, step, shard=None):
        from ..framework.errors import CheckpointCorruptError

        manifest = self.validate(step)
        if manifest is None:
            raise CheckpointCorruptError(
                f"checkpoint step {step} under {self.root!r} is missing or "
                f"fails checksum validation; use load_latest() to fall back "
                f"to the newest valid checkpoint")
        d = self.step_path(step)
        t0 = time.perf_counter()
        if manifest.get("sharded"):
            if shard is not None:
                out = _deserialize(
                    self._read_file(os.path.join(d, self.shard_entry(shard))))
            else:
                out = [_deserialize(
                    self._read_file(os.path.join(d, self.shard_entry(r))))
                    for r in range(manifest["world_size"])]
        else:
            out = _deserialize(
                self._read_file(os.path.join(d, "state.pdparams")))
        _m_load_seconds.observe(time.perf_counter() - t0)
        return out

    def load_sharded(self, step=None, rank=0, world_size=1,
                     zero3_world=None, allow_reshard=False):
        """This rank's payload of the sharded checkpoint at `step` (default:
        the newest valid sharded step), with elastic geometry handling.

        `world_size` is the LIVE job's shard-file world; `zero3_world` the
        live at-rest sharding degree when it differs from the file count
        (the single-process emulation keeps one shard file whose zero3
        state spans the whole world). When the checkpoint's geometry
        differs from the live one:

        - ``allow_reshard=False`` (default): raise a typed
          CheckpointGeometryError carrying both worlds — the PR-9 refusal,
          now diagnosable.
        - ``allow_reshard=True``: run the N→M transform
          (distributed/sharding/reshard.py) host-side over ALL old shard
          files and return this rank's transformed payload. Deterministic
          and communication-free, so every rank may do it independently
          from shared storage. Counted on ``reshard_total``.

        Returns ``(payload, step, manifest)``; None when no valid sharded
        checkpoint exists.
        """
        from ..framework.errors import (
            CheckpointCorruptError, CheckpointGeometryError,
        )

        if step is None:
            for s in sorted(self.steps(), reverse=True):
                m = self.validate(s)
                if m is not None and m.get("sharded"):
                    step = s
                    break
            if step is None:
                return None
        manifest = self.validate(step)
        if manifest is None:
            raise CheckpointCorruptError(
                f"checkpoint step {step} under {self.root!r} is missing or "
                f"fails checksum validation")
        if not manifest.get("sharded"):
            raise CheckpointCorruptError(
                f"checkpoint step {step} is not sharded — use load()")
        ckpt_world = int(manifest["world_size"])
        live_world = int(world_size)
        drifted = ckpt_world != live_world
        from_world = ckpt_world
        if not drifted and zero3_world is not None:
            # emulated layout: one shard file, geometry lives in the
            # payload's zero3 state
            p0 = self.load(step, shard=0)
            z3 = p0.get("zero3") if isinstance(p0, dict) else None
            if z3 is not None and \
                    int(z3.get("world", zero3_world)) != int(zero3_world):
                drifted = True
                from_world = int(z3["world"])
        if not drifted:
            return self.load(step, shard=rank), step, manifest
        target = int(zero3_world) if zero3_world is not None else live_world
        if not allow_reshard:
            raise CheckpointGeometryError(
                f"sharded checkpoint step {step} was written at world="
                f"{from_world} but this job runs world={target}; pass "
                f"allow_reshard=True to transform it "
                f"(distributed/sharding/reshard.py)",
                from_world=from_world, to_world=target)
        from ..distributed.sharding import reshard as _reshard

        t0 = time.perf_counter()
        payloads = [self.load(step, shard=r) for r in range(ckpt_world)]
        new_payloads = _reshard.reshard_payloads(payloads, target)
        ms = (time.perf_counter() - t0) * 1e3
        _reshard._m_reshards.labels(from_world=str(from_world),
                                    to_world=str(target)).inc()
        _reshard._m_reshard_ms.set(round(ms, 3))
        get_event_log().info(
            "reshard", "geometry-drifted sharded load resharded",
            step=int(step), from_world=from_world, to_world=target,
            rank=int(rank), ms=round(ms, 3))
        # emulated layouts collapse to a single payload (rank 0 carries
        # the whole world); real layouts index by rank
        idx = int(rank) if int(rank) < len(new_payloads) else 0
        return new_payloads[idx], step, manifest

    def load_job_state(self, step=None):
        """The deserialized job_state entry of `step` (default: the newest
        valid step). None when the checkpoint predates job_state or nothing
        valid exists — resume then proceeds weights-only (lossy), which the
        caller should surface."""
        if step is None:
            valid = self.valid_steps()
            if not valid:
                return None
            step = valid[-1]
        manifest = self.validate(step)
        if manifest is None:
            from ..framework.errors import CheckpointCorruptError

            raise CheckpointCorruptError(
                f"checkpoint step {step} under {self.root!r} is missing or "
                f"fails checksum validation")
        if JOB_STATE_NAME not in (manifest.get("entries") or {}):
            return None
        return _deserialize(self._read_file(
            os.path.join(self.step_path(step), JOB_STATE_NAME)))

    def load_latest(self, shard=None):
        """(state, step, manifest) for the newest checkpoint that passes
        validation, skipping corrupt/partial ones; None if nothing valid."""
        for step in sorted(self.steps(), reverse=True):
            manifest = self.validate(step)
            if manifest is None:
                _LOG.warning("skipping corrupt/partial checkpoint %s",
                             self.step_path(step))
                _m_corrupt.value += 1
                get_event_log().warning(
                    "checkpoint", "skipped corrupt/partial checkpoint",
                    step=int(step), path=self.step_path(step))
                continue
            return self.load(step, shard=shard), step, manifest
        return None

    # --------------------------------------------------------------- gc
    def _manifest_metadata(self, step) -> dict:
        """Cheap manifest metadata read (no entry checksumming) — what the
        retention policy consults; {} when the manifest is unreadable."""
        try:
            m = json.loads(self._read_file(
                os.path.join(self.step_path(step), MANIFEST_NAME)))
            return m.get("metadata") or {}
        except (ValueError, OSError):
            return {}

    def is_emergency(self, step) -> bool:
        """True for checkpoints tagged metadata.reason='preemption' (the
        PreemptionHandler's emergency saves)."""
        return self._manifest_metadata(step).get("reason") == "preemption"

    def gc(self):
        """Stale-tmp collection + keep-last-N retention (oldest first).

        Emergency preemption checkpoints (metadata.reason='preemption')
        are EXEMPT both ways: they never count toward the keep-last-N
        window (so an emergency save can't evict the last full periodic
        checkpoint) and retention never deletes them (they are consumed —
        and replaced — by the next resume's own periodic saves)."""
        with self._lock:
            self._gc_tmps()
            if not self.keep_last_n:
                return
            valid = [s for s in self.valid_steps()
                     if not self.is_emergency(s)]
            if not valid:
                return
            keep_min = valid[-self.keep_last_n] if \
                len(valid) > self.keep_last_n else valid[0]
            for s in self.steps():  # ascending: oldest deleted first
                if s < keep_min and not self.is_emergency(s):
                    try:
                        self.fs.rmtree(self.step_path(s))
                    except OSError:
                        pass

    def _gc_tmps(self):
        now = time.time()
        for name in self.fs.listdir(self.root):
            if _TMP_MARK not in name:
                continue
            path = os.path.join(self.root, name)
            if path in self._active_tmps:
                continue
            try:
                if now - self.fs.mtime(path) < self.tmp_grace_sec:
                    continue  # possibly another process's in-flight save
                self.fs.rmtree(path)
            except OSError:
                pass

"""Preemption-tolerant training: SIGTERM latch + emergency checkpoint.

On preemptible TPU pods the scheduler's eviction notice is a SIGTERM with
a short grace window; the reference stack's EDL/auto-checkpoint machinery
exists so that notice means "checkpoint and come back", not "job dead".
This module is that contract for our runtime:

- :class:`PreemptionHandler` registers handlers via ``signal.signal``
  whose bodies do NOTHING but set a latch — no allocation, no locks, no
  logging (analysis rule S002 machine-checks this for every handler in
  the tree; a signal handler runs between arbitrary bytecodes, so
  anything heavier can deadlock or corrupt the interpreter state it
  interrupted). An optional preemption FLAG FILE (some schedulers write
  one instead of signaling) is polled at the same step boundaries.
- The train loops (`hapi.Model.fit(preemption=)`,
  `TrainEpochRange(preemption_handler=)`, tools/chaos_train.py) call
  :meth:`PreemptionHandler.should_stop` at STEP boundaries — the one
  point where model/optimizer/job state is consistent — and on a hit
  fire :func:`timed_emergency_save`: an async manifest-committed
  checkpoint tagged ``metadata.reason="preemption"`` (exempt from
  keep-last-N retention GC), waited on so it commits inside the grace
  window, then exit with a RESUMABLE status (128+signum, the shell
  convention for a signal death — supervisors relaunch instead of
  declaring failure).
- Resume pairs with elastic resharding: the relaunched job (possibly at
  world−k) loads through ``CheckpointManager.load_sharded(
  allow_reshard=True)`` so a shrunk world transforms the shard geometry
  instead of refusing (distributed/sharding/reshard.py).
"""
from __future__ import annotations

import os
import signal
import threading
import time

from ..observability import get_event_log
from ..observability.metrics import get_registry as _get_registry

__all__ = ["PreemptionHandler", "timed_emergency_save",
           "EMERGENCY_REASON"]

EMERGENCY_REASON = "preemption"

_m_preemptions = _get_registry().counter(
    "preemptions_total",
    help="preemption notices latched (signal or flag file)",
    labels=("source",))
_m_emergency_saves = _get_registry().counter(
    "emergency_checkpoints_total",
    help="emergency preemption checkpoints committed").bind()
_m_emergency_ms = _get_registry().gauge(
    "emergency_save_ms",
    help="wall ms of the last emergency preemption checkpoint commit")
_m_budget_exceeded = _get_registry().counter(
    "emergency_save_budget_exceeded_total",
    help="emergency saves that committed AFTER their grace budget").bind()


class PreemptionHandler:
    """Async-signal-safe preemption latch.

        handler = PreemptionHandler()          # SIGTERM by default
        handler.install()
        for step, batch in enumerate(loader):
            train_step(batch)
            if handler.should_stop():          # step boundary only
                emergency_save(...)            # timed_emergency_save
                sys.exit(handler.exit_status())

    The registered handler body only assigns the signum and sets the
    latch (threading.Event.set — CPython runs Python-level signal
    handlers on the main thread between bytecodes, and the latch is the
    single cross-thread hand-off point). Everything observable —
    logging, metrics, checkpointing — happens later, on the training
    thread, from should_stop()/drain().

    `flag_file`: some schedulers write a sentinel file instead of (or
    before) signaling; should_stop() polls it, and a hit latches exactly
    like a signal (sticky).
    """

    def __init__(self, signals=(signal.SIGTERM,), flag_file=None,
                 grace_seconds: float = 30.0):
        self.signals = tuple(signals)
        self.flag_file = flag_file
        self.grace_seconds = float(grace_seconds)
        self._latch = threading.Event()
        self._signum = None
        self._latched_at = None      # monotonic ts, stamped on drain
        self._source = None
        self._prev = {}
        self.installed = False
        self._drained = False

    # ----------------------------------------------------------- handler
    def _handler(self, signum, frame):
        # S002 contract: flag/latch assignment ONLY — no allocation-heavy
        # calls, locks, or logging in a signal context
        self._signum = signum
        self._latch.set()

    def install(self):
        """Register the latch handler for every configured signal (main
        thread only — a CPython constraint on signal.signal). Idempotent;
        previous handlers are saved for uninstall()."""
        if self.installed:
            return self
        for s in self.signals:
            self._prev[s] = signal.signal(s, self._handler)
        self.installed = True
        return self

    def uninstall(self):
        """Restore the previous handlers."""
        for s, prev in self._prev.items():
            try:
                signal.signal(s, prev)
            except (ValueError, TypeError):  # non-main thread / exotic prev
                pass
        self._prev.clear()
        self.installed = False
        return self

    # ------------------------------------------------------------- state
    def request(self, signum=None):
        """Programmatic preemption (tests, chaos harnesses, flag pollers):
        latch exactly as a delivered signal would."""
        self._signum = signum if signum is not None else signal.SIGTERM
        self._latch.set()

    @property
    def requested(self) -> bool:
        """Latched? Checks the signal latch first, then the flag file
        (a flag hit latches, so the answer is sticky)."""
        if self._latch.is_set():
            return True
        if self.flag_file and os.path.exists(self.flag_file):
            self._source = "flag_file"
            self._latch.set()
            return True
        return False

    def should_stop(self) -> bool:
        """The step-boundary check: latched → drain (log + count once,
        stamp the grace clock) and return True."""
        if not self.requested:
            return False
        self._drain()
        return True

    def _drain(self):
        if self._drained:
            return
        self._drained = True
        self._latched_at = time.monotonic()
        src = self._source or (f"signal:{self._signum}"
                               if self._signum is not None else "request")
        _m_preemptions.labels(source=src).inc()
        get_event_log().warning(
            "preemption", "preemption latched — stopping at step boundary",
            source=src, grace_seconds=self.grace_seconds)

    def grace_remaining(self) -> float:
        """Seconds of grace window left (the full window before drain)."""
        if self._latched_at is None:
            return self.grace_seconds
        return max(0.0, self.grace_seconds
                   - (time.monotonic() - self._latched_at))

    def exit_status(self) -> int:
        """The resumable exit status: 128+signum (the shell convention
        for a signal death — supervisors treat it as relaunch-me, not
        failed), 1 when latched without a signal."""
        return 128 + int(self._signum) if self._signum is not None else 1

    def wait(self, timeout=None) -> bool:
        return self._latch.wait(timeout)

    def reset(self):
        """Clear the latch (tests / a supervisor that decided to keep
        going after all)."""
        self._latch.clear()
        self._signum = None
        self._source = None
        self._latched_at = None
        self._drained = False

    def __repr__(self):
        return (f"PreemptionHandler(signals={self.signals}, "
                f"requested={self._latch.is_set()}, "
                f"installed={self.installed})")


def timed_emergency_save(manager, state, step, job_state=None,
                         metadata=None, budget_s=None):
    """Commit one emergency checkpoint through `manager`
    (robustness.CheckpointManager): async manifest-committed save tagged
    ``metadata.reason="preemption"`` (keep-last-N GC exempts it), waited
    to completion so the commit lands inside the grace window. Returns
    the elapsed wall ms (also on the ``emergency_save_ms`` gauge).

    ``budget_s`` (typically ``handler.grace_remaining()``) enforces the
    grace contract: a commit that lands after the budget cannot be
    un-spent, but it is the exact signal a fleet must alarm on — the
    next preemption at this save size WILL lose the step. Counted on
    ``emergency_save_budget_exceeded_total`` and logged as an error."""
    meta = dict(metadata or {})
    meta.setdefault("reason", EMERGENCY_REASON)
    t0 = time.perf_counter()
    manager.save_async(state, step, metadata=meta, job_state=job_state)
    manager.wait()
    ms = (time.perf_counter() - t0) * 1e3
    _m_emergency_saves.value += 1
    _m_emergency_ms.set(round(ms, 3))
    if budget_s is not None and ms > float(budget_s) * 1e3:
        _m_budget_exceeded.value += 1
        get_event_log().error(
            "preemption", "emergency checkpoint exceeded its grace budget",
            step=int(step), ms=round(ms, 3),
            budget_ms=round(float(budget_s) * 1e3, 3))
    else:
        get_event_log().info(
            "preemption", "emergency checkpoint committed", step=int(step),
            ms=round(ms, 3))
    return ms

"""Fault injection for the checkpoint commit protocol.

FaultyFS wraps the LocalFS syscall surface and injects the failure modes a
real fleet produces — process death just before the commit rename, torn
(partial) writes, transient `OSError`s from a flaky filesystem, and slow
I/O — at deterministic, test-controlled points. This is how atomicity and
recovery are *proved* (tests/test_robustness.py, tools/ckpt_torture.py)
rather than asserted.

InjectedCrash subclasses BaseException (like KeyboardInterrupt): it models
the process dying at that exact syscall, so cleanup/retry code — which
handles Exception — must not see it, exactly as a real crash would leave
the partial state behind.
"""
from __future__ import annotations

import time

from .checkpoint import LocalFS

__all__ = ["FaultyFS", "InjectedCrash"]


class InjectedCrash(BaseException):
    """Simulated process death at an injected fault point."""


class _FaultyFile:
    """File wrapper that routes write() through the owning FaultyFS's
    fault schedule."""

    def __init__(self, fs, f, path):
        self._fs = fs
        self._f = f
        self._path = path

    def write(self, data):
        return self._fs._on_write(self._f, data, self._path)

    def __getattr__(self, name):
        return getattr(self._f, name)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self._f.close()
        return False


class FaultyFS(LocalFS):
    """LocalFS with a configurable fault schedule.

    crash_on_rename   : 1-based index of the replace() call that "kills the
                        process" (raises InjectedCrash before renaming).
    partial_write_on  : 1-based index of the write() call that writes only
                        half its payload, flushes, then crashes — a torn
                        file exactly as power loss mid-write leaves it.
    transient_oserrors: the first N write() calls raise OSError, then
                        succeed — exercises retry/backoff.
    crash_on_fsync    : 1-based index of the fsync() call that crashes
                        (data may be in the page cache but not durable).
    slow_io           : seconds to sleep inside every write() — widens race
                        windows for async-save tests.

    Counters (`writes`, `renames`, `fsyncs`) and the `log` of (op, path)
    tuples let tests assert exactly which syscalls ran.
    """

    def __init__(self, crash_on_rename=None, partial_write_on=None,
                 transient_oserrors=0, crash_on_fsync=None, slow_io=0.0):
        self.crash_on_rename = crash_on_rename
        self.partial_write_on = partial_write_on
        self.crash_on_fsync = crash_on_fsync
        self.slow_io = float(slow_io)
        self.writes = 0
        self.renames = 0
        self.fsyncs = 0
        self._transient_left = int(transient_oserrors)
        self.log = []

    # ------------------------------------------------------- fault points
    def open(self, path, mode="rb"):
        f = super().open(path, mode)
        if "w" in mode or "a" in mode or "+" in mode:
            return _FaultyFile(self, f, path)
        return f

    def _on_write(self, f, data, path):
        self.writes += 1
        self.log.append(("write", path))
        if self._transient_left > 0:
            self._transient_left -= 1
            raise OSError(f"injected transient I/O error writing {path!r}")
        if self.slow_io:
            time.sleep(self.slow_io)
        if self.partial_write_on is not None and \
                self.writes == self.partial_write_on:
            f.write(data[: max(1, len(data) // 2)])
            f.flush()
            raise InjectedCrash(f"torn write (crash mid-write) at {path!r}")
        return f.write(data)

    def fsync(self, fileobj):
        self.fsyncs += 1
        self.log.append(("fsync", getattr(fileobj, "name", "?")))
        if self.crash_on_fsync is not None and \
                self.fsyncs == self.crash_on_fsync:
            raise InjectedCrash("crash at fsync")
        inner = getattr(fileobj, "_f", fileobj)
        super().fsync(inner)

    def replace(self, src, dst):
        self.renames += 1
        self.log.append(("rename", dst))
        if self.crash_on_rename is not None and \
                self.renames == self.crash_on_rename:
            raise InjectedCrash(f"crash before rename {src!r} -> {dst!r}")
        super().replace(src, dst)

"""Fault injection for the checkpoint commit protocol and the collectives.

FaultyFS wraps the LocalFS syscall surface and injects the failure modes a
real fleet produces — process death just before the commit rename, torn
(partial) writes, transient `OSError`s from a flaky filesystem, and slow
I/O — at deterministic, test-controlled points. This is how atomicity and
recovery are *proved* (tests/test_robustness.py, tools/ckpt_torture.py)
rather than asserted.

FaultyCollective does the same for the distributed runtime: it interposes on
every guarded eager collective (distributed_ft.execute_collective) and
injects, at exact 1-based call indices, the three failure classes the
fault-tolerance layer must recover from — a hang (peer dead: tests the
group timeout + escalation), a transient failure (flaky interconnect: tests
retry + backoff), and a payload bit-flip (SDC on the wire: tests the
ReplicaGuard detection + policy path). ChaosGroup pairs a fault plan with a
short timeout so one object hands a collective its whole failure scenario.
tests/test_distributed_ft.py and tools/chaos_train.py drive both.

InjectedCrash subclasses BaseException (like KeyboardInterrupt): it models
the process dying at that exact syscall, so cleanup/retry code — which
handles Exception — must not see it, exactly as a real crash would leave
the partial state behind.
"""
from __future__ import annotations

import time

import numpy as np

from .checkpoint import LocalFS

__all__ = ["FaultyFS", "InjectedCrash", "FaultyCollective", "ChaosGroup",
           "LateHeartbeatStore", "flip_bit"]


class InjectedCrash(BaseException):
    """Simulated process death at an injected fault point."""


class _FaultyFile:
    """File wrapper that routes write() through the owning FaultyFS's
    fault schedule."""

    def __init__(self, fs, f, path):
        self._fs = fs
        self._f = f
        self._path = path

    def write(self, data):
        return self._fs._on_write(self._f, data, self._path)

    def __getattr__(self, name):
        return getattr(self._f, name)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self._f.close()
        return False


class FaultyFS(LocalFS):
    """LocalFS with a configurable fault schedule.

    crash_on_rename   : 1-based index of the replace() call that "kills the
                        process" (raises InjectedCrash before renaming).
    partial_write_on  : 1-based index of the write() call that writes only
                        half its payload, flushes, then crashes — a torn
                        file exactly as power loss mid-write leaves it.
    transient_oserrors: the first N write() calls raise OSError, then
                        succeed — exercises retry/backoff.
    crash_on_fsync    : 1-based index of the fsync() call that crashes
                        (data may be in the page cache but not durable).
    slow_io           : seconds to sleep inside every write() — widens race
                        windows for async-save tests.
    delay_on          : {("write"|"rename"|"fsync", 1-based call index):
                        seconds} — targeted delay/hang injection (ISSUE
                        17). Where slow_io taxes EVERY write, this stalls
                        exactly one syscall — e.g. the manifest fsync of
                        an emergency save inside a tight preemption grace
                        window, or a rename held long enough to look like
                        a hang to a watchdog. The call still succeeds.

    Counters (`writes`, `renames`, `fsyncs`, `delays`) and the `log` of
    (op, path) tuples let tests assert exactly which syscalls ran.
    """

    def __init__(self, crash_on_rename=None, partial_write_on=None,
                 transient_oserrors=0, crash_on_fsync=None, slow_io=0.0,
                 delay_on=None):
        self.crash_on_rename = crash_on_rename
        self.partial_write_on = partial_write_on
        self.crash_on_fsync = crash_on_fsync
        self.slow_io = float(slow_io)
        self.delay_on = dict(delay_on or {})
        self.writes = 0
        self.renames = 0
        self.fsyncs = 0
        self.delays = 0
        self._transient_left = int(transient_oserrors)
        self.log = []

    def _maybe_delay(self, op: str, index: int):
        d = self.delay_on.get((op, index))
        if d:
            self.delays += 1
            self.log.append(("delay", f"{op}#{index}"))
            time.sleep(float(d))

    # ------------------------------------------------------- fault points
    def open(self, path, mode="rb"):
        f = super().open(path, mode)
        if "w" in mode or "a" in mode or "+" in mode:
            return _FaultyFile(self, f, path)
        return f

    def _on_write(self, f, data, path):
        self.writes += 1
        self.log.append(("write", path))
        self._maybe_delay("write", self.writes)
        if self._transient_left > 0:
            self._transient_left -= 1
            raise OSError(f"injected transient I/O error writing {path!r}")
        if self.slow_io:
            time.sleep(self.slow_io)
        if self.partial_write_on is not None and \
                self.writes == self.partial_write_on:
            f.write(data[: max(1, len(data) // 2)])
            f.flush()
            raise InjectedCrash(f"torn write (crash mid-write) at {path!r}")
        return f.write(data)

    def fsync(self, fileobj):
        self.fsyncs += 1
        self.log.append(("fsync", getattr(fileobj, "name", "?")))
        self._maybe_delay("fsync", self.fsyncs)
        if self.crash_on_fsync is not None and \
                self.fsyncs == self.crash_on_fsync:
            raise InjectedCrash("crash at fsync")
        inner = getattr(fileobj, "_f", fileobj)
        super().fsync(inner)

    def replace(self, src, dst):
        self.renames += 1
        self.log.append(("rename", dst))
        self._maybe_delay("rename", self.renames)
        if self.crash_on_rename is not None and \
                self.renames == self.crash_on_rename:
            raise InjectedCrash(f"crash before rename {src!r} -> {dst!r}")
        super().replace(src, dst)


# ---------------------------------------------------------------------------
# membership fault injection
# ---------------------------------------------------------------------------

class LateHeartbeatStore:
    """KV-store wrapper that loses or delays one host's heartbeat
    re-registrations, so its TTL lease expires and the ElasticManager
    observes the member vanish — the "process alive but partitioned from
    the membership store" failure, distinct from a crash (ISSUE 17).

        store = LateHeartbeatStore(LocalKVStore(), host="b", drop_puts=5)
        ElasticManager("b", "1:4", store=store, ttl=0.2,
                       heartbeat_interval=0.05).register()
        # b's next 5 put()s are swallowed; the lease expires mid-window,
        # peers see the membership shrink, then b's heartbeat recovers
        # and re-registers (the manager re-puts, healing the lease)

    drop_puts  : number of the host's put() calls to swallow entirely.
    delay_puts : number of the host's put() calls to forward only after
                 sleeping `delay_s` — the heartbeat arrives LATE, after
                 the previous lease already lapsed.

    Only keys ending in "/{host}" are intercepted; every other key and
    every read passes straight through, so one wrapper injects a single
    host's partition into a shared store.
    """

    def __init__(self, inner, host, drop_puts=0, delay_puts=0,
                 delay_s=0.0):
        self.inner = inner
        self.host = str(host)
        self.drop_puts = int(drop_puts)
        self.delay_puts = int(delay_puts)
        self.delay_s = float(delay_s)
        self.dropped = 0
        self.delayed = 0

    def put(self, key, value, ttl=None):
        if key.endswith("/" + self.host):
            if self.drop_puts > 0:
                self.drop_puts -= 1
                self.dropped += 1
                return
            if self.delay_puts > 0:
                self.delay_puts -= 1
                self.delayed += 1
                time.sleep(self.delay_s)
        return self.inner.put(key, value, ttl=ttl)

    def __getattr__(self, name):
        return getattr(self.inner, name)


# ---------------------------------------------------------------------------
# collective fault injection
# ---------------------------------------------------------------------------

def flip_bit(tensor, bit_index=0):
    """Flip one bit of a Tensor's payload in place — the modeled SDC. The
    byte view of the value is XOR'd at `bit_index` (mod payload size), so a
    crc32 digest of the parameter is guaranteed to change."""
    val = np.ascontiguousarray(np.asarray(tensor._value))
    raw = bytearray(val.tobytes())
    i = (int(bit_index) // 8) % max(1, len(raw))
    raw[i] ^= 1 << (int(bit_index) % 8)
    flipped = np.frombuffer(bytes(raw), dtype=val.dtype).reshape(val.shape)
    import jax.numpy as jnp

    tensor._value = jnp.asarray(flipped)
    return tensor


class FaultyCollective:
    """Scheduled fault injection for guarded eager collectives.

    plan: {1-based call index: (kind, arg)} where kind is
        "hang"    — sleep `arg` seconds inside the collective (the group
                    timeout, if any, fires while the worker thread sleeps);
        "fail"    — raise TransientCollectiveError (retried with backoff);
        "bitflip" — flip bit `arg` of the collective's input payload
                    (silent corruption: the call itself succeeds).
    ops: restrict injection to these op names (e.g. ("all_reduce",));
         None = all guarded collectives.

    Every *invocation* of a guarded collective advances the call counter —
    including retries — so `plan={1: ("hang", 9)}` with a short timeout
    models a transient hang: attempt 1 times out, the retry (call 2) finds
    no fault and succeeds.

    Use as a context manager to install globally
    (`with FaultyCollective({...}):`), or attach to a ChaosGroup to scope
    the faults to one group's traffic. Counters (`calls`, `hangs`, `fails`,
    `bitflips`) and the `log` of (index, op, kind) let tests assert exactly
    which faults fired.
    """

    def __init__(self, plan=None, ops=None):
        self.plan = dict(plan or {})
        self.ops = tuple(ops) if ops else None
        self.calls = 0
        self.hangs = 0
        self.fails = 0
        self.bitflips = 0
        self.log = []

    def on_call(self, op, payload):
        if self.ops is not None and op not in self.ops:
            return
        self.calls += 1
        action = self.plan.get(self.calls)
        if action is None:
            return
        kind, arg = action
        self.log.append((self.calls, op, kind))
        if kind == "hang":
            self.hangs += 1
            time.sleep(float(arg))
        elif kind == "fail":
            self.fails += 1
            from ..framework.errors import TransientCollectiveError

            raise TransientCollectiveError(
                f"injected transient failure in {op!r} (call {self.calls})")
        elif kind == "bitflip":
            self.bitflips += 1
            if payload is not None:
                flip_bit(payload, arg or 0)
        else:
            raise ValueError(f"unknown fault kind {kind!r}")

    def __enter__(self):
        from .distributed_ft import install_chaos

        install_chaos(self)
        return self

    def __exit__(self, *exc):
        from .distributed_ft import uninstall_chaos

        uninstall_chaos(self)
        return False


def ChaosGroup(plan=None, ops=None, timeout=None, axes=("data",), nranks=1):
    """A communication Group whose traffic runs under a fault plan: the
    attached FaultyCollective fires only for collectives issued on this
    group, and `timeout` bounds them (seconds). The one-stop handle for
    exercising a full failure scenario through the public collective API:

        g = ChaosGroup(plan={1: ("hang", 9.0)}, timeout=0.1)
        dist.all_reduce(t, group=g)   # times out, retries, succeeds
    """
    from ..distributed.collective import Group, _next_gid

    gid = _next_gid[0]
    _next_gid[0] += 1
    g = Group(gid, axes, nranks=nranks, timeout=timeout)
    g.chaos = FaultyCollective(plan, ops=ops)
    return g

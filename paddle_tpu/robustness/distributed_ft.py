"""Distributed fault-tolerance runtime.

PR 2 made single-process persistence crash-safe; this layer extends the same
failure discipline to the *multi-rank* runtime, where the failure modes are
stateful and distributed:

- **hang**: an eager collective waits forever on a dead/slow peer. Every
  eager collective in `distributed/collective.py` now runs through
  ``execute_collective`` — when the group carries a timeout (``new_group
  (timeout=)`` or ``FLAGS_collective_timeout_s``) the call is bounded, timed
  out attempts are retried with exponential backoff (a peer mid-preemption
  often comes back), and exhaustion raises a typed
  ``CollectiveTimeoutError`` carrying op/group/rank context and escalates to
  the registered ``HangDetector``.
- **transient failure**: a flaky interconnect raises
  ``TransientCollectiveError``; retried with backoff like checkpoint I/O.
- **silent corruption (SDC) / DP desync**: a bit-flip or a desynced replica
  corrupts every later step. ``ReplicaGuard`` runs a cheap cross-replica
  agreement check — crc32 digest of the parameters, reduced with MIN and
  MAX across the group; disagreement within N steps triggers a configured
  policy (``raise`` / ``rebroadcast_from_src`` / ``rollback`` to the last
  valid checkpoint).
- **lossy resume**: a "resumed" job silently differs from the original —
  data position, RNG streams, and grad_comm's int8 error-feedback residuals
  are lost on restart. ``capture_job_state``/``restore_job_state`` snapshot
  them into the checkpoint's ``job_state`` entry so resume is
  bit-reproducible (proven by the crash→resume parity test), and
  ``ResumableLoader`` makes the data iterator itself a checkpointable
  object.
- **rank loss**: the elastic controller detects the death; ``elastic_
  resume`` + ``agree_bucket_assignment`` restore the shrunk job from the
  newest valid checkpoint and prove the remaining replicas agree on the
  grad_comm bucket layout before the first post-shrink sync.

Chaos (`fault_injection.FaultyCollective` / `ChaosGroup`) injects each
failure class at exact collective call indices; tests/test_distributed_ft.py
and tools/chaos_train.py exercise every recovery path above.

In-trace collectives (inside shard_map/pjit) are NOT guarded: XLA owns their
scheduling and a traced op cannot be bounded from Python. The guard covers
the eager path — which is exactly where a Python-visible hang can occur.
"""
from __future__ import annotations

import logging
import threading
import time
import zlib

import numpy as np

from ..framework.errors import (
    CollectiveTimeoutError, ReplicaDivergenceError, TransientCollectiveError,
)
from ..observability import get_event_log
from ..observability.flight_recorder import (
    dump_flight_recorder, get_flight_recorder,
)
from ..observability.metrics import get_registry as _get_registry

__all__ = [
    "CollectiveTimeoutError", "TransientCollectiveError",
    "ReplicaDivergenceError", "execute_collective", "effective_timeout",
    "install_chaos", "uninstall_chaos",
    "set_default_hang_detector", "get_default_hang_detector",
    "ReplicaGuard", "INTEGRITY_POLICIES", "params_digest",
    "agree_bucket_assignment",
    "capture_job_state", "restore_job_state", "ResumableLoader",
    "elastic_resume",
]

_LOG = logging.getLogger(__name__)

# fault-tolerance telemetry (rides the ISSUE 3 registry): how often the
# runtime had to act — the numbers that decide timeout/retry budgets and
# integrity-check cadence in production
_m_timeouts = _get_registry().counter(
    "collective_timeouts_total",
    help="eager collectives that exceeded their group timeout", labels=("op",))
_m_retries = _get_registry().counter(
    "collective_retries_total",
    help="collective retry attempts", labels=("op", "reason"))
_m_integrity = _get_registry().counter(
    "integrity_checks_total",
    help="cross-replica parameter agreement checks", labels=("result",))
_m_restored = _get_registry().counter(
    "resume_restored_entries",
    help="job_state entries restored on resume").bind()

# retry budget for timed-out / transient collectives (checkpoint.py uses the
# same shape for filesystem I/O)
DEFAULT_RETRIES = 2
DEFAULT_BACKOFF = 0.05

# ---------------------------------------------------------------------------
# collective robustness
# ---------------------------------------------------------------------------

_chaos_lock = threading.Lock()
_chaos: list = []          # installed FaultyCollective interposers
_hang_detector = [None]    # escalation target (watchdog.HangDetector)


def install_chaos(interposer):
    """Register a chaos interposer consulted on every guarded eager
    collective (see fault_injection.FaultyCollective)."""
    with _chaos_lock:
        _chaos.append(interposer)
    return interposer


def uninstall_chaos(interposer):
    with _chaos_lock:
        if interposer in _chaos:
            _chaos.remove(interposer)


def set_default_hang_detector(hd):
    """Register the HangDetector that collective-timeout exhaustion
    escalates to. Returns the previous one (restore it when done)."""
    prev = _hang_detector[0]
    _hang_detector[0] = hd
    return prev


def get_default_hang_detector():
    return _hang_detector[0]


def effective_timeout(group):
    """The timeout bounding an eager collective on `group`: the group's own
    (new_group(timeout=)) if set, else FLAGS_collective_timeout_s. None/0 =
    unbounded (the seed behavior)."""
    t = getattr(group, "timeout", None) if group is not None else None
    if t is None:
        from ..framework.flags import flag

        t = flag("FLAGS_collective_timeout_s", 0.0)
    t = float(t or 0.0)
    return t if t > 0 else None


def _run_bounded(fn, timeout, op, group, attempt):
    """Run fn, bounded by `timeout` seconds. The call runs on a worker
    thread so a hang cannot wedge the training thread; a timed-out worker is
    abandoned (daemon) — its eventual result, if any, is discarded, which is
    why collective.py's guarded thunks compute into a fresh value instead of
    mutating their input tensor."""
    if not timeout:
        return fn()
    box = {}
    done = threading.Event()

    def work():
        try:
            box["result"] = fn()
        except BaseException as e:
            box["error"] = e
        finally:
            done.set()

    t = threading.Thread(target=work, daemon=True,
                         name=f"collective-{op}-a{attempt}")
    t.start()
    if not done.wait(timeout):
        from ..distributed.env import get_rank

        raise CollectiveTimeoutError(
            f"collective {op!r} on {group!r} exceeded its {timeout}s timeout "
            f"(rank {get_rank()}, attempt {attempt + 1}) — a peer is hung or "
            f"dead", op=op, group=group, rank=get_rank(), timeout=timeout,
            attempt=attempt + 1)
    if "error" in box:
        raise box["error"]
    return box["result"]


def _escalate_timeout(err):
    """Final-timeout escalation: the run is wedged, not flaking — hand the
    stall to the HangDetector (whose on_hang pairs with the external
    supervisor that can actually kill the process) and dump the flight
    recorder so the postmortem names the op/group that never came back."""
    get_event_log().error(
        "distributed_ft", "collective timed out after retries",
        op=err.op, group=repr(err.group), rank=err.rank,
        timeout_seconds=err.timeout, attempts=err.attempt)
    dump = dump_flight_recorder(f"collective_timeout:{err.op}")
    if dump:
        get_event_log().info("flight_recorder", "postmortem dumped",
                             path=dump, trigger="collective_timeout")
    hd = _hang_detector[0]
    if hd is not None:
        try:
            hd.escalate(f"collective {err.op!r} timeout after "
                        f"{err.attempt} attempts")
        except Exception:
            _LOG.exception("hang-detector escalation failed")


def execute_collective(op, group, thunk, payload=None, retries=None,
                       backoff=None):
    """Run one eager collective body under the fault-tolerance policy.

    `thunk` computes and returns the collective's result WITHOUT mutating
    its input (so an abandoned timed-out attempt cannot race a retry).
    `payload` is the input Tensor, exposed to chaos interposers (bit-flip
    injection corrupts it in place — that is the modeled SDC).

    Fast path: no chaos installed and no timeout configured → plain call,
    zero overhead beyond two attribute reads.
    """
    interposers = _chaos
    group_chaos = getattr(group, "chaos", None)
    timeout = effective_timeout(group)
    if not interposers and group_chaos is None and timeout is None:
        return thunk()
    if group_chaos is not None:
        interposers = list(interposers) + [group_chaos]
    retries = DEFAULT_RETRIES if retries is None else int(retries)
    backoff = DEFAULT_BACKOFF if backoff is None else float(backoff)

    flightrec = get_flight_recorder()

    def attempt_once():
        for fc in interposers:
            fc.on_call(op, payload)
        return thunk()

    attempt = 0
    while True:
        try:
            flightrec.lane(f"collective:{op}", op=op, group=repr(group),
                           attempt=attempt + 1, phase="attempt")
            return _run_bounded(attempt_once, timeout, op, group, attempt)
        except CollectiveTimeoutError as e:
            _m_timeouts.labels(op=op).inc()
            attempt += 1
            if attempt > retries:
                _escalate_timeout(e)
                raise
            reason = "timeout"
        except TransientCollectiveError as e:
            attempt += 1
            if attempt > retries:
                get_event_log().error(
                    "distributed_ft",
                    f"transient collective failure persisted: {e}",
                    op=op, group=repr(group), attempts=attempt)
                raise
            reason = "transient"
        _m_retries.labels(op=op, reason=reason).inc()
        delay = backoff * (2 ** (attempt - 1))
        get_event_log().warning(
            "distributed_ft", f"collective {reason}, retrying",
            op=op, attempt=attempt, retry_in_seconds=delay)
        time.sleep(delay)


# ---------------------------------------------------------------------------
# replica-integrity guard (SDC / DP-desync detection)
# ---------------------------------------------------------------------------

INTEGRITY_POLICIES = ("raise", "rebroadcast_from_src", "rollback")


def params_digest(params) -> np.ndarray:
    """Cheap deterministic fingerprint of a parameter set: a chained crc32
    over every parameter's raw bytes, split into two int32 halves (jax
    collectives carry int32 exactly; float64 would be truncated under the
    default x32 mode). Identical across replicas iff every byte is."""
    crc = 0
    for p in params:
        val = getattr(p, "_value", p)
        crc = zlib.crc32(np.ascontiguousarray(np.asarray(val)).tobytes(), crc)
    return np.array([crc >> 16, crc & 0xFFFF], dtype=np.int32)


def _reduce_min_max(digest, group):
    """Default cross-replica agreement reduce: MIN and MAX of the digest
    over the group. Goes through collective.all_reduce so chaos injection
    and group timeouts apply to the check itself.

    The eager all_reduce treats axis 0 of a host value as the per-rank
    shard, so the digest is tiled to one row per rank (every row identical
    in the replicated world) and the elementwise reduce across rows IS the
    cross-replica agreement; row 0 is this rank's view of the result."""
    from ..distributed import collective as coll
    from ..framework.tensor import Tensor

    n = max(1, coll._group_size(coll._axes(group), group))
    tiled = np.tile(np.asarray(digest), (n, 1))
    tmin = Tensor(tiled.copy(), _internal=True)
    coll.all_reduce(tmin, op=coll.ReduceOp.MIN, group=group)
    tmax = Tensor(tiled.copy(), _internal=True)
    coll.all_reduce(tmax, op=coll.ReduceOp.MAX, group=group)
    return (np.asarray(tmin.numpy())[0].copy(),
            np.asarray(tmax.numpy())[0].copy())


class ReplicaGuard:
    """Periodic cross-replica parameter agreement check.

        guard = ReplicaGuard(policy="rollback", every_n=20,
                             checkpoint=robust_ckpt_callback)
        for step, batch in enumerate(loader):
            train_step(batch)
            guard.maybe_check(model.parameters(), step=step)

    Detection: each replica digests its parameters (crc32 → int32 pair) and
    the group reduces the digest with MIN and MAX; MIN != MAX means at least
    one replica disagrees — SDC or DP desync — caught within `every_n`
    steps instead of never. Cost per check is one tiny host hash plus two
    scalar-ish collectives.

    Policies on divergence:
    - ``raise``: fail fast with ReplicaDivergenceError (digests attached).
    - ``rebroadcast_from_src``: re-replicate parameters from `src_rank`
      (via `rebroadcast_fn(params)` when given — the eager/emulated path —
      else collective.broadcast per parameter), then re-verify.
    - ``rollback``: restore the last valid checkpoint through `checkpoint`
      (any object with a ``rollback() -> bool`` — e.g.
      hapi.callbacks.RobustCheckpoint), then re-verify.
    A policy that fails to restore agreement escalates to ``raise``.

    `reduce_fn(digest) -> (min, max)` overrides the group reduce — the
    chaos harness and tools/chaos_train.py use it to emulate an N-replica
    world in one process.
    """

    def __init__(self, policy="raise", every_n=1, group=None, checkpoint=None,
                 src_rank=0, reduce_fn=None, rebroadcast_fn=None):
        if policy not in INTEGRITY_POLICIES:
            raise ValueError(
                f"policy must be one of {INTEGRITY_POLICIES}, got {policy!r}")
        if policy == "rollback" and checkpoint is None:
            raise ValueError("policy='rollback' needs a checkpoint target "
                             "(an object with .rollback())")
        self.policy = policy
        self.every_n = max(1, int(every_n))
        self.group = group
        self.checkpoint = checkpoint
        self.src_rank = int(src_rank)
        self.reduce_fn = reduce_fn
        self.rebroadcast_fn = rebroadcast_fn
        self.checks = 0
        self.divergences = 0
        self._step = 0

    # ------------------------------------------------------------ checking
    def maybe_check(self, params, step=None):
        """check() on every `every_n`-th call; "skipped" otherwise."""
        self._step += 1
        if self._step % self.every_n:
            return "skipped"
        return self.check(params, step=step)

    def _agree(self, params):
        digest = params_digest(params)
        if self.reduce_fn is not None:
            dmin, dmax = self.reduce_fn(digest)
        else:
            dmin, dmax = _reduce_min_max(digest, self.group)
        return digest, np.asarray(dmin), np.asarray(dmax)

    def check(self, params, step=None):
        """Run one agreement check. Returns "ok" or the recovery action
        taken; raises ReplicaDivergenceError under policy='raise' or when
        recovery fails to restore agreement."""
        params = list(params)
        self.checks += 1
        digest, dmin, dmax = self._agree(params)
        if np.array_equal(dmin, dmax):
            _m_integrity.labels(result="ok").inc()
            return "ok"
        self.divergences += 1
        _m_integrity.labels(result="diverged").inc()
        get_event_log().error(
            "integrity", "replica divergence detected",
            step=step, policy=self.policy, local=digest.tolist(),
            agreed_min=dmin.tolist(), agreed_max=dmax.tolist())
        # SDC postmortem: the ring's tail shows what ran between the last
        # agreeing check and this one — where the corruption crept in
        dump_flight_recorder(f"replica_divergence:step{step}")
        if self.policy == "raise":
            raise self._error(step, digest, dmin, dmax)
        if self.policy == "rebroadcast_from_src":
            self._rebroadcast(params)
        else:  # rollback
            if not self.checkpoint.rollback():
                raise self._error(
                    step, digest, dmin, dmax,
                    note="rollback found no valid checkpoint")
        # recovery must actually restore agreement — re-verify, fail loud
        digest, dmin, dmax = self._agree(params)
        if not np.array_equal(dmin, dmax):
            raise self._error(step, digest, dmin, dmax,
                              note=f"{self.policy} did not restore agreement")
        _m_integrity.labels(result=self.policy).inc()
        get_event_log().warning(
            "integrity", f"replicas re-agreed after {self.policy}", step=step)
        return self.policy

    def _rebroadcast(self, params):
        if self.rebroadcast_fn is not None:
            self.rebroadcast_fn(params)
            return
        from ..distributed import collective as coll

        for p in params:
            coll.broadcast(p, src=self.src_rank, group=self.group)

    @staticmethod
    def _error(step, digest, dmin, dmax, note=None):
        msg = (f"replica parameter digests disagree (min {dmin.tolist()} != "
               f"max {dmax.tolist()}, local {digest.tolist()})"
               + (f" at step {step}" if step is not None else "")
               + (f": {note}" if note else "")
               + " — silent data corruption or DP desync")
        return ReplicaDivergenceError(msg, step=step, local=digest,
                                      agreed_min=dmin, agreed_max=dmax)


def agree_bucket_assignment(reducer, params, group=None, reduce_fn=None):
    """Prove the (possibly just-shrunk) replicas agree on the grad_comm
    bucket layout before the first sync: digest the deterministic bucket
    signatures and reduce MIN/MAX across the group. Raises
    ReplicaDivergenceError on disagreement (a rank would otherwise feed the
    wrong parameters into a collective — the worst kind of silent
    corruption). Returns the agreed digest."""
    sig = tuple(b.signature() for b in reducer.buckets_for(params))
    crc = zlib.crc32(repr(sig).encode())
    digest = np.array([crc >> 16, crc & 0xFFFF], dtype=np.int32)
    if reduce_fn is not None:
        dmin, dmax = reduce_fn(digest)
    else:
        dmin, dmax = _reduce_min_max(digest, group)
    if not (np.array_equal(np.asarray(dmin), digest)
            and np.array_equal(np.asarray(dmax), digest)):
        raise ReplicaDivergenceError(
            f"grad_comm bucket assignment disagrees across ranks "
            f"(local {digest.tolist()}, min {np.asarray(dmin).tolist()}, "
            f"max {np.asarray(dmax).tolist()}) — ranks would exchange "
            f"mismatched buckets", local=digest, agreed_min=dmin,
            agreed_max=dmax)
    return digest


# ---------------------------------------------------------------------------
# deterministic full-job resume
# ---------------------------------------------------------------------------

JOB_STATE_VERSION = 1


class ResumableLoader:
    """Checkpointable position wrapper around a DataLoader.

    The wrapped loader's sampler draws from the paddle.seed-governed host
    RNG at each epoch's iterator creation, so the permutation is a pure
    function of the host-RNG state at epoch start. This wrapper snapshots
    that state per epoch; ``state_dict()`` is {epoch, batch_idx,
    epoch_rng, rank, world}. After ``load_state_dict``, the next
    iteration rewinds the host RNG to the epoch start, re-derives the
    identical permutation, and fast-forwards `batch_idx` batches —
    landing bit-exactly on the batch the crashed run would have produced
    next (and leaving the host RNG in the identical mid-epoch state).

    **Epoch boundary**: a checkpoint taken right at an epoch boundary
    (iterator exhausted, next epoch not started) records ``batch_idx=0``
    with no epoch RNG, so the resume draws the NEXT epoch's permutation
    from the restored host stream — it does not replay-and-skip the
    completed epoch (which used to surface as a spurious empty epoch and
    a drifted epoch counter).

    **Rank streams (elastic world changes)**: with ``rank``/``world`` set
    (or :meth:`reassign` called), the underlying loader is treated as the
    JOB-global batch stream and this rank consumes global indices
    ``g % world == rank``. ``batch_idx`` tracks the GLOBAL position; in
    ``state_dict()`` it is rounded up to the enclosing step boundary
    (a multiple of ``world`` — checkpoints happen at step boundaries,
    where every rank has consumed the same number of batches), so a
    resume at a DIFFERENT world size simply reassigns the remaining
    global stream across the new rank count: position carries over,
    assignment is re-derived. ``reassign(rank, world)`` is the explicit
    post-reshard hook (load_state_dict never clobbers the live
    assignment).
    """

    def __init__(self, loader, rank: int = 0, world: int = 1):
        self.loader = loader
        self.rank = int(rank)
        self.world = max(1, int(world))
        if not (0 <= self.rank < self.world):
            raise ValueError(f"rank {rank} outside world {world}")
        self.epoch = 0
        self.batch_idx = 0          # GLOBAL position in the batch stream
        self._epoch_rng = None
        self._pending_skip = 0

    def reassign(self, rank: int, world: int):
        """Re-derive this loader's slice of the global stream — the
        elastic resume hook after a world-size change. Takes effect from
        the current (restored) global position."""
        rank, world = int(rank), max(1, int(world))
        if not (0 <= rank < world):
            raise ValueError(f"rank {rank} outside world {world}")
        self.rank, self.world = rank, world
        return self

    def __iter__(self):
        from ..framework import random as rng_mod

        if self._pending_skip:
            # resume: replay this epoch's sampler draws from its start
            rng_mod.set_host_rng_state(self._epoch_rng)
        else:
            self._epoch_rng = rng_mod.host_rng_state()
            self.batch_idx = 0
        it = iter(self.loader)
        skip, self._pending_skip = self._pending_skip, 0
        for _ in range(skip):
            next(it)
        self.batch_idx = skip
        g = skip
        for batch in it:
            mine = (g % self.world) == self.rank
            g += 1
            self.batch_idx = g
            if mine:
                yield batch
        self.epoch += 1
        # epoch boundary: position resets so a boundary checkpoint resumes
        # into the NEXT epoch's fresh permutation instead of replaying
        # (and skipping through) the completed one
        self.batch_idx = 0
        self._epoch_rng = None

    def __len__(self):
        n = len(self.loader)
        if self.world <= 1:
            return n
        return (n - self.rank + self.world - 1) // self.world

    def state_dict(self):
        # step-align the global position: mid-step per-rank positions
        # differ by < world, and a checkpoint is only taken once every
        # rank finished the step — the enclosing multiple of world is the
        # position all ranks agree on (and the one a different world size
        # can take over from)
        idx = self.batch_idx
        if self.world > 1 and idx % self.world:
            idx += self.world - (idx % self.world)
        return {"epoch": self.epoch, "batch_idx": idx,
                "epoch_rng": self._epoch_rng,
                "rank": self.rank, "world": self.world}

    def load_state_dict(self, state):
        self.epoch = int(state["epoch"])
        self.batch_idx = int(state["batch_idx"])
        self._epoch_rng = state["epoch_rng"]
        self._pending_skip = self.batch_idx


def capture_job_state(reducer=None, data_iter=None, nan_guard=None,
                      extra=None, train_step=None, zero3=None) -> dict:
    """Snapshot everything a bit-reproducible resume needs beyond
    model/optimizer weights: per-rank RNG streams (device key + host data
    order), the data-iterator position (`ResumableLoader.state_dict`), the
    grad_comm reducer's error-feedback residuals — including the TRACED
    residuals a `jit.TrainStep(grad_comm=...)` carries through its
    compiled step (pass the step as `train_step=`, or its
    `grad_comm_communicator` as `reducer=`) — and the NanGuard breaker
    counters. `zero3` (a `sharding.Stage3ParamShards`) records the at-rest
    sharding GEOMETRY (world / bucket layout fingerprint) so a resume
    whose sharding changed is refused instead of mis-slicing every
    parameter — the shard payloads themselves ride the sharded checkpoint
    entries (`save_group_sharded_checkpoint`), not job_state. Store the
    result as the checkpoint's `job_state` entry
    (CheckpointManager.save(..., job_state=...))."""
    from ..distributed.env import get_rank
    from ..framework import random as rng_mod

    if reducer is None and train_step is not None:
        reducer = getattr(train_step, "grad_comm_communicator", None)
    js = {"version": JOB_STATE_VERSION, "rank": get_rank(),
          "rng": rng_mod.get_rng_state()}
    if reducer is not None:
        js["grad_comm"] = reducer.state_dict()
    if data_iter is not None:
        js["data"] = data_iter.state_dict()
    if nan_guard is not None:
        js["nan_guard"] = nan_guard.state_dict()
    if zero3 is not None:
        js["zero3"] = zero3.meta_state()
    if extra:
        js["extra"] = dict(extra)
    return js


def restore_job_state(job_state, reducer=None, data_iter=None,
                      nan_guard=None, train_step=None, zero3=None,
                      allow_reshard=False) -> list:
    """Inverse of capture_job_state: restore each entry into the live
    objects. Returns the list of restored entry names (and counts them on
    the `resume_restored_entries` metric). `train_step=` restores the
    traced error-feedback residuals into a fresh
    `jit.TrainStep(grad_comm=...)`'s communicator; `zero3=` verifies the
    live store's sharding geometry against the checkpointed one (raises
    on world/bucket-layout drift). With ``allow_reshard=True`` a
    WORLD-SIZE drift is accepted instead of refused — the elastic-resume
    contract: the caller already transformed the shard payloads via
    `CheckpointManager.load_sharded(allow_reshard=True)` /
    `distributed.sharding.reshard`, so the historical world in job_state
    is informational (the bucket layout is world-independent and still
    checked)."""
    from ..framework import random as rng_mod

    if reducer is None and train_step is not None:
        reducer = getattr(train_step, "grad_comm_communicator", None)
    restored = []
    if "rng" in job_state:
        rng_mod.set_rng_state(job_state["rng"])
        restored.append("rng")
    if reducer is not None and "grad_comm" in job_state:
        reducer.load_state_dict(job_state["grad_comm"])
        restored.append("grad_comm")
    if data_iter is not None and "data" in job_state:
        data_iter.load_state_dict(job_state["data"])
        restored.append("data")
    if nan_guard is not None and "nan_guard" in job_state:
        nan_guard.load_state_dict(job_state["nan_guard"])
        restored.append("nan_guard")
    if zero3 is not None and "zero3" in job_state:
        zero3.check_meta(job_state["zero3"],
                         allow_world_drift=allow_reshard)
        restored.append("zero3")
    _m_restored.value += len(restored)
    get_event_log().info("distributed_ft", "job_state restored",
                         entries=restored, rank=job_state.get("rank"))
    return restored


def elastic_resume(manager, reducer=None, data_iter=None, nan_guard=None):
    """Resume point for an elastic restart (rank loss → shrink → resume):
    newest valid checkpoint from `manager` (robustness.CheckpointManager)
    plus its job_state, with the job_state entries already restored into
    the live objects passed in. Returns (state, step, job_state) or None
    when no valid checkpoint exists (cold start). The caller applies
    `state` (model/optimizer weights) and should then prove bucket
    agreement via agree_bucket_assignment() before the first sync."""
    manager.wait()
    found = manager.load_latest()
    if found is None:
        return None
    state, step, _manifest = found
    job_state = manager.load_job_state(step)
    if job_state:
        restore_job_state(job_state, reducer=reducer, data_iter=data_iter,
                          nan_guard=nan_guard)
    get_event_log().info("distributed_ft", "elastic resume",
                         step=int(step), has_job_state=bool(job_state))
    return state, step, job_state

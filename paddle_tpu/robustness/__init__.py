"""Robustness subsystem: crash-safe checkpointing, fault injection for
proving it, a training watchdog (NaN guard / circuit breaker / hang
detector), and the distributed fault-tolerance runtime (collective
timeouts, replica-integrity guard, deterministic full-job resume). See
docs/ARCHITECTURE.md "Checkpointing & fault tolerance" and "Distributed
fault tolerance"."""
from .checkpoint import (  # noqa: F401
    CheckpointManager, LocalFS, atomic_write,
)
from .distributed_ft import (  # noqa: F401
    CollectiveTimeoutError, ReplicaDivergenceError, ReplicaGuard,
    ResumableLoader, TransientCollectiveError, capture_job_state,
    elastic_resume, restore_job_state,
)
from .fault_injection import (  # noqa: F401
    ChaosGroup, FaultyCollective, FaultyFS, InjectedCrash,
)
from .preemption import (  # noqa: F401
    PreemptionHandler, timed_emergency_save,
)
from .watchdog import (  # noqa: F401
    CircuitBreakerTripped, HangDetector, NanGuard, NanLossError,
)

__all__ = ["CheckpointManager", "LocalFS", "atomic_write", "FaultyFS",
           "InjectedCrash", "NanGuard", "HangDetector", "NanLossError",
           "CircuitBreakerTripped", "CollectiveTimeoutError",
           "TransientCollectiveError", "ReplicaDivergenceError",
           "ReplicaGuard", "ResumableLoader", "capture_job_state",
           "restore_job_state", "elastic_resume", "FaultyCollective",
           "ChaosGroup", "PreemptionHandler", "timed_emergency_save"]

"""Robustness subsystem: crash-safe checkpointing, fault injection for
proving it, and a training watchdog (NaN guard / circuit breaker / hang
detector). See docs/ARCHITECTURE.md "Checkpointing & fault tolerance"."""
from .checkpoint import (  # noqa: F401
    CheckpointManager, LocalFS, atomic_write,
)
from .fault_injection import FaultyFS, InjectedCrash  # noqa: F401
from .watchdog import (  # noqa: F401
    CircuitBreakerTripped, HangDetector, NanGuard, NanLossError,
)

__all__ = ["CheckpointManager", "LocalFS", "atomic_write", "FaultyFS",
           "InjectedCrash", "NanGuard", "HangDetector", "NanLossError",
           "CircuitBreakerTripped"]

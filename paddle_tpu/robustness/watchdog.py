"""Training watchdog: NaN/Inf guard + circuit breaker + hang detector.

Production jobs diverge (NaN loss), flake (one bad batch), and wedge (a
collective waits forever on a dead peer). The watchdog turns each into a
policy decision instead of silent corruption:

- NanGuard.check(loss, grads): per-step finiteness check with policy
  `skip_step` (drop the update), `rollback` (restore the last checkpoint),
  or `raise` (fail fast), plus a consecutive-bad-step circuit breaker that
  overrides any policy — N bad steps in a row means the run is diverging,
  not flaking.
- AMP interplay: a step the GradScaler already skipped (fp16 overflow →
  scale shrink) is NORMAL mixed-precision behavior; pass
  `scaler_skipped=True` and the guard neither acts nor advances the
  breaker.
- HangDetector: heartbeat-based stall detection for stuck steps/collectives
  — the training loop beat()s, a daemon thread fires `on_hang` when the
  last beat goes stale.
"""
from __future__ import annotations

import logging
import threading
import time

import numpy as np

from ..observability import get_event_log
from ..observability.flight_recorder import dump_flight_recorder
from ..observability.metrics import get_registry as _get_registry

__all__ = ["NanGuard", "HangDetector", "NanLossError",
           "CircuitBreakerTripped", "POLICIES"]

_LOG = logging.getLogger(__name__)

# watchdog telemetry (ISSUE 3 sweep): trip/heartbeat counts go to the
# registry; each trip/stall also lands in the event log with full context
_m_guard_steps = _get_registry().counter(
    "nan_guard_steps_total", help="steps classified by NanGuard").bind()
_m_guard_trips = _get_registry().counter(
    "nan_guard_trips_total",
    help="non-finite steps caught by NanGuard", labels=("action",))
_m_scaler_skips = _get_registry().counter(
    "nan_guard_scaler_skipped_total",
    help="steps exempted because the AMP scaler already skipped").bind()
_m_heartbeats = _get_registry().counter(
    "watchdog_heartbeats_total", help="HangDetector beats").bind()
_m_hangs = _get_registry().counter(
    "watchdog_hangs_total", help="stalls detected by HangDetector").bind()

POLICIES = ("skip_step", "rollback", "raise")


class NanLossError(FloatingPointError):
    """Non-finite loss/gradient under policy='raise'."""


class CircuitBreakerTripped(RuntimeError):
    """Too many consecutive non-finite steps — the run is diverging."""


def _is_finite(x):
    if x is None:
        return True
    if hasattr(x, "numpy"):
        x = x.numpy()
    arr = np.asarray(x)
    if arr.dtype.kind not in "fc":
        return True
    return bool(np.isfinite(arr).all())


class NanGuard:
    def __init__(self, policy="skip_step", max_consecutive_bad=8):
        if policy not in POLICIES:
            raise ValueError(f"policy must be one of {POLICIES}, got {policy!r}")
        self.policy = policy
        self.max_consecutive_bad = max_consecutive_bad
        self.reset()

    def reset(self):
        self.consecutive_bad = 0
        self.total_bad = 0
        self.total_steps = 0

    def state_dict(self) -> dict:
        """Breaker counters for the checkpoint job_state entry: a resumed
        run that was 7/8 steps into a divergence must not get a fresh
        breaker budget."""
        return {"consecutive_bad": self.consecutive_bad,
                "total_bad": self.total_bad,
                "total_steps": self.total_steps}

    def load_state_dict(self, state: dict):
        self.consecutive_bad = int(state["consecutive_bad"])
        self.total_bad = int(state["total_bad"])
        self.total_steps = int(state["total_steps"])

    def check(self, loss=None, grads=None, scaler_skipped=False):
        """Classify one step. Returns "ok" or the policy action
        ("skip_step"/"rollback"); raises NanLossError under policy='raise'
        and CircuitBreakerTripped when the breaker limit is hit."""
        self.total_steps += 1
        _m_guard_steps.value += 1
        if scaler_skipped:
            # the loss scaler found the overflow, skipped the update, and
            # will shrink its scale — routine fp16 dynamics, not divergence;
            # must not advance the breaker
            _m_scaler_skips.value += 1
            return "ok"
        bad = not _is_finite(loss) or any(
            not _is_finite(g) for g in (grads or []))
        if not bad:
            self.consecutive_bad = 0
            return "ok"
        self.consecutive_bad += 1
        self.total_bad += 1
        if self.max_consecutive_bad and \
                self.consecutive_bad >= self.max_consecutive_bad:
            _m_guard_trips.labels(action="breaker").inc()
            get_event_log().error(
                "nan_guard", "circuit breaker tripped",
                step=self.total_steps, consecutive=self.consecutive_bad,
                policy=self.policy)
            dump_flight_recorder("nan_guard:breaker")
            raise CircuitBreakerTripped(
                f"{self.consecutive_bad} consecutive non-finite steps "
                f"(policy {self.policy!r} could not recover) — aborting")
        _m_guard_trips.labels(action=self.policy).inc()
        get_event_log().warning(
            "nan_guard", "non-finite loss/gradient", step=self.total_steps,
            action=self.policy, consecutive=self.consecutive_bad)
        # postmortem while the evidence is fresh: the ring's tail is the
        # exact op/comm sequence that produced the non-finite step
        dump_flight_recorder(f"nan_guard:{self.policy}")
        if self.policy == "raise":
            raise NanLossError(
                f"non-finite loss/gradient at step {self.total_steps}")
        _LOG.warning("non-finite loss/gradient at step %d -> %s "
                     "(%d consecutive)", self.total_steps, self.policy,
                     self.consecutive_bad)
        return self.policy

    def check_gradients(self, parameters, scaler_skipped=False):
        grads = [p.grad for p in parameters if getattr(p, "grad", None)
                 is not None]
        return self.check(grads=grads, scaler_skipped=scaler_skipped)


class HangDetector:
    """Heartbeat-based stall detection.

        hd = HangDetector(timeout=120, on_hang=alert)
        hd.start()
        for batch in loader:
            train_step(batch)
            hd.beat()
        hd.stop()

    When no beat arrives for `timeout` seconds the daemon thread marks the
    run stalled, bumps `hang_count`, and calls `on_hang(stall_age)` once per
    stall (re-armed by the next beat). It observes and reports — it cannot
    interrupt a thread stuck inside a collective; pair it with an external
    supervisor (elastic relaunch) for the kill.
    """

    def __init__(self, timeout=60.0, poll_interval=None, on_hang=None,
                 state_fn=None, compile_grace=None):
        self.timeout = float(timeout)
        self.poll_interval = poll_interval if poll_interval is not None \
            else max(min(self.timeout / 4.0, 1.0), 0.01)
        self.on_hang = on_hang
        # compile-aware grace (ISSUE 17 satellite): when `state_fn()`
        # reports "compiling" the effective deadline stretches to
        # max(timeout, compile_grace). A cold XLA compile inside the
        # first step looks exactly like a hang to a heartbeat detector —
        # PR 14's chaos phase had to size the watchdog above worst-case
        # compile time fleet-wide; this scopes the allowance to the
        # window where the watched loop *says* it is compiling.
        self.state_fn = state_fn
        self.compile_grace = float(compile_grace) if compile_grace else 0.0
        self.stalled = False
        self.hang_count = 0
        self._last = time.monotonic()
        self._stop = threading.Event()
        self._thread = None

    def effective_timeout(self) -> float:
        if self.state_fn is not None and self.compile_grace:
            try:
                state = self.state_fn()
            except Exception:
                state = None
            if state == "compiling":
                return max(self.timeout, self.compile_grace)
        return self.timeout

    def beat(self):
        self._last = time.monotonic()
        self.stalled = False
        _m_heartbeats.value += 1

    def escalate(self, reason="external stall report"):
        """External stall escalation (e.g. a collective that exhausted its
        timeout retries — distributed_ft): counts as a hang and fires
        `on_hang` immediately instead of waiting for the heartbeat to go
        stale. Re-armed by the next beat like a detected stall."""
        self.stalled = True
        self.hang_count += 1
        _m_hangs.value += 1
        age = time.monotonic() - self._last
        get_event_log().error("watchdog", f"stall escalated: {reason}",
                              stall_age_seconds=round(age, 3))
        dump_flight_recorder(f"hang_escalated:{reason}"[:120])
        if self.on_hang is not None:
            try:
                self.on_hang(age)
            except Exception:
                _LOG.exception("on_hang callback failed")

    def start(self):
        self.beat()
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="hang-detector")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    def _run(self):
        while not self._stop.wait(self.poll_interval):
            age = time.monotonic() - self._last
            deadline = self.effective_timeout()
            if age > deadline and not self.stalled:
                self.stalled = True
                self.hang_count += 1
                _m_hangs.value += 1
                get_event_log().error(
                    "watchdog", "training stalled: heartbeat stale",
                    stall_age_seconds=round(age, 3),
                    timeout_seconds=deadline)
                dump_flight_recorder("hang:heartbeat_stale")
                if self.on_hang is not None:
                    try:
                        self.on_hang(age)
                    except Exception:
                        _LOG.exception("on_hang callback failed")
                else:
                    _LOG.warning("training stalled: no heartbeat for %.1fs "
                                 "(timeout %.1fs)", age, self.timeout)

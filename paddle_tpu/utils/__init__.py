"""paddle.utils (parity: python/paddle/utils/ — deprecated decorator,
unique_name, try_import, dlpack, cpp_extension pointer)."""
from __future__ import annotations

import functools
import importlib
import warnings

from . import unique_name  # noqa: F401

__all__ = ["deprecated", "try_import", "run_check", "unique_name", "dlpack"]


def deprecated(update_to="", since="", reason="", level=0):
    def decorator(func):
        @functools.wraps(func)
        def wrapper(*args, **kwargs):
            msg = (f"API {func.__module__}.{func.__name__} is deprecated "
                   f"since {since or 'an earlier release'}")
            if update_to:
                msg += f"; use {update_to} instead"
            if reason:
                msg += f" ({reason})"
            warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return func(*args, **kwargs)

        return wrapper

    return decorator


def try_import(module_name, err_msg=None):
    try:
        return importlib.import_module(module_name)
    except ImportError as e:
        raise ImportError(err_msg or
                          f"module {module_name!r} is required") from e


def run_check():
    """paddle.utils.run_check — sanity-check the install + device."""
    import numpy as np

    import paddle_tpu as paddle

    x = paddle.to_tensor(np.ones((2, 2), "float32"))
    y = paddle.matmul(x, x)
    assert float(y.numpy()[0, 0]) == 2.0
    print(f"paddle_tpu is installed successfully! device={paddle.get_device()}")


class dlpack:
    """paddle.utils.dlpack (zero-copy interop via the DLPack protocol,
    reference: dlpack_tensor.cc)."""

    @staticmethod
    def to_dlpack(x):
        # return the DLPack-protocol exporter (object with __dlpack__ /
        # __dlpack_device__) — what consumers like np.from_dlpack expect
        from ..framework.tensor import Tensor

        return x._value if isinstance(x, Tensor) else x

    @staticmethod
    def from_dlpack(ext):
        import jax

        from ..framework.tensor import Tensor

        return Tensor(jax.dlpack.from_dlpack(ext), _internal=True)


def require_version(min_version, max_version=None):
    """paddle.utils.require_version (reference: utils/install_check.py):
    assert the installed framework version is inside [min, max]."""
    from ..version import full_version

    def parse(v):
        return tuple(int(p) for p in str(v).split(".")[:3] if p.isdigit())

    cur = parse(full_version)
    if parse(min_version) > cur:
        raise Exception(
            f"installed version {full_version} < required {min_version}")
    if max_version is not None and parse(max_version) < cur:
        raise Exception(
            f"installed version {full_version} > allowed {max_version}")

"""paddle.utils.cpp_extension — custom C++ operator plug-in.

Reference: python/paddle/utils/cpp_extension/ + framework/custom_operator.cc:
users compile a C++ source exposing PD_BUILD_OP operators and call them from
Python with autograd support.

TPU-native protocol: the hot path on TPU is XLA; custom HOST ops (the only
place hand-written C++ beats the compiler here) plug in through a C ABI and
run inside the graph via jax.pure_callback. A source file defines, for op
NAME:

    extern "C" void NAME(const float** inputs, const int64_t* sizes,
                         int num_inputs, float* out, int64_t out_size);
    // optional backward: cotangent appended as the LAST input, one call
    // per differentiable input writing that input's gradient
    extern "C" void NAME_grad(const float** inputs, const int64_t* sizes,
                              int num_inputs, int wrt,
                              float* out, int64_t out_size);

`load(name=..., sources=[...])` compiles with g++ (no pybind11 needed),
dlopens, and returns a module whose ops are Tensor-in/Tensor-out callables
wired into the eager tape (and usable under jit via the callback).
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import types
from typing import List, Optional, Sequence

import numpy as np

__all__ = ["load", "CppExtension", "CUDAExtension", "BuildExtension",
           "get_build_directory"]

_BUILD_ROOT = os.path.join(os.path.expanduser("~"), ".cache",
                           "paddle_tpu_extensions")


def get_build_directory():
    os.makedirs(_BUILD_ROOT, exist_ok=True)
    return _BUILD_ROOT


def CppExtension(sources, *args, **kwargs):
    return {"sources": list(sources)}


def CUDAExtension(sources, *args, **kwargs):
    raise NotImplementedError(
        "CUDA extensions have no meaning on TPU; write a host C++ op "
        "(CppExtension) or a pallas kernel (paddle_tpu.ops)")


class BuildExtension:  # setuptools-cmdclass parity shim
    @staticmethod
    def with_options(**kw):
        return BuildExtension


def _compile(name: str, sources: Sequence[str],
             extra_cxx_flags: Optional[List[str]] = None) -> str:
    tag = hashlib.sha256(
        b"\0".join(open(s, "rb").read() for s in sources)).hexdigest()[:16]
    out = os.path.join(get_build_directory(), f"{name}_{tag}.so")
    if not os.path.exists(out):
        cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-o", out,
               *(extra_cxx_flags or []), *sources]
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"custom op build failed:\n{proc.stderr[-4000:]}")
    return out


_FWD_SIG = [ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int, ctypes.c_void_p, ctypes.c_int64]
_BWD_SIG = [ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int, ctypes.c_int, ctypes.c_void_p, ctypes.c_int64]


def _call_c(cfn, arrays: Sequence[np.ndarray], out_shape, wrt=None):
    arrs = [np.ascontiguousarray(a, dtype=np.float32) for a in arrays]
    ptrs = (ctypes.c_void_p * len(arrs))(
        *[a.ctypes.data_as(ctypes.c_void_p).value for a in arrs])
    sizes = (ctypes.c_int64 * len(arrs))(*[a.size for a in arrs])
    out = np.zeros(out_shape, np.float32)
    if wrt is None:
        cfn(ptrs, sizes, len(arrs), out.ctypes.data_as(ctypes.c_void_p),
            out.size)
    else:
        cfn(ptrs, sizes, len(arrs), wrt,
            out.ctypes.data_as(ctypes.c_void_p), out.size)
    return out


def _make_op(lib, name: str):
    import jax
    import jax.numpy as jnp

    from ..framework.autograd import call_op
    from ..framework.tensor import Tensor

    fwd = getattr(lib, name)
    fwd.argtypes = _FWD_SIG
    fwd.restype = None
    bwd = getattr(lib, name + "_grad", None)
    if bwd is not None:
        bwd.argtypes = _BWD_SIG
        bwd.restype = None

    def val_fn(*vals, out_shape=None):
        shape = tuple(out_shape) if out_shape is not None else vals[0].shape

        def host(*np_ins):
            return _call_c(fwd, np_ins, shape)

        call = lambda *vs: jax.pure_callback(
            host, jax.ShapeDtypeStruct(shape, jnp.float32), *vs,
            vmap_method="sequential")
        if bwd is None:
            return call(*vals)

        @jax.custom_vjp
        def op_(*vs):
            return call(*vs)

        def op_fwd(*vs):
            return call(*vs), vs

        def op_bwd(res, cot):
            def host_g(wrt_shape, wrt, *np_ins):
                return _call_c(bwd, np_ins, wrt_shape, wrt=wrt)

            grads = []
            for i, v in enumerate(res):
                g = jax.pure_callback(
                    lambda *ins, _i=i, _s=v.shape: host_g(_s, _i, *ins),
                    jax.ShapeDtypeStruct(v.shape, jnp.float32),
                    *res, cot, vmap_method="sequential")
                grads.append(g)
            return tuple(grads)

        op_.defvjp(op_fwd, op_bwd)
        return op_(*vals)

    def tensor_fn(*tensors, out_shape=None):
        return call_op(lambda *vs: val_fn(*vs, out_shape=out_shape),
                       *tensors, op_name=f"custom_{name}")

    tensor_fn.__name__ = name
    return tensor_fn


def load(name: str, sources: Sequence[str], extra_cxx_flags=None,
         ops: Optional[Sequence[str]] = None, verbose=False, **kwargs):
    """Compile + load custom ops; returns a module exposing each op.

    `ops` lists the exported op symbols (default: [name]). Reference:
    cpp_extension.load(name=..., sources=[...]) returning a module of ops.
    """
    so = _compile(name, sources, extra_cxx_flags)
    lib = ctypes.CDLL(so)
    mod = types.ModuleType(f"paddle_tpu_custom.{name}")
    for op_name in (ops or [name]):
        setattr(mod, op_name, _make_op(lib, op_name))
    mod.__file__ = so
    return mod

"""Shared once-per-process compat warning (VERDICT r2 weak #5: accepted
API-parity knobs that select nothing here must say so, not silently no-op).
"""
from __future__ import annotations

import warnings

__all__ = ["warn_compat_once"]


def warn_compat_once(seen: set, prefix: str, knob: str, why: str,
                     stacklevel: int = 3):
    """Warn the first time `knob` is used; `seen` is the caller's module
    registry so tests can reset it."""
    if knob in seen:
        return
    seen.add(knob)
    warnings.warn(f"{prefix}{knob} is a compatibility no-op on this "
                  f"framework: {why}", stacklevel=stacklevel)

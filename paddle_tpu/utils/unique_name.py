"""paddle.utils.unique_name (parity: fluid/unique_name.py)."""
from __future__ import annotations

import contextlib
import threading

_tls = threading.local()


def _counters():
    if not hasattr(_tls, "counters"):
        _tls.counters = {}
    return _tls.counters


def generate(key):
    c = _counters()
    c[key] = c.get(key, -1) + 1
    return f"{key}_{c[key]}"


def switch(new_generator=None):
    old = _counters().copy()
    _tls.counters = new_generator or {}
    return old


@contextlib.contextmanager
def guard(new_generator=None):
    old = switch(new_generator if isinstance(new_generator, dict) else {})
    try:
        yield
    finally:
        _tls.counters = old

"""Interop with reference-format model artifacts (one-way importer).

`load_paddle_inference_model` reads a reference `__model__` ProgramDesc
protobuf + persistables and executes them with jax kernels — the bridge for
users migrating saved reference models onto this framework.
"""
from .importer import (  # noqa: F401
    PaddleProgram, load_paddle_inference_model, parse_program_desc,
    read_lod_tensor_stream,
)
from .serializer import (  # noqa: F401
    save_paddle_inference_model, serialize_program_desc,
    write_lod_tensor_stream,
)

__all__ = ["PaddleProgram", "load_paddle_inference_model",
           "parse_program_desc", "read_lod_tensor_stream",
           "save_paddle_inference_model", "serialize_program_desc",
           "write_lod_tensor_stream"]
